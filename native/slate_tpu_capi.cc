/* Embedded-interpreter driver C API for slate_tpu.
 *
 * Analog of the reference's generated driver C tier
 * (ref: src/c_api/wrappers.cc:1-1307, include/slate/c_api/wrappers.h):
 * C programs call slate_tpu_dgesv / dposv / dgels / dsyev / dgesvd with
 * raw row-major buffers.  The reference's C API wraps a C++ runtime
 * in-process; here the runtime is the JAX program layer, so this shim
 * embeds CPython, imports slate_tpu.compat.capi once, and forwards
 * buffer POINTERS (as integers) plus dimensions — the Python side wraps
 * them with numpy and runs the real drivers on whatever backend JAX has.
 *
 * Build: native/Makefile target libslate_tpu_capi.so (links libpython).
 * The embedding process must have slate_tpu importable (PYTHONPATH).
 */
#include <Python.h>
#include <stdint.h>

#include "slate_tpu_capi.h"

static PyObject* g_mod = NULL;

int slate_tpu_init(void) {
  if (!Py_IsInitialized()) Py_InitializeEx(0);
  PyGILState_STATE g = PyGILState_Ensure();
  if (g_mod == NULL) {
    g_mod = PyImport_ImportModule("slate_tpu.compat.capi");
    if (g_mod == NULL) PyErr_Print();
  }
  int rc = (g_mod == NULL) ? 1 : 0;
  PyGILState_Release(g);
  return rc;
}

void slate_tpu_finalize(void) {
  if (g_mod != NULL) {
    PyGILState_STATE g = PyGILState_Ensure();
    Py_CLEAR(g_mod);
    PyGILState_Release(g);
  }
}

/* Call capi.<name>(...) -> int rc; returns 1 on any Python error. */
static int call_rc(const char* name, const char* fmt, ...) {
  if (g_mod == NULL && slate_tpu_init() != 0) return 1;
  PyGILState_STATE g = PyGILState_Ensure();
  va_list ap;
  va_start(ap, fmt);
  PyObject* args = Py_VaBuildValue(fmt, ap);
  va_end(ap);
  int rc = 1;
  if (args != NULL) {
    PyObject* fn = PyObject_GetAttrString(g_mod, name);
    if (fn != NULL) {
      PyObject* res = PyObject_CallObject(fn, args);
      if (res != NULL) {
        rc = (int)PyLong_AsLong(res);
        Py_DECREF(res);
      }
      Py_DECREF(fn);
    }
    Py_DECREF(args);
  }
  if (PyErr_Occurred()) PyErr_Print();
  PyGILState_Release(g);
  return rc;
}

int slate_tpu_dgesv(int64_t n, int64_t nrhs, const double* a, int64_t lda,
                    const double* b, int64_t ldb, double* x, int64_t ldx,
                    int64_t nb) {
  return call_rc("dgesv", "(LLKLKLKLL)", (long long)n, (long long)nrhs,
                 (unsigned long long)(uintptr_t)a, (long long)lda,
                 (unsigned long long)(uintptr_t)b, (long long)ldb,
                 (unsigned long long)(uintptr_t)x, (long long)ldx,
                 (long long)nb);
}

int slate_tpu_dposv(int64_t n, int64_t nrhs, const double* a, int64_t lda,
                    const double* b, int64_t ldb, double* x, int64_t ldx,
                    int64_t nb) {
  return call_rc("dposv", "(LLKLKLKLL)", (long long)n, (long long)nrhs,
                 (unsigned long long)(uintptr_t)a, (long long)lda,
                 (unsigned long long)(uintptr_t)b, (long long)ldb,
                 (unsigned long long)(uintptr_t)x, (long long)ldx,
                 (long long)nb);
}

int slate_tpu_dgels(int64_t m, int64_t n, int64_t nrhs, const double* a,
                    int64_t lda, const double* b, int64_t ldb, double* x,
                    int64_t ldx, int64_t nb) {
  return call_rc("dgels", "(LLLKLKLKLL)", (long long)m, (long long)n,
                 (long long)nrhs, (unsigned long long)(uintptr_t)a,
                 (long long)lda, (unsigned long long)(uintptr_t)b,
                 (long long)ldb, (unsigned long long)(uintptr_t)x,
                 (long long)ldx, (long long)nb);
}

int slate_tpu_dsyev(int64_t n, const double* a, int64_t lda, double* w,
                    int64_t nb) {
  return call_rc("dsyev", "(LKLKL)", (long long)n,
                 (unsigned long long)(uintptr_t)a, (long long)lda,
                 (unsigned long long)(uintptr_t)w, (long long)nb);
}

int slate_tpu_dgesvd(int64_t m, int64_t n, const double* a, int64_t lda,
                     double* s, int64_t nb) {
  return call_rc("dgesvd", "(LLKLKL)", (long long)m, (long long)n,
                 (unsigned long long)(uintptr_t)a, (long long)lda,
                 (unsigned long long)(uintptr_t)s, (long long)nb);
}
