// slate_tpu native runtime: host-side storage-layer kernels + C API.
//
// Analog of the reference's native storage/layout layer
// (ref: include/slate/internal/MatrixStorage.hh tile map + distribution
// lambdas; include/slate/Tile.hh:707 layoutConvert; src/c_api/wrappers.cc
// C API tier).  The TPU compute path is JAX/XLA; what remains native is
// the HOST runtime around it: importing/exporting user LAPACK/ScaLAPACK
// buffers into the framework's 2D block-cyclic tile layout
// [p*mtl, q*ntl, mb, nb] at memory bandwidth (OpenMP across tiles), plus
// the ScaLAPACK descriptor arithmetic.  Python binds via ctypes
// (slate_tpu/native.py) with a pure-numpy fallback when the library is
// not built.
//
// Build: make -C native   (g++ -O3 -march=native -fopenmp -shared -fPIC)
//
// Layout contract (must match slate_tpu/core/layout.py):
//   cyclic slot (s, t) holds tile (i, j) with
//     i = (s % mtl) * p + s / mtl,   j = (t % ntl) * q + t / ntl
//   i.e. storage row s = (i % p) * mtl + i / p, mtl = ceil(Mt / p).
//   Tiles are row-major [mb, nb]; out-of-range elements are ZERO (the
//   pad-is-zero invariant every kernel relies on).

#include <cstdint>
#include <cstring>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// Library identity (analog of src/version.cc / c_api slate_version).
int64_t slate_tpu_native_version(void) { return 20260730; }

// ScaLAPACK numroc: rows of an n x nb-blocked dimension owned by iproc
// (ref: scalapack tools/numroc.f; used by compat/scalapack.py).
int64_t slate_tpu_numroc(int64_t n, int64_t nb, int64_t iproc,
                         int64_t isrcproc, int64_t nprocs) {
    int64_t mydist = (nprocs + iproc - isrcproc) % nprocs;
    int64_t nblocks = n / nb;
    int64_t numroc = (nblocks / nprocs) * nb;
    int64_t extrablks = nblocks % nprocs;
    if (mydist < extrablks)
        numroc += nb;
    else if (mydist == extrablks)
        numroc += n % nb;
    return numroc;
}

// Pack a ROW-major m x n matrix with row stride ld into the framework's
// cyclic tile array dst[p*mtl][q*ntl][mb][nb] (row-major throughout).
// Row-major matches numpy's default order so the Python binding passes
// buffers straight through with no transpose copy; LAPACK column-major
// callers pass the transpose view and flip (m, n).  Pads with zeros.
#define DEFINE_PACK(NAME, T)                                               \
void NAME(const T* src, int64_t m, int64_t n, int64_t ld, int64_t mb,      \
          int64_t nb, int64_t p, int64_t q, T* dst) {                      \
    int64_t Mt = (m + mb - 1) / mb, Nt = (n + nb - 1) / nb;                \
    int64_t mtl = (Mt + p - 1) / p, ntl = (Nt + q - 1) / q;                \
    int64_t rows = p * mtl, cols = q * ntl;                                \
    _Pragma("omp parallel for collapse(2) schedule(static)")               \
    for (int64_t s = 0; s < rows; ++s) {                                   \
        for (int64_t t = 0; t < cols; ++t) {                               \
            int64_t i = (s % mtl) * p + s / mtl;                           \
            int64_t j = (t % ntl) * q + t / ntl;                           \
            T* tile = dst + ((s * cols + t) * mb) * nb;                    \
            if (i >= Mt || j >= Nt) {                                      \
                std::memset(tile, 0, sizeof(T) * mb * nb);                 \
                continue;                                                  \
            }                                                              \
            int64_t r0 = i * mb, c0 = j * nb;                              \
            int64_t rlim = (r0 + mb <= m) ? mb : (m > r0 ? m - r0 : 0);    \
            int64_t clim = (c0 + nb <= n) ? nb : (n > c0 ? n - c0 : 0);    \
            for (int64_t a = 0; a < rlim; ++a) {                           \
                const T* srow = src + (r0 + a) * ld + c0;                  \
                T* trow = tile + a * nb;                                   \
                for (int64_t b = 0; b < clim; ++b)                         \
                    trow[b] = srow[b];                                     \
                for (int64_t b = clim; b < nb; ++b) trow[b] = (T)0;        \
            }                                                              \
            for (int64_t a = rlim; a < mb; ++a)                            \
                std::memset(tile + a * nb, 0, sizeof(T) * nb);             \
        }                                                                  \
    }                                                                      \
}

DEFINE_PACK(slate_tpu_pack_tiles_f64, double)
DEFINE_PACK(slate_tpu_pack_tiles_f32, float)

// Unpack the cyclic tile array back into a ROW-major m x n buffer
// (row stride ld).
#define DEFINE_UNPACK(NAME, T)                                             \
void NAME(const T* src, int64_t m, int64_t n, int64_t ld, int64_t mb,      \
          int64_t nb, int64_t p, int64_t q, T* dst) {                      \
    int64_t Mt = (m + mb - 1) / mb, Nt = (n + nb - 1) / nb;                \
    int64_t mtl = (Mt + p - 1) / p, ntl = (Nt + q - 1) / q;                \
    int64_t cols = q * ntl;                                                \
    _Pragma("omp parallel for collapse(2) schedule(static)")               \
    for (int64_t i = 0; i < Mt; ++i) {                                     \
        for (int64_t j = 0; j < Nt; ++j) {                                 \
            int64_t s = (i % p) * mtl + i / p;                             \
            int64_t t = (j % q) * ntl + j / q;                             \
            const T* tile = src + ((s * cols + t) * mb) * nb;              \
            int64_t r0 = i * mb, c0 = j * nb;                              \
            int64_t rlim = (r0 + mb <= m) ? mb : m - r0;                   \
            int64_t clim = (c0 + nb <= n) ? nb : n - c0;                   \
            for (int64_t a = 0; a < rlim; ++a) {                           \
                T* drow = dst + (r0 + a) * ld + c0;                        \
                for (int64_t b = 0; b < clim; ++b)                         \
                    drow[b] = tile[a * nb + b];                            \
            }                                                              \
        }                                                                  \
    }                                                                      \
}

DEFINE_UNPACK(slate_tpu_unpack_tiles_f64, double)
DEFINE_UNPACK(slate_tpu_unpack_tiles_f32, float)

}  // extern "C"
