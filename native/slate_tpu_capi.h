/* Driver-level C API of slate_tpu (analog of the reference's
 * include/slate/c_api/wrappers.h generated tier).
 *
 * Buffers are double precision, ROW-major, with `ld*` = elements between
 * consecutive rows (>= the column count).  `nb` is the tile size.
 * Every routine returns 0 on success.  The process embeds CPython:
 * call slate_tpu_init() first (slate_tpu must be importable), and
 * slate_tpu_finalize() before exit if desired.
 */
#ifndef SLATE_TPU_CAPI_H
#define SLATE_TPU_CAPI_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

int slate_tpu_init(void);
void slate_tpu_finalize(void);

/* Solve A X = B by partially-pivoted LU (A [n, n], B/X [n, nrhs]). */
int slate_tpu_dgesv(int64_t n, int64_t nrhs, const double* a, int64_t lda,
                    const double* b, int64_t ldb, double* x, int64_t ldx,
                    int64_t nb);

/* Solve A X = B for Hermitian positive-definite A (lower triangle read). */
int slate_tpu_dposv(int64_t n, int64_t nrhs, const double* a, int64_t lda,
                    const double* b, int64_t ldb, double* x, int64_t ldx,
                    int64_t nb);

/* Least squares min ||A X - B||: A [m, n] (m >= n), B [m, nrhs],
 * X [n, nrhs]. */
int slate_tpu_dgels(int64_t m, int64_t n, int64_t nrhs, const double* a,
                    int64_t lda, const double* b, int64_t ldb, double* x,
                    int64_t ldx, int64_t nb);

/* Eigenvalues (ascending) of symmetric A (lower triangle read), w [n]. */
int slate_tpu_dsyev(int64_t n, const double* a, int64_t lda, double* w,
                    int64_t nb);

/* Singular values (descending) of A [m, n], s [min(m, n)]. */
int slate_tpu_dgesvd(int64_t m, int64_t n, const double* a, int64_t lda,
                     double* s, int64_t nb);

#ifdef __cplusplus
}
#endif

#endif /* SLATE_TPU_CAPI_H */
