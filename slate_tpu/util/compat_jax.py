"""jax version compatibility shims.

The framework targets the stable ``jax.shard_map`` API.  On older jax
releases (<= 0.4.x) the same function lives at
``jax.experimental.shard_map.shard_map`` with an identical keyword
signature (f, mesh, in_specs, out_specs); installing it under the stable
name at import time lets every mesh path run unmodified on both.  Import
this module before any ``jax.shard_map`` call site (slate_tpu/__init__.py
does, first thing).
"""

from __future__ import annotations

import jax


def install() -> None:
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map
        jax.shard_map = shard_map


def shard_map_unchecked(f, *, mesh, in_specs, out_specs):
    """shard_map with the static replication checker disabled.

    Scan carries that start as constants and become psum-replicated inside
    the loop (dist_lu's perm/min-pivot trace, dist_chol's health trace) are
    genuinely replicated but not provably so to the checker — it requires
    exact carry-rep equality and has no join for constant reps.  The kwarg
    spelling differs across jax versions (check_rep / check_vma), so probe
    the signature."""
    import inspect
    kw = {}
    try:
        params = inspect.signature(jax.shard_map).parameters
        for name in ("check_rep", "check_vma"):
            if name in params:
                kw[name] = False
                break
    except (TypeError, ValueError):  # C-accelerated / exotic signature
        kw["check_rep"] = False
    return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, **kw)


def pvary(x, axes):
    """Mark ``x`` device-varying over ``axes`` for the replication checker.

    Newer jax spells this ``lax.pcast(..., to="varying")`` or
    ``lax.pvary``; on versions without the varying-manual-axes machinery
    the annotation is a semantic no-op (identity) and the enclosing
    shard_map must be built with :func:`shard_map_unchecked`."""
    from jax import lax
    try:
        return lax.pcast(x, axes, to="varying")
    except (AttributeError, TypeError):
        pass
    try:
        return lax.pvary(x, axes)
    except AttributeError:
        return x


install()
