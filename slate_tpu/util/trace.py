"""Tracing: the label-every-op discipline.

Analog of the reference's trace::Block RAII instrumentation (ref:
include/slate/internal/Trace.hh:103-110 — every kernel, MPI call and
memcpy opens a named block; Trace.cc:359-448 renders the SVG timeline).

On TPU the timeline renderer is jax.profiler (Perfetto/TensorBoard), so
the framework's job is to NAME things: :func:`span` opens both a host-side
profiler TraceAnnotation (visible on the host timeline) and a
jax.named_scope (labels the emitted XLA ops, so device-side kernels in a
profile carry driver/phase names like ``slate.potrf/panel``).

Capture a profile the standard jax way::

    with jax.profiler.trace("/tmp/jax-trace"):
        st.posv(A, B)
    # tensorboard --logdir /tmp/jax-trace  ->  named phases
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def span(name: str):
    """Named block around driver/kernel phases (trace::Block analog).

    Safe both outside jit (host annotation) and while tracing (XLA op
    names)."""
    with jax.profiler.TraceAnnotation(name):
        with jax.named_scope(name):
            yield


def annotate(name: str):
    """Decorator form of :func:`span` for whole drivers."""
    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(name):
                return fn(*args, **kwargs)
        return wrapper
    return deco
