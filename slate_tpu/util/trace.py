"""Tracing: the label-every-op discipline, now with optional recording.

Analog of the reference's trace::Block RAII instrumentation (ref:
include/slate/internal/Trace.hh:103-110 — every kernel, MPI call and
memcpy opens a named block; Trace.cc:359-448 renders the SVG timeline).

On TPU the timeline renderer is jax.profiler (Perfetto/TensorBoard), so
the framework's job is to NAME things: :func:`span` opens both a host-side
profiler TraceAnnotation (visible on the host timeline) and a
jax.named_scope (labels the emitted XLA ops, so device-side kernels in a
profile carry driver/phase names like ``slate.potrf/panel``).

Two observability layers ride on the same names (slate_tpu/obs,
docs/OBSERVABILITY.md), both zero-overhead when inactive and both
host-side only — the traced computation is byte-identical either way:

- inside ``obs.record_spans()`` every span's wall time is recorded for
  Chrome/Perfetto export (the trace::Block timeline, kept this time);
- :func:`annotate` additionally opens a driver *boundary* for the
  structured-event layer: one event per public driver call, fed by the
  health/recovery/tune seams, and the retrace sentinel counts traced
  executions per signature.

Capture a profile the standard jax way::

    with jax.profiler.trace("/tmp/jax-trace"):
        st.posv(A, B)
    # tensorboard --logdir /tmp/jax-trace  ->  named phases
"""

from __future__ import annotations

import contextlib
import functools

import jax

from ..obs import events as _events
from ..obs import tracer as _tracer


@contextlib.contextmanager
def span(name: str):
    """Named block around driver/kernel phases (trace::Block analog).

    Safe both outside jit (host annotation) and while tracing (XLA op
    names).  Records wall times when an obs.record_spans() recorder is
    active on this thread."""
    rec = _tracer.active()
    tok = rec.enter(name) if rec is not None else None
    try:
        with jax.profiler.TraceAnnotation(name):
            with jax.named_scope(name):
                yield
    finally:
        if rec is not None:
            rec.exit(tok)


def annotate(name: str):
    """Decorator form of :func:`span` for whole drivers — also the
    structured-event boundary: one obs event per outermost call.

    Under ``obs.timing()`` the outermost eager boundary additionally
    blocks until the result is device-ready before closing, so its event
    carries a true dispatch->ready ``device_ms`` (and derived mfu /
    achieved_gbps).  The sync is host-side and never runs while tracing
    — ``should_time`` refuses traced frames — so enabling timing cannot
    change a jaxpr."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            tok = _events.boundary_enter(name, args)
            try:
                with span(name):
                    out = fn(*args, **kwargs)
                if _events.should_time(tok):
                    jax.block_until_ready(out)
                    _events.note_device_ready(tok)
            except BaseException as e:
                _events.boundary_exit(tok, error=e)
                # slate-lint: disable=TRC006 -- bare re-raise after noting
                raise
            _events.boundary_exit(tok)
            return out
        return wrapper
    return deco
