"""Debug dumps of tile state (analog of the reference's Debug class,
ref: src/core/Debug.cc:66-336 checkTilesLives / printTilesLives /
printTilesMaps, which print per-tile existence/life/MOSI state).

The TPU storage model has no tile lives or MOSI states to dump (one
sharded array, SSA — see core/storage.py); what remains debuggable is
the MAP: which device owns each tile, what lives in it (norm), and
whether the pad invariant holds.  These helpers print exactly that.
"""

from __future__ import annotations

import numpy as np


def tiles_map(A, *, max_tiles: int = 32) -> str:
    """Owner + per-tile Frobenius norm map (ref: Debug::printTilesMaps).

    One cell per tile: ``r<rank>:<norm>``; '.' for all-zero tiles.
    Truncated to ``max_tiles`` rows/cols like the reference's dumps."""
    st = A.storage
    can = np.asarray(st.canonical())
    Mt, Nt = min(st.Mt, max_tiles), min(st.Nt, max_tiles)
    lines = [f"tiles_map {st.m}x{st.n} mb={st.mb} nb={st.nb} "
             f"grid={st.grid.p}x{st.grid.q}"]
    for i in range(Mt):
        cells = []
        for j in range(Nt):
            nrm = float(np.linalg.norm(can[i, j]))
            r = st.tile_rank(i, j)
            cells.append("." if nrm == 0 else f"r{r}:{nrm:.2e}")
        lines.append(" ".join(cells) + (" ..." if Nt < st.Nt else ""))
    if Mt < st.Mt:
        lines.append("...")
    return "\n".join(lines)


def check_pad_invariant(A) -> bool:
    """True iff every out-of-matrix pad entry is exactly zero — the
    invariant every kernel preserves (the analog of Debug::checkTiles
    consistency checking)."""
    st = A.storage
    can = np.asarray(st.canonical())
    dense = can.transpose(0, 2, 1, 3).reshape(st.Mt * st.mb, st.Nt * st.nb)
    ok = True
    if st.Mt * st.mb > st.m:
        ok &= not np.any(dense[st.m:, :])
    if st.Nt * st.nb > st.n:
        ok &= not np.any(dense[:, st.n:])
    return bool(ok)


def memory_report(A) -> str:
    """Per-device HBM footprint of a matrix's storage (the analog of the
    reference Memory pool counters, Memory.hh:29-95)."""
    st = A.storage
    itemsize = np.dtype(st.dtype).itemsize
    per_dev = (st.data.size * itemsize) / max(st.grid.p * st.grid.q, 1)
    return (f"storage {st.data.shape} {st.dtype}: "
            f"{st.data.size * itemsize / 1e6:.2f} MB total, "
            f"{per_dev / 1e6:.2f} MB per device over "
            f"{st.grid.p * st.grid.q} device(s)")
