"""Deterministic test-matrix generator.

Analog of the reference's generator (ref: test/matrix_generator.{hh,cc},
test/matrix_params.hh:17-77, test/random.{hh,cc}): named kinds with optional
condition-number control, deterministic for a given seed REGARDLESS of the
tile distribution (the reference guarantees the same, CHANGELOG.md:9-10) —
here guaranteed trivially because generation happens in the global index
space before tiling.
"""

from __future__ import annotations

import numpy as np

from ..core.grid import Grid
from ..core.matrix import HermitianMatrix, Matrix, SymmetricMatrix
from ..exceptions import slate_error
from ..types import Uplo

KINDS = ("zeros", "ones", "identity", "jordan", "rand", "randn", "rands",
         "rand_dominant", "svd", "poev", "heev", "chebspec")


def _dense(kind: str, m: int, n: int, rng, dtype, cond: float):
    cplx = np.issubdtype(dtype, np.complexfloating)

    def rnd(shape, dist):
        if dist == "rand":
            x = rng.random(shape)
        elif dist == "rands":
            x = 2.0 * rng.random(shape) - 1.0
        else:
            x = rng.standard_normal(shape)
        if cplx:
            x = x + 1j * (rng.random(shape) if dist == "rand"
                          else rng.standard_normal(shape))
        return x.astype(dtype)

    if kind == "zeros":
        return np.zeros((m, n), dtype)
    if kind == "ones":
        return np.ones((m, n), dtype)
    if kind == "identity":
        return np.eye(m, n, dtype=dtype)
    if kind == "jordan":
        return (np.eye(m, n, dtype=dtype) +
                np.eye(m, n, k=1, dtype=dtype))
    if kind in ("rand", "randn", "rands"):
        return rnd((m, n), kind)
    if kind == "rand_dominant":
        a = rnd((m, n), "rand")
        k = min(m, n)
        a[np.arange(k), np.arange(k)] += max(m, n)
        return a
    if kind == "chebspec":
        # mild deterministic non-normal test matrix
        i = np.arange(m)[:, None]
        j = np.arange(n)[None, :]
        return np.cos(np.pi * (i * j) / max(m, n)).astype(dtype)
    if kind in ("svd", "poev", "heev"):
        k = min(m, n)
        # geometric singular/eigen-value distribution sigma_i = cond^{-i/(k-1)}
        # (ref: matrix_generator geometric sigma)
        c = cond or 1e3
        sigma = c ** (-np.arange(k) / max(k - 1, 1))
        q1, _ = np.linalg.qr(rnd((m, k), "randn"))
        q2, _ = np.linalg.qr(rnd((n, k), "randn"))
        if kind == "svd":
            return (q1 * sigma) @ q2.conj().T
        if kind == "poev":                      # SPD/HPD with cond c
            return ((q1 * sigma) @ q1.conj().T).astype(dtype)
        lam = np.linspace(-1.0, 1.0, k) * sigma[::-1]
        return ((q1 * lam) @ q1.conj().T).astype(dtype)
    raise ValueError(f"unknown matrix kind {kind!r}")


def generate_matrix(kind: str, m: int, n: int, mb: int, nb: int | None = None,
                    *, seed: int = 0, dtype=np.float64, cond: float | None =
                    None, grid: Grid | None = None) -> Matrix:
    """Generate a distributed general matrix of a named kind."""
    slate_error(kind in KINDS, f"kind must be one of {KINDS}")
    rng = np.random.default_rng(seed)
    a = _dense(kind, m, n, rng, np.dtype(dtype), cond or 0.0)
    return Matrix.from_numpy(a, mb, nb or mb, grid)


def generate_hermitian(kind: str, n: int, nb: int, *, seed: int = 0,
                       dtype=np.float64, cond: float | None = None,
                       grid: Grid | None = None,
                       uplo: Uplo = Uplo.Lower) -> HermitianMatrix:
    """Hermitian (or HPD for kind='poev') generator."""
    rng = np.random.default_rng(seed)
    a = _dense(kind if kind in ("poev", "heev") else "randn",
               n, n, rng, np.dtype(dtype), cond or 0.0)
    if kind not in ("poev", "heev"):
        a = (a + a.conj().T) / 2
    return HermitianMatrix.from_numpy(a, nb, uplo, grid)
