"""Options / enums / method auto-selection.

TPU-native analog of the reference's per-call configuration system:

- ``Option`` / ``Options`` map passed to every routine
  (ref: include/slate/internal/enums.hh:69-101, include/slate/types.hh:32-61).
- ``Target`` execution-target dispatch (ref: enums.hh:33-39,48-54).  On TPU the
  meaningful split is *single* (one chip: statically-shaped blocked algorithms,
  fully unrolled under one jit, maximal MXU utilisation) vs *mesh* (a
  ``jax.sharding.Mesh`` process grid: shard_map + masked fori_loop pipelines
  with ICI collectives).  ``HostTask/HostNest/HostBatch/Devices`` from the
  reference all collapse onto these two, chosen by where the data lives.
- Method auto-selection heuristics (ref: include/slate/method.hh:25-316).
"""

from __future__ import annotations

import enum
from typing import Any, Mapping


class Target(enum.Enum):
    """Execution target (ref: enums.hh:33-39).

    auto    pick from the matrix' grid (mesh if p*q > 1 else single)
    single  one device, statically-shaped blocked algorithm under one jit
    mesh    shard_map over a p*q device mesh, collectives over ICI
    """

    auto = "auto"
    single = "single"
    mesh = "mesh"

    # Reference spellings kept as aliases so ported call sites read naturally.
    HostTask = "single"
    Devices = "mesh"


class ErrorPolicy(enum.Enum):
    """Failure-surfacing contract for factor/solve drivers (robust/health.py).

    Raise  eager calls raise the typed exception (SlateSingularError /
           SlateNotPositiveDefiniteError); traced calls cannot raise, so
           failures surface as non-finite values (the XLA convention).
           This is the default — it unifies the eager-raise vs traced-NaN
           contracts the drivers previously pinned ad hoc.
    Nan    never raise, even eagerly; failed results are explicitly
           NaN-poisoned (jit-safe, deterministic garbage-out signalling).
    Info   never raise, never poison; the driver additionally returns a
           jit-compatible ``HealthInfo`` pytree (non-finite flag, LAPACK
           info code, min-pivot index/magnitude, growth, IR iterations).
    """

    Raise = "raise"
    Nan = "nan"
    Info = "info"


class Speculate(enum.Enum):
    """Speculate-then-certify execution mode (docs/ROBUSTNESS.md).

    The robust layer's escalation ladders run *backwards* by default: try
    the requested (safe) method, escalate on failure.  ``Speculate.On``
    runs them *forwards* as a performance feature: the solver first tries
    the cheapest pivot/structure-free method in its family (gesv: RBT
    NoPiv LU + refinement; gels: CholQR2 semi-normal equations; hesv:
    Cholesky), certifies the result a-posteriori (residual + growth folded
    into HealthInfo), and only a failed certificate escalates to the
    conventional method — eagerly, via the same bounded_retry policy.

    Auto    currently Off (the heuristic seam for future auto-enabling)
    Off     conventional method order
    On      speculative fast path first, certified
    """

    Auto = "auto"
    Off = "off"
    On = "on"


class Abft(enum.Enum):
    """Algorithm-based fault tolerance mode (robust/abft.py).

    With ``Abft.On`` the blocked GEMM/LU/Cholesky paths carry Huang-
    Abraham row/column checksums through every panel + trailing-update
    step: a checksum mismatch is DETECTED, the corrupted tile is LOCATED
    from the row/column residual cross-pattern, and single-element strikes
    are CORRECTED in place by checksum reconstruction — an O(n^2) repair
    rung below the O(n^3) method-escalation ladder.  Counters fold into
    HealthInfo (abft_detected / abft_corrected / abft_site).

    Auto    currently Off (the heuristic seam for future auto-enabling)
    Off     no checksum maintenance (zero overhead)
    On      checksum-verified factorizations + localized repair
    """

    Auto = "auto"
    Off = "off"
    On = "on"


class Precision(enum.Enum):
    """Working-precision policy for the certified low-precision rung
    (robust/precision.py, docs/ROBUSTNESS.md).

    ``Precision.Bf16`` makes bf16 the *first rung* of the escalation
    ladders: factor in bf16 storage with fp32 accumulation on the MXU,
    refine in f32, accept only on an a-posteriori certificate
    (robust/certify), and escalate per problem to the full-precision
    route on certificate failure.  The knob is resolved ONCE at each
    driver/serving boundary by ``robust.precision.resolve_precision``
    (the ErrorPolicy / Speculate / Abft discipline); every cast below
    the boundary goes through the ``robust/precision.py`` seam
    (slate-lint SEAM014).

    Auto    currently F32 (the heuristic seam for future auto-enabling)
    F32     full working precision everywhere (default)
    Bf16    certified bf16 first rung, f32 escalation
    """

    Auto = "auto"
    F32 = "f32"
    Bf16 = "bf16"


class Option(enum.Enum):
    """Option keys (ref: enums.hh:69-101)."""

    Lookahead = "lookahead"
    BlockSize = "block_size"
    InnerBlocking = "inner_blocking"
    MaxPanelThreads = "max_panel_threads"
    MaxIterations = "max_iterations"
    Tolerance = "tolerance"
    Target = "target"
    ErrorPolicy = "error_policy"
    Speculate = "speculate"
    Abft = "abft"
    Precision = "precision"
    UseFallbackSolver = "use_fallback_solver"
    PivotThreshold = "pivot_threshold"
    MethodGemm = "method_gemm"
    MethodHemm = "method_hemm"
    MethodTrsm = "method_trsm"
    MethodCholQR = "method_cholqr"
    MethodGels = "method_gels"
    MethodLU = "method_lu"
    MethodEig = "method_eig"
    MethodSvd = "method_svd"
    HoldLocalWorkspace = "hold_local_workspace"
    Depth = "depth"
    PrintVerbose = "print_verbose"
    PrintEdgeItems = "print_edgeitems"
    PrintWidth = "print_width"
    PrintPrecision = "print_precision"


class MethodGemm(enum.Enum):
    """gemm variant selection (ref: method.hh:76-112)."""

    Auto = "auto"
    gemmA = "gemmA"  # stationary A, reduce over C owners
    gemmC = "gemmC"  # stationary C (SUMMA); default for nt >= 2


class MethodTrsm(enum.Enum):
    """trsm variant (ref: method.hh:25-74)."""

    Auto = "auto"
    trsmA = "trsmA"  # stationary A
    trsmB = "trsmB"  # stationary B; default


class MethodHemm(enum.Enum):
    Auto = "auto"
    hemmA = "hemmA"
    hemmC = "hemmC"


class MethodCholQR(enum.Enum):
    """A^H A accumulation method inside cholqr (ref: method.hh:114-160)."""

    Auto = "auto"
    GemmA = "gemmA"
    GemmC = "gemmC"
    HerkC = "herkC"


class MethodGels(enum.Enum):
    """Least-squares path (ref: method.hh:236-275)."""

    Auto = "auto"
    QR = "qr"
    CholQR = "cholqr"


class MethodLU(enum.Enum):
    """LU pivoting variant (ref: method.hh:277-316)."""

    Auto = "auto"
    PartialPiv = "PPLU"
    CALU = "CALU"  # tournament pivoting (tntpiv)
    NoPiv = "NoPiv"


class MethodEig(enum.Enum):
    """Stage-2 eigensolver seam (ref: heev.cc:79 MethodEig).

    Auto (TPU default): eigendecompose the stage-1 BAND directly with the
    vendor kernel (XLA eigh).  The reference chases band -> tridiagonal
    because its tridiagonal kernels (steqr2/stedc) are O(n^2); XLA's eigh
    is O(n^3) dense regardless of bandwidth, so on TPU the bulge chase
    buys nothing on this seam — it is pure latency (VERDICT r3 weak #2).
    QR / DC: parity route through the hb2st bulge chase to a true
    tridiagonal, then the tridiagonal kernel (today XLA eigh of T; the
    stedc D&C seam slots in here)."""

    Auto = "auto"  # band seam: no chase (TPU-first default)
    QR = "qr"      # steqr2 analog: chase + QR-iteration seam
    DC = "dc"      # stedc analog: chase + divide-and-conquer seam


class MethodSvd(enum.Enum):
    """Stage-2 SVD seam, mirroring MethodEig (ref: svd.cc:286 bdsqr).

    Auto: SVD the stage-1 band directly (XLA svd is O(n^3) dense either
    way).  Bidiag: parity route through the tb2bd bulge chase to a true
    bidiagonal, then the bdsqr-analog seam."""

    Auto = "auto"
    Bidiag = "bidiag"


class NormScope(enum.Enum):
    Columns = "columns"
    Rows = "rows"
    Matrix = "matrix"


class GridOrder(enum.Enum):
    """Process-grid numbering order (ref: enums.hh:127-131)."""

    Col = "col"
    Row = "row"


Options = Mapping[Option, Any]

_DEFAULTS = {
    Option.Lookahead: 1,
    Option.InnerBlocking: 16,
    Option.MaxPanelThreads: 4,
    Option.MaxIterations: 30,
    Option.Tolerance: None,
    Option.Target: Target.auto,
    Option.ErrorPolicy: ErrorPolicy.Raise,
    Option.Speculate: Speculate.Auto,
    Option.Abft: Abft.Auto,
    Option.Precision: Precision.Auto,
    Option.UseFallbackSolver: True,
    Option.PivotThreshold: 1.0,
    Option.MethodGemm: MethodGemm.Auto,
    Option.MethodHemm: MethodHemm.Auto,
    Option.MethodTrsm: MethodTrsm.Auto,
    Option.MethodCholQR: MethodCholQR.Auto,
    Option.MethodGels: MethodGels.Auto,
    Option.MethodLU: MethodLU.Auto,
    Option.MethodEig: MethodEig.Auto,
    Option.MethodSvd: MethodSvd.Auto,
    Option.HoldLocalWorkspace: False,
    Option.Depth: 2,
    Option.PrintVerbose: 4,
    Option.PrintEdgeItems: 16,
    Option.PrintWidth: 10,
    Option.PrintPrecision: 4,
}


_UNSET = object()

# options whose values have a canonical enum: string spellings are accepted
# uniformly ({Option.Target: "mesh"}, {Option.ErrorPolicy: "info"}) and
# coerced here so every consumer sees the enum.
_ENUM_VALUED = {Option.Target: Target, Option.ErrorPolicy: ErrorPolicy,
                Option.Speculate: Speculate, Option.Abft: Abft,
                Option.Precision: Precision}


def get_option(opts: Options | None, key: Option,
               default: Any = _UNSET) -> Any:
    """Read one option with framework defaults (ref: types.hh:180-206).

    An explicitly passed ``default`` wins over the framework default even
    when it is None (a sentinel distinguishes "no default given" from
    ``default=None``)."""
    if opts and key in opts:
        val = opts[key]
    elif default is not _UNSET:
        val = default
    else:
        val = _DEFAULTS.get(key)
    coerce = _ENUM_VALUED.get(key)
    if coerce is not None and isinstance(val, str):
        val = coerce(val)
    return val


def resolve_target(opts: Options | None, matrix) -> Target:
    """Target::auto resolution: mesh iff the matrix lives on a >1-device grid."""
    t = get_option(opts, Option.Target)
    if t is not Target.auto:
        return t
    grid = getattr(matrix, "grid", None)
    if grid is not None and grid.size > 1:
        return Target.mesh
    return Target.single


def resolve_speculate(opts: Options | None) -> bool:
    """Resolve Option.Speculate ONCE at a driver boundary (the same
    discipline as ErrorPolicy / health.error_policy): True only for an
    explicit ``Speculate.On`` — ``Auto`` currently maps to Off so the
    default solver behavior is unchanged.  Every consumer below the
    boundary receives the decision, never the knob."""
    resolved = get_option(opts, Option.Speculate) is Speculate.On
    from .obs import events as _obs_events
    _obs_events.note_resolved("speculate", resolved)
    return resolved


def resolve_abft(opts: Options | None) -> bool:
    """Resolve Option.Abft ONCE at a driver boundary (same discipline as
    ErrorPolicy / Speculate): True only for an explicit ``Abft.On`` —
    ``Auto`` currently maps to Off so default drivers pay zero checksum
    overhead.  Every consumer below the boundary receives the resolved
    boolean, never the knob."""
    resolved = get_option(opts, Option.Abft) is Abft.On
    from .obs import events as _obs_events
    _obs_events.note_resolved("abft", resolved)
    return resolved


def select_gemm_method(opts: Options | None, nt: int) -> MethodGemm:
    """ref: method.hh:87-98 — gemmA when C is a single block column, else gemmC."""
    m = get_option(opts, Option.MethodGemm)
    if m is not MethodGemm.Auto:
        return m
    return MethodGemm.gemmA if nt < 2 else MethodGemm.gemmC


def select_trsm_method(opts: Options | None, nt: int) -> MethodTrsm:
    """ref: method.hh:56-74 — trsmA for very wide RHS stays with A; default B."""
    m = get_option(opts, Option.MethodTrsm)
    if m is not MethodTrsm.Auto:
        return m
    return MethodTrsm.trsmB


def select_gels_method(opts: Options | None, m: int, n: int) -> MethodGels:
    """ref: method.hh:236-275 — CholQR for tall-skinny well-shaped problems."""
    meth = get_option(opts, Option.MethodGels)
    if meth is not MethodGels.Auto:
        return meth
    return MethodGels.CholQR if m >= 3 * n else MethodGels.QR


def select_lu_method(opts: Options | None) -> MethodLU:
    m = get_option(opts, Option.MethodLU)
    if m is not MethodLU.Auto:
        return m
    return MethodLU.PartialPiv
