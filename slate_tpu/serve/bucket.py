"""Shape bucketing for the serving layer: ladder + ragged packing.

Mixed-size requests cannot share a compiled executable unless their
shapes agree, so every request is rounded UP to a bucket shape drawn
from a ladder (PAPERS.md "Ragged Paged Attention": pack ragged work
into fixed tile grids).  The default ladder is geometric — each rung
double the last, starting at the tile edge — because a geometric
ladder bounds padding waste at a constant factor while keeping the
number of distinct executables logarithmic in the size range.  A chip
that has been profiled can override it: ``tune.serve_buckets`` reads
``serve_bucket`` entries from the plan cache (the SEAM011-sanctioned
accessor; see docs/SERVING.md and docs/TUNING.md).

Packing is exact, not approximate: a problem of size n placed in an
n_b-bucket is augmented with the identity — ``blockdiag(A, I)`` — the
same trick ``internal/trsm.py::_pad_tri`` uses for ragged triangular
tiles.  The augmented system decouples: the first n components solve
the original problem bit-for-bit in exact arithmetic, the padding
components solve ``I x = 0``.  For least squares the identity block is
placed in fresh rows, keeping the padded operand full-rank and its
Gram matrix HPD, so both the CholQR and Householder routes accept it.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

DEFAULT_BASE = 32
DEFAULT_MAX = 8192


class BucketLadder(NamedTuple):
    """Ascending rung sizes; ``bucket_for`` rounds a size up to a rung.

    ``source`` records where the rungs came from ('geometric' or
    'tuned') for the serve-batch obs events."""

    rungs: tuple
    source: str = "geometric"

    def bucket_for(self, n: int) -> int:
        n = int(n)
        if n <= 0:
            raise ValueError(f"bucket_for: need a positive size, got {n}")
        for r in self.rungs:
            if n <= r:
                return int(r)
        # beyond the top rung: keep doubling so oversize requests still
        # bucket (and therefore still cache) instead of erroring
        top = int(self.rungs[-1])
        while top < n:
            top *= 2
        return top


def geometric_ladder(base: int = DEFAULT_BASE,
                     top: int = DEFAULT_MAX) -> BucketLadder:
    rungs = []
    r = int(base)
    while r <= top:
        rungs.append(r)
        r *= 2
    return BucketLadder(tuple(rungs), "geometric")


def default_ladder(dtype: str = "float32") -> BucketLadder:
    """The serving ladder: tuned rungs for this chip when the plan cache
    has ``serve_bucket`` entries, else the geometric default.  Dtype
    spellings normalize through the one shared helper
    (robust/precision.normalize_dtype) so ladder lookups and plan-cache
    keys can never disagree on "bf16" vs "bfloat16"."""
    from ..robust.precision import normalize_dtype
    from ..tune import serve_buckets
    tuned = serve_buckets(normalize_dtype(dtype))
    if tuned:
        return BucketLadder(tuple(int(r) for r in tuned), "tuned")
    return geometric_ladder()


def next_pow2(n: int) -> int:
    """Batch-count bucket: smallest power of two >= n (>= 1)."""
    n = max(int(n), 1)
    p = 1
    while p < n:
        p *= 2
    return p


# ------------------------------------------------------------------ packing
#
# All packers take/return plain dense arrays (host numpy or jnp) — the
# batched cores re-tile inside the executable, so the packed buffers are
# the steady-state donation surface (docs/SERVING.md).


def pad_square(a, nb: int):
    """blockdiag(A, I) in an (nb, nb) bucket — the ``_pad_tri`` idiom.

    Exact for general and HPD solves alike: the augmented matrix is
    nonsingular iff A is, and HPD iff A is."""
    n = a.shape[0]
    if a.shape[0] != a.shape[1]:
        raise ValueError(f"pad_square: need square A, got {a.shape}")
    if n > nb:
        raise ValueError(f"pad_square: A ({n}) exceeds bucket ({nb})")
    if n == nb:
        return jnp.asarray(a)
    out = jnp.eye(nb, dtype=a.dtype)
    return out.at[:n, :n].set(a)


def pad_rows(b, mb: int, kb: int):
    """Zero-pad a (m, k) right-hand side into an (mb, kb) bucket."""
    m, k = b.shape
    if m > mb or k > kb:
        raise ValueError(f"pad_rows: B {b.shape} exceeds bucket "
                         f"({mb}, {kb})")
    out = jnp.zeros((mb, kb), dtype=b.dtype)
    return out.at[:m, :k].set(b)


def pad_tall(a, mb: int, nb: int):
    """Identity-augment a tall (m, n) operand into an (mb, nb) bucket.

    The nb - n extra columns get an identity block in FRESH rows
    (m : m + nb - n), so columns stay linearly independent and the
    padded least-squares problem decouples: x_pad = [x; 0] exactly.
    Requires mb >= m + (nb - n) — ``least_squares_buckets`` picks mb
    after nb to guarantee it."""
    m, n = a.shape
    if m < n:
        raise ValueError(f"pad_tall: need m >= n, got {a.shape}")
    extra = nb - n
    if m + extra > mb:
        raise ValueError(f"pad_tall: bucket ({mb}, {nb}) cannot hold "
                         f"{a.shape} plus its {extra} identity rows")
    out = jnp.zeros((mb, nb), dtype=a.dtype)
    out = out.at[:m, :n].set(a)
    if extra:
        out = out.at[m:m + extra, n:].set(jnp.eye(extra, dtype=a.dtype))
    return out


def solve_buckets(ladder: BucketLadder, n: int, k: int):
    """Bucket dims (nb, kb) for a square solve of (n, n) x (n, k)."""
    return ladder.bucket_for(n), next_pow2(k)


def least_squares_buckets(ladder: BucketLadder, m: int, n: int, k: int):
    """Bucket dims (mb, nb, kb) for least squares: nb first, then mb
    large enough for the identity-augmentation rows."""
    nb = ladder.bucket_for(n)
    mb = ladder.bucket_for(m + (nb - n))
    return mb, nb, next_pow2(k)


def padded_fraction(real_elems: int, bucket_elems: int) -> float:
    """Padding waste of one batch: 1 - real/bucket element ratio."""
    if bucket_elems <= 0:
        return 0.0
    return 1.0 - real_elems / bucket_elems
