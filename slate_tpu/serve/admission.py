"""Deadline-aware admission control for the serving front door.

The synchronous server (server.py) accepts unboundedly and blocks the
caller until drain; that is not survivable under overload.  This module
is the survival layer's intake: a BOUNDED queue with a declared
overflow policy, per-request deadlines, and the SLO budget wired in as
a LIVE control signal (obs/slo.py's :class:`~slate_tpu.obs.slo.
LatencyGovernor`) rather than a post-hoc verdict:

- **overflow policy** (:data:`OVERFLOW_POLICIES`): ``reject`` raises a
  typed :class:`SlateServeOverloadError` at submit; ``shed_oldest``
  admits the newcomer and shed the oldest queued request (its sticky
  error lands on the victim's ticket); ``block`` parks the submitter
  until space frees or ``block_timeout_s`` elapses.
- **deadline shedding**: a request whose deadline would expire before
  the governor's rolling service-time estimate completes is shed AT
  ADMISSION with :class:`SlateServeTimeoutError` — it never wastes a
  batch slot.  Requests that age out while queued are shed at flush.
- **SLO backpressure**: while the governor's rolling latency p99 runs
  over the declared budget, the queue's effective capacity halves —
  load sheds earlier until the tail recovers.

Every submitted request gets a :class:`Ticket` — a one-shot,
first-write-wins result slot.  First-write-wins is the no-double-answer
guarantee: if the watchdog fails a wedged flush's requests and the
flush later limps home, the late delivery is dropped, not duplicated.

Thread safety: all queue state is guarded by ``_lock`` (a Condition —
the waiters are blocked producers and the parked flush loop), all
ticket state by the ticket's own ``_lock``; both are declared in the
slate-lint LockSpec registry (tools/slate_lint/rules/concurrency.py)
so CON001–003 enforce the discipline.  Lock order is queue -> governor;
ticket locks nest under nothing.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from ..exceptions import (SlateServeError, SlateServeOverloadError,
                          SlateServeTimeoutError)
from ..obs import slo as _slo

#: what happens when the bounded queue is full at submit
OVERFLOW_POLICIES = ("reject", "shed_oldest", "block")


def _closed_error(reason: str) -> SlateServeTimeoutError:
    return SlateServeTimeoutError(
        f"serve: admission closed ({reason}) — the server is wedged or "
        f"shut down", reason=reason)


@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
    """Front-door knobs (docs/SERVING.md "Survival" documents each).

    ``max_queue`` bounds pending requests; ``overflow`` picks the
    full-queue policy; ``block_timeout_s`` bounds a blocked submit;
    ``default_deadline_ms`` stamps submits that bring no deadline
    (None = no deadline); ``flush_occupancy`` / ``max_batch_delay_ms``
    are the background loop's flush watermarks (batch when this many
    are pending, or when the oldest has waited this long);
    ``watchdog_timeout_s`` is how long one flush may run before the
    watchdog declares it wedged; ``slo_budget_ms`` / ``slo_window``
    parameterize the live latency governor (None = no backpressure).

    Online ladder retuning (docs/TUNING.md "Hot-swap"):
    ``retune_interval_s`` paces the background refit tick (None = off);
    ``retune_min_samples`` is how many observed sizes the DP fitter
    needs before it argues; ``retune_margin`` is the padding-waste
    improvement a fitted ladder must show before the server hot-swaps
    it (hysteresis — a marginal win is not worth recompiling)."""

    max_queue: int = 256
    overflow: str = "reject"
    block_timeout_s: float = 1.0
    default_deadline_ms: float | None = None
    flush_occupancy: int = 8
    max_batch_delay_ms: float = 5.0
    watchdog_timeout_s: float = 30.0
    slo_budget_ms: float | None = None
    slo_window: int = 64
    retune_interval_s: float | None = None
    retune_min_samples: int = 64
    retune_margin: float = 0.05

    def __post_init__(self):
        if self.overflow not in OVERFLOW_POLICIES:
            raise ValueError(f"admission: unknown overflow policy "
                             f"{self.overflow!r} (known: "
                             f"{OVERFLOW_POLICIES})")
        if self.max_queue < 1:
            raise ValueError("admission: max_queue must be >= 1")
        if self.flush_occupancy < 1:
            raise ValueError("admission: flush_occupancy must be >= 1")
        if (self.retune_interval_s is not None
                and self.retune_interval_s <= 0):
            raise ValueError("admission: retune_interval_s must be > 0")
        if self.retune_min_samples < 1:
            raise ValueError("admission: retune_min_samples must be >= 1")


class Ticket(int):
    """Handle for one admitted request: a one-shot result slot.

    Subclasses int so the synchronous contract survives — the value is
    the request's index into the next ``drain()``'s results, exactly
    what ``submit`` has always returned.  Under the background flush
    loop (or any shedding policy) indices shift, so the DURABLE
    interface is :meth:`result`, which blocks for the outcome and
    re-raises the stored typed error — the sticky-error guarantee: a
    failed flush is re-raised at the caller's result() site, never
    silently dropped.

    Settling is first-write-wins and atomic: whichever of the flush
    loop, the watchdog, or shutdown settles first wins; later writes
    are dropped (no request is ever answered twice).  ``tid`` is the
    queue-unique request id used by the accounting tests."""

    def __new__(cls, index: int, tid: int):
        t = super().__new__(cls, index)
        t.tid = tid
        t._lock = threading.Lock()
        t._done = threading.Event()
        t._value = None
        t._error = None
        return t

    def _settle(self, value, error) -> bool:
        with self._lock:
            if self._done.is_set():
                return False
            self._value = value
            self._error = error
            self._done.set()           # inside the lock: check-then-set
            return True                # stays atomic vs a racing settler

    def deliver(self, result) -> bool:
        """Settle with a result; False if already settled (late write)."""
        return self._settle(result, None)

    def fail(self, error: BaseException) -> bool:
        """Settle with a sticky typed error; False if already settled."""
        return self._settle(None, error)

    def done(self) -> bool:
        return self._done.is_set()

    def error(self) -> BaseException | None:
        """The stored sticky error, without raising (None if none/unset)."""
        with self._lock:
            return self._error

    def result(self, timeout: float | None = None):
        """Block for the outcome; re-raises the stored typed error.
        Raises :class:`SlateServeTimeoutError` if ``timeout`` elapses
        first (the ticket itself stays unsettled and can be re-waited)."""
        if not self._done.wait(timeout):
            raise SlateServeTimeoutError(
                f"serve: result() timed out after {timeout}s "
                f"(request id {self.tid} still pending)",
                reason="result_timeout")
        with self._lock:
            if self._error is not None:
                raise self._error
            return self._value


class AdmissionQueue:
    """The bounded, deadline-aware pending queue behind Server.submit.

    State (``_items`` and the admission counters) is guarded by
    ``_lock``; producers blocked by the ``block`` overflow policy and
    the parked flush loop wait on the same Condition.  The queue never
    executes anything — it admits, sheds, and hands batches to the
    flush path via :meth:`take_all`."""

    def __init__(self, config: AdmissionConfig | None = None,
                 governor: _slo.LatencyGovernor | None = None):
        self.config = config or AdmissionConfig()
        self.governor = governor if governor is not None else \
            _slo.LatencyGovernor(self.config.slo_budget_ms,
                                 self.config.slo_window)
        self._lock = threading.Condition()
        self._items: list = []
        self._next_id = 0
        self._admitted = 0
        self._shed = 0
        self._closed: str | None = None    # close reason; None = open

    # --------------------------------------------------------- admission

    def capacity(self) -> int:
        """Effective capacity right now: ``max_queue`` scaled by the
        governor's overloaded share of the device pool — ``1 - frac/2``
        (backpressure).  A union-only stream (no per-device samples)
        reports fraction 1 when over budget, so the pre-pool behavior
        — halve the world — is the single-device special case; one
        slow member out of four only trims capacity by an eighth."""
        cap = self.config.max_queue
        frac = self.governor.overload_fraction()
        if frac > 0.0:
            cap = max(1, int(cap * (1.0 - frac / 2.0)))
        return cap

    def offer(self, build, deadline: float | None, now: float):
        """Admit one request; returns ``(ticket, shed_victims)``.

        ``build(ticket)`` constructs the Request once a slot is won (it
        runs under the queue lock and must be cheap and lock-free).
        Raises :class:`SlateServeTimeoutError` for a deadline-doomed or
        closed-queue submit and :class:`SlateServeOverloadError` for an
        overflow reject/block-timeout; ``shed_victims`` are the requests
        a ``shed_oldest`` admission evicted — the caller fails their
        tickets and emits the shed events."""
        wait_s = self.governor.estimate_wait_ms() / 1e3
        if deadline is not None and now + wait_s > deadline:
            with self._lock:
                self._shed += 1
            raise SlateServeTimeoutError(
                f"serve: request deadline expires in "
                f"{(deadline - now) * 1e3:.1f}ms but the rolling service "
                f"estimate is {wait_s * 1e3:.1f}ms — shed at admission",
                reason="deadline")
        victims: list = []
        with self._lock:
            if self._closed is not None:
                raise _closed_error(self._closed)
            cap = self.capacity()
            if len(self._items) >= cap:
                policy = self.config.overflow
                if policy == "reject":
                    self._shed += 1
                    raise SlateServeOverloadError(
                        f"serve: queue full ({len(self._items)}/{cap}) — "
                        f"request rejected", policy="reject")
                if policy == "shed_oldest":
                    while len(self._items) >= cap:
                        victims.append(self._items.pop(0))
                        self._shed += 1
                else:                                   # block
                    t_giveup = now + self.config.block_timeout_s
                    while len(self._items) >= self.capacity():
                        if self._closed is not None:
                            raise _closed_error(self._closed)
                        remaining = t_giveup - time.perf_counter()
                        if remaining <= 0:
                            self._shed += 1
                            raise SlateServeOverloadError(
                                f"serve: queue still full after blocking "
                                f"{self.config.block_timeout_s}s",
                                policy="block")
                        self._lock.wait(remaining)
            ticket = Ticket(len(self._items), self._next_id)
            self._next_id += 1
            self._admitted += 1
            self._items.append(build(ticket))
            self._lock.notify_all()        # wake the parked flush loop
        return ticket, victims

    # ------------------------------------------------------------- flush

    def take_all(self, now: float | None = None):
        """Swap out every pending request; returns ``(live, expired)``.
        Requests whose deadline already passed come back separately so
        the flush path sheds them instead of batching them."""
        now = time.perf_counter() if now is None else now
        with self._lock:
            items, self._items = self._items, []
            self._lock.notify_all()      # space freed: wake blockers
        live = [r for r in items
                if r.deadline is None or r.deadline > now]
        expired = [r for r in items
                   if not (r.deadline is None or r.deadline > now)]
        return live, expired

    def flush_due(self, now: float | None = None) -> bool:
        """Do the watermarks say a batch is due?  True when occupancy
        reaches ``flush_occupancy``, the oldest request has waited
        ``max_batch_delay_ms``, or a queued deadline has less slack
        than the governor's service estimate plus one batch delay."""
        now = time.perf_counter() if now is None else now
        with self._lock:
            if not self._items:
                return False
            if len(self._items) >= self.config.flush_occupancy:
                return True
            oldest = min(r.t_submit for r in self._items)
            if (now - oldest) * 1e3 >= self.config.max_batch_delay_ms:
                return True
            slack_s = (self.governor.estimate_wait_ms()
                       + self.config.max_batch_delay_ms) / 1e3
            return any(r.deadline is not None
                       and r.deadline - now <= slack_s
                       for r in self._items)

    def park(self, timeout_s: float) -> None:
        """Park the flush loop until work arrives (or timeout)."""
        with self._lock:
            if not self._items and self._closed is None:
                self._lock.wait(timeout_s)

    def kick(self) -> None:
        """Wake every waiter (shutdown uses this to unblock parkers)."""
        with self._lock:
            self._lock.notify_all()

    # --------------------------------------------------------- lifecycle

    def close(self, reason: str = "shutdown") -> list:
        """Refuse further admissions; returns the stranded requests
        (the caller drains or fails them — they are never dropped)."""
        with self._lock:
            if self._closed is None:
                self._closed = reason
            items, self._items = self._items, []
            self._lock.notify_all()
        return items

    def closed(self) -> str | None:
        with self._lock:
            return self._closed

    def depth(self) -> int:
        with self._lock:
            return len(self._items)

    def note_shed(self, n: int = 1) -> None:
        """Account sheds decided outside offer() (age-out at flush,
        watchdog strandings)."""
        with self._lock:
            self._shed += n

    def stats(self) -> dict:
        with self._lock:
            return {"depth": len(self._items), "admitted": self._admitted,
                    "shed": self._shed,
                    "closed": self._closed is not None}


# re-exported so serve-layer callers have one import site for the
# admission surface (serve/__init__.py publishes these)
__all__ = [
    "OVERFLOW_POLICIES", "AdmissionConfig", "AdmissionQueue", "Ticket",
    "SlateServeError", "SlateServeOverloadError", "SlateServeTimeoutError",
]
