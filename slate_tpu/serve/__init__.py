"""slate_tpu.serve — shape-bucketed ragged-batch solver serving.

The production-serving subsystem (docs/SERVING.md): streams of
mixed-size ``solve`` / ``chol_solve`` / ``least_squares_solve``
requests execute as shape-bucketed batches over the vmap-clean driver
cores, with

- a bucket ladder (geometric default, tunable via the plan cache)
  and exact identity-augmentation packing (:mod:`bucket`),
- per-problem in-graph escalation and leading-axis ``HealthInfo``
  (:mod:`batched`),
- a persistent compiled-executable cache with donated steady-state
  buffers (:mod:`cache` — the only module allowed to compile,
  slate-lint SEAM012),
- deadline-aware admission control with SLO-driven backpressure and
  typed overflow policies (:mod:`admission`),
- a ``Server`` front end emitting one obs record per batch, with an
  optional background flush loop, wedge watchdog, and poison-request
  quarantine — the survival layer of docs/SERVING.md (:mod:`server`),
- an elastic :class:`DevicePool` that round-robins batches across the
  node's accelerators, fails the SAME packed batch over to a survivor
  when a member dies (zero lost tickets, bit-identical results),
  quarantines sick members and readmits them after a clean canary
  probe (:mod:`pool` — docs/SERVING.md "Device pool").
"""

from .admission import (OVERFLOW_POLICIES, AdmissionConfig, AdmissionQueue,
                        SlateServeError, SlateServeOverloadError,
                        SlateServeTimeoutError, Ticket)
from .batched import (CORES, chol_solve_core, least_squares_core,
                      make_batched, solve_core)
from .bucket import (BucketLadder, default_ladder, geometric_ladder,
                     least_squares_buckets, next_pow2, pad_rows, pad_square,
                     pad_tall, solve_buckets)
from .cache import ExecutableCache, default_cache, options_fingerprint
from .pool import DevicePool, PoolConfig, PoolMember
from .server import SERVE_OPS, Request, Result, Server

__all__ = [
    "AdmissionConfig", "AdmissionQueue", "BucketLadder", "CORES",
    "DevicePool", "ExecutableCache", "OVERFLOW_POLICIES", "PoolConfig",
    "PoolMember", "Request", "Result",
    "SERVE_OPS", "Server", "SlateServeError", "SlateServeOverloadError",
    "SlateServeTimeoutError", "Ticket", "chol_solve_core", "default_cache",
    "default_ladder", "geometric_ladder", "least_squares_buckets",
    "least_squares_core", "make_batched", "next_pow2",
    "options_fingerprint", "pad_rows", "pad_square", "pad_tall",
    "solve_buckets", "solve_core",
]
