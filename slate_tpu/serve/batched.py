"""vmap-clean single-problem solve cores with in-graph escalation.

The eager recovery ladders (robust/recovery.py) branch on HOST health
values, which a vmapped problem cannot do — every problem in a batch
shares one trace.  These cores are the serving-layer counterpart: the
fast attempt runs first, ``health.acceptable`` gates a ``lax.cond``
into the safe attempt, and under ``vmap`` that cond lowers to a
per-problem select — both rungs execute batched, each problem keeps
whichever its own health chose.  That is the deliberate trade: a
factor-of-two worst case on the escalating bucket instead of a host
round-trip that would serialize the whole batch (docs/SERVING.md).

Ladders (mirroring the eager ones, truncated to two rungs so the cond
stays one level):

- ``solve``                NoPiv LU (speculative, growth-gated)  -> PartialPiv LU
- ``chol_solve``           Cholesky                              -> PartialPiv LU
- ``least_squares_solve``  CholQR semi-normal equations          -> Householder QR

Every core returns ``(x_dense, HealthInfo, escalated)``; vmapped, the
HealthInfo comes back as a leading-axis pytree (one scalar per problem
per field — including the per-problem ABFT counters when
``Option.Abft`` is on) and ``escalated`` as a per-problem bool.

Ragged fast rungs: when the tune/ plan cache resolves a Pallas plan for
``batch_potrf`` / ``batch_getrf`` / ``batch_geqrf`` at the bucket size
(`_ragged_plan`), the batch's fast rung runs as ONE ragged batched
Pallas factorization (internal/batched.py) whose grid consumes the
per-problem size vector via scalar prefetch — each problem computes
only its own tiles instead of the full identity-padded bucket.  The
escalation ladder is unchanged: the batched fast-rung health feeds the
same per-problem ``lax.cond`` (`_vmap_escalate`), whose safe rung is
the identical per-problem driver attempt.  A plan miss (or a dtype /
option the ragged rung does not implement) falls back to the vmapped
cores; both routes share one ``fn(a, b, sizes)`` executable signature,
so routing never costs the warm server a retrace.

Precision rung (``Option.Precision = bf16``, or bf16 operands): one
more rung BELOW the ladders above — factor in bf16 storage with f32
accumulation (the bf16 batched Pallas kernels when the plan cache
resolves one under the ``bfloat16`` plan key, a whole-bucket XLA factor
of the bf16-rounded operand otherwise), refine with one-two f32 IR
sweeps against the ORIGINAL operands, and accept each problem only on
an a-posteriori certificate (robust/certify.certify_solve /
certify_lstsq).  A failed certificate escalates that problem — and only
that problem — to the f32 route, whose result is computed by the
UNCHANGED code above and is therefore bit-identical to serving with the
rung disabled.  Dtypes are canonicalized once at the boundary
(robust/precision.normalize_dtype); an unsupported dtype raises
``SlateUnsupportedDtypeError`` instead of quietly taking a slow route.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.matrix import HermitianMatrix, Matrix
from ..core.storage import TileStorage
from ..options import ErrorPolicy, Option, Options, resolve_abft
from ..robust import certify as _cert
from ..robust import health as _h
from ..robust import precision as _prec
from ..types import Uplo

_TILE = 128

# dtypes the serving boundary accepts: f32 (both routes), bf16 (the
# certified precision rung), f64 (vmapped XLA cores only).  Anything
# else raises SlateUnsupportedDtypeError at the boundary.
SERVE_DTYPES = ("float32", "bfloat16", "float64")


def _tile(n: int) -> int:
    """Static tile edge for bucket-shaped operands: one tile up to
    _TILE, then the largest divisor-free cap the drivers pad anyway."""
    return min(int(n), _TILE)


def _info(opts: Options | None) -> dict:
    o = dict(opts or {})
    o[Option.ErrorPolicy] = ErrorPolicy.Info
    return o


def _demote(h, dtype):
    """The bounded_retry growth gate, in-graph: catastrophic pivot
    growth reads as not-converged so it both escalates and stays
    visible in the returned health."""
    return h._replace(
        converged=h.converged & (h.growth <= _h.growth_limit(dtype)))


def _mat(dense, t: int) -> Matrix:
    return Matrix(TileStorage.from_dense(dense, t, t))


def _cond_escalate(h1, x1, safe, operands, dtype):
    """Shared escalation seam: keep the fast attempt where its health is
    acceptable, run ``safe`` where not.  jit: one branch executes.
    vmap: both branches run batched, selected per problem."""
    escalated = ~_h.acceptable(h1, dtype)
    x, h = lax.cond(escalated, safe, lambda ops: (x1, h1), operands)
    return x, h, escalated


# ------------------------------------------------------------------- cores


def solve_core(a: jax.Array, b: jax.Array, opts: Options | None = None):
    """General solve A x = b on bucket-shaped dense operands.

    Fast rung: NoPiv LU — no pivot search, the serving speculation —
    plus two sweeps of iterative refinement in the original system
    (the ``_rbt_attempt`` recipe), demoted on pivot growth beyond
    ``health.growth_limit`` exactly like the eager speculative path.
    Safe rung: partial-pivot LU."""
    from ..drivers import lu as _lu
    t = _tile(a.shape[0])
    o = _info(opts)

    def attempt(factor, ops, ir_steps):
        ad, bd = ops
        F, fh = factor(_mat(ad, t), o)
        xd = _lu.getrs(F, _mat(bd, t), o).to_dense()
        for _ in range(ir_steps):          # r = b - A x, dx through F
            rd = bd - ad @ xd
            xd = xd + _lu.getrs(F, _mat(rd, t), o).to_dense()
        h = _h.merge(fh, _h.from_result(xd))
        return xd, _demote(h, ad.dtype)

    x1, h1 = attempt(_lu.getrf_nopiv, (a, b), 2)
    return _cond_escalate(h1, x1,
                          lambda ops: attempt(_lu.getrf, ops, 0),
                          (a, b), a.dtype)


def chol_solve_core(a: jax.Array, b: jax.Array,
                    opts: Options | None = None):
    """HPD solve on bucket-shaped dense operands (full symmetric ``a``).

    Fast rung: Cholesky — an indefinite problem NaN-fills its factor,
    reads ``nonfinite`` and escalates.  Safe rung: partial-pivot LU,
    which solves any nonsingular Hermitian system."""
    from ..drivers import cholesky as _chol
    from ..drivers import lu as _lu
    t = _tile(a.shape[0])
    o = _info(opts)

    def chol(ops):
        ad, bd = ops
        H = HermitianMatrix._from_view(_mat(ad, t), Uplo.Lower)
        L, fh = _chol.potrf(H, o)
        X = _chol.potrs(L, _mat(bd, t), o)
        h = _h.merge(fh, _h.from_result(X.storage.data))
        return X.to_dense(), _demote(h, ad.dtype)

    def lu(ops):
        ad, bd = ops
        F, fh = _lu.getrf(_mat(ad, t), o)
        X = _lu.getrs(F, _mat(bd, t), o)
        h = _h.merge(fh, _h.from_result(X.storage.data))
        return X.to_dense(), _demote(h, ad.dtype)

    x1, h1 = chol((a, b))
    return _cond_escalate(h1, x1, lu, (a, b), a.dtype)


def least_squares_core(a: jax.Array, b: jax.Array,
                       opts: Options | None = None):
    """Least squares min ||A x - b|| on bucket-shaped (mb, nb) operands.

    Fast rung: CholQR semi-normal equations — rank deficiency or squared
    conditioning fails the Gram Cholesky and escalates.  Safe rung:
    Householder QR.  Returns x of shape (nb, kb)."""
    from ..drivers import qr as _qr
    t = _tile(a.shape[1])
    o = _info(opts)

    def cholqr(ops):
        ad, bd = ops
        X, h = _qr._gels_cholqr_attempt(_mat(ad, t), _mat(bd, t), o)
        return X.to_dense(), _demote(h, ad.dtype)

    def house(ops):
        ad, bd = ops
        X, h = _qr._gels_qr_attempt(_mat(ad, t), _mat(bd, t), o)
        return X.to_dense(), _demote(h, ad.dtype)

    x1, h1 = cholqr((a, b))
    return _cond_escalate(h1, x1, house, (a, b), a.dtype)


CORES = {
    "solve": solve_core,
    "chol_solve": chol_solve_core,
    "least_squares_solve": least_squares_core,
}

# ------------------------------------------------------------ ragged route

# plan-cache op implementing each serve op's fast rung as ONE ragged
# batched Pallas factorization (internal/batched.py) instead of a
# vmapped full-bucket XLA driver
RAGGED_OPS = {
    "solve": "batch_getrf",
    "chol_solve": "batch_potrf",
    "least_squares_solve": "batch_geqrf",
}


def _interpret() -> bool:
    # slate-lint: disable=TRC001 -- capability probe: backend kind is host-static, never tracer data
    return jax.default_backend() != "tpu"


def _ragged_plan(op: str, a: jax.Array, opts: Options | None,
                 dtype: str | None = None):
    """The measured routing decision, taken at TRACE time from static
    shape/dtype/plan data: the ragged batched kernel runs only when the
    tune/ plan cache (or a plan_override) hands back a Pallas plan for
    this op's batch kernel at this bucket size — `tune.resolve_plan` is
    the ONLY selection seam (SEAM011), exactly as for the single-shot
    drivers.  ``dtype`` overrides the plan-key dtype (the precision
    rung factors in bf16 while ``a`` itself stays f32); spellings are
    canonicalized so a ``jnp.bfloat16`` object and the ``"bfloat16"``
    string hit the same plan row.  Returns the plan with nb clamped to
    the bucket, or None for the vmapped-XLA fallback (plan miss, a
    dtype the Pallas panels don't implement, or an option the ragged
    rung does not implement)."""
    from .. import tune as _tune
    nb_bucket = int(a.shape[2] if op == "least_squares_solve"
                    else a.shape[1])
    dtype = _prec.normalize_dtype(a.dtype if dtype is None else dtype)
    if dtype not in (_prec.HIGH, _prec.LOW):
        return None
    if resolve_abft(opts) and op != "chol_solve":
        # only batch_potrf carries the checksum rungs in-batch; the
        # other ops honor Abft through the vmapped driver cores
        return None
    plan = _tune.resolve_plan(RAGGED_OPS[op], nb_bucket, dtype)
    if plan.kernel != "pallas":
        return None
    nb = min(int(plan.nb), nb_bucket)
    if nb_bucket % nb or nb % max(int(plan.bw), 8):
        return None
    return plan._replace(nb=nb)


def _vmap_escalate(h1, x1, safe, operands, dtype):
    """Batched escalation seam: per-problem lax.cond against the ragged
    fast rung's batched health — identical branch pytrees to the
    vmapped cores', so escalating problems get exactly the safe rung
    they would have gotten on the vmapped route."""
    return jax.vmap(
        lambda h1i, x1i, *ops: _cond_escalate(h1i, x1i, safe, ops, dtype)
    )(h1, x1, *operands)


def _ragged_solve(a, b, sizes, plan, opts: Options | None):
    """solve fast rung via batch_getrf (ragged NoPiv LU + 2 IR sweeps),
    safe rung the per-problem partial-pivot LU."""
    from ..drivers import lu as _lu
    from ..internal import batched as _bk
    t = _tile(a.shape[1])
    o = _info(opts)
    fa = _bk.batch_getrf(a, sizes, nb=plan.nb, bw=plan.bw,
                         interpret=_interpret())
    x = _bk.batch_getrs(fa, b)
    for _ in range(2):                     # r = b - A x, dx through fa
        x = x + _bk.batch_getrs(fa, b - a @ x)
    h1 = _h.merge(_bk.batch_lu_health(a, fa),
                  jax.vmap(_h.from_result)(x))
    h1 = _demote(h1, a.dtype)

    def safe(ops):
        ad, bd = ops
        F, fh = _lu.getrf(_mat(ad, t), o)
        xd = _lu.getrs(F, _mat(bd, t), o).to_dense()
        h = _h.merge(fh, _h.from_result(xd))
        return xd, _demote(h, ad.dtype)

    return _vmap_escalate(h1, x, safe, (a, b), a.dtype)


def _ragged_chol(a, b, sizes, plan, opts: Options | None):
    """chol_solve fast rung via batch_potrf (with the in-batch ABFT
    rungs when Option.Abft is on), safe rung the per-problem
    partial-pivot LU — the same ladder as chol_solve_core."""
    from ..drivers import lu as _lu
    from ..internal import batched as _bk
    t = _tile(a.shape[1])
    o = _info(opts)
    fa, counts = _bk.batch_potrf(a, sizes, nb=plan.nb, bw=plan.bw,
                                 interpret=_interpret(),
                                 abft=resolve_abft(opts))
    y = lax.linalg.triangular_solve(fa, b, left_side=True, lower=True)
    x = lax.linalg.triangular_solve(fa, y, left_side=True, lower=True,
                                    transpose_a=True)
    h1 = _bk.batch_chol_health(fa)._replace(
        abft_detected=counts.detected, abft_corrected=counts.corrected,
        abft_site=counts.site)
    h1 = _demote(_h.merge(h1, jax.vmap(_h.from_result)(x)), a.dtype)

    def lu(ops):
        ad, bd = ops
        F, fh = _lu.getrf(_mat(ad, t), o)
        X = _lu.getrs(F, _mat(bd, t), o)
        h = _h.merge(fh, _h.from_result(X.storage.data))
        return X.to_dense(), _demote(h, ad.dtype)

    return _vmap_escalate(h1, x, lu, (a, b), a.dtype)


def _ragged_lstsq(a, b, sizes, plan, opts: Options | None):
    """least_squares_solve fast rung via batch_geqrf (ragged Householder
    QR — rank-revealing on |diag R|), safe rung the per-problem
    Householder QR driver."""
    from ..drivers import qr as _qr
    from ..internal import batched as _bk
    nb = a.shape[2]
    t = _tile(nb)
    o = _info(opts)
    x, packed = _bk.batch_gels(a, b, sizes, nb=plan.nb,
                               interpret=_interpret())

    def hone(p, xi):
        d = jnp.abs(jnp.diagonal(p[:nb, :nb]))
        return _h.merge(_h.from_pivots(d), _h.from_result(xi))

    h1 = _demote(jax.vmap(hone)(packed, x), a.dtype)

    def house(ops):
        ad, bd = ops
        X, h = _qr._gels_qr_attempt(_mat(ad, t), _mat(bd, t), o)
        return X.to_dense(), _demote(h, ad.dtype)

    return _vmap_escalate(h1, x, house, (a, b), a.dtype)


RAGGED_CORES = {
    "solve": _ragged_solve,
    "chol_solve": _ragged_chol,
    "least_squares_solve": _ragged_lstsq,
}

# ---------------------------------------------------------- precision rung


def _fro_batch(v):
    """Per-problem Frobenius norms of a [B, m, n] stack, f32."""
    v = _prec.promote(v)
    return jnp.sqrt(jnp.sum(v * v, axis=(1, 2)))


def _bf16_chol_attempt(a, b, sizes, plan, opts: Options | None):
    """bf16 Cholesky attempt: factor the demoted bucket (ragged Pallas
    when ``plan`` keys a bf16 kernel, whole-bucket XLA otherwise), solve
    + 2 IR sweeps in f32 against the ORIGINAL operands, certify per
    problem.  Returns ``(x, h)`` with the certificate folded in."""
    from ..internal import batched as _bk
    al = _prec.demote(a)
    if plan is not None:
        fal, counts = _bk.batch_potrf(al, sizes, nb=plan.nb, bw=plan.bw,
                                      interpret=_interpret(),
                                      abft=resolve_abft(opts))
    else:
        # bf16 factor storage emulated around the batched XLA factor
        fal = _prec.demote(lax.linalg.cholesky(_prec.promote(al)))
        counts = None
    fa = _prec.promote(fal)

    def solve(rhs):
        y = lax.linalg.triangular_solve(fa, rhs, left_side=True, lower=True)
        return lax.linalg.triangular_solve(fa, y, left_side=True,
                                           lower=True, transpose_a=True)

    x = solve(b)
    for _ in range(2):                     # f32 IR against the ORIGINAL a
        x = x + solve(b - a @ x)
    r = b - a @ x
    cert = jax.vmap(
        lambda an, xi, bi, ri: _cert.certify_solve(an, xi, bi, ri, iters=2)
    )(_fro_batch(a), x, b, r)
    h1 = _bk.batch_chol_health(fa)
    if counts is not None:
        h1 = h1._replace(abft_detected=counts.detected,
                         abft_corrected=counts.corrected,
                         abft_site=counts.site)
    h1 = _h.merge(h1, cert, jax.vmap(_h.from_result)(x))
    return x, _demote(h1, a.dtype)


def _bf16_solve_attempt(a, b, sizes, plan, opts: Options | None):
    """bf16 LU attempt: ragged NoPiv batch_getrf on the demoted bucket
    (partial-pivot XLA LU when no bf16 plan resolves), f32 solves + 2 IR
    sweeps against the original operands, per-problem certificate."""
    from ..internal import batched as _bk
    al = _prec.demote(a)
    if plan is not None:
        fal = _bk.batch_getrf(al, sizes, nb=plan.nb, bw=plan.bw,
                              interpret=_interpret())
        getrs = lambda rhs: _bk.batch_getrs(fal, rhs)  # noqa: E731
        fh = _bk.batch_lu_health(a, _prec.promote(fal))
    else:
        lu, _, perm = lax.linalg.lu(_prec.promote(al))
        fa = _prec.promote(_prec.demote(lu))   # bf16 factor storage

        def getrs(rhs):
            pb = jnp.take_along_axis(rhs, perm[:, :, None], axis=1)
            y = lax.linalg.triangular_solve(fa, pb, left_side=True,
                                            lower=True, unit_diagonal=True)
            return lax.linalg.triangular_solve(fa, y, left_side=True,
                                               lower=False)

        fh = _bk.batch_lu_health(a, fa)
    x = getrs(b)
    for _ in range(2):                     # f32 IR against the ORIGINAL a
        x = x + getrs(b - a @ x)
    r = b - a @ x
    cert = jax.vmap(
        lambda an, xi, bi, ri: _cert.certify_solve(an, xi, bi, ri, iters=2)
    )(_fro_batch(a), x, b, r)
    h1 = _h.merge(fh, cert, jax.vmap(_h.from_result)(x))
    return x, _demote(h1, a.dtype)


def _bf16_lstsq_attempt(a, b, sizes, plan, opts: Options | None):
    """bf16 least-squares attempt: ragged batch_gels on the demoted
    bucket (whole-bucket XLA QR when no bf16 plan resolves), one
    corrected-semi-normal-equations sweep through the bf16 R in f32
    against the original operands, per-problem normal-equations
    certificate (certify_lstsq)."""
    from ..internal import batched as _bk
    nb = a.shape[2]
    al = _prec.demote(a)
    if plan is not None:
        x, packed = _bk.batch_gels(al, b, sizes, nb=plan.nb,
                                   interpret=_interpret())
        R = _prec.promote(packed[:, :nb, :nb])
    else:
        q, r = lax.linalg.qr(_prec.promote(al), full_matrices=False)
        R = _prec.promote(_prec.demote(r))     # bf16 factor storage
        qtb = jnp.matmul(jnp.swapaxes(_prec.promote(_prec.demote(q)), 1, 2),
                         b)
        x = lax.linalg.triangular_solve(R, qtb, left_side=True, lower=False)
    at = jnp.swapaxes(a, 1, 2)

    def csne(rhs):                          # R^T R dx = A^T rhs (Björck)
        g = at @ rhs
        z = lax.linalg.triangular_solve(R, g, left_side=True, lower=False,
                                        transpose_a=True)
        return lax.linalg.triangular_solve(R, z, left_side=True,
                                           lower=False)

    for _ in range(2):                      # f32 CSNE against ORIGINAL a
        x = x + csne(b - a @ x)
    rn = at @ (b - a @ x)
    cert = jax.vmap(_cert.certify_lstsq)(_fro_batch(a), x, b, rn)
    d = jnp.abs(jnp.diagonal(R, axis1=1, axis2=2))
    # normal-equations certification is a backward-error gate that a
    # rank-collapsed rounding can pass trivially (huge ||x|| swamps the
    # denominator); fold a conditioning estimate through R's diagonal
    # into ``growth`` so health.acceptable escalates those problems
    piv = jax.vmap(_h.from_pivots)(d)
    piv = piv._replace(growth=_fro_batch(a) / jnp.maximum(
        jnp.min(d, axis=1), jnp.finfo(R.dtype).tiny))
    h1 = _h.merge(piv, cert, jax.vmap(_h.from_result)(x))
    return x, _demote(h1, a.dtype)


BF16_ATTEMPTS = {
    "solve": _bf16_solve_attempt,
    "chol_solve": _bf16_chol_attempt,
    "least_squares_solve": _bf16_lstsq_attempt,
}


def _bf16_rung(op: str, a, b, sizes, opts: Options | None):
    """The certified precision rung: bf16 fast attempt below the f32
    ladders.  The f32 route — ragged or vmapped, picked by the SAME plan
    logic as with the rung disabled — computes every problem's
    escalation target with unchanged code, so a certificate failure
    escalates that problem (and only that problem, via the per-problem
    ``lax.cond``) onto a result bit-identical to the f32-only route.
    The returned ``escalated`` flags certificate failures: the bench's
    accept-rate is ``1 - mean(escalated)`` over live slots."""
    plan_lo = _ragged_plan(op, a, opts, dtype=_prec.LOW)
    x1, h1 = BF16_ATTEMPTS[op](a, b, sizes, plan_lo, opts)
    plan32 = _ragged_plan(op, a, opts)
    if plan32 is not None:
        x32, h32, _ = RAGGED_CORES[op](a, b, sizes, plan32, opts)
    else:
        core = CORES[op]
        x32, h32, _ = jax.vmap(lambda ai, bi: core(ai, bi, opts))(a, b)
    return _vmap_escalate(h1, x1, lambda ops: ops, (x32, h32), a.dtype)


def make_batched(op: str, opts: Options | None = None):
    """The leading-axis-batched core for one op: ``fn(a, b, sizes)``.

    ``sizes`` is the per-problem live-size vector ([B] int32: n for
    square solves, m + (nb - n) live rows for least squares, 0 for
    filler slots).  At trace time `_ragged_plan` consults the tune/
    plan cache: a Pallas plan routes the fast rung through the ragged
    batched kernels (each problem computes only its own tiles), a miss
    vmaps the per-problem cores over the full bucket — which ignore
    ``sizes`` entirely, so both routes share one executable signature
    and the warm server stays retrace-free whichever is picked.  ``opts``
    is closed over as static configuration (it participates in the
    executable-cache fingerprint, never in the traced data).

    ``Option.Precision = bf16`` (resolved ONCE here, the seam contract)
    inserts the certified bf16 rung below the f32 ladder for f32
    buckets; bf16 operands take the same rung unconditionally (promoted
    working copies, results demoted back).  f64 serves on the vmapped
    XLA cores; any other dtype raises SlateUnsupportedDtypeError at the
    boundary instead of quietly taking a slow route."""
    core = CORES[op]
    bf16_rung = _prec.resolve_precision(opts)

    def fn(a, b, sizes):
        dtype = _prec.normalize_dtype(a.dtype, supported=SERVE_DTYPES)
        low = dtype == _prec.LOW
        if low:
            a, b = _prec.promote(a), _prec.promote(b)
        if low or (bf16_rung and dtype == _prec.HIGH):
            x, h, esc = _bf16_rung(op, a, b, sizes, opts)
            return (_prec.demote(x) if low else x), h, esc
        plan = _ragged_plan(op, a, opts)
        if plan is not None:
            return RAGGED_CORES[op](a, b, sizes, plan, opts)
        del sizes                          # vmapped route pads to bucket
        return jax.vmap(lambda ai, bi: core(ai, bi, opts))(a, b)

    return fn
