"""vmap-clean single-problem solve cores with in-graph escalation.

The eager recovery ladders (robust/recovery.py) branch on HOST health
values, which a vmapped problem cannot do — every problem in a batch
shares one trace.  These cores are the serving-layer counterpart: the
fast attempt runs first, ``health.acceptable`` gates a ``lax.cond``
into the safe attempt, and under ``vmap`` that cond lowers to a
per-problem select — both rungs execute batched, each problem keeps
whichever its own health chose.  That is the deliberate trade: a
factor-of-two worst case on the escalating bucket instead of a host
round-trip that would serialize the whole batch (docs/SERVING.md).

Ladders (mirroring the eager ones, truncated to two rungs so the cond
stays one level):

- ``solve``                NoPiv LU (speculative, growth-gated)  -> PartialPiv LU
- ``chol_solve``           Cholesky                              -> PartialPiv LU
- ``least_squares_solve``  CholQR semi-normal equations          -> Householder QR

Every core returns ``(x_dense, HealthInfo, escalated)``; vmapped, the
HealthInfo comes back as a leading-axis pytree (one scalar per problem
per field — including the per-problem ABFT counters when
``Option.Abft`` is on) and ``escalated`` as a per-problem bool.
"""

from __future__ import annotations

import jax
from jax import lax

from ..core.matrix import HermitianMatrix, Matrix
from ..core.storage import TileStorage
from ..options import ErrorPolicy, Option, Options
from ..robust import health as _h
from ..types import Uplo

_TILE = 128


def _tile(n: int) -> int:
    """Static tile edge for bucket-shaped operands: one tile up to
    _TILE, then the largest divisor-free cap the drivers pad anyway."""
    return min(int(n), _TILE)


def _info(opts: Options | None) -> dict:
    o = dict(opts or {})
    o[Option.ErrorPolicy] = ErrorPolicy.Info
    return o


def _demote(h, dtype):
    """The bounded_retry growth gate, in-graph: catastrophic pivot
    growth reads as not-converged so it both escalates and stays
    visible in the returned health."""
    return h._replace(
        converged=h.converged & (h.growth <= _h.growth_limit(dtype)))


def _mat(dense, t: int) -> Matrix:
    return Matrix(TileStorage.from_dense(dense, t, t))


def _cond_escalate(h1, x1, safe, operands, dtype):
    """Shared escalation seam: keep the fast attempt where its health is
    acceptable, run ``safe`` where not.  jit: one branch executes.
    vmap: both branches run batched, selected per problem."""
    escalated = ~_h.acceptable(h1, dtype)
    x, h = lax.cond(escalated, safe, lambda ops: (x1, h1), operands)
    return x, h, escalated


# ------------------------------------------------------------------- cores


def solve_core(a: jax.Array, b: jax.Array, opts: Options | None = None):
    """General solve A x = b on bucket-shaped dense operands.

    Fast rung: NoPiv LU — no pivot search, the serving speculation —
    plus two sweeps of iterative refinement in the original system
    (the ``_rbt_attempt`` recipe), demoted on pivot growth beyond
    ``health.growth_limit`` exactly like the eager speculative path.
    Safe rung: partial-pivot LU."""
    from ..drivers import lu as _lu
    t = _tile(a.shape[0])
    o = _info(opts)

    def attempt(factor, ops, ir_steps):
        ad, bd = ops
        F, fh = factor(_mat(ad, t), o)
        xd = _lu.getrs(F, _mat(bd, t), o).to_dense()
        for _ in range(ir_steps):          # r = b - A x, dx through F
            rd = bd - ad @ xd
            xd = xd + _lu.getrs(F, _mat(rd, t), o).to_dense()
        h = _h.merge(fh, _h.from_result(xd))
        return xd, _demote(h, ad.dtype)

    x1, h1 = attempt(_lu.getrf_nopiv, (a, b), 2)
    return _cond_escalate(h1, x1,
                          lambda ops: attempt(_lu.getrf, ops, 0),
                          (a, b), a.dtype)


def chol_solve_core(a: jax.Array, b: jax.Array,
                    opts: Options | None = None):
    """HPD solve on bucket-shaped dense operands (full symmetric ``a``).

    Fast rung: Cholesky — an indefinite problem NaN-fills its factor,
    reads ``nonfinite`` and escalates.  Safe rung: partial-pivot LU,
    which solves any nonsingular Hermitian system."""
    from ..drivers import cholesky as _chol
    from ..drivers import lu as _lu
    t = _tile(a.shape[0])
    o = _info(opts)

    def chol(ops):
        ad, bd = ops
        H = HermitianMatrix._from_view(_mat(ad, t), Uplo.Lower)
        L, fh = _chol.potrf(H, o)
        X = _chol.potrs(L, _mat(bd, t), o)
        h = _h.merge(fh, _h.from_result(X.storage.data))
        return X.to_dense(), _demote(h, ad.dtype)

    def lu(ops):
        ad, bd = ops
        F, fh = _lu.getrf(_mat(ad, t), o)
        X = _lu.getrs(F, _mat(bd, t), o)
        h = _h.merge(fh, _h.from_result(X.storage.data))
        return X.to_dense(), _demote(h, ad.dtype)

    x1, h1 = chol((a, b))
    return _cond_escalate(h1, x1, lu, (a, b), a.dtype)


def least_squares_core(a: jax.Array, b: jax.Array,
                       opts: Options | None = None):
    """Least squares min ||A x - b|| on bucket-shaped (mb, nb) operands.

    Fast rung: CholQR semi-normal equations — rank deficiency or squared
    conditioning fails the Gram Cholesky and escalates.  Safe rung:
    Householder QR.  Returns x of shape (nb, kb)."""
    from ..drivers import qr as _qr
    t = _tile(a.shape[1])
    o = _info(opts)

    def cholqr(ops):
        ad, bd = ops
        X, h = _qr._gels_cholqr_attempt(_mat(ad, t), _mat(bd, t), o)
        return X.to_dense(), _demote(h, ad.dtype)

    def house(ops):
        ad, bd = ops
        X, h = _qr._gels_qr_attempt(_mat(ad, t), _mat(bd, t), o)
        return X.to_dense(), _demote(h, ad.dtype)

    x1, h1 = cholqr((a, b))
    return _cond_escalate(h1, x1, house, (a, b), a.dtype)


CORES = {
    "solve": solve_core,
    "chol_solve": chol_solve_core,
    "least_squares_solve": least_squares_core,
}


def make_batched(op: str, opts: Options | None = None):
    """The leading-axis-batched core for one op: vmap over problems.
    ``opts`` is closed over as static configuration (it participates in
    the executable-cache fingerprint, never in the traced data)."""
    core = CORES[op]
    return jax.vmap(lambda a, b: core(a, b, opts))
