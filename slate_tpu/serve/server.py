"""The serving front end: submit mixed-size solves, drain bucketed batches.

Synchronous usage (unchanged)::

    from slate_tpu import serve

    srv = serve.Server()
    t0 = srv.submit("solve", a0, b0)              # (n0, n0), (n0, k0)
    t1 = srv.submit("chol_solve", a1, b1)
    t2 = srv.submit("least_squares_solve", a2, b2)
    results = srv.drain()                         # [Result] in submit order

Survival-layer usage (the background front door)::

    srv = serve.Server(admission=serve.AdmissionConfig(
        max_queue=64, overflow="shed_oldest", default_deadline_ms=250,
        slo_budget_ms=250))
    srv.start()                                   # flush loop + watchdog
    t = srv.submit("solve", a, b)                 # admission-controlled
    x = t.result(timeout=1.0).x                   # sticky typed errors
    srv.shutdown()                                # drains or fails loudly

Each flush groups pending requests by ``(op, dtype, bucket)``,
identity-pads every problem to its bucket (bucket.py), rounds the
batch count up to a power of two with identity filler slots, runs the
bucket's cached executable (cache.py — compiled once, B donated), and
unpacks per-problem results, ``HealthInfo`` and escalation flags.

Survival properties (docs/SERVING.md "Survival"):

- **admission control / backpressure** — submit goes through the
  bounded :class:`~slate_tpu.serve.admission.AdmissionQueue`: overflow
  policy, per-request deadlines, and SLO-budget backpressure (the
  rolling-latency governor) decide at admission; shed requests carry
  typed errors, never silence.
- **background flush loop** — a daemon thread batches by occupancy /
  age / deadline-slack watermarks while callers keep submitting; a
  watchdog daemon declares a flush wedged after ``watchdog_timeout_s``
  and fails every pending request loudly with
  :class:`SlateServeTimeoutError` instead of blocking callers forever.
  Tickets are first-write-wins, so a wedged flush that later limps
  home cannot double-answer.
- **poison quarantine** — a problem that exhausts the in-graph
  escalation ladder (``escalated`` with unhealthy ``HealthInfo``) is
  retried at most once in a fresh batch, then quarantined to a
  singleton slow path; its neighbors' batches never carry it again.
- **sticky errors** — a failed flush stores its typed error on every
  affected ticket AND on the server; the next ``drain()`` re-raises it
  even when the queue is already empty.

One ``slate-obs-v1`` record of kind ``serve_batch`` is emitted per
executed batch; sheds and quarantines emit ``serve_shed`` /
``serve_quarantine`` records (obs/events.py) feeding the ``shed/1k``
and ``quar/1k`` columns of the ``python -m slate_tpu.obs`` serving
table.  The flight-recorder fields (queue depth, per-problem
``age_at_flush_ms`` / ``latency_ms``, device-time ``mfu`` under
``obs.timing()``) are unchanged from the synchronous server.
"""

from __future__ import annotations

import threading
import time
from concurrent import futures
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..exceptions import SlateServeError, SlateServeTimeoutError
from ..obs import events as _events
from ..obs import flops as _flops
from ..obs import sentinel as _sentinel
from ..options import Options
from ..robust import faults as _faults
from ..robust.health import HealthInfo
from . import admission as _admission
from . import bucket as _bucket
from . import cache as _cache
from . import pool as _pool

SERVE_OPS = ("solve", "chol_solve", "least_squares_solve")


class Request(NamedTuple):
    """One pending problem: ``op`` in SERVE_OPS, dense ``a``/``b``, the
    flight-recorder submit stamp (perf_counter seconds), the admission
    ticket, the absolute deadline (perf_counter seconds, None = never),
    and how many batched attempts have come back poison (strikes: one
    earns the fresh-batch retry, two the quarantine slow path)."""
    op: str
    a: np.ndarray
    b: np.ndarray
    t_submit: float = 0.0
    ticket: object = None
    deadline: float | None = None
    retries: int = 0


class Result(NamedTuple):
    """One served problem: solution, per-problem health, whether the
    in-graph safety rung produced it."""
    x: np.ndarray
    health: HealthInfo
    escalated: bool


def _as_2d(x, name: str) -> np.ndarray:
    x = np.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"serve: {name} must be 2-D, got shape {x.shape}")
    return x


def _poison(req: Request, res: Result) -> bool:
    """Did this problem exhaust the in-graph escalation ladder?  The
    safety rung ran AND still reports unhealthy — the per-request
    analog of a tile fault the recovery ladder could not repair."""
    return bool(res.escalated) and not bool(res.health.ok)


class Server:
    """Shape-bucketed batch server over the vmap-clean solve cores.

    ``opts`` apply to every request (they are part of the executable
    fingerprint); ``ladder`` overrides the bucket ladder (default:
    tuned rungs when the plan cache has them, else geometric);
    ``cache`` shares or isolates the executable store (default: the
    process-wide cache); ``admission`` configures the survival layer
    (default :class:`AdmissionConfig`: effectively the old unbounded
    synchronous behavior — queue of 256, no deadlines, no loop until
    :meth:`start`); ``governor`` injects a shared latency governor.

    ``pool`` / ``devices`` configure the elastic device pool
    (serve/pool.py): pass ``devices=jax.local_devices()`` (or a
    prebuilt :class:`~slate_tpu.serve.pool.DevicePool`) to round-robin
    flushed batches across the node's accelerators with automatic
    failover / quarantine / canary readmission.  The DEFAULT is a
    single-member pool on the process default device — placement,
    executable-cache accounting and the retrace-free warm contract are
    identical to the pre-pool server unless the caller opts into more
    members."""

    def __init__(self, opts: Options | None = None,
                 ladder: _bucket.BucketLadder | None = None,
                 cache: _cache.ExecutableCache | None = None,
                 admission: _admission.AdmissionConfig | None = None,
                 governor=None, pool: _pool.DevicePool | None = None,
                 devices=None):
        self.opts = dict(opts or {})
        self._ladder = ladder
        self.cache = cache if cache is not None else _cache.default_cache()
        self.admission = admission or _admission.AdmissionConfig()
        self.queue = _admission.AdmissionQueue(self.admission, governor)
        if pool is None:
            members = (list(devices) if devices is not None
                       else [jax.local_devices()[0]])
            pool = _pool.DevicePool(members, governor=self.queue.governor)
        self.pool = pool
        self.pool.set_canary(self._canary_probe)
        # flush/watchdog/lifecycle state shared between the submitting
        # threads, the flush loop and the watchdog; the registry
        # declares _lock's guards (rules/concurrency.py)
        self._lock = threading.Lock()
        self._inflight: list = []          # requests in the running flush
        self._flush_deadline: float | None = None   # watchdog deadline
        self._wedged: Exception | None = None       # sticky watchdog error
        self._flush_error: Exception | None = None  # sticky flush error
        self._quarantined = 0
        self._flusher: threading.Thread | None = None
        self._watchdog: threading.Thread | None = None
        self._stop_event = threading.Event()        # self-synchronized
        # online-retune state: per-dtype hot-swapped ladders, the
        # observed square-size history feeding the DP fitter, and the
        # swap counter the metrics serving table reports
        self._ladders: dict = {}           # dtype -> hot-swapped ladder
        self._sizes: dict = {}             # dtype -> observed n history
        self._retunes = 0
        self._retuning = False
        self._last_retune = time.perf_counter()

    # ------------------------------------------------------------ intake

    def ladder(self, dtype) -> _bucket.BucketLadder:
        dtype = str(jnp.dtype(dtype))
        with self._lock:
            swapped = self._ladders.get(dtype)
        if swapped is not None:            # online retune hot-swap wins
            return swapped
        if self._ladder is not None:
            return self._ladder
        return _bucket.default_ladder(dtype)

    def _canary_probe(self, member) -> bool:
        """The pool's readmission probe: one tiny well-conditioned solve
        through this member's cached executable; True iff the result is
        finite and healthy.  Runs the same code path a real batch takes,
        so a device that only fails under dispatch stays quarantined."""
        n = self.pool.config.canary_n
        a = np.eye(n, dtype="float32") * 2.0
        b = np.ones((n, 1), dtype="float32")
        exe, _ = self.cache.get_or_compile("solve", (n, 1), "float32", 1,
                                           self.opts,
                                           device=member.device)
        a_d, b_d, s_d = jax.device_put(
            (a[None], b[None], np.array([n], np.int32)), member.device)
        x, h, _ = exe(a_d, b_d, s_d)
        x = np.asarray(x)
        return bool(np.isfinite(x).all()
                    and np.asarray(h.ok).all()
                    and np.allclose(x[0], 0.5, atol=1e-4))

    def submit(self, op: str, a, b,
               deadline_ms: float | None = None) -> _admission.Ticket:
        """Queue one problem through admission control; returns its
        :class:`~slate_tpu.serve.admission.Ticket` (an int: the index
        into a synchronous ``drain()``'s results; ``ticket.result()``
        is the durable interface).  ``deadline_ms`` overrides the
        config default; a request that would age out is shed HERE with
        a typed error, not silently dropped in a batch."""
        if op not in SERVE_OPS:
            raise ValueError(f"serve: unknown op {op!r} "
                             f"(known: {SERVE_OPS})")
        a = _as_2d(a, "a")
        b = _as_2d(b, "b")
        if a.dtype != b.dtype:
            raise ValueError(f"serve: a/b dtypes differ "
                             f"({a.dtype} vs {b.dtype})")
        if op == "least_squares_solve":
            if a.shape[0] < a.shape[1]:
                raise ValueError("serve: least_squares_solve needs "
                                 f"m >= n, got {a.shape}")
        elif a.shape[0] != a.shape[1]:
            raise ValueError(f"serve: {op} needs square A, got {a.shape}")
        if b.shape[0] != a.shape[0]:
            raise ValueError(f"serve: A {a.shape} / B {b.shape} row "
                             "mismatch")
        wedge = self.wedged()
        if wedge is not None:
            raise SlateServeTimeoutError(
                f"serve: server is wedged ({wedge}); restart it",
                reason="wedged")
        now = time.perf_counter()
        if deadline_ms is None:
            deadline_ms = self.admission.default_deadline_ms
        deadline = None if deadline_ms is None else now + deadline_ms / 1e3
        dtype = str(a.dtype)

        def build(ticket):
            return Request(op, a, b, now, ticket, deadline, 0)

        try:
            ticket, victims = self.queue.offer(build, deadline, now)
        except SlateServeTimeoutError as e:
            self._emit_shed(op, dtype, e.reason, 0.0)
            raise
        except SlateServeError as e:
            self._emit_shed(op, dtype,
                            f"overflow_{getattr(e, 'policy', 'reject')}",
                            0.0)
            raise
        for v in victims:
            err = _admission.SlateServeOverloadError(
                "serve: shed (oldest queued) to admit new work under "
                "overload", policy="shed_oldest")
            if v.ticket is not None:
                v.ticket.fail(err)
            self._emit_shed(v.op, str(v.a.dtype), "overflow_shed_oldest",
                            (now - v.t_submit) * 1e3)
        return ticket

    def serve_batch(self, requests) -> list:
        """Synchronous convenience: submit every (op, a, b) and drain."""
        for op, a, b in requests:
            self.submit(op, a, b)
        return self.drain()

    def _emit_shed(self, op: str, dtype: str, reason: str,
                   age_ms: float) -> None:
        _events.emit_serve_shed({
            "op": op, "dtype": dtype, "reason": reason,
            "age_ms": round(age_ms, 3),
            "queue_depth": self.queue.depth(),
            "device_id": None,   # shed at admission: no member involved
        })

    # ------------------------------------------------- background loop

    def start(self) -> None:
        """Start the background flush loop and its watchdog (both
        daemon threads; idempotent while they are alive)."""
        with self._lock:
            if self._flusher is not None and self._flusher.is_alive():
                return
            self._stop_event.clear()
            self._flusher = threading.Thread(
                target=self._flush_loop, name="slate-serve-flush",
                daemon=True)
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="slate-serve-watchdog",
                daemon=True)
            self._flusher.start()
            self._watchdog.start()

    def running(self) -> bool:
        with self._lock:
            return (self._flusher is not None
                    and self._flusher.is_alive())

    def wedged(self) -> Exception | None:
        """The sticky watchdog error, if the server is wedged."""
        with self._lock:
            return self._wedged

    def health_info(self) -> dict:
        """Front-door health: admission stats, loop/wedge state, and
        the quarantine count — what a load balancer would scrape."""
        with self._lock:
            wedged = self._wedged
            inflight = len(self._inflight)
            quarantined = self._quarantined
            retunes = self._retunes
        return {
            "queue": self.queue.stats(),
            "inflight": inflight,
            "running": self.running(),
            "wedged": None if wedged is None else str(wedged),
            "quarantined": quarantined,
            "retunes": retunes,
            "pool": self.pool.stats(),
            "degraded": self.pool.degraded(),
            "slo_p99_ms": self.queue.governor.p99_ms(),
            "slo_budget_ms": self.queue.governor.budget_ms,
            "slo_device_p99_ms": self.queue.governor.device_p99s(),
        }

    def shutdown(self, drain: bool = True,
                 timeout_s: float | None = None) -> None:
        """Stop the loop and settle every pending request: drain them
        (default) or fail them loudly with a typed shutdown error —
        never leave a ticket unsettled or a daemon thread parked.  A
        wedged flush thread cannot be killed; its requests were already
        failed by the watchdog and the daemon thread dies with the
        process."""
        with self._lock:
            flusher, watchdog = self._flusher, self._watchdog
        self._stop_event.set()
        self.queue.kick()
        join_s = (timeout_s if timeout_s is not None
                  else self.admission.watchdog_timeout_s + 1.0)
        for t in (flusher, watchdog):
            if t is not None and t is not threading.current_thread():
                t.join(join_s)
        stranded = self.queue.close("shutdown")
        if stranded:
            if drain and self.wedged() is None:
                results, err = self._execute(stranded)
                if err is not None:
                    with self._lock:
                        self._flush_error = err
            else:
                err = SlateServeTimeoutError(
                    f"serve: shutdown with {len(stranded)} request(s) "
                    f"still pending", reason="shutdown")
                self.queue.note_shed(len(stranded))
                for r in stranded:
                    if r.ticket is not None:
                        r.ticket.fail(err)
                    self._emit_shed(
                        r.op, str(r.a.dtype), "shutdown",
                        (time.perf_counter() - r.t_submit) * 1e3)
        with self._lock:
            self._flusher = None
            self._watchdog = None

    def _flush_loop(self) -> None:
        poll_s = max(self.admission.max_batch_delay_ms / 2e3, 1e-3)
        while not self._stop_event.is_set():
            self._retune_tick()
            if self.queue.flush_due():
                self._flush_once()
            else:
                self.queue.park(poll_s)
                if not self.queue.flush_due():
                    self._stop_event.wait(poll_s)

    def _flush_once(self) -> None:
        live, expired = self.queue.take_all()
        self._shed_expired(expired)
        if not live:
            return
        with self._lock:
            self._inflight = live
            self._flush_deadline = (time.perf_counter()
                                    + self.admission.watchdog_timeout_s)
        err = None
        try:
            _, err = self._execute(live)
        except Exception as e:          # never kill the loop: stickify
            err = e
            for r in live:
                if r.ticket is not None:
                    r.ticket.fail(e)
        finally:
            with self._lock:
                self._inflight = []
                self._flush_deadline = None
        if err is not None:
            with self._lock:
                self._flush_error = err

    def _watchdog_loop(self) -> None:
        poll_s = min(max(self.admission.watchdog_timeout_s / 8.0, 1e-3),
                     0.25)
        while not self._stop_event.is_set():
            with self._lock:
                deadline = self._flush_deadline
            if deadline is not None and time.perf_counter() > deadline:
                self._declare_wedged()
            self._stop_event.wait(poll_s)

    def _declare_wedged(self) -> None:
        err = SlateServeTimeoutError(
            f"serve: flush exceeded watchdog_timeout_s="
            f"{self.admission.watchdog_timeout_s} (stuck compile or "
            f"device hang) — failing pending requests", reason="watchdog")
        with self._lock:
            if self._flush_deadline is None:    # flush just completed
                return
            self._wedged = err
            inflight, self._inflight = self._inflight, []
            self._flush_deadline = None
        stranded = self.queue.close("wedged")
        self.queue.note_shed(len(inflight) + len(stranded))
        now = time.perf_counter()
        for r in inflight + stranded:
            if r.ticket is not None:
                r.ticket.fail(err)
            self._emit_shed(r.op, str(r.a.dtype), "watchdog",
                            (now - r.t_submit) * 1e3)

    def _shed_expired(self, expired) -> None:
        if not expired:
            return
        self.queue.note_shed(len(expired))
        now = time.perf_counter()
        for r in expired:
            err = SlateServeTimeoutError(
                "serve: request deadline expired while queued — shed at "
                "flush", reason="deadline")
            if r.ticket is not None:
                r.ticket.fail(err)
            self._emit_shed(r.op, str(r.a.dtype), "deadline",
                            (now - r.t_submit) * 1e3)

    # ---------------------------------------------------- online retune

    def _note_sizes(self, dtype: str, entries) -> None:
        """Record observed problem shapes — ``(op, n, kb)`` triples —
        into the live histogram the background retune fits against.
        No-op unless online retuning is enabled (retune_interval_s)."""
        if self.admission.retune_interval_s is None:
            return
        with self._lock:
            hist = self._sizes.setdefault(dtype, [])
            hist.extend(entries)
            if len(hist) > 4096:           # a window, not forever
                del hist[:len(hist) - 4096]

    def _retune_tick(self) -> None:
        """Flush-loop tick: kick one background retune worker when the
        interval has elapsed.  The fit and the executable warming run
        OFF the flush loop; only the final ladder swap takes the lock."""
        interval = self.admission.retune_interval_s
        if interval is None:
            return
        now = time.perf_counter()
        with self._lock:
            if self._retuning or now - self._last_retune < interval:
                return
            self._retuning = True
            self._last_retune = now
        threading.Thread(target=self._retune_worker,
                         name="slate-serve-retune", daemon=True).start()

    def _retune_worker(self) -> None:
        try:
            with self._lock:
                due = [d for d, h in self._sizes.items()
                       if len(h) >= self.admission.retune_min_samples]
            for dtype in due:
                try:
                    self.retune_now(dtype)
                except Exception:      # best-effort: never kill serving
                    pass
        finally:
            with self._lock:
                self._retuning = False

    def retune_now(self, dtype: str) -> dict | None:
        """Refit the bucket ladder for ``dtype`` from the live size
        histogram (PR 11's padded-area-optimal DP fitter) and hot-swap
        it when the fitted ladder beats the live one by at least
        ``retune_margin`` padding waste.  Returns the swap info dict
        (also emitted as a ``serve_retune`` obs record), or None when
        nothing swapped (too few samples, or no win worth the churn).

        The swap is atomic under the server lock: batches already
        bucketed keep their plan and settle on the old executables; the
        next flush buckets on the fitted ladder.  The fitted rungs'
        executables warm on every healthy pool member BEFORE the swap
        (off the flush loop when driven by the background tick), so the
        first post-swap flush is a cache hit, not a compile stall.  The
        histogram resets after a swap — the next fit argues from fresh
        evidence instead of re-litigating the sizes it already served."""
        from ..tune import autotune as _autotune
        dtype = str(jnp.dtype(dtype))
        cfg = self.admission
        with self._lock:
            entries = list(self._sizes.get(dtype, ()))
        if len(entries) < cfg.retune_min_samples:
            return None
        ns = [n for _, n, _ in entries]
        live = self.ladder(dtype)
        fitted = _bucket.BucketLadder(
            _autotune.serve_ladder_from_sizes(ns), "retuned")
        w_live = _autotune.ladder_waste(ns, live)
        w_fit = _autotune.ladder_waste(ns, fitted)
        if w_fit >= w_live - cfg.retune_margin:
            return None
        self._warm_rungs(fitted, dtype, entries)
        with self._lock:
            self._ladders[dtype] = fitted
            self._sizes[dtype] = []
            self._retunes += 1
        info = {"op": "ladder", "dtype": dtype,
                "old": [int(r) for r in live.rungs],
                "new": [int(r) for r in fitted.rungs],
                "waste_live": round(w_live, 4),
                "waste_fitted": round(w_fit, 4),
                "samples": len(entries)}
        _events.emit_serve_retune(info)
        return info

    def _warm_rungs(self, ladder, dtype: str, entries) -> None:
        """Best-effort pre-compile of the fitted ladder's hottest
        buckets on every healthy pool member — the old executables keep
        serving while these compile; a warm failure is ignored (the
        flush path compiles on demand)."""
        from collections import Counter
        shapes = Counter((op, ladder.bucket_for(n), kb)
                         for op, n, kb in entries
                         if op != "least_squares_solve")
        batch = _bucket.next_pow2(self.admission.flush_occupancy)
        for (op, nb, kb), _ in shapes.most_common(4):
            for _, dev in self.pool.healthy_devices():
                try:
                    self.cache.get_or_compile(op, (nb, kb), dtype, batch,
                                              self.opts, device=dev)
                except Exception:
                    return

    # ------------------------------------------------------------- drain

    def _bucket_of(self, req: Request):
        lad = self.ladder(req.a.dtype)
        if req.op == "least_squares_solve":
            return _bucket.least_squares_buckets(
                lad, req.a.shape[0], req.a.shape[1], req.b.shape[1])
        return _bucket.solve_buckets(lad, req.a.shape[0], req.b.shape[1])

    def drain(self) -> list:
        """Execute every pending request; results in submit order.

        Errors are never silent: a sticky error from a failed
        background flush is re-raised HERE first (then cleared), even
        when the queue is already empty; a group that fails during this
        drain stores the typed error on every affected ticket and
        drain re-raises the first one after every group has been
        attempted."""
        with self._lock:
            err, self._flush_error = self._flush_error, None
        if err is not None:
            raise err
        live, expired = self.queue.take_all()
        self._shed_expired(expired)
        if not live:
            return []
        results, err = self._execute(live)
        if err is not None:
            raise err
        return results

    def _execute(self, pending):
        """Run every request of one flush: group, execute, retry
        poisons once in a fresh batch, quarantine repeat offenders to a
        singleton slow path, deliver to tickets.  Returns ``(results,
        first_error)`` with results aligned to ``pending`` (None in a
        failed slot — its ticket holds the sticky error)."""
        plan = _faults.host_fire("serve_flush_delay")
        if plan is not None:
            time.sleep(plan.delay_s)
        t_flush = time.perf_counter()
        results: list = [None] * len(pending)
        first_err: Exception | None = None

        def deliver(idx: int, res: Result,
                    device: int | None = None) -> None:
            results[idx] = res
            req = pending[idx]
            self.queue.governor.observe(
                (time.perf_counter() - req.t_submit) * 1e3, device)
            if req.ticket is not None:
                req.ticket.deliver(res)

        def run_pass(members_by_idx, queue_depth):
            """One grouped pass; returns the poison list [(idx, req)]."""
            nonlocal first_err
            reqs = dict(members_by_idx)
            groups: dict = {}
            for idx, req in members_by_idx:
                key = (req.op, str(req.a.dtype), self._bucket_of(req))
                groups.setdefault(key, []).append((idx, req))
            keys = sorted(groups, key=repr)

            def attempt(key):
                try:
                    return key, self._run_group(*key, groups[key],
                                                t_flush, queue_depth), None
                except Exception as e:
                    return key, None, e

            workers = min(len(keys), self.pool.healthy_count())
            if workers > 1:
                # distinct buckets dispatch CONCURRENTLY: the pool
                # round-robins them onto different members, so a
                # multi-device node has several batches in flight at
                # once instead of serializing behind one chip
                with futures.ThreadPoolExecutor(
                        workers, "slate-serve-group") as ex:
                    outcomes = list(ex.map(attempt, keys))
            else:
                outcomes = [attempt(k) for k in keys]

            poisons = []
            for key, ran, exc in outcomes:
                op, dtype, shape = key
                if exc is not None:
                    err = exc if isinstance(exc, SlateServeError) else \
                        SlateServeError(
                            f"serve: flush failed for {op}/{dtype} "
                            f"bucket {shape}: {exc}")
                    err.__cause__ = exc if err is not exc else None
                    first_err = first_err or err
                    for idx, req in groups[key]:
                        if req.ticket is not None:
                            req.ticket.fail(err)
                    continue
                out, device = ran
                for idx, res in out:
                    req = reqs[idx]
                    if _poison(req, res):
                        # withhold the bad result: first strike earns the
                        # fresh-batch retry, second goes to quarantine
                        poisons.append((idx, req._replace(
                            retries=req.retries + 1)))
                    else:
                        deliver(idx, res, device)
            return poisons

        poisons = run_pass(list(enumerate(pending)), len(pending))
        # the at-most-once fresh-batch retry: poisons ride together,
        # never again with the healthy requests they degraded
        repeat = run_pass(poisons, len(poisons)) if poisons else []
        for idx, req in repeat:
            # second strike: quarantine to the singleton slow path and
            # deliver whatever it produces — HealthInfo reports the rest
            self._quarantine(idx, req, t_flush, deliver)
        return results, first_err

    def _quarantine(self, idx: int, req: Request, t_flush: float,
                    deliver) -> None:
        with self._lock:
            self._quarantined += 1
        key = (req.op, str(req.a.dtype), self._bucket_of(req))
        op, dtype, shape = key
        t0 = time.perf_counter()
        try:
            ((_, res),), device = self._run_group(
                op, dtype, shape, [(idx, req)], t_flush, 1)
        except Exception as e:
            err = e if isinstance(e, SlateServeError) else \
                SlateServeError(f"serve: quarantine slow path failed for "
                                f"{op}/{dtype}: {e}")
            if req.ticket is not None:
                req.ticket.fail(err)
            return
        _events.emit_serve_quarantine({
            "op": op, "dtype": dtype, "bucket": list(shape),
            "reason": "escalation_exhausted",
            "retries": max(req.retries - 1, 0),   # fresh-batch retries spent
            "ok": bool(res.health.ok),
            "dur_ms": round((time.perf_counter() - t0) * 1e3, 3),
            "device_id": device,
        })
        deliver(idx, res, device)

    def _run_group(self, op: str, dtype: str, shape: tuple, members,
                   t_flush: float, queue_depth: int):
        """Pack, dispatch through the device pool, unpack one group.
        Returns ``(out, device_index)`` with ``out`` the per-member
        ``(idx, Result)`` list."""
        t0 = time.perf_counter()
        n_real = len(members)
        batch = _bucket.next_pow2(n_real)
        if len(shape) == 3:
            mb, nb, kb = shape
        else:
            nb, kb = shape
            mb = nb
        a_pad = np.zeros((batch, mb, nb), dtype)
        b_pad = np.zeros((batch, mb, kb), dtype)
        # per-problem live sizes, TRACED data for the ragged kernels:
        # n for square slots, m + (nb - n) live augmented rows for least
        # squares, 0 for filler slots (batched.make_batched's contract)
        sizes = np.zeros((batch,), np.int32)
        real_elems = 0
        for slot, (_, req) in enumerate(members):
            m_i, n_i = req.a.shape
            if op == "least_squares_solve":
                a_pad[slot] = _bucket.pad_tall(jnp.asarray(req.a), mb, nb)
                sizes[slot] = m_i + (nb - n_i)
            else:
                a_pad[slot] = _bucket.pad_square(jnp.asarray(req.a), nb)
                sizes[slot] = n_i
            b_pad[slot] = _bucket.pad_rows(jnp.asarray(req.b), mb, kb)
            real_elems += m_i * n_i + m_i * req.b.shape[1]
        for slot in range(n_real, batch):          # identity filler slots
            a_pad[slot, :nb, :nb] = np.eye(nb, dtype=dtype)
        self._note_sizes(dtype, [(op, req.a.shape[1], kb)
                                 for _, req in members])

        traces0 = _trace_total()
        # warm the executable on EVERY healthy member before dispatch: a
        # cold compile is minutes on a real chip and must never read as
        # a dispatch-deadline miss (the watchdog guards wedged compiles)
        exes: dict = {}
        warm = []
        for midx, dev in self.pool.healthy_devices():
            exes[midx], was_hit = self.cache.get_or_compile(
                op, shape, dtype, batch, self.opts, device=dev)
            warm.append(was_hit)
        hit = bool(warm) and all(warm)
        retraces = _trace_total() - traces0

        def run(member):
            exe = exes.get(member.index)
            if exe is None:        # readmitted after the warm pass
                exe, _ = self.cache.get_or_compile(
                    op, shape, dtype, batch, self.opts,
                    device=member.device)
            # each attempt device_puts FRESH device arrays: b's donation
            # consumes the device copy, never the host buffers, so a
            # failover redispatches the SAME untouched packed batch and
            # (same jaxpr, same executable) reproduces bit-identically
            a_d, b_d, s_d = jax.device_put((a_pad, b_pad, sizes),
                                           member.device)
            t_exec = time.perf_counter()
            x, h, esc = exe(a_d, b_d, s_d)
            dev_ms = None
            if _events.timing_enabled():
                x, h, esc = jax.block_until_ready((x, h, esc))
                dev_ms = round((time.perf_counter() - t_exec) * 1e3, 3)
            x = np.asarray(x)
            esc = np.asarray(esc)
            h_np = HealthInfo(*(np.asarray(leaf) for leaf in h))
            return x, h_np, esc, dev_ms

        def validate(ran) -> bool:
            x, h_np, _, _ = ran
            ok = np.asarray(h_np.ok, bool).reshape(-1)
            # only slots whose HealthInfo CLAIMS success are checked for
            # device garbage: a poison request honestly reports not-ok,
            # and its non-finite x is the escalation ladder's verdict,
            # not a lost device
            return all(not ok[s] or bool(np.isfinite(x[s]).all())
                       for s in range(n_real))

        (x, h_np, esc, device_ms), dev_idx, failovers = \
            self.pool.dispatch(run, validate, op=op, dtype=dtype)

        out = []
        for slot, (ticket, req) in enumerate(members):
            n_i, k_i = req.a.shape[1], req.b.shape[1]
            out.append((ticket, Result(
                x[slot, :n_i, :k_i],
                HealthInfo(*(leaf[slot] for leaf in h_np)),
                bool(esc[slot]))))

        t_done = time.perf_counter()
        ages = [round((t_flush - req.t_submit) * 1e3, 3)
                for _, req in members]
        latency = [round((t_done - req.t_submit) * 1e3, 3)
                   for _, req in members]
        mfu = gbps = None
        if device_ms:
            secs = device_ms * 1e-3
            # waste-adjusted by construction: LIVE problem flops only,
            # against the batch dtype's chip peak (f64 reads n/a)
            mfu = _flops.mfu(_flops.serve_flops(
                op, [(req.a.shape, req.b.shape) for _, req in members]),
                secs, dtype)
            item = np.dtype(dtype).itemsize
            gbps = _flops.achieved_gbps(
                float(batch) * (mb * nb + 2 * mb * kb) * item, secs)

        bucket_elems = batch * (mb * nb + mb * kb)
        _events.emit_serve_batch({
            "op": op,
            "dtype": dtype,
            "bucket": list(shape),
            "batch": batch,
            "problems": n_real,
            "occupancy": round(n_real / batch, 4),
            "padding_waste": round(
                _bucket.padded_fraction(real_elems, bucket_elems), 4),
            "escalated": int(esc[:n_real].sum()),
            "cache": self.cache.stats(),
            "compiled": not hit,
            "retraces": retraces,
            "ladder": self.ladder(dtype).source,
            "dur_ms": round((time.perf_counter() - t0) * 1e3, 3),
            "device_ms": device_ms,
            "mfu": mfu,
            "achieved_gbps": gbps,
            "queue_depth": queue_depth,
            "age_at_flush_ms": ages,
            "latency_ms": latency,
            "device_id": dev_idx,
            "failovers": failovers,
        })
        return out, dev_idx


def _trace_total() -> int:
    return sum(s["traces"] for s in _sentinel.stats().values())
