"""The serving front end: submit mixed-size solves, drain bucketed batches.

Usage::

    from slate_tpu import serve

    srv = serve.Server()
    t0 = srv.submit("solve", a0, b0)              # (n0, n0), (n0, k0)
    t1 = srv.submit("chol_solve", a1, b1)
    t2 = srv.submit("least_squares_solve", a2, b2)
    results = srv.drain()                         # [Result] in submit order

Each ``drain`` groups pending requests by ``(op, dtype, bucket)``,
identity-pads every problem to its bucket (bucket.py), rounds the
batch count up to a power of two with identity filler slots, runs the
bucket's cached executable (cache.py — compiled once, B donated), and
unpacks per-problem results, ``HealthInfo`` and escalation flags.

One ``slate-obs-v1`` record of kind ``serve_batch`` is emitted per
executed batch (obs.events.emit_serve_batch) carrying bucket occupancy,
padding waste, escalations, executable-cache stats and the retrace
delta observed across the execution — the fields ``python -m
slate_tpu.obs`` aggregates into the serving table.

The server is also a flight recorder: every request is stamped at
submit, so each ``serve_batch`` event additionally carries
``queue_depth`` (pending requests when drain started), per-problem
``age_at_flush_ms`` (submit -> drain start) and ``latency_ms``
(submit -> result materialized) — the tail-latency inputs
``obs.slo`` aggregates into p50/p99 verdicts.  Under ``obs.timing()``
the batch also reports ``device_ms`` (dispatch -> device-ready) and a
waste-adjusted ``mfu`` priced over LIVE problem flops only
(obs.flops.serve_flops), so padding can never inflate utilization.
"""

from __future__ import annotations

import threading
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import events as _events
from ..obs import flops as _flops
from ..obs import sentinel as _sentinel
from ..options import Options
from ..robust.health import HealthInfo
from . import bucket as _bucket
from . import cache as _cache

SERVE_OPS = ("solve", "chol_solve", "least_squares_solve")


class Request(NamedTuple):
    """One pending problem: ``op`` in SERVE_OPS, dense ``a``/``b``,
    and the flight-recorder submit stamp (perf_counter seconds)."""
    op: str
    a: np.ndarray
    b: np.ndarray
    t_submit: float = 0.0


class Result(NamedTuple):
    """One served problem: solution, per-problem health, whether the
    in-graph safety rung produced it."""
    x: np.ndarray
    health: HealthInfo
    escalated: bool


def _as_2d(x, name: str) -> np.ndarray:
    x = np.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"serve: {name} must be 2-D, got shape {x.shape}")
    return x


class Server:
    """Shape-bucketed batch server over the vmap-clean solve cores.

    ``opts`` apply to every request (they are part of the executable
    fingerprint); ``ladder`` overrides the bucket ladder (default:
    tuned rungs when the plan cache has them, else geometric);
    ``cache`` shares or isolates the executable store (default: the
    process-wide cache)."""

    def __init__(self, opts: Options | None = None,
                 ladder: _bucket.BucketLadder | None = None,
                 cache: _cache.ExecutableCache | None = None):
        self.opts = dict(opts or {})
        self._ladder = ladder
        self.cache = cache if cache is not None else _cache.default_cache()
        # submit/drain may come from different threads (a web front end
        # submitting while a drain loop flushes); the queue swap must be
        # atomic or tickets tear
        self._lock = threading.Lock()
        self._pending: list[Request] = []

    # ------------------------------------------------------------ intake

    def ladder(self, dtype) -> _bucket.BucketLadder:
        if self._ladder is not None:
            return self._ladder
        return _bucket.default_ladder(str(jnp.dtype(dtype)))

    def submit(self, op: str, a, b) -> int:
        """Queue one problem; returns its ticket (index into drain())."""
        if op not in SERVE_OPS:
            raise ValueError(f"serve: unknown op {op!r} "
                             f"(known: {SERVE_OPS})")
        a = _as_2d(a, "a")
        b = _as_2d(b, "b")
        if a.dtype != b.dtype:
            raise ValueError(f"serve: a/b dtypes differ "
                             f"({a.dtype} vs {b.dtype})")
        if op == "least_squares_solve":
            if a.shape[0] < a.shape[1]:
                raise ValueError("serve: least_squares_solve needs "
                                 f"m >= n, got {a.shape}")
        elif a.shape[0] != a.shape[1]:
            raise ValueError(f"serve: {op} needs square A, got {a.shape}")
        if b.shape[0] != a.shape[0]:
            raise ValueError(f"serve: A {a.shape} / B {b.shape} row "
                             "mismatch")
        with self._lock:
            self._pending.append(Request(op, a, b, time.perf_counter()))
            return len(self._pending) - 1

    def serve_batch(self, requests) -> list:
        """Synchronous convenience: submit every (op, a, b) and drain."""
        for op, a, b in requests:
            self.submit(op, a, b)
        return self.drain()

    # ------------------------------------------------------------- drain

    def _bucket_of(self, req: Request):
        lad = self.ladder(req.a.dtype)
        if req.op == "least_squares_solve":
            return _bucket.least_squares_buckets(
                lad, req.a.shape[0], req.a.shape[1], req.b.shape[1])
        return _bucket.solve_buckets(lad, req.a.shape[0], req.b.shape[1])

    def drain(self) -> list:
        """Execute every pending request; results in submit order."""
        with self._lock:
            pending, self._pending = self._pending, []
        if not pending:
            return []
        t_flush = time.perf_counter()
        groups: dict = {}
        for ticket, req in enumerate(pending):
            key = (req.op, str(req.a.dtype), self._bucket_of(req))
            groups.setdefault(key, []).append((ticket, req))
        results: list = [None] * len(pending)
        for key in sorted(groups, key=repr):
            op, dtype, shape = key
            for ticket, res in self._run_group(op, dtype, shape,
                                               groups[key], t_flush,
                                               len(pending)):
                results[ticket] = res
        return results

    def _run_group(self, op: str, dtype: str, shape: tuple, members,
                   t_flush: float, queue_depth: int):
        t0 = time.perf_counter()
        n_real = len(members)
        batch = _bucket.next_pow2(n_real)
        if len(shape) == 3:
            mb, nb, kb = shape
        else:
            nb, kb = shape
            mb = nb
        a_pad = np.zeros((batch, mb, nb), dtype)
        b_pad = np.zeros((batch, mb, kb), dtype)
        # per-problem live sizes, TRACED data for the ragged kernels:
        # n for square slots, m + (nb - n) live augmented rows for least
        # squares, 0 for filler slots (batched.make_batched's contract)
        sizes = np.zeros((batch,), np.int32)
        real_elems = 0
        for slot, (_, req) in enumerate(members):
            m_i, n_i = req.a.shape
            if op == "least_squares_solve":
                a_pad[slot] = _bucket.pad_tall(jnp.asarray(req.a), mb, nb)
                sizes[slot] = m_i + (nb - n_i)
            else:
                a_pad[slot] = _bucket.pad_square(jnp.asarray(req.a), nb)
                sizes[slot] = n_i
            b_pad[slot] = _bucket.pad_rows(jnp.asarray(req.b), mb, kb)
            real_elems += m_i * n_i + m_i * req.b.shape[1]
        for slot in range(n_real, batch):          # identity filler slots
            a_pad[slot, :nb, :nb] = np.eye(nb, dtype=dtype)

        traces0 = _trace_total()
        exe, hit = self.cache.get_or_compile(op, shape, dtype, batch,
                                             self.opts)
        # b is DONATED to the executable (cache.py's contract): hand it
        # a fresh device array and never touch that buffer again
        t_exec = time.perf_counter()
        x, h, esc = exe(jnp.asarray(a_pad), jnp.asarray(b_pad),
                        jnp.asarray(sizes))
        device_ms = None
        if _events.timing_enabled():
            x, h, esc = jax.block_until_ready((x, h, esc))
            device_ms = round((time.perf_counter() - t_exec) * 1e3, 3)
        x = np.asarray(x)
        esc = np.asarray(esc)
        h_np = HealthInfo(*(np.asarray(leaf) for leaf in h))
        retraces = _trace_total() - traces0

        out = []
        for slot, (ticket, req) in enumerate(members):
            n_i, k_i = req.a.shape[1], req.b.shape[1]
            out.append((ticket, Result(
                x[slot, :n_i, :k_i],
                HealthInfo(*(leaf[slot] for leaf in h_np)),
                bool(esc[slot]))))

        t_done = time.perf_counter()
        ages = [round((t_flush - req.t_submit) * 1e3, 3)
                for _, req in members]
        latency = [round((t_done - req.t_submit) * 1e3, 3)
                   for _, req in members]
        mfu = gbps = None
        if device_ms:
            secs = device_ms * 1e-3
            # waste-adjusted by construction: LIVE problem flops only
            mfu = _flops.mfu(_flops.serve_flops(
                op, [(req.a.shape, req.b.shape) for _, req in members]),
                secs)
            item = np.dtype(dtype).itemsize
            gbps = _flops.achieved_gbps(
                float(batch) * (mb * nb + 2 * mb * kb) * item, secs)

        bucket_elems = batch * (mb * nb + mb * kb)
        _events.emit_serve_batch({
            "op": op,
            "dtype": dtype,
            "bucket": list(shape),
            "batch": batch,
            "problems": n_real,
            "occupancy": round(n_real / batch, 4),
            "padding_waste": round(
                _bucket.padded_fraction(real_elems, bucket_elems), 4),
            "escalated": int(esc[:n_real].sum()),
            "cache": self.cache.stats(),
            "compiled": not hit,
            "retraces": retraces,
            "ladder": self.ladder(dtype).source,
            "dur_ms": round((time.perf_counter() - t0) * 1e3, 3),
            "device_ms": device_ms,
            "mfu": mfu,
            "achieved_gbps": gbps,
            "queue_depth": queue_depth,
            "age_at_flush_ms": ages,
            "latency_ms": latency,
        })
        return out


def _trace_total() -> int:
    return sum(s["traces"] for s in _sentinel.stats().values())
