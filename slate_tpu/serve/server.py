"""The serving front end: submit mixed-size solves, drain bucketed batches.

Synchronous usage (unchanged)::

    from slate_tpu import serve

    srv = serve.Server()
    t0 = srv.submit("solve", a0, b0)              # (n0, n0), (n0, k0)
    t1 = srv.submit("chol_solve", a1, b1)
    t2 = srv.submit("least_squares_solve", a2, b2)
    results = srv.drain()                         # [Result] in submit order

Survival-layer usage (the background front door)::

    srv = serve.Server(admission=serve.AdmissionConfig(
        max_queue=64, overflow="shed_oldest", default_deadline_ms=250,
        slo_budget_ms=250))
    srv.start()                                   # flush loop + watchdog
    t = srv.submit("solve", a, b)                 # admission-controlled
    x = t.result(timeout=1.0).x                   # sticky typed errors
    srv.shutdown()                                # drains or fails loudly

Each flush groups pending requests by ``(op, dtype, bucket)``,
identity-pads every problem to its bucket (bucket.py), rounds the
batch count up to a power of two with identity filler slots, runs the
bucket's cached executable (cache.py — compiled once, B donated), and
unpacks per-problem results, ``HealthInfo`` and escalation flags.

Survival properties (docs/SERVING.md "Survival"):

- **admission control / backpressure** — submit goes through the
  bounded :class:`~slate_tpu.serve.admission.AdmissionQueue`: overflow
  policy, per-request deadlines, and SLO-budget backpressure (the
  rolling-latency governor) decide at admission; shed requests carry
  typed errors, never silence.
- **background flush loop** — a daemon thread batches by occupancy /
  age / deadline-slack watermarks while callers keep submitting; a
  watchdog daemon declares a flush wedged after ``watchdog_timeout_s``
  and fails every pending request loudly with
  :class:`SlateServeTimeoutError` instead of blocking callers forever.
  Tickets are first-write-wins, so a wedged flush that later limps
  home cannot double-answer.
- **poison quarantine** — a problem that exhausts the in-graph
  escalation ladder (``escalated`` with unhealthy ``HealthInfo``) is
  retried at most once in a fresh batch, then quarantined to a
  singleton slow path; its neighbors' batches never carry it again.
- **sticky errors** — a failed flush stores its typed error on every
  affected ticket AND on the server; the next ``drain()`` re-raises it
  even when the queue is already empty.

One ``slate-obs-v1`` record of kind ``serve_batch`` is emitted per
executed batch; sheds and quarantines emit ``serve_shed`` /
``serve_quarantine`` records (obs/events.py) feeding the ``shed/1k``
and ``quar/1k`` columns of the ``python -m slate_tpu.obs`` serving
table.  The flight-recorder fields (queue depth, per-problem
``age_at_flush_ms`` / ``latency_ms``, device-time ``mfu`` under
``obs.timing()``) are unchanged from the synchronous server.
"""

from __future__ import annotations

import threading
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..exceptions import SlateServeError, SlateServeTimeoutError
from ..obs import events as _events
from ..obs import flops as _flops
from ..obs import sentinel as _sentinel
from ..options import Options
from ..robust import faults as _faults
from ..robust.health import HealthInfo
from . import admission as _admission
from . import bucket as _bucket
from . import cache as _cache

SERVE_OPS = ("solve", "chol_solve", "least_squares_solve")


class Request(NamedTuple):
    """One pending problem: ``op`` in SERVE_OPS, dense ``a``/``b``, the
    flight-recorder submit stamp (perf_counter seconds), the admission
    ticket, the absolute deadline (perf_counter seconds, None = never),
    and how many batched attempts have come back poison (strikes: one
    earns the fresh-batch retry, two the quarantine slow path)."""
    op: str
    a: np.ndarray
    b: np.ndarray
    t_submit: float = 0.0
    ticket: object = None
    deadline: float | None = None
    retries: int = 0


class Result(NamedTuple):
    """One served problem: solution, per-problem health, whether the
    in-graph safety rung produced it."""
    x: np.ndarray
    health: HealthInfo
    escalated: bool


def _as_2d(x, name: str) -> np.ndarray:
    x = np.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"serve: {name} must be 2-D, got shape {x.shape}")
    return x


def _poison(req: Request, res: Result) -> bool:
    """Did this problem exhaust the in-graph escalation ladder?  The
    safety rung ran AND still reports unhealthy — the per-request
    analog of a tile fault the recovery ladder could not repair."""
    return bool(res.escalated) and not bool(res.health.ok)


class Server:
    """Shape-bucketed batch server over the vmap-clean solve cores.

    ``opts`` apply to every request (they are part of the executable
    fingerprint); ``ladder`` overrides the bucket ladder (default:
    tuned rungs when the plan cache has them, else geometric);
    ``cache`` shares or isolates the executable store (default: the
    process-wide cache); ``admission`` configures the survival layer
    (default :class:`AdmissionConfig`: effectively the old unbounded
    synchronous behavior — queue of 256, no deadlines, no loop until
    :meth:`start`); ``governor`` injects a shared latency governor."""

    def __init__(self, opts: Options | None = None,
                 ladder: _bucket.BucketLadder | None = None,
                 cache: _cache.ExecutableCache | None = None,
                 admission: _admission.AdmissionConfig | None = None,
                 governor=None):
        self.opts = dict(opts or {})
        self._ladder = ladder
        self.cache = cache if cache is not None else _cache.default_cache()
        self.admission = admission or _admission.AdmissionConfig()
        self.queue = _admission.AdmissionQueue(self.admission, governor)
        # flush/watchdog/lifecycle state shared between the submitting
        # threads, the flush loop and the watchdog; the registry
        # declares _lock's guards (rules/concurrency.py)
        self._lock = threading.Lock()
        self._inflight: list = []          # requests in the running flush
        self._flush_deadline: float | None = None   # watchdog deadline
        self._wedged: Exception | None = None       # sticky watchdog error
        self._flush_error: Exception | None = None  # sticky flush error
        self._quarantined = 0
        self._flusher: threading.Thread | None = None
        self._watchdog: threading.Thread | None = None
        self._stop_event = threading.Event()        # self-synchronized

    # ------------------------------------------------------------ intake

    def ladder(self, dtype) -> _bucket.BucketLadder:
        if self._ladder is not None:
            return self._ladder
        return _bucket.default_ladder(str(jnp.dtype(dtype)))

    def submit(self, op: str, a, b,
               deadline_ms: float | None = None) -> _admission.Ticket:
        """Queue one problem through admission control; returns its
        :class:`~slate_tpu.serve.admission.Ticket` (an int: the index
        into a synchronous ``drain()``'s results; ``ticket.result()``
        is the durable interface).  ``deadline_ms`` overrides the
        config default; a request that would age out is shed HERE with
        a typed error, not silently dropped in a batch."""
        if op not in SERVE_OPS:
            raise ValueError(f"serve: unknown op {op!r} "
                             f"(known: {SERVE_OPS})")
        a = _as_2d(a, "a")
        b = _as_2d(b, "b")
        if a.dtype != b.dtype:
            raise ValueError(f"serve: a/b dtypes differ "
                             f"({a.dtype} vs {b.dtype})")
        if op == "least_squares_solve":
            if a.shape[0] < a.shape[1]:
                raise ValueError("serve: least_squares_solve needs "
                                 f"m >= n, got {a.shape}")
        elif a.shape[0] != a.shape[1]:
            raise ValueError(f"serve: {op} needs square A, got {a.shape}")
        if b.shape[0] != a.shape[0]:
            raise ValueError(f"serve: A {a.shape} / B {b.shape} row "
                             "mismatch")
        wedge = self.wedged()
        if wedge is not None:
            raise SlateServeTimeoutError(
                f"serve: server is wedged ({wedge}); restart it",
                reason="wedged")
        now = time.perf_counter()
        if deadline_ms is None:
            deadline_ms = self.admission.default_deadline_ms
        deadline = None if deadline_ms is None else now + deadline_ms / 1e3
        dtype = str(a.dtype)

        def build(ticket):
            return Request(op, a, b, now, ticket, deadline, 0)

        try:
            ticket, victims = self.queue.offer(build, deadline, now)
        except SlateServeTimeoutError as e:
            self._emit_shed(op, dtype, e.reason, 0.0)
            raise
        except SlateServeError as e:
            self._emit_shed(op, dtype,
                            f"overflow_{getattr(e, 'policy', 'reject')}",
                            0.0)
            raise
        for v in victims:
            err = _admission.SlateServeOverloadError(
                "serve: shed (oldest queued) to admit new work under "
                "overload", policy="shed_oldest")
            if v.ticket is not None:
                v.ticket.fail(err)
            self._emit_shed(v.op, str(v.a.dtype), "overflow_shed_oldest",
                            (now - v.t_submit) * 1e3)
        return ticket

    def serve_batch(self, requests) -> list:
        """Synchronous convenience: submit every (op, a, b) and drain."""
        for op, a, b in requests:
            self.submit(op, a, b)
        return self.drain()

    def _emit_shed(self, op: str, dtype: str, reason: str,
                   age_ms: float) -> None:
        _events.emit_serve_shed({
            "op": op, "dtype": dtype, "reason": reason,
            "age_ms": round(age_ms, 3),
            "queue_depth": self.queue.depth(),
        })

    # ------------------------------------------------- background loop

    def start(self) -> None:
        """Start the background flush loop and its watchdog (both
        daemon threads; idempotent while they are alive)."""
        with self._lock:
            if self._flusher is not None and self._flusher.is_alive():
                return
            self._stop_event.clear()
            self._flusher = threading.Thread(
                target=self._flush_loop, name="slate-serve-flush",
                daemon=True)
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="slate-serve-watchdog",
                daemon=True)
            self._flusher.start()
            self._watchdog.start()

    def running(self) -> bool:
        with self._lock:
            return (self._flusher is not None
                    and self._flusher.is_alive())

    def wedged(self) -> Exception | None:
        """The sticky watchdog error, if the server is wedged."""
        with self._lock:
            return self._wedged

    def health_info(self) -> dict:
        """Front-door health: admission stats, loop/wedge state, and
        the quarantine count — what a load balancer would scrape."""
        with self._lock:
            wedged = self._wedged
            inflight = len(self._inflight)
            quarantined = self._quarantined
        return {
            "queue": self.queue.stats(),
            "inflight": inflight,
            "running": self.running(),
            "wedged": None if wedged is None else str(wedged),
            "quarantined": quarantined,
            "slo_p99_ms": self.queue.governor.p99_ms(),
            "slo_budget_ms": self.queue.governor.budget_ms,
        }

    def shutdown(self, drain: bool = True,
                 timeout_s: float | None = None) -> None:
        """Stop the loop and settle every pending request: drain them
        (default) or fail them loudly with a typed shutdown error —
        never leave a ticket unsettled or a daemon thread parked.  A
        wedged flush thread cannot be killed; its requests were already
        failed by the watchdog and the daemon thread dies with the
        process."""
        with self._lock:
            flusher, watchdog = self._flusher, self._watchdog
        self._stop_event.set()
        self.queue.kick()
        join_s = (timeout_s if timeout_s is not None
                  else self.admission.watchdog_timeout_s + 1.0)
        for t in (flusher, watchdog):
            if t is not None and t is not threading.current_thread():
                t.join(join_s)
        stranded = self.queue.close("shutdown")
        if stranded:
            if drain and self.wedged() is None:
                results, err = self._execute(stranded)
                if err is not None:
                    with self._lock:
                        self._flush_error = err
            else:
                err = SlateServeTimeoutError(
                    f"serve: shutdown with {len(stranded)} request(s) "
                    f"still pending", reason="shutdown")
                self.queue.note_shed(len(stranded))
                for r in stranded:
                    if r.ticket is not None:
                        r.ticket.fail(err)
                    self._emit_shed(
                        r.op, str(r.a.dtype), "shutdown",
                        (time.perf_counter() - r.t_submit) * 1e3)
        with self._lock:
            self._flusher = None
            self._watchdog = None

    def _flush_loop(self) -> None:
        poll_s = max(self.admission.max_batch_delay_ms / 2e3, 1e-3)
        while not self._stop_event.is_set():
            if self.queue.flush_due():
                self._flush_once()
            else:
                self.queue.park(poll_s)
                if not self.queue.flush_due():
                    self._stop_event.wait(poll_s)

    def _flush_once(self) -> None:
        live, expired = self.queue.take_all()
        self._shed_expired(expired)
        if not live:
            return
        with self._lock:
            self._inflight = live
            self._flush_deadline = (time.perf_counter()
                                    + self.admission.watchdog_timeout_s)
        err = None
        try:
            _, err = self._execute(live)
        except Exception as e:          # never kill the loop: stickify
            err = e
            for r in live:
                if r.ticket is not None:
                    r.ticket.fail(e)
        finally:
            with self._lock:
                self._inflight = []
                self._flush_deadline = None
        if err is not None:
            with self._lock:
                self._flush_error = err

    def _watchdog_loop(self) -> None:
        poll_s = min(max(self.admission.watchdog_timeout_s / 8.0, 1e-3),
                     0.25)
        while not self._stop_event.is_set():
            with self._lock:
                deadline = self._flush_deadline
            if deadline is not None and time.perf_counter() > deadline:
                self._declare_wedged()
            self._stop_event.wait(poll_s)

    def _declare_wedged(self) -> None:
        err = SlateServeTimeoutError(
            f"serve: flush exceeded watchdog_timeout_s="
            f"{self.admission.watchdog_timeout_s} (stuck compile or "
            f"device hang) — failing pending requests", reason="watchdog")
        with self._lock:
            if self._flush_deadline is None:    # flush just completed
                return
            self._wedged = err
            inflight, self._inflight = self._inflight, []
            self._flush_deadline = None
        stranded = self.queue.close("wedged")
        self.queue.note_shed(len(inflight) + len(stranded))
        now = time.perf_counter()
        for r in inflight + stranded:
            if r.ticket is not None:
                r.ticket.fail(err)
            self._emit_shed(r.op, str(r.a.dtype), "watchdog",
                            (now - r.t_submit) * 1e3)

    def _shed_expired(self, expired) -> None:
        if not expired:
            return
        self.queue.note_shed(len(expired))
        now = time.perf_counter()
        for r in expired:
            err = SlateServeTimeoutError(
                "serve: request deadline expired while queued — shed at "
                "flush", reason="deadline")
            if r.ticket is not None:
                r.ticket.fail(err)
            self._emit_shed(r.op, str(r.a.dtype), "deadline",
                            (now - r.t_submit) * 1e3)

    # ------------------------------------------------------------- drain

    def _bucket_of(self, req: Request):
        lad = self.ladder(req.a.dtype)
        if req.op == "least_squares_solve":
            return _bucket.least_squares_buckets(
                lad, req.a.shape[0], req.a.shape[1], req.b.shape[1])
        return _bucket.solve_buckets(lad, req.a.shape[0], req.b.shape[1])

    def drain(self) -> list:
        """Execute every pending request; results in submit order.

        Errors are never silent: a sticky error from a failed
        background flush is re-raised HERE first (then cleared), even
        when the queue is already empty; a group that fails during this
        drain stores the typed error on every affected ticket and
        drain re-raises the first one after every group has been
        attempted."""
        with self._lock:
            err, self._flush_error = self._flush_error, None
        if err is not None:
            raise err
        live, expired = self.queue.take_all()
        self._shed_expired(expired)
        if not live:
            return []
        results, err = self._execute(live)
        if err is not None:
            raise err
        return results

    def _execute(self, pending):
        """Run every request of one flush: group, execute, retry
        poisons once in a fresh batch, quarantine repeat offenders to a
        singleton slow path, deliver to tickets.  Returns ``(results,
        first_error)`` with results aligned to ``pending`` (None in a
        failed slot — its ticket holds the sticky error)."""
        plan = _faults.host_fire("serve_flush_delay")
        if plan is not None:
            time.sleep(plan.delay_s)
        t_flush = time.perf_counter()
        results: list = [None] * len(pending)
        first_err: Exception | None = None

        def deliver(idx: int, res: Result) -> None:
            results[idx] = res
            req = pending[idx]
            self.queue.governor.observe(
                (time.perf_counter() - req.t_submit) * 1e3)
            if req.ticket is not None:
                req.ticket.deliver(res)

        def run_pass(members_by_idx, queue_depth):
            """One grouped pass; returns the poison list [(idx, req)]."""
            nonlocal first_err
            reqs = dict(members_by_idx)
            groups: dict = {}
            for idx, req in members_by_idx:
                key = (req.op, str(req.a.dtype), self._bucket_of(req))
                groups.setdefault(key, []).append((idx, req))
            poisons = []
            for key in sorted(groups, key=repr):
                op, dtype, shape = key
                try:
                    out = self._run_group(op, dtype, shape, groups[key],
                                          t_flush, queue_depth)
                except Exception as e:
                    err = e if isinstance(e, SlateServeError) else \
                        SlateServeError(
                            f"serve: flush failed for {op}/{dtype} "
                            f"bucket {shape}: {e}")
                    err.__cause__ = e if err is not e else None
                    first_err = first_err or err
                    for idx, req in groups[key]:
                        if req.ticket is not None:
                            req.ticket.fail(err)
                    continue
                for idx, res in out:
                    req = reqs[idx]
                    if _poison(req, res):
                        # withhold the bad result: first strike earns the
                        # fresh-batch retry, second goes to quarantine
                        poisons.append((idx, req._replace(
                            retries=req.retries + 1)))
                    else:
                        deliver(idx, res)
            return poisons

        poisons = run_pass(list(enumerate(pending)), len(pending))
        # the at-most-once fresh-batch retry: poisons ride together,
        # never again with the healthy requests they degraded
        repeat = run_pass(poisons, len(poisons)) if poisons else []
        for idx, req in repeat:
            # second strike: quarantine to the singleton slow path and
            # deliver whatever it produces — HealthInfo reports the rest
            self._quarantine(idx, req, t_flush, deliver)
        return results, first_err

    def _quarantine(self, idx: int, req: Request, t_flush: float,
                    deliver) -> None:
        with self._lock:
            self._quarantined += 1
        key = (req.op, str(req.a.dtype), self._bucket_of(req))
        op, dtype, shape = key
        t0 = time.perf_counter()
        try:
            ((_, res),) = self._run_group(op, dtype, shape, [(idx, req)],
                                          t_flush, 1)
        except Exception as e:
            err = e if isinstance(e, SlateServeError) else \
                SlateServeError(f"serve: quarantine slow path failed for "
                                f"{op}/{dtype}: {e}")
            if req.ticket is not None:
                req.ticket.fail(err)
            return
        _events.emit_serve_quarantine({
            "op": op, "dtype": dtype, "bucket": list(shape),
            "reason": "escalation_exhausted",
            "retries": max(req.retries - 1, 0),   # fresh-batch retries spent
            "ok": bool(res.health.ok),
            "dur_ms": round((time.perf_counter() - t0) * 1e3, 3),
        })
        deliver(idx, res)

    def _run_group(self, op: str, dtype: str, shape: tuple, members,
                   t_flush: float, queue_depth: int):
        t0 = time.perf_counter()
        n_real = len(members)
        batch = _bucket.next_pow2(n_real)
        if len(shape) == 3:
            mb, nb, kb = shape
        else:
            nb, kb = shape
            mb = nb
        a_pad = np.zeros((batch, mb, nb), dtype)
        b_pad = np.zeros((batch, mb, kb), dtype)
        # per-problem live sizes, TRACED data for the ragged kernels:
        # n for square slots, m + (nb - n) live augmented rows for least
        # squares, 0 for filler slots (batched.make_batched's contract)
        sizes = np.zeros((batch,), np.int32)
        real_elems = 0
        for slot, (_, req) in enumerate(members):
            m_i, n_i = req.a.shape
            if op == "least_squares_solve":
                a_pad[slot] = _bucket.pad_tall(jnp.asarray(req.a), mb, nb)
                sizes[slot] = m_i + (nb - n_i)
            else:
                a_pad[slot] = _bucket.pad_square(jnp.asarray(req.a), nb)
                sizes[slot] = n_i
            b_pad[slot] = _bucket.pad_rows(jnp.asarray(req.b), mb, kb)
            real_elems += m_i * n_i + m_i * req.b.shape[1]
        for slot in range(n_real, batch):          # identity filler slots
            a_pad[slot, :nb, :nb] = np.eye(nb, dtype=dtype)

        traces0 = _trace_total()
        exe, hit = self.cache.get_or_compile(op, shape, dtype, batch,
                                             self.opts)
        # b is DONATED to the executable (cache.py's contract): hand it
        # a fresh device array and never touch that buffer again
        t_exec = time.perf_counter()
        x, h, esc = exe(jnp.asarray(a_pad), jnp.asarray(b_pad),
                        jnp.asarray(sizes))
        device_ms = None
        if _events.timing_enabled():
            x, h, esc = jax.block_until_ready((x, h, esc))
            device_ms = round((time.perf_counter() - t_exec) * 1e3, 3)
        x = np.asarray(x)
        esc = np.asarray(esc)
        h_np = HealthInfo(*(np.asarray(leaf) for leaf in h))
        retraces = _trace_total() - traces0

        out = []
        for slot, (ticket, req) in enumerate(members):
            n_i, k_i = req.a.shape[1], req.b.shape[1]
            out.append((ticket, Result(
                x[slot, :n_i, :k_i],
                HealthInfo(*(leaf[slot] for leaf in h_np)),
                bool(esc[slot]))))

        t_done = time.perf_counter()
        ages = [round((t_flush - req.t_submit) * 1e3, 3)
                for _, req in members]
        latency = [round((t_done - req.t_submit) * 1e3, 3)
                   for _, req in members]
        mfu = gbps = None
        if device_ms:
            secs = device_ms * 1e-3
            # waste-adjusted by construction: LIVE problem flops only,
            # against the batch dtype's chip peak (f64 reads n/a)
            mfu = _flops.mfu(_flops.serve_flops(
                op, [(req.a.shape, req.b.shape) for _, req in members]),
                secs, dtype)
            item = np.dtype(dtype).itemsize
            gbps = _flops.achieved_gbps(
                float(batch) * (mb * nb + 2 * mb * kb) * item, secs)

        bucket_elems = batch * (mb * nb + mb * kb)
        _events.emit_serve_batch({
            "op": op,
            "dtype": dtype,
            "bucket": list(shape),
            "batch": batch,
            "problems": n_real,
            "occupancy": round(n_real / batch, 4),
            "padding_waste": round(
                _bucket.padded_fraction(real_elems, bucket_elems), 4),
            "escalated": int(esc[:n_real].sum()),
            "cache": self.cache.stats(),
            "compiled": not hit,
            "retraces": retraces,
            "ladder": self.ladder(dtype).source,
            "dur_ms": round((time.perf_counter() - t0) * 1e3, 3),
            "device_ms": device_ms,
            "mfu": mfu,
            "achieved_gbps": gbps,
            "queue_depth": queue_depth,
            "age_at_flush_ms": ages,
            "latency_ms": latency,
        })
        return out


def _trace_total() -> int:
    return sum(s["traces"] for s in _sentinel.stats().values())
