"""Persistent compiled-executable cache for the serving layer.

A warmed server must never retrace and never re-allocate its
steady-state buffers; both properties live here:

- Executables are AOT-compiled once per key — ``(op, bucket_shape,
  dtype, options-fingerprint, batch)`` — and held for the life of the
  process.  A repeat batch is a dictionary hit: zero tracing (the PR 8
  retrace sentinel observes none) and zero compilation.
- The packed right-hand-side buffer is DONATED (``donate_argnums``)
  for the square solves: its shape and dtype equal the result's, so
  XLA reuses the allocation for the output and the steady-state submit
  loop runs allocation-neutral.  The packed operand ``A`` is NOT
  donated (the solve reads it after the factor phase), and least
  squares donates nothing (its result is (nb, kb), not b's (mb, kb) —
  the donation would be unusable).  This is the donation contract of
  docs/SERVING.md: callers hand the packed B to the executable and
  must not reuse that buffer afterwards.

Seam contract (slate-lint SEAM012, the serving mirror of SEAM011):
serve/ modules obtain executables ONLY through this module — no
``jax.jit`` / ``lower`` / ``compile`` anywhere else in the package —
so every compilation is accounted in :meth:`ExecutableCache.stats`
and surfaced in the per-batch obs events.
"""

from __future__ import annotations

import threading
import time

import jax

from ..obs import sentinel as _sentinel
from ..options import Options
from ..robust import faults as _faults
from . import batched as _batched


def options_fingerprint(opts: Options | None) -> tuple:
    """Canonical, hashable digest of an options dict for cache keying.
    Order-insensitive; enum keys and values collapse to their names so
    equivalent spellings ({Option.Abft: 'on'} vs Abft.On) agree."""
    items = []
    for k, v in (opts or {}).items():
        kn = getattr(k, "name", str(k))
        vn = getattr(v, "name", None) or str(v)
        items.append((kn, vn))
    return tuple(sorted(items))


class ExecutableCache:
    """In-process executable store with hit/miss accounting."""

    def __init__(self):
        self._lock = threading.Lock()
        self._exes: dict = {}
        self._hits = 0
        self._misses = 0
        self._compile_ms = 0.0

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._exes), "hits": self._hits,
                    "misses": self._misses,
                    "compile_ms": round(self._compile_ms, 3)}

    def clear(self) -> None:
        with self._lock:
            self._exes.clear()
            self._hits = 0
            self._misses = 0
            self._compile_ms = 0.0

    def get_or_compile(self, op: str, bucket_shape: tuple, dtype,
                       batch: int, opts: Options | None = None,
                       device=None):
        """The compiled batch executable for one bucket, compiling on
        first use.  Returns ``(executable, hit)``.

        ``bucket_shape`` is ``(nb, kb)`` for square solves or
        ``(mb, nb, kb)`` for least squares; ``batch`` the (bucketed)
        problem count.  The executable maps packed stacks
        ``(a [batch, ...], b [batch, mb|nb, kb], sizes [batch] int32)``
        to ``(x, HealthInfo, escalated)`` with leading axis ``batch``,
        donating ``b``.  ``sizes`` carries per-problem live sizes as
        TRACED data — the ragged kernels consume it via scalar
        prefetch, the vmapped fallback ignores it — so mixed-size
        batches never alter the executable's static signature.

        ``device`` pins the executable to one accelerator (the device
        pool compiles the same jaxpr once per member; input specs carry
        a SingleDeviceSharding so dispatch needs no transfer fallback).
        Distinct devices are distinct cache keys, but two pool members
        backed by the SAME physical device (the CPU drill harness)
        share one entry."""
        dtype = str(jax.numpy.dtype(dtype))
        devkey = (None if device is None
                  else (device.platform, int(device.id)))
        key = (op, tuple(int(s) for s in bucket_shape), dtype,
               options_fingerprint(opts), int(batch), devkey)
        # chaos site: a mid-flight eviction forces the recompile path —
        # the serving layer must survive losing its warm executables
        if _faults.host_fire("serve_cache_evict") is not None:
            self.clear()
        with self._lock:
            exe = self._exes.get(key)
            if exe is not None:
                self._hits += 1
                return exe, True
        # compile OUTSIDE the lock (it can take seconds); a racing
        # duplicate compile is wasted work, not a correctness problem —
        # which is also where the chaos compile-stall site lives: the
        # serving watchdog must catch a wedged compile, and a stall
        # under the lock would be the CON003 bug class, not a test
        stall = _faults.host_fire("serve_compile_stall")
        if stall is not None:
            time.sleep(stall.delay_s)
        t0 = time.perf_counter()
        exe = self._compile(op, key[1], dtype, int(batch), opts, device)
        dt_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            winner = self._exes.setdefault(key, exe)
            self._misses += 1
            self._compile_ms += dt_ms
        return winner, False

    @staticmethod
    def _compile(op: str, bucket_shape: tuple, dtype: str, batch: int,
                 opts: Options | None, device=None):
        if len(bucket_shape) == 3:
            mb, nb, kb = bucket_shape
        else:
            nb, kb = bucket_shape
            mb = nb
        sharding = (None if device is None
                    else jax.sharding.SingleDeviceSharding(device))
        a_spec = jax.ShapeDtypeStruct((batch, mb, nb), dtype,
                                      sharding=sharding)
        b_spec = jax.ShapeDtypeStruct((batch, mb, kb), dtype,
                                      sharding=sharding)
        s_spec = jax.ShapeDtypeStruct((batch,), "int32",
                                      sharding=sharding)
        fn = _batched.make_batched(op, opts)
        # donate b only where the result aliases it exactly: a square
        # solve's x has b's shape, least squares returns (nb, kb) != b
        # and the donation would be unusable (XLA warns, nothing reused)
        donate = (1,) if mb == nb else ()
        # one executable staging enters many same-shaped driver
        # boundaries; suppress those per-boundary sentinel feeds and
        # account the compile as ONE serve-level trace instead
        with _sentinel.suppressed():
            exe = jax.jit(fn, donate_argnums=donate).lower(
                a_spec, b_spec, s_spec).compile()
        _sentinel.record_trace(
            f"serve.{op}", f"{dtype}:b{batch}:"
            + "x".join(str(s) for s in bucket_shape))
        return exe


_DEFAULT = ExecutableCache()


def default_cache() -> ExecutableCache:
    """The process-wide cache shared by Servers that don't bring one."""
    return _DEFAULT
