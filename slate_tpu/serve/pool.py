"""Elastic device pool: multi-device dispatch that survives losing one.

The survival layer (admission.py / server.py) made the front door
robust against overload, wedged flushes and poison requests — but every
batch still ran on the process default device, so one sick chip took
the whole tier down.  This module makes placement and recovery the
LIBRARY's job (SLATE's premise, scaled to the node): a
:class:`DevicePool` owns one :class:`PoolMember` per accelerator,
round-robins flushed batches across the healthy ones, and runs the
failover ladder when a member misbehaves:

1. **detect** — a dispatch that raises, returns non-finite results for
   problems whose ``HealthInfo`` claims health (the device lied — a
   real device-loss signature, distinct from a poison request whose
   health honestly reports failure), or exceeds the per-dispatch
   deadline derived from the :class:`~slate_tpu.obs.slo.
   LatencyGovernor`'s rolling tail (a wedged device: the dispatch
   thread lingers, the pool moves on — tickets are first-write-wins,
   so a zombie result that limps home later is dropped, not
   double-delivered).
2. **fail over** — the SAME packed batch is redispatched onto the next
   healthy member.  The packed host buffers are untouched by a failed
   attempt (each attempt ``device_put``'s fresh device arrays, so B's
   donation never consumes the host copy), and every member runs the
   same executable compiled from the same jaxpr — results after
   failover are bit-identical to a no-fault run and zero tickets are
   lost.
3. **quarantine** — ``strike_limit`` consecutive failures retire the
   member from rotation (one transient blip heals itself: any success
   resets the counter).
4. **canary & readmit** — every ``canary_interval_s`` the pool probes
   a quarantined member with a small canary solve; a clean probe
   readmits it, a failed probe (or a ``serve_canary_flake`` chaos
   plan) reschedules the next one.

Degraded modes: with one healthy member left the pool keeps serving
single-device (``degraded()`` is True — what a load balancer scrapes);
with none it raises a loud typed
:class:`~slate_tpu.exceptions.SlateServeOverloadError` — callers'
tickets carry the error, nothing is silently dropped.  Probes run
BEFORE member selection, so a pool in total blackout readmits a
recovered device instead of staying dark forever.

Chaos sites (robust/faults.py ``SERVE_SITES``, deterministic on CPU):
``serve_device_fail`` (kind ``nan`` poisons the batch output so the
non-finite sentinel must catch it; any other kind raises at dispatch),
``serve_device_slow`` (sleeps past the dispatch deadline — the wedged
path), ``serve_canary_flake`` (the probe fails).  All three honor
``FaultPlan(device=i)`` targeting.

Per-device truth: every failover / quarantine / readmission / probe
emits a ``serve_device`` obs record (obs/events.py) and the governor
files delivered latencies per member, so backpressure tightens by the
POOL's sick fraction (``LatencyGovernor.overload_fraction``) instead
of halving the world.

Thread safety: all member state (strikes, quarantine, rotation cursor,
counters) is guarded by ``_lock``, declared in the slate-lint LockSpec
registry.  Dispatch and compilation never run under it — CON003's
compile-under-lock class is the bug this layer must not have.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import jax
import numpy as np

from ..exceptions import SlateServeError, SlateServeOverloadError
from ..obs import events as _events
from ..obs import slo as _slo
from ..robust import faults as _faults

#: member lifecycle states
HEALTHY, QUARANTINED = "healthy", "quarantined"


@dataclasses.dataclass(frozen=True)
class PoolConfig:
    """Failure-detection knobs (docs/SERVING.md "Device pool").

    ``strike_limit`` consecutive dispatch failures quarantine a member;
    ``dispatch_timeout_s`` is the per-dispatch deadline (None derives
    it live from the governor: ``max(dispatch_floor_s,
    dispatch_factor * rolling p99)`` — and no deadline at all while the
    governor has no latency budget, so default sync serving never pays
    a watcher thread); ``canary_interval_s`` paces readmission probes
    of quarantined members; ``canary_n`` is the canary solve's size."""

    strike_limit: int = 2
    dispatch_timeout_s: float | None = None
    dispatch_floor_s: float = 10.0
    dispatch_factor: float = 8.0
    canary_interval_s: float = 0.25
    canary_n: int = 8

    def __post_init__(self):
        if self.strike_limit < 1:
            raise ValueError("pool: strike_limit must be >= 1")
        if self.canary_interval_s <= 0:
            raise ValueError("pool: canary_interval_s must be > 0")


class PoolMember:
    """One accelerator in the pool: the device handle plus the mutable
    health bookkeeping (mutated only under the owning pool's lock)."""

    __slots__ = ("index", "device", "state", "strikes", "dispatches",
                 "failures", "next_probe", "quarantined_at")

    def __init__(self, index: int, device):
        self.index = index
        self.device = device
        self.state = HEALTHY
        self.strikes = 0
        self.dispatches = 0
        self.failures = 0
        self.next_probe = 0.0
        self.quarantined_at: float | None = None

    def describe(self) -> dict:
        return {"index": self.index, "device": str(self.device),
                "state": self.state, "strikes": self.strikes,
                "dispatches": self.dispatches, "failures": self.failures}


class _DeviceFailure(Exception):
    """Internal dispatch-failure sentinel: why one member's attempt was
    declared dead (``exception`` / ``nonfinite`` / ``deadline``)."""

    def __init__(self, reason: str, cause: BaseException | None = None):
        super().__init__(reason)
        self.reason = reason
        self.cause = cause


def _poison_tree(out):
    """The ``serve_device_fail kind='nan'`` payload: every inexact leaf
    of the dispatch result becomes NaN — finite-typed leaves (health
    flags, escalation bits) keep claiming success, which is exactly the
    lie the non-finite sentinel exists to catch."""
    def leaf(x):
        a = np.asarray(x)
        if np.issubdtype(a.dtype, np.inexact):
            return np.full_like(a, np.nan)
        return x
    return jax.tree_util.tree_map(leaf, out)


class DevicePool:
    """Round-robin dispatcher over the node's healthy accelerators.

    ``devices`` defaults to ``jax.local_devices()``; tests pass an
    explicit list (duplicating the CPU device gives a K-member pool on
    one chip — the kill-a-device drill's harness).  ``governor`` is the
    shared :class:`~slate_tpu.obs.slo.LatencyGovernor` the per-dispatch
    deadline derives from; ``canary`` is the probe callable
    ``(member) -> bool`` (the Server wires a real canary solve through
    its executable cache; standalone pools readmit on the chaos-gated
    default)."""

    def __init__(self, devices=None, config: PoolConfig | None = None,
                 governor: _slo.LatencyGovernor | None = None,
                 canary=None):
        devices = list(devices) if devices is not None \
            else list(jax.local_devices())
        if not devices:
            raise ValueError("pool: need at least one device")
        self.config = config or PoolConfig()
        self.governor = governor if governor is not None \
            else _slo.LatencyGovernor()
        self._canary = canary
        self._lock = threading.Lock()
        self._members = [PoolMember(i, d) for i, d in enumerate(devices)]
        self._rr = 0
        self._failovers = 0
        self._quarantines = 0
        self._readmissions = 0

    # ------------------------------------------------------------ queries

    def size(self) -> int:
        # slate-lint: disable=CON001 -- the member list is built once in __init__ and never reassigned or resized; only per-member fields mutate (under the lock), so its length is immutable
        return len(self._members)

    def members(self) -> list:
        """Snapshot descriptions of every member (for health scrapes)."""
        with self._lock:
            return [m.describe() for m in self._members]

    def healthy_devices(self) -> list:
        """(index, device) of every in-rotation member, rotation order."""
        with self._lock:
            return [(m.index, m.device) for m in self._members
                    if m.state == HEALTHY]

    def healthy_count(self) -> int:
        with self._lock:
            return sum(1 for m in self._members if m.state == HEALTHY)

    def degraded(self) -> bool:
        """One survivor (or fewer) in a multi-device pool — serving
        continues but the next strike is an outage."""
        return self.size() > 1 and self.healthy_count() <= 1

    def stats(self) -> dict:
        with self._lock:
            healthy = sum(1 for m in self._members
                          if m.state == HEALTHY)
            return {"devices": len(self._members), "healthy": healthy,
                    "failovers": self._failovers,
                    "quarantines": self._quarantines,
                    "readmissions": self._readmissions}

    def set_canary(self, canary) -> None:
        """Install the readmission probe (Server does this once at
        construction; last write wins)."""
        self._canary = canary

    # ----------------------------------------------------------- deadline

    def dispatch_timeout_s(self) -> float | None:
        """The per-dispatch deadline: the configured override, else
        derived from the governor's rolling p99 (None — direct, no
        watcher thread — while no latency budget is declared)."""
        cfg = self.config
        if cfg.dispatch_timeout_s is not None:
            return cfg.dispatch_timeout_s
        if self.governor.budget_ms is None:
            return None
        p99 = self.governor.p99_ms()
        derived = (p99 or 0.0) * cfg.dispatch_factor / 1e3
        return max(cfg.dispatch_floor_s, derived)

    # ----------------------------------------------------------- dispatch

    def dispatch(self, run, validate=None, op: str | None = None,
                 dtype: str | None = None):
        """Run one packed batch on the pool; returns ``(result,
        device_index, failovers)``.

        ``run(member)`` executes the batch on ``member.device`` and
        returns the materialized host result; ``validate(result)``
        (optional) returns False when the result smells like device
        garbage — non-finite output in a slot whose health claims
        success.  Failures strike the member and the SAME batch fails
        over to the next healthy one; when every member has been tried
        (or the pool is fully quarantined and every probe failed) a
        :class:`SlateServeOverloadError` is raised — the flush path
        stickies it onto every affected ticket."""
        self._probe_due()
        tried: set = set()
        failovers = 0
        while True:
            member = self._select(tried)
            if member is None:
                raise SlateServeOverloadError(
                    f"serve: no healthy device left in the pool "
                    f"({self.size()} member(s), all quarantined or "
                    f"already failed this batch) — retrying after a "
                    f"clean canary probe", policy="pool_exhausted")
            try:
                out = self._attempt(run, member)
                if validate is not None and not validate(out):
                    raise _DeviceFailure("nonfinite")
            except _DeviceFailure as f:
                self._strike(member, f.reason, op, dtype)
                tried.add(member.index)
                failovers += 1
                continue
            except Exception as e:      # an exception IS the sentinel
                self._strike(member, "exception", op, dtype, e)
                tried.add(member.index)
                failovers += 1
                continue
            with self._lock:
                member.strikes = 0      # consecutive counter: success heals
                member.dispatches += 1
            return out, member.index, failovers

    def _attempt(self, run, member: PoolMember):
        """One member's try, under the per-dispatch deadline.  The
        chaos sites live INSIDE the worker so a ``serve_device_slow``
        sleep is what the deadline watches, exactly like a real hang."""
        timeout = self.dispatch_timeout_s()

        def work():
            slow = _faults.host_fire("serve_device_slow",
                                     device=member.index)
            if slow is not None:
                time.sleep(slow.delay_s)
            fail = _faults.host_fire("serve_device_fail",
                                     device=member.index)
            if fail is not None and fail.kind != "nan":
                raise SlateServeError(
                    f"chaos: device {member.index} lost at dispatch")
            out = run(member)
            if fail is not None:        # kind == "nan": the device lies
                out = _poison_tree(out)
            return out

        if timeout is None:
            return work()
        box: dict = {}
        done = threading.Event()

        def _worker():
            try:
                box["value"] = work()
            except BaseException as e:  # delivered to the waiter below
                box["error"] = e
            done.set()

        t = threading.Thread(target=_worker, daemon=True,
                             name=f"slate-serve-dispatch-{member.index}")
        t.start()
        if not done.wait(timeout):
            # wedged: the zombie thread may still finish, but its result
            # is dropped here and its tickets are settled by the
            # survivor — first-write-wins makes the late answer a no-op
            raise _DeviceFailure("deadline")
        err = box.get("error")
        if err is not None:
            raise err
        return box["value"]

    def _select(self, tried: set) -> PoolMember | None:
        """Next healthy member in rotation not yet tried this batch."""
        with self._lock:
            n = len(self._members)
            for off in range(n):
                m = self._members[(self._rr + off) % n]
                if m.state == HEALTHY and m.index not in tried:
                    self._rr = (m.index + 1) % n
                    return m
        return None

    def _strike(self, member: PoolMember, reason: str, op, dtype,
                cause: BaseException | None = None) -> None:
        now = time.perf_counter()
        with self._lock:
            member.strikes += 1
            member.failures += 1
            self._failovers += 1
            quarantine = (member.state == HEALTHY
                          and member.strikes >= self.config.strike_limit)
            if quarantine:
                member.state = QUARANTINED
                member.quarantined_at = now
                member.next_probe = now + self.config.canary_interval_s
                self._quarantines += 1
            strikes = member.strikes
        _events.emit_serve_device({
            "event": "failover", "device_id": member.index,
            "op": op, "dtype": dtype, "reason": reason,
            "strikes": strikes,
            "cause": None if cause is None else repr(cause),
        })
        if quarantine:
            _events.emit_serve_device({
                "event": "quarantine", "device_id": member.index,
                "op": op, "dtype": dtype, "reason": reason,
                "strikes": strikes,
            })

    # ------------------------------------------------------------- canary

    def _probe_due(self) -> None:
        """Probe every quarantined member whose canary is due; a clean
        probe readmits it into rotation."""
        now = time.perf_counter()
        with self._lock:
            due = [m for m in self._members
                   if m.state == QUARANTINED and now >= m.next_probe]
        for m in due:
            self._probe(m)

    def probe(self, index: int) -> bool:
        """Force one member's canary probe now (tests and operators);
        returns True when the member is (back) in rotation."""
        with self._lock:
            member = self._members[index]
            if member.state == HEALTHY:
                return True
        return self._probe(member)

    def _probe(self, member: PoolMember) -> bool:
        ok = False
        flake = _faults.host_fire("serve_canary_flake",
                                  device=member.index)
        if flake is None:
            try:
                ok = True if self._canary is None \
                    else bool(self._canary(member))
            except Exception:
                ok = False
        now = time.perf_counter()
        if not ok:
            with self._lock:
                member.next_probe = now + self.config.canary_interval_s
            _events.emit_serve_device({
                "event": "probe_fail", "device_id": member.index,
                "op": None, "dtype": None,
                "reason": "flake" if flake is not None else "canary",
            })
            return False
        with self._lock:
            quarantined_ms = (
                None if member.quarantined_at is None
                else round((now - member.quarantined_at) * 1e3, 3))
            member.state = HEALTHY
            member.strikes = 0
            member.quarantined_at = None
            self._readmissions += 1
        _events.emit_serve_device({
            "event": "readmit", "device_id": member.index,
            "op": None, "dtype": None, "reason": "canary_ok",
            "quarantined_ms": quarantined_ms,
        })
        return True
