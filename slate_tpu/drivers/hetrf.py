"""Symmetric-indefinite solvers: hetrf / hetrs / hesv (Aasen).

Analog of the reference's Aasen chain (ref: src/hetrf.cc:1-619 — Aasen's
factorization P A P^H = L T L^H with L unit lower triangular, first column
e_0, and T a band matrix solved by band LU; src/hetrs.cc applies
L / T / L^H in sequence; src/hesv.cc drives both).

TPU-first shape: the factorization is ONE lax.fori_loop over columns — each
step is a full-height gemv against the accumulated L (H = T L^H recurrence,
Higham ASNA ch. 11 formulation), a masked argmax pivot, and two masked row
writes.  Static shapes throughout; pivoting is tracked as a permutation
vector (symmetric row+column gather, never a materialized P A P^H).  The
tridiagonal T solve reuses the pivoted band LU (internal/band.py, kl=ku=1)
— the same "solve T by band LU" choice the reference makes (hetrf.cc
factors T with gbtrf).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.matrix import HermitianMatrix, Matrix, SymmetricMatrix
from ..core.storage import TileStorage
from ..exceptions import slate_error
from ..internal.band import gbtrf_banded, gbtrs_banded
from ..options import Options
from ..types import is_complex


class HEFactors(NamedTuple):
    """Aasen factors: P A P^H = L T L^H.  ``L`` dense unit-lower [n, n]
    (column 0 = e_0), ``d`` real diagonal of T, ``e`` subdiagonal of T,
    ``piv`` the row/column permutation (A[piv][:, piv] = L T L^H)."""
    L: jax.Array
    d: jax.Array
    e: jax.Array
    piv: jax.Array


def _aasen(a):
    """Scalar Aasen with partial pivoting on a dense Hermitian matrix
    (both triangles populated).  Returns (L, d, e, piv)."""
    n = a.shape[0]
    dt = a.dtype
    rdt = jnp.real(a).dtype
    idx = jnp.arange(n)

    L0 = jnp.zeros((n, n), dt).at[:, 0].set(
        jnp.zeros((n,), dt).at[0].set(1))
    d0 = jnp.zeros((n,), rdt)
    e0 = jnp.zeros((n,), dt)                      # e[j] = T[j+1, j]
    piv0 = idx

    def body(j, carry):
        L, d, e, piv = carry
        # permuted column j of A: A[piv, piv[j]]
        pj = jnp.take(piv, j)
        acol = jnp.take(a[:, :], pj, axis=1)
        acol = jnp.take(acol, piv, axis=0)        # [n]

        # H[k, j] = e[k-1] conj(L[j,k-1]) + d[k] conj(L[j,k])
        #           + conj(e[k]) conj(L[j,k+1]),  for k < j
        lrow = jnp.conj(jnp.take(L, j, axis=0))   # conj(L[j, :])
        lm1 = jnp.concatenate([jnp.zeros((1,), dt), lrow[:-1]])
        lp1 = jnp.concatenate([lrow[1:], jnp.zeros((1,), dt)])
        em1 = jnp.concatenate([jnp.zeros((1,), dt), e[:-1]])
        h = em1 * lm1 + d.astype(dt) * lrow + jnp.conj(e) * lp1
        h = jnp.where(idx < j, h, jnp.zeros_like(h))

        w = acol - L @ h                          # [n] gemv (the hot op)
        hj = jnp.take(w, j)
        ljm1 = jnp.take(lm1, j)                   # conj(L[j, j-1])
        ejm1 = jnp.take(em1, j)                   # e[j-1]
        dj = hj - ejm1 * ljm1
        d = d.at[j].set(jnp.real(dj) if is_complex(dt) else dj.astype(rdt))

        r = w - jnp.take(L, j, axis=1) * hj
        r = jnp.where(idx > j, r, jnp.zeros_like(r))

        # pivot: largest |r| among rows > j; swap rows j+1 <-> p
        live = j + 1 < n
        jp1 = jnp.minimum(j + 1, n - 1)
        p = jnp.argmax(jnp.where(idx > j, jnp.abs(r),
                                 -jnp.ones_like(jnp.abs(r))))
        p = jnp.where(live, p, jp1)

        def swap_vec(v):
            vj, vp = jnp.take(v, jp1), jnp.take(v, p)
            return v.at[jp1].set(vp).at[p].set(vj)

        r = swap_vec(r)
        piv_new = swap_vec(piv)
        rowj, rowp = jnp.take(L, jp1, axis=0), jnp.take(L, p, axis=0)
        L_sw = L.at[jp1].set(rowp).at[p].set(rowj)

        ej = jnp.take(r, jp1)
        safe = jnp.where(jnp.abs(ej) > 0, ej, jnp.ones_like(ej))
        newcol = jnp.where(idx > j + 1, r / safe, jnp.zeros_like(r))
        newcol = newcol.at[jp1].set(jnp.ones((), dt))
        e_new = e.at[j].set(jnp.where(live, ej, jnp.zeros_like(ej)))
        Lcol = jnp.where(live, newcol, jnp.take(L_sw, jp1, axis=1))
        L_new = L_sw.at[:, jp1].set(Lcol)

        L = jnp.where(live, L_new, L)
        piv = jnp.where(live, piv_new, piv)
        e = jnp.where(live, e_new, e)
        return L, d, e, piv

    L, d, e, piv = lax.fori_loop(0, n, body, (L0, d0, e0, piv0))
    return L, d, e[: max(n - 1, 0)], piv


def hetrf(A, opts: Options | None = None) -> HEFactors:
    """Aasen factorization of a Hermitian indefinite matrix
    (ref: src/hetrf.cc).  Returns HEFactors."""
    slate_error(isinstance(A, (HermitianMatrix, SymmetricMatrix)),
                "hetrf: need HermitianMatrix/SymmetricMatrix")
    slate_error(isinstance(A, HermitianMatrix) or not is_complex(A.dtype),
                "hetrf: complex SymmetricMatrix unsupported (use "
                "HermitianMatrix)")
    ad = A.to_dense()
    L, d, e, piv = _aasen(ad)
    return HEFactors(L, d, e, piv)


def _tridiag_solve_piv(d, e, b):
    """Pivoted solve of the Hermitian tridiagonal T (diagonal d, subdiag e)
    against b — via the in-house band LU with kl = ku = 1 (stable for
    indefinite T, unlike the Thomas algorithm)."""
    n = d.shape[0]
    dt = jnp.result_type(d.dtype, e.dtype if e.size else d.dtype, b.dtype)
    gp = jnp.zeros((3, n), dt)
    gp = gp.at[1].set(d.astype(dt))
    if n > 1:
        gp = gp.at[2, :-1].set(e.astype(dt))      # sub: A[j+1, j] at col j
        gp = gp.at[0, 1:].set(jnp.conj(e).astype(dt))   # super at col j+1
    work = jnp.zeros((4, n), dt).at[1:].set(gp)   # +kl fill row on top
    w = min(8, max(n, 1))
    lu, perms = gbtrf_banded(work, 1, 1, n, w)
    return gbtrs_banded(lu, perms, 1, 1, n, w, b.astype(dt))


def hetrs(F: HEFactors, B, opts: Options | None = None):
    """Solve from Aasen factors (ref: src/hetrs.cc):
    x = P^H L^-H T^-1 L^-1 P b."""
    b = B.to_dense() if isinstance(B, Matrix) else jnp.asarray(B)
    bp = jnp.take(b, F.piv, axis=0)
    z = lax.linalg.triangular_solve(F.L, bp, left_side=True, lower=True,
                                    unit_diagonal=True)
    y = _tridiag_solve_piv(F.d, F.e, z)
    wv = lax.linalg.triangular_solve(F.L, y.astype(F.L.dtype),
                                     left_side=True, lower=True,
                                     transpose_a=True, conjugate_a=True,
                                     unit_diagonal=True)
    x = jnp.zeros_like(wv).at[F.piv].set(wv)
    if isinstance(B, Matrix):
        return Matrix(TileStorage.from_dense(x, B.mb, B.nb, B.grid))
    return x


def hesv(A, B, opts: Options | None = None):
    """Solve A X = B for Hermitian indefinite A (ref: src/hesv.cc).
    Returns (HEFactors, X)."""
    F = hetrf(A, opts)
    return F, hetrs(F, B, opts)
