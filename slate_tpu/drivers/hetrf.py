"""Symmetric-indefinite solvers: hetrf / hetrs / hesv (blocked Aasen).

Analog of the reference's Aasen chain (ref: src/hetrf.cc:1-619 — blocked
Aasen factorization P A P^H = L T L^H with L unit lower triangular whose
first block column is [I; 0], and T a Hermitian BAND matrix of bandwidth
nb factored by band LU; src/hetrs.cc applies L / T / L^H in sequence;
src/hesv.cc drives both).

TPU-first shape: the reference's panel/update task graph becomes a
statically-unrolled loop over ~n/nb block columns where the hot operation
per step is ONE tall gemm ``W = A[j0:, j] - L[j0:, :j0] @ H[:j0, j]`` —
n³/3 total flops, all MXU-shaped (the r3 column-at-a-time gemv recurrence
forfeited all blocking; this is the fix).  Pivoting is confined to the
panel LU (internal/getrf.panel_lu), exactly the reference's scheme, so no
precomputed panel data is ever invalidated; pivots are applied as one
symmetric row/column gather per panel.  T's band LU solve reuses the
packed-band kernels (internal/band.py gbtrf/gbtrs with kl = ku = nb),
the same "factor T with gbtrf" choice the reference makes.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.matrix import HermitianMatrix, Matrix, SymmetricMatrix
from ..core.storage import TileStorage
from ..exceptions import SlateSingularError, slate_error
from ..internal.band import gbtrf_banded, gbtrs_banded
from ..internal.getrf import panel_lu
from ..options import Options
from ..robust import certify as _certify
from ..robust import faults as _faults
from ..robust import health as _health
from ..types import is_complex
from ..util.trace import annotate


class HEFactors(NamedTuple):
    """Blocked Aasen factors: P A P^H = L T L^H.

    ``L``     [n, n] dense unit lower (block column 0 = [I; 0])
    ``Tdiag`` [Nt, nb, nb] Hermitian diagonal blocks of T (padded space)
    ``Tsub``  [Nt-1, nb, nb] subdiagonal blocks T[j+1, j] (upper
              triangular — the panel LU's U factors); T[j, j+1] = Tsub^H
    ``piv``   [n] row/column permutation: A[piv][:, piv] = L T L^H
    ``nb``    panel width = T's bandwidth
    ``Tlu``/``Tperms``  T's band-LU factors, computed ONCE here so every
              hetrs reuses them (ref: hetrf.cc factors T with gbtrf
              inside the factorization)
    """
    L: jax.Array
    Tdiag: jax.Array
    Tsub: jax.Array
    piv: jax.Array
    nb: int
    Tlu: jax.Array
    Tperms: jax.Array

    @property
    def n(self) -> int:
        return self.L.shape[0]

    def T_dense(self):
        """Assemble the band matrix T as a dense [n, n] array."""
        nb = self.nb
        Nt = self.Tdiag.shape[0]
        n_pad = Nt * nb
        t = jnp.zeros((n_pad, n_pad), self.Tdiag.dtype)
        for j in range(Nt):
            j0 = j * nb
            t = t.at[j0:j0 + nb, j0:j0 + nb].set(self.Tdiag[j])
            if j + 1 < Nt:
                t = t.at[j0 + nb:j0 + 2 * nb, j0:j0 + nb].set(self.Tsub[j])
                t = t.at[j0:j0 + nb, j0 + nb:j0 + 2 * nb].set(
                    jnp.conj(self.Tsub[j]).T)
        return t[: self.n, : self.n]


def _blocks_of_row(L, j0, j1, nb):
    """L[j0:j1, :j1] as [j+1, nb, nb] block array (block m = L[j, m])."""
    j = j0 // nb
    row = L[j0:j1, :j1]
    return row.reshape(j1 - j0, j + 1, nb).transpose(1, 0, 2)


def _aasen_blocked(a, nb: int, constrain=None):
    """Blocked Aasen on a dense Hermitian matrix (both triangles
    populated).  Returns (L, Tdiag, Tsub, piv) over the nb-padded space
    (pad block = identity; pivots never select the zero pad rows).

    ``constrain`` (mesh path): a function pinning an [n, *] array's rows
    across the mesh — applied to the two big live arrays (ap, L) each
    panel so GSPMD keeps them distributed and partitions the hot gemm
    row-parallel; everything it feeds that is O(n nb) or smaller stays
    replicated, the same big/small split as the reference's layout
    (ref: hetrf.cc panel/update tasks)."""
    pin = constrain or (lambda x: x)
    n0 = a.shape[0]
    dt = a.dtype
    Nt = max(1, -(-n0 // nb))
    n = Nt * nb
    ap = jnp.zeros((n, n), dt).at[:n0, :n0].set(a)
    pad = jnp.arange(n0, n)
    ap = pin(ap.at[pad, pad].set(1))

    L = pin(jnp.zeros((n, n), dt).at[jnp.arange(nb),
                                     jnp.arange(nb)].set(1))
    Tdiag = jnp.zeros((Nt, nb, nb), dt)
    Tsub = jnp.zeros((max(Nt - 1, 1), nb, nb), dt)
    piv = jnp.arange(n)

    for j in range(Nt):
        j0, j1 = j * nb, (j + 1) * nb
        Ljj = L[j0:j1, j0:j1]
        if j > 0:
            # H[k, j] = T[k,k-1] L[j,k-1]^H + T[k,k] L[j,k]^H
            #           + T[k,k+1] L[j,k+1]^H   for k < j
            Lb = _blocks_of_row(L, j0, j1, nb)        # [j+1, nb, nb]
            LbH = jnp.conj(Lb).transpose(0, 2, 1)
            H = jnp.einsum("kab,kbc->kac", Tdiag[:j], LbH[:j])
            if j > 1:
                H = H.at[1:].add(jnp.einsum("kab,kbc->kac",
                                            Tsub[: j - 1], LbH[: j - 1]))
            TsubH = jnp.conj(Tsub[:j]).transpose(0, 2, 1)
            H = H + jnp.einsum("kab,kbc->kac", TsubH, LbH[1: j + 1])
            Hflat = H.reshape(j * nb, nb)
            # the hot op: one tall MXU gemm (ref: hetrf.cc trailing gemms)
            W = ap[j0:, j0:j1] - L[j0:, :j0] @ Hflat
        else:
            W = ap[:, :nb]

        Hjj = lax.linalg.triangular_solve(
            Ljj, W[:nb], left_side=True, lower=True, unit_diagonal=True)
        rhs = Hjj if j == 0 else (
            Hjj - Tsub[j - 1] @ jnp.conj(L[j0:j1, j0 - nb:j0]).T)
        Tjj = lax.linalg.triangular_solve(
            Ljj, rhs, left_side=False, lower=True, transpose_a=True,
            conjugate_a=True, unit_diagonal=True)
        Tjj = (Tjj + jnp.conj(Tjj).T) / 2
        Tdiag = Tdiag.at[j].set(Tjj)

        if j + 1 < Nt:
            V = W[nb:] - L[j1:, j0:j1] @ Hjj
            R = lax.linalg.triangular_solve(
                Ljj, V, left_side=False, lower=True, transpose_a=True,
                conjugate_a=True, unit_diagonal=True)   # = L[j1:, j+1] T[j+1,j]
            # pivot only among the LIVE rows (static slice): an exactly-zero
            # R column ties every row at 0 and XLA's LU may otherwise hand
            # the pivot to a pad row, leaking an out-of-range index into piv
            wl = n0 - j1                                # live trailing rows
            lu, perm = panel_lu(R[:wl])                 # R[perm] = Lp Up
            Lp = jnp.zeros((n - j1, nb), dt).at[:wl].set(
                jnp.tril(lu, -1)[:wl] + jnp.eye(wl, nb, dtype=dt))
            Tsub = Tsub.at[j].set(
                jnp.zeros((nb, nb), dt).at[:min(wl, nb)].set(
                    jnp.triu(lu[:nb])[:min(wl, nb)]))
            # symmetric pivot application to the trailing rows/columns
            rp = jnp.arange(n).at[j1:j1 + wl].set(j1 + perm)
            ap = pin(ap[rp][:, rp])
            L = L[rp]
            piv = piv[rp]
            L = pin(L.at[j1:, j1:j1 + nb].set(Lp))

    return L[:n0, :n0], Tdiag, Tsub, piv[:n0]


def _hetrf_health(A, F: HEFactors) -> _health.HealthInfo:
    """Health of an Aasen factorization: (a) the band-T pivot record —
    T's band LU has no pivoting escape beyond its band, so a zero/
    non-finite U diagonal (row kl+ku = 2 kd of the packed factor,
    internal/band.py layout) means a singular T and a poisoned solve,
    reported LAPACK-style through ``info`` — and (b) the a-posteriori
    LDLT certificate of P A P^H = L T L^H against the original matrix
    (``certify.certify_ldlt``), which catches corruption the pivot
    record cannot (a bit-flipped L is finite with healthy-looking T)."""
    n0 = F.n
    kd = min(F.nb, max(n0 - 1, 0))
    udiag = F.Tlu[2 * kd, :n0]
    cert = _certify.certify_ldlt(A.to_dense(), F.L, F.T_dense(), F.piv)
    return _health.merge(_health.from_pivots(udiag), cert,
                         _health.from_result(F.L))


def _hetrf_exc(h):
    return SlateSingularError(
        f"hetrf: singular band T — Aasen's tridiagonal factor has a "
        f"zero/non-finite pivot ({h.describe()})", info=int(h.info))


@annotate("slate.hetrf")
def hetrf(A, opts: Options | None = None):
    """Blocked Aasen factorization of a Hermitian indefinite matrix
    (ref: src/hetrf.cc).  Returns HEFactors; T has bandwidth A.nb.
    Under ``ErrorPolicy.Info`` returns ``(HEFactors, HealthInfo)``; a
    singular band T raises ``SlateSingularError(info=k)`` eagerly under
    the default Raise policy (LAPACK's hetrf info contract — previously
    ``gbtrf_banded`` emitted non-finite values with no signal).

    The recurrence amplifies matmul rounding, so the factorization pins
    true-f32 multiplication (TPU's default bf16-pass matmul loses the
    factorization entirely at n in the thousands)."""
    slate_error(isinstance(A, (HermitianMatrix, SymmetricMatrix)),
                "hetrf: need HermitianMatrix/SymmetricMatrix")
    slate_error(isinstance(A, HermitianMatrix) or not is_complex(A.dtype),
                "hetrf: complex SymmetricMatrix unsupported (use "
                "HermitianMatrix)")
    from ..options import Target, resolve_target
    nb = A.nb
    if resolve_target(opts, A) is Target.mesh and A.grid.mesh is not None:
        F = _hetrf_mesh(A, nb)
    else:
        with jax.default_matmul_precision("highest"):
            L, Tdiag, Tsub, piv = _aasen_blocked(A.to_dense(), nb)
            L = _faults.maybe_corrupt("post_stage1", L)
            F = _finish_factors(L, Tdiag, Tsub, piv, nb)
    with jax.default_matmul_precision("highest"):
        h = _hetrf_health(A, F)
    return _health.finalize("hetrf", F, h, opts, _hetrf_exc)


def _hetrf_mesh(A, nb: int) -> HEFactors:
    """Mesh Aasen (ref: src/hetrf.cc:1-619 distributes the panel/update
    gemms over ranks).

    TPU-first layout choice: Aasen's live state is two [n, n] arrays (the
    pivoted A and the growing L) updated one O(n nb) block column per
    step — a ROW-SHARDED dense layout under GSPMD, not block-cyclic
    tiles, maps this best: the hot gemm W = A[j0:, j] - L[j0:, :j0] H
    partitions row-parallel with ZERO collectives (H is replicated and
    O(n nb)), and the symmetric pivot gather is the only communicating
    op.  A is expanded tile->dense with its rows immediately pinned
    across all mesh devices — no replicated [n, n] ever materializes —
    and every panel re-pins A and L (see _aasen_blocked's ``constrain``).
    Panel-sized objects (H, T blocks, panel LU, T's band factors) stay
    replicated: the same big/small split as the reference's layout."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..core.grid import AXIS_P, AXIS_Q
    rowsh = NamedSharding(A.grid.mesh, P((AXIS_P, AXIS_Q), None))

    def pin(x):
        return jax.lax.with_sharding_constraint(x, rowsh)

    with jax.default_matmul_precision("highest"):
        ad = pin(A.to_dense())
        L, Tdiag, Tsub, piv = _aasen_blocked(ad, nb, constrain=pin)
        return _finish_factors(L, Tdiag, Tsub, piv, nb)


def _finish_factors(L, Tdiag, Tsub, piv, nb: int) -> HEFactors:
    """Band-LU T once (ref: hetrf.cc factors T with gbtrf inside the
    factorization); callers hold matmul precision pinned."""
    n0 = L.shape[0]
    kd = min(nb, max(n0 - 1, 0))
    gp = _packed_band_T(Tdiag, Tsub, nb, n0, kd)      # [2kd+1, n0]
    work = jnp.zeros((3 * kd + 1, n0), gp.dtype).at[kd:].set(gp)
    w = min(max(nb, 1), max(n0, 1))
    Tlu, Tperms = gbtrf_banded(work, kd, kd, n0, w)
    return HEFactors(L, Tdiag, Tsub, piv, nb, Tlu, Tperms)


def _packed_band_T(Tdiag, Tsub, nb: int, n0: int, kd: int):
    """General packed band [2kd+1, n0] of T straight from its block
    arrays (no dense assembly): P[kd + i - c, c] = T[i, c] with the three
    block cases diag / sub / super-as-conj-sub."""
    dt = Tdiag.dtype
    Nt = Tdiag.shape[0]
    rr = jnp.arange(2 * kd + 1)[:, None]
    c = jnp.arange(n0)[None, :]
    i = c + rr - kd                                   # global row index
    bi, il = i // nb, i % nb
    bc, cl = c // nb, c % nb
    valid = (i >= 0) & (i < n0)
    bis = jnp.clip(bi, 0, Nt - 1)
    diag = Tdiag[jnp.clip(bc, 0, Nt - 1), jnp.clip(il, 0, nb - 1), cl]
    if Tsub.shape[0]:
        sub = Tsub[jnp.clip(bc, 0, Tsub.shape[0] - 1),
                   jnp.clip(il, 0, nb - 1), cl]
        sup = jnp.conj(Tsub[jnp.clip(bis, 0, Tsub.shape[0] - 1),
                            cl, jnp.clip(il, 0, nb - 1)])
    else:
        sub = sup = jnp.zeros_like(diag)
    out = jnp.where(bi == bc, diag,
                    jnp.where(bi == bc + 1, sub,
                              jnp.where(bi == bc - 1, sup,
                                        jnp.zeros((), dt))))
    return jnp.where(valid, out, jnp.zeros((), dt))


@annotate("slate.hetrs")
def hetrs(F: HEFactors, B, opts: Options | None = None):
    """Solve from Aasen factors (ref: src/hetrs.cc):
    x = P^H L^-H T^-1 L^-1 P b.  T's band-LU factors come precomputed in
    HEFactors; matmul precision pinned for the same reason as hetrf."""
    b = B.to_dense() if isinstance(B, Matrix) else jnp.asarray(B)
    n0 = F.n
    nb = F.nb
    kd = min(nb, max(n0 - 1, 0))
    w = min(max(nb, 1), max(n0, 1))
    with jax.default_matmul_precision("highest"):
        bp = jnp.take(b, F.piv, axis=0)
        z = lax.linalg.triangular_solve(F.L, bp, left_side=True,
                                        lower=True, unit_diagonal=True)
        y = gbtrs_banded(F.Tlu, F.Tperms, kd, kd, n0, w,
                         z.astype(F.Tlu.dtype))
        wv = lax.linalg.triangular_solve(F.L, y.astype(F.L.dtype),
                                         left_side=True, lower=True,
                                         transpose_a=True, conjugate_a=True,
                                         unit_diagonal=True)
        x = jnp.zeros_like(wv).at[F.piv].set(wv)
    x = _faults.maybe_corrupt("solve", x)
    if isinstance(B, Matrix):
        return Matrix(TileStorage.from_dense(x, B.mb, B.nb, B.grid))
    return x


@annotate("slate.hesv")
def hesv(A, B, opts: Options | None = None):
    """Solve A X = B for Hermitian indefinite A (ref: src/hesv.cc).
    Returns (HEFactors, X); under ``ErrorPolicy.Info``,
    ``(F, X, HealthInfo)``.

    A singular band T (no pivoting escape inside Aasen's tridiagonal
    factor) falls back to densified LU ``gesv`` when
    ``Option.UseFallbackSolver`` is set — see
    ``recovery.hesv_with_recovery``."""
    from ..robust.recovery import hesv_with_recovery
    return hesv_with_recovery(A, B, opts)
