"""SVD via two-stage bidiagonalization: ge2tb -> tb2bd -> bdsqr -> back.

Analog of the reference's SVD chain (ref: src/svd.cc:65-363 orchestration:
ge2tb -> ge2tbGather -> tb2bd -> copytb2bd -> lapack::bdsqr on rank 0 ->
unmbr_tb2bd/unmbr_ge2tb back-transforms; src/ge2tb.cc QR+LQ panel
alternation; src/tb2bd.cc bulge chasing).

TPU-first shape mirrors drivers/heev.py:

- ge2tb: alternating QR (left) and LQ (right) Householder panels — all
  O(mn^2) work in larfb MXU gemms; band result is upper triangular with
  bandwidth nb.
- stage-2 seam (MethodSvd): Auto SVDs the stage-1 band directly with the
  vendor kernel (no chase — see _stage2_svd); Bidiag is the parity route:
  tb2bd bulge chase as ONE lax.scan of alternating right/left kd-window
  reflectors (the reference's sweep/step task pipeline, tb2bd.cc) with
  U2/V2 accumulated in the scan, then the bdsqr-analog seam (svd.cc:286).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.matrix import Matrix
from ..core.storage import TileStorage
from ..exceptions import SlateNotConvergedError, slate_error
from ..internal.qr import (apply_q_left, apply_q_right,
                           householder_panel_blocked, householder_vec,
                           phase_of)
from ..options import (ErrorPolicy, MethodSvd, Option, Options, Target,
                       get_option, resolve_target)
from ..robust import certify as _certify
from ..robust import faults as _faults
from ..robust import health as _health
from ..types import Op, is_complex
from ..util.trace import annotate, span


def _notconv_exc(name):
    return lambda h: SlateNotConvergedError(
        f"{name}: singular value decomposition failed certification "
        f"({h.describe()})", iters=int(h.iters))


# ---------------------------------------------------------------- stage 1

def _ge2tb_scan(a, nb: int):
    """Dense m x n (m >= n) -> upper triangular band of bandwidth nb, as
    ONE lax.scan step per QR+LQ panel pair with uniform shapes.

    The reference's ge2tb alternates shrinking QR and LQ panels
    (ref: src/ge2tb.cc); a statically-unrolled translation compiles K
    copies of the body (the compile-size blowup fixed in heev._he2hb_scan
    — same re-anchoring discipline here).  After each pair the trailing
    block moves to the origin, so every step is shape-identical; rows and
    columns past the live block are exactly zero and reflectors there are
    identity (tau = 0).

    Returns (Vqs, Tqs, Vls, Tls, Ds, Ss): QR panels [K, Mp, nb] (panel
    k's local row 0 = global row k nb), LQ panels [K, Np-nb, nb]
    conjugate-transposed to column form (local row 0 = global col
    (k+1) nb), the T triangles, band diagonal tiles Ds [K, nb, nb] (R in
    the triu) and superdiagonal tiles Ss [K, nb, nb] (L in the tril).
    Mp = ceil(m/nb) nb, Np = ceil(n/nb) nb, K = Np/nb."""
    m, n = a.shape
    Mp = -(-m // nb) * nb
    Np = -(-n // nb) * nb
    K = Np // nb
    ap = jnp.zeros((Mp, Np), a.dtype).at[:m, :n].set(a)
    if Np == nb:
        # single block column: pure QR, no LQ side at all
        packed_q, Tq = householder_panel_blocked(ap)
        return (packed_q[None], Tq[None],
                jnp.zeros((0, 1, nb), a.dtype),
                jnp.zeros((0, nb, nb), a.dtype),
                packed_q[None, :nb, :nb], jnp.zeros((1, nb, nb), a.dtype))

    iw = jnp.arange(nb)[:, None]
    jk = jnp.arange(Np - nb)[None, :]

    def step(A, _):
        # left QR panel on the leading nb columns (zero tail rows inert)
        packed_q, Tq = householder_panel_blocked(A[:, :nb])
        trail = apply_q_left(packed_q, Tq, A[:, nb:], conj_trans=True)
        D = packed_q[:nb, :nb]                   # R -> band diag tile
        # right LQ panel on the leading nb rows of the trailing columns:
        # factor conj(blk)^T = Q_l R_l; blk <- blk conj(Q_l) = [L 0]
        blk = trail[:nb, :]                      # [nb, Np - nb]
        packed_l, Tl = householder_panel_blocked(jnp.conj(blk).T)
        # band superdiag tile: L (= R_l^H) on/below the diagonal with the
        # reflector v entries strictly above (LAPACK gelqf packing)
        ell = jnp.conj(jnp.triu(packed_l)).T     # [nb, Np - nb]
        vrows = jnp.conj(packed_l).T
        newblk = jnp.where(jk <= iw, ell, vrows)
        S = newblk[:, :nb]
        # trailing right update, then re-anchor to the origin
        tr = apply_q_right(packed_l, Tl, trail[nb:, :], conj_trans=False)
        A_next = jnp.zeros_like(A).at[: Mp - nb, : Np - nb].set(tr)
        return A_next, (packed_q, Tq, packed_l, Tl, D, S)

    _, (Vqs, Tqs, Vls, Tls, Ds, Ss) = lax.scan(step, ap, None, length=K)
    return Vqs, Tqs, Vls, Tls, Ds, Ss


def _band_upper_from_stacks(Ds, Ss, n: int, nb: int):
    """Dense upper band from the ge2tb scan's band tiles (single-target
    twin of _band_upper_from_tiles)."""
    from ..core.layout import assemble_band
    bd = assemble_band(jnp.triu(Ds), jnp.tril(Ss), lower=False)
    return _band_upper_of(bd[:n, :n], n, nb)


def _band_upper_of(a_packed, n: int, kd: int):
    """Extract the n x n upper band (0 <= j - i <= kd) from ge2tb packing."""
    sq = a_packed[:n, :n]
    i = jnp.arange(n)[:, None]
    j = jnp.arange(n)[None, :]
    mask = (j - i >= 0) & (j - i <= kd)
    return jnp.where(mask, sq, jnp.zeros_like(sq))


# ---------------------------------------------------------------- stage 2

def _tb2bd(band, kd: int, want_uv: bool):
    """Upper band (bandwidth kd) -> real upper bidiagonal (d, e) via
    alternating right/left bulge-chase reflectors in one lax.scan
    (ref: tb2bd.cc gebr1/2/3 sweep pipeline).

    Returns (d, e, U2, V2) with band = U2 B V2^H."""
    n = band.shape[0]
    dt = band.dtype
    if n == 1:
        d = jnp.abs(band[0, 0])[None]
        eye = jnp.eye(1, dtype=dt)
        ph = phase_of(band[0, 0])
        return d, jnp.zeros((0,), d.dtype), ph * eye if want_uv else None, \
            eye if want_uv else None
    kd = max(1, min(kd, n - 1))
    off = 2 * kd                                  # top/left padding
    N = n + 4 * kd + 2
    A = jnp.zeros((N, N), dt).at[off:off + n, off:off + n].set(band)
    U = jnp.eye(N, dtype=dt) if want_uv else jnp.zeros((1, 1), dt)
    V = jnp.eye(N, dtype=dt) if want_uv else jnp.zeros((1, 1), dt)

    Umax = max(1, -(-(n - 1) // kd))              # chase pairs per sweep

    def step(carry, jus):
        A, U, V = carry
        j, u = jus
        # ---- right sub-step: clear row r beyond its first superdiag ----
        r = jnp.where(u == 0, j, j + 1 + (u - 1) * kd) + off
        cb = j + 1 + u * kd + off
        # row-clearing by RIGHT multiplication: build the reflector from
        # conj(row) so that x H = beta e1^T (column-semantics householder_vec)
        x = lax.dynamic_slice(A, (r, cb), (1, kd))[0]
        v, tau, _ = householder_vec(jnp.conj(x))
        # cols [cb, cb+kd), rows [cb-kd, cb+kd)
        Wr = lax.dynamic_slice(A, (cb - kd, cb), (2 * kd, kd))
        Wr = Wr - tau * (Wr @ v)[:, None] * jnp.conj(v)[None, :]
        A = lax.dynamic_update_slice(A, Wr, (cb - kd, cb))
        if want_uv:
            Vc = lax.dynamic_slice(V, (0, cb), (N, kd))
            Vc = Vc - tau * (Vc @ v)[:, None] * jnp.conj(v)[None, :]
            V = lax.dynamic_update_slice(V, Vc, (0, cb))
        # ---- left sub-step: clear col rb below its diagonal ----
        rb = j + 1 + u * kd + off
        x2 = lax.dynamic_slice(A, (rb, rb), (kd, 1))[:, 0]
        v2, tau2, _ = householder_vec(x2)
        W2 = lax.dynamic_slice(A, (rb, rb), (kd, 2 * kd + 1))
        W2 = W2 - jnp.conj(tau2) * v2[:, None] * (jnp.conj(v2) @ W2)[None, :]
        A = lax.dynamic_update_slice(A, W2, (rb, rb))
        if want_uv:
            Uc = lax.dynamic_slice(U, (0, rb), (N, kd))
            Uc = Uc - tau2 * (Uc @ v2)[:, None] * jnp.conj(v2)[None, :]
            U = lax.dynamic_update_slice(U, Uc, (0, rb))
        return (A, U, V), None

    # static schedule: only live (sweep, chase-pair) steps — pair u of sweep
    # j starts at column j+1+u*kd, so later sweeps need fewer pairs
    pairs = [(j, u) for j in range(n - 1) for u in range(Umax)
             if j + 1 + u * kd < n]
    js = jnp.asarray([pr[0] for pr in pairs])
    us = jnp.asarray([pr[1] for pr in pairs])
    (A, U, V), _ = lax.scan(step, (A, U, V), (js, us))

    sq = A[off:off + n, off:off + n]
    d_c = jnp.diagonal(sq)
    e_c = jnp.diagonal(sq, offset=1)
    U = U[off:off + n, off:off + n] if want_uv else None
    V = V[off:off + n, off:off + n] if want_uv else None

    # phase-normalise to a real bidiagonal (ref: zbdsqr requires real d, e)
    if is_complex(dt):
        def phase_step(rprev, de):
            dj, ej = de
            lj = phase_of(dj * rprev)             # makes conj(l) d r real
            rnext = jnp.conj(phase_of(jnp.conj(lj) * ej))
            return rnext, (lj, rnext)

        e_pad = jnp.concatenate([e_c, jnp.ones((1,), dt)])
        _, (ls, rs) = lax.scan(phase_step, jnp.ones((), dt), (d_c, e_pad))
        rs = jnp.concatenate([jnp.ones((1,), dt), rs[:-1]])
        d = jnp.real(jnp.conj(ls) * d_c * rs)
        e = jnp.real(jnp.conj(ls[:-1]) * e_c * rs[1:])
        if want_uv:
            # band = (U L) B_real (V R)^H with L = diag(ls), R = diag(rs)
            U = U * ls[None, :]
            V = V * rs[None, :]
    else:
        d, e = d_c, e_c
    return d, e, U, V


# ---------------------------------------------------------------- driver

def _bd_svd(d, e, want_uv: bool):
    """Vendor-kernel seam (ref: svd.cc:286 lapack::bdsqr on rank 0): SVD of
    the assembled bidiagonal via XLA's native svd."""
    n = d.shape[0]
    B = jnp.diag(d) + (jnp.diag(e, 1) if n > 1 else 0)
    if want_uv:
        Ub, s, Vbh = jnp.linalg.svd(B)
        return s, Ub, Vbh
    return jnp.linalg.svd(B, compute_uv=False), None, None


@annotate("slate.bdsqr")
def bdsqr(d, e, opts: Options | None = None):
    """SVD of a real upper bidiagonal (d, e) as a public driver
    (ref: src/bdsqr.cc wrapping lapack::bdsqr).  Returns (s, U, Vh);
    under ``ErrorPolicy.Info``, ``(s, U, Vh, HealthInfo)``."""
    s, U, Vh = _bd_svd(jnp.asarray(d), jnp.asarray(e), True)
    return _health.finalize_flat("bdsqr", (s, U, Vh),
                                 _health.from_result(s), opts,
                                 _notconv_exc("bdsqr"))


@annotate("slate.tb2bd")
def tb2bd(TB, opts: Options | None = None, *, want_uv: bool = True):
    """Band -> bidiagonal bulge chase as a public driver
    (ref: src/tb2bd.cc): takes a TriangularBandMatrix (upper), returns
    (d, e, U2, V2) with band = U2 B V2^H; under ``ErrorPolicy.Info``,
    ``(d, e, U2, V2, HealthInfo)``."""
    from ..core.matrix import TriangularBandMatrix
    slate_error(isinstance(TB, TriangularBandMatrix),
                "tb2bd: need TriangularBandMatrix")
    d, e, U2, V2 = _tb2bd(TB.to_dense(), TB.kd, want_uv=want_uv)
    h = _health.merge(_health.from_result(d), _health.from_result(e))
    return _health.finalize_flat("tb2bd", (d, e, U2, V2), h, opts,
                                 _notconv_exc("tb2bd"))


def _stage2_svd(band, nb: int, jobu: bool, opts: Options | None):
    """Stage 2 + small-problem seam, method-dispatched (the MethodSvd
    consumer).  Returns (s, Un, Vn, HealthInfo) with
    band = Un diag(s) Vn^H (Un/Vn None when jobu=False); the fault sites
    ``post_stage1`` (the band handed to stage 2) and ``post_chase`` (the
    chased bidiagonal) fire here.

    Auto: SVD the band DIRECTLY with XLA's svd — the tb2bd chase's
    sequential scan is pure latency when the downstream kernel is O(n^3)
    dense svd anyway (same reasoning as MethodEig.Auto; cf. ref svd.cc:286
    where the chase feeds O(n^2) bdsqr, which does pay).
    Bidiag: the parity route — tb2bd bulge chase to a true bidiagonal,
    then the bdsqr-analog seam."""
    band = _faults.maybe_corrupt("post_stage1", band)
    meth = get_option(opts, Option.MethodSvd)
    if meth is MethodSvd.Auto:
        if jobu:
            Ub, s, Vbh = jnp.linalg.svd(band)
            return s, Ub, jnp.conj(Vbh).T, _health.from_result(s)
        s = jnp.linalg.svd(band, compute_uv=False)
        return s, None, None, _health.from_result(s)
    d, e, U2, V2 = _tb2bd(band, nb, want_uv=jobu)
    d = _faults.maybe_corrupt("post_chase", d)
    s, Ub, Vbh = _bd_svd(d, e, jobu)
    h = _health.merge(_health.from_result(d), _health.from_result(e),
                      _health.from_result(s))
    if not jobu:
        return s, None, None, h
    Un = U2 @ Ub.astype(U2.dtype)
    Vn = V2 @ jnp.conj(Vbh.astype(V2.dtype)).T
    return s, Un, Vn, h


def _unmbr_ge2tb_u(Vqs, Tqs, nb: int, Z):
    """Z <- Q_qr Z (ref: unmbr_ge2tb U side): QR panels descending;
    panel k's reflectors start at global row k nb.  Z has Mp rows."""
    from ..internal.qr import rolled_apply
    K = Tqs.shape[0]
    return rolled_apply(Vqs, Tqs, jnp.arange(K) * nb, Z)


def _unmbr_ge2tb_v(Vls, Tls, nb: int, Z):
    """Z <- V1 Z with V1 = W_0 W_1 ... (ref: unmbr_ge2tb V side): each
    W_k = Q_lq_k acts on global rows (k+1) nb and below.  Z has Np rows."""
    from ..internal.qr import rolled_apply
    K = Tls.shape[0]
    return rolled_apply(Vls, Tls, (jnp.arange(K) + 1) * nb, Z)


def _svd_compute(A: Matrix, opts: Options | None, jobu: bool):
    """svd compute recursion: ``(s, Um, Vm, HealthInfo)``, no policy and
    no certificate — the m < n case recurses on A^H with U/V swapped, and
    certification must happen exactly once at the svd_info boundary."""
    slate_error(type(A) is Matrix,
                "svd: need a general Matrix (convert structured types "
                "with .general())")
    m, n = A.m, A.n
    if m < n:
        s, V, U, h = _svd_compute(_conj_t_root(A), opts, jobu)
        return s, U, V, h
    if resolve_target(opts, A) is Target.mesh and A.grid.mesh is not None:
        return _svd_mesh(A, opts, jobu)
    nb = A.nb
    ad = A.to_dense()
    with span("slate.svd/ge2tb"):
        Vqs, Tqs, Vls, Tls, Ds, Ss = _ge2tb_scan(ad, nb)
        band = _band_upper_from_stacks(Ds, Ss, n, nb)
    with span("slate.svd/stage2"):
        s, Un, Vn, h = _stage2_svd(band, nb, jobu, opts)
    if not jobu:
        return s, None, None, h
    with span("slate.svd/backtransform"):
        dt = ad.dtype
        Mp = Vqs.shape[1]
        Np = -(-n // nb) * nb
        Upad = jnp.zeros((Mp, n), dt).at[:n, :n].set(Un.astype(dt))
        Ufull = _unmbr_ge2tb_u(Vqs, Tqs, nb, Upad)[:m]
        Ufull = _faults.maybe_corrupt("post_backtransform", Ufull)
        Vpad = jnp.zeros((Np, n), dt).at[:n].set(Vn.astype(dt))
        Vfull = _unmbr_ge2tb_v(Vls, Tls, nb, Vpad)[:n]
        g = A.grid
        Um = Matrix(TileStorage.from_dense(Ufull, A.mb, A.nb, g))
        Vm = Matrix(TileStorage.from_dense(Vfull, A.nb, A.nb, g))
    return s, Um, Vm, h


def svd_info(A: Matrix, opts: Options | None = None, *, jobu: bool = True):
    """svd compute body: ``((s, Um, Vm), HealthInfo)``, no policy
    resolution (the recovery layer escalates on this seam).  The health
    merges the stage-2 flags with the a-posteriori SVD certificate of the
    back-transformed factors against the ORIGINAL A
    (``certify.certify_svd``)."""
    s, Um, Vm, h = _svd_compute(A, opts, jobu)
    if jobu:
        h = _health.merge(
            _certify.certify_svd(A.to_dense(), s, Um.to_dense(),
                                 Vm.to_dense()), h)
    else:
        h = _health.merge(_health.from_result(s), h)
    return (s, Um, Vm), h


@annotate("slate.svd")
def svd(A: Matrix, opts: Options | None = None, *, jobu: bool = True):
    """Singular value decomposition A = U diag(s) V^H (ref: src/svd.cc).

    Returns (s, U, V) with thin U [m, r], V [n, r], r = min(m, n);
    (s, None, None) when jobu=False; under ``ErrorPolicy.Info`` the
    HealthInfo is appended.  m < n handled by factoring A^H.

    Every result is a-posteriori certified (residual + left/right
    orthogonality, robust/certify.py); an eager certification failure
    escalates MethodSvd Auto -> Bidiag before the ErrorPolicy resolves —
    see ``recovery.svd_with_recovery`` and docs/ROBUSTNESS.md."""
    from ..robust.recovery import svd_with_recovery
    return svd_with_recovery(A, opts, jobu=jobu)


def _band_upper_from_tiles(st, n: int, nb: int):
    """Assemble the n x n upper band from ge2tb-packed storage: triu of
    diagonal tiles + tril of superdiagonal tiles, gathered straight from
    the cyclic data (the analog of TriangularBandMatrix::ge2tbGather,
    ref: svd.cc:153-160 — only the O(n nb) band tiles leave the mesh)."""
    from ..core.layout import assemble_band
    from .heev import _band_diag_tiles
    Ntn = -(-n // nb)
    dd = jnp.triu(_band_diag_tiles(st, 0)[:Ntn])
    ss = (jnp.tril(_band_diag_tiles(st, -1)[:Ntn - 1]) if Ntn > 1
          else jnp.zeros((0, nb, nb), st.dtype))  # tiles (g, g+1)
    bd = assemble_band(dd, ss, lower=False)
    return _band_upper_of(bd[:n, :n], n, nb)


def _svd_mesh(A: Matrix, opts, jobu: bool):
    """Mesh path: stage 1 (all the O(mn^2) flops) runs DISTRIBUTED via
    dist_ge2tb — the input is never densified; only the O(n nb) band is
    gathered for stage 2 (the reference's ge2tbGather seam, svd.cc:153).
    The U2 Ub / V2 Vb products are mesh SUMMA gemms and the stage-1
    back-transforms are distributed panel applies."""
    from ..parallel.dist_ge2tb import (dist_ge2tb, dist_unmbr_ge2tb_u,
                                       dist_unmbr_ge2tb_v)
    m, n, nb = A.m, A.n, A.nb
    grid = A.grid
    if (A.op is Op.NoTrans and A.is_root_view() and A.storage.mb == nb):
        st_in = A.storage                        # zero-copy
    else:
        st_in = TileStorage.from_dense(A.to_dense(), nb, nb, grid)
    from ..parallel.dist_chol import SUPERBLOCKS, superblock
    la = max(1, int(get_option(opts, Option.Lookahead)))
    with span("slate.svd/ge2tb"):
        data, Tqs, Tls = dist_ge2tb(st_in.data, st_in.Mt, st_in.Nt, m, n,
                                    grid,
                                    sb=superblock(max(st_in.Nt, 1),
                                                  SUPERBLOCKS * la))
        st_packed = TileStorage(data, m, n, nb, nb, grid)
        band = _band_upper_from_tiles(st_packed, n, nb)
    # ONE stage-2 dispatch shared with the single-target path (stage 2 is
    # single-node by design, as the reference's is); only the stage-1
    # back-transforms below are mesh-distributed
    with span("slate.svd/stage2"):
        s, Uns, Vns, h = _stage2_svd(band, nb, jobu, opts)
    if not jobu:
        return s, None, None, h
    with span("slate.svd/backtransform"):
        dt = st_packed.dtype
        Un = Matrix(TileStorage.from_dense(Uns.astype(dt), nb, nb, grid))
        Vn = Matrix(TileStorage.from_dense(Vns.astype(dt), nb, nb, grid))
        # U = U1 [Un; 0], V = V1 Vn, both distributed panel chains.  Pad Un
        # [n, n] to [m, n] in TILE space — a static cyclic-slot scatter,
        # never a replicated [m, n] dense intermediate (m can be huge for
        # tall A)
        Uf = Matrix.zeros(m, n, nb, nb, grid, st_packed.dtype)
        us_, fs_ = Un.storage, Uf.storage
        gsrc = np.arange(us_.Mt)
        src = (gsrc % grid.p) * us_.mtl + gsrc // grid.p
        dst = (gsrc % grid.p) * fs_.mtl + gsrc // grid.p
        uf_data = fs_.data.at[dst].set(us_.data[src])
        Uf = Matrix(TileStorage(uf_data, m, n, nb, nb, grid))
        u_data = dist_unmbr_ge2tb_u(data, Tqs, Uf.storage.data, grid, m)
        u_data = _faults.maybe_corrupt("post_backtransform", u_data)
        v_data = dist_unmbr_ge2tb_v(data, Tls, Vn.storage.data, grid, n)
        us, vs = Uf.storage, Vn.storage
        Um = Matrix(TileStorage(u_data, us.m, us.n, us.mb, us.nb, us.grid))
        Vm = Matrix(TileStorage(v_data, vs.m, vs.n, vs.mb, vs.nb, vs.grid))
    return s, Um, Vm, h


@annotate("slate.svd_vals")
def svd_vals(A: Matrix, opts: Options | None = None):
    """Singular values only (ref: simplified_api svd_vals).  Under
    ``ErrorPolicy.Info`` returns ``(s, HealthInfo)``."""
    res = svd(A, opts, jobu=False)
    if _health.error_policy(opts) is ErrorPolicy.Info:
        s, _, _, h = res
        return s, h
    return res[0]


def _conj_t_root(A) -> Matrix:
    d = jnp.conj(A.to_dense()).T
    return Matrix(TileStorage.from_dense(d, A.nb, A.mb, A.grid))
