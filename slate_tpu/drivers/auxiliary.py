"""Auxiliary drivers: add, copy, scale, scale_row_col, set, norm, colNorms,
redistribute.

Analog of the reference's elementwise/aux driver set (ref: src/add.cc,
src/copy.cc, src/scale.cc, src/scale_row_col.cc, src/set.cc, src/norm.cc,
src/redistribute.cc:17-154).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.grid import Grid
from ..core.matrix import (BandMatrix, BaseBandMatrix, BaseMatrix,
                           BaseTrapezoidMatrix, HermitianBandMatrix,
                           HermitianMatrix, Matrix, SymmetricMatrix,
                           TriangularMatrix)
from ..core.storage import TileStorage
from ..exceptions import slate_error
from ..ops import elementwise as ew
from ..ops import norms as nrm
from ..types import Diag, Norm, Uplo
from ..options import NormScope


def _st(A: BaseMatrix) -> TileStorage:
    return A.storage


def _simple(*mats) -> bool:
    """True when tile kernels may run directly on storage: every operand is a
    root, untransposed view AND the operands agree structurally (all general,
    or all the same trapezoid class with matching uplo/diag — the reference
    poses the same requirement on add/copy of trapezoid pairs).  Otherwise
    drivers fall back to the dense path (to_dense/with_dense), which is
    correct for any view/op/structure mix."""
    from ..types import Op
    if not all(m.is_root_view() and m.op is Op.NoTrans for m in mats):
        return False
    first = mats[0]
    if type(first) is Matrix:
        return all(type(m) is Matrix for m in mats)
    return all(type(m) is type(first) and m.uplo is first.uplo and
               m.diag is first.diag for m in mats)


def add(alpha, A: BaseMatrix, beta, B: BaseMatrix) -> BaseMatrix:
    """B = alpha*A + beta*B (ref: src/add.cc -> internal_geadd/tzadd)."""
    slate_error(A.m == B.m and A.n == B.n, "add: dims differ")
    if not _simple(A, B):
        return B.with_dense(alpha * A.to_dense() + beta * B.to_dense())
    sa, sb = _st(A), _st(B)
    if isinstance(B, BaseTrapezoidMatrix):
        lower = B._uplo_logical() is Uplo.Lower
        out = ew.tzadd(alpha, sa.canonical(), beta, sb.canonical(),
                       sb.m, sb.n, sb.mb, sb.nb, lower)
    else:
        out = ew.geadd(alpha, sa.canonical(), beta, sb.canonical())
    return _rewrap(B, sb.with_canonical(out))


def copy(A: BaseMatrix, B: BaseMatrix) -> BaseMatrix:
    """B = A with precision conversion (ref: src/copy.cc gecopy/tzcopy)."""
    slate_error(A.m == B.m and A.n == B.n, "copy: dims differ")
    if not _simple(A, B):
        return B.with_dense(A.to_dense().astype(B.dtype))
    sa, sb = _st(A), _st(B)
    if isinstance(B, BaseTrapezoidMatrix):
        lower = B._uplo_logical() is Uplo.Lower
        out = ew.tzcopy(sa.canonical(), sb.canonical(), sb.m, sb.n,
                        sb.mb, sb.nb, lower, sb.dtype)
    else:
        out = ew.gecopy(sa.canonical(), sb.dtype)
    return _rewrap(B, sb.with_canonical(out))


def scale(numer, denom, A: BaseMatrix) -> BaseMatrix:
    """A *= numer/denom (ref: src/scale.cc)."""
    if not _simple(A):
        return A.with_dense(A.to_dense() * (numer / denom))
    sa = _st(A)
    if isinstance(A, BaseTrapezoidMatrix):
        lower = A._uplo_logical() is Uplo.Lower
        out = ew.tzscale(numer, denom, sa.canonical(), sa.m, sa.n,
                         sa.mb, sa.nb, lower)
    else:
        out = ew.gescale(numer, denom, sa.canonical())
    return _rewrap(A, sa.with_canonical(out))


def scale_row_col(r, c, A: BaseMatrix) -> BaseMatrix:
    """A[i,j] *= r[i]*c[j] (ref: src/scale_row_col.cc equilibration)."""
    if not _simple(A):
        r = jnp.asarray(r)
        c = jnp.asarray(c)
        return A.with_dense(A.to_dense() * r[:, None] * c[None, :])
    sa = _st(A)
    out = ew.gescale_row_col(jnp.asarray(r), jnp.asarray(c), sa.canonical(),
                             sa.m, sa.n, sa.mb, sa.nb)
    return _rewrap(A, sa.with_canonical(out))


def set(offdiag, diag, A: BaseMatrix) -> BaseMatrix:  # noqa: A001
    """A = offdiag off-diagonal, diag on diagonal (ref: src/set.cc)."""
    if not _simple(A):
        m, n = A.m, A.n
        d = jnp.full((m, n), offdiag, A.dtype)
        k = min(m, n)
        d = d.at[jnp.arange(k), jnp.arange(k)].set(diag)
        return A.with_dense(d)
    sa = _st(A)
    if isinstance(A, BaseTrapezoidMatrix):
        lower = A._uplo_logical() is Uplo.Lower
        out = ew.tzset(offdiag, diag, sa.canonical(), sa.m, sa.n,
                       sa.mb, sa.nb, lower)
    else:
        out = ew.geset(offdiag, diag, sa.canonical(), sa.m, sa.n,
                       sa.mb, sa.nb)
    return _rewrap(A, sa.with_canonical(out))


def norm(norm_type: Norm, A: BaseMatrix,
         scope: NormScope = NormScope.Matrix):
    """Matrix norm dispatching on structure (ref: src/norm.cc; kernel files
    internal_genorm/synorm/henorm/trnorm/gbnorm/hbnorm.cc).  The cross-rank
    MPI_Allreduce is implicit: reductions over the sharded canonical array
    compile to psum/pmax over the mesh."""
    # structured matrices and views/transposes: materialise (expands the
    # stored triangle / band / mirror) and measure as general
    if not _simple(A) or (scope is NormScope.Columns
                          and type(A) is not Matrix):
        d = A.to_dense()
        absd = jnp.abs(d)
        if scope is NormScope.Columns:
            return jnp.max(absd, axis=0)
        if norm_type is Norm.Max:
            return jnp.max(absd)
        if norm_type is Norm.One:
            return jnp.max(jnp.sum(absd, axis=0))
        if norm_type is Norm.Inf:
            return jnp.max(jnp.sum(absd, axis=1))
        return jnp.linalg.norm(d)
    sa = _st(A)
    tiles = sa.canonical()
    if scope is NormScope.Columns:
        return nrm.ge_col_norms(tiles, sa.m, sa.n, sa.mb, sa.nb)
    if isinstance(A, HermitianBandMatrix):
        return nrm.hb_norm(norm_type, tiles, sa.n, sa.nb, A.kd,
                           A.uplo is Uplo.Lower)
    if isinstance(A, BaseBandMatrix):
        return nrm.gb_norm(norm_type, tiles, sa.m, sa.n, sa.mb, sa.nb,
                           A.kl, A.ku)
    if isinstance(A, (SymmetricMatrix, HermitianMatrix)):
        return nrm.sy_norm(norm_type, tiles, sa.n, sa.nb,
                           A.uplo is Uplo.Lower,
                           hermitian=isinstance(A, HermitianMatrix))
    if isinstance(A, BaseTrapezoidMatrix):
        return nrm.tr_norm(norm_type, tiles, sa.m, sa.n, sa.mb, sa.nb,
                           A._uplo_logical() is Uplo.Lower,
                           unit_diag=A.diag is Diag.Unit)
    return nrm.ge_norm(norm_type, tiles, sa.m, sa.n, sa.mb, sa.nb)


def col_norms(A: BaseMatrix):
    """Per-column max-abs (ref: colNorms driver)."""
    return norm(Norm.Max, A, scope=NormScope.Columns)


def redistribute(A: BaseMatrix, mb: int | None = None, nb: int | None = None,
                 grid: Grid | None = None) -> Matrix:
    """General re-distribution between any two layouts/grids
    (ref: src/redistribute.cc:17-154 tile-by-tile send/recv).  On TPU the
    all-to-all is one resharding, emitted by XLA from the layout change.

    Same-tile-size grid changes keep tile blocks intact (a pure cyclic
    re-permutation + device_put to the new mesh sharding); only tile-size
    changes go through element-level re-tiling."""
    from ..types import Op
    mb = mb or A.mb
    nb = nb or A.nb
    grid = grid or A.grid
    if (type(A) is Matrix and A.op is Op.NoTrans and A.is_root_view()
            and mb == A.storage.mb and nb == A.storage.nb):
        tiles = A.storage.canonical()
        return Matrix(TileStorage.from_canonical(tiles, A.m, A.n, grid))
    dense = A.to_dense()
    return Matrix(TileStorage.from_dense(dense, mb, nb, grid))


def _rewrap(like: BaseMatrix, new_storage: TileStorage) -> BaseMatrix:
    v = like.__class__.__new__(like.__class__)
    BaseMatrix.__init__(v, new_storage, like.io, like.jo, like._mt, like._nt,
                        like.op, like.kind)
    v._apply_extra_aux(like._extra_aux())
    return v
