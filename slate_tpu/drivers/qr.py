"""QR / LQ / least-squares drivers: geqrf, gelqf, unmqr, unmlq, cholqr, gels.

Analog of the reference's least-squares chain (ref: src/geqrf.cc:195-206
local panel + ttqrt reduction tree, src/gelqf.cc, src/unmqr.cc, src/unmlq.cc,
src/cholqr.cc, src/gels.cc:141 + method dispatch method.hh:236-275).

TPU-first shape:

- single target: blocked Householder QR, panels factored by one fori_loop
  kernel (internal/qr.py) and trailing updates as larfb MXU gemms, the whole
  factorization unrolled under one jit (the analog of the HostTask DAG).
- cholqr / gels_cholqr compose herk + potrf + trsm drivers, so they are
  distributed on a mesh for free — and CholQR is the auto-selected method
  for tall-skinny problems (the BASELINE tall-skinny config), matching the
  reference's MethodGels heuristic.
- mesh geqrf: communication-avoiding CAQR (parallel/dist_qr.py) — local
  block-cyclic panel QR per mesh row + replicated tt-reduction of the nb x nb
  R factors, trailing updates via one psum per panel (ref: the ttqrt tree,
  src/internal/internal_ttqrt.cc:1-160).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.matrix import Matrix, TriangularMatrix
from ..core.storage import TileStorage
from ..exceptions import SlateNotPositiveDefiniteError, slate_error
from ..internal.qr import apply_q_left, apply_q_right, geqrf_panel
from ..options import ErrorPolicy, Option, Options, Target, resolve_target
from ..robust import health as _health
from ..types import Op, Side, Uplo, is_complex
from ..util.trace import annotate
from .blas3 import _dense_to_like, _side, gemm, herk, trsm
from .cholesky import potrf


@jax.tree_util.register_pytree_node_class
class QRFactors:
    """Packed QR factors: V (unit lower, below diag) \\ R (upper) in ``QR``
    plus the block-reflector triangles T [K, nb, nb]
    (ref: geqrf's TriangularFactors T, include/slate/slate.hh geqrf)."""

    def __init__(self, QR: Matrix, T):
        self.QR = QR
        self.T = T

    def tree_flatten(self):
        return (self.QR, self.T), ()

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"QRFactors({self.QR.m}x{self.QR.n}, nb={self.QR.nb})"


@jax.tree_util.register_pytree_node_class
class LQFactors:
    """LQ factors, stored as the QR factors of A^H (A = L Q, Q = Qr^H)."""

    def __init__(self, F: QRFactors):
        self.F = F

    def tree_flatten(self):
        return (self.F,), ()

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


@jax.tree_util.register_pytree_node_class
class CAQRFactors:
    """Mesh CAQR factors: packed local V's + final R in ``QR``, per-mesh-row
    block-reflector triangles ``Tloc`` [p, Kt, nb, nb], and the replicated
    tt-reduction tree factors ``Vtree`` [Kt, p*nb, nb] / ``Ttree``
    [Kt, nb, nb] (ref: geqrf's ttqrt tree triangles,
    src/internal/internal_ttqrt.cc)."""

    def __init__(self, QR: Matrix, Tloc, Vtree, Ttree):
        self.QR = QR
        self.Tloc = Tloc
        self.Vtree = Vtree
        self.Ttree = Ttree

    def tree_flatten(self):
        return (self.QR, self.Tloc, self.Vtree, self.Ttree), ()

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"CAQRFactors({self.QR.m}x{self.QR.n}, nb={self.QR.nb})"


def _geqrf_dense_blocked(a, nb: int):
    """Blocked Householder QR on a dense [m, n]; returns (packed, T[K,nb,nb]).

    Statically unrolled panel loop (same discipline as _potrf_dense_blocked):
    each panel is a fori_loop Householder kernel + larfb trailing gemms.
    """
    m, n = a.shape
    r = min(m, n)
    Ts = []
    for k0 in range(0, r, nb):
        k1 = min(k0 + nb, r)
        w = k1 - k0
        panel = a[k0:, k0:k1]
        packed, T = geqrf_panel(panel)   # tuned: Pallas panel or XLA
        a = a.at[k0:, k0:k1].set(packed)
        if k1 < n:
            trail = apply_q_left(packed, T, a[k0:, k1:], conj_trans=True)
            a = a.at[k0:, k1:].set(trail)
        if w < nb:
            T = jnp.zeros((nb, nb), T.dtype).at[:w, :w].set(T)
        Ts.append(T)
    T_stack = jnp.stack(Ts) if Ts else jnp.zeros((0, nb, nb), a.dtype)
    return a, T_stack


@annotate("slate.geqrf")
def geqrf(A: Matrix, opts: Options | None = None) -> QRFactors:
    """QR factorization A = Q R (ref: src/geqrf.cc).  Returns packed factors;
    use :func:`unmqr` to apply Q and ``triu(R)`` for solves."""
    nb = A.nb
    target = resolve_target(opts, A)
    if target is Target.mesh and A.grid.mesh is not None:
        from ..parallel.dist_qr import dist_geqrf_data
        from .blas3 import as_root_general
        An = as_root_general(A, nb, nb, A.grid)
        st = An.storage
        Kt = -(-min(st.m, st.n) // nb)
        data, Tloc, Vtree, Ttree = dist_geqrf_data(
            st.data, Kt, st.Mt, st.m, st.n, A.grid)
        Qm = Matrix(TileStorage(data, st.m, st.n, nb, nb, A.grid))
        return CAQRFactors(Qm, Tloc, Vtree, Ttree)
    ad = A.to_dense()
    packed, T = _geqrf_dense_blocked(ad, nb)
    Qm = Matrix(TileStorage.from_dense(packed, A.mb, A.nb, A.grid))
    return QRFactors(Qm, T)


@annotate("slate.gelqf")
def gelqf(A: Matrix, opts: Options | None = None) -> LQFactors:
    """LQ factorization A = L Q via QR of A^H (ref: src/gelqf.cc computes the
    mirrored Householder chain; algebraically identical)."""
    Ah = Matrix(TileStorage.from_dense(
        jnp.conj(A.to_dense()).T, A.nb, A.mb, A.grid))
    return LQFactors(geqrf(Ah, opts))


def _parse_trans(op, dtype) -> bool:
    """Map an op spec to conj_trans, rejecting plain-transpose on complex
    data (LAPACK unmqr rejects 'T' for complex rather than reinterpreting)."""
    if op in (Op.NoTrans,) or str(op).lower() == "n":
        return False
    plain_t = op is Op.Trans or str(op).lower() == "t"
    slate_error(not (plain_t and is_complex(dtype)),
                "unmqr: op='t' undefined for complex; use 'c'")
    return True


def _panel_ranges(m: int, n: int, nb: int):
    r = min(m, n)
    return [(k0, min(k0 + nb, r)) for k0 in range(0, r, nb)]


@annotate("slate.unmqr")
def unmqr(side, op, F: QRFactors, C, opts: Options | None = None) -> Matrix:
    """Multiply C by Q (op='n') or Q^H (op='c'/'t') from the given side
    (ref: src/unmqr.cc).  Q is the implicit factor from :func:`geqrf`."""
    sd = _side(side)
    conj_trans = _parse_trans(op, F.QR.dtype)
    if isinstance(F, CAQRFactors):
        return _unmqr_caqr(sd, conj_trans, F, C, opts)
    packed = F.QR.to_dense()
    mq, nq = packed.shape
    nb = F.QR.nb
    cd = C.to_dense()
    ranges = _panel_ranges(mq, nq, nb)
    # Q = B_0 B_1 ... B_{K-1}: Q^H C / C Q apply panels ascending,
    # Q C / C Q^H descending.
    ascending = (sd is Side.Left) == conj_trans
    order = ranges if ascending else ranges[::-1]
    for k0, k1 in order:
        w = k1 - k0
        pk = packed[k0:, k0:k1]
        Tk = F.T[k0 // nb][:w, :w]
        if sd is Side.Left:
            cd = cd.at[k0:, :].set(
                apply_q_left(pk, Tk, cd[k0:, :], conj_trans))
        else:
            cd = cd.at[:, k0:].set(
                apply_q_right(pk, Tk, cd[:, k0:], conj_trans))
    return _dense_to_like(C, cd)


def _unmqr_caqr(sd: Side, conj_trans: bool, F: CAQRFactors, C,
                opts: Options | None = None) -> Matrix:
    """Mesh apply of the CAQR implicit Q (ref: unmqr + ttmqr tree apply)."""
    from ..parallel.dist_qr import dist_unmqr_data
    from .blas3 import as_root_general
    st = F.QR.storage
    if sd is Side.Right:
        # C op(Q) = (op(Q)^H C^H)^H — route through the left apply
        d = jnp.conj(C.to_dense()).T
        Ct = Matrix(TileStorage.from_dense(d, st.nb, C.mb, C.grid))
        Xt = _unmqr_caqr(Side.Left, not conj_trans, F, Ct, opts)
        return _dense_to_like(C, jnp.conj(Xt.to_dense()).T)
    Cn = as_root_general(C, st.nb, None, grid=F.QR.grid)
    Kt = F.Tloc.shape[1]
    data = dist_unmqr_data(st.data, Cn.storage.data, F.Tloc, F.Vtree,
                           F.Ttree, Kt, st.Mt, st.m, F.QR.grid, conj_trans)
    cs = Cn.storage
    return Matrix(TileStorage(data, cs.m, cs.n, cs.mb, cs.nb, cs.grid))


@annotate("slate.unmlq")
def unmlq(side, op, F: LQFactors, C, opts: Options | None = None) -> Matrix:
    """Multiply C by the LQ factor Q = Qr^H (ref: src/unmlq.cc): flips op on
    the underlying QR reflectors."""
    conj_trans = _parse_trans(op, F.F.QR.dtype)
    return unmqr(side, "n" if conj_trans else "c", F.F, C, opts)


def qr_multiply(F: QRFactors, opts: Options | None = None):
    """Materialise the thin Q (first min(m,n) columns) by applying Q to I."""
    mq = F.QR.m
    r = min(mq, F.QR.n)
    eye = jnp.eye(mq, r, dtype=F.QR.dtype)
    E = Matrix(TileStorage.from_dense(eye, F.QR.mb, F.QR.nb, F.QR.grid))
    return unmqr(Side.Left, "n", F, E, opts)


def _gram(A: Matrix, opts: Options | None):
    """G = A^H A as a lower Hermitian matrix (shared by the CholQR paths).

    MethodCholQR picks the accumulation (ref: method.hh:114-160): HerkC
    (default) is the triangle-aware rank-k — half the flops; GemmC/GemmA
    compute the full square via the corresponding gemm comm pattern."""
    from ..core.matrix import HermitianMatrix
    from ..options import MethodCholQR, MethodGemm, Option, get_option
    meth = get_option(opts, Option.MethodCholQR)
    if meth in (MethodCholQR.GemmC, MethodCholQR.GemmA):
        o = dict(opts or {})
        o[Option.MethodGemm] = (MethodGemm.gemmA
                                if meth is MethodCholQR.GemmA
                                else MethodGemm.gemmC)
        G = gemm(1.0, A.conj_transpose(), A, 0.0, None, o)
        return HermitianMatrix._from_view(G, Uplo.Lower)
    return herk(1.0, A.conj_transpose(), 0.0,
                HermitianMatrix._from_view(
                    Matrix.zeros(A.n, A.n, A.nb, A.nb, A.grid, A.dtype),
                    Uplo.Lower), opts)


def _info_opts(opts: Options | None) -> dict:
    o = dict(opts or {})
    o[Option.ErrorPolicy] = ErrorPolicy.Info
    return o


def _gram_exc(name: str):
    """Typed failure for the CholQR family: the Gram matrix A^H A failed
    Cholesky, i.e. A is rank-deficient or cond(A)^2 overwhelmed the
    working precision (CholQR squares the conditioning)."""
    return lambda h: SlateNotPositiveDefiniteError(
        f"{name}: Gram matrix A^H A not positive definite — A is "
        f"rank-deficient or too ill-conditioned for CholQR "
        f"({h.describe()})", info=int(h.info))


@annotate("slate.cholqr")
def cholqr(A: Matrix, opts: Options | None = None):
    """Cholesky QR: G = A^H A, R = chol(G)^H, Q = A R^-1
    (ref: src/cholqr.cc).  Composes herk/potrf/trsm so the mesh path is the
    distributed one.  Returns (Q, R) with R upper triangular.

    Failure contract (docs/ROBUSTNESS.md): an eager call on a
    rank-deficient A raises :class:`SlateNotPositiveDefiniteError` (the
    Gram matrix fails Cholesky); under ``Option.ErrorPolicy = info`` the
    return is ``((Q, R), HealthInfo)``."""
    slate_error(A.m >= A.n, "cholqr: need m >= n")
    G = _gram(A, opts)
    L, fh = potrf(G, _info_opts(opts))       # G = L L^H
    R = L.conj_transpose()                   # upper
    Q = trsm(Side.Right, 1.0, R, A, opts)    # Q = A R^-1
    h = _health.merge(fh, _health.from_result(Q.storage.data))
    return _health.finalize("cholqr", (Q, R), h, opts, _gram_exc("cholqr"))


def _gels_cholqr_attempt(A: Matrix, B, opts: Options | None, *,
                         refine: int = 0, certify: bool = False):
    """One semi-normal-equations solve under ErrorPolicy.Info; health
    merges the Gram factor's record with the solution's finiteness.

    ``refine`` adds that many corrected-semi-normal-equations sweeps
    (Björck's CSNE: dx from A^H r through the same Gram factor), and
    ``certify`` merges an a-posteriori normal-equations certificate
    (robust/certify.certify_lstsq) — together these make the attempt the
    speculative gels fast path (robust/recovery.gels_with_recovery)."""
    L, fh = potrf(_gram(A, opts), _info_opts(opts))

    def sne(Rhs):
        Z = gemm(1.0, A.conj_transpose(), Rhs, 0.0, None, opts)  # A^H rhs
        Y = trsm(Side.Left, 1.0, L, Z, opts)
        return trsm(Side.Left, 1.0, L.conj_transpose(), Y, opts)

    X = sne(B)
    h = _health.merge(fh, _health.from_result(X.storage.data))
    if refine or certify:
        from ..robust import certify as _certify
        from ..types import Norm
        from . import auxiliary as _aux
        for _ in range(refine):
            R = gemm(-1.0, A, X, 1.0, B, opts)        # r = B - A X
            X = _aux.add(1.0, sne(R), 1.0, X)
        if certify:
            R = gemm(-1.0, A, X, 1.0, B, opts)
            Rn = gemm(1.0, A.conj_transpose(), R, 0.0, None, opts)
            anorm = _aux.norm(Norm.Fro, A)
            cert = _certify.certify_lstsq(
                anorm, X.to_dense(), B.to_dense(), Rn.to_dense(),
                tol=_certify.tolerance(A.dtype, max(A.m, A.n)))
            h = _health.merge(h, cert._replace(iters=jnp.asarray(
                refine, jnp.int32)))
    return X, h


@annotate("slate.gels_cholqr")
def gels_cholqr(A: Matrix, B, opts: Options | None = None) -> Matrix:
    """Least squares via the semi-normal equations R^H R x = A^H b with R
    from CholQR (ref: src/gels_cholqr.cc).  Mesh-distributed by
    construction.  Same failure contract as :func:`cholqr`; no fallback —
    use :func:`gels` for the method-escalating entry point."""
    slate_error(A.m >= A.n, "gels_cholqr: need m >= n")
    X, h = _gels_cholqr_attempt(A, B, opts)
    return _health.finalize("gels_cholqr", X, h, opts,
                            _gram_exc("gels_cholqr"))


@annotate("slate.gels_qr")
def gels_qr(A: Matrix, B, opts: Options | None = None) -> Matrix:
    """Least squares via Householder QR (ref: src/gels_qr.cc):
    min ||Ax - b||: x = R^-1 (Q^H b)[:n]."""
    m, n = A.m, A.n
    slate_error(m >= n, "gels_qr: need m >= n (use gels for m < n)")
    F = geqrf(A, opts)
    Y = unmqr(Side.Left, "c", F, B, opts)
    yd = Y.to_dense()[:n]
    rd = jnp.triu(F.QR.to_dense()[:n, :n])
    xd = lax.linalg.triangular_solve(rd, yd, left_side=True, lower=False)
    X = Matrix.zeros(n, B.n, A.nb, B.nb, A.grid, xd.dtype)
    return X.with_dense(xd)


def _gels_qr_attempt(A: Matrix, B, opts: Options | None):
    """Householder-QR fallback attempt for gels' bounded retry."""
    X = gels_qr(A, B, opts)
    return X, _health.from_result(X.storage.data)


@annotate("slate.gels")
def gels(A: Matrix, B, opts: Options | None = None) -> Matrix:
    """Linear least squares / minimum-norm solve (ref: src/gels.cc:141):

    m >= n: overdetermined min ||Ax - b||, QR or CholQR per MethodGels
    (auto: CholQR for tall-skinny, ref method.hh:236-275).
    m < n:  minimum-norm solution via LQ: x = Q^H L^-1 b.

    With Option.UseFallbackSolver an eager CholQR attempt whose Gram
    matrix fails Cholesky (rank-deficient / squared-conditioning) retries
    once via Householder QR — the bounded_retry policy shared with
    gesv/posv (robust/recovery.py, docs/ROBUSTNESS.md).  Under
    ``Option.Speculate = on`` the CholQR2 fast path runs FIRST for any
    m >= n shape, refined and certified a-posteriori, with the same QR
    escalation on a failed certificate (gels_with_recovery).
    """
    m, n = A.m, A.n
    if m >= n:
        from ..robust.recovery import gels_with_recovery
        return gels_with_recovery(A, B, opts)
    # minimum norm: A = L Q (L m x m lower), x = Q^H (L^-1 b)
    F = gelqf(A, opts)
    packed = F.F.QR.to_dense()               # QR of A^H: [n, m]
    ld = jnp.conj(jnp.triu(packed[:m, :m])).T   # L = R^H, lower m x m
    bd = B.to_dense()
    yd = lax.linalg.triangular_solve(ld, bd, left_side=True, lower=True)
    ypad = jnp.zeros((n, yd.shape[1]), yd.dtype).at[:m].set(yd)
    Yp = Matrix.zeros(n, yd.shape[1], A.nb, B.nb, A.grid, yd.dtype)
    Yp = Yp.with_dense(ypad)
    # x = Qlq^H y = Qr y  (Qlq = Qr^H)
    X = unmqr(Side.Left, "n", F.F, Yp, opts)
    # same boundary contract as the m >= n routes: Info returns (X, h)
    return _health.finalize("gels", X,
                            _health.from_result(X.storage.data), opts)
