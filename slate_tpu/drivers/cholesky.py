"""Cholesky solvers: potrf, potrs, posv (+ band pbtrf/pbsv elsewhere).

Analog of the reference's Cholesky driver chain (ref: src/potrf.cc:141-302
task-DAG driver, src/potrs.cc two trsm sweeps, src/posv.cc).

single target: statically-shaped blocked left-looking factorisation on the
dense array — block-column gemm update, diagonal potrf (XLA Cholesky),
panel gemm against the inverted diagonal block — unrolled under one jit,
full MXU shapes (the analog of the HostTask DAG with the whole problem
visible to the compiler).

mesh target: slate_tpu.parallel.dist_chol / dist_trsm shard_map pipelines
over the 2D block-cyclic grid.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.matrix import (BaseTrapezoidMatrix, HermitianMatrix, Matrix,
                           SymmetricMatrix, TriangularMatrix)
from ..core.storage import TileStorage
from ..exceptions import SlateNotPositiveDefiniteError, slate_error
from ..options import (Option, Options, Target, get_option, resolve_abft,
                       resolve_target)
from ..parallel.dist_chol import SUPERBLOCKS, dist_potrf, superblock
from ..robust import abft as _abft
from ..robust import faults
from ..robust import health as _health
from ..types import Diag, Op, Uplo
from .blas3 import as_root_general, trsm
from ..internal.potrf import potrf_panel_fused, potrf_panel_ok, potrf_tile
from ..internal.trsm import tri_inv_lower
from ..util.trace import annotate


def _potrf_dense_blocked(a, nb: int, abft: bool = False):
    """Blocked LEFT-looking Cholesky, lower, static shapes (unrolled).

    Left-looking does exactly n^3/3 multiply-adds — the right-looking
    full-square trailing update costs 2x that on TPU, where the
    symmetric half of A22 - L21 L21^H cannot be skipped (VERDICT r4
    weak #2).  Panel solves multiply by the explicitly inverted diagonal
    block (internal/trsm.py tri_inv_lower, MAGMA-style): one MXU gemm
    instead of a per-column substitution loop measured at 675 GFLOP/s.

    When the tuned plan selects it (internal/potrf.py potrf_panel_ok),
    the whole panel step — rank-k update, diagonal factor, TRSM — runs
    as ONE fused Pallas kernel that also emits the pre-factor panel, so
    every ABFT rung below verifies the same quantities either way.  A
    fault that slips into the fused gemm is caught exactly like an XLA
    gemm fault: sum_check repairs the pre-factor panel, and the tile /
    panel rungs then see (and repair) the stale factored element.

    ``abft`` verifies every step against Huang-Abraham checksums
    (robust/abft.py): the block-column gemm through additive checksums,
    the diagonal tile through its Cholesky residual, the panel through
    the checksums of its right-hand side.  Returns ``(a, AbftCounts)``.
    """
    n = a.shape[0]
    counts = _abft.zero_counts()
    for k0 in range(0, n, nb):
        k1 = min(k0 + nb, n)
        w = k1 - k0
        # slate-lint: disable=TRC001 -- capability probe: reads only static shape/dtype/plan, never tracer data
        fused = potrf_panel_ok(a.dtype, n - k0, w, nb)
        fac = None
        upd = a[k0:, k0:k1]
        if fused:
            left = (a[k0:, :k0] if k0
                    else jnp.zeros((n - k0, 0), a.dtype))
            lead = jnp.conj(a[k0:k1, :k0]).T
            upd, fac = potrf_panel_fused(a[k0:, k0:k1], left, lead)
        elif k0:
            left = a[k0:, :k0]
            lead = jnp.conj(a[k0:k1, :k0]).T
            upd = upd - left @ lead
        if k0:
            if abft:
                exp_r = (jnp.sum(a[k0:, k0:k1], axis=1)
                         - left @ jnp.sum(lead, axis=1))
                exp_c = (jnp.sum(a[k0:, k0:k1], axis=0)
                         - jnp.sum(left, axis=0) @ lead)
                upd, ev = _abft.sum_check(upd, exp_r, exp_c, n_ctx=n,
                                          nb=nb, row0=k0, col0=k0)
                counts = _abft.add_counts(counts, ev)
        lkk = faults.maybe_corrupt(
            "post_panel", fac[:w] if fused else potrf_tile(upd[:w]))
        if abft:
            lkk, det, cor = _abft.chol_tile_check(upd[:w], lkk, n_ctx=n)
            counts = _abft.add_counts(
                counts, _abft.count_event(det, cor, k0 // nb, k0 // nb))
        a = a.at[k0:k1, k0:k1].set(lkk)
        if k1 < n:
            if fused:
                panel = fac[w:]          # in-kernel TRSM (A21 U^-1)
            else:
                panel = upd[w:] @ jnp.conj(tri_inv_lower(lkk)).T
            if abft:
                # panel X solves X L^H = R; conjugate-transpose it into
                # the canonical left product L X^H = R^H and verify via
                # R's checksums
                xh, det, cor, _, pj_ = _abft.left_product_check(
                    lkk, jnp.conj(panel).T,
                    jnp.conj(jnp.sum(upd[w:], axis=0)),
                    jnp.conj(jnp.sum(upd[w:], axis=1)),
                    unit=False, n_ctx=n)
                panel = jnp.conj(xh).T
                counts = _abft.add_counts(
                    counts,
                    _abft.count_event(det, cor, (k1 + pj_) // nb,
                                      k0 // nb))
            a = a.at[k1:, k0:k1].set(panel)
    return a, counts


@annotate("slate.potrf")
def potrf(A, opts: Options | None = None) -> TriangularMatrix:
    """Factor A = L L^H (Lower) or A = U^H U (Upper); returns the triangular
    factor (ref: src/potrf.cc).

    Failure contract (Option.ErrorPolicy, see docs/ROBUSTNESS.md): eager
    calls raise :class:`SlateNotPositiveDefiniteError` when a leading minor
    is not positive definite (a NaN/zero L diagonal); under ``info`` the
    return is ``(L, HealthInfo)`` with the LAPACK-style 1-based index of
    the first bad diagonal."""
    slate_error(isinstance(A, (HermitianMatrix, SymmetricMatrix)),
                "potrf: need HermitianMatrix/SymmetricMatrix")
    uplo = A._uplo_logical()
    target = resolve_target(opts, A)
    nb = A.nb
    abft = resolve_abft(opts)  # the one Option.Abft read (driver boundary)

    if target is Target.mesh and A.grid.mesh is not None:
        # factor the LOWER representation; Upper comes back as L^H view.
        # dist_potrf reads ONLY the lower triangle (diag tiles are
        # Hermitian-completed in-kernel), so a lower-stored root view goes
        # in zero-copy — no whole-matrix densification on the mesh path.
        if (A.uplo is Uplo.Lower and A.op is Op.NoTrans
                and A.is_root_view() and A.storage.mb == nb):
            st_l = A.storage
        else:
            full = A.to_dense()
            st_l = TileStorage.from_dense(full, nb, nb, A.grid)
        data_in = faults.maybe_corrupt("input", st_l.data)
        # Option.Lookahead scales the unrolled-superblock count: more
        # lookahead = more statically visible k steps for XLA to pipeline
        # across (the analog of the reference's lookahead task depth,
        # potrf.cc:266-287), at proportional compile-time cost
        la = max(1, int(get_option(opts, Option.Lookahead)))
        out, minpiv, minidx, adet, acor, asite = dist_potrf(
            data_in, st_l.Nt, A.grid, n=st_l.n,
            sb=superblock(st_l.Nt, SUPERBLOCKS * la), abft=abft)
        st_out = TileStorage(out, st_l.m, st_l.n, nb, nb, A.grid)
        L = TriangularMatrix._from_view(Matrix(st_out), Uplo.Lower)
        # finiteness over the WRITTEN (lower) triangle only — the kernel
        # never touches strictly-upper tiles, which may hold stale input
        h = _chol_health(jnp.tril(st_out.canonical()), minpiv, minidx)
        h = _abft_fold(h, _abft.AbftCounts(adet, acor, asite))
        return _finalize_potrf(L, h, uplo, opts)

    full = faults.maybe_corrupt("input", A.to_dense())
    lfac, counts = _potrf_dense_blocked(full, nb, abft=abft)
    st_out = TileStorage.from_dense(lfac, nb, nb, A.grid)
    L = TriangularMatrix._from_view(Matrix(st_out), Uplo.Lower)
    d = jnp.abs(jnp.diagonal(lfac))
    d = jnp.where(jnp.isnan(d), jnp.zeros_like(d), d)
    minidx = jnp.argmin(d)
    h = _chol_health(jnp.tril(lfac), d[minidx], minidx)
    h = _abft_fold(h, counts)
    return _finalize_potrf(L, h, uplo, opts)


def _abft_fold(h, counts: "_abft.AbftCounts") -> "_health.HealthInfo":
    """Fold checksum-verification counters into the driver's health."""
    return h._replace(abft_detected=counts.detected,
                      abft_corrected=counts.corrected,
                      abft_site=counts.site)


def _chol_health(lower_arr, minpiv, minidx) -> "_health.HealthInfo":
    """HealthInfo for a Cholesky factor: diagonal record + finiteness of
    the written triangle.  Growth is left at 1.0 — unpivoted Cholesky of an
    HPD matrix cannot exhibit element growth, so it carries no signal."""
    h = _health.healthy(lower_arr.dtype)
    bad = (minpiv == 0) | ~jnp.isfinite(minpiv)
    return h._replace(
        nonfinite=~jnp.all(jnp.isfinite(
            jnp.abs(lower_arr) if jnp.iscomplexobj(lower_arr)
            else lower_arr)),
        info=jnp.where(bad, minidx.astype(jnp.int32) + 1, 0),
        min_pivot=minpiv.astype(h.min_pivot.dtype),
        min_pivot_index=minidx.astype(jnp.int32),
    )


def _finalize_potrf(L, h, uplo, opts):
    Lv = L.conj_transpose() if uplo is Uplo.Upper else L
    return _health.finalize(
        "potrf", Lv, h, opts,
        lambda hh: SlateNotPositiveDefiniteError(
            f"potrf: leading minor not positive definite "
            f"({hh.describe()})", info=int(hh.info)))


def _ooc_chol_health(lfac_host) -> "_health.HealthInfo":
    """Cholesky health from HOST reductions: the OOC factor must never be
    re-materialized on device just to check it (it may not fit)."""
    import numpy as np
    d = np.abs(np.diagonal(lfac_host))
    d = np.where(np.isnan(d), 0.0, d)
    minidx = int(np.argmin(d)) if d.size else 0
    minpiv = float(d[minidx]) if d.size else float("inf")
    h = _health.healthy(lfac_host.dtype)
    bad = (minpiv == 0.0) or not np.isfinite(minpiv)
    return h._replace(
        nonfinite=jnp.asarray(not bool(np.all(np.isfinite(lfac_host)))),
        info=jnp.asarray(minidx + 1 if bad else 0, jnp.int32),
        min_pivot=jnp.asarray(minpiv, h.min_pivot.dtype),
        min_pivot_index=jnp.asarray(minidx, jnp.int32),
    )


@annotate("slate.potrf_ooc")
def potrf_ooc(a, nb: int | None = None, opts: Options | None = None,
              checkpoint=None, resume: bool = False):
    """Out-of-core Cholesky of a HOST-resident SPD matrix (lower).

    ``a`` is a dense host numpy array that need not fit device memory:
    a :class:`~slate_tpu.core.storage.TileMap` streams block-column
    panels through HBM, with the next left panel's H2D prefetch
    overlapped against the current panel's update — the distributed
    kernels' hide-communication discipline applied to the host-device
    axis.  Only the lower triangle (and diagonal) of ``a`` is read.
    Returns the lower-triangular factor as a host numpy array;
    Option.ErrorPolicy resolves failures exactly like :func:`potrf`.

    Durability (docs/ROBUSTNESS.md "Durable jobs"): with a ``checkpoint``
    :class:`~slate_tpu.robust.checkpoint.CheckpointManager` the host tile
    map is snapshotted at panel-step boundaries per the manager cadence;
    ``resume=True`` verifies and continues from the latest snapshot —
    bit-identical to the uninterrupted run — refusing with a typed
    ``SlateCheckpointError`` on torn/stale/corrupt state.  The in-core
    ABFT rungs do not ride this loop; the checkpoint's row/column
    checksums guard the offloaded state instead.
    """
    import numpy as np
    from ..core.storage import TileMap
    from ..internal.potrf import ooc_chol_panel, ooc_chol_update
    from ..robust.checkpoint import ensure_fingerprint, ooc_fingerprint
    from ..tune import ooc_panel_width

    if resume:
        slate_error(checkpoint is not None,
                    "potrf_ooc: resume=True needs a checkpoint manager")
        ck = checkpoint.load(op="potrf_ooc")
        n = ck.matrix.shape[0]
        nb = int(ck.meta["nb"])
        fp = ooc_fingerprint("potrf_ooc", n, n, nb, ck.meta["dtype"])
        ensure_fingerprint(ck, fp)
        tm = TileMap(ck.matrix, nb, nb)
        k_start = int(ck.step)
    else:
        ad = np.asarray(a)
        slate_error(ad.ndim == 2 and ad.shape[0] == ad.shape[1],
                    "potrf_ooc: square 2D host matrix")
        n = ad.shape[0]
        nb = int(nb) if nb else ooc_panel_width(n, ad.dtype.name)
        fp = ooc_fingerprint("potrf_ooc", n, n, nb, ad.dtype.name)
        tm = TileMap(ad, nb, nb)
        k_start = 0

    steps = list(range(0, n, nb))
    for si in range(k_start, len(steps)):
        k0 = steps[si]
        k1 = min(k0 + nb, n)
        w = k1 - k0
        if checkpoint is not None and checkpoint.should_save(si):
            checkpoint.save("potrf_ooc", si, tm.host_array(), nb, nb, fp)
        prev = steps[:si]
        if prev:
            tm.prefetch(k0, n, prev[0], prev[0] + nb)
        acc = tm.fetch(k0, n, k0, k1)
        for idx, j0 in enumerate(prev):
            left = tm.fetch(k0, n, j0, j0 + nb)
            if idx + 1 < len(prev):
                tm.prefetch(k0, n, prev[idx + 1], prev[idx + 1] + nb)
            # A[k0:k1, j0:j1] is the leading w rows of the left panel
            acc = ooc_chol_update(acc, left, left[:w])
        tm.store(k0, n, k0, k1, ooc_chol_panel(acc))
    lfac = np.tril(tm.host_array())
    return _health.finalize(
        "potrf_ooc", lfac, _ooc_chol_health(lfac), opts,
        lambda hh: SlateNotPositiveDefiniteError(
            f"potrf_ooc: leading minor not positive definite "
            f"({hh.describe()})", info=int(hh.info)))


@annotate("slate.potrs")
def potrs(L: TriangularMatrix, B, opts: Options | None = None) -> Matrix:
    """Solve with the Cholesky factor: two triangular sweeps
    (ref: src/potrs.cc)."""
    slate_error(isinstance(L, BaseTrapezoidMatrix), "potrs: need factor")
    if L._uplo_logical() is Uplo.Lower:
        Y = trsm("l", 1.0, L, B, opts)
        X = trsm("l", 1.0, L.conj_transpose(), Y, opts)
    else:
        Y = trsm("l", 1.0, L.conj_transpose(), B, opts)
        X = trsm("l", 1.0, L, Y, opts)
    if faults.active("solve") is not None:
        sx = X.storage
        X = Matrix(TileStorage(faults.maybe_corrupt("solve", sx.data),
                               sx.m, sx.n, sx.mb, sx.nb, sx.grid))
    return X


@annotate("slate.posv")
def posv(A, B, opts: Options | None = None):
    """Solve A X = B for Hermitian positive definite A
    (ref: src/posv.cc).  Returns (L, X); with Option.UseFallbackSolver an
    eager call on a non-HPD matrix falls back to hesv, then gesv — see
    robust/recovery.py and docs/ROBUSTNESS.md.

    Option.HoldLocalWorkspace fuses factor+solve into ONE jitted program
    so the factor's workspace stays live on device between the phases —
    the XLA analog of the reference's held workspace tiles
    (ref: potrf.cc:169 passing HoldLocalWorkspace into potrs)."""
    if get_option(opts, Option.HoldLocalWorkspace):
        key = (tuple(sorted(opts.items(), key=lambda kv: kv[0].value))
               if opts else ())
        return _fused_posv(key)(A, B)
    return _posv_body(A, B, opts)


def _posv_body(A, B, opts):
    from ..robust.recovery import posv_with_recovery
    return posv_with_recovery(A, B, opts)


@functools.lru_cache(maxsize=32)
def _fused_posv(opts_items):
    """One cached jitted factor+solve program per distinct opts — a fresh
    jit per call would retrace and recompile every invocation."""
    opts = dict(opts_items) if opts_items else None
    return jax.jit(lambda A, B: _posv_body(A, B, opts))


@annotate("slate.potri")
def potri(L: TriangularMatrix, opts: Options | None = None):
    """Inverse from Cholesky factor: A^{-1} = L^-H L^-1
    (ref: src/potri.cc = trtri + trtrm).  Returns a HermitianMatrix;
    under ``ErrorPolicy.Info`` returns ``(Ainv, HealthInfo)`` with the
    two stage healths merged."""
    from ..options import ErrorPolicy
    from .inverse import trtri, trtrm
    if _health.error_policy(opts) is ErrorPolicy.Info:
        Linv, h1 = trtri(L, opts)
        C, h2 = trtrm(Linv, opts)
        return C, _health.merge(h1, h2)
    Linv = trtri(L, opts)
    return trtrm(Linv, opts)
