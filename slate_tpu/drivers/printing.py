"""Distributed matrix printing.

Analog of the reference's print driver (ref: src/print.cc:1-1281 —
``slate::print`` gathers tiles to rank 0 and renders any matrix type with
per-call verbosity/width/precision options from Option::PrintVerbose /
PrintEdgeItems / PrintWidth / PrintPrecision, enums.hh:80-90).

Here the gather is ``to_dense()`` (one XLA gather off the mesh — the
analog of the tile send loop) and the renderer is pure host code.
Verbosity levels follow the reference:

    0  print nothing
    1  metadata only (type, dims, tiling, grid)
    2  edgeitems view: corners + ellipses (numpy printoptions style)
    3  full matrix when it fits (<= 2*edgeitems per dim), else edgeitems
    4  full matrix always
"""

from __future__ import annotations

import numpy as np

from ..core.matrix import (BaseBandMatrix, BaseMatrix, BaseTrapezoidMatrix,
                           HermitianBandMatrix, HermitianMatrix,
                           SymmetricMatrix, TriangularMatrix)
from ..options import Option, Options, get_option


def _meta_line(name: str, A: BaseMatrix) -> str:
    kind = type(A).__name__
    extra = ""
    if isinstance(A, BaseBandMatrix):
        if isinstance(A, HermitianBandMatrix):
            extra = f", kd={A.kd}"
        else:
            extra = f", kl={A.kl}, ku={A.ku}"
    if isinstance(A, BaseTrapezoidMatrix):
        extra += f", uplo={A.uplo.name}"
    g = A.grid
    return (f"% {name}: {kind} {A.m}x{A.n}, tiles {A.mb}x{A.nb}, "
            f"grid {g.p}x{g.q}{extra}, dtype {np.dtype(A.dtype).name}")


def format_matrix(name: str, A: BaseMatrix,
                  opts: Options | None = None) -> str:
    """Render a matrix to a string (print.cc's formatting core)."""
    verbose = get_option(opts, Option.PrintVerbose)
    if verbose == 0:
        return ""
    lines = [_meta_line(name, A)]
    if verbose == 1:
        return "\n".join(lines)

    edge = get_option(opts, Option.PrintEdgeItems)
    width = get_option(opts, Option.PrintWidth)
    prec = get_option(opts, Option.PrintPrecision)
    d = np.asarray(A.to_dense())

    full = (verbose == 4 or
            (verbose == 3 and max(A.m, A.n) <= 2 * edge))
    threshold = d.size + 1 if full else 2 * edge
    with np.printoptions(precision=prec, linewidth=max(79, (width + 2) * 8),
                         threshold=threshold, edgeitems=edge,
                         suppress=False):
        body = np.array2string(d)
    lines.append(f"{name} = [")
    lines.append(body)
    lines.append("];")
    return "\n".join(lines)


def print_matrix(name: str, A: BaseMatrix,
                 opts: Options | None = None) -> None:
    """Print a matrix of any type (ref: slate::print overload set,
    src/print.cc).  Controlled by the Print* options."""
    s = format_matrix(name, A, opts)
    if s:
        print(s)
