"""Matrix inverses: trtri, trtrm, getri, potri pieces.

Analog of the reference's inverse drivers (ref: src/trtri.cc blocked
triangular inverse, src/trtrm.cc triangular * its-transpose product used by
potri, src/getri.cc / src/getriOOP.cc LU-based inverse).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.matrix import (BaseTrapezoidMatrix, HermitianMatrix, Matrix,
                           TriangularMatrix)
from ..core.storage import TileStorage
from ..exceptions import SlateSingularError, slate_error
from ..options import Options
from ..robust import health as _health
from ..types import Diag, Uplo
from ..util.trace import annotate


def _singular_exc(name):
    def make(h: _health.HealthInfo):
        return SlateSingularError(f"{name}: {h.describe()}",
                                  info=int(h.info))
    return make


@annotate("slate.trtri")
def trtri(A: TriangularMatrix, opts: Options | None = None):
    """Triangular inverse (ref: src/trtri.cc).  Solves op(T) X = I
    through the trsm driver, so the execution target follows trsm's:
    the dist_trsm substitution pipeline on a mesh (the reference's
    distributed trtri, src/trtri.cc:1-160), blocked substitution with
    batched diagonal inverses single-target.

    Health: a zero diagonal entry of A makes op(A) exactly singular —
    reported LAPACK-style as ``info = k`` (1-based index of the first
    zero pivot) and resolved against ``Option.ErrorPolicy`` (raise /
    NaN-fill / ``(X, HealthInfo)``)."""
    from .blas3 import trsm
    slate_error(isinstance(A, BaseTrapezoidMatrix), "trtri: need triangular")
    n = A.m
    nb = A.storage.nb
    eye = jnp.eye(n, dtype=A.dtype)
    I = Matrix(TileStorage.from_dense(eye, nb, nb, A.grid))
    X = trsm("l", 1.0, A, I, opts)
    # result has the effective (logical) triangle of op(A)
    eff_lower = A._uplo_logical() is Uplo.Lower
    Xt = TriangularMatrix._from_view(
        X, Uplo.Lower if eff_lower else Uplo.Upper, A.diag)
    if A.diag is Diag.Unit:
        # unit diagonal is implicit 1s — never singular, skip the pivots
        h = _health.from_result(X.storage.data)
    else:
        h = _health.merge(_health.from_pivots(jnp.diagonal(A.to_dense())),
                          _health.from_result(X.storage.data))
    return _health.finalize("trtri", Xt, h, opts, _singular_exc("trtri"))


@annotate("slate.trtrm")
def trtrm(L: TriangularMatrix, opts: Options | None = None):
    """Hermitian product of a triangular factor with its adjoint
    (ref: src/trtrm.cc).  For Linv lower: returns Linv^H Linv, i.e. the
    second half of potri — computed through the herk driver, so the
    mesh path is the triangle-aware distributed rank-k kernel."""
    from .blas3 import herk
    n = L.m
    nb = L.storage.nb
    C0 = HermitianMatrix._from_view(
        Matrix.zeros(n, n, nb, nb, L.grid, L.dtype), Uplo.Lower)
    if L._uplo_logical() is Uplo.Lower:
        C = herk(1.0, L.conj_transpose().general(), 0.0, C0, opts)
    else:
        C = herk(1.0, L.general(), 0.0, C0, opts)
    h = _health.from_result(C.storage.data)
    return _health.finalize("trtrm", C, h, opts, _singular_exc("trtrm"))
