"""Matrix inverses: trtri, trtrm, getri, potri pieces.

Analog of the reference's inverse drivers (ref: src/trtri.cc blocked
triangular inverse, src/trtrm.cc triangular * its-transpose product used by
potri, src/getri.cc / src/getriOOP.cc LU-based inverse).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.matrix import (BaseTrapezoidMatrix, HermitianMatrix, Matrix,
                           TriangularMatrix)
from ..core.storage import TileStorage
from ..exceptions import slate_error
from ..options import Options
from ..types import Uplo


def trtri(A: TriangularMatrix, opts: Options | None = None):
    """Triangular inverse (ref: src/trtri.cc).  Solves op(T) X = I
    through the trsm driver, so the execution target follows trsm's:
    the dist_trsm substitution pipeline on a mesh (the reference's
    distributed trtri, src/trtri.cc:1-160), blocked substitution with
    batched diagonal inverses single-target."""
    from .blas3 import trsm
    slate_error(isinstance(A, BaseTrapezoidMatrix), "trtri: need triangular")
    n = A.m
    nb = A.storage.nb
    eye = jnp.eye(n, dtype=A.dtype)
    I = Matrix(TileStorage.from_dense(eye, nb, nb, A.grid))
    X = trsm("l", 1.0, A, I, opts)
    # result has the effective (logical) triangle of op(A)
    eff_lower = A._uplo_logical() is Uplo.Lower
    return TriangularMatrix._from_view(
        X, Uplo.Lower if eff_lower else Uplo.Upper, A.diag)


def trtrm(L: TriangularMatrix, opts: Options | None = None):
    """Hermitian product of a triangular factor with its adjoint
    (ref: src/trtrm.cc).  For Linv lower: returns Linv^H Linv, i.e. the
    second half of potri — computed through the herk driver, so the
    mesh path is the triangle-aware distributed rank-k kernel."""
    from .blas3 import herk
    n = L.m
    nb = L.storage.nb
    C0 = HermitianMatrix._from_view(
        Matrix.zeros(n, n, nb, nb, L.grid, L.dtype), Uplo.Lower)
    if L._uplo_logical() is Uplo.Lower:
        return herk(1.0, L.conj_transpose().general(), 0.0, C0, opts)
    return herk(1.0, L.general(), 0.0, C0, opts)
