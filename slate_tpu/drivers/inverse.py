"""Matrix inverses: trtri, trtrm, getri, potri pieces.

Analog of the reference's inverse drivers (ref: src/trtri.cc blocked
triangular inverse, src/trtrm.cc triangular * its-transpose product used by
potri, src/getri.cc / src/getriOOP.cc LU-based inverse).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.matrix import (BaseTrapezoidMatrix, HermitianMatrix, Matrix,
                           TriangularMatrix)
from ..core.storage import TileStorage
from ..exceptions import slate_error
from ..options import Options
from ..types import Diag, Op, Uplo


def trtri(A: TriangularMatrix, opts: Options | None = None):
    """Triangular inverse (ref: src/trtri.cc).  Solves op(T) X = I with one
    statically-shaped triangular_solve — the blocked recursion the reference
    hand-codes is what XLA's lowering performs internally."""
    slate_error(isinstance(A, BaseTrapezoidMatrix), "trtri: need triangular")
    n = A.m
    ad = A._dense_store()
    lower = A.uplo is Uplo.Lower
    eye = jnp.eye(n, dtype=A.dtype)
    inv = lax.linalg.triangular_solve(
        ad, eye, left_side=True, lower=lower,
        transpose_a=(A.op is not Op.NoTrans),
        conjugate_a=(A.op is Op.ConjTrans),
        unit_diagonal=A.diag is Diag.Unit)
    # result has the effective (logical) triangle of op(A)
    eff_lower = lower if A.op is Op.NoTrans else not lower
    st = TileStorage.from_dense(inv, A.storage.nb, A.storage.nb, A.grid)
    return TriangularMatrix._from_view(
        Matrix(st), Uplo.Lower if eff_lower else Uplo.Upper, A.diag)


def trtrm(L: TriangularMatrix, opts: Options | None = None):
    """Hermitian product of a triangular factor with its adjoint
    (ref: src/trtrm.cc).  For Linv lower: returns Linv^H Linv, i.e. the
    second half of potri."""
    ld = L.to_dense()
    if L._uplo_logical() is Uplo.Lower:
        full = jnp.conj(ld).T @ ld
    else:
        full = ld @ jnp.conj(ld).T
    st = TileStorage.from_dense(full, L.storage.nb, L.storage.nb, L.grid)
    return HermitianMatrix._from_view(Matrix(st), Uplo.Lower)
