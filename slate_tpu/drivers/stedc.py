"""stedc: divide & conquer symmetric tridiagonal eigensolver.

Analog of the reference's stedc family (ref: src/stedc.cc:46-96
orchestration; stedc_solve recursive splits; stedc_merge.cc:232 rank-one
merge; stedc_deflate.cc:595 z/close-d deflation; stedc_secular.cc:271
secular-equation roots; stedc_sort.cc final ordering).

TPU-first shape: D&C is the one tridiagonal eigensolver whose work is
matmul-shaped — each merge's eigenvector update is a GEMM (Q <- Q0 @ U),
which is why the reference (and LAPACK stedc) prefers it for vectors.

- Recursion: static Python halving to <= LEAF-sized base problems solved
  by the vendor eigh.  All shapes static; everything jits.
- Rank-one merge diag(D) + rho z z^T: deflation is MASKED, not compacted
  by dynamic sizes — z-deflated entries keep z_i = 0 (their terms vanish
  from every secular sum) and near-equal d's are rotated by a lax.scan
  Givens chain (ref: stedc_deflate.cc), so the whole merge is one static
  program.
- Secular roots: bisection on mu = lambda - d_i in each active interval —
  64 fixed iterations, vectorized over ALL roots at once (an [n, n]
  masked reduction per iteration), unconditionally convergent (ref:
  stedc_secular.cc uses the laed4 iteration; bisection trades a few
  iterations for branch-free robustness).
- Orthogonality: Gu-Eisenstat's trick — recompute zhat from the COMPUTED
  roots (log-space products over the active set), then eigenvectors
  u_i = zhat_j / (d_j - lambda_i), normalized.  This is what makes the
  masked/vectorized formulation stable without iterative refinement.

On a mesh, every merge's eigenvector gemm is ROW-DISTRIBUTED (Z
block-rows per device, the reference's stedc_merge rank layout — see
_merge_gemm); deflation and the secular solves stay replicated, being
O(n^2) against the merges' O(n^3).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

import numpy as np

from ..exceptions import SlateNotConvergedError
from ..options import Options
from ..robust import certify as _certify
from ..robust import faults as _faults
from ..robust import health as _health
from ..types import eps as _eps
from ..util.trace import annotate, span

LEAF = 32


def _limits(dt):
    """(log_range, tiny, log_max) calibrated to the dtype: the log-space
    bisection and log-product guards must stay inside the dtype exp
    range (f32 overflows exp beyond ~88; 1e-300 is zero in f32)."""
    fi = np.finfo(np.dtype(dt))
    log_max = float(np.log(fi.max)) * 0.9
    return log_max, float(fi.tiny), log_max


def _secular_roots(cd, cz2, rho, na):
    """Roots of 1 + rho * sum_j cz2_j / (cd_j - lambda) in each active
    interval, each anchored at its NEAREST pole for relative accuracy
    (the laed4 discipline — lambda - d computed by subtraction of near
    poles loses the digits the eigenvector formula needs).

    Returns (delta, use_up): lambda_i = cd_{i + use_up_i} + delta_i, with
    delta >= 0 for lower-anchored and delta <= 0 for upper-anchored roots.
    rho > 0, cd ascending over the active prefix, cz2 zero elsewhere."""
    n = cd.shape[0]
    i_all = jnp.arange(n)
    cd_next = jnp.concatenate([cd[1:], cd[-1:]])
    last = i_all == na - 1
    ub = jnp.where(last, cd + rho, cd_next)
    gap = jnp.maximum(ub - cd, 0.0)

    dij_lo = cd[None, :] - cd[:, None]           # cd_j - cd_i
    dij_up = cd[None, :] - ub[:, None]           # cd_j - anchor_up_i

    def f_at(dij, off):
        """secular f at lambda_i = anchor_i + off_i (f increasing in off)."""
        den = dij - off[:, None]                 # cd_j - lambda_i
        safe = jnp.where(den == 0, jnp.ones_like(den), den)
        terms = jnp.where(den == 0, jnp.zeros_like(safe),
                          cz2[None, :] / safe)
        return 1.0 + rho * jnp.sum(terms, axis=1)

    lrange, tiny, _ = _limits(cd.dtype)
    safe_gap = jnp.maximum(gap, tiny)

    def bisect(dij, sgn, flip):
        """LOG-space bisection: off = sgn * gap * e^t, t in [-700, 0].

        Roots sit anywhere from O(gap) down to O(z_i^2 * gap) — leaf
        eigenvector edge rows decay exponentially, so microscopic z's (and
        hence microscopic root offsets) are the common case in the
        recursion.  Linear bisection bottoms out at gap * 2^-64; bisecting
        the EXPONENT delivers full relative accuracy at every scale."""
        lo = jnp.full_like(gap, -lrange)
        hi = jnp.zeros_like(gap)

        def bis(_, lohi):
            lo, hi = lohi
            mid = 0.5 * (lo + hi)
            fm = f_at(dij, sgn * safe_gap * jnp.exp(mid))
            go_hi = (fm < 0) != flip             # root at larger t?
            return jnp.where(go_hi, mid, lo), jnp.where(go_hi, hi, mid)

        lo, hi = lax.fori_loop(0, 80, bis, (lo, hi))
        return sgn * safe_gap * jnp.exp(0.5 * (lo + hi))

    # lower-anchored: f increasing in off > 0; upper-anchored: off < 0 and
    # f DECREASES as t grows (off -> -gap), hence the flipped branch
    mu = bisect(dij_lo, jnp.ones_like(gap), False)
    nu = bisect(dij_up, -jnp.ones_like(gap), True)
    # anchor each root at its nearest pole; the last root has no upper
    # pole (its upper end d_last + rho is not a singularity) — keep lower
    use_up = (mu > 0.5 * gap) & ~last & (i_all < na)
    delta = jnp.where(use_up, nu, mu)
    return delta, use_up


def _zhat(num, cd, cz, rho, na):
    """Gu-Eisenstat: |zhat_j|^2 = prod_i (lambda_i - cd_j) /
    (rho * prod_{i!=j} (cd_i - cd_j)) over the active set, in log space.

    ``num[i, j] = lambda_i - cd_j`` is computed by the caller with
    per-root pole ANCHORING so the near-pole factors carry full relative
    accuracy; the denominator's pole differences are exact f64
    subtractions of input data (Sterbenz) and need no anchoring."""
    n = cz.shape[0]
    i_all = jnp.arange(n)
    act_i = (i_all < na)[:, None]
    offdiag = (i_all[:, None] != i_all[None, :])
    dij = cd[:, None] - cd[None, :]              # cd_i - cd_j

    _, tiny, log_max = _limits(cz.dtype)

    def logprod(terms, mask):
        t = jnp.where(mask, terms, jnp.ones_like(terms))
        return jnp.sum(jnp.log(jnp.abs(t) + tiny), axis=0)

    lnum = logprod(num, act_i)
    lden = logprod(dij, act_i & offdiag)
    ratio = jnp.exp(jnp.clip(lnum - lden - jnp.log(rho),
                             -log_max, log_max))
    # interlacing makes the ratio positive on active j; clamp for safety
    zh = jnp.sqrt(jnp.maximum(ratio, 0.0))
    return jnp.where(i_all < na, jnp.where(cz < 0, -zh, zh),
                     jnp.zeros_like(zh))


def _merge_gemm(Q0, ut, grid):
    """THE merge gemm Qm = Q0 @ U, row-distributed over the mesh.

    The reference distributes stedc's merge by Z block-rows per rank
    (ref: src/stedc_merge.cc:1-232; csteqr2.f's NR row slices) — the
    rank-one update U is replicated (O(n^2) secular data) while each
    rank updates only its rows of Q.  Here that is one sharding
    constraint: Q0's rows sharded over ALL mesh devices, U replicated,
    so XLA partitions the gemm with zero collectives (each device
    computes its row slice locally)."""
    if grid is not None and grid.mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..core.grid import AXIS_P, AXIS_Q
        if Q0.shape[0] % (grid.p * grid.q) == 0:
            Q0 = jax.lax.with_sharding_constraint(
                Q0, NamedSharding(grid.mesh, P((AXIS_P, AXIS_Q), None)))
    return Q0 @ ut


def _merge(d1, Q1, d2, Q2, rho, grid=None):
    """Eigendecomposition of [[T1, rho e e^T], [rho e e^T, T2]] given the
    halves' decompositions (ref: stedc_merge.cc).

    Returns ``(lam, Qm, ok)`` — ``ok`` is a traced scalar bool ANDing the
    deflation-mask NaN guard (a NaN z survives the ``<= tol`` deflation
    comparisons silently, so finiteness is checked BEFORE the masks) with
    the secular-root sanity check (finite and inside the merged spectrum's
    span; LAPACK's laed4 reports the same condition through ``info``)."""
    dt = d1.dtype
    n1 = d1.shape[0]
    d = jnp.concatenate([d1, d2])
    n = d.shape[0]
    z = jnp.concatenate([Q1[-1, :], Q2[0, :]])
    # deflation-mask NaN guard: NaN compares False against tol, so a
    # poisoned z/d would silently stay "active" — flag it here instead
    defl_ok = jnp.all(jnp.isfinite(d)) & jnp.all(jnp.isfinite(z))
    # mirror to rho > 0: eig(D + rho z z^T) = -eig(-D + (-rho) z z^T)
    sgn = jnp.where(rho >= 0, jnp.ones((), dt), -jnp.ones((), dt))
    dm = sgn * d
    rho_m = sgn * rho
    # normalize z (||z||^2 = 2 from two unit rows, but compute it)
    znorm2 = jnp.sum(z * z)
    rho_eff = rho_m * znorm2
    zn = z / jnp.sqrt(jnp.maximum(znorm2,
                                  jnp.asarray(_limits(dt)[1], dt)))

    # sort ascending
    order = jnp.argsort(dm)
    ds = dm[order]
    zs = zn[order]

    amax = jnp.maximum(jnp.max(jnp.abs(ds)), jnp.abs(rho_eff))
    tol = 8.0 * jnp.asarray(_eps(dt), dt) * amax   # relative: no abs floor

    # -- z deflation (ref: stedc_deflate z test) --
    zdef = jnp.abs(rho_eff * zs) <= tol
    zs = jnp.where(zdef, jnp.zeros_like(zs), zs)

    # compact: actives first (stable argsort keeps d ascending per group)
    # so the close-d Givens chain below sees every active pair ADJACENT
    act1 = zs != 0
    pi1 = jnp.argsort(jnp.where(act1, 0, 1), stable=True)
    cd = ds[pi1]
    cz = zs[pi1]

    # -- close-d deflation: Givens chain over adjacent active pairs --
    # (ref: stedc_deflate.cc rotations; d perturbation <= tol accepted)
    def defl(carry, i):
        zv, cs = carry
        zp, zi = zv[i - 1], zv[i]
        close = (cd[i] - cd[i - 1]) <= tol
        do = close & (zp != 0) & (zi != 0)
        r = jnp.sqrt(zp * zp + zi * zi)
        rs = jnp.where(r == 0, jnp.ones_like(r), r)
        c, s = zi / rs, zp / rs                  # G^T [zp, zi] = [0, r]
        zv = zv.at[i - 1].set(jnp.where(do, 0.0, zp))
        zv = zv.at[i].set(jnp.where(do, r, zi))
        cs = cs.at[i].set(jnp.where(do, jnp.stack([c, s]),
                                    jnp.stack([jnp.ones((), dt),
                                               jnp.zeros((), dt)])))
        return (zv, cs), None

    cs0 = jnp.tile(jnp.asarray([1.0, 0.0], dt), (n, 1))
    (cz, cs), _ = lax.scan(defl, (cz, cs0), jnp.arange(1, n))

    # compact again: the chain zeroed some z's
    act = cz != 0
    pi2 = jnp.argsort(jnp.where(act, 0, 1), stable=True)
    cd = cd[pi2]
    cz = cz[pi2]
    na = jnp.sum(act.astype(jnp.int32))

    delta, use_up = _secular_roots(cd, cz * cz, rho_eff, na)
    delta = _faults.maybe_corrupt("post_secular", delta)
    i_all = jnp.arange(n)
    # secular sanity: every active root offset must be finite and inside
    # the merged spectrum's span (bisection guarantees |delta| <= gap;
    # anything outside means the solve — or the data under it — is bad)
    span = (jnp.max(cd) - jnp.min(cd)) + jnp.abs(rho_eff)
    sec_ok = jnp.all(jnp.where(
        i_all < na,
        jnp.isfinite(delta) & (jnp.abs(delta) <= span + tol), True))
    # anchored lambda_i - cd_j: (cd_anchor_i - cd_j) + delta_i, where
    # anchor_i = i (+1 for upper-anchored roots) — every factor carries
    # full relative accuracy near both poles
    anchor = jnp.clip(i_all + use_up.astype(i_all.dtype), 0, n - 1)
    anchor_d = cd[anchor]
    num = (anchor_d[:, None] - cd[None, :]) + delta[:, None]
    zh = _zhat(num, cd, cz, rho_eff, na)

    # eigenvectors of the compacted rank-one problem
    den = -num                                       # cd_j - lambda_i
    safe = jnp.where(den == 0, jnp.ones_like(den), den)
    u = zh[None, :] / safe                           # [i, j]
    u = jnp.where((i_all < na)[None, :], u, jnp.zeros_like(u))
    nrm = jnp.sqrt(jnp.sum(u * u, axis=1, keepdims=True))
    nrm = jnp.where(nrm == 0, jnp.ones_like(nrm), nrm)
    u = u / nrm
    # deflated slots: unit vectors
    eye = (i_all[:, None] == i_all[None, :]).astype(dt)
    u = jnp.where((i_all < na)[:, None], u, eye)     # rows i = eigvec i
    lam_c = jnp.where(i_all < na, anchor_d + delta, cd)

    # assemble Q0 with the deflation Givens chain + permutations applied
    Q0 = jnp.zeros((n, n), dt)
    Q0 = Q0.at[:n1, :n1].set(Q1)
    Q0 = Q0.at[n1:, n1:].set(Q2)
    Q0 = Q0[:, order][:, pi1]

    def rot(Q, i):
        c, s = cs[i, 0], cs[i, 1]
        qp, qi = Q[:, i - 1], Q[:, i]
        Q = Q.at[:, i - 1].set(c * qp - s * qi)
        Q = Q.at[:, i].set(s * qp + c * qi)
        return Q, None

    Q0, _ = lax.scan(rot, Q0, jnp.arange(1, n))
    Q0 = Q0[:, pi2]

    # THE gemm: eigenvectors of the merged problem (row-distributed on a
    # mesh — see _merge_gemm)
    Qm = _merge_gemm(Q0, u.T, grid)                 # columns = eigvecs

    # undo the mirror, final ascending sort
    lam = sgn * lam_c
    fin = jnp.argsort(lam)
    return lam[fin], Qm[:, fin], defl_ok & sec_ok


def _stedc_rec(d, e, grid=None):
    n = d.shape[0]
    if n <= LEAF:
        T = jnp.diag(d)
        if n > 1:
            T = T + jnp.diag(e, 1) + jnp.diag(e, -1)
        w, Q = jnp.linalg.eigh(T)
        return w, Q, jnp.asarray(True)
    m = n // 2
    rho = e[m - 1]
    d1 = d[:m].at[m - 1].add(-rho)
    d2 = d[m:].at[0].add(-rho)
    w1, Q1, ok1 = _stedc_rec(d1, e[: m - 1], grid)
    w2, Q2, ok2 = _stedc_rec(d2, e[m:], grid)
    lam, Qm, okm = _merge(w1, Q1, w2, Q2, rho, grid)
    return lam, Qm, ok1 & ok2 & okm


def stedc_info(d, e, grid=None, certify=True):
    """stedc compute body: ``((w, Z), HealthInfo)``, no policy resolution.

    The health merges (a) the per-merge traced flags — secular-bisection
    sanity and the deflation-mask NaN guard — ANDed across the recursion
    into ``converged``, and (b) the a-posteriori eigen-certificate of the
    final (w, Z) against the tridiagonal itself (``certify.certify_eig``;
    assembling T densely is O(n^2), cheaper than one merge gemm).
    ``certify=False`` skips (b) — for callers like heev's DC route that
    certify their own final result against the original matrix, where a
    tridiagonal-level certificate would be redundant work."""
    d = jnp.asarray(d)
    e = jnp.asarray(e)
    if d.shape[0] == 1:
        w, Z = d, jnp.ones((1, 1), d.dtype)
        return (w, Z), _health.from_result(w)
    # pin true-precision matmuls: the merge gemm Qm = Q0 @ U accumulates
    # across O(log n) levels, and TPU's default bf16-pass matmul costs
    # ~3 digits of orthogonality per level (measured ~2e-2 vs ~1e-4 at
    # n=64 f32) — same discipline as hetrf's recurrence gemms
    with jax.default_matmul_precision("highest"):
        with span("slate.stedc/recurse"):
            w, Z, ok = _stedc_rec(d, e, grid)
        flags = _health.healthy(d.dtype)._replace(converged=ok)
        if not certify:
            return (w, Z), _health.merge(flags, _health.from_result(w))
        with span("slate.stedc/certify"):
            T = jnp.diag(d) + jnp.diag(e, 1) + jnp.diag(e, -1)
            cert = _certify.certify_eig(T, w, Z)
    return (w, Z), _health.merge(cert, flags, _health.from_result(w))


@annotate("slate.stedc")
def stedc(d, e, grid=None, opts: Options | None = None):
    """Eigendecomposition of the symmetric tridiagonal (d, e) by divide &
    conquer (ref: src/stedc.cc).  Returns (w, Z) ascending; under
    ``ErrorPolicy.Info``, ``(w, Z, HealthInfo)`` — the health carries the
    secular/deflation traced flags in ``converged`` plus the residual and
    orthogonality certificate (docs/ROBUSTNESS.md).

    ``grid``: a slate Grid whose mesh (if any) row-distributes every
    merge's eigenvector gemm (the reference's stedc_merge rank layout);
    deflation and the secular solves stay replicated — they are O(n^2)
    against the merges' O(n^3).

    Use float64 (CPU backend) for LAPACK-grade orthogonality; the f32
    path (TPU) uses dtype-calibrated exp/log guards and delivers
    f32-grade (~1e-6 * ||T||) residuals."""
    (w, Z), h = stedc_info(d, e, grid)
    return _health.finalize_flat(
        "stedc", (w, Z), h, opts,
        lambda hh: SlateNotConvergedError(
            f"stedc: secular solve / certification failed "
            f"({hh.describe()})", iters=int(hh.iters)))
