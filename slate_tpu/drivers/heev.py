"""Two-stage Hermitian eigensolver: he2hb -> hb2st -> tridiag eig -> back.

Analog of the reference's heev chain (ref: src/heev.cc:56-177 orchestration;
src/he2hb.cc:25 stage 1 full->band via panel QR + two-sided her2k-form
updates; src/hb2st.cc:41-314 stage 2 band->tridiag multithreaded bulge
chasing; src/stedc.cc:46-96 / src/steqr2.cc tridiagonal kernels;
src/unmtr_he2hb.cc, src/unmtr_hb2st.cc back-transforms).

TPU-first shape:

- he2hb: blocked Householder band reduction where ALL the O(n^3) work is
  larfb/her2k-form MXU gemms (the Bischof-Lang two-stage design the
  reference uses for exactly this reason, SURVEY §5 "hard-dimension
  scaling"); panels factored by the fori_loop Householder kernel.
- hb2st: the bulge chase is ONE lax.scan over (sweep, chase-step) pairs with
  static kd-sized windows — the sequential dependency chain the reference
  schedules with its sweep/step progress table (hb2st.cc:139-186) becomes a
  single compiled scan; per-step work is O(kd^2) on dynamic slices.
- stage-2 seam (MethodEig): Auto eigendecomposes the band directly with
  the vendor eigh (no chase — see _stage2_eig); DC chases to tridiagonal
  and runs the native divide & conquer (drivers/stedc.py); QR chases and
  uses the vendor eigh of T (the steqr2 analog).
- eigenvectors: Z = Q1 (Q2 Z_tri): Q2 accumulated inside the chase scan,
  Q1 applied panel-wise with larfb gemms (unmtr_he2hb).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.matrix import HermitianMatrix, Matrix, SymmetricMatrix
from ..core.storage import TileStorage
from ..exceptions import SlateNotConvergedError, slate_error
from ..internal.qr import (householder_panel_blocked, householder_vec,
                           phase_of, unit_lower)
from ..options import (ErrorPolicy, MethodEig, Option, Options, Target,
                       get_option, resolve_target)
from ..robust import certify as _certify
from ..robust import faults as _faults
from ..robust import health as _health
from ..types import Op, Uplo, is_complex
from ..util.trace import annotate, span


def _notconv_exc(name):
    return lambda h: SlateNotConvergedError(
        f"{name}: eigensolve failed certification ({h.describe()})",
        iters=int(h.iters))


# ---------------------------------------------------------------- stage 1

def _he2hb_scan(a, nb: int):
    """Full Hermitian (dense, both triangles) -> band of bandwidth nb, as
    ONE lax.scan step per panel with uniform shapes.

    The reference's he2hb is a task DAG over shrinking trailing blocks
    (ref: src/he2hb.cc:25 panel QR + two-sided her2k-form updates); a
    statically-unrolled translation compiles K copies of the body, which
    at bench sizes produced multi-hundred-MB HLO that overran the
    remote-compile tunnel (VERDICT r4 weak #3).  Here the trailing block
    is re-anchored to the origin after every panel, so every step has
    IDENTICAL shapes and XLA compiles the body once.  Rows past the live
    trailing block are exactly zero, and zero rows are fixed points of
    the update (reflectors there have tau = 0), so the padding never
    contaminates the result — the same pad-is-zero invariant the tile
    storage relies on.

    Returns (Vs, Ts, Ds, Ss): packed panels [K, N-nb, nb] in step-local
    coordinates (panel k's row 0 is global row (k+1) nb), T triangles
    [K, nb, nb], band diagonal tiles Ds [Mt, nb, nb], and subdiagonal R
    tiles Ss [K, nb, nb] (upper-triangular).  N = Mt nb >= n."""
    n = a.shape[0]
    Mt = -(-n // nb)
    N = Mt * nb
    K = Mt - 1
    ap = jnp.zeros((N, N), a.dtype).at[:n, :n].set(a)
    if K == 0:
        return (jnp.zeros((0, max(N - nb, 0), nb), a.dtype),
                jnp.zeros((0, nb, nb), a.dtype), ap[None, :nb, :nb],
                jnp.zeros((0, nb, nb), a.dtype))

    def step(A, _):
        D = A[:nb, :nb]                          # this panel's diag tile
        panel = A[nb:, :nb]                      # [N-nb, nb], zero tail
        packed, T = householder_panel_blocked(panel)
        V = unit_lower(packed)
        # two-sided her2k-form update of the trailing block
        # (ref: he2hb.cc:438-578): A <- A - V W^H - W V^H,
        # W = Y T - 1/2 V (T^H (V^H Y) T),  Y = A V
        trail = A[nb:, nb:]
        Y = trail @ V
        VY = jnp.conj(V).T @ Y
        W = Y @ T - 0.5 * (V @ (jnp.conj(T).T @ (VY @ T)))
        trail = trail - V @ jnp.conj(W).T - W @ jnp.conj(V).T
        # re-anchor: next step sees the trailing block at the origin
        A_next = jnp.zeros_like(A).at[: N - nb, : N - nb].set(trail)
        return A_next, (packed, T, D, packed[:nb, :nb])

    A_fin, (Vs, Ts, Ds, Ss) = lax.scan(step, ap, None, length=K)
    Ds = jnp.concatenate([Ds, A_fin[None, :nb, :nb]], axis=0)
    return Vs, Ts, Ds, Ss


def _band_from_stacks(Ds, Ss, n: int, nb: int):
    """Dense Hermitian band from the he2hb scan's band tiles
    (single-target twin of _band_from_tiles)."""
    from ..core.layout import assemble_band
    bd = assemble_band(Ds, jnp.triu(Ss), lower=True)
    return _band_of(bd[:n, :n], nb)


def _band_of(a_packed, kd: int):
    """Extract the Hermitian band (both triangles) from he2hb packing."""
    n = a_packed.shape[0]
    i = jnp.arange(n)[:, None]
    j = jnp.arange(n)[None, :]
    low = jnp.where((i - j <= kd) & (i - j >= 0), a_packed,
                    jnp.zeros_like(a_packed))
    low = jnp.tril(low)
    diag = jnp.diagonal(low)
    if is_complex(a_packed.dtype):
        diag = jnp.real(diag).astype(a_packed.dtype)
    full = low + jnp.conj(low).T
    return full.at[jnp.arange(n), jnp.arange(n)].set(diag)


def _band_diag_tiles(st, off: int):
    """Gather the tile diagonal at row-offset ``off`` (tiles
    ``(g + max(off,0), g + max(-off,0))``) straight from cyclic storage —
    one O(min(Mt,Nt)) tile gather, never a full canonical() reshuffle."""
    import numpy as np
    from ..core import layout
    ci, _, _ = layout.cyclic_row_maps(st.Mt, st.grid.p)
    cj, _, _ = layout.cyclic_row_maps(st.Nt, st.grid.q)
    count = min(st.Mt - max(off, 0), st.Nt - max(-off, 0))
    g = np.arange(max(count, 0))
    return st.data[ci[g + max(off, 0)], cj[g + max(-off, 0)]]


def _band_from_tiles(st, n: int, nb: int):
    """Assemble the Hermitian band (dense [n, n], both triangles) from the
    he2hb-packed storage: diagonal tiles + triu of the subdiagonal R blocks
    (the analog of HermitianBandMatrix::he2hbGather, ref: heev.cc:109-111 —
    only the O(n nb) band tiles leave the mesh).

    Two vectorized tile scatters + one untile (core/layout.py
    assemble_band) — not an O(Mt) unrolled chain of full-matrix updates
    (at n=30k/nb=512 that chain was ~60 sequential dense writes in the
    compiled program)."""
    from ..core.layout import assemble_band
    Mt = st.Mt
    dd = _band_diag_tiles(st, 0)                  # [Mt, nb, nb]
    ss = (jnp.triu(_band_diag_tiles(st, 1)) if Mt > 1
          else jnp.zeros((0, nb, nb), st.dtype))  # tiles (g+1, g)
    bd = assemble_band(dd, ss, lower=True)
    return _band_of(bd[:n, :n], nb)


def _unmtr_he2hb_stack(Vs, Ts, nb: int, Z):
    """Z <- Q1 Z where Q1 is the he2hb panel product
    (ref: unmtr_he2hb.cc): panel k lives at global rows [(k+1) nb, N).
    Z must have N = Mt nb rows (caller pads)."""
    from ..internal.qr import rolled_apply
    K = Ts.shape[0]
    return rolled_apply(Vs, Ts, (jnp.arange(K) + 1) * nb, Z)


# ---------------------------------------------------------------- stage 2

def _hb2st(band, kd: int, want_q: bool):
    """Band (full Hermitian, bandwidth kd) -> real tridiagonal (d, e) by
    Householder bulge chasing; one lax.scan over (sweep, step) pairs
    (ref: hb2st.cc:41-314 hebr1/2/3 kernel pipeline).

    Returns (d [n], e [n-1], Q2 [n, n] or None) with band = Q2 T Q2^H.
    """
    n = band.shape[0]
    dt = band.dtype
    if n == 1:
        d = jnp.real(band[jnp.arange(1), jnp.arange(1)])
        return d, jnp.zeros((0,), d.dtype), (
            jnp.eye(1, dtype=dt) if want_q else None)
    kd = max(1, min(kd, n - 1))
    N = n + 3 * kd + 2                           # padded to keep slices in
    A = jnp.zeros((N, N), dt).at[:n, :n].set(band)
    Q = jnp.eye(N, dtype=dt) if want_q else jnp.zeros((1, 1), dt)

    Tmax = max(1, -(-(n - 1) // kd))             # chase steps per sweep

    def step(carry, jt):
        A, Q = carry
        j, t = jt
        b = j + 1 + t * kd                       # window row base
        c = jnp.where(t == 0, j, b - kd)         # column being cleared
        x = lax.dynamic_slice(A, (b, c), (kd, 1))[:, 0]
        v, tau, _ = householder_vec(x)
        W = 3 * kd + 1
        # left: rows [b, b+kd) x cols [c, c+W):  H^H A
        Wr = lax.dynamic_slice(A, (b, c), (kd, W))
        Wr = Wr - jnp.conj(tau) * v[:, None] * (jnp.conj(v) @ Wr)[None, :]
        A = lax.dynamic_update_slice(A, Wr, (b, c))
        # right: rows [c, c+W) x cols [b, b+kd):  A H
        Wc = lax.dynamic_slice(A, (c, b), (W, kd))
        Wc = Wc - tau * (Wc @ v)[:, None] * jnp.conj(v)[None, :]
        A = lax.dynamic_update_slice(A, Wc, (c, b))
        if want_q:
            Qc = lax.dynamic_slice(Q, (0, b), (N, kd))
            Qc = Qc - tau * (Qc @ v)[:, None] * jnp.conj(v)[None, :]
            Q = lax.dynamic_update_slice(Q, Qc, (0, b))
        return (A, Q), None

    # static schedule: only the live (sweep, step) pairs — step t of sweep j
    # touches rows from j+1+t*kd, so later sweeps need fewer chase steps
    # (the reference's sweep/step progress table encodes the same frontier)
    pairs = [(j, t) for j in range(n - 1) for t in range(Tmax)
             if j + 1 + t * kd < n]
    js = jnp.asarray([pr[0] for pr in pairs])
    ts = jnp.asarray([pr[1] for pr in pairs])
    (A, Q), _ = lax.scan(step, (A, Q), (js, ts))

    d = jnp.real(jnp.diagonal(A)[:n])
    e_c = jnp.diagonal(A, offset=-1)[: n - 1]
    if is_complex(dt):
        # phase-normalise the subdiagonal (LAPACK zhbtrd final scaling):
        # T_real = D^H T D, Z gets D folded in
        D = jnp.concatenate([jnp.ones((1,), dt), jnp.cumprod(phase_of(e_c))])
        e = jnp.abs(e_c)
        if want_q:
            Q = Q.at[:, :n].multiply(D[None, :])
    else:
        e = e_c
    return d, e, (Q[:n, :n] if want_q else None)


# ---------------------------------------------------------------- driver

def _tridiag_eig(d, e, want_z: bool, opts: Options | None = None,
                 grid=None):
    """Tridiagonal kernel seam (ref: heev.cc:141-153 steqr2/stedc
    dispatch): MethodEig.DC runs the native divide & conquer
    (drivers/stedc.py — merge work is MXU gemms, the reference's default,
    with merge gemms row-distributed when ``grid`` carries a mesh);
    MethodEig.QR is the vendor seam (XLA eigh of the assembled T, the
    steqr2 analog)."""
    meth = get_option(opts, Option.MethodEig)
    if meth is MethodEig.DC and want_z and d.shape[0] > 1:
        from .stedc import stedc_info
        # certify=False: heev certifies its own (w, Z) against A at the
        # driver boundary; only the secular/deflation flags are needed here
        (w, z), h = stedc_info(d, e, grid, certify=False)
        return w, z, h
    n = d.shape[0]
    T = (jnp.diag(d) + jnp.diag(e, -1) + jnp.diag(e, 1)
         if n > 1 else jnp.diag(d))
    if want_z:
        w, z = jnp.linalg.eigh(T)
        return w, z, _health.from_result(w)
    w = jnp.linalg.eigvalsh(T)
    return w, None, _health.from_result(w)


def _stage2_eig(band, nb: int, jobz: bool, opts: Options | None,
                grid=None):
    """Stage 2 + tridiagonal seam, method-dispatched (the MethodEig
    consumer).  Returns (w, Z2, HealthInfo) with band = Z2 diag(w) Z2^H
    (Z2 None when jobz=False); the fault sites ``post_stage1`` (the band
    handed to stage 2) and ``post_chase`` (the chased tridiagonal) fire
    here, and the health ANDs in the tridiagonal kernel's flags (stedc's
    secular/deflation guards on the DC route).

    Auto: eigendecompose the band DIRECTLY with XLA's eigh — measured
    ~60x faster than the chase at n=4096 on TPU (the chase's ~n^2/(2 kd)
    sequential rank-1 scan steps are pure latency, and the tridiagonal
    kernel is O(n^3) dense eigh either way, so the chase cannot pay for
    itself on this seam; cf. ref heev.cc:128 where hb2st feeds O(n^2)
    steqr2/stedc, which DOES pay).
    QR/DC: the parity route — hb2st bulge chase to a true tridiagonal,
    then the (d, e) kernel."""
    band = _faults.maybe_corrupt("post_stage1", band)
    meth = get_option(opts, Option.MethodEig)
    if meth is MethodEig.Auto:
        if jobz:
            w, Z2 = jnp.linalg.eigh(band)
            return w, Z2, _health.from_result(w)
        w = jnp.linalg.eigvalsh(band)
        return w, None, _health.from_result(w)
    d, e, Q2 = _hb2st(band, nb, want_q=jobz)
    d = _faults.maybe_corrupt("post_chase", d)
    w, ztri, h = _tridiag_eig(d, e, jobz, opts, grid)
    h = _health.merge(h, _health.from_result(d), _health.from_result(e))
    if not jobz:
        return w, None, h
    return w, Q2 @ ztri.astype(Q2.dtype), h


@annotate("slate.sterf")
def sterf(d, e, opts: Options | None = None):
    """Eigenvalues of a real symmetric tridiagonal (d, e) — no vectors
    (ref: src/sterf.cc wrapping LAPACK sterf).  Under ``ErrorPolicy.Info``
    returns ``(w, HealthInfo)``."""
    w, _, h = _tridiag_eig(jnp.asarray(d), jnp.asarray(e), False, opts)
    return _health.finalize("sterf", w, h, opts, _notconv_exc("sterf"))


@annotate("slate.steqr")
def steqr(d, e, opts: Options | None = None):
    """Eigendecomposition of a real symmetric tridiagonal (d, e)
    (ref: src/steqr2.cc QR iteration with distributed Z rows — here the
    vendor eigh seam).  Returns (w, Z); under ``ErrorPolicy.Info``,
    ``(w, Z, HealthInfo)``."""
    w, z, h = _tridiag_eig(jnp.asarray(d), jnp.asarray(e), True, opts)
    return _health.finalize_flat("steqr", (w, z), h, opts,
                                 _notconv_exc("steqr"))


@annotate("slate.hb2st")
def hb2st(HB, opts: Options | None = None, *, want_q: bool = True):
    """Band -> tridiagonal bulge chase as a public driver
    (ref: src/hb2st.cc): takes a HermitianBandMatrix, returns (d, e, Q2)
    with band = Q2 T Q2^H; under ``ErrorPolicy.Info``,
    ``(d, e, Q2, HealthInfo)``."""
    from ..core.matrix import HermitianBandMatrix
    slate_error(isinstance(HB, HermitianBandMatrix), "hb2st: need "
                "HermitianBandMatrix")
    d, e, Q2 = _hb2st(HB.to_dense(), HB.kd, want_q=want_q)
    h = _health.merge(_health.from_result(d), _health.from_result(e))
    return _health.finalize_flat("hb2st", (d, e, Q2), h, opts,
                                 _notconv_exc("hb2st"))


def heev_info(A, opts: Options | None = None, *, jobz: bool = True):
    """heev compute body: ``((w, Zm), HealthInfo)``, no policy resolution
    (the recovery layer escalates on this seam).  The health merges the
    stage-2/tridiagonal flags with the a-posteriori eigen-certificate of
    the back-transformed pairs against the ORIGINAL A
    (``certify.certify_eig`` — so corruption anywhere in the two-stage
    pipeline, including a silent bit-flip, fails the residual or
    orthogonality check)."""
    slate_error(isinstance(A, (HermitianMatrix, SymmetricMatrix)),
                "heev: need HermitianMatrix/SymmetricMatrix")
    # complex-symmetric (non-Hermitian) has no real eigendecomposition of
    # this form; LAPACK/SLATE likewise have no such driver (ref heev.cc
    # instantiates syev only for real scalar types)
    slate_error(isinstance(A, HermitianMatrix) or not is_complex(A.dtype),
                "heev: complex SymmetricMatrix is not Hermitian — "
                "no eigensolver for complex-symmetric matrices")
    n = A.m
    nb = A.nb
    if resolve_target(opts, A) is Target.mesh and A.grid.mesh is not None:
        w, Zm, h = _heev_mesh(A, opts, jobz)
    else:
        ad = A.to_dense()
        with span("slate.heev/he2hb"):
            Vs, Ts, Ds, Ss = _he2hb_scan(ad, nb)
            band = _band_from_stacks(Ds, Ss, n, nb)
        with span("slate.heev/stage2"):
            w, Z2, h = _stage2_eig(band, nb, jobz, opts)
        if jobz:
            with span("slate.heev/backtransform"):
                N = Ds.shape[0] * nb
                Zpad = jnp.zeros((N, n), Z2.dtype).at[:n].set(Z2)
                Z = _unmtr_he2hb_stack(Vs, Ts, nb, Zpad)[:n]
                Z = _faults.maybe_corrupt("post_backtransform", Z)
                Zm = Matrix(TileStorage.from_dense(Z, A.mb, A.nb, A.grid))
        else:
            Zm = None
    if jobz:
        h = _health.merge(
            _certify.certify_eig(A.to_dense(), w, Zm.to_dense()), h)
    else:
        h = _health.merge(_health.from_result(w), h)
    return (w, Zm), h


@annotate("slate.heev")
def heev(A, opts: Options | None = None, *, jobz: bool = True):
    """Eigendecomposition A = Z diag(w) Z^H for Hermitian/symmetric A
    (ref: src/heev.cc).  Returns (w, Z) — Z is None when jobz=False;
    under ``ErrorPolicy.Info``, ``(w, Z, HealthInfo)``.

    Every result is a-posteriori certified (residual + orthogonality,
    robust/certify.py); an eager certification failure escalates
    MethodEig Auto -> DC -> QR (ScaLAPACK's D&C -> QR ladder) before the
    ErrorPolicy resolves — see ``recovery.heev_with_recovery`` and
    docs/ROBUSTNESS.md.

    On a mesh, stage 1 (he2hb — all the O(n^3) flops) runs distributed
    (_heev_mesh -> parallel/dist_he2hb); only the O(n nb) band is gathered
    for the stage-2 bulge chase, exactly the reference's he2hbGather seam
    (heev.cc:109-111).
    """
    from ..robust.recovery import heev_with_recovery
    return heev_with_recovery(A, opts, jobz=jobz)


def _heev_mesh(A, opts, jobz: bool):
    """Mesh path: stage 1 (all the O(n^3) flops) runs DISTRIBUTED via
    dist_he2hb — the input is never densified; only the O(n nb) band is
    gathered for stage 2, exactly the reference's he2hbGather seam
    (ref: heev.cc:104-111).  The Q2 Z_tri product and the Q1
    back-transform are mesh-distributed (SUMMA gemm + dist_unmtr_he2hb)."""
    from ..parallel.dist_he2hb import dist_he2hb, dist_unmtr_he2hb
    n, nb = A.m, A.nb
    grid = A.grid
    # zero-copy for canonical lower storage; ConjTrans is also safe (the
    # conj-transpose of a Hermitian matrix IS the matrix), as is Trans of a
    # real symmetric one.  Op.Trans of a complex Hermitian is conj(A) != A —
    # that view must densify so the op is applied.
    safe_ops = ((Op.NoTrans, Op.ConjTrans) if is_complex(A.dtype)
                else (Op.NoTrans, Op.ConjTrans, Op.Trans))
    if (A.uplo is Uplo.Lower and A.op in safe_ops
            and A.is_root_view() and A.storage.mb == nb):
        st_in = A.storage                        # zero-copy, lower-stored
    else:
        st_in = TileStorage.from_dense(A.to_dense(), nb, nb, grid)
    from ..parallel.dist_chol import SUPERBLOCKS, superblock
    la = max(1, int(get_option(opts, Option.Lookahead)))
    with span("slate.heev/he2hb"):
        data, Ts = dist_he2hb(st_in.data, st_in.Nt, grid, n=n,
                              sb=superblock(max(st_in.Nt - 1, 1),
                                            SUPERBLOCKS * la))
        st_packed = TileStorage(data, st_in.m, st_in.n, nb, nb, grid)
        band = _band_from_tiles(st_packed, n, nb)
    # ONE stage-2 dispatch shared with the single-target path; the DC
    # route's merge gemms are row-distributed over this grid's mesh
    # (drivers/stedc.py _merge_gemm), the rest of stage 2 is single-node
    # by design, as the reference's is
    with span("slate.heev/stage2"):
        w, Z2, h = _stage2_eig(band, nb, jobz, opts, grid)
    if not jobz:
        return w, None, h
    with span("slate.heev/backtransform"):
        Z0 = Matrix(TileStorage.from_dense(Z2, nb, nb, grid))
        z_data = dist_unmtr_he2hb(data, Ts, Z0.storage.data, st_in.Nt,
                                  grid, n=n)
        z_data = _faults.maybe_corrupt("post_backtransform", z_data)
    zs = Z0.storage
    return (w, Matrix(TileStorage(z_data, zs.m, zs.n, zs.mb, zs.nb,
                                  zs.grid)), h)


@annotate("slate.heevd")
def heevd(A, opts: Options | None = None):
    """Eigenvalues AND vectors, divide-and-conquer flavor — the LAPACK
    heevd contract (our seams are XLA's eigh, itself D&C/QDWH;
    ref: heev.cc MethodEig::DC).  Same result as heev(A)."""
    return heev(A, opts, jobz=True)


@annotate("slate.heev_vals")
def heev_vals(A, opts: Options | None = None):
    """Eigenvalues only (ref: heev with Job::NoVec; simplified_api
    eig_vals).  Values-only twin of svd_vals.  Under ``ErrorPolicy.Info``
    returns ``(w, HealthInfo)``."""
    res = heev(A, opts, jobz=False)
    if _health.error_policy(opts) is ErrorPolicy.Info:
        w, _, h = res
        return w, h
    return res[0]


@annotate("slate.hegst")
def hegst(A, L, opts: Options | None = None, *, itype: int = 1):
    """Reduce a generalized Hermitian-definite problem to standard form
    with B = L L^H (ref: src/hegst.cc:40-41 supports itype 1/2/3):

    itype 1 (A x = w B x):            C = L^-1 A L^-H  (two trsm sweeps)
    itype 2/3 (A B x / B A x = w x):  C = L^H  A L     (two trmm sweeps)
    """
    from .blas3 import trmm, trsm
    slate_error(itype in (1, 2, 3), "hegst: itype must be 1, 2, or 3")
    Ag = A.general() if not isinstance(A, Matrix) else A
    if itype == 1:
        G = trsm("l", 1.0, L, Ag, opts)
        G2 = trsm("r", 1.0, L.conj_transpose(), G, opts)
    else:
        G = trmm("l", 1.0, L.conj_transpose(), Ag, opts)
        G2 = trmm("r", 1.0, L, G, opts)
    return HermitianMatrix._from_view(G2, Uplo.Lower)


@annotate("slate.hegv")
def hegv(A, B, opts: Options | None = None, *, jobz: bool = True,
         itype: int = 1):
    """Generalized Hermitian-definite eigenproblem (ref: src/hegv.cc:22-35,
    the three LAPACK problem types):

    itype 1: A x = w B x   -> C = L^-1 A L^-H, x = L^-H z
    itype 2: A B x = w x   -> C = L^H A L,     x = L^-H z
    itype 3: B A x = w x   -> C = L^H A L,     x = L z

    B = L L^H (Cholesky); returns (w, X) with X None when jobz=False;
    under ``ErrorPolicy.Info``, ``(w, X, HealthInfo)`` merging the
    Cholesky and eigensolve healths."""
    from .blas3 import trmm, trsm
    from .cholesky import potrf
    slate_error(itype in (1, 2, 3), "hegv: itype must be 1, 2, or 3")
    info = _health.error_policy(opts) is ErrorPolicy.Info
    if info:
        L, h_chol = potrf(B, opts)
    else:
        L = potrf(B, opts)                       # Raise/Nan resolve inside
    C = hegst(A, L, opts, itype=itype)
    res = heev(C, opts, jobz=jobz)
    if info:
        w, Z, h_eig = res
        h = _health.merge(h_chol, h_eig)
    else:
        w, Z = res
    if not jobz:
        return (w, None, h) if info else (w, None)
    if itype == 3:
        X = trmm("l", 1.0, L, Z, opts)
    else:
        X = trsm("l", 1.0, L.conj_transpose(), Z, opts)
    if info:
        return w, X, _health.merge(h, _health.from_result(X.storage.data))
    return w, X
