"""Parallel BLAS-3 drivers.

Analog of the reference's L6 BLAS-3 routine set (ref: src/gemm.cc, gemmC.cc,
gemmA.cc, hemm.cc, symm.cc, trmm.cc, trsm.cc/trsmA.cc/trsmB.cc, herk.cc,
syrk.cc, her2k.cc, syr2k.cc).  Each driver:

- validates shapes and resolves the execution target (Option::Target),
- single target: statically-shaped dense/blocked computation under jit —
  the analog of the HostTask path but feeding the whole problem to the MXU,
- mesh target: shard_map pipeline over the 2D block-cyclic grid with ICI
  collectives (SUMMA for gemm; masked-panel pipelines for triangular ops).

All drivers are functional: they RETURN the updated matrix instead of
mutating C (XLA buffer donation recovers in-place performance).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.grid import Grid
from ..core.matrix import BaseMatrix, Matrix
from ..core.storage import TileStorage
from ..exceptions import slate_error
from ..options import (MethodGemm, Option, Options, Target, resolve_abft,
                       resolve_target, select_gemm_method)
from ..parallel import summa
from ..robust import abft as _abft
from ..types import Diag, Op, Side, Uplo
from ..util.trace import annotate


def as_root_general(A: BaseMatrix, mb: int | None = None,
                    nb: int | None = None,
                    grid: Grid | None = None) -> Matrix:
    """Normalise any view/op/structure to a root general Matrix with the given
    tile sizes on the given grid (materialises/redistributes only when
    needed).  Mesh drivers use this so the shard_map body sees plain cyclic
    storage laid out for the OUTPUT's grid."""
    mb = mb or A.mb
    nb = nb or A.nb
    grid = grid or A.grid
    if (type(A) is Matrix and A.op is Op.NoTrans and A.is_root_view()
            and A.mb == mb and A.nb == nb and A.grid is grid):
        return A
    dense = A.to_dense()
    return Matrix(TileStorage.from_dense(dense, mb, nb, grid))


def _result_mat(C: BaseMatrix, data) -> Matrix:
    st = C.storage
    return Matrix(TileStorage(data, st.m, st.n, st.mb, st.nb, st.grid))


# ---------------------------------------------------------------- gemm

@annotate("slate.gemm")
def gemm(alpha, A: BaseMatrix, B: BaseMatrix, beta=0.0,
         C: Matrix | None = None, opts: Options | None = None) -> Matrix:
    """C = alpha op(A) op(B) + beta C (ref: src/gemm.cc:66-89 dispatch,
    src/gemmC.cc:29-192 stationary-C algorithm)."""
    slate_error(A.n == B.m, "gemm: inner dims differ")
    if C is None:
        dt = jnp.result_type(A.dtype, B.dtype)
        C = Matrix.zeros(A.m, B.n, A.mb, B.nb, A.grid, dt)
        beta = 0.0
    slate_error(C.m == A.m and C.n == B.n, "gemm: C dims differ")
    target = resolve_target(opts, C)
    method = select_gemm_method(opts, C.nt)
    abft = resolve_abft(opts)  # the one Option.Abft read (driver boundary)

    if target is Target.mesh and C.grid.mesh is not None:
        # All operands are normalised onto C's grid (redistributing if they
        # live elsewhere — the analog of the reference's requirement that all
        # three matrices share one MPI communicator).
        Cn = as_root_general(C, grid=C.grid)
        An = as_root_general(A, Cn.storage.mb, None, grid=C.grid)
        Bn = as_root_general(B, An.storage.nb, Cn.storage.nb, grid=C.grid)
        slate_error(An.storage.Nt == Bn.storage.Mt, "gemm: k tiling differs")
        if method is MethodGemm.gemmA:
            # stationary-A, replicate-B + reduce-over-C (ref: gemmA.cc)
            from ..parallel.gemm_a import dist_gemmA_data
            data = dist_gemmA_data(
                An.storage.data, Bn.storage.data, Cn.storage.data,
                alpha, beta, An.storage.Nt, Cn.grid)
        elif abft:
            # gemm has no health channel, so ABFT here is SILENT repair:
            # a single struck accumulator tile is fixed in place, the
            # counters are dropped (an uncorrectable multi-strike leaves
            # the data for the caller's certification to catch)
            data, _, _, _ = summa.summa_gemm_data(
                An.storage.data, Bn.storage.data, Cn.storage.data,
                alpha, beta, An.storage.Nt, Cn.grid, abft=True)
        else:
            data = summa.summa_gemm_data(
                An.storage.data, Bn.storage.data, Cn.storage.data,
                alpha, beta, An.storage.Nt, Cn.grid)
        return _result_mat(Cn, data)

    # single target: one fused MXU contraction.  Literal alpha=1 / beta=0
    # skip their passes entirely — XLA cannot fold 0*C itself (0*NaN
    # semantics), and the beta=0 path otherwise materialises and reads a
    # zeros C for nothing (measured ~35% of the n=8192 gemm wall-clock)
    Ad, Bd = A.to_dense(), B.to_dense()
    Cd = Ad @ Bd
    if abft:
        # additive checksums of the raw product (silent repair, as above)
        Cd, _ = _abft.sum_check(Cd, Ad @ jnp.sum(Bd, axis=1),
                                jnp.sum(Ad, axis=0) @ Bd, n_ctx=A.n)
    if not (isinstance(alpha, (int, float)) and alpha == 1.0):
        Cd = jnp.asarray(alpha, Cd.dtype) * Cd
    if not (isinstance(beta, (int, float)) and beta == 0.0):
        Cd = Cd + jnp.asarray(beta, Cd.dtype) * C.to_dense()
    return C.with_dense(Cd) if type(C) is Matrix else _dense_to_like(C, Cd)


def _dense_to_like(C: BaseMatrix, dense) -> Matrix:
    g = Matrix.zeros(C.m, C.n, C.mb, C.nb, C.grid, dense.dtype)
    return g.with_dense(dense)


def _side(side) -> Side:
    if isinstance(side, Side):
        return side
    return Side.Left if str(side).lower().startswith("l") else Side.Right


# ---------------------------------------------------------------- trsm/trmm

@annotate("slate.trsm")
def trsm(side, alpha, A, B, opts: Options | None = None) -> Matrix:
    """Solve op(A) X = alpha B (Left) or X op(A) = alpha B (Right), A
    triangular (ref: src/trsm.cc method dispatch -> src/trsmB.cc ->
    work/work_trsm.cc; trsmA variant src/trsmA.cc).

    single: one XLA triangular_solve (blocked internally, MXU-shaped).
    mesh: parallel.dist_trsm substitution pipeline with panel broadcasts.
    MethodTrsm picks the anchor grid when A and B live on different grids:
    trsmB (default) moves A's triangle onto B's grid, trsmA keeps A
    stationary and redistributes B onto A's grid — the reference's
    stationary-operand distinction (ref: src/trsmA.cc vs src/trsmB.cc).
    """
    from ..core.matrix import BaseTrapezoidMatrix
    from ..options import MethodTrsm, select_trsm_method
    from ..parallel.dist_trsm import dist_trsm_left, dist_trsm_right
    sd = _side(side)
    slate_error(isinstance(A, BaseTrapezoidMatrix), "trsm: A not triangular")
    slate_error(A._m_store() == A._n_store(), "trsm: A not square")
    if sd is Side.Left:
        slate_error(A.n == B.m, "trsm: dims")
    else:
        slate_error(B.n == A.m, "trsm: dims")
    target = resolve_target(opts, B)
    unit = A.diag is Diag.Unit
    abft = resolve_abft(opts)  # the one Option.Abft read (driver boundary)

    if target is Target.mesh and B.grid.mesh is not None:
        meth = select_trsm_method(opts, B.nt)
        grid = A.grid if (meth is MethodTrsm.trsmA
                          and A.grid.mesh is not None) else B.grid
        lower = A.uplo is Uplo.Lower       # storage triangle
        nb = A.storage.nb
        An = _root_storage_triangular(A, grid=grid)
        if sd is Side.Right:
            # direct column-substitution kernel: no dense transpose
            Bn = as_root_general(B, None, nb, grid=grid)
            data = dist_trsm_right(An.storage.data, Bn.storage.data,
                                   jnp.asarray(alpha, Bn.dtype),
                                   Nt=An.storage.Nt, grid=grid,
                                   lower=lower, op_a=A.op, unit_diag=unit,
                                   n=An.storage.n)
        else:
            Bn = as_root_general(B, nb, None, grid=grid)
            data = dist_trsm_left(An.storage.data, Bn.storage.data,
                                  jnp.asarray(alpha, Bn.dtype),
                                  Nt=An.storage.Nt, grid=grid, lower=lower,
                                  op_a=A.op, unit_diag=unit, n=An.storage.n)
        st = Bn.storage
        return Matrix(TileStorage(data, st.m, st.n, st.mb, st.nb, st.grid))

    ad = A._dense_store()                  # storage triangle, op separate
    bd = alpha * B.to_dense()
    lower = A.uplo is Uplo.Lower
    nb = A.storage.nb
    if ad.shape[0] >= 2 * nb:
        # block substitution with batched diagonal inversions — every op
        # an MXU gemm (internal/trsm.py; XLA's per-column solve measured
        # 4.1 TFLOP/s at [16384, 256]); ragged n identity-augmented inside
        from ..internal.trsm import trsm_left_blocked, trsm_right_blocked
        kw = dict(lower=lower, trans=(A.op is not Op.NoTrans),
                  conj=(A.op is Op.ConjTrans), unit=unit, nb=nb,
                  check=abft)  # checksum-verify + silent single repair
        xd = (trsm_left_blocked(ad, bd, **kw) if sd is Side.Left
              else trsm_right_blocked(ad, bd, **kw))
        return _dense_to_like(B, xd)
    from jax import lax as _lax
    xd = _lax.linalg.triangular_solve(
        ad, bd, left_side=(sd is Side.Left), lower=lower,
        transpose_a=(A.op is not Op.NoTrans),
        conjugate_a=(A.op is Op.ConjTrans), unit_diagonal=unit)
    return _dense_to_like(B, xd)


def _root_storage_triangular(A, grid=None):
    """Root general matrix holding A's STORAGE triangle (op ignored —
    callers pass A.op separately)."""
    grid = grid or A.grid
    if (A.op in (Op.NoTrans, Op.Trans, Op.ConjTrans) and A.is_root_view()
            and A.grid is grid and A.storage.mb == A.storage.nb):
        return Matrix(A.storage)
    d = A._dense_store()
    nb = A.storage.nb
    return Matrix(TileStorage.from_dense(d, nb, nb, grid))


@annotate("slate.trmm")
def trmm(side, alpha, A, B, opts: Options | None = None) -> Matrix:
    """B = alpha op(A) B (Left) or alpha B op(A) (Right), A triangular
    (ref: src/trmm.cc -> work/work_trmm.cc).

    mesh: triangle-aware packed-pair kernel over A's STORED tiles only —
    half a gemm's flops, no dense expansion (parallel/dist_herk.py
    dist_trmm_data).  Transposed-A views fall back to the dense path."""
    sd = _side(side)
    if (resolve_target(opts, B) is Target.mesh and B.grid.mesh is not None
            and A.op is Op.NoTrans and A.is_root_view()
            and A.storage.mb == A.storage.nb
            # the kernel reads A.storage raw, so its cyclic layout must be
            # B's grid's; cross-grid operands fall back to the dense path
            and A.grid is B.grid):
        from ..parallel.dist_herk import (dist_trmm_data,
                                          dist_trmm_right_data)
        lower = A.uplo is Uplo.Lower
        unit = A.diag is Diag.Unit
        nb = A.storage.nb
        An = Matrix(A.storage)
        if sd is Side.Left:
            Bn = as_root_general(B, nb, None, grid=B.grid)
            data = dist_trmm_data(
                An.storage.data, Bn.storage.data, alpha,
                Kt=An.storage.Nt, Mt=An.storage.Mt, grid=B.grid,
                lower=lower, unit_diag=unit, n=An.storage.n)
        else:
            Bn = as_root_general(B, None, nb, grid=B.grid)
            data = dist_trmm_right_data(
                An.storage.data, Bn.storage.data, alpha,
                Kt=An.storage.Mt, Nt=An.storage.Nt, grid=B.grid,
                lower=lower, unit_diag=unit, n=An.storage.n)
        return _result_mat(Bn, data)
    ad = A.to_dense()                      # expands triangle incl. unit diag
    if resolve_target(opts, B) is Target.mesh and B.grid.mesh is not None:
        Ag = Matrix(TileStorage.from_dense(ad, A.mb, A.nb, B.grid))
        return gemm(alpha, Ag, B, 0.0, None, opts) if sd is Side.Left \
            else gemm(alpha, B, Ag, 0.0, None, opts)
    bd = B.to_dense()
    out = alpha * (ad @ bd) if sd is Side.Left else alpha * (bd @ ad)
    return _dense_to_like(B, out)


# ---------------------------------------------------------------- rank-k

def _rank_k_mesh(alpha, A, beta, C, opts, conj: bool, B=None, alpha2=None):
    """Shared mesh fast path for herk/syrk/her2k/syr2k: triangle-aware
    packed-pair kernel over C's STORED triangle tiles — half a full gemm's
    flops and comm (ref: internal_herk.cc diagonal herk + off-diag gemm).
    Returns the updated general storage Matrix, or None when the operands
    don't qualify (caller falls back to the gemm composition)."""
    from ..parallel.dist_herk import dist_herk_data
    if not (resolve_target(opts, C) is Target.mesh
            and C.grid.mesh is not None and C.op is Op.NoTrans
            and C.is_root_view() and C.storage.mb == C.storage.nb):
        return None
    nb = C.storage.nb
    An = as_root_general(A, nb, None, grid=C.grid)
    b_data = None
    if B is not None:
        Bn = as_root_general(B, nb, An.storage.nb, grid=C.grid)
        slate_error(Bn.storage.Nt == An.storage.Nt, "rank-2k: k tiling")
        b_data = Bn.storage.data
    cs = C.storage
    data = dist_herk_data(
        An.storage.data, cs.data, alpha, beta, Kt=An.storage.Nt,
        Mt=cs.Mt, Nt=cs.Nt, grid=C.grid, lower=C.uplo is Uplo.Lower,
        conj=conj, b_data=b_data, alpha2=alpha2)
    return _result_mat(C, data)


@annotate("slate.herk")
def herk(alpha, A, beta, C, opts: Options | None = None):
    """C = alpha A A^H + beta C, C Hermitian (ref: src/herk.cc,
    internal_herk.cc:843).  mesh: triangle-aware, half-gemm cost."""
    from ..core.matrix import BaseTrapezoidMatrix, HermitianMatrix
    slate_error(isinstance(C, BaseTrapezoidMatrix),
                "herk: C must be Hermitian/Symmetric")
    slate_error(A.m == C.m, "herk: dims")
    out = _rank_k_mesh(alpha, A, beta, C, opts, conj=True)
    if out is None:
        out = gemm(alpha, A, A.conj_transpose(), beta, _general_of(C), opts)
    return HermitianMatrix._from_view(out, C._uplo_logical())


@annotate("slate.syrk")
def syrk(alpha, A, beta, C, opts: Options | None = None):
    """C = alpha A A^T + beta C, C symmetric (ref: src/syrk.cc)."""
    from ..core.matrix import BaseTrapezoidMatrix, SymmetricMatrix
    slate_error(isinstance(C, BaseTrapezoidMatrix),
                "syrk: C must be Symmetric")
    out = _rank_k_mesh(alpha, A, beta, C, opts, conj=False)
    if out is None:
        out = gemm(alpha, A, A.transpose(), beta, _general_of(C), opts)
    return SymmetricMatrix._from_view(out, C._uplo_logical())


@annotate("slate.her2k")
def her2k(alpha, A, B, beta, C, opts: Options | None = None):
    """C = alpha A B^H + conj(alpha) B A^H + beta C (ref: src/her2k.cc,
    internal_her2k.cc:1062).  mesh: one triangle-aware pass."""
    from ..core.matrix import BaseTrapezoidMatrix, HermitianMatrix
    slate_error(isinstance(C, BaseTrapezoidMatrix),
                "her2k: C must be Hermitian")
    out = _rank_k_mesh(alpha, A, beta, C, opts, conj=True, B=B,
                       alpha2=jnp.conj(jnp.asarray(alpha)))
    if out is None:
        t1 = gemm(alpha, A, B.conj_transpose(), beta, _general_of(C), opts)
        out = gemm(jnp.conj(jnp.asarray(alpha)), B, A.conj_transpose(), 1.0,
                   t1, opts)
    return HermitianMatrix._from_view(out, C._uplo_logical())


@annotate("slate.syr2k")
def syr2k(alpha, A, B, beta, C, opts: Options | None = None):
    """C = alpha A B^T + alpha B A^T + beta C (ref: src/syr2k.cc)."""
    from ..core.matrix import BaseTrapezoidMatrix, SymmetricMatrix
    slate_error(isinstance(C, BaseTrapezoidMatrix),
                "syr2k: C must be Symmetric")
    out = _rank_k_mesh(alpha, A, beta, C, opts, conj=False, B=B,
                       alpha2=alpha)
    if out is None:
        t1 = gemm(alpha, A, B.transpose(), beta, _general_of(C), opts)
        out = gemm(alpha, B, A.transpose(), 1.0, t1, opts)
    return SymmetricMatrix._from_view(out, C._uplo_logical())


@annotate("slate.hemm")
def hemm(side, alpha, A, B, beta=0.0, C=None, opts=None) -> Matrix:
    """C = alpha A B + beta C with A Hermitian (ref: src/hemm.cc method
    dispatch, hemmA variant src/hemmA.cc).  A.to_dense() expands the stored
    triangle, then the multiply rides gemm (SUMMA on mesh); MethodHemm
    selects the stationary-A comm pattern (hemmA) explicitly or by the
    single-block-column heuristic (ref: method.hh MethodHemm::select_algo)."""
    from ..options import MethodHemm, get_option
    sd = _side(side)
    meth = get_option(opts, Option.MethodHemm)
    if meth is MethodHemm.Auto and sd is Side.Left and B.nt < 2:
        meth = MethodHemm.hemmA
    if meth is MethodHemm.hemmA and sd is Side.Left:
        o = dict(opts or {})
        o[Option.MethodGemm] = MethodGemm.gemmA
        return gemm(alpha, A, B, beta, C, o)
    if meth is MethodHemm.hemmA and sd is Side.Right:
        # honor the stationary-A request on the Right via the Hermitian
        # identity alpha B A = (conj(alpha) A B^H)^H — a left hemmA on B^H
        # followed by one elementwise add (never silently ignored)
        from .auxiliary import add as _add
        G = hemm(Side.Left, jnp.conj(jnp.asarray(alpha)), A,
                 B.conj_transpose(), 0.0, None,
                 {**(opts or {}), Option.MethodHemm: MethodHemm.hemmA})
        if C is None:
            dtc = jnp.result_type(A.dtype, B.dtype)
            C = Matrix.zeros(B.m, A.n, B.mb, A.nb, B.grid, dtc)
            beta = 0.0
        return _add(1.0, G.conj_transpose(), beta, C)
    if sd is Side.Left:
        return gemm(alpha, A, B, beta, C, opts)
    return gemm(alpha, B, A, beta, C, opts)


def symm(side, alpha, A, B, beta=0.0, C=None, opts=None) -> Matrix:
    """C = alpha A B + beta C with A symmetric (ref: src/symm.cc)."""
    return hemm(side, alpha, A, B, beta, C, opts)


def hemmA(side, alpha, A, B, beta=0.0, C=None, opts=None) -> Matrix:
    """Stationary-A hemm (ref: src/hemmA.cc): the expanded Hermitian A
    stays put while skinny B is replicated and C is reduce-scattered to
    its owners — gemmA's comm pattern (parallel/gemm_a.py).  Side.Right
    swaps the operands into gemm's replicated slot, which would replicate
    the LARGE Hermitian matrix, so only Side.Left forces gemmA."""
    o = dict(opts or {})
    if _side(side) is Side.Left:
        o[Option.MethodGemm] = MethodGemm.gemmA
    return hemm(side, alpha, A, B, beta, C, o)


def _general_of(C) -> Matrix:
    """General matrix holding C's expanded structure."""
    return C if type(C) is Matrix else C.general()


def gemmA(alpha, A, B, beta=0.0, C=None, opts=None) -> Matrix:
    """Stationary-A gemm (ref: src/gemmA.cc): A never moves; skinny B is
    replicated and partial C is psum_scattered to its owners
    (parallel/gemm_a.py).  Auto-selected for single-block-column C
    (method.hh:87-98); force with Option.MethodGemm."""
    o = dict(opts or {})
    o[Option.MethodGemm] = MethodGemm.gemmA
    return gemm(alpha, A, B, beta, C, o)


def gemmC(alpha, A, B, beta=0.0, C=None, opts=None) -> Matrix:
    """Stationary-C gemm (ref: src/gemmC.cc)."""
    o = dict(opts or {})
    o[Option.MethodGemm] = MethodGemm.gemmC
    return gemm(alpha, A, B, beta, C, o)
