"""Parallel BLAS-3 drivers.

Analog of the reference's L6 BLAS-3 routine set (ref: src/gemm.cc, gemmC.cc,
gemmA.cc, hemm.cc, symm.cc, trmm.cc, trsm.cc/trsmA.cc/trsmB.cc, herk.cc,
syrk.cc, her2k.cc, syr2k.cc).  Each driver:

- validates shapes and resolves the execution target (Option::Target),
- single target: statically-shaped dense/blocked computation under jit —
  the analog of the HostTask path but feeding the whole problem to the MXU,
- mesh target: shard_map pipeline over the 2D block-cyclic grid with ICI
  collectives (SUMMA for gemm; masked-panel pipelines for triangular ops).

All drivers are functional: they RETURN the updated matrix instead of
mutating C (XLA buffer donation recovers in-place performance).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.grid import Grid
from ..core.matrix import BaseMatrix, Matrix
from ..core.storage import TileStorage
from ..exceptions import slate_error
from ..options import (MethodGemm, Option, Options, Target,
                       resolve_target, select_gemm_method)
from ..parallel import summa
from ..types import Op


def as_root_general(A: BaseMatrix, mb: int | None = None,
                    nb: int | None = None,
                    grid: Grid | None = None) -> Matrix:
    """Normalise any view/op/structure to a root general Matrix with the given
    tile sizes on the given grid (materialises/redistributes only when
    needed).  Mesh drivers use this so the shard_map body sees plain cyclic
    storage laid out for the OUTPUT's grid."""
    mb = mb or A.mb
    nb = nb or A.nb
    grid = grid or A.grid
    if (type(A) is Matrix and A.op is Op.NoTrans and A.is_root_view()
            and A.mb == mb and A.nb == nb and A.grid is grid):
        return A
    dense = A.to_dense()
    return Matrix(TileStorage.from_dense(dense, mb, nb, grid))


def _result_mat(C: BaseMatrix, data) -> Matrix:
    st = C.storage
    return Matrix(TileStorage(data, st.m, st.n, st.mb, st.nb, st.grid))


# ---------------------------------------------------------------- gemm

def gemm(alpha, A: BaseMatrix, B: BaseMatrix, beta=0.0,
         C: Matrix | None = None, opts: Options | None = None) -> Matrix:
    """C = alpha op(A) op(B) + beta C (ref: src/gemm.cc:66-89 dispatch,
    src/gemmC.cc:29-192 stationary-C algorithm)."""
    slate_error(A.n == B.m, "gemm: inner dims differ")
    if C is None:
        dt = jnp.result_type(A.dtype, B.dtype)
        C = Matrix.zeros(A.m, B.n, A.mb, B.nb, A.grid, dt)
        beta = 0.0
    slate_error(C.m == A.m and C.n == B.n, "gemm: C dims differ")
    target = resolve_target(opts, C)
    method = select_gemm_method(opts, C.nt)

    if target is Target.mesh and C.grid.mesh is not None:
        # All operands are normalised onto C's grid (redistributing if they
        # live elsewhere — the analog of the reference's requirement that all
        # three matrices share one MPI communicator).
        del method  # gemmA mesh variant not yet distinct: see gemmA().
        Cn = as_root_general(C, grid=C.grid)
        An = as_root_general(A, Cn.storage.mb, None, grid=C.grid)
        Bn = as_root_general(B, An.storage.nb, Cn.storage.nb, grid=C.grid)
        slate_error(An.storage.Nt == Bn.storage.Mt, "gemm: k tiling differs")
        data = summa.summa_gemm_data(
            An.storage.data, Bn.storage.data, Cn.storage.data,
            alpha, beta, An.storage.Nt, Cn.grid)
        return _result_mat(Cn, data)

    # single target: one fused MXU contraction
    Cd = alpha * (A.to_dense() @ B.to_dense()) + beta * C.to_dense()
    return C.with_dense(Cd) if type(C) is Matrix else _dense_to_like(C, Cd)


def _dense_to_like(C: BaseMatrix, dense) -> Matrix:
    g = Matrix.zeros(C.m, C.n, C.mb, C.nb, C.grid, dense.dtype)
    return g.with_dense(dense)


def gemmA(alpha, A, B, beta=0.0, C=None, opts=None) -> Matrix:
    """Stationary-A gemm (ref: src/gemmA.cc).  NOTE: on mesh the
    reduce-over-C-owners communication pattern is not yet distinct — this is
    currently an alias of the stationary-C path (correct, not comm-optimal
    for single-block-column C)."""
    o = dict(opts or {})
    o[Option.MethodGemm] = MethodGemm.gemmA
    return gemm(alpha, A, B, beta, C, o)


def gemmC(alpha, A, B, beta=0.0, C=None, opts=None) -> Matrix:
    """Stationary-C gemm (ref: src/gemmC.cc)."""
    o = dict(opts or {})
    o[Option.MethodGemm] = MethodGemm.gemmC
    return gemm(alpha, A, B, beta, C, o)
