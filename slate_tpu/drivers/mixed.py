"""Mixed-precision solvers: low-precision factor + high-precision refinement.

Analog of the reference's mixed drivers (ref: src/gesv_mixed.cc,
src/gesv_mixed_gmres.cc:24-117, src/posv_mixed.cc, src/posv_mixed_gmres.cc):
factor in the lower precision, iterate refinement (plain IR or GMRES-IR) in
the working precision, fall back to a full-precision factorization after
``itermax`` (default 30) non-converged iterations when
Option::UseFallbackSolver is set.

On TPU this is the *headline* solver path, not a curiosity: the MXU is
natively fast in f32/bf16 while f64 is emulated, so "factor fast + refine
accurate" is how f64-grade solutions are produced at speed
(types.lower_precision: f64->f32, c128->c64, f32->bf16).

Convergence test mirrors the reference (gesv_mixed.cc): the residual is
converged when ||r||_inf <= ||x||_inf * ||A||_inf * eps * sqrt(n) * stew.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

from ..core.matrix import HermitianMatrix, Matrix
from ..core.storage import TileStorage
from ..exceptions import slate_error
from ..options import Option, Options, get_option
from ..types import Norm, eps, lower_precision
from . import auxiliary as aux
from .cholesky import potrf, potrs
from .lu import getrf, getrs


class MixedResult(NamedTuple):
    X: Matrix
    iters: int
    converged: bool


def _refine(A: Matrix, B, solve_lo, opts: Options | None, hermitian=False):
    """Shared IR loop (ref: gesv_mixed.cc iterative refinement body)."""
    itermax = get_option(opts, Option.MaxIterations)
    use_fallback = get_option(opts, Option.UseFallbackSolver)
    ad = A.to_dense()
    bd = B.to_dense()
    n = ad.shape[0]
    anorm = jnp.max(jnp.sum(jnp.abs(ad), axis=1))        # inf-norm
    tol = eps(ad.dtype) * math.sqrt(n)

    x = solve_lo(bd)
    it = 0
    converged = False
    for it in range(1, itermax + 1):
        r = bd - ad @ x
        xnorm = jnp.max(jnp.abs(x))
        rnorm = jnp.max(jnp.abs(r))
        if bool(rnorm <= xnorm * anorm * tol):
            converged = True
            break
        x = x + solve_lo(r)
    return x, it, converged


def _wrap(B, xd) -> Matrix:
    return Matrix(TileStorage.from_dense(xd, B.mb, B.nb, B.grid))


def gesv_mixed(A: Matrix, B, opts: Options | None = None) -> MixedResult:
    """LU in low precision + IR to working precision
    (ref: src/gesv_mixed.cc)."""
    lo = lower_precision(A.dtype)
    Alo = Matrix(A.storage.astype(lo), A.io, A.jo, A._mt, A._nt, A.op)
    F = getrf(Alo, opts)

    def solve_lo(rhs):
        R = _wrap(B, rhs.astype(lo))
        return getrs(F, R, opts).to_dense().astype(A.dtype)

    x, it, ok = _refine(A, B, solve_lo, opts)
    if not ok and get_option(opts, Option.UseFallbackSolver):
        # ref: gesv_mixed_gmres.cc:58-77 — full-precision fallback
        Ff = getrf(A, opts)
        x = getrs(Ff, B, opts).to_dense()
        ok = True
    return MixedResult(_wrap(B, x), it, ok)


def posv_mixed(A: HermitianMatrix, B, opts: Options | None = None
               ) -> MixedResult:
    """Cholesky in low precision + IR (ref: src/posv_mixed.cc)."""
    lo = lower_precision(A.dtype)
    Alo = HermitianMatrix._from_view(
        Matrix(A.storage.astype(lo), A.io, A.jo, A._mt, A._nt, A.op),
        A.uplo)
    L = potrf(Alo, opts)

    def solve_lo(rhs):
        R = _wrap(B, rhs.astype(lo))
        return potrs(L, R, opts).to_dense().astype(A.dtype)

    x, it, ok = _refine(A, B, solve_lo, opts, hermitian=True)
    if not ok and get_option(opts, Option.UseFallbackSolver):
        Lf = potrf(A, opts)
        x = potrs(Lf, B, opts).to_dense()
        ok = True
    return MixedResult(_wrap(B, x), it, ok)


def _gmres_ir(A: Matrix, B, solve_lo, opts: Options | None):
    """GMRES-IR: restarted GMRES in working precision, low-precision factor
    as right preconditioner (ref: src/gesv_mixed_gmres.cc:24-117; restart
    depth 10, itermax 30)."""
    itermax = get_option(opts, Option.MaxIterations)
    restart = 10
    ad = A.to_dense()
    bd = B.to_dense()
    n = ad.shape[0]
    anorm = jnp.max(jnp.sum(jnp.abs(ad), axis=1))
    tol = eps(ad.dtype) * math.sqrt(n)

    nrhs = bd.shape[1]
    x = jnp.zeros_like(bd)
    total_it = 0
    converged = False
    # solve each RHS column with GMRES (reference solves the block with one
    # Krylov space per column internally too)
    cols = []
    for j in range(nrhs):
        b = bd[:, j]
        xj = jnp.zeros_like(b)
        done = False
        for _ in range(itermax // restart + 1):
            r = b - ad @ xj
            beta = jnp.linalg.norm(r)
            if bool(beta <= jnp.max(jnp.abs(xj)) * anorm * tol + 1e-300):
                done = True
                break
            V = [r / beta]
            H = jnp.zeros((restart + 1, restart), ad.dtype)
            m_used = restart
            for i in range(restart):
                z = solve_lo(V[i][:, None])[:, 0]        # precondition
                w = ad @ z
                for t in range(i + 1):
                    h = jnp.vdot(V[t], w)
                    H = H.at[t, i].set(h)
                    w = w - h * V[t]
                hn = jnp.linalg.norm(w)
                H = H.at[i + 1, i].set(hn)
                V.append(w / (hn + 1e-300))
                total_it += 1
            # solve least squares min ||beta e1 - H y||
            e1 = jnp.zeros((restart + 1,), ad.dtype).at[0].set(beta)
            y, *_ = jnp.linalg.lstsq(H, e1)
            Z = jnp.stack([solve_lo(v[:, None])[:, 0]
                           for v in V[:restart]], axis=1)
            xj = xj + Z @ y
        cols.append(xj)
        converged = done
    x = jnp.stack(cols, axis=1)
    return x, total_it, converged


def gesv_mixed_gmres(A: Matrix, B, opts: Options | None = None
                     ) -> MixedResult:
    """ref: src/gesv_mixed_gmres.cc"""
    lo = lower_precision(A.dtype)
    Alo = Matrix(A.storage.astype(lo), A.io, A.jo, A._mt, A._nt, A.op)
    F = getrf(Alo, opts)

    def solve_lo(rhs):
        R = _wrap(B, rhs.astype(lo))
        return getrs(F, R, opts).to_dense().astype(A.dtype)

    x, it, ok = _gmres_ir(A, B, solve_lo, opts)
    if not ok and get_option(opts, Option.UseFallbackSolver):
        Ff = getrf(A, opts)
        x = getrs(Ff, B, opts).to_dense()
        ok = True
    return MixedResult(_wrap(B, x), it, ok)


def posv_mixed_gmres(A: HermitianMatrix, B, opts: Options | None = None
                     ) -> MixedResult:
    """ref: src/posv_mixed_gmres.cc"""
    lo = lower_precision(A.dtype)
    Alo = HermitianMatrix._from_view(
        Matrix(A.storage.astype(lo), A.io, A.jo, A._mt, A._nt, A.op),
        A.uplo)
    L = potrf(Alo, opts)

    def solve_lo(rhs):
        R = _wrap(B, rhs.astype(lo))
        return potrs(L, R, opts).to_dense().astype(A.dtype)

    x, it, ok = _gmres_ir(A, B, solve_lo, opts)
    if not ok and get_option(opts, Option.UseFallbackSolver):
        Lf = potrf(A, opts)
        x = potrs(Lf, B, opts).to_dense()
        ok = True
    return MixedResult(_wrap(B, x), it, ok)
