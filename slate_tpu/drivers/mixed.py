"""Mixed-precision solvers: low-precision factor + high-precision refinement.

Analog of the reference's mixed drivers (ref: src/gesv_mixed.cc,
src/gesv_mixed_gmres.cc:24-117, src/posv_mixed.cc, src/posv_mixed_gmres.cc):
factor in the lower precision, iterate refinement (plain IR or GMRES-IR) in
the working precision, fall back to a full-precision factorization after
``itermax`` (default 30) non-converged iterations when
Option::UseFallbackSolver is set.

On TPU this is the *headline* solver path, not a curiosity: the MXU is
natively fast in f32/bf16 while f64 is emulated, so "factor fast + refine
accurate" is how f64-grade solutions are produced at speed
(types.lower_precision: f64->f32, c128->c64, f32->bf16).

TPU-first shape: both refinement loops are lax.while_loop bodies whose
residuals ride the DISTRIBUTED gemm (never a replicated dense A), solves
ride the distributed factor paths, and the whole solver jits into one XLA
program.  GMRES-IR solves the whole RHS block at once — one Krylov basis
per column, advanced in lockstep (columnwise Arnoldi, the blocked analog of
gesv_mixed_gmres.cc's per-column spaces).  Only the optional full-precision
fallback syncs one boolean to the host, and only when called eagerly.

Convergence test mirrors the reference (gesv_mixed.cc): the residual is
converged when ||r||_max <= ||x||_max * ||A||_inf * eps * sqrt(n).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.matrix import HermitianMatrix, Matrix
from ..core.storage import TileStorage
from ..options import (ErrorPolicy, Option, Options, get_option,
                       resolve_speculate)
from ..robust import health as _health
from ..robust.health import HealthInfo
from ..robust.recovery import bounded_retry
from ..types import Norm, eps, lower_precision
from ..util.trace import annotate
from . import auxiliary as aux
from .blas3 import gemm
from .cholesky import potrf, potrs
from .lu import getrf, getrs


class MixedResult(NamedTuple):
    """Mixed-precision solve result.  ``converged`` is the contract — a
    mixed driver NEVER raises on mere non-convergence (the reference
    returns its iter count the same way); ``health`` carries the full
    HealthInfo of whichever attempt produced X."""
    X: Matrix
    iters: int
    converged: bool
    health: HealthInfo | None = None


def _info_opts(opts: Options | None) -> dict:
    """Internal factor calls always run under ErrorPolicy.Info: the
    low-precision factor is EXPECTED to fail on hard inputs (that is what
    the refinement loop and fallback are for), so its health is data, not
    an exception."""
    o = dict(opts or {})
    o[Option.ErrorPolicy] = ErrorPolicy.Info
    return o


def _cast_matrix(M, dt) -> Matrix:
    return Matrix(M.storage.astype(dt), M.io, M.jo, M._mt, M._nt, M.op)


def _residual(A: Matrix, X: Matrix, B: Matrix, opts) -> Matrix:
    """R = B - A X via the (mesh-aware) gemm driver — A is never
    densified (ref: gesv_mixed.cc residual gemm)."""
    return gemm(-1.0, A, X, 1.0, _cast_matrix(B, X.dtype), opts)


def _refine(A: Matrix, B: Matrix, solve_lo, opts: Options | None):
    """Shared IR loop (ref: gesv_mixed.cc body) as ONE lax.while_loop."""
    itermax = get_option(opts, Option.MaxIterations)
    n = A.m
    anorm = aux.norm(Norm.Inf, A)
    # Option.Tolerance overrides the eps*sqrt(n) default (ref: enums.hh
    # Tolerance; gesv_mixed.cc cte)
    t = get_option(opts, Option.Tolerance)
    tol = t if t is not None else eps(A.dtype) * math.sqrt(n)

    x0 = solve_lo(B)
    r0 = _residual(A, x0, B, opts)

    def is_conv(x, r):
        # per-column test (ref: gesv_mixed.cc:188-193 iterRefConverged uses
        # colNorms(Max) — a block-global max could declare a badly scaled
        # column converged on the strength of another column's large ||x||)
        rn = aux.col_norms(r)
        xn = aux.col_norms(x)
        return jnp.all(rn <= xn * anorm * tol)

    def cond(state):
        _, _, it, conv = state
        return jnp.logical_not(conv) & (it < itermax)

    def body(state):
        x, r, it, _ = state
        x = aux.add(1.0, solve_lo(r), 1.0, x)
        r = _residual(A, x, B, opts)
        return x, r, it + 1, is_conv(x, r)

    # slate-lint: disable=COL007 -- the stop flag comes from col_norms, whose reductions are collective: every rank holds the identical replicated norms and agrees on the trip count
    x, r, it, conv = lax.while_loop(
        cond, body, (x0, r0, jnp.asarray(0), is_conv(x0, r0)))
    return x, it, conv


def _mixed_health(fh, x, it, ok) -> HealthInfo:
    """Health of a refine attempt: low-precision factor record + final-x
    finiteness; converged is the IR verdict."""
    h = _health.merge(fh, _health.from_result(x.storage.data))
    return h._replace(iters=jnp.asarray(it, jnp.int32),
                      converged=jnp.asarray(ok))


def _full_lu_attempt(A, B, opts):
    """Full-precision fallback attempt (ref: gesv_mixed_gmres.cc:58-77)."""
    F, fh = getrf(A, _info_opts(opts))
    X = getrs(F, B, opts)
    return X, _health.merge(fh, _health.from_result(X.storage.data))


def _full_chol_attempt(A, B, opts):
    L, fh = potrf(A, _info_opts(opts))
    X = potrs(L, B, opts)
    return X, _health.merge(fh, _health.from_result(X.storage.data))


def _finish_mixed(x, it, h, fallback, opts):
    """Route the optional full-precision fallback through the shared
    bounded-retry policy (eager-only; traced calls report health as-is)."""
    fallbacks = ([fallback] if get_option(opts, Option.UseFallbackSolver)
                 else [])
    x, h, used = bounded_retry((x, h), fallbacks, dtype=x.dtype,
                               max_retries=1)
    return MixedResult(x, it, h.ok, h)


@annotate("slate.gesv_mixed")
def gesv_mixed(A: Matrix, B, opts: Options | None = None) -> MixedResult:
    """LU in low precision + IR to working precision
    (ref: src/gesv_mixed.cc).

    ``Option.Speculate = on`` (resolved once here) swaps the low-precision
    factor for the RBT-preconditioned NoPiv fast path (lu.getrf_rbt): the
    IR loop already certifies the solve against the working-precision A,
    so a bad NoPiv factor reads as non-convergence and the existing
    full-precision fallback engages — no extra certificate needed."""
    lo = lower_precision(A.dtype)
    Alo = _cast_matrix(A, lo)
    if resolve_speculate(opts):
        from .lu import getrf_rbt
        F, fh = getrf_rbt(Alo, _info_opts(opts))
    else:
        F, fh = getrf(Alo, _info_opts(opts))

    def solve_lo(R):
        return _cast_matrix(getrs(F, _cast_matrix(R, lo), opts), A.dtype)

    x, it, ok = _refine(A, B, solve_lo, opts)
    return _finish_mixed(x, it, _mixed_health(fh, x, it, ok),
                         lambda: _full_lu_attempt(A, B, opts), opts)


@annotate("slate.posv_mixed")
def posv_mixed(A: HermitianMatrix, B, opts: Options | None = None
               ) -> MixedResult:
    """Cholesky in low precision + IR (ref: src/posv_mixed.cc)."""
    lo = lower_precision(A.dtype)
    Alo = HermitianMatrix._from_view(_cast_matrix(A, lo), A.uplo)
    L, fh = potrf(Alo, _info_opts(opts))

    def solve_lo(R):
        return _cast_matrix(potrs(L, _cast_matrix(R, lo), opts), A.dtype)

    x, it, ok = _refine(A, B, solve_lo, opts)
    return _finish_mixed(x, it, _mixed_health(fh, x, it, ok),
                         lambda: _full_chol_attempt(A, B, opts), opts)


# ---------------------------------------------------------------- GMRES-IR

def _gmres_ir(A: Matrix, B: Matrix, solve_lo, opts: Options | None,
              restart: int = 10):
    """Blocked right-preconditioned restarted GMRES in working precision
    (ref: src/gesv_mixed_gmres.cc:24-117; restart depth 10, itermax 30).

    All nrhs columns advance one shared Arnoldi loop in lockstep — each
    column keeps its own Krylov basis and Hessenberg, stored batched.  The
    basis vectors are skinny [n, nrhs] blocks (replicating them is cheap);
    every matvec is the distributed gemm and every preconditioner
    application is the distributed low-precision solve."""
    itermax = get_option(opts, Option.MaxIterations)
    n = A.m
    dt = A.dtype
    anorm = aux.norm(Norm.Inf, A)
    t = get_option(opts, Option.Tolerance)
    tol = t if t is not None else eps(dt) * math.sqrt(n)
    bd = B.to_dense()                         # skinny [n, nrhs]
    nrhs = bd.shape[1]

    def mat_vec(z):
        """A @ z for a skinny block z [n, nrhs] (distributed gemm)."""
        Z = Matrix(TileStorage.from_dense(z, A.nb, B.nb, A.grid))
        return gemm(1.0, A, Z, 0.0, None, opts).to_dense()

    def prec(z):
        Z = Matrix(TileStorage.from_dense(z, A.nb, B.nb, A.grid))
        return solve_lo(Z).to_dense()

    def arnoldi(x):
        """One restart cycle for every column at once."""
        r = bd - mat_vec(x)
        beta = jnp.linalg.norm(r, axis=0)                  # [nrhs]
        conv = (jnp.max(jnp.abs(r), axis=0) <=
                jnp.max(jnp.abs(x), axis=0) * anorm * tol + 1e-300)
        safe_beta = jnp.where(beta > 0, beta, jnp.ones_like(beta))
        V0 = jnp.zeros((restart + 1, n, nrhs), dt)
        V0 = V0.at[0].set(r / safe_beta)
        H0 = jnp.zeros((restart + 1, restart, nrhs), dt)

        def arn_step(i, carry):
            V, H = carry
            vi = lax.dynamic_index_in_dim(V, i, axis=0, keepdims=False)
            w = mat_vec(prec(vi))                          # [n, nrhs]
            # modified Gram-Schmidt against all stored vectors (rows > i
            # are zero, so their coefficients vanish identically)
            def mgs(t, wh):
                w, H = wh
                vt = lax.dynamic_index_in_dim(V, t, axis=0, keepdims=False)
                h = jnp.sum(jnp.conj(vt) * w, axis=0)      # [nrhs]
                live = t <= i
                h = jnp.where(live, h, jnp.zeros_like(h))
                H = H.at[t, i].set(h)
                return w - vt * h[None, :], H

            w, H = lax.fori_loop(0, restart + 1, mgs, (w, H))
            hn = jnp.linalg.norm(w, axis=0)
            H = H.at[i + 1, i].set(hn.astype(dt))
            # happy breakdown (hn == 0): keep a zero basis vector instead of
            # NaN — the column is already converged in this subspace
            ok = hn[None, :] > 0
            V = V.at[i + 1].set(jnp.where(ok, w / jnp.where(ok, hn, 1), 0))
            return V, H

        V, H = lax.fori_loop(0, restart, arn_step, (V0, H0))

        # per-column least squares: min_y ||beta e1 - H_j y|| via batched QR
        # of the (restart+1) x restart Hessenberg (ref uses Givens rotation
        # updates — same triangular solve, built all at once here)
        Hc = jnp.transpose(H, (2, 0, 1))                   # [nrhs, m+1, m]
        rhs = jnp.zeros((nrhs, restart + 1), dt).at[:, 0].set(
            beta.astype(dt))
        Q, R = jnp.linalg.qr(Hc)                           # reduced QR
        qb = jnp.einsum("nij,ni->nj", jnp.conj(Q), rhs)    # [nrhs, m]
        # guard (near-)singular R (breakdown / nearly-converged columns):
        # a relative threshold, so subnormal diagonals can't divide to Inf
        diag = jnp.abs(jnp.diagonal(R, axis1=-2, axis2=-1))
        floor = eps(dt) * jnp.max(diag, axis=-1, keepdims=True)
        shift = jnp.where(diag > floor, 0.0, 1.0).astype(dt)
        R = R + shift[..., None] * jnp.eye(restart, dtype=dt)[None]
        y = jax.scipy.linalg.solve_triangular(R, qb[..., None],
                                              lower=False)[..., 0]
        # x += M^-1 (V y)   (right preconditioning is linear)
        vy = jnp.einsum("inr,ir->nr", V[:restart], y.T)
        dx = prec(vy)
        x_new = x + dx
        return jnp.where(conv[None, :], x, x_new), conv

    def cond(state):
        _, it, conv = state
        return jnp.logical_not(jnp.all(conv)) & (it < itermax)

    def body(state):
        x, it, _ = state
        x, conv = arnoldi(x)
        return x, it + restart, conv

    x0 = jnp.zeros_like(bd)
    # slate-lint: disable=COL007 -- conv derives from collectively-reduced Arnoldi norms, replicated across the mesh: all ranks agree on the trip count
    x, it, conv = lax.while_loop(
        cond, body, (x0, jnp.asarray(0), jnp.zeros((nrhs,), bool)))
    X = Matrix(TileStorage.from_dense(x, B.mb, B.nb, B.grid))
    return X, it, jnp.all(conv)


@annotate("slate.gesv_mixed_gmres")
def gesv_mixed_gmres(A: Matrix, B, opts: Options | None = None
                     ) -> MixedResult:
    """ref: src/gesv_mixed_gmres.cc"""
    lo = lower_precision(A.dtype)
    Alo = _cast_matrix(A, lo)
    F, fh = getrf(Alo, _info_opts(opts))

    def solve_lo(R):
        return _cast_matrix(getrs(F, _cast_matrix(R, lo), opts), A.dtype)

    x, it, ok = _gmres_ir(A, B, solve_lo, opts)
    return _finish_mixed(x, it, _mixed_health(fh, x, it, ok),
                         lambda: _full_lu_attempt(A, B, opts), opts)


@annotate("slate.posv_mixed_gmres")
def posv_mixed_gmres(A: HermitianMatrix, B, opts: Options | None = None
                     ) -> MixedResult:
    """ref: src/posv_mixed_gmres.cc"""
    lo = lower_precision(A.dtype)
    Alo = HermitianMatrix._from_view(_cast_matrix(A, lo), A.uplo)
    L, fh = potrf(Alo, _info_opts(opts))

    def solve_lo(R):
        return _cast_matrix(potrs(L, _cast_matrix(R, lo), opts), A.dtype)

    x, it, ok = _gmres_ir(A, B, solve_lo, opts)
    return _finish_mixed(x, it, _mixed_health(fh, x, it, ok),
                         lambda: _full_chol_attempt(A, B, opts), opts)
