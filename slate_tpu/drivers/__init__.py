from . import blas3  # noqa: F401
