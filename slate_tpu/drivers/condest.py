"""Condition estimation: Hager/Higham 1-norm estimator + gecondest /
trcondest.

Analog of the reference's condition-estimation group (ref:
src/gecondest.cc:1-197, src/trcondest.cc, src/internal/internal_norm1est.cc:
1-523 — the LAPACK xLACN2 iteration distributed over tiles).  Here the
estimator is ONE lax.while_loop over (solve, solve^H) pairs — each solve is
a pair of blocked triangular solves, so the whole estimate jits into a
single XLA program.

Failure contract: a singular factor poisons the appliers (NaN/Inf flow
through the triangular solves), and NaN compares False everywhere — an
unguarded xLACN2 loop then returns a NaN estimate AND corrupts its own
convergence logic (``argmax`` of an all-NaN vector, a ``done`` flag that
never sets).  The loop state here carries an explicit ``bad`` flag checked
on every applier output; ``gecondest``/``trcondest`` resolve a poisoned
estimate to ``rcond = 0`` ("singular as far as the estimate is concerned",
the LAPACK convention) — never NaN — and report ``nonfinite=True`` through
``HealthInfo`` under ``ErrorPolicy.Info``.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.matrix import TriangularMatrix
from ..exceptions import slate_error
from ..internal.qr import phase_of
from ..options import ErrorPolicy, Options
from ..robust import health as _health
from ..types import Norm, Uplo
from ..util.trace import annotate


def _norm1est_flag(apply_inv, apply_inv_h, n: int, dtype, itmax: int = 5):
    """Guarded Hager/Higham body: returns ``(est, bad)`` where ``bad``
    flags any non-finite applier output.  Once bad, the loop freezes its
    state and exits (NaN would otherwise sail through every comparison
    with ``done`` never setting)."""
    rdt = jnp.zeros((), dtype).real.dtype

    def body(state):
        x, est_old, jprev, k, done, bad = state
        y = apply_inv(x)
        y_ok = jnp.all(jnp.isfinite(jnp.abs(y)))
        est = jnp.sum(jnp.abs(y))
        xi = phase_of(y)
        z = apply_inv_h(xi)
        z_ok = jnp.all(jnp.isfinite(jnp.abs(z)))
        newly_bad = ~(y_ok & z_ok)
        j = jnp.argmax(jnp.abs(z))
        # convergence: repeated index or no growth in the dual norm
        zj = jnp.abs(z)[j]
        ztx = jnp.real(jnp.vdot(z, x))
        stop = (zj <= ztx) | (j == jprev) | (est <= est_old)
        x_new = jnp.zeros((n,), dtype).at[j].set(1)
        est_out = jnp.maximum(est, est_old)
        freeze = done | newly_bad
        return (jnp.where(freeze, x, x_new),
                jnp.where(freeze, est_old, est_out),
                jnp.where(freeze, jprev, j), k + 1,
                done | stop | newly_bad, bad | newly_bad)

    def cond(state):
        _, _, _, k, done, _ = state
        return (k < itmax) & jnp.logical_not(done)

    x0 = jnp.full((n,), 1.0 / n, dtype)
    state = (x0, jnp.zeros((), rdt), jnp.asarray(-1), jnp.asarray(0),
             jnp.asarray(False), jnp.asarray(False))
    _, est, _, _, _, bad = lax.while_loop(cond, body, state)

    # alternating-magnitude safeguard vector (LAPACK xLACN2 final stage)
    i = jnp.arange(n)
    v = ((-1.0) ** i * (1.0 + i / max(n - 1, 1))).astype(dtype)
    est2 = 2.0 * jnp.sum(jnp.abs(apply_inv(v))) / (3.0 * n)
    bad = bad | ~jnp.isfinite(est2)
    est = jnp.maximum(est, jnp.where(jnp.isfinite(est2), est2, 0.0))
    return est, bad


def norm1est(apply_inv, apply_inv_h, n: int, dtype, itmax: int = 5):
    """Estimate ||A^-1||_1 given y = A^-1 x and z = A^-H x appliers
    (Hager/Higham, ref internal_norm1est.cc / LAPACK xLACN2).

    Runs as a lax.while_loop; jittable.  Returns a scalar estimate —
    ``+inf`` (not NaN) when the appliers produce non-finite values, i.e.
    the factor is singular as far as the estimate is concerned."""
    est, bad = _norm1est_flag(apply_inv, apply_inv_h, n, dtype, itmax)
    return jnp.where(bad, jnp.asarray(jnp.inf, est.dtype), est)


def _condest_result(name, rcond, bad, dtype, opts):
    """Shared policy resolution for the condition estimators: rcond = 0
    IS the failure resolution (never a raise, never NaN — matching
    LAPACK, whose xxCON quietly returns rcond = 0 for a singular factor);
    Info additionally returns the HealthInfo with ``nonfinite`` set."""
    if _health.error_policy(opts) is ErrorPolicy.Info:
        h = _health.healthy(dtype)._replace(
            nonfinite=bad, converged=jnp.logical_not(bad))
        return rcond, h
    return rcond


@annotate("slate.gecondest")
def gecondest(F, anorm, opts: Options | None = None, norm: Norm = Norm.One):
    """Reciprocal condition estimate from LU factors (ref:
    src/gecondest.cc): rcond = 1 / (||A|| * est(||A^-1||)).

    ``F`` is an LUFactors; ``anorm`` the 1-norm of the original A (compute
    with st.norm(Norm.One, A) before factoring, as the reference's tester
    does).  A singular/non-finite factor returns ``rcond = 0`` — never
    NaN; under ``ErrorPolicy.Info``, ``(rcond, HealthInfo)`` with
    ``nonfinite=True`` flagging the poisoned estimate."""
    slate_error(norm in (Norm.One, Norm.Inf), "gecondest: One or Inf norm")
    lu = F.LU.to_dense()
    n = lu.shape[0]
    perm = F.perm

    def apply_inv(x):
        # A^-1 x = U^-1 L^-1 (P x)
        xp = jnp.take(x, perm, axis=0)[:, None]
        y = lax.linalg.triangular_solve(lu, xp, left_side=True, lower=True,
                                        unit_diagonal=True)
        y = lax.linalg.triangular_solve(lu, y, left_side=True, lower=False)
        return y[:, 0]

    def apply_inv_h(x):
        # A^-H x = P^H L^-H U^-H x
        y = lax.linalg.triangular_solve(lu, x[:, None], left_side=True,
                                        lower=False, transpose_a=True,
                                        conjugate_a=True)
        y = lax.linalg.triangular_solve(lu, y, left_side=True, lower=True,
                                        transpose_a=True, conjugate_a=True,
                                        unit_diagonal=True)
        y = y[:, 0]
        return jnp.zeros_like(y).at[perm].set(y)

    if norm is Norm.Inf:
        # ||A^-1||_inf = ||A^-H||_1: swap the appliers
        apply_inv, apply_inv_h = apply_inv_h, apply_inv
    ainv, bad = _norm1est_flag(apply_inv, apply_inv_h, n, lu.dtype)
    anorm = jnp.asarray(anorm)
    bad = bad | ~jnp.isfinite(anorm)
    safe = (anorm > 0) & (ainv > 0) & ~bad
    rcond = jnp.where(safe, 1.0 / jnp.where(safe, anorm * ainv, 1.0),
                      jnp.zeros(()))
    return _condest_result("gecondest", rcond, bad, lu.dtype, opts)


@annotate("slate.trcondest")
def trcondest(R, opts: Options | None = None, norm: Norm = Norm.One):
    """Reciprocal condition estimate of a triangular matrix (ref:
    src/trcondest.cc — used on QR's R factor for least-squares
    conditioning).  rcond = 1 / (||R||_1 * est(||R^-1||_1)).  A singular/
    non-finite R returns ``rcond = 0`` — never NaN; under
    ``ErrorPolicy.Info``, ``(rcond, HealthInfo)``."""
    slate_error(isinstance(R, TriangularMatrix), "trcondest: triangular")
    slate_error(norm in (Norm.One, Norm.Inf), "trcondest: One or Inf norm")
    rd = R.to_dense()
    n = rd.shape[0]
    lower = R.uplo is Uplo.Lower
    from ..types import Diag
    unit = R.diag is Diag.Unit

    def apply_inv(x):
        return lax.linalg.triangular_solve(
            rd, x[:, None], left_side=True, lower=lower,
            unit_diagonal=unit)[:, 0]

    def apply_inv_h(x):
        return lax.linalg.triangular_solve(
            rd, x[:, None], left_side=True, lower=lower, transpose_a=True,
            conjugate_a=True, unit_diagonal=unit)[:, 0]

    a1, a2 = (apply_inv, apply_inv_h) if norm is Norm.One else (
        apply_inv_h, apply_inv)
    rinv, bad = _norm1est_flag(a1, a2, n, rd.dtype)
    rnorm = jnp.max(jnp.sum(jnp.abs(rd), axis=0)) if norm is Norm.One \
        else jnp.max(jnp.sum(jnp.abs(rd), axis=1))
    bad = bad | ~jnp.isfinite(rnorm)
    safe = (rnorm > 0) & (rinv > 0) & ~bad
    rcond = jnp.where(safe, 1.0 / jnp.where(safe, rnorm * rinv, 1.0),
                      jnp.zeros(()))
    return _condest_result("trcondest", rcond, bad, rd.dtype, opts)
