"""LU drivers: getrf (partial pivot / nopiv / tournament), getrs, gesv,
getri.

Analog of the reference's LU chain (ref: src/getrf.cc:23-240,
src/getrf_nopiv.cc, src/getrf_tntpiv.cc:455, src/getrs.cc, src/gesv.cc,
src/getri.cc / src/getriOOP.cc; method dispatch src/gesv.cc + method.hh
MethodLU).

The factorization result is ``LUFactors``: one matrix whose strictly-lower
part is unit-L and upper part U (exactly the reference's overwritten-A
convention) plus a global row-permutation vector ``perm`` with
``A[perm] = L @ U`` — the composition of the reference's per-panel Pivot
lists (ref: getrf.cc pivots bcast :112-117).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.matrix import Matrix, TriangularMatrix
from ..core.storage import TileStorage
from ..exceptions import SlateSingularError, slate_error
from ..ops.elementwise import entry_mask
from ..options import (ErrorPolicy, MethodLU, Option, Options, Target,
                       get_option, resolve_abft, resolve_target,
                       select_lu_method)
from ..parallel.dist_lu import dist_getrf
from ..robust import abft as _abft
from ..robust import faults
from ..robust import health as _health
from ..types import Diag, Op, Uplo
from ..util.trace import annotate
from .blas3 import as_root_general, trsm


class LUFactors(NamedTuple):
    """L\\U packed in one matrix + row permutation (A[perm] = L U)."""
    LU: Matrix
    perm: jax.Array

    def lower(self) -> TriangularMatrix:
        return TriangularMatrix._from_view(self.LU, Uplo.Lower, Diag.Unit)

    def upper(self) -> TriangularMatrix:
        return TriangularMatrix._from_view(self.LU, Uplo.Upper)


def _apply_row_perm(mat, perm, bound: int):
    """Apply a row permutation that displaces at most ``bound`` rows by
    touching ONLY those rows (gather + scatter of the bundle) — a full
    ``mat[perm]`` gather reads and rewrites the entire trailing matrix
    per panel.  Partial pivoting, threshold pivoting and the tournament
    placement are all products of <= nb transpositions, so bound = 2 nb.
    """
    W = perm.shape[0]
    if W == 0 or mat.shape[1] == 0:
        return mat
    k = min(W, bound)
    moved = (perm != jnp.arange(W)).astype(jnp.int32)
    _, idx = lax.top_k(moved, k)
    return mat.at[idx].set(mat[perm[idx]], unique_indices=True)


def _getrf_dense_blocked(a, nb: int, method: str, tau: float = 1.0,
                         mpt: int = 4, depth: int = 2, abft: bool = False):
    """Blocked right-looking LU, statically-shaped panels (unrolled).

    Panel factor delegates to XLA's native pivoted LU (the analog of the
    reference's lapack panel kernel); the nopiv and tournament panels
    route through internal/getrf.py's tuned seams, which dispatch to the
    fused Pallas panel kernels (internal/pallas_lu.py) when the plan
    cache selects them (slate_tpu.tune, docs/TUNING.md).  The trailing
    row exchange touches
    only the <= 2 nb displaced rows, and the U12 solve is one MXU gemm
    against the inverted unit-L11 (internal/trsm.py tri_inv_lower) —
    ref: getrf.cc:174-215 trailing task.  ``tau`` < 1 switches to
    threshold pivoting (Option.PivotThreshold); ``mpt``
    (Option.MaxPanelThreads) splits the tournament panel into ~mpt
    independent row blocks (the analog of panel threads: more threads =
    more, smaller blocks) and ``depth`` (Option.Depth) is the
    reduction-tree fan-in.

    With ``abft`` (the resolved Option.Abft boolean) every step carries
    Huang-Abraham checksums (robust/abft.py): the packed panel is
    verified against its pre-factor input, the U12 solve against the
    pre-solve row's checksums, and the trailing update against the
    expected checksum deltas — each an O(n^2)-per-step check that
    locates and repairs a single corrupted element in place.  Returns
    ``(factor, perm, AbftCounts)``."""
    from ..internal.getrf import (panel_lu, panel_lu_nopiv,
                                  panel_lu_threshold, panel_lu_tournament)
    from ..internal.trsm import tri_inv_lower
    m, n = a.shape
    kmax = min(m, n)
    perm_g = jnp.arange(m)
    counts = _abft.zero_counts()
    for k0 in range(0, kmax, nb):
        k1 = min(k0 + nb, kmax)
        w = k1 - k0
        kt = k0 // nb
        pan = a[k0:, k0:k1]
        if method == "nopiv":
            lu, perm = panel_lu_nopiv(pan)
        elif method == "tntpiv":
            bh = pan.shape[0]
            br = max(nb, (-(-bh // (mpt * nb))) * nb)
            lu, perm = panel_lu_tournament(pan, block_rows=br, arity=depth)
        elif tau < 1.0:
            lu, perm = panel_lu_threshold(pan, tau)
        else:
            lu, perm = panel_lu(pan)
        lu = faults.maybe_corrupt("post_panel", lu)
        if abft:
            lu, det, cor, pi, _ = _abft.lu_panel_check(pan, lu, perm,
                                                       n_ctx=m)
            counts = _abft.add_counts(counts, _abft.count_event(
                det, cor, kt + pi // nb, kt))
        a = a.at[k0:, k0:k1].set(lu)
        if method != "nopiv":
            a = a.at[k0:, :k0].set(_apply_row_perm(a[k0:, :k0], perm, 2 * w))
            a = a.at[k0:, k1:].set(_apply_row_perm(a[k0:, k1:], perm, 2 * w))
            perm_g = perm_g.at[k0:].set(perm_g[k0:][perm])
        if k1 < n:
            l11 = lu[:w, :w]
            r12 = a[k0:k1, k1:]
            u12 = tri_inv_lower(l11, unit_diag=True) @ r12
            if abft:
                u12, det, cor, _, pj = _abft.left_product_check(
                    l11, u12, jnp.sum(r12, axis=1), jnp.sum(r12, axis=0),
                    unit=True, n_ctx=m)
                counts = _abft.add_counts(counts, _abft.count_event(
                    det, cor, kt, (k1 + pj) // nb))
            a = a.at[k0:k1, k1:].set(u12)
            if k1 < m:
                l21 = lu[w:, :w]
                if abft:
                    tb = a[k1:, k1:]
                    exp_row = (jnp.sum(tb, axis=1)
                               - l21 @ jnp.sum(u12, axis=1))
                    exp_col = (jnp.sum(tb, axis=0)
                               - jnp.sum(l21, axis=0) @ u12)
                    tb, ev = _abft.sum_check(tb - l21 @ u12, exp_row,
                                             exp_col, n_ctx=m, nb=nb,
                                             row0=k1, col0=k1)
                    counts = _abft.add_counts(counts, ev)
                    a = a.at[k1:, k1:].set(tb)
                else:
                    a = a.at[k1:, k1:].add(-(l21 @ u12))
    return a, perm_g, counts


@annotate("slate.getrf")
def getrf(A: Matrix, opts: Options | None = None) -> LUFactors:
    """LU with partial pivoting (ref: src/getrf.cc).

    Failure contract (Option.ErrorPolicy, see docs/ROBUSTNESS.md): eager
    calls raise :class:`SlateSingularError` on an exactly-zero or
    non-finite pivot; under ``info`` the return is
    ``(LUFactors, HealthInfo)``."""
    return _getrf(A, opts, "partial")


@annotate("slate.getrf_nopiv")
def getrf_nopiv(A: Matrix, opts: Options | None = None) -> LUFactors:
    """LU without pivoting (ref: src/getrf_nopiv.cc)."""
    return _getrf(A, opts, "nopiv")


@annotate("slate.getrf_tntpiv")
def getrf_tntpiv(A: Matrix, opts: Options | None = None) -> LUFactors:
    """CALU tournament-pivoting LU (ref: src/getrf_tntpiv.cc)."""
    return _getrf(A, opts, "tntpiv")


@jax.tree_util.register_pytree_node_class
class RBTFactors:
    """Factors of the butterfly-preconditioned pivot-free LU (getrf_rbt):
    ``F`` is the NoPiv LUFactors of the TRANSFORMED padded matrix
    A~ = U^T diag(A, I_pad) V, ``u``/``v`` the two depth-2 butterflies
    (internal/rbt.py level tuples) and ``n`` the logical (unpadded) size.
    getrs dispatches on this type: x = V (A~^-1 (U^T [b; 0]))[:n]."""

    def __init__(self, F: LUFactors, u, v, n: int):
        self.F = F
        self.u = u
        self.v = v
        self.n = n

    def tree_flatten(self):
        return (self.F, self.u, self.v), (self.n,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], children[2], aux[0])

    def __repr__(self):
        return (f"RBTFactors(n={self.n}, padded={self.F.LU.m}, "
                f"depth={len(self.u)})")


# host-static butterfly seed: the transform is a preconditioner, not a
# security primitive — determinism (bit-reproducible factors, replayable
# fault tests) is worth more than per-call entropy
_RBT_SEED = 0x5B17


def _info(opts: Options | None) -> dict:
    o = dict(opts or {})
    o[Option.ErrorPolicy] = ErrorPolicy.Info
    return o


@annotate("slate.getrf_rbt")
def getrf_rbt(A: Matrix, opts: Options | None = None):
    """Butterfly-preconditioned pivot-free LU (PRBT: Parker '95, Baboulin
    et al. '13): A~ = U^T diag(A, I_pad) V with depth-2 recursive random
    butterflies (internal/rbt.py, O(n^2) elementwise), then
    :func:`getrf_nopiv` on A~ — panels are pure MXU gemms against
    triangular inverses, no pivot hunt.  Returns :class:`RBTFactors`.

    This is the mechanism of the gesv speculative fast path; the policy
    (Option.Speculate resolution, iterative refinement, residual
    certification, escalation to pivoted LU on a failed certificate)
    lives in robust/recovery.py.  Health: the NoPiv factor's pivot/growth
    record over the TRANSFORMED matrix.

    Mesh target: the two-sided transform is applied on the block-cyclic
    storage via all-gathered row/column strips (parallel/dist_lu.py
    dist_rbt_two_sided) when the padded size is butterfly-divisible;
    otherwise falls back to the dense single-device transform."""
    from ..internal import rbt
    slate_error(A.m == A.n, "getrf_rbt: square matrices (gesv path)")
    n, nb = A.m, A.nb
    target = resolve_target(opts, A)
    o = _info(opts)
    if target is Target.mesh and A.grid.mesh is not None:
        from ..parallel.dist_lu import dist_rbt_two_sided
        An = as_root_general(A, nb, nb, grid=A.grid)
        st = An.storage
        m_pad = st.Mt * nb
        if m_pad % (1 << rbt.DEFAULT_DEPTH) == 0:
            u = rbt.generate(m_pad, seed=_RBT_SEED, dtype=A.dtype)
            v = rbt.generate(m_pad, seed=_RBT_SEED + 1, dtype=A.dtype)
            data = faults.maybe_corrupt("input", st.data)
            data = dist_rbt_two_sided(data, u, v, A.grid, n)
            data = faults.maybe_corrupt("post_rbt", data)
            At = Matrix(TileStorage(data, m_pad, m_pad, nb, nb, st.grid))
            Fi, fh = getrf_nopiv(At, o)
            return _health.finalize("getrf_rbt", RBTFactors(Fi, u, v, n),
                                    fh, opts, _singular("getrf_rbt"))
    nt = rbt.padded_size(n)
    ad = faults.maybe_corrupt("input", A.to_dense())
    abar = jnp.zeros((nt, nt), ad.dtype).at[:n, :n].set(ad)
    if nt > n:
        r = jnp.arange(n, nt)
        abar = abar.at[r, r].set(1)
    u = rbt.generate(nt, seed=_RBT_SEED, dtype=ad.dtype)
    v = rbt.generate(nt, seed=_RBT_SEED + 1, dtype=ad.dtype)
    at = rbt.transform(abar, u, v)
    at = faults.maybe_corrupt("post_rbt", at)
    At = Matrix(TileStorage.from_dense(at, nb, nb, A.grid))
    Fi, fh = getrf_nopiv(At, o)
    return _health.finalize("getrf_rbt", RBTFactors(Fi, u, v, n), fh,
                            opts, _singular("getrf_rbt"))


def _lu_health(factor_arr, minpiv, minidx, amax):
    """Assemble the LU HealthInfo: pivot record from the panel min-pivot
    trace + whole-factor finiteness + pivot-growth ratio."""
    h = _health.healthy(factor_arr.dtype)
    fmax = jnp.max(jnp.abs(factor_arr))
    bad = (minpiv == 0) | ~jnp.isfinite(minpiv)
    return h._replace(
        nonfinite=~jnp.all(jnp.isfinite(factor_arr)),
        info=jnp.where(bad, minidx.astype(jnp.int32) + 1, 0),
        min_pivot=minpiv.astype(h.min_pivot.dtype),
        min_pivot_index=minidx.astype(jnp.int32),
        growth=jnp.where(amax > 0, fmax / amax,
                         jnp.inf).astype(h.growth.dtype),
    )


def _abft_fold(h, counts: "_abft.AbftCounts"):
    """Fold checksum-verification counters into a HealthInfo: a detected
    but uncorrected strike flips ``h.ok`` (health.py), which is what the
    recovery ladder escalates on."""
    return h._replace(abft_detected=counts.detected,
                      abft_corrected=counts.corrected,
                      abft_site=counts.site)


def _getrf(A: Matrix, opts: Options | None, method: str):
    target = resolve_target(opts, A)
    nb = A.nb
    tau = float(get_option(opts, Option.PivotThreshold))
    mpt = int(get_option(opts, Option.MaxPanelThreads))
    depth = int(get_option(opts, Option.Depth))
    abft = resolve_abft(opts)

    if target is Target.mesh and A.grid.mesh is not None:
        from ..parallel.dist_chol import SUPERBLOCKS, superblock
        slate_error(A.m == A.n, "mesh getrf: square matrices (gesv path)")
        An = as_root_general(A, nb, nb, grid=A.grid)
        st = An.storage
        data_in = faults.maybe_corrupt("input", st.data)
        amax = jnp.max(jnp.abs(data_in))
        la = max(1, int(get_option(opts, Option.Lookahead)))
        data, perm, minpiv, minidx, adet, acor, asite = dist_getrf(
            data_in, st.Nt, A.grid, st.n, method,
            ib=get_option(opts, Option.InnerBlocking),
            sb=superblock(st.Nt, SUPERBLOCKS * la),
            tau=tau, mpt=mpt, depth=depth, abft=abft)
        out = TileStorage(data, st.m, st.n, nb, nb, st.grid)
        # restore the pad-region-zero invariant (final ragged panel is
        # identity-augmented inside the factorization)
        clean = out.canonical() * entry_mask(st.m, st.n, nb, nb).astype(
            out.dtype)
        out = out.with_canonical(clean)
        F = LUFactors(Matrix(out), perm[: st.m])
        h = _abft_fold(_lu_health(clean, minpiv, minidx, amax),
                       _abft.AbftCounts(adet, acor, asite))
        return _health.finalize(f"getrf[{method}]", F, h, opts,
                                _singular(f"getrf[{method}]"))

    ad = faults.maybe_corrupt("input", A.to_dense())
    amax = jnp.max(jnp.abs(ad))
    lu, perm, counts = _getrf_dense_blocked(ad, nb, method, tau=tau,
                                            mpt=mpt, depth=depth, abft=abft)
    st = TileStorage.from_dense(lu, nb, nb, A.grid)
    F = LUFactors(Matrix(st), perm)
    udiag = jnp.abs(jnp.diagonal(lu))
    minidx = jnp.argmin(udiag)
    h = _abft_fold(_lu_health(lu, udiag[minidx], minidx, amax), counts)
    return _health.finalize(f"getrf[{method}]", F, h, opts,
                            _singular(f"getrf[{method}]"))


def _singular(name: str):
    return lambda h: SlateSingularError(
        f"{name}: exactly-singular or non-finite factor "
        f"({h.describe()})", info=int(h.info))


class OocLUFactors(NamedTuple):
    """Out-of-core LU result: L\\U packed in one HOST numpy array + global
    row permutation (A[perm] = L U).  Host-resident because the whole
    point of getrf_ooc is that the factor need not fit device memory."""
    LU: "np.ndarray"  # noqa: F821 — host array, numpy imported lazily
    perm: "np.ndarray"  # noqa: F821


def _ooc_lu_health(lu_host, minpiv: float, minidx: int, amax: float):
    """LU health from HOST reductions (the OOC factor stays off-device)."""
    import numpy as np
    h = _health.healthy(lu_host.dtype)
    fmax = float(np.max(np.abs(lu_host))) if lu_host.size else 0.0
    bad = (minpiv == 0.0) or not np.isfinite(minpiv)
    growth = fmax / amax if amax > 0 else float("inf")
    return h._replace(
        nonfinite=jnp.asarray(not bool(np.all(np.isfinite(lu_host)))),
        info=jnp.asarray(minidx + 1 if bad else 0, jnp.int32),
        min_pivot=jnp.asarray(minpiv, h.min_pivot.dtype),
        min_pivot_index=jnp.asarray(minidx, jnp.int32),
        growth=jnp.asarray(growth, h.growth.dtype),
    )


@annotate("slate.getrf_ooc")
def getrf_ooc(a, nb: int | None = None, opts: Options | None = None,
              checkpoint=None, resume: bool = False):
    """Out-of-core partially-pivoted LU of a HOST-resident matrix.

    ``a`` is a dense host numpy array that need not fit device memory: a
    :class:`~slate_tpu.core.storage.TileMap` streams the pivot panel and
    one trailing block column at a time through HBM, prefetching the next
    trailing column while the current one updates (PR 15's
    hide-communication discipline on the host-device axis).  Returns
    :class:`OocLUFactors`; Option.ErrorPolicy resolves failures exactly
    like :func:`getrf`.

    Durability (docs/ROBUSTNESS.md "Durable jobs"): with a ``checkpoint``
    :class:`~slate_tpu.robust.checkpoint.CheckpointManager` the host tile
    map plus the accumulated permutation are snapshotted at panel-step
    boundaries; ``resume=True`` verifies the latest snapshot's ABFT
    checksums before continuing and is bit-identical to the
    uninterrupted run, refusing with a typed ``SlateCheckpointError``
    on torn/stale/corrupt state.
    """
    import numpy as np
    from ..core.storage import TileMap
    from ..internal.getrf import ooc_lu_panel, ooc_lu_trailing
    from ..robust.checkpoint import ensure_fingerprint, ooc_fingerprint
    from ..tune import ooc_panel_width

    if resume:
        slate_error(checkpoint is not None,
                    "getrf_ooc: resume=True needs a checkpoint manager")
        ck = checkpoint.load(op="getrf_ooc")
        m, n = ck.matrix.shape
        nb = int(ck.meta["nb"])
        fp = ooc_fingerprint("getrf_ooc", m, n, nb, ck.meta["dtype"])
        ensure_fingerprint(ck, fp)
        tm = TileMap(ck.matrix, nb, nb)
        perm_g = ck.extras["perm"].astype(np.int64, copy=True)
        amax = float(ck.extras["amax"][()])
        k_start = int(ck.step)
    else:
        ad = np.asarray(a)
        slate_error(ad.ndim == 2, "getrf_ooc: 2D host matrix")
        m, n = ad.shape
        nb = int(nb) if nb else ooc_panel_width(max(m, n), ad.dtype.name)
        fp = ooc_fingerprint("getrf_ooc", m, n, nb, ad.dtype.name)
        tm = TileMap(ad, nb, nb)
        perm_g = np.arange(m, dtype=np.int64)
        amax = float(np.max(np.abs(ad))) if ad.size else 0.0
        k_start = 0

    kmax = min(m, n)
    steps = list(range(0, kmax, nb))
    for si in range(k_start, len(steps)):
        k0 = steps[si]
        k1 = min(k0 + nb, kmax)
        if checkpoint is not None and checkpoint.should_save(si):
            checkpoint.save(
                "getrf_ooc", si, tm.host_array(), nb, nb, fp,
                extras={"perm": perm_g,
                        "amax": np.asarray(amax, np.float64)})
        panel = tm.fetch(k0, m, k0, k1)
        lu, perm = ooc_lu_panel(panel)
        perm_h = np.asarray(perm)
        if k0:
            tm.permute_rows(k0, 0, k0, perm_h)
        perm_g[k0:] = perm_g[k0:][perm_h]
        tm.store(k0, m, k0, k1, lu)
        trail = list(range(k1, n, nb))
        if trail:
            tm.prefetch(k0, m, trail[0], min(trail[0] + nb, n))
        for ti, j0 in enumerate(trail):
            j1 = min(j0 + nb, n)
            colj = tm.fetch(k0, m, j0, j1)
            if ti + 1 < len(trail):
                tm.prefetch(k0, m, trail[ti + 1],
                            min(trail[ti + 1] + nb, n))
            tm.store(k0, m, j0, j1, ooc_lu_trailing(colj, lu, perm))
    lu_h = tm.host_array().copy()
    udiag = np.abs(np.diagonal(lu_h[:kmax, :kmax]))
    udiag = np.where(np.isnan(udiag), 0.0, udiag)
    minidx = int(np.argmin(udiag)) if udiag.size else 0
    minpiv = float(udiag[minidx]) if udiag.size else float("inf")
    h = _ooc_lu_health(lu_h, minpiv, minidx, amax)
    return _health.finalize("getrf_ooc", OocLUFactors(lu_h, perm_g), h,
                            opts, _singular("getrf_ooc"))


def _getrs_rbt(F: RBTFactors, B, opts: Options | None) -> Matrix:
    """getrs body for RBT factors: the RAW transformed solve
    x = V (A~^-1 (U^T [b; 0]))[:n] — no refinement, no certification
    (those belong to the speculative gesv seam, robust/recovery.py).
    B is skinny, so the butterfly applies on the dense RHS are O(n nrhs)
    and mesh-safe; the inner triangular sweeps ride the tiled solve."""
    from ..internal import rbt
    slate_error(F.n == B.m, "getrs: dims")
    nt = F.F.LU.m
    bd = B.to_dense()
    bbar = jnp.zeros((nt, bd.shape[1]), bd.dtype).at[: F.n].set(bd)
    yt = rbt.apply_left_t(F.u, bbar)
    Yt = Matrix(TileStorage.from_dense(yt, F.F.LU.nb, B.nb, B.grid))
    Z = getrs(F.F, Yt, opts)
    xbar = rbt.apply_left(F.v, Z.to_dense())
    return Matrix(TileStorage.from_dense(xbar[: F.n], B.mb, B.nb, B.grid))


@annotate("slate.getrs")
def getrs(F: LUFactors, B, opts: Options | None = None) -> Matrix:
    """Solve with LU factors: X = U^-1 L^-1 B[perm] (ref: src/getrs.cc).
    :class:`RBTFactors` dispatch to the butterfly transform sandwich.

    On the mesh the pivot application is sharded (dist_permute_rows —
    each rank holds a 1/q column strip, never a replicated dense B)."""
    from ..parallel.dist_lu import dist_permute_rows
    if isinstance(F, RBTFactors):
        return _getrs_rbt(F, B, opts)
    slate_error(F.LU.m == B.m, "getrs: dims")
    target = resolve_target(opts, B)
    if (target is Target.mesh and B.grid.mesh is not None
            and type(B) is Matrix and B.op is Op.NoTrans
            and B.is_root_view()):
        st = B.storage
        bp_data = dist_permute_rows(st.data, F.perm, B.grid)
        Bp = Matrix(TileStorage(bp_data, st.m, st.n, st.mb, st.nb, st.grid))
    else:
        bperm = B.to_dense()[F.perm]
        Bp = Matrix(TileStorage.from_dense(bperm, B.mb, B.nb, B.grid))
    Y = trsm("l", 1.0, F.lower(), Bp, opts)
    X = trsm("l", 1.0, F.upper(), Y, opts)
    if faults.active("solve") is not None:
        sx = X.storage
        X = Matrix(TileStorage(faults.maybe_corrupt("solve", sx.data),
                               sx.m, sx.n, sx.mb, sx.nb, sx.grid))
    return X


@annotate("slate.gesv")
def gesv(A: Matrix, B, opts: Options | None = None):
    """Solve A X = B via LU (ref: src/gesv.cc; MethodLU dispatch).
    Returns (LUFactors, X); with Option.UseFallbackSolver an eager call
    escalates pivoting (NoPiv -> PartialPiv -> CALU) on unhealthy
    factors.  Under ``Option.Speculate = on`` the first attempt is the
    RBT-preconditioned pivot-free fast path (:func:`getrf_rbt` + 2 steps
    of iterative refinement), certified by its relative residual; only a
    failed certificate escalates to the pivoted chain — see
    robust/recovery.py and docs/ROBUSTNESS.md."""
    from ..robust.recovery import gesv_with_recovery
    return gesv_with_recovery(A, B, opts)


def gesv_nopiv(A: Matrix, B, opts: Options | None = None):
    """ref: src/gesv_nopiv.cc — no escalation: the raw NoPiv contract."""
    from ..robust.recovery import gesv_nopiv_raw
    return gesv_nopiv_raw(A, B, opts)


def _getri_health(F: LUFactors, X: Matrix):
    """Inverse health: a zero/non-finite U pivot means the factor is
    exactly singular (LAPACK getri's info = k contract) — checked here
    because getri is often handed factors produced under Info/Nan
    policies that deliberately did not raise at factor time."""
    udiag = jnp.diagonal(F.LU.to_dense())
    return _health.merge(_health.from_pivots(udiag),
                         _health.from_result(X.storage.data))


@annotate("slate.getri")
def getri(F: LUFactors, opts: Options | None = None) -> Matrix:
    """In-place-style inverse from LU factors (ref: src/getri.cc):
    A^-1 = U^-1 L^-1 P.

    Failure contract: a singular factor (zero U pivot) resolves per
    ``Option.ErrorPolicy`` — eager raise of :class:`SlateSingularError`
    with ``info = k``, NaN-fill, or ``(X, HealthInfo)``."""
    n = F.LU.m
    eye = jnp.eye(n, dtype=F.LU.dtype)
    I = Matrix(TileStorage.from_dense(eye, F.LU.mb, F.LU.nb, F.LU.grid))
    X = getrs(F, I, opts)
    return _health.finalize("getri", X, _getri_health(F, X), opts,
                            _singular("getri"))


@annotate("slate.getriOOP")
def getriOOP(A: Matrix, opts: Options | None = None) -> Matrix:
    """Out-of-place inverse (ref: src/getriOOP.cc): factor + solve vs I.
    Under ``ErrorPolicy.Info`` returns ``(X, HealthInfo)`` with the
    factor and solve healths merged."""
    from ..options import ErrorPolicy
    if _health.error_policy(opts) is ErrorPolicy.Info:
        F, fh = getrf(A, opts)
        X, ih = getri(F, opts)
        return X, _health.merge(fh, ih)
    F = getrf(A, opts)
    return getri(F, opts)
