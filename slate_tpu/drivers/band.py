"""Band drivers: pbsv/pbtrf/pbtrs, gbsv/gbtrf/gbtrs, tbsm, gbmm, hbmm.

Analog of the reference's band routine group (ref: src/pbsv.cc, pbtrf.cc:
1-241, pbtrs.cc, gbsv.cc, gbtrf.cc:1-318, gbtrs.cc, tbsm.cc, gbmm.cc,
hbmm.cc).  The reference distributes band tiles block-cyclically and skips
out-of-band tiles; here the algorithms run on LAPACK-style packed band
storage (see internal/band.py) as single compiled scans with static dense
windows — compile time O(1) in n, flops O(n·bandwidth²) on MXU-shaped
blocks.  Matrix-class in/out keeps the reference's driver signatures; the
packed kernels are directly usable for at-scale band problems without ever
materializing an n x n dense array.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.matrix import (BandMatrix, HermitianBandMatrix, Matrix,
                           TriangularBandMatrix)
from ..core.storage import TileStorage
from ..exceptions import (SlateNotPositiveDefiniteError, SlateSingularError,
                          slate_error)
from ..internal.band import (band_transpose, banded_trsm_lower,
                             banded_trsm_upper, dense_to_banded,
                             gbmm_banded, gbtrf_banded, gbtrs_banded,
                             hermitian_band_expand, pbtrf_banded,
                             pbtrs_banded)
from ..options import ErrorPolicy, Option, Options
from ..robust import faults
from ..robust import health as _health
from ..types import Diag, Op, Side, Uplo
from ..util.trace import annotate


def _block_width(nb: int, band: int) -> int:
    """Window block width: the tile size, floored so tiny bands still get
    reasonably square windows."""
    return max(min(nb, max(band, 8)), 1)


class PBFactors(NamedTuple):
    """Packed Cholesky factor of a Hermitian positive-definite band matrix:
    L lower band [kd+1, n] with A = L L^H."""
    L_band: jax.Array
    kd: int
    n: int
    w: int

    def solve(self, b):
        return pbtrs_banded(self.L_band, self.kd, self.n, self.w, b)


class GBFactors(NamedTuple):
    """Packed band LU: working array [2kl+ku+1, n] (U rows 0..kl+ku, unit-L
    multipliers below) + per-block window permutations."""
    LU_band: jax.Array
    perms: jax.Array
    kl: int
    ku: int
    n: int
    w: int

    def solve(self, b):
        return gbtrs_banded(self.LU_band, self.perms, self.kl, self.ku,
                            self.n, self.w, b)


# ------------------------------------------------------------- packing

def _hermitian_band_packed(A: HermitianBandMatrix):
    """Lower packed [kd+1, n] with A.op applied: A^H = A is an identity for
    Hermitian matrices, but A^T = conj(A) is not."""
    kd = A.kd
    ad = A._expand(A._dense_store())      # full Hermitian, no op applied
    lp = dense_to_banded(ad, kd, 0)
    if A.op is Op.Trans:
        lp = jnp.conj(lp)
    return lp, kd


def _general_band_packed(A: BandMatrix):
    """Packed [kl+ku+1, n] of the STORED band — A.op is applied by the
    caller via band_transpose (to_dense would double-apply it)."""
    ad = A._expand(A._dense_store())
    return dense_to_banded(ad, A.kl, A.ku)


def _as_dense_rhs(B):
    if isinstance(B, Matrix):
        return B.to_dense(), B
    b = jnp.asarray(B)
    return b, None


def _wrap_like(x, Bm, n):
    if Bm is None:
        return x
    return Matrix(TileStorage.from_dense(x, Bm.mb, Bm.nb, Bm.grid))


# ------------------------------------------------------------- pb chain

@annotate("slate.pbtrf")  # slate-lint: disable=OBS002 -- band cost needs kl/ku, not recoverable from event shapes
def pbtrf(A: HermitianBandMatrix, opts: Options | None = None) -> PBFactors:
    """Band Cholesky A = L L^H (ref: src/pbtrf.cc)."""
    slate_error(isinstance(A, HermitianBandMatrix),
                "pbtrf: need HermitianBandMatrix")
    lp, kd = _hermitian_band_packed(A)
    lp = faults.maybe_corrupt("input", lp)
    n = A.m
    w = _block_width(A.nb, kd)
    lband = pbtrf_banded(lp, kd, n, w)
    # definiteness shows up as NaN on the packed diagonal row (cholesky
    # NaN-fills on failure); finalize keeps the historical contract — eager
    # raise, traced NaN-flow — and adds the info/nan/policy variants
    h = _health.merge(_health.from_pivots(lband[0]),
                      _health.from_result(lband))
    return _health.finalize(
        "pbtrf", PBFactors(lband, kd, n, w), h, opts,
        lambda hh: SlateNotPositiveDefiniteError(
            f"pbtrf: not positive definite ({hh.describe()})",
            info=int(hh.info)))


@annotate("slate.pbtrs")  # slate-lint: disable=OBS002 -- band cost needs kl/ku, not recoverable from event shapes
def pbtrs(F: PBFactors, B, opts: Options | None = None):
    """Solve from pbtrf factors (ref: src/pbtrs.cc)."""
    b, Bm = _as_dense_rhs(B)
    x = faults.maybe_corrupt("solve", F.solve(b))
    return _wrap_like(x, Bm, F.n)


@annotate("slate.pbsv")  # slate-lint: disable=OBS002 -- band cost needs kl/ku, not recoverable from event shapes
def pbsv(A: HermitianBandMatrix, B, opts: Options | None = None):
    """Solve A X = B, A Hermitian positive-definite band (ref: src/pbsv.cc).
    Returns (PBFactors, X); ``(F, X, HealthInfo)`` under ErrorPolicy.Info."""
    F, fh = pbtrf(A, _with_policy(opts, ErrorPolicy.Info))
    X = pbtrs(F, B, opts)
    h = _health.merge(fh, _health.from_result(_raw(X)))
    return _finalize_band_solve(
        "pbsv", F, X, h, opts,
        lambda hh: SlateNotPositiveDefiniteError(
            f"pbsv: not positive definite ({hh.describe()})",
            info=int(hh.info)))


# ------------------------------------------------------------- gb chain

@annotate("slate.gbtrf")  # slate-lint: disable=OBS002 -- band cost needs kl/ku, not recoverable from event shapes
def gbtrf(A: BandMatrix, opts: Options | None = None) -> GBFactors:
    """Band LU with partial pivoting (ref: src/gbtrf.cc).  Pivoting is
    bounded within kl rows below the diagonal, so the factorization runs as
    static (w+kl)-row windows; U's bandwidth grows to kl+ku (the LAPACK
    fill-in bound)."""
    slate_error(isinstance(A, BandMatrix), "gbtrf: need BandMatrix")
    slate_error(A.m == A.n, "gbtrf: square (gbsv path)")
    kl, ku = A.kl, A.ku
    n = A.n
    gp0 = _general_band_packed(A)
    if A.op is not Op.NoTrans:
        gp0 = band_transpose(gp0, kl, ku, n, conj=(A.op is Op.ConjTrans))
        kl, ku = ku, kl
    # working array with kl fill rows on top
    gp = jnp.zeros((2 * kl + ku + 1, n), gp0.dtype).at[kl:].set(gp0)
    gp = faults.maybe_corrupt("input", gp)
    w = _block_width(A.nb, kl + ku)
    amax = jnp.max(jnp.abs(gp))
    lu, perms = gbtrf_banded(gp, kl, ku, n, w)
    # U's diagonal lives at packed row kl+ku; an exactly-zero or
    # non-finite pivot is a singular factorization (eager calls raise
    # SlateSingularError under the default policy)
    growth = jnp.where(amax > 0, jnp.max(jnp.abs(lu)) / amax, jnp.inf)
    h = _health.merge(_health.from_pivots(lu[kl + ku], growth=growth),
                      _health.from_result(lu))
    return _health.finalize(
        "gbtrf", GBFactors(lu, perms, kl, ku, n, w), h, opts,
        lambda hh: SlateSingularError(
            f"gbtrf: exactly-singular or non-finite factor "
            f"({hh.describe()})", info=int(hh.info)))


@annotate("slate.gbtrs")  # slate-lint: disable=OBS002 -- band cost needs kl/ku, not recoverable from event shapes
def gbtrs(F: GBFactors, B, opts: Options | None = None):
    """Solve from gbtrf factors (ref: src/gbtrs.cc)."""
    b, Bm = _as_dense_rhs(B)
    x = faults.maybe_corrupt("solve", F.solve(b))
    return _wrap_like(x, Bm, F.n)


@annotate("slate.gbsv")  # slate-lint: disable=OBS002 -- band cost needs kl/ku, not recoverable from event shapes
def gbsv(A: BandMatrix, B, opts: Options | None = None):
    """Solve A X = B, A general band (ref: src/gbsv.cc).
    Returns (GBFactors, X); ``(F, X, HealthInfo)`` under ErrorPolicy.Info."""
    F, fh = gbtrf(A, _with_policy(opts, ErrorPolicy.Info))
    X = gbtrs(F, B, opts)
    h = _health.merge(fh, _health.from_result(_raw(X)))
    return _finalize_band_solve(
        "gbsv", F, X, h, opts,
        lambda hh: SlateSingularError(
            f"gbsv: singular band matrix ({hh.describe()})",
            info=int(hh.info)))


def _with_policy(opts: Options | None, policy: ErrorPolicy) -> dict:
    o = dict(opts or {})
    o[Option.ErrorPolicy] = policy
    return o


def _raw(X):
    return X.storage.data if isinstance(X, Matrix) else jnp.asarray(X)


def _finalize_band_solve(name, F, X, h, opts, make_exc):
    res = _health.finalize(name, (F, X), h, opts, make_exc)
    if _health.error_policy(opts) is ErrorPolicy.Info:
        (F, X), h = res
        return F, X, h
    return res


# ------------------------------------------------------------- tbsm

@annotate("slate.tbsm")  # slate-lint: disable=OBS002 -- band cost needs kl/ku, not recoverable from event shapes
def tbsm(side, alpha, A: TriangularBandMatrix, B,
         opts: Options | None = None):
    """Triangular band solve op(A) X = alpha B (Left) or X op(A) = alpha B
    (Right) (ref: src/tbsm.cc — the pivoted variant is gbtrs's job here;
    tbsm is the pure triangular-band solve)."""
    slate_error(isinstance(A, TriangularBandMatrix),
                "tbsm: need TriangularBandMatrix")
    sd = side if isinstance(side, Side) else (
        Side.Left if str(side).lower().startswith("l") else Side.Right)
    b, Bm = _as_dense_rhs(B)
    if sd is Side.Right:
        # X op(A) = alpha B  <=>  op(A)^T X^T = alpha B^T
        xt = _tbsm_left(A, alpha, b.T, extra_op=Op.Trans)
        return _wrap_like(xt.T, Bm, A.m)
    x = _tbsm_left(A, alpha, b, extra_op=Op.NoTrans)
    return _wrap_like(x, Bm, A.m)


def _tbsm_left(A: TriangularBandMatrix, alpha, b, extra_op: Op):
    """Solve op(A) X = alpha b with op = A.op (+ optional extra transpose
    from right-side mapping)."""
    n = A.m
    kd = A.kd
    unit = A.diag is Diag.Unit
    w = _block_width(A.nb, kd)
    lp_lower = A.uplo is Uplo.Lower
    # stored triangle masked to the band (+ explicit unit diagonal, which
    # the unit_diag solves then ignore)
    ad = A._expand(A._dense_store())
    op = A.op
    if extra_op is Op.Trans:
        op = {Op.NoTrans: Op.Trans, Op.Trans: Op.NoTrans,
              Op.ConjTrans: Op.NoTrans}[op]
        conj_extra = A.op is Op.ConjTrans
    else:
        conj_extra = False
    b = alpha * b
    if lp_lower:
        lp = dense_to_banded(ad, kd, 0)
        if conj_extra:
            lp = jnp.conj(lp)
        if op is Op.NoTrans:
            return banded_trsm_lower(lp, kd, n, w, b, unit_diag=unit)
        if op is Op.ConjTrans:
            return banded_trsm_lower(lp, kd, n, w, b, conj_trans=True,
                                     unit_diag=unit)
        # plain transpose: conj twice around the ConjTrans solve
        return jnp.conj(banded_trsm_lower(lp, kd, n, w, jnp.conj(b),
                                          conj_trans=True, unit_diag=unit))
    up = dense_to_banded(ad, 0, kd)
    if conj_extra:
        up = jnp.conj(up)
    if op is Op.NoTrans:
        return banded_trsm_upper(up, kd, n, w, b, unit_diag=unit)
    # op(U) is lower-band: transpose the packed storage
    lpt = band_transpose(up, 0, kd, n, conj=(op is Op.ConjTrans))
    if op is Op.ConjTrans:
        # solve U^H x = b: U^H is lower band with the conj-transposed packing
        return banded_trsm_lower(lpt, kd, n, w, b, unit_diag=unit)
    return banded_trsm_lower(lpt, kd, n, w, b, unit_diag=unit)


# ------------------------------------------------------------- band multiply

@annotate("slate.gbmm")  # slate-lint: disable=OBS002 -- band cost needs kl/ku, not recoverable from event shapes
def gbmm(alpha, A: BandMatrix, B, beta=0.0, C=None,
         opts: Options | None = None):
    """C = alpha op(A) B + beta C with A band (ref: src/gbmm.cc)."""
    slate_error(isinstance(A, BandMatrix), "gbmm: need BandMatrix")
    gp = _general_band_packed(A)
    kl, ku = A.kl, A.ku
    m, n = A.m, A.n
    if A.op is not Op.NoTrans:
        slate_error(m == n, "gbmm: op on non-square band")
        gp = band_transpose(gp, kl, ku, n, conj=(A.op is Op.ConjTrans))
        kl, ku = ku, kl
    b, Bm = _as_dense_rhs(B)
    cd = C.to_dense() if isinstance(C, Matrix) else C
    out = gbmm_banded(gp, kl, ku, m, n, b, alpha, beta, cd)
    return _wrap_like(out, Bm if Bm is not None else C, m)


@annotate("slate.hbmm")  # slate-lint: disable=OBS002 -- band cost needs kl/ku, not recoverable from event shapes
def hbmm(side, alpha, A: HermitianBandMatrix, B, beta=0.0, C=None,
         opts: Options | None = None):
    """C = alpha A B + beta C with A Hermitian band (ref: src/hbmm.cc).
    Right side uses A^H = A: B A = (A B^H)^H."""
    slate_error(isinstance(A, HermitianBandMatrix), "hbmm: need "
                "HermitianBandMatrix")
    lp, kd = _hermitian_band_packed(A)
    gp = hermitian_band_expand(lp, kd, A.m)
    sd = side if isinstance(side, Side) else (
        Side.Left if str(side).lower().startswith("l") else Side.Right)
    b, Bm = _as_dense_rhs(B)
    cd = C.to_dense() if isinstance(C, Matrix) else C
    if sd is Side.Left:
        out = gbmm_banded(gp, kd, kd, A.m, A.m, b, alpha, beta, cd)
        return _wrap_like(out, Bm if Bm is not None else C, A.m)
    # B A: (conj(alpha) A B^H)^H + beta C
    t = gbmm_banded(gp, kd, kd, A.m, A.m, jnp.conj(b).T,
                    jnp.conj(jnp.asarray(alpha)), 0.0, None)
    out = jnp.conj(t).T + (beta * cd if cd is not None else 0)
    return _wrap_like(out, Bm if Bm is not None else C, A.m)
