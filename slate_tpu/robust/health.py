"""HealthInfo: jit-compatible numerical-health record + ErrorPolicy glue.

The reference reports failures through LAPACK-style ``info`` codes returned
from each driver (ref: getrf's pivot info, potrf's leading-minor index).
Under jit those codes cannot become Python exceptions, so the seed drivers
improvised: eager ``pbtrf`` raised, traced ``pbtrf`` NaN-filled, ``gbtrf``
silently emitted non-finite values, and the mixed solvers smuggled a
``converged`` bool out.  ``HealthInfo`` is the uniform replacement: a small
pytree of scalars every factor/solve driver computes (cheap reductions over
data it already holds), carried losslessly through jit, shard_map and scan,
and resolved against ``Option.ErrorPolicy`` exactly once at the driver
boundary by :func:`finalize`.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..obs import events as _obs
from ..options import ErrorPolicy, Option, Options, get_option


class HealthInfo(NamedTuple):
    """Numerical health of one factor/solve, as traced scalars.

    nonfinite        bool — any NaN/Inf in the result
    info             int32 LAPACK-style code: 0 healthy, k > 0 the 1-based
                     index of the first zero/non-finite pivot
    min_pivot        smallest \\|pivot\\| magnitude seen (real dtype)
    min_pivot_index  int32 0-based position of ``min_pivot``
    growth           max\\|factor\\| / max\\|input\\| — the pivot-growth ratio
                     escalation keys on (1.0 when not tracked)
    iters            int32 refinement iterations (0 for direct solves)
    converged        bool — iterative convergence (True for direct paths)
    abft_detected    int32 — checksum-verification events that found a
                     mismatch (robust/abft.py; 0 when ABFT is off)
    abft_corrected   int32 — of those, how many were repaired in place
    abft_site        int32 — located global tile of the FIRST detection,
                     encoded ``ti * 65536 + tj``; -1 when none
    """

    nonfinite: jax.Array
    info: jax.Array
    min_pivot: jax.Array
    min_pivot_index: jax.Array
    growth: jax.Array
    iters: jax.Array
    converged: jax.Array
    abft_detected: jax.Array = jnp.asarray(0, jnp.int32)
    abft_corrected: jax.Array = jnp.asarray(0, jnp.int32)
    abft_site: jax.Array = jnp.asarray(-1, jnp.int32)

    @property
    def ok(self):
        """Scalar bool: no failure flag set (still traced under jit).
        A detected-but-uncorrected checksum mismatch is a failure."""
        return ((~self.nonfinite) & (self.info == 0) & self.converged
                & (self.abft_detected == self.abft_corrected))

    def is_traced(self) -> bool:
        return any(isinstance(x, jax.core.Tracer) for x in self)

    def describe(self) -> str:
        """Eager-only human summary (used in exception messages)."""
        s = (f"info={int(self.info)} nonfinite={bool(self.nonfinite)} "
             f"min_pivot={float(self.min_pivot):.3e}"
             f"@{int(self.min_pivot_index)} "
             f"growth={float(self.growth):.3e} iters={int(self.iters)} "
             f"converged={bool(self.converged)}")
        if int(self.abft_detected) or int(self.abft_corrected):
            site = int(self.abft_site)
            where = (f"tile({site >> 16},{site & 0xffff})" if site >= 0
                     else "unlocated")
            s += (f" abft={int(self.abft_corrected)}/"
                  f"{int(self.abft_detected)}@{where}")
        return s


def healthy(dtype=jnp.float64) -> HealthInfo:
    rdt = jnp.finfo(dtype).dtype if jnp.issubdtype(
        dtype, jnp.inexact) else jnp.float64
    return HealthInfo(
        nonfinite=jnp.asarray(False),
        info=jnp.asarray(0, jnp.int32),
        min_pivot=jnp.asarray(jnp.inf, rdt),
        min_pivot_index=jnp.asarray(-1, jnp.int32),
        growth=jnp.asarray(1.0, rdt),
        iters=jnp.asarray(0, jnp.int32),
        converged=jnp.asarray(True),
        abft_detected=jnp.asarray(0, jnp.int32),
        abft_corrected=jnp.asarray(0, jnp.int32),
        abft_site=jnp.asarray(-1, jnp.int32),
    )


def from_pivots(diag, *, growth=None, valid=None) -> HealthInfo:
    """Health of a factorization from its pivot magnitudes.

    ``diag``: the factor's diagonal (U diag for LU, L diag for Cholesky),
    any dtype.  ``valid``: optional bool mask for ragged/padded entries.
    ``info`` is the 1-based index of the first exactly-zero or non-finite
    pivot (the LAPACK convention), 0 if none.
    """
    mag = jnp.abs(jnp.asarray(diag))
    n = mag.shape[0]
    if valid is None:
        valid = jnp.ones((n,), bool)
    bad = valid & ((mag == 0) | ~jnp.isfinite(mag))
    first_bad = jnp.argmax(bad)                    # 0 when no True
    info = jnp.where(jnp.any(bad), first_bad + 1, 0).astype(jnp.int32)
    mag_v = jnp.where(valid, mag, jnp.inf)
    mpi = jnp.argmin(mag_v).astype(jnp.int32)
    h = healthy(mag.dtype)
    return h._replace(
        nonfinite=jnp.any(valid & ~jnp.isfinite(mag)),
        info=info,
        min_pivot=mag_v[mpi],
        min_pivot_index=mpi,
        growth=(h.growth if growth is None
                else jnp.asarray(growth, h.growth.dtype)),
    )


def from_result(x) -> HealthInfo:
    """Health of a computed result: the non-finite flag only."""
    x = jnp.asarray(x)
    return healthy(x.dtype)._replace(
        nonfinite=~jnp.all(jnp.isfinite(
            jnp.abs(x) if jnp.iscomplexobj(x) else x)))


def merge(*hs: HealthInfo) -> HealthInfo:
    """Combine phase healths (factor + solve + ...): worst-of on every
    field; ``info`` keeps the first nonzero code; iters accumulate."""
    out = hs[0]
    for h in hs[1:]:
        out = HealthInfo(
            nonfinite=out.nonfinite | h.nonfinite,
            info=jnp.where(out.info != 0, out.info, h.info),
            min_pivot=jnp.minimum(
                out.min_pivot, h.min_pivot.astype(out.min_pivot.dtype)),
            min_pivot_index=jnp.where(
                out.min_pivot <= h.min_pivot, out.min_pivot_index,
                h.min_pivot_index),
            growth=jnp.maximum(out.growth,
                               h.growth.astype(out.growth.dtype)),
            iters=out.iters + h.iters,
            converged=out.converged & h.converged,
            abft_detected=out.abft_detected + h.abft_detected,
            abft_corrected=out.abft_corrected + h.abft_corrected,
            abft_site=jnp.where(out.abft_site >= 0, out.abft_site,
                                h.abft_site),
        )
    return out


def error_policy(opts: Options | None) -> ErrorPolicy:
    return get_option(opts, Option.ErrorPolicy)


def growth_limit(dtype) -> float:
    """Pivot-growth escalation threshold: 1/sqrt(eps) of the REAL dtype —
    growth beyond this has consumed half the significand, and partial /
    tournament pivoting keeps growth orders of magnitude smaller on any
    non-adversarial matrix (f64: ~6.7e7, f32: ~2.9e3).  Computed with host
    numpy: a jnp expression here would stage into the caller's trace and
    break the float() under jit."""
    import numpy as np
    rdt = jnp.finfo(dtype).dtype
    return float(1.0 / np.sqrt(np.finfo(np.dtype(rdt)).eps))


def acceptable(h: HealthInfo, dtype) -> jax.Array:
    """ok AND pivot growth within the dtype's escalation threshold."""
    return h.ok & (h.growth <= growth_limit(dtype))


def poison(tree, h: HealthInfo):
    """NaN-fill every inexact leaf where the health is bad (jit-safe):
    the ErrorPolicy.Nan guarantee that a failed result is never finite
    garbage."""
    def leaf(x):
        xa = jnp.asarray(x)
        if not jnp.issubdtype(xa.dtype, jnp.inexact):
            return x          # untouched: static ints (e.g. a block size
        #                       riding in a factor pytree) must stay ints
        return jnp.where(h.ok, xa, jnp.full_like(xa, jnp.nan))
    return jax.tree_util.tree_map(leaf, tree)


def finalize(name: str, result, h: HealthInfo, opts: Options | None,
             make_exc=None):
    """Resolve a driver result against Option.ErrorPolicy — the single
    seam every factor/solve driver routes its failures through.

    Raise  eager + bad health: raise ``make_exc(h)`` (typed).  Traced:
           return the result unchanged (failures flow as non-finites, the
           XLA convention).
    Nan    NaN-poison the result where bad; never raise.
    Info   return ``(result, h)``.
    """
    policy = error_policy(opts)
    # host-side note into the open obs boundary frame (no-op when none):
    # nested finalizes are overwritten by the boundary's own, so the
    # emitted event carries the recovery-merged health.
    _obs.note_health(name, h, policy.name)
    if policy is ErrorPolicy.Info:
        return result, h
    if policy is ErrorPolicy.Nan:
        return poison(result, h)
    ok = h.ok
    if not isinstance(ok, jax.core.Tracer) and not bool(ok):
        exc = (make_exc(h) if make_exc is not None
               else _default_exc(name, h))
        raise exc
    return result


def finalize_flat(name: str, result: tuple, h: HealthInfo,
                  opts: Options | None, make_exc=None):
    """:func:`finalize` for tuple-shaped driver results (w, Z), (s, U, V):
    under Info the HealthInfo is APPENDED to the tuple — ``(w, Z, h)`` —
    instead of nesting ``((w, Z), h)``, matching the solver convention of
    ``recovery._finalize_solve``."""
    res = finalize(name, tuple(result), h, opts, make_exc)
    if error_policy(opts) is ErrorPolicy.Info:
        r, hh = res
        return (*r, hh)
    return res


def _default_exc(name: str, h: HealthInfo):
    from ..exceptions import SlateSingularError
    return SlateSingularError(f"{name}: {h.describe()}", info=int(h.info))
