"""Precision policy seam: the one place dtype decisions are made.

SLATE's mixed-precision drivers (ref: src/gesv_mixed.cc) hard-wire the
factor-low/refine-high split into one driver.  Here the split is a
*policy knob* resolved once per boundary — ``Option.Precision`` is read
exactly like ErrorPolicy / Speculate / Abft (options.py), and every
cast between working precisions in ``drivers/`` / ``serve/`` goes
through this module's helpers (slate-lint SEAM014).  That gives three
guarantees the ad-hoc version cannot:

- drivers never read the raw knob, so a boundary's precision decision
  is visible in the flight recorder (``note_resolved("precision", ...)``)
  and cannot silently diverge between rungs;
- dtype spellings are canonicalized in ONE helper (``normalize_dtype``)
  shared by the serving gate, tune plan keys, and bucket ladders — the
  ``jnp.bfloat16``-object vs ``"bfloat16"``-string confusion that made
  the old serving gate silently fall back is structurally gone;
- the bf16 rung is *certified*: ``demote``/``promote``/``round_through``
  are value casts only — acceptance is decided a-posteriori by
  robust/certify, never by the cast site.

The low precision is bf16 with fp32 accumulation (the MXU's native
contract; see internal/pallas_chol.py); fp16 is deliberately absent
until a driver certifies it.
"""

from __future__ import annotations

import numpy as np

from ..exceptions import SlateUnsupportedDtypeError
from ..options import Option, Options, Precision, get_option

# canonical spellings of the two working precisions of the bf16 rung
HIGH = "float32"
LOW = "bfloat16"

# spellings accepted anywhere a dtype crosses a boundary; values are the
# canonical form.  np.dtype() handles objects/strings; this table only
# catches spellings np.dtype would mangle or reject.
_ALIASES = {"bf16": "bfloat16", "f32": "float32", "fp32": "float32",
            "f64": "float64", "fp64": "float64"}


def normalize_dtype(dtype, *, supported: tuple[str, ...] | None = None) -> str:
    """Canonicalize a dtype spelling (``jnp.bfloat16`` object, np.dtype,
    array ``.dtype``, or string) to its numpy name — the ONE spelling the
    serving gate, ``tune.plans.plan_key`` and ``serve.bucket
    .default_ladder`` all key on.  With ``supported`` given, a canonical
    name outside the set raises :class:`SlateUnsupportedDtypeError`
    instead of letting the caller quietly take a slow route."""
    name = getattr(dtype, "name", None)
    if not isinstance(name, str):
        spelled = _ALIASES.get(dtype, dtype) if isinstance(dtype, str) else dtype
        try:
            name = np.dtype(spelled).name
        except TypeError as exc:
            # slate-lint: disable=TRC006 -- host dtype spelling gate: fails at trace time, never in-graph
            raise SlateUnsupportedDtypeError(
                f"unrecognized dtype spelling {dtype!r}", str(dtype)) from exc
    if supported is not None and name not in supported:
        # slate-lint: disable=TRC006 -- static dtype gate: fails at trace time, never in-graph
        raise SlateUnsupportedDtypeError(
            f"dtype {name} not supported here (supported: "
            f"{', '.join(supported)})", name)
    return name


def resolve_precision(opts: Options | None) -> bool:
    """Resolve Option.Precision ONCE at a driver/serving boundary (the
    ErrorPolicy / Speculate / Abft discipline): True only for an explicit
    ``Precision.Bf16`` — ``Auto`` currently maps to F32 so default
    numerics are unchanged.  Every consumer below the boundary receives
    the decision, never the knob."""
    resolved = get_option(opts, Option.Precision) is Precision.Bf16
    from ..obs import events as _obs_events
    _obs_events.note_resolved("precision", resolved)
    return resolved


def demote(x):
    """Cast to the low working precision (bf16 storage).  The sanctioned
    cast site for the speculative rung's factor inputs."""
    import jax.numpy as jnp
    return x.astype(jnp.bfloat16)


def promote(x):
    """Cast to the high working precision (f32) — the refine/certify
    side of the factor-low/refine-high split."""
    import jax.numpy as jnp
    return x.astype(jnp.float32)


def round_through(x):
    """Round a value through bf16 storage and back to its own dtype:
    models what surviving a bf16 memory hop costs, without changing the
    array's type.  Exact for values representable in bf16 (identity
    blocks, zero padding), a half-ulp-of-bf16 perturbation otherwise."""
    import jax.numpy as jnp
    return x.astype(jnp.bfloat16).astype(x.dtype)
