"""Algorithm-based fault tolerance: Huang-Abraham checksums for the
blocked BLAS-3 / LU / Cholesky paths — detect, locate, correct.

The classic ABFT invariant: augment ``A`` with a row-checksum vector
``A e`` and a column-checksum vector ``e^T A`` (``e`` the all-ones
vector).  Matrix products and blocked factorization steps map checksums
to checksums — ``(L U) e = L (U e)``, ``e^T (A - L21 U12) =
e^T A - (e^T L21) U12`` — at O(n^2) cost per step, against the O(n^3)
compute they shadow.  A single corrupted element delta at ``(i0, j0)``
leaves a CROSS pattern in the residuals: one spiked entry in the row
residual (locating ``i0``), one in the column residual (locating
``j0``), and the element is reconstructed from either checksum's masked
complement — which works for ``nan``/``inf`` payloads too, where the
corrupted value itself is unusable.  Every correction is re-verified:
a multi-element strike that fools the locator fails the re-check and is
reported detected-but-uncorrected, which the health layer turns into an
escalation (docs/ROBUSTNESS.md).

This module is pure mechanism, mirroring internal/rbt.py's discipline:
no options, no policies, no exceptions — every function returns arrays
plus :class:`AbftCounts`, and the driver boundary folds those into
``HealthInfo`` (robust/health.py).  Everything is jit/shard_map-safe:
locations are argmaxes over boolean masks, corrections are
``jnp.where``-gated scatters, and thresholds reuse certify.py's
dtype-calibrated tolerance family scaled by the operands' magnitude.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..internal.gemm import (tile_product_col_sums,  # noqa: F401
                             tile_product_row_sums)
from .certify import tolerance


class AbftState(NamedTuple):
    """Checksum pair for one matrix: ``row = A e`` (length m) and
    ``col = e^T A`` (length n).  A jit-safe pytree of two vectors."""

    row: jax.Array
    col: jax.Array


class AbftCounts(NamedTuple):
    """Detection bookkeeping for one or more checksum verifications.

    detected   int32 — verification events that found a mismatch
    corrected  int32 — of those, repaired in place (re-verified)
    site       int32 — ``ti * 65536 + tj`` of the first located tile,
               -1 when nothing was detected
    """

    detected: jax.Array
    corrected: jax.Array
    site: jax.Array


def zero_counts() -> AbftCounts:
    return AbftCounts(jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32),
                      jnp.asarray(-1, jnp.int32))


def site_code(ti, tj):
    """Encode a global tile coordinate into the int32 HealthInfo site."""
    return (jnp.asarray(ti, jnp.int32) * 65536
            + jnp.asarray(tj, jnp.int32))


def count_event(detected, corrected, ti, tj) -> AbftCounts:
    """Counts for ONE verification event at (possibly traced) tile
    coordinates; the site is recorded only when something was detected."""
    det = jnp.asarray(detected)
    return AbftCounts(
        det.astype(jnp.int32),
        jnp.asarray(corrected).astype(jnp.int32),
        jnp.where(det, site_code(ti, tj), jnp.asarray(-1, jnp.int32)))


def add_counts(a: AbftCounts, b: AbftCounts) -> AbftCounts:
    """Accumulate events: counters sum, the first located site wins."""
    return AbftCounts(a.detected + b.detected,
                      a.corrected + b.corrected,
                      jnp.where(a.site >= 0, a.site, b.site))


def checksums(a) -> AbftState:
    """The Huang-Abraham pair for a dense matrix."""
    a = jnp.asarray(a)
    return AbftState(row=jnp.sum(a, axis=-1), col=jnp.sum(a, axis=-2))


# ---------------------------------------------------------------- utils

def _threshold(dtype, n: int, *mags):
    """Detection threshold: certify's dtype-calibrated ``50 n eps``
    scaled by the participating magnitudes (each clamped below at 1 so a
    zero block never yields a zero threshold).  Legitimate rounding in an
    n-term checksum reduction sits orders of magnitude below this; a
    2^100 bitflip sits ~80 orders above."""
    t = jnp.asarray(tolerance(dtype, n), jnp.finfo(dtype).dtype)
    for m in mags:
        t = t * jnp.maximum(jnp.asarray(m).real.astype(t.dtype), 1.0)
    return t


def _amax(x):
    return jnp.max(jnp.abs(x)) if x.size else jnp.asarray(0.0)


def _finite_amax(x):
    """max |x| over finite entries only — the magnitude scale of a block
    that may carry an injected NaN/Inf payload."""
    a = jnp.abs(x)
    return jnp.max(jnp.where(jnp.isfinite(a), a, 0.0))


def _bad(d, t):
    """Residual-exceeds-threshold mask; NaN/Inf residuals count as bad."""
    return ~(jnp.abs(d) <= t)


def _score(d):
    """Magnitude for argmax localization with NaN/Inf forced to the top."""
    a = jnp.abs(d)
    return jnp.where(jnp.isfinite(a), a, jnp.inf)


def _excl_sum(v, skip):
    """Sum of 1D ``v`` excluding (traced) index ``skip`` — the masked
    complement a corrupted element is reconstructed from."""
    return jnp.sum(jnp.where(jnp.arange(v.shape[0]) != skip, v, 0))


def _nf_locate(x):
    """(any_nonfinite, i, j) of the first non-finite element of 2D ``x``.

    A NaN/Inf payload poisons every residual it touches (``NaN * 0 =
    NaN``), so the cross-pattern's first-bad-index locate degenerates —
    the element must be found from the factor's own non-finite mask.
    Finite payloads (bitflip) keep the zeros in the spread direction and
    are located from the residual pattern instead."""
    nf = ~jnp.isfinite(jnp.abs(x))
    flat = jnp.argmax(nf.reshape(-1))
    return (jnp.any(nf), (flat // x.shape[1]).astype(jnp.int32),
            (flat % x.shape[1]).astype(jnp.int32))


# -------------------------------------------- additive (GEMM) checksums

def sum_check(x, exp_row, exp_col, *, dtype=None, n_ctx=None,
              nb=1, row0=0, col0=0):
    """Verify a dense block against expected checksums; correct a single
    corrupted element.

    ``x`` [m, n] should satisfy ``x @ e == exp_row`` and ``e^T x ==
    exp_col`` up to rounding.  A single corrupted element produces
    exactly one bad row-residual entry and one bad column-residual entry
    (the cross pattern); the true value is rebuilt from the row
    checksum's masked complement and cross-checked against the column's.
    Anything wider (two elements, two tiles) fails the single-spike /
    consistency gates and is left untouched.

    Returns ``(x', AbftCounts)`` with the site mapped to global tile
    coordinates via ``nb`` / ``row0`` / ``col0``.
    """
    x = jnp.asarray(x)
    dtype = dtype or x.dtype
    n_ctx = n_ctx or max(x.shape)
    dr = jnp.sum(x, axis=1) - exp_row
    dc = jnp.sum(x, axis=0) - exp_col
    t = _threshold(dtype, n_ctx, _amax(exp_row), _amax(exp_col))
    bad_r, bad_c = _bad(dr, t), _bad(dc, t)
    detected = jnp.any(bad_r) | jnp.any(bad_c)
    i0 = jnp.argmax(_score(dr)).astype(jnp.int32)
    j0 = jnp.argmax(_score(dc)).astype(jnp.int32)
    # reconstruct from the row complement, cross-check with the column's
    v_r = exp_row[i0] - _excl_sum(x[i0, :], j0)
    v_c = exp_col[j0] - _excl_sum(x[:, j0], i0)
    consistent = jnp.abs(v_r - v_c) <= t
    corrected = (detected & (jnp.sum(bad_r) == 1) & (jnp.sum(bad_c) == 1)
                 & consistent)
    x = x.at[i0, j0].set(jnp.where(corrected, v_r, x[i0, j0]))
    counts = count_event(detected, corrected,
                         (row0 + i0) // nb, (col0 + j0) // nb)
    return x, counts


def tile_sum_check(t4, exp_r, exp_c, *, dtype=None, n_ctx=None):
    """Tile-granular :func:`sum_check` for a 4D tile array [S, T, mb, nb]
    with per-tile expected row sums ``exp_r`` [S, T, mb] and column sums
    ``exp_c`` [S, T, nb].  Locates the worst tile, corrects a single
    corrupted element inside it, and refuses (detected-but-uncorrected)
    when more than one tile — or more than one element — is implicated.

    Returns ``(t4', AbftCounts-with-LOCAL-tile-site, ti, tj)`` so mesh
    callers can remap the local tile index to global coordinates."""
    t4 = jnp.asarray(t4)
    S, T, mb, nb = t4.shape
    dtype = dtype or t4.dtype
    n_ctx = n_ctx or max(S * mb, T * nb)
    dr = jnp.sum(t4, axis=3) - exp_r                      # [S, T, mb]
    dc = jnp.sum(t4, axis=2) - exp_c                      # [S, T, nb]
    t = _threshold(dtype, n_ctx, _amax(exp_r), _amax(exp_c))
    bad_r, bad_c = _bad(dr, t), _bad(dc, t)
    tile_bad = jnp.any(bad_r, axis=2) | jnp.any(bad_c, axis=2)  # [S, T]
    detected = jnp.any(tile_bad)
    n_tiles_bad = jnp.sum(tile_bad)
    tile_score = (jnp.max(_score(dr), axis=2)
                  + jnp.max(_score(dc), axis=2))
    flat = jnp.argmax(tile_score.reshape(-1))
    ti, tj = (flat // T).astype(jnp.int32), (flat % T).astype(jnp.int32)
    sub = t4[ti, tj]                                       # [mb, nb]
    sub_dr, sub_dc = dr[ti, tj], dc[ti, tj]
    i0 = jnp.argmax(_score(sub_dr)).astype(jnp.int32)
    j0 = jnp.argmax(_score(sub_dc)).astype(jnp.int32)
    v_r = exp_r[ti, tj, i0] - _excl_sum(sub[i0, :], j0)
    v_c = exp_c[ti, tj, j0] - _excl_sum(sub[:, j0], i0)
    corrected = (detected & (n_tiles_bad == 1)
                 & (jnp.sum(_bad(sub_dr, t)) == 1)
                 & (jnp.sum(_bad(sub_dc, t)) == 1)
                 & (jnp.abs(v_r - v_c) <= t))
    sub = sub.at[i0, j0].set(jnp.where(corrected, v_r, sub[i0, j0]))
    t4 = t4.at[ti, tj].set(sub)
    return t4, count_event(detected, corrected, ti, tj), ti, tj


# ------------------------------------------------------ LU panel check

def _lu_panel_resid(pan_row_p, pan_col, lu):
    """Checksum residuals of a packed LU panel against its PRE-factor
    input: ``dr = L (U e) - rowsum(pan)[perm]`` and ``dc = (e^T L) U -
    colsum(pan)`` — O(M w), no product formed.  ``L`` is the implicit
    unit-lower factor, ``U`` the upper part of the first w rows."""
    M, w = lu.shape
    l_strict = jnp.tril(lu, -1) if M == w else \
        jnp.where(jnp.arange(M)[:, None] > jnp.arange(w)[None, :], lu, 0)
    u = jnp.triu(lu[:w])
    ru = jnp.sum(u, axis=1)                                # U e, [w]
    cl = 1.0 + jnp.sum(l_strict, axis=0)                   # e^T L, [w]
    act_row = l_strict @ ru
    act_row = act_row.at[:w].add(ru)                       # unit diagonal
    dr = act_row - pan_row_p
    dc = cl @ u - pan_col
    return dr, dc, u, ru, cl, l_strict


def lu_panel_check(pan, lu, perm, *, n_ctx=None):
    """Verify a just-factored packed panel ``lu`` (= L\\U, [M, w], unit
    L implicit) against its pre-factor input ``pan`` and permutation
    ``perm`` (``pan[perm] = L U``); locate + correct one corrupted
    factor element.

    Column sums are invariant under the row permutation and row sums are
    permutation-equivariant, so both checks need only the checksum
    vectors of ``pan``.  A strike in the L part (i0 > j0) spikes exactly
    one row residual and spreads along U's row j0 in the column
    residual; a strike in the U part spikes exactly one column residual
    and spreads along L's column i0 — either way ``(first bad row,
    first bad column)`` is the element.  Reconstruction solves the
    element's own checksum identity with the corrupted entry masked out
    (NaN/Inf-proof), and the panel is re-verified before the correction
    is accepted.

    Returns ``(lu', AbftCounts-with-LOCAL-element-site-unset, i0, j0)``
    — the caller maps the element to its global tile."""
    pan = jnp.asarray(pan)
    lu = jnp.asarray(lu)
    M, w = lu.shape
    n_ctx = n_ctx or M
    pan_row_p = jnp.sum(pan, axis=1)[perm]
    pan_col = jnp.sum(pan, axis=0)
    dr, dc, u, ru, cl, l_strict = _lu_panel_resid(pan_row_p, pan_col, lu)
    t = _threshold(lu.dtype, n_ctx, _amax(pan), _finite_amax(lu))
    bad_r, bad_c = _bad(dr, t), _bad(dc, t)
    detected = jnp.any(bad_r) | jnp.any(bad_c)
    any_nf, nf_i, nf_j = _nf_locate(lu)
    i0 = jnp.where(any_nf, nf_i, jnp.argmax(bad_r).astype(jnp.int32))
    j0 = jnp.where(any_nf, nf_j, jnp.argmax(bad_c).astype(jnp.int32))
    is_l = i0 > j0
    rows = jnp.arange(M)
    cols = jnp.arange(w)
    # --- L-part reconstruction: column j0's checksum identity.
    # true (e^T L)[j0] = (colsum(pan)[j0] - sum_{i<j0} (e^T L)[i] U[i,j0])
    #                    / U[j0,j0]; the strike is the only unknown term.
    num = pan_col[j0] - jnp.sum(jnp.where(cols < j0, cl * u[:, j0], 0))
    den = u[j0, j0]
    cl_true = num / jnp.where(den == 0, 1.0, den)
    col_j0 = jnp.where((rows > j0) & (rows != i0), lu[:, j0], 0)
    v_l = cl_true - 1.0 - jnp.sum(col_j0)
    # --- U-part reconstruction: row i0's checksum identity.
    # true (U e)[i0] = rowsum(pan)[perm][i0] - sum_{j<i0} L[i0,j] (U e)[j]
    ru_true = pan_row_p[i0] - jnp.sum(
        jnp.where(cols < i0, lu[i0, :] * ru, 0))
    row_i0 = jnp.where((cols >= i0) & (cols != j0), lu[i0, :], 0)
    v_u = ru_true - jnp.sum(row_i0)
    v = jnp.where(is_l, v_l, v_u)
    lu_fix = lu.at[i0, j0].set(v)
    dr2, dc2, *_ = _lu_panel_resid(pan_row_p, pan_col, lu_fix)
    clean2 = ~(jnp.any(_bad(dr2, t)) | jnp.any(_bad(dc2, t)))
    corrected = detected & clean2
    out = jnp.where(corrected, lu_fix, lu)
    return out, detected, corrected, i0, j0


# ------------------------------------------------- Cholesky tile check

def chol_tile_check(hh, lkk, *, n_ctx=None):
    """Verify a just-factored diagonal tile ``lkk`` (lower triangular)
    against the Hermitian tile ``hh`` it factored; locate + correct one
    corrupted factor element.

    The product ``L L^H`` is Hermitian, so its row/column checksum
    residuals are conjugate mirrors and carry no cross information —
    instead the full tile residual ``E = tril(L L^H - H)`` is formed at
    O(nb^3), the cost of the tile factorization itself and noise next to
    the O(n^2 nb) trailing update it guards.  A single strike at
    ``(i0, j0)`` confines E's support to row i0 (columns >= j0) and
    column i0, so (first bad row, first bad column) of E locates it; the
    element is rebuilt from its own Cholesky defining equation —
    forward-substitution of row i0 against H — and the tile re-verified.

    Returns ``(lkk', detected, corrected)``."""
    hh = jnp.asarray(hh)
    lkk = jnp.asarray(lkk)
    nb = lkk.shape[0]
    n_ctx = n_ctx or nb
    tril_m = jnp.tril(jnp.ones((nb, nb), bool))

    def resid(l):
        lo = jnp.tril(l)
        e = lo @ jnp.conj(lo).T - hh
        return jnp.where(tril_m, e, 0)

    e1 = resid(lkk)
    t = _threshold(lkk.dtype, n_ctx, _amax(hh))
    bad = _bad(e1, t)
    detected = jnp.any(bad)
    any_nf, nf_i, nf_j = _nf_locate(jnp.tril(lkk))
    i0 = jnp.where(any_nf, nf_i,
                   jnp.argmax(jnp.any(bad, axis=1)).astype(jnp.int32))
    j0 = jnp.where(any_nf, nf_j,
                   jnp.argmax(jnp.any(bad, axis=0)).astype(jnp.int32))
    cols = jnp.arange(nb)
    # row-i0 forward substitution with the struck element masked out:
    # H[i0,j0] = sum_{k<j0} L[i0,k] conj(L[j0,k]) + L[i0,j0] conj(L[j0,j0])
    part = jnp.sum(jnp.where(cols < j0, lkk[i0, :] * jnp.conj(lkk[j0, :]),
                             0))
    den = jnp.conj(lkk[j0, j0])
    v_off = (hh[i0, j0] - part) / jnp.where(den == 0, 1.0, den)
    # diagonal strike: L[i0,i0] = sqrt(H[i0,i0] - sum_{k<i0} |L[i0,k]|^2)
    d2 = (hh[i0, i0] - jnp.sum(jnp.where(
        cols < i0, jnp.abs(lkk[i0, :]) ** 2, 0))).real
    v_diag = jnp.sqrt(jnp.maximum(d2, 0)).astype(lkk.dtype)
    v = jnp.where(i0 == j0, v_diag, v_off)
    lkk_fix = lkk.at[i0, j0].set(v)
    clean2 = ~jnp.any(_bad(resid(lkk_fix), t))
    corrected = detected & clean2
    return jnp.where(corrected, lkk_fix, lkk), detected, corrected


# -------------------------------------- triangular product (TRSM) check

def _left_product_resid(lmat, x, r_row, r_col, unit):
    m = lmat.shape[0]
    lo = jnp.tril(lmat, -1) if unit else jnp.tril(lmat)
    cl = jnp.sum(lo, axis=0) + (1.0 if unit else 0.0)      # e^T L
    xe = jnp.sum(x, axis=1)
    act_row = lo @ xe + (xe if unit else 0.0)
    dr = act_row - r_row
    dc = cl @ x - r_col
    return dr, dc


def left_product_check(lmat, x, r_row, r_col, *, unit, n_ctx=None):
    """Verify ``L @ X == R`` through R's checksums only (``r_row = R e``,
    ``r_col = e^T R``) and correct one corrupted element of X.  L is
    lower triangular ([m, m], unit optional), so a strike at (i0, j0)
    spikes the row residual first at i0 (L's column i0 starts at its
    nonzero diagonal) and the column residual exactly at j0.  The row's
    own identity is solved for the true row sum, then the element —
    masked sums throughout, so NaN/Inf payloads reconstruct too.

    Works with just the checksum vectors of R, which is what rides the
    mesh collectives (dist_lu's U12 psum): no extra communication beyond
    the checksum rows.  Returns ``(x', detected, corrected, i0, j0)``."""
    lmat = jnp.asarray(lmat)
    x = jnp.asarray(x)
    m, ncol = x.shape
    n_ctx = n_ctx or max(m, ncol)
    dr, dc = _left_product_resid(lmat, x, r_row, r_col, unit)
    t = _threshold(x.dtype, n_ctx, _amax(r_row), _amax(r_col),
                   _finite_amax(x))
    bad_r, bad_c = _bad(dr, t), _bad(dc, t)
    detected = jnp.any(bad_r) | jnp.any(bad_c)
    any_nf, nf_i, nf_j = _nf_locate(x)
    i0 = jnp.where(any_nf, nf_i, jnp.argmax(bad_r).astype(jnp.int32))
    j0 = jnp.where(any_nf, nf_j, jnp.argmax(bad_c).astype(jnp.int32))
    rows = jnp.arange(m)
    xe = jnp.sum(x, axis=1)
    den = jnp.asarray(1.0, x.dtype) if unit else lmat[i0, i0]
    # mask AFTER the product: xe[i0] may be NaN and 0 * NaN = NaN
    xe_true = (r_row[i0] - jnp.sum(jnp.where(
        rows < i0, lmat[i0, :] * xe, 0))) / jnp.where(den == 0, 1.0, den)
    v = xe_true - _excl_sum(x[i0, :], j0)
    x_fix = x.at[i0, j0].set(v)
    dr2, dc2 = _left_product_resid(lmat, x_fix, r_row, r_col, unit)
    clean2 = ~(jnp.any(_bad(dr2, t)) | jnp.any(_bad(dc2, t)))
    corrected = detected & clean2
    return jnp.where(corrected, x_fix, x), detected, corrected, i0, j0
