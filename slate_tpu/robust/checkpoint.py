"""Durable factorization checkpoints: atomic panel-boundary snapshots
with ABFT-verified resume.

A preempted or crashed long factorization should cost the tail of the
run, not the whole run — and a resumed run must never be a silent wrong
answer.  The out-of-core drivers (drivers/cholesky.py ``potrf_ooc``,
drivers/lu.py ``getrf_ooc``) snapshot the host tile map here at
panel-step boundaries; :meth:`CheckpointManager.load` re-verifies the
snapshot BEFORE any step executes and refuses with a typed
:class:`~slate_tpu.exceptions.SlateCheckpointError` when it cannot be
trusted.  Because a checkpoint stores the exact host bytes entering step
k and the per-step kernels are pure functions of those bytes, a resumed
run is bit-identical to the uninterrupted one.

Snapshot contents (docs/ROBUSTNESS.md "Durable jobs"): the panel-step
index k, the offloaded tile map in the canonical ScaLAPACK layout
(compat/scalapack.py ``scatter_locals`` — a real ScaLAPACK program could
consume the payload), ABFT row/column checksums of the matrix state, the
resolved-options/plan-decision fingerprint of the writing run, and any
per-op extras (the LU row permutation, the input amax).

Write protocol — atomic write-then-rename, twice:

1. the payload (magic + length-prefixed JSON header + raw array bytes)
   is written to a temp file, fsync'd, and ``os.replace``'d into place;
2. the manifest (step, seq, payload name, byte size, SHA-256) is then
   written the same way.

A crash between any two points leaves either the previous checkpoint
fully intact or a manifest/payload pair that verification refuses.  The
verification ladder on load, each rung a distinct refusal ``reason``:

``missing``      no manifest in the directory
``corrupt``      manifest unparsable, or payload digest != manifest
``torn``         payload absent/truncated/size-mismatched (torn write)
``stale``        manifest and payload disagree on step/seq (the manifest
                 was published against stale payload bytes)
``abft``         the matrix fails its stored row/column checksums
``fingerprint``  the resuming run resolved different options or plan
                 decisions than the writing run (drivers raise this rung
                 via :func:`ensure_fingerprint`)

Chaos sites (robust/faults.py ``CKPT_SITES``, consumed via
``host_fire``): ``ckpt_torn_write`` truncates the payload after the
manifest digest was computed; ``ckpt_stale_read`` makes the manifest
writer re-read stale payload bytes.  Both MUST surface as refusals,
never as silent restarts — tests/test_checkpoint.py holds that line.

The raw serialization layer (``write_payload`` / ``read_payload`` /
``write_manifest`` / ``read_manifest``) lives only here: slate-lint
SEAM013 bans touching it from any other module, so every checkpoint
byte on disk went through the one verified writer.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time

import numpy as np

from ..compat.scalapack import gather_locals, scatter_locals
from ..exceptions import SlateCheckpointError, slate_error
from ..obs import events as _obs_events
from ..util.trace import span
from . import faults

#: on-disk format tag; bump on any layout change
MAGIC = b"SLCKPT01"
MANIFEST_NAME = "MANIFEST.json"
PAYLOAD_NAME = "payload.bin"
SCHEMA = "slate-ckpt-v1"


class SimulatedPreemption(Exception):
    """Chaos-harness kill switch: raised by
    :meth:`CheckpointManager.save` right after the checkpoint for
    ``abort_after_step`` lands, simulating a preemption at the worst
    honest moment (snapshot durable, all later work lost).  The
    kill-at-every-step resume tests drive it; production runs never see
    it (``abort_after_step=None``)."""


def _atomic_write(path: str, blob: bytes) -> None:
    """write-then-rename: the file at ``path`` is either the old bytes
    or the complete new bytes, never a prefix."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_payload(path: str, header: dict, arrays: dict) -> tuple:
    """Serialize ``header`` + named numpy arrays to ``path`` atomically.

    Returns ``(sha256_hex, nbytes)`` of the INTENDED payload — under the
    ``ckpt_torn_write`` chaos plan the file on disk is truncated midway
    while the digest still describes the full bytes, exactly the skew a
    crash between write and fsync leaves behind."""
    specs = []
    body = b""
    for name, arr in arrays.items():
        arr = np.asarray(arr)
        order = "F" if arr.flags.f_contiguous and not arr.flags.c_contiguous \
            else "C"
        raw = arr.tobytes(order=order)
        specs.append({"name": name, "dtype": arr.dtype.name,
                      "shape": list(arr.shape), "order": order,
                      "nbytes": len(raw)})
        body += raw
    head = dict(header)
    head["arrays"] = specs
    hb = json.dumps(head, sort_keys=True).encode()
    blob = MAGIC + len(hb).to_bytes(8, "little") + hb + body
    digest = hashlib.sha256(blob).hexdigest()
    plan = faults.host_fire("ckpt_torn_write")
    if plan is not None:
        _atomic_write(path, blob[: len(blob) // 2])
    else:
        _atomic_write(path, blob)
    return digest, len(blob)


def read_payload(path: str) -> tuple:
    """Deserialize ``(header, {name: array})`` from ``path``, refusing
    structurally-torn files (bad magic, truncated header or body)."""
    step = -1
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise SlateCheckpointError(
            f"checkpoint payload unreadable: {e}", reason="torn") from e
    if len(blob) < len(MAGIC) + 8 or blob[: len(MAGIC)] != MAGIC:
        raise SlateCheckpointError(
            "checkpoint payload torn: bad magic/short file", reason="torn")
    hlen = int.from_bytes(blob[len(MAGIC): len(MAGIC) + 8], "little")
    off = len(MAGIC) + 8
    if len(blob) < off + hlen:
        raise SlateCheckpointError(
            "checkpoint payload torn: truncated header", reason="torn")
    try:
        header = json.loads(blob[off: off + hlen].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise SlateCheckpointError(
            f"checkpoint payload corrupt: unparsable header ({e})",
            reason="corrupt") from e
    step = int(header.get("step", -1))
    off += hlen
    arrays = {}
    for spec in header.get("arrays", ()):
        nb_ = int(spec["nbytes"])
        if len(blob) < off + nb_:
            raise SlateCheckpointError(
                f"checkpoint payload torn: array {spec['name']!r} "
                f"truncated", reason="torn", step=step)
        arrays[spec["name"]] = np.frombuffer(
            blob[off: off + nb_], dtype=np.dtype(spec["dtype"])).reshape(
            spec["shape"], order=spec.get("order", "C")).copy()
        off += nb_
    return header, arrays


def write_manifest(directory: str, manifest: dict) -> None:
    """Publish the manifest atomically (the commit point of a save)."""
    blob = json.dumps(manifest, sort_keys=True).encode()
    _atomic_write(os.path.join(directory, MANIFEST_NAME), blob)


def read_manifest(directory: str) -> dict:
    """Read the manifest; typed refusal when absent or unparsable."""
    path = os.path.join(directory, MANIFEST_NAME)
    if not os.path.exists(path):
        raise SlateCheckpointError(
            f"no checkpoint manifest in {directory!r}", reason="missing")
    try:
        with open(path, "rb") as f:
            return json.loads(f.read().decode())
    except (OSError, UnicodeDecodeError, json.JSONDecodeError) as e:
        raise SlateCheckpointError(
            f"checkpoint manifest corrupt: {e}", reason="corrupt") from e


class Checkpoint:
    """One verified snapshot: the dense host matrix state entering panel
    step ``step``, plus per-op ``extras`` (e.g. the LU permutation) and
    the full payload ``meta`` header."""

    def __init__(self, op: str, step: int, matrix: np.ndarray,
                 extras: dict, meta: dict):
        self.op = op
        self.step = step
        self.matrix = matrix
        self.extras = extras
        self.meta = meta

    def __repr__(self):
        return (f"Checkpoint(op={self.op!r}, step={self.step}, "
                f"matrix {self.matrix.shape} {self.matrix.dtype})")


def _fp_norm(fp) -> str:
    return json.dumps(fp, sort_keys=True, default=str)


def ensure_fingerprint(ck: Checkpoint, current: dict) -> None:
    """The semantic verification rung: refuse resume when the current
    run's resolved options / plan decisions differ from the writing
    run's — continuing under different kernels or numerics could not be
    bit-identical, so it must not be silent."""
    stored = ck.meta.get("fingerprint")
    if _fp_norm(stored) != _fp_norm(current):
        raise SlateCheckpointError(
            f"checkpoint fingerprint mismatch: stored {stored!r} vs "
            f"current {current!r}", reason="fingerprint", step=ck.step)


def ooc_fingerprint(op: str, m: int, n: int, nb: int,
                    dtype_name: str) -> dict:
    """The resolved-options/plan-decision fingerprint an OOC driver
    stamps into every snapshot: problem geometry, dtype, streaming panel
    width, and the tuned kernel decision the per-step kernels will
    dispatch on.  Any difference between the writing and resuming run —
    a retuned plan cache, a different panel width, a different dtype —
    changes the bytes the remaining steps would produce, so
    :func:`ensure_fingerprint` refuses instead of resuming."""
    from ..tune import resolve_plan
    tile_op = "potrf_tile" if "potrf" in op else "getrf_panel"
    plan = resolve_plan(tile_op, int(nb), str(dtype_name))
    return {"op": op, "m": int(m), "n": int(n), "nb": int(nb),
            "dtype": str(dtype_name),
            "plan": {"op": tile_op, "kernel": plan.kernel,
                     "nb": int(plan.nb), "bw": int(plan.bw)}}


class CheckpointManager:
    """Panel-boundary checkpointing for the out-of-core drivers.

    ``every`` sets the cadence (save at steps k with k % every == 0);
    ``abort_after_step`` arms the chaos kill switch (see
    :class:`SimulatedPreemption`).  One manager owns one directory; the
    monotonic ``_seq`` counter (lock-guarded — a background flush or
    observer thread may save concurrently with a reader) orders saves so
    a stale manifest/payload skew is detectable.
    """

    def __init__(self, directory, every: int = 1,
                 abort_after_step: int | None = None):
        self.directory = str(directory)
        self.every = max(1, int(every))
        self.abort_after_step = abort_after_step
        self._seq = 0
        self._lock = threading.Lock()
        os.makedirs(self.directory, exist_ok=True)

    def should_save(self, step: int) -> bool:
        return step % self.every == 0

    def has_checkpoint(self) -> bool:
        return os.path.exists(os.path.join(self.directory, MANIFEST_NAME))

    # ---- save ----
    def save(self, op: str, step: int, matrix: np.ndarray,
             mb: int, nb: int, fingerprint: dict,
             extras: dict | None = None) -> None:
        """Snapshot the host state entering panel step ``step``.

        ``matrix`` is the authoritative host array (TileMap.host_array);
        it is serialized in the canonical ScaLAPACK layout with ABFT
        row/column checksums computed over the dense state.  Emits one
        ``checkpoint_save`` obs event (step, bytes, verify, wall ms).
        """
        t0 = time.perf_counter()
        matrix = np.asarray(matrix)
        slate_error(matrix.ndim == 2, "checkpoint: 2D matrix state")
        with span("slate.checkpoint_save"):
            desc, locals_ = scatter_locals(matrix, mb, nb, 1, 1)
            arrays = {"local_0_0": locals_[(0, 0)]}
            # ABFT rung: row/column checksums of the dense state in wide
            # precision — recomputed bitwise on load (same np.sum
            # reduction order)
            cdt = (np.complex128 if np.iscomplexobj(matrix)
                   else np.float64)
            arrays["abft_row"] = np.sum(matrix, axis=1, dtype=cdt)
            arrays["abft_col"] = np.sum(matrix, axis=0, dtype=cdt)
            for name, arr in (extras or {}).items():
                arrays["x_" + name] = np.asarray(arr)
            with self._lock:
                self._seq += 1
                seq = self._seq
            header = {
                "schema": SCHEMA, "op": op, "step": int(step), "seq": seq,
                "desc": [int(x) for x in desc],
                "m": int(matrix.shape[0]), "n": int(matrix.shape[1]),
                "mb": int(mb), "nb": int(nb),
                "dtype": matrix.dtype.name,
                "fingerprint": fingerprint,
            }
            ppath = os.path.join(self.directory, PAYLOAD_NAME)
            stale = faults.host_fire("ckpt_stale_read")
            if stale is not None and os.path.exists(ppath):
                # chaos: manifest republished against a stale read of the
                # previous payload — digest/size describe the OLD bytes,
                # so load() passes the digest rung and refuses on skew
                with open(ppath, "rb") as f:
                    old = f.read()
                digest, size = hashlib.sha256(old).hexdigest(), len(old)
            else:
                digest, size = write_payload(ppath, header, arrays)
            manifest = {
                "schema": SCHEMA, "seq": seq, "op": op, "step": int(step),
                "payload": PAYLOAD_NAME, "sha256": digest, "size": size,
                "written_at": time.time(),
            }
            write_manifest(self.directory, manifest)
        _obs_events.emit_checkpoint("checkpoint_save", {
            "op": op, "step": int(step), "bytes": size, "verify": "ok",
            "wall_ms": round((time.perf_counter() - t0) * 1e3, 3)})
        if self.abort_after_step is not None \
                and step == self.abort_after_step:
            raise SimulatedPreemption(
                f"chaos: simulated preemption after checkpoint at "
                f"step {step}")

    # ---- load / verify ----
    def _refuse(self, op, t0, exc: SlateCheckpointError):
        _obs_events.emit_checkpoint("checkpoint_restore", {
            "op": op, "step": exc.step, "bytes": 0, "verify": exc.reason,
            "wall_ms": round((time.perf_counter() - t0) * 1e3, 3)})
        raise exc

    def load(self, op: str | None = None) -> Checkpoint:
        """Verify and deserialize the latest checkpoint.

        Runs the full structural ladder (manifest -> size -> digest ->
        skew -> ABFT checksums) BEFORE returning; any failed rung raises
        :class:`SlateCheckpointError` with the rung's ``reason``.  The
        semantic ``fingerprint`` rung is the caller's (the driver holds
        the current resolution): pass the result to
        :func:`ensure_fingerprint`.  Emits one ``checkpoint_restore``
        event either way (verify = "ok" or the refusal reason).
        """
        t0 = time.perf_counter()
        try:
            with span("slate.checkpoint_restore"):
                manifest = read_manifest(self.directory)
                step = int(manifest.get("step", -1))
                ppath = os.path.join(self.directory,
                                     str(manifest.get("payload",
                                                      PAYLOAD_NAME)))
                if not os.path.exists(ppath):
                    raise SlateCheckpointError(
                        "checkpoint payload missing (torn save)",
                        reason="torn", step=step)
                size = os.path.getsize(ppath)
                if size != int(manifest.get("size", -1)):
                    raise SlateCheckpointError(
                        f"checkpoint payload torn: {size} bytes on disk "
                        f"!= {manifest.get('size')} in manifest",
                        reason="torn", step=step)
                with open(ppath, "rb") as f:
                    digest = hashlib.sha256(f.read()).hexdigest()
                if digest != manifest.get("sha256"):
                    raise SlateCheckpointError(
                        "checkpoint payload corrupt: SHA-256 mismatch",
                        reason="corrupt", step=step)
                header, arrays = read_payload(ppath)
                if (int(header.get("step", -2)) != step
                        or int(header.get("seq", -2))
                        != int(manifest.get("seq", -1))):
                    raise SlateCheckpointError(
                        f"checkpoint stale: manifest step/seq "
                        f"({step}/{manifest.get('seq')}) != payload "
                        f"({header.get('step')}/{header.get('seq')})",
                        reason="stale", step=step)
                if op is not None and header.get("op") != op:
                    raise SlateCheckpointError(
                        f"checkpoint holds op {header.get('op')!r}, "
                        f"resume requested {op!r}",
                        reason="fingerprint", step=step)
                matrix = gather_locals(
                    header["desc"], {(0, 0): arrays["local_0_0"]}, 1, 1)
                cdt = (np.complex128 if np.iscomplexobj(matrix)
                       else np.float64)
                row = np.sum(matrix, axis=1, dtype=cdt)
                col = np.sum(matrix, axis=0, dtype=cdt)
                if (not np.array_equal(row, arrays["abft_row"])
                        or not np.array_equal(col, arrays["abft_col"])):
                    raise SlateCheckpointError(
                        "checkpoint ABFT checksum mismatch: matrix state "
                        "does not reproduce its stored row/column sums",
                        reason="abft", step=step)
                extras = {name[2:]: arr for name, arr in arrays.items()
                          if name.startswith("x_")}
        except SlateCheckpointError as e:
            self._refuse(op or "?", t0, e)
        _obs_events.emit_checkpoint("checkpoint_restore", {
            "op": header.get("op"), "step": step, "bytes": size,
            "verify": "ok",
            "wall_ms": round((time.perf_counter() - t0) * 1e3, 3)})
        return Checkpoint(header.get("op"), step, matrix, extras, header)
