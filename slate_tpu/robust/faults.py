"""Deterministic, seeded fault injection at named driver sites.

Tile-level fault sensitivity is the production concern for long-running
accelerator kernels ("Design in Tiles" / "Ragged Paged Attention",
PAPERS.md): a single corrupted tile in a factorization propagates into a
finite-but-wrong solution unless detection is explicit.  This module makes
those faults reproducible on CPU so the detection and recovery paths in
:mod:`health` / :mod:`recovery` are testable in tier-1.

Sites are trace-time gates: :func:`maybe_corrupt` is a no-op (returns its
input untouched, traces nothing) unless a plan for that site is active via
the :func:`inject` context manager.  Because activation is decided when the
computation is TRACED, jitted functions must be traced inside the context —
a function compiled without faults will not retroactively corrupt.

Named sites (see docs/ROBUSTNESS.md):

=================  =====================================================
``input``          driver inputs (A's tiles) before factorization
``post_panel``     a just-factored panel, before the trailing update
``post_collective`` a collective result (SUMMA accumulator, the psum'd
                   U12 row in dist_lu, the broadcast panel in dist_chol)
``solve``          the computed solution X
``post_stage1``    the band matrix produced by stage 1 of the two-stage
                   reductions (he2hb / ge2tb), before stage 2 consumes it
``post_chase``     the tri/bidiagonal output of the stage-2 bulge chase
                   (hb2st / tb2bd), before the small-problem eigensolver
``post_secular``   the secular-equation roots inside the stedc D&C merge
``post_backtransform`` the accumulated eigen/singular vectors after the
                   stage-1 back-transform (unmtr_he2hb / unmbr_ge2tb)
``post_rbt``       the butterfly-transformed matrix U^T A V, before the
                   speculative NoPiv factorization consumes it (a strike
                   here yields a finite-but-wrong fast-path solve that
                   only the a-posteriori residual certificate catches)
=================  =====================================================

Payloads: ``nan``, ``inf``, and ``bitflip`` — a high-exponent-bit flip
(value scaled by 2^100), the silent-data-corruption payload that stays
FINITE and is only caught by pivot-growth / residual / checksum checks.

Plans are PERSISTENT by default: the corruption re-fires every time the
site is reached while the plan is active (a stuck-at fault).  Pass
``transient=True`` for single-shot SDC semantics: the strike fires at most
once per :func:`inject` activation, decided at RUN time through an ordered
host callback — so a shape/dtype retrace of the same jitted driver inside
one ``inject`` block neither re-fires the strike nor loses it, and a
recovery retry (e.g. heev escalating Auto -> DC -> QR) sees clean data on
the second attempt, which is exactly how a transient bit-flip behaves in
production.

Strikes can be confined to one tile of the site's array with
``FaultPlan(tile=(i, j), nb=...)``: for 4D tile arrays ``[.., .., mb, nb]``
the strike lands inside ``x[i, j]``; for 3D tile stacks ``[T, mb, nb]``
inside ``x[i]``; for 2D arrays inside the ``nb x nb`` block at block-row
``i``, block-column ``j`` (``nb`` required).  A tile index outside the
array is a miss (no-op) — a persistent plan aimed at the last panel tile
therefore lands exactly once across a blocked factorization's shrinking
panels.  ``tile=None`` keeps the whole-array behavior.
"""

from __future__ import annotations

import contextlib
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import io_callback

SITES = ("input", "post_panel", "post_collective", "solve",
         "post_stage1", "post_chase", "post_secular", "post_backtransform",
         "post_rbt")
#: HOST-side serving-layer chaos sites (docs/SERVING.md "Survival"):
#: consumed via :func:`host_fire` by serve/server.py and serve/cache.py,
#: never woven into a trace —
#:
#: ``serve_flush_delay``    the flush loop sleeps ``delay_s`` before
#:                          executing (ages the batch: deadline sheds
#:                          and watermark behavior become testable)
#: ``serve_compile_stall``  the executable cache sleeps ``delay_s``
#:                          before compiling a miss (a stuck compile:
#:                          what the serving watchdog must catch)
#: ``serve_cache_evict``    the executable cache drops every entry at
#:                          the next lookup (mid-flight eviction: the
#:                          recompile path under load)
#: ``serve_device_fail``    one pool member's dispatch fails: kind
#:                          ``nan`` poisons the batch output (the
#:                          non-finite sentinel path), any other kind
#:                          raises at dispatch (the exception sentinel
#:                          path).  ``FaultPlan(device=i)`` confines the
#:                          strike to pool member ``i``; transient plans
#:                          kill the device once, persistent plans keep
#:                          it dead until the plan deactivates (the
#:                          canary probes it back in)
#: ``serve_device_slow``    one pool member sleeps ``delay_s`` around a
#:                          dispatch — past the pool's per-dispatch
#:                          deadline this reads as a wedged device and
#:                          the batch fails over to a survivor
#: ``serve_canary_flake``   the quarantine canary probe fails (the sick
#:                          device is still sick): readmission is
#:                          refused and the next probe is rescheduled
SERVE_SITES = ("serve_flush_delay", "serve_compile_stall",
               "serve_cache_evict", "serve_device_fail",
               "serve_device_slow", "serve_canary_flake")
#: HOST-side durability chaos sites (docs/ROBUSTNESS.md "Durable jobs"):
#: consumed via :func:`host_fire` by robust/checkpoint.py and the
#: out-of-core tile map in core/storage.py —
#:
#: ``ckpt_torn_write``   the checkpoint payload write is truncated
#:                       mid-file after the manifest digest was computed
#:                       (a crash/preemption landing between write and
#:                       fsync): resume must refuse with reason "torn"
#: ``ckpt_stale_read``   the manifest writer re-reads a stale payload —
#:                       the payload write is skipped but the manifest is
#:                       republished against the old bytes: resume must
#:                       refuse with reason "stale"
#: ``ooc_copy_stall``    the tile map sleeps ``delay_s`` around a
#:                       host<->device panel copy (a congested PCIe/DMA
#:                       path): out-of-core results must stay correct,
#:                       merely late
CKPT_SITES = ("ckpt_torn_write", "ckpt_stale_read", "ooc_copy_stall")
#: every host-side site host_fire will serve
HOST_SITES = SERVE_SITES + CKPT_SITES
KINDS = ("nan", "inf", "bitflip")

# flipping exponent bit 6 of an O(1) value: finite, wildly wrong
_BITFLIP_SCALE = 2.0 ** 100


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """One corruption: ``count`` elements of the first array that flows
    through ``site``, positions drawn deterministically from ``seed``."""

    site: str
    kind: str = "nan"
    seed: int = 0
    count: int = 1
    # transient faults strike once per inject() activation (single-shot
    # SDC); the default is a stuck-at fault that re-fires on every pass.
    transient: bool = False
    # confine the strike to one tile: (block-row, block-col), or None for
    # the whole array.  ``nb`` gives the block edge for 2D arrays.
    tile: tuple[int, int] | None = None
    nb: int = 0
    # host-side serving sites only: how long the chaos sleep lasts
    delay_s: float = 0.0
    # host-side device-pool sites only: confine the strike to one pool
    # member index (None = any member that reaches the site first)
    device: int | None = None

    def __post_init__(self):
        if self.site not in SITES and self.site not in HOST_SITES:
            raise ValueError(f"unknown fault site {self.site!r}; "
                             f"sites: {SITES + HOST_SITES}")
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"kinds: {KINDS}")
        if self.tile is not None:
            if (len(self.tile) != 2
                    or any(int(t) != t or t < 0 for t in self.tile)):
                raise ValueError(f"tile must be two non-negative block "
                                 f"indices, got {self.tile!r}")
        if self.device is not None and (int(self.device) != self.device
                                        or self.device < 0):
            raise ValueError(f"device must be a non-negative pool member "
                             f"index, got {self.device!r}")


_ACTIVE: dict[str, FaultPlan] = {}
# per-inject() activation bookkeeping for transient plans: which
# activation a site's plan belongs to, and which (activation, site) pairs
# have already struck.  Consumption is recorded at RUN time (io_callback),
# so retraces of the same driver share one consumption record.
_EPOCH = 0
_PLAN_EPOCH: dict[str, int] = {}
_SPENT: set[tuple[int, str]] = set()


@contextlib.contextmanager
def inject(*plans: FaultPlan):
    """Activate fault plans for the dynamic extent of the block.  Traced
    computations pick up the corruption only if traced inside."""
    global _EPOCH
    saved = dict(_ACTIVE)
    saved_epoch = dict(_PLAN_EPOCH)
    _EPOCH += 1
    epoch = _EPOCH
    try:
        for p in plans:
            _ACTIVE[p.site] = p
            _PLAN_EPOCH[p.site] = epoch
        yield
    finally:
        _ACTIVE.clear()
        _ACTIVE.update(saved)
        _PLAN_EPOCH.clear()
        _PLAN_EPOCH.update(saved_epoch)
        _SPENT.difference_update({k for k in _SPENT if k[0] == epoch})


def active(site: str) -> FaultPlan | None:
    return _ACTIVE.get(site)


def host_fire(site: str, device: int | None = None) -> FaultPlan | None:
    """Consume an active HOST-side chaos plan at ``site``.

    Unlike :func:`maybe_corrupt` this never touches a trace: the serving
    and durability layers call it from plain host code (the flush loop,
    the executable cache, the checkpoint writer, the tile-map copy path)
    and act on the returned plan (sleep, evict, tear a write).  Transient
    plans fire at most once per :func:`inject` activation — one stalled
    compile or one torn checkpoint, not a permanently broken disk.

    ``device`` is the calling pool member's index (serve/pool.py): a
    plan declaring ``FaultPlan(device=i)`` fires only when member ``i``
    reaches the site — a miss neither fires nor consumes, so a transient
    kill-device-1 plan cannot be eaten by member 0 passing by first."""
    if site not in HOST_SITES:
        return None
    plan = _ACTIVE.get(site)
    if plan is None:
        return None
    if plan.device is not None and plan.device != device:
        return None
    if plan.transient:
        epoch = _PLAN_EPOCH.get(site, 0)
        if (epoch, site) in _SPENT:
            return None
        _SPENT.add((epoch, site))
    return plan


def poisson_workload(seed: int, problems: int, rate_hz: float, sizes,
                     nrhs: int = 2, dtype=np.float32,
                     ops=("solve", "chol_solve", "least_squares_solve")):
    """Deterministic seeded open-loop serving workload: ``problems``
    mixed-size requests with exponential (Poisson-process) inter-arrival
    gaps at ``rate_hz``.  Same seed -> same arrival times, sizes and
    operand values, so overload/shed/quarantine behavior is reproducible
    on CPU — the chaos harness's load generator (bench_serve_survival
    and the survival tests replay it).

    Returns ``[(t_arrival_s, op, a, b)]`` sorted by arrival; matrices
    are well-conditioned (diagonally dominated / SPD-shifted), so every
    admitted request should serve healthy unless chaos intervenes."""
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / max(rate_hz, 1e-9),
                                         size=problems))
    out = []
    for i in range(problems):
        n = int(sizes[i % len(sizes)])
        op = ops[i % len(ops)]
        if op == "least_squares_solve":
            a = rng.standard_normal((n + 8, n)).astype(dtype)
            b = rng.standard_normal((n + 8, nrhs)).astype(dtype)
        else:
            a = rng.standard_normal((n, n)).astype(dtype)
            if op == "chol_solve":
                a = (a @ a.T / n + np.eye(n, dtype=dtype)).astype(dtype)
            else:
                a = a + np.eye(n, dtype=dtype) * 4.0
            b = rng.standard_normal((n, nrhs)).astype(dtype)
        out.append((float(arrivals[i]), op, a, b))
    return out


def _strike_flat(flat, size: int, plan: FaultPlan):
    """Corrupt ``plan.count`` deterministic positions of a flat array."""
    k = min(plan.count, size)
    idx = jnp.asarray(np.random.default_rng(plan.seed).choice(
        size, size=k, replace=False))
    if plan.kind == "nan":
        return flat.at[idx].set(jnp.nan)
    if plan.kind == "inf":
        return flat.at[idx].set(jnp.inf)
    # bitflip: exponent-bit flip — finite but wildly wrong
    return flat.at[idx].multiply(_BITFLIP_SCALE)


def corrupt(x, plan: FaultPlan):
    """Apply ``plan`` to array ``x`` (pure, jit-safe): deterministic flat
    positions from the seed, payload per ``plan.kind``.

    Positions are drawn with HOST numpy at trace time (seed, count and
    x.size are all static), so the corruption lowers to constant-index
    scatters — no jax.random traffic inside jit/shard_map, where this
    jax's replication checker rejects the shuffle primitives.

    With ``plan.tile`` set, the strike is confined to that tile of ``x``
    (see module docstring); an out-of-range tile index is a miss."""
    x = jnp.asarray(x)
    if x.size == 0 or not jnp.issubdtype(x.dtype, jnp.inexact):
        return x
    if plan.tile is None:
        flat = _strike_flat(x.reshape(-1), x.size, plan)
        return flat.reshape(x.shape)
    ti, tj = plan.tile
    if x.ndim == 4:
        if ti >= x.shape[0] or tj >= x.shape[1]:
            return x
        sub = x[ti, tj]
        sub = _strike_flat(sub.reshape(-1), sub.size, plan).reshape(sub.shape)
        return x.at[ti, tj].set(sub)
    if x.ndim == 3:
        if ti >= x.shape[0]:
            return x
        sub = x[ti]
        sub = _strike_flat(sub.reshape(-1), sub.size, plan).reshape(sub.shape)
        return x.at[ti].set(sub)
    if x.ndim == 2:
        if plan.nb <= 0:
            # slate-lint: disable=TRC006 -- plan validation on static config (nb is a host int): raises at trace time, before any tracer exists
            raise ValueError("FaultPlan.tile on a 2D array requires nb > 0")
        r0, c0 = ti * plan.nb, tj * plan.nb
        if r0 >= x.shape[0] or c0 >= x.shape[1]:
            return x
        sub = x[r0:r0 + plan.nb, c0:c0 + plan.nb]
        sub = _strike_flat(sub.reshape(-1), sub.size, plan).reshape(sub.shape)
        return x.at[r0:r0 + sub.shape[0], c0:c0 + sub.shape[1]].set(sub)
    # slate-lint: disable=TRC006 -- dispatch on static ndim: unsupported ranks fail at trace time by design
    raise ValueError(f"FaultPlan.tile targeting needs a 2D/3D/4D array, "
                     f"got ndim={x.ndim}")


def maybe_corrupt(site: str, x):
    """The site hook drivers call: identity unless a plan is active.

    A ``transient`` plan strikes at most once per :func:`inject`
    activation.  Consumption is decided when the computation RUNS, not
    when it is traced: the corrupted and clean values are both woven into
    the trace and an ordered host callback picks one per execution.  A
    retrace under the same activation therefore cannot re-fire a spent
    strike, and tracing at a throwaway shape cannot eat the strike meant
    for the real one."""
    plan = _ACTIVE.get(site)
    if plan is None:
        return x
    if not plan.transient:
        return corrupt(x, plan)
    x = jnp.asarray(x)
    if x.size == 0 or not jnp.issubdtype(x.dtype, jnp.inexact):
        return x
    epoch = _PLAN_EPOCH.get(site, 0)

    def _consume():
        if _PLAN_EPOCH.get(site) != epoch or (epoch, site) in _SPENT:
            return np.asarray(False)
        _SPENT.add((epoch, site))
        return np.asarray(True)

    fire = io_callback(_consume, jax.ShapeDtypeStruct((), np.bool_),
                       ordered=True)
    return jnp.where(fire, corrupt(x, plan), x)
