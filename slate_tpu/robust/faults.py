"""Deterministic, seeded fault injection at named driver sites.

Tile-level fault sensitivity is the production concern for long-running
accelerator kernels ("Design in Tiles" / "Ragged Paged Attention",
PAPERS.md): a single corrupted tile in a factorization propagates into a
finite-but-wrong solution unless detection is explicit.  This module makes
those faults reproducible on CPU so the detection and recovery paths in
:mod:`health` / :mod:`recovery` are testable in tier-1.

Sites are trace-time gates: :func:`maybe_corrupt` is a no-op (returns its
input untouched, traces nothing) unless a plan for that site is active via
the :func:`inject` context manager.  Because activation is decided when the
computation is TRACED, jitted functions must be traced inside the context —
a function compiled without faults will not retroactively corrupt.

Named sites (see docs/ROBUSTNESS.md):

=================  =====================================================
``input``          driver inputs (A's tiles) before factorization
``post_panel``     a just-factored panel, before the trailing update
``post_collective`` a collective result (SUMMA accumulator, broadcast
                   X row in the distributed trsm sweep)
``solve``          the computed solution X
``post_stage1``    the band matrix produced by stage 1 of the two-stage
                   reductions (he2hb / ge2tb), before stage 2 consumes it
``post_chase``     the tri/bidiagonal output of the stage-2 bulge chase
                   (hb2st / tb2bd), before the small-problem eigensolver
``post_secular``   the secular-equation roots inside the stedc D&C merge
``post_backtransform`` the accumulated eigen/singular vectors after the
                   stage-1 back-transform (unmtr_he2hb / unmbr_ge2tb)
``post_rbt``       the butterfly-transformed matrix U^T A V, before the
                   speculative NoPiv factorization consumes it (a strike
                   here yields a finite-but-wrong fast-path solve that
                   only the a-posteriori residual certificate catches)
=================  =====================================================

Payloads: ``nan``, ``inf``, and ``bitflip`` — a high-exponent-bit flip
(value scaled by 2^100), the silent-data-corruption payload that stays
FINITE and is only caught by pivot-growth / residual checks.

Plans are PERSISTENT by default: the corruption re-fires every time the
site is reached while the plan is active (a stuck-at fault).  Pass
``transient=True`` for single-shot SDC semantics — the plan deactivates
after its first strike, so a recovery retry (e.g. heev escalating
Auto -> DC -> QR) sees clean data on the second attempt, which is exactly
how a transient bit-flip behaves in production.
"""

from __future__ import annotations

import contextlib
import dataclasses

import jax
import jax.numpy as jnp

SITES = ("input", "post_panel", "post_collective", "solve",
         "post_stage1", "post_chase", "post_secular", "post_backtransform",
         "post_rbt")
KINDS = ("nan", "inf", "bitflip")

# flipping exponent bit 6 of an O(1) value: finite, wildly wrong
_BITFLIP_SCALE = 2.0 ** 100


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """One corruption: ``count`` elements of the first array that flows
    through ``site``, positions drawn deterministically from ``seed``."""

    site: str
    kind: str = "nan"
    seed: int = 0
    count: int = 1
    # transient faults strike once and deactivate (single-shot SDC);
    # the default is a stuck-at fault that re-fires on every pass.
    transient: bool = False

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(f"unknown fault site {self.site!r}; "
                             f"sites: {SITES}")
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"kinds: {KINDS}")


_ACTIVE: dict[str, FaultPlan] = {}


@contextlib.contextmanager
def inject(*plans: FaultPlan):
    """Activate fault plans for the dynamic extent of the block.  Traced
    computations pick up the corruption only if traced inside."""
    saved = dict(_ACTIVE)
    try:
        for p in plans:
            _ACTIVE[p.site] = p
        yield
    finally:
        _ACTIVE.clear()
        _ACTIVE.update(saved)


def active(site: str) -> FaultPlan | None:
    return _ACTIVE.get(site)


def corrupt(x, plan: FaultPlan):
    """Apply ``plan`` to array ``x`` (pure, jit-safe): deterministic flat
    positions from the seed, payload per ``plan.kind``.

    Positions are drawn with HOST numpy at trace time (seed, count and
    x.size are all static), so the corruption lowers to constant-index
    scatters — no jax.random traffic inside jit/shard_map, where this
    jax's replication checker rejects the shuffle primitives."""
    import numpy as np
    x = jnp.asarray(x)
    if x.size == 0 or not jnp.issubdtype(x.dtype, jnp.inexact):
        return x
    k = min(plan.count, x.size)
    idx = jnp.asarray(np.random.default_rng(plan.seed).choice(
        x.size, size=k, replace=False))
    flat = x.reshape(-1)
    if plan.kind == "nan":
        flat = flat.at[idx].set(jnp.nan)
    elif plan.kind == "inf":
        flat = flat.at[idx].set(jnp.inf)
    else:  # bitflip: exponent-bit flip — finite but wildly wrong
        flat = flat.at[idx].multiply(_BITFLIP_SCALE)
    return flat.reshape(x.shape)


def maybe_corrupt(site: str, x):
    """The site hook drivers call: identity unless a plan is active.
    A ``transient`` plan deactivates after its first strike."""
    plan = _ACTIVE.get(site)
    if plan is None:
        return x
    if plan.transient:
        del _ACTIVE[site]
    return corrupt(x, plan)
