"""Driver-level graceful degradation: escalate, fall back, retry — bounded.

The reference has exactly one recovery path (gesv_mixed's full-precision
fallback after itermax, ref: src/gesv_mixed.cc).  Production service wants
the same shape everywhere a cheap method can fail on hard inputs:

- :func:`gesv_with_recovery` — LU pivoting escalation
  NoPiv -> PartialPiv -> CALU, keyed on non-finite factors, zero pivots,
  or pivot growth beyond ``health.growth_limit`` (a NoPiv factor of a
  row-scaled matrix, or a bit-flipped panel, explodes the growth ratio
  long before the residual is ever formed).
- :func:`posv_with_recovery` — non-HPD input falls back to the Aasen
  ``hesv`` (Hermitian indefinite), then to plain ``gesv``, when
  ``Option.UseFallbackSolver`` is set.
- :func:`bounded_retry` — the shared policy: at most ``max_retries``
  fallback attempts, eager-only (a traced call cannot branch on health;
  it reports the HealthInfo instead), each attempt health-checked.
- :func:`heev_with_recovery` / :func:`svd_with_recovery` —
  certification-gated METHOD escalation for the spectral drivers:
  when the a-posteriori certificate (:mod:`certify`) fails, heev walks
  ``MethodEig`` Auto -> DC -> QR and svd walks ``MethodSvd``
  Auto -> Bidiag (ScaLAPACK's documented ladder: D&C falls back to QR
  iteration on non-convergence), re-certifying each attempt.
- :func:`hesv_with_recovery` — a singular band T (Aasen's tridiagonal
  factor has no pivoting to save it) falls back to plain LU ``gesv``
  on the densified Hermitian matrix.
- Speculation (``Option.Speculate = on``, resolved once per driver
  boundary like ErrorPolicy): the same ladders run FORWARDS as a
  performance feature — gesv tries the RBT-preconditioned NoPiv fast
  path (:func:`_rbt_attempt`), gels tries CholQR2 semi-normal equations
  (:func:`gels_with_recovery`), hesv tries Cholesky first
  (:func:`hesv_with_recovery`) — each attempt certified a-posteriori
  (:mod:`certify`) so a wrong fast answer escalates instead of escaping.
- Precision (``Option.Precision = bf16``, resolved once per boundary via
  :func:`precision.resolve_precision`): with Speculate also on, posv and
  gels grow a rung BELOW the f32 ladder — factor the bf16-ROUNDED
  operand (:func:`_chol_bf16_attempt` / :func:`_gels_bf16_attempt`),
  refine in the ORIGINAL f32 system, and certify at the f32 tolerance;
  a failed certificate escalates to the unchanged f32 chain.

Escalation requires host control flow, so it engages only on EAGER calls;
traced calls run the requested method once and surface health per
``Option.ErrorPolicy`` (docs/ROBUSTNESS.md has the full contract table).
"""

from __future__ import annotations

from ..exceptions import (SlateNotConvergedError,
                          SlateNotPositiveDefiniteError, SlateSingularError)
from ..obs import events as _obs
from ..options import (ErrorPolicy, MethodEig, MethodGels, MethodLU,
                       MethodSvd, Option, Options, get_option, resolve_abft,
                       resolve_speculate, select_gels_method,
                       select_lu_method)
from . import health as _h
from .precision import resolve_precision


def _with(opts: Options | None, **kv) -> dict:
    o = dict(opts or {})
    for name, v in kv.items():
        o[Option[name]] = v
    return o


def bounded_retry(first, fallbacks, *, dtype, max_retries: int = 2):
    """Run ``fallbacks`` (closures returning ``(result, HealthInfo)``) in
    order until a health passes :func:`health.acceptable`, trying at most
    ``max_retries`` of them.  ``first`` is the already-computed
    ``(result, HealthInfo)`` of the primary attempt.  Traced health →
    return ``first`` (no host branch exists under jit).
    Returns ``(result, health, retries_used)``.

    The returned health holds the same growth bound the retry loop selects
    on: ``converged`` is demoted when growth exceeds
    :func:`health.growth_limit`, so a finite-but-catastrophically-grown
    final attempt (e.g. a silently bit-flipped panel — finite values,
    ``info == 0``) can never read as ``h.ok`` from a recovering entry
    point.  jit-safe — pure jnp ops on the health leaves."""

    def demote(hh):
        return hh._replace(
            converged=hh.converged & (hh.growth <= _h.growth_limit(dtype)))

    result, h = first
    if h.is_traced():
        return result, demote(h), 0
    used = 0
    for fb in fallbacks:
        if bool(_h.acceptable(h, dtype)) or used >= max_retries:
            break
        result, h = fb()
        used += 1
    return result, demote(h), used


# ------------------------------------------------------------------ gesv

_LU_CHAIN = {
    MethodLU.NoPiv: (MethodLU.NoPiv, MethodLU.PartialPiv, MethodLU.CALU),
    MethodLU.PartialPiv: (MethodLU.PartialPiv, MethodLU.CALU),
    MethodLU.CALU: (MethodLU.CALU,),
}


def _lu_attempt(A, B, opts, method):
    """One factor+solve attempt under ErrorPolicy.Info; health merges the
    factor's pivot record with the solution's finiteness."""
    from ..drivers import lu as _lu
    o = _with(opts, MethodLU=method, ErrorPolicy=ErrorPolicy.Info)
    factor = {MethodLU.NoPiv: _lu.getrf_nopiv,
              MethodLU.CALU: _lu.getrf_tntpiv}.get(method, _lu.getrf)
    F, fh = factor(A, o)
    X = _lu.getrs(F, B, o)
    h = _h.merge(fh, _h.from_result(X.storage.data))
    return (F, X), h


def _rbt_attempt(A, B, opts, ir_steps: int = 2):
    """The speculative gesv fast path: RBT-preconditioned NoPiv LU
    (drivers/lu.py getrf_rbt), ``ir_steps`` rounds of iterative refinement
    in the ORIGINAL system, then an a-posteriori residual certificate
    (certify.certify_solve) merged into the factor health — a wrong
    fast-path solve (adversarial growth, a post_rbt bit flip) reads as
    ``converged=False`` and escalates in gesv_with_recovery.

    Fully traceable: the attempt itself is pure jnp + drivers; only the
    escalation branch (bounded_retry) needs eager health."""
    from ..drivers import auxiliary as _aux
    from ..drivers import lu as _lu
    from ..drivers.blas3 import gemm
    from ..types import Norm
    from . import certify as _certify
    o = _with(opts, ErrorPolicy=ErrorPolicy.Info)
    F, fh = _lu.getrf_rbt(A, o)
    X = _lu.getrs(F, B, o)
    for _ in range(ir_steps):
        R = gemm(-1.0, A, X, 1.0, B, opts)         # r = B - A X, mesh-aware
        X = _aux.add(1.0, _lu.getrs(F, R, o), 1.0, X)
    R = gemm(-1.0, A, X, 1.0, B, opts)
    anorm = _aux.norm(Norm.Fro, A)
    cert = _certify.certify_solve(anorm, X.to_dense(), B.to_dense(),
                                  R.to_dense(), iters=ir_steps)
    return (F, X), _h.merge(fh, cert)


def gesv_with_recovery(A, B, opts: Options | None = None):
    """gesv body with pivoting escalation (drivers/lu.py delegates here).

    Default order is the safety ladder: requested method first, escalate
    on unhealthy factors.  ``Option.Speculate = on`` (resolved ONCE here,
    like ErrorPolicy) inverts it into a performance feature: the first
    attempt is the certified RBT NoPiv fast path and the pivoted chain
    only runs when the certificate fails — eagerly, as always.

    With ``Option.Abft`` the ladder grows a rung BELOW method
    escalation: the drivers' in-place checksum repair handles a single
    struck tile silently, and an UNREPAIRED detection (a multi-tile
    strike reads ``abft_detected > abft_corrected``, which fails
    ``health.acceptable``) retries the SAME method once — a transient
    strike will not repeat — before the pivoted chain engages.

    Return shape matches gesv's ErrorPolicy contract: ``(F, X)`` under
    Raise/Nan, ``(F, X, HealthInfo)`` under Info."""
    method = select_lu_method(opts)
    speculate = resolve_speculate(opts)
    abft = resolve_abft(opts)  # the one Option.Abft read (like Speculate)
    chain = _LU_CHAIN[method]
    if speculate:
        # the RBT attempt IS the NoPiv rung — escalation goes pivoted
        fb_methods = tuple(m for m in chain if m is not MethodLU.NoPiv)
        first = _rbt_attempt(A, B, opts)
        same = lambda: _rbt_attempt(A, B, opts)            # noqa: E731
    else:
        fb_methods = chain[1:]
        first = _lu_attempt(A, B, opts, chain[0])
        same = lambda: _lu_attempt(A, B, opts, chain[0])   # noqa: E731
    if not get_option(opts, Option.UseFallbackSolver):
        fb_methods = ()
    retry_same = [same] if (abft and fb_methods) else []
    # bounded_retry demotes `converged` on growth beyond the limit: the raw
    # drivers keep growth out of .ok, the recovering solver does not.
    (F, X), h, used = bounded_retry(
        first,
        retry_same + [lambda m=m: _lu_attempt(A, B, opts, m)
                      for m in fb_methods],
        dtype=A.dtype,
        max_retries=max(len(fb_methods) + len(retry_same), 1))
    _obs.note_path("rbt" if speculate else chain[0].name,
                   (["retry_same"] if retry_same else [])
                   + [m.name for m in fb_methods], used, speculate)
    return _finalize_solve("gesv", F, X, h, opts, _singular_exc("gesv"))


def gesv_nopiv_raw(A, B, opts: Options | None = None):
    """gesv_nopiv body: single NoPiv attempt, NO escalation and NO growth
    demotion — the historical contract is that a finite (if catastrophic)
    NoPiv solve returns rather than raises."""
    (F, X), h = _lu_attempt(A, B, opts, MethodLU.NoPiv)
    _obs.note_path("NoPiv", (), 0, False)
    return _finalize_solve("gesv_nopiv", F, X, h, opts,
                           _singular_exc("gesv_nopiv"))


# ------------------------------------------------------------------ posv

def _chol_attempt(A, B, opts):
    """One potrf+potrs attempt under Info.  Shared between posv's primary
    try and hesv's HPD speculation: an indefinite input NaN-fills the
    Cholesky factor, which reads as ``nonfinite`` and falls through the
    retry ladder — no extra certificate needed."""
    from ..drivers import cholesky as _chol
    o = _with(opts, ErrorPolicy=ErrorPolicy.Info)
    L, fh = _chol.potrf(A, o)
    X = _chol.potrs(L, B, o)
    return (L, X), _h.merge(fh, _h.from_result(X.storage.data))


def _round_bf16(M):
    """Round a matrix's values through bf16 storage (precision.py
    round_through) — the dense model of factor-low storage: the values a
    bf16-resident copy would hold, kept in the caller's dtype so every
    driver below runs unchanged.  ``with_dense`` preserves the concrete
    matrix class, so triangular factors stay triangular."""
    from .precision import round_through
    return M.with_dense(round_through(M.to_dense()))


def _chol_bf16_attempt(A, B, opts, ir_steps: int = 2):
    """The speculative posv fast path one precision lower
    (Option.Speculate + Option.Precision = bf16): Cholesky of the
    bf16-ROUNDED operand with the factor itself bf16-rounded — the dense
    model of the serving rung's bf16-stored factor (serve/batched.py) —
    then ``ir_steps`` refinement sweeps in the ORIGINAL f32 system and an
    a-posteriori residual certificate at the f32 tolerance.  A failed
    certificate (or a non-HPD rounding) escalates to the unchanged f32
    Cholesky attempt in posv_with_recovery."""
    from ..drivers import auxiliary as _aux
    from ..drivers import cholesky as _chol
    from ..drivers.blas3 import gemm
    from ..types import Norm
    from . import certify as _certify
    o = _with(opts, ErrorPolicy=ErrorPolicy.Info)
    L, fh = _chol.potrf(_round_bf16(A), o)
    L = _round_bf16(L)
    X = _chol.potrs(L, B, o)
    for _ in range(ir_steps):
        R = gemm(-1.0, A, X, 1.0, B, opts)     # r = B - A X, ORIGINAL A
        X = _aux.add(1.0, _chol.potrs(L, R, o), 1.0, X)
    R = gemm(-1.0, A, X, 1.0, B, opts)
    anorm = _aux.norm(Norm.Fro, A)
    cert = _certify.certify_solve(anorm, X.to_dense(), B.to_dense(),
                                  R.to_dense(), iters=ir_steps)
    return (L, X), _h.merge(fh, cert)


def posv_with_recovery(A, B, opts: Options | None = None):
    """posv body with non-HPD fallback (drivers/cholesky.py delegates).

    On an eager non-HPD failure with Option.UseFallbackSolver set, retries
    the solve as Hermitian-indefinite (hesv), then as plain LU (gesv).
    posv is already speculation-shaped in f32 — Cholesky (the cheapest
    factor) first, certified by its own pivots — so ``Option.Speculate``
    alone changes nothing here.  With ``Option.Precision = bf16`` as well
    (both resolved ONCE at this boundary, like ErrorPolicy) the ladder
    grows a rung BELOW f32: factor the bf16-rounded operand, refine in
    the original system, accept only on the residual certificate
    (:func:`_chol_bf16_attempt`); the f32 Cholesky attempt is always the
    first escalation target, so anything posv could solve before it
    still solves.
    With ``Option.Abft`` an unrepaired checksum detection retries the
    SAME attempt once before the indefinite fallbacks — the
    localized-repair-then-retry rung below full escalation (see
    gesv_with_recovery).

    The first returned element is the factor object of whichever method
    succeeded (TriangularMatrix / HEFactors / LUFactors)."""
    speculate = resolve_speculate(opts)   # resolved ONCE, like ErrorPolicy
    low = resolve_precision(opts)         # the one Option.Precision read
    bf16 = speculate and low
    if bf16:
        first_name = "cholesky_bf16"
        first = _chol_bf16_attempt(A, B, opts)
        same = lambda: _chol_bf16_attempt(A, B, opts)      # noqa: E731
        fallbacks = [lambda: _chol_attempt(A, B, opts)]
        rungs = ["cholesky"]
    else:
        first_name = "cholesky"
        first = _chol_attempt(A, B, opts)
        same = lambda: _chol_attempt(A, B, opts)           # noqa: E731
        fallbacks, rungs = [], []
    if get_option(opts, Option.UseFallbackSolver):
        fallbacks += [lambda: _hesv_attempt(A, B, opts),
                      lambda: _gesv_attempt(A, B, opts)]
        rungs += ["hesv", "gesv"]
        if resolve_abft(opts):  # the one Option.Abft read here
            fallbacks.insert(0, same)
            rungs.insert(0, "retry_same")
    (F, X), h, used = bounded_retry(first, fallbacks, dtype=A.dtype,
                                    max_retries=max(len(fallbacks), 2))
    _obs.note_path(first_name, rungs, used, bf16)
    return _finalize_solve(
        "posv", F, X, h, opts,
        lambda hh: SlateNotPositiveDefiniteError(
            f"posv: not positive definite and fallback failed "
            f"({hh.describe()})", info=int(hh.info)))


def _hesv_attempt(A, B, opts):
    from ..drivers import hetrf as _he
    o = _with(opts, ErrorPolicy=ErrorPolicy.Raise)
    try:
        F, X = _he.hesv(A, B, o)
    except Exception:  # noqa: BLE001 — a failed fallback is just unhealthy
        return (None, None), _h.healthy(A.dtype)._replace(
            converged=_false())
    h = _h.from_result(X.storage.data)
    return (F, X), h


def _gesv_attempt(A, B, opts):
    from ..core.matrix import Matrix
    from ..core.storage import TileStorage
    from ..drivers import lu as _lu
    Ag = Matrix(TileStorage.from_dense(A.to_dense(), A.nb, A.nb, A.grid))
    o = _with(opts, ErrorPolicy=ErrorPolicy.Info)
    F, fh = _lu.getrf(Ag, o)
    X = _lu.getrs(F, B, o)
    return (F, X), _h.merge(fh, _h.from_result(X.storage.data))


# ------------------------------------------------------------- heev / svd

# ScaLAPACK's documented spectral ladder: divide-and-conquer falls back to
# QR iteration on non-convergence.  Auto tries the vendor band eigensolver
# first, then the explicit two-stage routes.
_EIG_CHAIN = {
    MethodEig.Auto: (MethodEig.Auto, MethodEig.DC, MethodEig.QR),
    MethodEig.DC: (MethodEig.DC, MethodEig.QR),
    MethodEig.QR: (MethodEig.QR,),
}

_SVD_CHAIN = {
    MethodSvd.Auto: (MethodSvd.Auto, MethodSvd.Bidiag),
    MethodSvd.Bidiag: (MethodSvd.Bidiag,),
}


def _notconverged_exc(name):
    return lambda h: SlateNotConvergedError(
        f"{name}: spectral result failed certification and escalation "
        f"was exhausted ({h.describe()})", iters=int(h.iters))


def heev_with_recovery(A, opts: Options | None = None, *, jobz: bool = True):
    """heev body with certification-gated MethodEig escalation
    (drivers/heev.py delegates here).

    Each attempt returns ``((w, Z), HealthInfo)`` with the a-posteriori
    eigen-certificate merged in (``certify.certify_eig``); a failed
    certificate reads as ``converged=False`` so :func:`bounded_retry`
    walks the Auto -> DC -> QR ladder.  Return shape: ``(w, Z)`` under
    Raise/Nan, ``(w, Z, HealthInfo)`` under Info."""
    from ..drivers import heev as _heev
    chain = _EIG_CHAIN[get_option(opts, Option.MethodEig)]
    if not get_option(opts, Option.UseFallbackSolver):
        chain = chain[:1]

    def attempt(m):
        return _heev.heev_info(A, _with(opts, MethodEig=m), jobz=jobz)

    (w, Z), h, used = bounded_retry(
        attempt(chain[0]),
        [lambda m=m: attempt(m) for m in chain[1:]],
        dtype=A.dtype, max_retries=len(chain))
    _obs.note_path(chain[0].name, [m.name for m in chain[1:]], used, False)
    return _h.finalize_flat("heev", (w, Z), h, opts,
                            _notconverged_exc("heev"))


def svd_with_recovery(A, opts: Options | None = None, *, jobu: bool = True):
    """svd body with certification-gated MethodSvd escalation
    (drivers/svd.py delegates here): Auto -> Bidiag, re-certified per
    attempt.  Return shape: ``(s, U, V)`` under Raise/Nan,
    ``(s, U, V, HealthInfo)`` under Info."""
    from ..drivers import svd as _svd
    chain = _SVD_CHAIN[get_option(opts, Option.MethodSvd)]
    if not get_option(opts, Option.UseFallbackSolver):
        chain = chain[:1]

    def attempt(m):
        return _svd.svd_info(A, _with(opts, MethodSvd=m), jobu=jobu)

    (s, U, V), h, used = bounded_retry(
        attempt(chain[0]),
        [lambda m=m: attempt(m) for m in chain[1:]],
        dtype=A.dtype, max_retries=len(chain))
    _obs.note_path(chain[0].name, [m.name for m in chain[1:]], used, False)
    return _h.finalize_flat("svd", (s, U, V), h, opts,
                            _notconverged_exc("svd"))


# ------------------------------------------------------------------ hesv

def hesv_with_recovery(A, B, opts: Options | None = None):
    """hesv body with singular-band-T fallback (drivers/hetrf.py
    delegates here): Aasen's tridiagonal T is factored without pivoting
    beyond its band, so a singular T poisons the solve — fall back to
    densified LU ``gesv`` when ``Option.UseFallbackSolver`` is set.

    ``Option.Speculate = on`` (resolved ONCE here) runs the posv ordering
    forward as speculation: Cholesky first — the cheapest Hermitian
    factorization, self-certifying through its pivots — with the Aasen
    method as the guaranteed fallback for indefinite inputs, then
    densified gesv.  The Aasen rung is always present when speculating
    (the baseline contract: any Hermitian input hesv could solve before,
    it still solves), gesv only with UseFallbackSolver.

    Return shape matches gesv's contract: ``(F, X)`` under Raise/Nan,
    ``(F, X, HealthInfo)`` under Info."""
    from ..drivers import hetrf as _he

    def aasen():
        o = _with(opts, ErrorPolicy=ErrorPolicy.Info)
        F, fh = _he.hetrf(A, o)
        X = _he.hetrs(F, B, o)
        return (F, X), _h.merge(fh, _h.from_result(X.storage.data))

    use_fb = get_option(opts, Option.UseFallbackSolver)
    speculate = resolve_speculate(opts)
    if speculate:
        first_name, first = "cholesky", _chol_attempt(A, B, opts)
        fallbacks, rungs = [aasen], ["aasen"]
        if use_fb:
            fallbacks.append(lambda: _gesv_attempt(A, B, opts))
            rungs.append("gesv")
    else:
        first_name, first = "aasen", aasen()
        fallbacks = [lambda: _gesv_attempt(A, B, opts)] if use_fb else []
        rungs = ["gesv"] if use_fb else []
    (F, X), h, used = bounded_retry(first, fallbacks, dtype=A.dtype,
                                    max_retries=max(len(fallbacks), 1))
    _obs.note_path(first_name, rungs, used, speculate)
    return _finalize_solve("hesv", F, X, h, opts, _singular_exc("hesv"))


# ------------------------------------------------------------------ gels

def _gels_bf16_attempt(A, B, opts, refine: int = 2):
    """The speculative gels fast path one precision lower
    (Option.Speculate + Option.Precision = bf16): Householder QR of the
    bf16-ROUNDED operand with R itself bf16-rounded — QR rather than
    CholQR so the low-precision factor error enters the refinement at
    cond(A), not cond(A)^2 — then Björck CSNE sweeps through R against
    the ORIGINAL system and the normal-equations certificate at the f32
    working tolerance.  A failed certificate escalates to the unchanged
    f32 chain in gels_with_recovery."""
    import jax.numpy as jnp
    from jax import lax
    from ..drivers import auxiliary as _aux
    from ..drivers import qr as _qr
    from ..drivers.blas3 import gemm
    from ..types import Norm
    from . import certify as _certify
    from .precision import round_through
    n = A.n
    F = _qr.geqrf(_round_bf16(A), opts)
    rd = round_through(jnp.triu(F.QR.to_dense()[:n, :n]))

    def sne(Rhs):
        # dx = R^-1 R^-T (A^H rhs): semi-normal equations through the low
        # factor; the dense triangular solves mirror gels_qr's idiom
        Z = gemm(1.0, A.conj_transpose(), Rhs, 0.0, None, opts)
        y = lax.linalg.triangular_solve(rd, Z.to_dense(), left_side=True,
                                        lower=False, transpose_a=True)
        return Z.with_dense(lax.linalg.triangular_solve(
            rd, y, left_side=True, lower=False))

    X = sne(B)
    for _ in range(refine):
        R = gemm(-1.0, A, X, 1.0, B, opts)     # r = B - A X, ORIGINAL A
        X = _aux.add(1.0, sne(R), 1.0, X)
    R = gemm(-1.0, A, X, 1.0, B, opts)
    Rn = gemm(1.0, A.conj_transpose(), R, 0.0, None, opts)
    anorm = _aux.norm(Norm.Fro, A)
    cert = _certify.certify_lstsq(
        anorm, X.to_dense(), B.to_dense(), Rn.to_dense(),
        tol=_certify.tolerance(A.dtype, max(A.m, A.n)))
    # the normal-equations certificate is a backward-error gate; a
    # rank-collapsed rounding (huge ||x|| from a tiny R pivot) can pass it
    # trivially, so fold a conditioning estimate through R's diagonal into
    # ``growth`` — bounded_retry's growth demotion then escalates it
    d = jnp.abs(jnp.diagonal(rd))
    piv = _h.from_pivots(d)._replace(
        growth=anorm / jnp.maximum(jnp.min(d), jnp.finfo(rd.dtype).tiny))
    h = _h.merge(piv, _h.merge(
        _h.from_result(X.storage.data),
        cert._replace(iters=jnp.asarray(refine, jnp.int32))))
    return X, h


def gels_with_recovery(A, B, opts: Options | None = None):
    """gels (m >= n) body with CholQR2 speculation and QR fallback
    (drivers/qr.py delegates here), unifying the previously ad-hoc
    CholQR -> QR fallback under bounded_retry.

    Method resolution (select_gels_method) picks CholQR for tall-skinny
    problems; ``Option.Speculate = on`` (resolved ONCE here) forces the
    CholQR2 semi-normal-equations fast path FIRST for any shape, with one
    refinement sweep and an a-posteriori normal-equations certificate
    (certify.certify_lstsq) merged into its health.  A failed certificate
    — squaring the condition number lost too much, or the Gram matrix was
    not numerically HPD — escalates to full Householder QR eagerly.

    ``Option.Precision = bf16`` (resolved ONCE here too) adds a rung
    BELOW that when speculating: the bf16-rounded-QR CSNE attempt
    (:func:`_gels_bf16_attempt`) runs first and the certified f32
    CholQR2 rung is always its escalation target, then Householder QR
    under Option.UseFallbackSolver as before.

    Return shape: ``X`` under Raise/Nan, ``(X, HealthInfo)`` under Info."""
    from ..drivers import qr as _qr
    speculate = resolve_speculate(opts)
    low = resolve_precision(opts)         # the one Option.Precision read
    method = select_gels_method(opts, A.m, A.n)
    fallbacks, rungs = [], []
    if speculate and low:
        first_name = "qr_bf16"
        first = _gels_bf16_attempt(A, B, opts)
        fallbacks = [lambda: _qr._gels_cholqr_attempt(A, B, opts, refine=1,
                                                      certify=True)]
        rungs = ["cholqr2"]
        exc = _qr._gram_exc("gels")
    elif speculate:
        first_name = "cholqr2"
        first = _qr._gels_cholqr_attempt(A, B, opts, refine=1, certify=True)
        exc = _qr._gram_exc("gels")
    elif method is MethodGels.CholQR:
        first_name = "cholqr"
        first = _qr._gels_cholqr_attempt(A, B, opts)
        exc = _qr._gram_exc("gels")
    else:
        # Householder QR directly — no speculation rung, but ErrorPolicy
        # still resolves at THIS boundary: an Info caller (or a vmapped
        # one) gets (X, h) here exactly as on the CholQR routes, not a
        # bare X.  bounded_retry with no fallbacks is just the growth
        # demotion, which QR should also be subject to.
        first_name = "qr"
        first = _qr._gels_qr_attempt(A, B, opts)
        exc = _singular_exc("gels")
    if first_name != "qr" and get_option(opts, Option.UseFallbackSolver):
        fallbacks += [lambda: _qr._gels_qr_attempt(A, B, opts)]
        rungs += ["qr"]
    X, h, used = bounded_retry(first, fallbacks, dtype=A.dtype,
                               max_retries=max(len(fallbacks), 1))
    _obs.note_path(first_name, rungs, used, speculate)
    return _h.finalize("gels", X, h, opts, exc)


# ------------------------------------------------------------------ shared

def _false():
    import jax.numpy as jnp
    return jnp.asarray(False)


def _singular_exc(name):
    return lambda h: SlateSingularError(
        f"{name}: singular or numerically unusable factor "
        f"({h.describe()})", info=int(h.info))


def _finalize_solve(name, F, X, h, opts, make_exc):
    res = _h.finalize(name, (F, X), h, opts, make_exc)
    if _h.error_policy(opts) is ErrorPolicy.Info:
        (F, X), h = res
        return F, X, h
    return res
