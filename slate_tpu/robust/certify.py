"""A-posteriori certification of spectral and speculative results
(jit-compatible).

The factor/solve drivers can read failure off their own pivots; the
spectral drivers cannot — a NaN-poisoned bulge chase, a non-converged
secular solve, or a silently bit-flipped band all produce *finite-looking*
eigenpairs with nothing in the decomposition itself to flag them.  The
LAPACK testers (and the tiled-accelerator verification loops of
"Evaluating Spatial Accelerator Architectures with Tiled Matrix-Matrix
Multiplication", PAPERS.md) close that gap with cheap residual checks
against the ORIGINAL input; this module packages those checks as
:class:`~slate_tpu.robust.health.HealthInfo` producers so the spectral
drivers join the same ErrorPolicy/recovery machinery as the factor
drivers (docs/ROBUSTNESS.md).

Each certificate costs O(n) gemm flops against the driver's O(n^2..n^3)
factor flops — one or two dense products plus Frobenius reductions — and
is pure jnp, so it traces through jit/shard_map unchanged.

Certificate -> HealthInfo mapping:

- ``converged``        False when any residual ratio exceeds the tolerance
- ``growth``           the worst residual ratio (decomposition residual or
                       orthogonality defect, whichever is larger)
- ``min_pivot_index``  0-based column index of the worst residual column
- ``nonfinite``        any NaN/Inf in the certified factors

``min_pivot`` stays +inf so merging a certificate with a factorization
health (hesv: band-T pivots + LDLT certificate) preserves the factor's
real pivot record.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import health as _health


def tolerance(dtype, n: int, factor: float = 50.0) -> float:
    """Dtype-calibrated certification tolerance: ``factor * n * eps`` of
    the REAL dtype.  Measured clean residual ratios sit at ~0.5 n eps
    (heev/svd, all method routes) and ~0.1 n eps (Aasen), so 50 n eps
    accepts every healthy route with a wide margin while a single
    exponent-bit flip (x 2^100) overshoots it by ~80 orders of magnitude.
    Host float — static under jit (dtype and n are trace constants)."""
    import numpy as np
    rdt = np.finfo(np.dtype(dtype)).dtype if np.issubdtype(
        np.dtype(dtype), np.inexact) else np.float64
    return float(factor * max(int(n), 1) * np.finfo(rdt).eps)


def _fro(x):
    """Frobenius norm, real result for any dtype."""
    ax = jnp.abs(x)
    return jnp.sqrt(jnp.sum(ax * ax))


def certify_eig(a, w, v, *, tol: float | None = None) -> _health.HealthInfo:
    """Certificate for A = V diag(w) V^H: relative residual
    ``||A V - V diag(w)||_F / ||A||_F`` and orthogonality defect
    ``||V^H V - I||_F / sqrt(n)``, each vs :func:`tolerance`.

    ``a`` and ``v`` are dense [n, n]; ``w`` is the real spectrum [n]."""
    a = jnp.asarray(a)
    v = jnp.asarray(v)
    w = jnp.asarray(w)
    n = a.shape[0]
    if tol is None:
        tol = tolerance(a.dtype, n)
    R = a @ v - v * w[None, :].astype(v.dtype)
    col = jnp.sum(jnp.abs(R) * jnp.abs(R), axis=0)
    worst = jnp.argmax(col).astype(jnp.int32)
    tiny = jnp.asarray(jnp.finfo(col.dtype).tiny, col.dtype)
    resid = _fro(R) / jnp.maximum(_fro(a), tiny)
    gram = jnp.conj(v).T @ v - jnp.eye(n, dtype=v.dtype)
    ortho = _fro(gram) / jnp.sqrt(jnp.asarray(float(max(n, 1)), col.dtype))
    finite = (jnp.all(jnp.isfinite(jnp.abs(v)))
              & jnp.all(jnp.isfinite(w)))
    ratio = jnp.maximum(resid, ortho)
    h = _health.healthy(a.dtype)
    return h._replace(
        nonfinite=~finite,
        min_pivot_index=worst,
        growth=ratio.astype(h.growth.dtype),
        converged=finite & (resid <= tol) & (ortho <= tol),
    )


def certify_svd(a, s, u, v, *, tol: float | None = None) \
        -> _health.HealthInfo:
    """Certificate for A = U diag(s) V^H (thin factors, r = min(m, n)):
    relative residual ``||A - U diag(s) V^H||_F / ||A||_F`` plus left and
    right orthogonality defects, each vs :func:`tolerance` at max(m, n)."""
    a = jnp.asarray(a)
    u = jnp.asarray(u)
    v = jnp.asarray(v)
    s = jnp.asarray(s)
    m, n = a.shape
    r = min(m, n)
    if tol is None:
        tol = tolerance(a.dtype, max(m, n))
    ur = u[:, :r]
    vr = v[:, :r]
    R = a - (ur * s[None, :r].astype(ur.dtype)) @ jnp.conj(vr).T
    col = jnp.sum(jnp.abs(R) * jnp.abs(R), axis=0)
    worst = jnp.argmax(col).astype(jnp.int32)
    tiny = jnp.asarray(jnp.finfo(col.dtype).tiny, col.dtype)
    resid = _fro(R) / jnp.maximum(_fro(a), tiny)
    rnorm = jnp.sqrt(jnp.asarray(float(max(r, 1)), col.dtype))
    ou = _fro(jnp.conj(ur).T @ ur - jnp.eye(r, dtype=ur.dtype)) / rnorm
    ov = _fro(jnp.conj(vr).T @ vr - jnp.eye(r, dtype=vr.dtype)) / rnorm
    finite = (jnp.all(jnp.isfinite(jnp.abs(u)))
              & jnp.all(jnp.isfinite(jnp.abs(v)))
              & jnp.all(jnp.isfinite(s)))
    ratio = jnp.maximum(jnp.maximum(resid, ou), ov)
    h = _health.healthy(a.dtype)
    return h._replace(
        nonfinite=~finite,
        min_pivot_index=worst,
        growth=ratio.astype(h.growth.dtype),
        converged=finite & (resid <= tol) & (ou <= tol) & (ov <= tol),
    )


def certify_solve(anorm, x, b, r, *, tol: float | None = None,
                  iters: int = 0) -> _health.HealthInfo:
    """Certificate for a linear solve A X = B from its residual
    ``r = B - A X`` (computed by the caller with the mesh-aware gemm
    driver so A is never densified here): relative residual

        ||r||_F / (||A||_F ||X||_F + ||B||_F)

    vs :func:`tolerance` at n = X rows.  This is the speculation gate of
    the RBT fast path (robust/recovery.py): a NoPiv factorization of the
    butterfly-transformed matrix that went numerically wrong — or a
    bit-flipped transform (the ``post_rbt`` fault site) — produces a
    finite X whose residual overshoots the tolerance by orders of
    magnitude.  ``anorm`` is a (possibly traced) scalar ||A||_F; ``iters``
    records refinement steps into the health."""
    x = jnp.asarray(x)
    b = jnp.asarray(b)
    r = jnp.asarray(r)
    if tol is None:
        tol = tolerance(x.dtype, x.shape[0])
    col = jnp.sum(jnp.abs(r) * jnp.abs(r), axis=0)
    worst = jnp.argmax(col).astype(jnp.int32)
    denom = (jnp.asarray(anorm) * _fro(x) + _fro(b))
    tiny = jnp.asarray(jnp.finfo(col.dtype).tiny, col.dtype)
    ratio = _fro(r) / jnp.maximum(denom, tiny)
    finite = jnp.all(jnp.isfinite(jnp.abs(x)))
    h = _health.healthy(x.dtype)
    return h._replace(
        nonfinite=~finite,
        min_pivot_index=worst,
        growth=ratio.astype(h.growth.dtype),
        iters=jnp.asarray(iters, jnp.int32),
        converged=finite & (ratio <= tol),
    )


def certify_lstsq(anorm, x, b, rn, *, tol: float | None = None) \
        -> _health.HealthInfo:
    """Certificate for a least-squares solve min ||A X - B|| from its
    normal-equations residual ``rn = A^H (B - A X)`` (which is ~0 at the
    true minimizer even when the plain residual is large): relative ratio

        ||A^H r||_F / (||A||_F^2 ||X||_F + ||A||_F ||B||_F)

    vs :func:`tolerance` at max(m, n) — pass ``tol`` explicitly to
    calibrate.  Gates the speculative CholQR2 gels path the same way
    :func:`certify_solve` gates the RBT gesv path."""
    x = jnp.asarray(x)
    b = jnp.asarray(b)
    rn = jnp.asarray(rn)
    if tol is None:
        tol = tolerance(x.dtype, max(x.shape[0], b.shape[0]))
    col = jnp.sum(jnp.abs(rn) * jnp.abs(rn), axis=0)
    worst = jnp.argmax(col).astype(jnp.int32)
    an = jnp.asarray(anorm)
    denom = an * an * _fro(x) + an * _fro(b)
    tiny = jnp.asarray(jnp.finfo(col.dtype).tiny, col.dtype)
    ratio = _fro(rn) / jnp.maximum(denom, tiny)
    finite = jnp.all(jnp.isfinite(jnp.abs(x)))
    h = _health.healthy(x.dtype)
    return h._replace(
        nonfinite=~finite,
        min_pivot_index=worst,
        growth=ratio.astype(h.growth.dtype),
        converged=finite & (ratio <= tol),
    )


def certify_ldlt(a, L, T, piv, *, tol: float | None = None) \
        -> _health.HealthInfo:
    """Certificate for the blocked Aasen factorization
    ``P A P^H = L T L^H``: relative residual
    ``||A[piv][:, piv] - L T L^H||_F / ||A||_F`` vs :func:`tolerance`.

    ``a`` dense Hermitian [n, n]; ``L`` unit lower [n, n]; ``T`` the
    assembled band [n, n] (``HEFactors.T_dense()``); ``piv`` the symmetric
    permutation (may be traced — applied as a gather)."""
    a = jnp.asarray(a)
    L = jnp.asarray(L)
    T = jnp.asarray(T)
    n = a.shape[0]
    if tol is None:
        tol = tolerance(a.dtype, n)
    ap = a[piv][:, piv]
    R = ap - L @ T @ jnp.conj(L).T
    col = jnp.sum(jnp.abs(R) * jnp.abs(R), axis=0)
    worst = jnp.argmax(col).astype(jnp.int32)
    tiny = jnp.asarray(jnp.finfo(col.dtype).tiny, col.dtype)
    resid = _fro(R) / jnp.maximum(_fro(a), tiny)
    finite = (jnp.all(jnp.isfinite(jnp.abs(L)))
              & jnp.all(jnp.isfinite(jnp.abs(T))))
    h = _health.healthy(a.dtype)
    return h._replace(
        nonfinite=~finite,
        min_pivot_index=worst,
        growth=resid.astype(h.growth.dtype),
        converged=finite & (resid <= tol),
    )
