"""Numerical-health subsystem: jit-safe info codes, fault injection, and
driver-level recovery/escalation.

Three parts (see docs/ROBUSTNESS.md for the per-driver contract table):

- :mod:`health`   — the ``HealthInfo`` pytree threaded through the factor
  and solve drivers, plus the ``Option.ErrorPolicy`` resolution that
  unifies the eager-raise vs traced-NaN contracts.
- :mod:`faults`   — a deterministic, seeded fault injector that corrupts
  named sites (input tiles, post-panel factors, post-collective results)
  so detection and recovery paths are testable on CPU.
- :mod:`recovery` — driver-level graceful degradation: LU pivoting
  escalation (NoPiv -> PartialPiv -> CALU), posv -> hesv/gesv fallback on
  non-HPD input, and the bounded-retry policy the mixed-precision
  full-precision fallback routes through.
"""

from .health import (  # noqa: F401
    HealthInfo, error_policy, finalize, from_pivots, from_result, healthy,
    merge, poison,
)
from .faults import FaultPlan, inject, maybe_corrupt  # noqa: F401
from .recovery import (  # noqa: F401
    bounded_retry, gesv_with_recovery, posv_with_recovery,
)
