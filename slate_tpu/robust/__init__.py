"""Numerical-health subsystem: jit-safe info codes, fault injection,
a-posteriori certification, driver-level recovery/escalation, and
durable panel-boundary checkpoints.

Six parts (see docs/ROBUSTNESS.md for the per-driver contract table):

- :mod:`health`   — the ``HealthInfo`` pytree threaded through the factor
  and solve drivers, plus the ``Option.ErrorPolicy`` resolution that
  unifies the eager-raise vs traced-NaN contracts.
- :mod:`certify`  — cheap a-posteriori residual/orthogonality certificates
  for the spectral drivers (heev/svd/hetrf), whose decompositions carry no
  pivot record to read failure from.
- :mod:`precision` — the working-precision policy seam:
  ``Option.Precision`` resolved once per boundary, dtype spellings
  canonicalized in one helper, and the sanctioned demote/promote casts
  for the certified bf16 first rung (slate-lint SEAM014).
- :mod:`faults`   — a deterministic, seeded fault injector that corrupts
  named sites (input tiles, post-panel factors, post-collective results,
  the two-stage spectral pipeline) so detection and recovery paths are
  testable on CPU.
- :mod:`recovery` — driver-level graceful degradation: LU pivoting
  escalation (NoPiv -> PartialPiv -> CALU), posv -> hesv/gesv fallback on
  non-HPD input, certification-gated spectral method escalation
  (heev Auto -> DC -> QR, svd Auto -> Bidiag, hesv -> gesv), and the
  bounded-retry policy the mixed-precision fallback routes through.
- :mod:`checkpoint` — durable panel-boundary snapshots for the
  out-of-core drivers, with atomic write-then-rename and an ABFT /
  digest / fingerprint verification ladder that refuses untrustworthy
  state with a typed ``SlateCheckpointError`` before resuming.
"""

from .health import (  # noqa: F401
    HealthInfo, error_policy, finalize, finalize_flat, from_pivots,
    from_result, healthy, merge, poison,
)
from .certify import (  # noqa: F401
    certify_eig, certify_ldlt, certify_svd, tolerance,
)
from .precision import (  # noqa: F401
    normalize_dtype, resolve_precision,
)
from .faults import FaultPlan, inject, maybe_corrupt  # noqa: F401
from .recovery import (  # noqa: F401
    bounded_retry, gesv_with_recovery, heev_with_recovery,
    hesv_with_recovery, posv_with_recovery, svd_with_recovery,
)
from .checkpoint import (  # noqa: F401
    Checkpoint, CheckpointManager, SimulatedPreemption,
)
