"""Dense <-> blocked-tile layout conversion.

TPU-native analog of the reference's layout machinery (ref:
include/slate/Tile.hh:645-792 layoutConvert / makeTransposable and the
fromLAPACK/fromScaLAPACK import paths, Matrix.hh:58-163).  The reference
converts each tile between col/row-major in place; on TPU the whole matrix is
one blocked array ``[Mt, Nt, mb, nb]`` and conversion is a single reshape +
transpose that XLA fuses into surrounding code (free under jit).

Padding discipline: partial boundary tiles are zero-padded.  Every kernel in
the framework preserves "pad region == 0" as an invariant so reductions can
run unmasked wherever zeros are absorbing; norms use explicit masks
(ops/norms.py) where they are not.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def num_tiles(m: int, mb: int) -> int:
    return -(-m // mb)


def tile_dense(dense, mb: int, nb: int):
    """[m, n] -> canonical tile array [Mt, Nt, mb, nb], zero-padded."""
    m, n = dense.shape
    Mt, Nt = num_tiles(m, mb), num_tiles(n, nb)
    pad_m, pad_n = Mt * mb - m, Nt * nb - n
    if pad_m or pad_n:
        dense = jnp.pad(dense, ((0, pad_m), (0, pad_n)))
    return dense.reshape(Mt, mb, Nt, nb).transpose(0, 2, 1, 3)


def untile_dense(tiles, m: int, n: int):
    """Canonical tile array [Mt, Nt, mb, nb] -> dense [m, n]."""
    Mt, Nt, mb, nb = tiles.shape
    dense = tiles.transpose(0, 2, 1, 3).reshape(Mt * mb, Nt * nb)
    return dense[:m, :n]


def cyclic_row_maps(Mt: int, p: int) -> tuple[np.ndarray, np.ndarray, int]:
    """Index maps between canonical tile order and 2D block-cyclic storage.

    Storage row ``s`` of the sharded store holds canonical tile-row
    ``i = (s % mtl) * p + (s // mtl)`` — i.e. device-row ``r = s // mtl`` owns
    tiles ``i ≡ r (mod p)`` (ref: MatrixStorage.hh:555-568 2D block-cyclic).

    Returns (c2s, s2c, mtl):
      c2s[i] = storage row of canonical tile-row i          (len Mt)
      s2c[s] = canonical tile-row of storage row s, or Mt for padding slots
               (len p*mtl; index Mt addresses an all-zero pad tile)
    """
    mtl = -(-Mt // p)
    c2s = np.empty(Mt, dtype=np.int32)
    s2c = np.full(p * mtl, Mt, dtype=np.int32)
    for i in range(Mt):
        s = (i % p) * mtl + i // p
        c2s[i] = s
        s2c[s] = i
    return c2s, s2c, mtl


def canonical_to_cyclic(tiles, p: int, q: int):
    """[Mt, Nt, mb, nb] canonical -> [p*mtl, q*ntl, mb, nb] cyclic storage."""
    Mt, Nt, mb, nb = tiles.shape
    _, s2c_r, _ = cyclic_row_maps(Mt, p)
    _, s2c_c, _ = cyclic_row_maps(Nt, q)
    # Append one zero pad-slot per axis, then gather with the s2c maps.
    ext = jnp.concatenate([tiles, jnp.zeros((1, Nt, mb, nb), tiles.dtype)], 0)
    ext = jnp.concatenate(
        [ext, jnp.zeros((Mt + 1, 1, mb, nb), tiles.dtype)], 1)
    return ext[s2c_r][:, s2c_c]


def cyclic_to_canonical(data, Mt: int, Nt: int, p: int, q: int):
    """[p*mtl, q*ntl, mb, nb] cyclic storage -> [Mt, Nt, mb, nb] canonical."""
    c2s_r, _, _ = cyclic_row_maps(Mt, p)
    c2s_c, _, _ = cyclic_row_maps(Nt, q)
    return data[c2s_r][:, c2s_c]
