"""Dense <-> blocked-tile layout conversion.

TPU-native analog of the reference's layout machinery (ref:
include/slate/Tile.hh:645-792 layoutConvert / makeTransposable and the
fromLAPACK/fromScaLAPACK import paths, Matrix.hh:58-163).  The reference
converts each tile between col/row-major in place; on TPU the whole matrix is
one blocked array ``[Mt, Nt, mb, nb]`` and conversion is a single reshape +
transpose that XLA fuses into surrounding code (free under jit).

Padding discipline: partial boundary tiles are zero-padded.  Every kernel in
the framework preserves "pad region == 0" as an invariant so reductions can
run unmasked wherever zeros are absorbing; norms use explicit masks
(ops/norms.py) where they are not.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def num_tiles(m: int, mb: int) -> int:
    return -(-m // mb)


def tile_dense(dense, mb: int, nb: int):
    """[m, n] -> canonical tile array [Mt, Nt, mb, nb], zero-padded."""
    m, n = dense.shape
    Mt, Nt = num_tiles(m, mb), num_tiles(n, nb)
    pad_m, pad_n = Mt * mb - m, Nt * nb - n
    if pad_m or pad_n:
        dense = jnp.pad(dense, ((0, pad_m), (0, pad_n)))
    return dense.reshape(Mt, mb, Nt, nb).transpose(0, 2, 1, 3)


def untile_dense(tiles, m: int, n: int):
    """Canonical tile array [Mt, Nt, mb, nb] -> dense [m, n]."""
    Mt, Nt, mb, nb = tiles.shape
    dense = tiles.transpose(0, 2, 1, 3).reshape(Mt * mb, Nt * nb)
    return dense[:m, :n]


def assemble_band(dd, ss, *, lower: bool):
    """Dense [K nb, K nb] block band from diag tiles ``dd`` [K, nb, nb]
    and off-diagonal tiles ``ss`` [K-1, nb, nb] (pre-masked by the
    caller), placed at (g+1, g) when ``lower`` else (g, g+1).

    Two vectorized tile scatters + one untile — the shared engine behind
    the heev/svd band gathers (an O(K) unrolled chain of dense updates
    compiled K sequential full-matrix writes)."""
    K, nb = dd.shape[0], dd.shape[1]
    g = jnp.arange(K)
    tiles = jnp.zeros((K, K, nb, nb), dd.dtype).at[g, g].set(dd)
    if K > 1 and ss.shape[0]:
        if lower:
            tiles = tiles.at[g[:-1] + 1, g[:-1]].set(ss[: K - 1])
        else:
            tiles = tiles.at[g[:-1], g[:-1] + 1].set(ss[: K - 1])
    return untile_dense(tiles, K * nb, K * nb)


def cyclic_row_maps(Mt: int, p: int) -> tuple[np.ndarray, np.ndarray, int]:
    """Index maps between canonical tile order and 2D block-cyclic storage.

    Storage row ``s`` of the sharded store holds canonical tile-row
    ``i = (s % mtl) * p + (s // mtl)`` — i.e. device-row ``r = s // mtl`` owns
    tiles ``i ≡ r (mod p)`` (ref: MatrixStorage.hh:555-568 2D block-cyclic).

    Returns (c2s, s2c, mtl):
      c2s[i] = storage row of canonical tile-row i          (len Mt)
      s2c[s] = canonical tile-row of storage row s, or Mt for padding slots
               (len p*mtl; index Mt addresses an all-zero pad tile)
    """
    mtl = -(-Mt // p)
    c2s = np.empty(Mt, dtype=np.int32)
    s2c = np.full(p * mtl, Mt, dtype=np.int32)
    for i in range(Mt):
        s = (i % p) * mtl + i // p
        c2s[i] = s
        s2c[s] = i
    return c2s, s2c, mtl


def canonical_to_cyclic(tiles, p: int, q: int):
    """[Mt, Nt, mb, nb] canonical -> [p*mtl, q*ntl, mb, nb] cyclic storage.

    The cyclic map ``i = t p + r  <->  s = r mtl + t`` is a pure
    reshape + transpose (after zero-padding ragged tile counts), NOT a
    gather — XLA lowers gathers of large tile arrays to scatter/gather
    HBM traffic an order of magnitude off peak (measured 59 ms for a
    1 GB roundtrip at n=16384), while reshape/transpose fuses."""
    Mt, Nt, mb, nb = tiles.shape
    mtl, ntl = -(-Mt // p), -(-Nt // q)
    if p * mtl > Mt or q * ntl > Nt:
        tiles = jnp.pad(tiles, ((0, p * mtl - Mt), (0, q * ntl - Nt),
                                (0, 0), (0, 0)))
    x = tiles.reshape(mtl, p, ntl, q, mb, nb).transpose(1, 0, 3, 2, 4, 5)
    return x.reshape(p * mtl, q * ntl, mb, nb)


def cyclic_to_canonical(data, Mt: int, Nt: int, p: int, q: int):
    """[p*mtl, q*ntl, mb, nb] cyclic storage -> [Mt, Nt, mb, nb] canonical.

    Inverse reshape/transpose of :func:`canonical_to_cyclic` (no gather)."""
    S, T, mb, nb = data.shape
    mtl, ntl = S // p, T // q
    x = data.reshape(p, mtl, q, ntl, mb, nb).transpose(1, 0, 3, 2, 4, 5)
    return x.reshape(p * mtl, q * ntl, mb, nb)[:Mt, :Nt]
