"""Distributed matrix class hierarchy.

TPU-native analog of the reference's 12-class matrix layer
(ref: include/slate/BaseMatrix.hh:39-738 and Matrix.hh / BaseTrapezoidMatrix.hh
/ TriangularMatrix.hh / SymmetricMatrix.hh / HermitianMatrix.hh /
BaseBandMatrix.hh / BandMatrix.hh / TriangularBandMatrix.hh /
HermitianBandMatrix.hh).

Differences forced (for the better) by the TPU programming model:

- Matrices are **immutable pytrees**.  Reference routines mutate tiles in
  place under MOSI coherency; here every driver returns new matrices whose
  storage is a new SSA value.  XLA's buffer donation recovers in-place update
  performance without aliasing hazards.
- ``sub``/``transpose``/``conj_transpose`` are metadata-only views sharing the
  parent's storage object (zero-copy, ref: BaseMatrix.hh:941-1122 sub/slice,
  Tile.hh:40-90 transpose views); materialisation happens lazily inside jit
  where XLA fuses the gather/transpose into consumers.
- Tile coherency API (tileGetForReading/Writing, BaseMatrix.hh:2968-3396) has
  no analog: there is one copy of every tile, owned by its mesh coordinate.
- The communication API (tileBcast/listBcast/listReduce,
  BaseMatrix.hh:451-477) lives in slate_tpu.comm as mesh collectives.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..exceptions import slate_error
from ..types import Diag, Op, TileKind, Uplo, compose_op, is_complex
from . import layout
from .grid import Grid
from .storage import TileStorage

__all__ = [
    "BaseMatrix", "Matrix", "BaseTrapezoidMatrix", "TrapezoidMatrix",
    "TriangularMatrix", "SymmetricMatrix", "HermitianMatrix",
    "BaseBandMatrix", "BandMatrix", "TriangularBandMatrix",
    "HermitianBandMatrix",
]


@jax.tree_util.register_pytree_node_class
class BaseMatrix:
    """Shared base: storage + (tile-offset, extent, op) view metadata.

    View coordinates (io, jo, mt, nt) index the *storage* tile grid; ``op``
    transposes on top, applied in accessors — mirroring how the reference
    routes every index through ``op()`` (BaseMatrix.hh:4048-4088).
    """

    uplo: Uplo = Uplo.General
    diag: Diag = Diag.NonUnit

    def __init__(self, storage: TileStorage, io: int = 0, jo: int = 0,
                 mt: Optional[int] = None, nt: Optional[int] = None,
                 op: Op = Op.NoTrans, kind: TileKind = TileKind.SlateOwned):
        self.storage = storage
        self.io, self.jo = int(io), int(jo)
        self._mt = storage.Mt - self.io if mt is None else int(mt)
        self._nt = storage.Nt - self.jo if nt is None else int(nt)
        self.op = op
        self.kind = kind
        slate_error(0 <= self.io and self.io + self._mt <= storage.Mt and
                    0 <= self.jo and self.jo + self._nt <= storage.Nt,
                    "view out of range")

    # ---- pytree ----
    def tree_flatten(self):
        aux = (self.io, self.jo, self._mt, self._nt, self.op, self.kind,
               self._extra_aux())
        return (self.storage,), aux

    def _extra_aux(self):
        return ()

    @classmethod
    def tree_unflatten(cls, aux, children):
        io, jo, mt, nt, op, kind, extra = aux
        obj = cls.__new__(cls)
        BaseMatrix.__init__(obj, children[0], io, jo, mt, nt, op, kind)
        obj._apply_extra_aux(extra)
        return obj

    def _apply_extra_aux(self, extra):
        pass

    # ---- shape accessors (op-aware) ----
    @property
    def grid(self) -> Grid:
        return self.storage.grid

    @property
    def dtype(self):
        return self.storage.dtype

    def _m_store(self) -> int:
        st = self.storage
        if self._mt == 0:
            return 0
        last = self.io + self._mt - 1
        return (self._mt - 1) * st.mb + st.tile_mb(last)

    def _n_store(self) -> int:
        st = self.storage
        if self._nt == 0:
            return 0
        last = self.jo + self._nt - 1
        return (self._nt - 1) * st.nb + st.tile_nb(last)

    @property
    def m(self) -> int:
        return self._m_store() if self.op is Op.NoTrans else self._n_store()

    @property
    def n(self) -> int:
        return self._n_store() if self.op is Op.NoTrans else self._m_store()

    @property
    def mt(self) -> int:
        return self._mt if self.op is Op.NoTrans else self._nt

    @property
    def nt(self) -> int:
        return self._nt if self.op is Op.NoTrans else self._mt

    @property
    def mb(self) -> int:
        return self.storage.mb if self.op is Op.NoTrans else self.storage.nb

    @property
    def nb(self) -> int:
        return self.storage.nb if self.op is Op.NoTrans else self.storage.mb

    def tile_mb(self, i: int) -> int:
        if self.op is Op.NoTrans:
            return min(self.storage.tile_mb(self.io + i), self._m_store() - i * self.mb)
        return min(self.storage.tile_nb(self.jo + i), self._n_store() - i * self.mb)

    def tile_nb(self, j: int) -> int:
        if self.op is Op.NoTrans:
            return min(self.storage.tile_nb(self.jo + j), self._n_store() - j * self.nb)
        return min(self.storage.tile_mb(self.io + j), self._m_store() - j * self.nb)

    def tile_rank(self, i: int, j: int) -> int:
        if self.op is not Op.NoTrans:
            i, j = j, i
        return self.storage.tile_rank(self.io + i, self.jo + j)

    # ---- views (zero-copy: share self.storage) ----
    def sub(self, i1: int, i2: int, j1: int, j2: int):
        """Tile-index submatrix view, inclusive ranges like the reference
        (ref: BaseMatrix.hh:941-1122).  Returns a general Matrix view."""
        if self.op is not Op.NoTrans:
            i1, i2, j1, j2 = j1, j2, i1, i2
        mt = max(0, i2 - i1 + 1)
        nt = max(0, j2 - j1 + 1)
        v = Matrix.__new__(Matrix)
        BaseMatrix.__init__(v, self.storage, self.io + i1, self.jo + j1,
                            mt, nt, self.op, self.kind)
        return v

    def transpose(self):
        v = self.__class__.__new__(self.__class__)
        BaseMatrix.__init__(v, self.storage, self.io, self.jo, self._mt,
                            self._nt, compose_op(self.op, Op.Trans), self.kind)
        v._apply_extra_aux(self._extra_aux())
        return v

    def conj_transpose(self):
        if not is_complex(self.dtype):
            return self.transpose()
        v = self.__class__.__new__(self.__class__)
        BaseMatrix.__init__(v, self.storage, self.io, self.jo, self._mt,
                            self._nt, compose_op(self.op, Op.ConjTrans),
                            self.kind)
        v._apply_extra_aux(self._extra_aux())
        return v

    @property
    def T(self):
        return self.transpose()

    @property
    def H(self):
        return self.conj_transpose()

    def is_root_view(self) -> bool:
        return (self.io == 0 and self.jo == 0 and
                self._mt == self.storage.Mt and self._nt == self.storage.Nt)

    # ---- materialisation ----
    def _dense_store(self):
        """Dense [m, n] of the untransposed view region."""
        st = self.storage
        if self.is_root_view():
            return st.to_dense()
        tiles = st.canonical()[self.io:self.io + self._mt,
                               self.jo:self.jo + self._nt]
        return layout.untile_dense(tiles, self._m_store(), self._n_store())

    def to_dense(self):
        """Materialise as a plain [m, n] jnp array (op applied, structure
        expanded — symmetric/triangular/band subclasses override _expand)."""
        d = self._expand(self._dense_store())
        if self.op is Op.Trans:
            d = d.T
        elif self.op is Op.ConjTrans:
            d = d.conj().T
        return d

    def _expand(self, dense):
        return dense

    def to_numpy(self) -> np.ndarray:
        # root general views export through the NATIVE tile unpacker when
        # built (one host pass over the fetched tile array); structured
        # types and op views need to_dense()'s expansion.  Check the
        # library and dtype BEFORE fetching — a failed attempt would have
        # paid the full device->host transfer twice
        if (type(self) is Matrix and self.op is Op.NoTrans
                and self.is_root_view()):
            from .. import native as _native
            st = self.storage
            if _native.available() and _native.supports(st.dtype):
                tiles = np.asarray(jax.device_get(st.data))
                out = _native.unpack_tiles(tiles, st.m, st.n, st.grid.p,
                                           st.grid.q)
                if out is not None:
                    return out
        return np.asarray(jax.device_get(self.to_dense()))

    def with_dense(self, dense):
        """Return a same-view matrix whose view region holds ``dense``
        (functional write-back; parent storage regions outside the view are
        preserved)."""
        if self.op is Op.Trans:
            dense = dense.T
        elif self.op is Op.ConjTrans:
            dense = jnp.conj(dense).T
        st = self.storage
        if self.is_root_view():
            new_st = st.with_dense(dense)
        else:
            tiles = st.canonical()
            sub = layout.tile_dense(dense, st.mb, st.nb)
            tiles = jax.lax.dynamic_update_slice(
                tiles, sub.astype(tiles.dtype), (self.io, self.jo, 0, 0))
            new_st = st.with_canonical(tiles)
        v = self.__class__.__new__(self.__class__)
        BaseMatrix.__init__(v, new_st, self.io, self.jo, self._mt, self._nt,
                            self.op, self.kind)
        v._apply_extra_aux(self._extra_aux())
        return v

    def emptyLike(self, dtype=None):
        """Same shape/distribution, zero data (ref: Matrix::emptyLike)."""
        st = self.storage
        z = TileStorage.zeros(st.m, st.n, st.mb, st.nb, st.grid,
                              dtype or st.dtype)
        v = self.__class__.__new__(self.__class__)
        BaseMatrix.__init__(v, z, self.io, self.jo, self._mt, self._nt,
                            self.op, self.kind)
        v._apply_extra_aux(self._extra_aux())
        return v

    def __repr__(self):
        extra = "" if self.op is Op.NoTrans else f", op={self.op.name}"
        return (f"{self.__class__.__name__}({self.m}x{self.n}, "
                f"tiles {self.mb}x{self.nb}, grid {self.grid.p}x"
                f"{self.grid.q}{extra})")


@jax.tree_util.register_pytree_node_class
class Matrix(BaseMatrix):
    """General m*n matrix (ref: include/slate/Matrix.hh:58-163)."""

    @classmethod
    def zeros(cls, m, n, mb, nb=None, grid=None, dtype=jnp.float32):
        nb = nb or mb
        return cls(TileStorage.zeros(m, n, mb, nb, grid or Grid(1, 1), dtype))

    @classmethod
    def from_numpy(cls, a, mb, nb=None, grid=None, kind=TileKind.UserOwned):
        """Import user data (ref: fromLAPACK, Matrix.hh:58-112).

        Host numpy arrays are passed through UNconverted so from_dense can
        take the native one-pass tile packer; jnp.asarray here would hide
        the numpy-ness and silently fall back to the device layout ops."""
        nb = nb or mb
        a = a if isinstance(a, np.ndarray) else jnp.asarray(a)
        st = TileStorage.from_dense(a, mb, nb, grid or Grid(1, 1))
        return cls(st, kind=kind)

    # ---- structure reinterpretation (ref: conversion ctors) ----
    def triangular(self, uplo: Uplo, diag: Diag = Diag.NonUnit):
        slate_error(self.m == self.n, "triangular view needs square")
        return TriangularMatrix._from_view(self, uplo, diag)

    def symmetric(self, uplo: Uplo):
        slate_error(self.m == self.n, "symmetric view needs square")
        return SymmetricMatrix._from_view(self, uplo)

    def hermitian(self, uplo: Uplo):
        slate_error(self.m == self.n, "hermitian view needs square")
        return HermitianMatrix._from_view(self, uplo)

    def trapezoid(self, uplo: Uplo, diag: Diag = Diag.NonUnit):
        return TrapezoidMatrix._from_view(self, uplo, diag)


@jax.tree_util.register_pytree_node_class
class BaseTrapezoidMatrix(BaseMatrix):
    """Upper/lower trapezoid storage base
    (ref: include/slate/BaseTrapezoidMatrix.hh)."""

    def __init__(self, storage, uplo: Uplo = Uplo.Lower,
                 diag: Diag = Diag.NonUnit, **kw):
        super().__init__(storage, **kw)
        self.uplo = uplo
        self.diag = diag

    def _extra_aux(self):
        return (self.uplo, self.diag)

    def _apply_extra_aux(self, extra):
        self.uplo, self.diag = extra

    @classmethod
    def _from_view(cls, src: BaseMatrix, uplo: Uplo, diag: Diag = Diag.NonUnit):
        v = cls.__new__(cls)
        BaseMatrix.__init__(v, src.storage, src.io, src.jo, src._mt, src._nt,
                            src.op, src.kind)
        # A lower view of a transposed matrix is an upper view of storage.
        if src.op is not Op.NoTrans:
            uplo = Uplo.Upper if uplo is Uplo.Lower else Uplo.Lower
        v._apply_extra_aux((uplo, diag))
        return v

    def _uplo_logical(self) -> Uplo:
        """uplo as seen through op (ref: BaseMatrix::uploLogical)."""
        if self.op is Op.NoTrans:
            return self.uplo
        return Uplo.Upper if self.uplo is Uplo.Lower else Uplo.Lower

    def _expand(self, dense):
        m, n = dense.shape
        i = jnp.arange(m)[:, None]
        j = jnp.arange(n)[None, :]
        mask = (i >= j) if self.uplo is Uplo.Lower else (i <= j)
        d = jnp.where(mask, dense, jnp.zeros((), dense.dtype))
        if self.diag is Diag.Unit:
            k = min(m, n)
            d = d.at[jnp.arange(k), jnp.arange(k)].set(1)
        return d

    def general(self) -> Matrix:
        """Expand to a general Matrix (materialises the structure)."""
        g = Matrix.zeros(self.m, self.n, self.mb, self.nb, self.grid,
                         self.dtype)
        return g.with_dense(self.to_dense())


@jax.tree_util.register_pytree_node_class
class TrapezoidMatrix(BaseTrapezoidMatrix):
    """ref: include/slate/TrapezoidMatrix.hh"""


@jax.tree_util.register_pytree_node_class
class TriangularMatrix(BaseTrapezoidMatrix):
    """ref: include/slate/TriangularMatrix.hh"""

    @classmethod
    def from_numpy(cls, a, mb, uplo=Uplo.Lower, diag=Diag.NonUnit, grid=None):
        return cls._from_view(Matrix.from_numpy(a, mb, mb, grid), uplo, diag)


@jax.tree_util.register_pytree_node_class
class SymmetricMatrix(BaseTrapezoidMatrix):
    """ref: include/slate/SymmetricMatrix.hh — only the uplo triangle is
    referenced; _expand mirrors it."""

    @classmethod
    def from_numpy(cls, a, mb, uplo=Uplo.Lower, grid=None):
        return cls._from_view(Matrix.from_numpy(a, mb, mb, grid), uplo)

    def _expand(self, dense):
        tri = BaseTrapezoidMatrix._expand(self, dense)
        d = jnp.diagonal(tri)
        return tri + tri.T - jnp.diag(d)


@jax.tree_util.register_pytree_node_class
class HermitianMatrix(BaseTrapezoidMatrix):
    """ref: include/slate/HermitianMatrix.hh"""

    @classmethod
    def from_numpy(cls, a, mb, uplo=Uplo.Lower, grid=None):
        return cls._from_view(Matrix.from_numpy(a, mb, mb, grid), uplo)

    def _expand(self, dense):
        tri = BaseTrapezoidMatrix._expand(self, dense)
        d = jnp.real(jnp.diagonal(tri))
        full = tri + jnp.conj(tri).T
        k = min(full.shape)
        return full.at[jnp.arange(k), jnp.arange(k)].set(
            d.astype(full.dtype))


@jax.tree_util.register_pytree_node_class
class BaseBandMatrix(BaseMatrix):
    """Band storage base (ref: include/slate/BaseBandMatrix.hh).

    The band is kept inside the same blocked layout; tiles wholly outside the
    band are structural zeros (the pad invariant covers them), matching the
    reference's choice to simply not insert out-of-band tiles."""

    def __init__(self, storage, kl: int = 0, ku: int = 0, **kw):
        super().__init__(storage, **kw)
        self.kl, self.ku = int(kl), int(ku)

    def _extra_aux(self):
        return (self.kl, self.ku)

    def _apply_extra_aux(self, extra):
        self.kl, self.ku = extra

    def _expand(self, dense):
        m, n = dense.shape
        i = jnp.arange(m)[:, None]
        j = jnp.arange(n)[None, :]
        mask = (j - i <= self.ku) & (i - j <= self.kl)
        return jnp.where(mask, dense, jnp.zeros((), dense.dtype))


@jax.tree_util.register_pytree_node_class
class BandMatrix(BaseBandMatrix):
    """General band (ref: include/slate/BandMatrix.hh)."""

    @classmethod
    def from_numpy(cls, a, kl, ku, mb, grid=None):
        a = a if isinstance(a, np.ndarray) else jnp.asarray(a)
        st = TileStorage.from_dense(a, mb, mb, grid or Grid(1, 1))
        return cls(st, kl=kl, ku=ku)


@jax.tree_util.register_pytree_node_class
class TriangularBandMatrix(BaseBandMatrix):
    """ref: include/slate/TriangularBandMatrix.hh"""

    @classmethod
    def from_numpy(cls, a, kd, mb, uplo: Uplo = Uplo.Lower,
                   diag: Diag = Diag.NonUnit, grid=None):
        st = TileStorage.from_dense(jnp.asarray(a), mb, mb,
                                    grid or Grid(1, 1))
        return cls(st, kd=kd, uplo=uplo, diag=diag)

    def __init__(self, storage, kd: int = 0, uplo: Uplo = Uplo.Lower,
                 diag: Diag = Diag.NonUnit, **kw):
        kl, ku = (kd, 0) if uplo is Uplo.Lower else (0, kd)
        super().__init__(storage, kl=kl, ku=ku, **kw)
        self.uplo, self.diag, self.kd = uplo, diag, int(kd)

    def _extra_aux(self):
        return (self.kd, self.uplo, self.diag)

    def _apply_extra_aux(self, extra):
        self.kd, self.uplo, self.diag = extra
        self.kl, self.ku = (self.kd, 0) if self.uplo is Uplo.Lower \
            else (0, self.kd)

    def _expand(self, dense):
        band = BaseBandMatrix._expand(self, dense)
        if self.diag is Diag.Unit:
            k = min(dense.shape)
            band = band.at[jnp.arange(k), jnp.arange(k)].set(1)
        return band


@jax.tree_util.register_pytree_node_class
class HermitianBandMatrix(BaseBandMatrix):
    """ref: include/slate/HermitianBandMatrix.hh"""

    @classmethod
    def from_numpy(cls, a, kd, mb, uplo: Uplo = Uplo.Lower, grid=None):
        st = TileStorage.from_dense(jnp.asarray(a), mb, mb,
                                    grid or Grid(1, 1))
        return cls(st, kd=kd, uplo=uplo)

    def __init__(self, storage, kd: int = 0, uplo: Uplo = Uplo.Lower, **kw):
        kl, ku = (kd, 0) if uplo is Uplo.Lower else (0, kd)
        super().__init__(storage, kl=kl, ku=ku, **kw)
        self.uplo, self.kd = uplo, int(kd)

    def _extra_aux(self):
        return (self.kd, self.uplo)

    def _apply_extra_aux(self, extra):
        self.kd, self.uplo = extra
        self.kl, self.ku = (self.kd, 0) if self.uplo is Uplo.Lower \
            else (0, self.kd)

    def _expand(self, dense):
        band = BaseBandMatrix._expand(self, dense)
        d = jnp.real(jnp.diagonal(band)) if is_complex(self.dtype) \
            else jnp.diagonal(band)
        full = band + jnp.conj(band).T
        k = min(full.shape)
        return full.at[jnp.arange(k), jnp.arange(k)].set(d.astype(full.dtype))
