"""TileStorage: the distributed tile map as one sharded blocked array.

TPU-native analog of the reference's storage & coherency layer
(ref: include/slate/internal/MatrixStorage.hh:284-529 `MatrixStorage`,
Memory.hh:29-95 block pool, MatrixStorage.hh:33-148 MOSI states):

- The reference keeps a hash map {(i, j) -> TileNode} with one TileInstance
  per device plus MOSI (Modified/Shared/Invalid/OnHold) coherency because a
  tile may be replicated across host + several GPUs.  On TPU there is a single
  memory space per chip and XLA owns buffer lifetimes, so the map becomes ONE
  dense blocked array ``[p*mtl, q*ntl, mb, nb]`` in cyclic order, sharded over
  the mesh so device (r, c) holds exactly its 2D-block-cyclic tiles
  ``{(i, j) : i ≡ r (mod p), j ≡ c (mod q)}`` in HBM.  MOSI is unnecessary:
  functional arrays cannot alias-stale, which is the whole problem MOSI solves.
- The reference's `Memory` pool (per-device stacks of mb*nb blocks) maps to
  XLA's arena allocator: tiles of one matrix are a single contiguous HBM
  buffer, the strongest form of pooling.  Workspace "life" counters
  (MatrixStorage.hh:1274-1283 tileTick) map to SSA value lifetimes inside the
  compiled program — a broadcast panel dies when its last consumer retires,
  which XLA computes exactly rather than by reference counting.
- tileMb/tileNb/tileRank/tileDevice distribution lambdas
  (MatrixStorage.hh:533-586) are `tile_mb`/`tile_nb` here plus Grid's maps.

Storage is a registered pytree so matrices flow through jit/shard_map.
"""

from __future__ import annotations

import threading
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..exceptions import slate_error
from . import layout
from .grid import Grid


@jax.tree_util.register_pytree_node_class
class TileStorage:
    """Tiles of an m*n matrix in 2D block-cyclic order on a p*q grid.

    data[s, t] holds tile (i, j) with i = (s % mtl)*p + s//mtl,
    j = (t % ntl)*q + t//ntl; rows s // mtl == r live on mesh row r.
    """

    def __init__(self, data, m: int, n: int, mb: int, nb: int, grid: Grid):
        self.data = data
        self.m, self.n = int(m), int(n)
        self.mb, self.nb = int(mb), int(nb)
        self.grid = grid
        self.Mt = layout.num_tiles(self.m, self.mb)
        self.Nt = layout.num_tiles(self.n, self.nb)
        self.mtl = -(-self.Mt // grid.p)
        self.ntl = -(-self.Nt // grid.q)

    # ---- pytree ----
    def tree_flatten(self):
        return (self.data,), (self.m, self.n, self.mb, self.nb, self.grid)

    @classmethod
    def tree_unflatten(cls, aux, children):
        m, n, mb, nb, grid = aux
        return cls(children[0], m, n, mb, nb, grid)

    # ---- constructors ----
    @classmethod
    def zeros(cls, m, n, mb, nb, grid: Grid | None = None, dtype=jnp.float32):
        grid = grid or Grid(1, 1)
        Mt, Nt = layout.num_tiles(m, mb), layout.num_tiles(n, nb)
        mtl, ntl = -(-Mt // grid.p), -(-Nt // grid.q)
        data = jnp.zeros((grid.p * mtl, grid.q * ntl, mb, nb), dtype)
        st = cls(data, m, n, mb, nb, grid)
        return st._shard()

    @classmethod
    def from_dense(cls, dense, mb, nb, grid: Grid | None = None):
        """Import a host/global array (ref: Matrix::fromLAPACK, Matrix.hh:344).

        Host numpy f32/f64 inputs go through the NATIVE tile packer
        (native/slate_tpu_native.cc, OpenMP across tiles) when built —
        one memory-bandwidth pass instead of a device reshape/transpose
        chain; traced/device inputs use the jnp layout ops."""
        grid = grid or Grid(1, 1)
        if isinstance(dense, np.ndarray) and dense.ndim == 2:
            from .. import native as _native
            packed = _native.pack_tiles(dense, mb, nb, grid.p, grid.q)
            if packed is not None:
                st = cls(jnp.asarray(packed), dense.shape[0],
                         dense.shape[1], mb, nb, grid)
                return st._shard()
        dense = jnp.asarray(dense)
        slate_error(dense.ndim == 2, "from_dense needs a 2D array")
        tiles = layout.tile_dense(dense, mb, nb)
        data = layout.canonical_to_cyclic(tiles, grid.p, grid.q)
        st = cls(data, dense.shape[0], dense.shape[1], mb, nb, grid)
        return st._shard()

    @classmethod
    def from_canonical(cls, tiles, m, n, grid: Grid | None = None):
        grid = grid or Grid(1, 1)
        Mt, Nt, mb, nb = tiles.shape
        slate_error(Mt == layout.num_tiles(m, mb) and
                    Nt == layout.num_tiles(n, nb), "tile grid mismatch")
        data = layout.canonical_to_cyclic(tiles, grid.p, grid.q)
        st = cls(data, m, n, mb, nb, grid)
        return st._shard()

    def _shard(self) -> "TileStorage":
        sh = self.grid.tile_sharding()
        if sh is not None:
            try:
                self.data = jax.device_put(self.data, sh)
            except (AssertionError, ValueError):
                # an eager compute result can carry a GSPMD (non-Named)
                # sharding, and jax's different-device-order reshard
                # path only accepts NamedSharding inputs; bounce through
                # host for that cross-mesh corner (redistribute between
                # permuted grids) — only reachable eagerly
                self.data = jax.device_put(jax.device_get(self.data), sh)
        return self

    # ---- distribution lambdas (ref: MatrixStorage.hh:533-586) ----
    def tile_mb(self, i: int) -> int:
        """Rows in tile-row i (last tile may be partial)."""
        return self.mb if i < self.Mt - 1 else self.m - (self.Mt - 1) * self.mb

    def tile_nb(self, j: int) -> int:
        return self.nb if j < self.Nt - 1 else self.n - (self.Nt - 1) * self.nb

    def tile_rank(self, i: int, j: int) -> int:
        return self.grid.tile_rank(i, j)

    def tile_device(self, i: int, j: int):
        return self.grid.tile_device(i, j)

    # ---- views of the store ----
    def canonical(self):
        """Tiles in natural (i, j) order: [Mt, Nt, mb, nb]."""
        return layout.cyclic_to_canonical(
            self.data, self.Mt, self.Nt, self.grid.p, self.grid.q)

    def to_dense(self):
        return layout.untile_dense(self.canonical(), self.m, self.n)

    def with_canonical(self, tiles) -> "TileStorage":
        data = layout.canonical_to_cyclic(tiles, self.grid.p, self.grid.q)
        st = TileStorage(data, self.m, self.n, self.mb, self.nb, self.grid)
        return st._shard()

    def with_dense(self, dense) -> "TileStorage":
        return TileStorage.from_dense(dense, self.mb, self.nb, self.grid)

    def tile(self, i: int, j: int):
        """Fetch one tile (debug/test path; ref: BaseMatrix::at)."""
        ci, _, _ = layout.cyclic_row_maps(self.Mt, self.grid.p)
        cj, _, _ = layout.cyclic_row_maps(self.Nt, self.grid.q)
        return self.data[int(ci[i]), int(cj[j])]

    def set_tile(self, i: int, j: int, tile) -> "TileStorage":
        ci, _, _ = layout.cyclic_row_maps(self.Mt, self.grid.p)
        cj, _, _ = layout.cyclic_row_maps(self.Nt, self.grid.q)
        data = self.data.at[int(ci[i]), int(cj[j])].set(tile)
        return TileStorage(data, self.m, self.n, self.mb, self.nb, self.grid)

    @property
    def dtype(self):
        return self.data.dtype

    def astype(self, dtype) -> "TileStorage":
        """Precision-converting copy (ref: copy / gecopy convert path)."""
        return TileStorage(self.data.astype(dtype), self.m, self.n,
                           self.mb, self.nb, self.grid)

    def __repr__(self):
        return (f"TileStorage({self.m}x{self.n}, tiles {self.mb}x{self.nb}, "
                f"grid {self.grid.p}x{self.grid.q}, {self.dtype})")


# residency codes for TileMap._res
_RES_HOST = 0    # host bytes authoritative, no device copy
_RES_DEVICE = 1  # clean copy staged on device (prefetch in flight or held)
_RES_DIRTY = 2   # device bytes newer than host (writeback pending)

_RES_NAMES = {_RES_HOST: "host", _RES_DEVICE: "device", _RES_DIRTY: "dirty"}


class TileMap:
    """Host-resident tile map with per-tile residency for out-of-core work.

    The explicit analog of the reference's ``MatrixStorage`` tile map with
    host/device coherency (ref: include/slate/internal/MatrixStorage.hh
    MOSI states, PAPER L3/L4): where ``TileStorage`` above collapses the
    map into one HBM-resident sharded array (fine when the matrix fits),
    ``TileMap`` keeps the authoritative bytes in host RAM and streams
    panel-shaped windows to the device on demand, so ``getrf``/``potrf``
    run at n beyond device memory.  The three-state residency ledger is
    the MOSI subset that matters on a single-memory-space accelerator:

    - ``host``    host bytes authoritative, nothing staged,
    - ``device``  a clean copy staged in HBM (``prefetch`` issued),
    - ``dirty``   device bytes newer than host (``store`` writeback
      pending until :meth:`drain`).

    Copies are ASYNC on both axes — ``jax.device_put`` for H2D and
    ``copy_to_host_async`` for D2H — so the OOC loops overlap the next
    panel's transfer against the current panel's update, the PR 15
    hide-communication discipline applied to the host-device axis.
    Double-buffer protocol: ``prefetch(region)`` stages the next window
    while compute runs; ``fetch(region)`` consumes (pops) the staged
    buffer or falls back to a synchronous-dispatch H2D on a miss;
    ``store(region, arr)`` queues an async writeback.  ``drain`` (called
    automatically by the first fetch after a store, and explicitly before
    a checkpoint snapshot) lands pending writebacks into host RAM.

    Thread safety: the residency ledger (``_res``), the staged-buffer
    table (``_device``) and the writeback queue (``_pending``) are
    guarded by ``_lock`` (see tools/slate_lint LOCK_REGISTRY) so a
    checkpoint/observer thread can read residency while the factorization
    thread streams.  Blocking work — chaos stalls, host materialization —
    happens OUTSIDE the lock.
    """

    def __init__(self, dense: np.ndarray, mb: int, nb: int,
                 max_pending: int = 4):
        slate_error(np.ndim(dense) == 2, "TileMap needs a 2D host array")
        self._host = np.array(dense, copy=True, order="C")
        self.m, self.n = self._host.shape
        self.mb, self.nb = int(mb), int(nb)
        # writeback queue depth before a forced drain: bounds how much
        # device memory in-flight D2H copies can pin
        self.max_pending = max(1, int(max_pending))
        self.Mt = layout.num_tiles(self.m, self.mb)
        self.Nt = layout.num_tiles(self.n, self.nb)
        self._res = np.zeros((self.Mt, self.Nt), np.uint8)
        self._device: dict[tuple, Any] = {}
        self._pending: list[tuple] = []
        self._lock = threading.Lock()

    @classmethod
    def from_dense(cls, dense, mb: int, nb: int) -> "TileMap":
        return cls(np.asarray(dense), mb, nb)

    # ---- residency ledger ----
    def _tiles_of(self, r0, r1, c0, c1):
        return (slice(r0 // self.mb, -(-r1 // self.mb)),
                slice(c0 // self.nb, -(-c1 // self.nb)))

    def residency(self, i: int, j: int) -> str:
        """Residency of tile (i, j): 'host' | 'device' | 'dirty'."""
        with self._lock:
            return _RES_NAMES[int(self._res[i, j])]

    def residency_counts(self) -> dict:
        with self._lock:
            counts = np.bincount(self._res.reshape(-1), minlength=3)
        return {name: int(counts[code]) for code, name in _RES_NAMES.items()}

    @staticmethod
    def _stall() -> None:
        # chaos: a congested host<->device copy path (docs/ROBUSTNESS.md);
        # the sleep must stay outside _lock (CON003)
        from ..robust import faults
        plan = faults.host_fire("ooc_copy_stall")
        if plan is not None and plan.delay_s > 0:
            time.sleep(plan.delay_s)

    @staticmethod
    def _hits(key: tuple, other: tuple) -> bool:
        return not (other[1] <= key[0] or other[0] >= key[1]
                    or other[3] <= key[2] or other[2] >= key[3])

    # ---- streaming ----
    def prefetch(self, r0: int, r1: int, c0: int, c1: int) -> None:
        """Stage host window [r0:r1, c0:c1] on device (async H2D)."""
        key = (int(r0), int(r1), int(c0), int(c1))
        with self._lock:
            staged = key in self._device
            conflict = any(self._hits(key, p[0]) for p in self._pending)
        if staged:
            return
        self._stall()
        if conflict:
            self.drain()
        buf = jax.device_put(self._host[r0:r1, c0:c1])
        ti, tj = self._tiles_of(*key)
        with self._lock:
            self._device[key] = buf
            self._res[ti, tj] = np.maximum(self._res[ti, tj], _RES_DEVICE)

    def fetch(self, r0: int, r1: int, c0: int, c1: int):
        """Consume the staged window (pop), or H2D it on a miss.

        A window overlapping a pending writeback drains first, so a
        fetch always observes the newest bytes; disjoint windows ride
        through without serializing against in-flight D2H copies."""
        key = (int(r0), int(r1), int(c0), int(c1))
        with self._lock:
            buf = self._device.pop(key, None)
            conflict = any(self._hits(key, p[0]) for p in self._pending)
        if buf is not None:
            return buf
        self._stall()
        if conflict:
            self.drain()
        buf = jax.device_put(self._host[r0:r1, c0:c1])
        ti, tj = self._tiles_of(*key)
        with self._lock:
            self._res[ti, tj] = np.maximum(self._res[ti, tj], _RES_DEVICE)
        return buf

    def store(self, r0: int, r1: int, c0: int, c1: int, arr) -> None:
        """Queue an async writeback of device ``arr`` into the window."""
        key = (int(r0), int(r1), int(c0), int(c1))
        slate_error(arr.shape == (r1 - r0, c1 - c0),
                    f"store shape {arr.shape} != window "
                    f"({r1 - r0},{c1 - c0})")
        self._stall()
        if hasattr(arr, "copy_to_host_async"):
            arr.copy_to_host_async()
        ti, tj = self._tiles_of(*key)
        with self._lock:
            self._pending.append((key, arr))
            depth = len(self._pending)
            self._res[ti, tj] = _RES_DIRTY
            # staged clean copies overlapping a dirty window are stale
            for k in [k for k in self._device if self._hits(key, k)]:
                del self._device[k]
        if depth > self.max_pending:
            self.drain()

    def drain(self) -> None:
        """Land every pending writeback in host RAM (blocks)."""
        with self._lock:
            pending, self._pending = self._pending, []
        for (r0, r1, c0, c1), arr in pending:
            self._host[r0:r1, c0:c1] = np.asarray(arr)
        if pending:
            with self._lock:
                for (r0, r1, c0, c1), _ in pending:
                    ti, tj = self._tiles_of(r0, r1, c0, c1)
                    self._res[ti, tj] = _RES_HOST

    def permute_rows(self, r0: int, c0: int, c1: int, perm) -> None:
        """Host-side row permutation of the window [r0:, c0:c1] — the LU
        left-columns pivot exchange: pure memory traffic, so it stays on
        the host where the authoritative bytes already live."""
        self.drain()
        if c1 > c0:
            self._host[r0:, c0:c1] = self._host[r0:, c0:c1][np.asarray(perm)]

    # ---- host views ----
    def host_array(self) -> np.ndarray:
        """The authoritative host bytes after draining writebacks.

        Returns the live backing array (no copy): callers snapshotting it
        (the checkpoint writer) must copy or serialize before the next
        factorization step mutates it."""
        self.drain()
        return self._host

    def to_dense(self) -> np.ndarray:
        return self.host_array().copy()

    @property
    def dtype(self):
        return self._host.dtype

    @property
    def nbytes(self) -> int:
        return self._host.nbytes

    def __repr__(self):
        counts = self.residency_counts()
        return (f"TileMap({self.m}x{self.n}, tiles {self.mb}x{self.nb}, "
                f"{self.dtype}, residency {counts})")
