"""TileStorage: the distributed tile map as one sharded blocked array.

TPU-native analog of the reference's storage & coherency layer
(ref: include/slate/internal/MatrixStorage.hh:284-529 `MatrixStorage`,
Memory.hh:29-95 block pool, MatrixStorage.hh:33-148 MOSI states):

- The reference keeps a hash map {(i, j) -> TileNode} with one TileInstance
  per device plus MOSI (Modified/Shared/Invalid/OnHold) coherency because a
  tile may be replicated across host + several GPUs.  On TPU there is a single
  memory space per chip and XLA owns buffer lifetimes, so the map becomes ONE
  dense blocked array ``[p*mtl, q*ntl, mb, nb]`` in cyclic order, sharded over
  the mesh so device (r, c) holds exactly its 2D-block-cyclic tiles
  ``{(i, j) : i ≡ r (mod p), j ≡ c (mod q)}`` in HBM.  MOSI is unnecessary:
  functional arrays cannot alias-stale, which is the whole problem MOSI solves.
- The reference's `Memory` pool (per-device stacks of mb*nb blocks) maps to
  XLA's arena allocator: tiles of one matrix are a single contiguous HBM
  buffer, the strongest form of pooling.  Workspace "life" counters
  (MatrixStorage.hh:1274-1283 tileTick) map to SSA value lifetimes inside the
  compiled program — a broadcast panel dies when its last consumer retires,
  which XLA computes exactly rather than by reference counting.
- tileMb/tileNb/tileRank/tileDevice distribution lambdas
  (MatrixStorage.hh:533-586) are `tile_mb`/`tile_nb` here plus Grid's maps.

Storage is a registered pytree so matrices flow through jit/shard_map.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..exceptions import slate_error
from . import layout
from .grid import Grid


@jax.tree_util.register_pytree_node_class
class TileStorage:
    """Tiles of an m*n matrix in 2D block-cyclic order on a p*q grid.

    data[s, t] holds tile (i, j) with i = (s % mtl)*p + s//mtl,
    j = (t % ntl)*q + t//ntl; rows s // mtl == r live on mesh row r.
    """

    def __init__(self, data, m: int, n: int, mb: int, nb: int, grid: Grid):
        self.data = data
        self.m, self.n = int(m), int(n)
        self.mb, self.nb = int(mb), int(nb)
        self.grid = grid
        self.Mt = layout.num_tiles(self.m, self.mb)
        self.Nt = layout.num_tiles(self.n, self.nb)
        self.mtl = -(-self.Mt // grid.p)
        self.ntl = -(-self.Nt // grid.q)

    # ---- pytree ----
    def tree_flatten(self):
        return (self.data,), (self.m, self.n, self.mb, self.nb, self.grid)

    @classmethod
    def tree_unflatten(cls, aux, children):
        m, n, mb, nb, grid = aux
        return cls(children[0], m, n, mb, nb, grid)

    # ---- constructors ----
    @classmethod
    def zeros(cls, m, n, mb, nb, grid: Grid | None = None, dtype=jnp.float32):
        grid = grid or Grid(1, 1)
        Mt, Nt = layout.num_tiles(m, mb), layout.num_tiles(n, nb)
        mtl, ntl = -(-Mt // grid.p), -(-Nt // grid.q)
        data = jnp.zeros((grid.p * mtl, grid.q * ntl, mb, nb), dtype)
        st = cls(data, m, n, mb, nb, grid)
        return st._shard()

    @classmethod
    def from_dense(cls, dense, mb, nb, grid: Grid | None = None):
        """Import a host/global array (ref: Matrix::fromLAPACK, Matrix.hh:344).

        Host numpy f32/f64 inputs go through the NATIVE tile packer
        (native/slate_tpu_native.cc, OpenMP across tiles) when built —
        one memory-bandwidth pass instead of a device reshape/transpose
        chain; traced/device inputs use the jnp layout ops."""
        grid = grid or Grid(1, 1)
        if isinstance(dense, np.ndarray) and dense.ndim == 2:
            from .. import native as _native
            packed = _native.pack_tiles(dense, mb, nb, grid.p, grid.q)
            if packed is not None:
                st = cls(jnp.asarray(packed), dense.shape[0],
                         dense.shape[1], mb, nb, grid)
                return st._shard()
        dense = jnp.asarray(dense)
        slate_error(dense.ndim == 2, "from_dense needs a 2D array")
        tiles = layout.tile_dense(dense, mb, nb)
        data = layout.canonical_to_cyclic(tiles, grid.p, grid.q)
        st = cls(data, dense.shape[0], dense.shape[1], mb, nb, grid)
        return st._shard()

    @classmethod
    def from_canonical(cls, tiles, m, n, grid: Grid | None = None):
        grid = grid or Grid(1, 1)
        Mt, Nt, mb, nb = tiles.shape
        slate_error(Mt == layout.num_tiles(m, mb) and
                    Nt == layout.num_tiles(n, nb), "tile grid mismatch")
        data = layout.canonical_to_cyclic(tiles, grid.p, grid.q)
        st = cls(data, m, n, mb, nb, grid)
        return st._shard()

    def _shard(self) -> "TileStorage":
        sh = self.grid.tile_sharding()
        if sh is not None:
            try:
                self.data = jax.device_put(self.data, sh)
            except (AssertionError, ValueError):
                # an eager compute result can carry a GSPMD (non-Named)
                # sharding, and jax's different-device-order reshard
                # path only accepts NamedSharding inputs; bounce through
                # host for that cross-mesh corner (redistribute between
                # permuted grids) — only reachable eagerly
                self.data = jax.device_put(jax.device_get(self.data), sh)
        return self

    # ---- distribution lambdas (ref: MatrixStorage.hh:533-586) ----
    def tile_mb(self, i: int) -> int:
        """Rows in tile-row i (last tile may be partial)."""
        return self.mb if i < self.Mt - 1 else self.m - (self.Mt - 1) * self.mb

    def tile_nb(self, j: int) -> int:
        return self.nb if j < self.Nt - 1 else self.n - (self.Nt - 1) * self.nb

    def tile_rank(self, i: int, j: int) -> int:
        return self.grid.tile_rank(i, j)

    def tile_device(self, i: int, j: int):
        return self.grid.tile_device(i, j)

    # ---- views of the store ----
    def canonical(self):
        """Tiles in natural (i, j) order: [Mt, Nt, mb, nb]."""
        return layout.cyclic_to_canonical(
            self.data, self.Mt, self.Nt, self.grid.p, self.grid.q)

    def to_dense(self):
        return layout.untile_dense(self.canonical(), self.m, self.n)

    def with_canonical(self, tiles) -> "TileStorage":
        data = layout.canonical_to_cyclic(tiles, self.grid.p, self.grid.q)
        st = TileStorage(data, self.m, self.n, self.mb, self.nb, self.grid)
        return st._shard()

    def with_dense(self, dense) -> "TileStorage":
        return TileStorage.from_dense(dense, self.mb, self.nb, self.grid)

    def tile(self, i: int, j: int):
        """Fetch one tile (debug/test path; ref: BaseMatrix::at)."""
        ci, _, _ = layout.cyclic_row_maps(self.Mt, self.grid.p)
        cj, _, _ = layout.cyclic_row_maps(self.Nt, self.grid.q)
        return self.data[int(ci[i]), int(cj[j])]

    def set_tile(self, i: int, j: int, tile) -> "TileStorage":
        ci, _, _ = layout.cyclic_row_maps(self.Mt, self.grid.p)
        cj, _, _ = layout.cyclic_row_maps(self.Nt, self.grid.q)
        data = self.data.at[int(ci[i]), int(cj[j])].set(tile)
        return TileStorage(data, self.m, self.n, self.mb, self.nb, self.grid)

    @property
    def dtype(self):
        return self.data.dtype

    def astype(self, dtype) -> "TileStorage":
        """Precision-converting copy (ref: copy / gecopy convert path)."""
        return TileStorage(self.data.astype(dtype), self.m, self.n,
                           self.mb, self.nb, self.grid)

    def __repr__(self):
        return (f"TileStorage({self.m}x{self.n}, tiles {self.mb}x{self.nb}, "
                f"grid {self.grid.p}x{self.grid.q}, {self.dtype})")
