"""Process grid: 2D block-cyclic tile->device mapping over a jax Mesh.

TPU-native analog of the reference's process-grid layer:

- 2D block-cyclic tile->rank map over a p*q grid with Col/Row major rank
  ordering (ref: include/slate/internal/MatrixStorage.hh:555-568,
  include/slate/BaseMatrix.hh:885-915, enums.hh:127-131 GridOrder).
- The reference separates MPI rank (inter-node) from device id (intra-node,
  1D col-block-cyclic, MatrixStorage.hh:575-586).  On TPU there is one level:
  each mesh coordinate (r, c) IS a chip, and collectives ride ICI along the
  mesh axes, so the two maps collapse into one.

The grid also owns the functional analog of the reference's per-device queue
set (MatrixStorage.hh:651-667 initQueues): on TPU, XLA's async dispatch plus
program-order scheduling replace explicit comm/compute queues; overlap is
obtained by issuing independent computations, not by managing streams.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..exceptions import slate_error
from ..options import GridOrder

# Mesh axis names used throughout the framework.  'p' indexes process-grid
# rows, 'q' process-grid columns (ref: p x q grid in BaseMatrix.hh:885).
AXIS_P = "p"
AXIS_Q = "q"

# Canonical PartitionSpec for block-cyclic local tile storage
# [mtl, ntl, nb, nb]: the two leading (tile-grid) dims are sharded over
# the mesh axes, the within-tile dims are replicated.  Every shard_map
# driver in parallel/ uses this spec; keeping it next to the axis names
# means a mesh rename cannot strand a stale spec.
TILE_SPEC = P(AXIS_P, AXIS_Q, None, None)


class Grid:
    """A p*q process grid backed by a ``jax.sharding.Mesh``.

    ``Grid(1, 1)`` is the serial fallback: no mesh, all data on the default
    device — the analog of the reference's MPI stubs build
    (ref: src/stubs/mpi_stubs.cc) in which every collective degenerates to a
    self-copy.
    """

    def __init__(self, p: int = 1, q: int = 1, *,
                 devices: Sequence[jax.Device] | None = None,
                 order: GridOrder = GridOrder.Col):
        slate_error(p >= 1 and q >= 1, "grid dims must be >= 1")
        self.p = p
        self.q = q
        self.order = order
        self.size = p * q
        if self.size == 1 and devices is None:
            self.mesh = None
            return
        if devices is None:
            devices = jax.devices()
        slate_error(len(devices) >= p * q,
                    f"need {p * q} devices, have {len(devices)}")
        devs = np.asarray(devices[: p * q], dtype=object)
        if order is GridOrder.Col:
            # rank = r + c*p  -> device array indexed [r, c]
            arr = devs.reshape(q, p).T
        else:
            arr = devs.reshape(p, q)
        self.mesh = Mesh(arr, (AXIS_P, AXIS_Q))

    # ---- tile -> coordinate maps (ref: MatrixStorage.hh:555-568) ----

    def tile_coords(self, i: int, j: int) -> tuple[int, int]:
        """2D block-cyclic owner coordinate of tile (i, j)."""
        return (i % self.p, j % self.q)

    def tile_rank(self, i: int, j: int) -> int:
        """Linear rank of tile (i, j)'s owner under this grid's GridOrder."""
        r, c = self.tile_coords(i, j)
        return r + c * self.p if self.order is GridOrder.Col else r * self.q + c

    def tile_device(self, i: int, j: int) -> jax.Device | None:
        """Owning jax device (ref: tileDevice lambda, MatrixStorage.hh:575)."""
        if self.mesh is None:
            return None
        r, c = self.tile_coords(i, j)
        return self.mesh.devices[r, c]

    # ---- shardings ----

    def tile_sharding(self) -> NamedSharding | None:
        """Sharding for cyclic-ordered tile storage [p*mtl, q*ntl, mb, nb]."""
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, P(AXIS_P, AXIS_Q, None, None))

    def replicated_sharding(self) -> NamedSharding | None:
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, P())

    def __repr__(self):
        return f"Grid(p={self.p}, q={self.q}, order={self.order.value})"


def make_grid(n_devices: int | None = None, *,
              devices: Sequence[jax.Device] | None = None) -> Grid:
    """Pick a near-square p*q factorisation of the available devices."""
    if devices is None:
        devices = jax.devices()
    n = n_devices if n_devices is not None else len(devices)
    if n == 1:
        return Grid(1, 1)
    p = int(math.sqrt(n))
    while n % p != 0:
        p -= 1
    return Grid(p, n // p, devices=devices)
