"""Batched norm kernels: Max / One / Inf / Fro for all matrix structures.

TPU-native analog of the reference's norm kernel family (ref:
src/internal/internal_genorm.cc:812, internal_synorm.cc, internal_henorm.cc,
internal_trnorm.cc, internal_gbnorm.cc, internal_hbnorm.cc and the CUDA
side src/cuda/device_genorm.cu:43-50 etc.), including the scaled-sumsq
formulation of the Frobenius norm (LAPACK lassq discipline) that avoids
overflow/underflow — reproduced here with jnp reductions in the value/scale
pair form.

The cross-rank MPI_Allreduce the reference drivers do (src/norm.cc) is a
psum/pmax along both mesh axes in the mesh driver; kernels here are
single-program over canonical tiles with explicit validity masks.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..types import Norm
from .elementwise import entry_mask, tri_mask


def _abs(x):
    return jnp.abs(x)


def _masked(a_tiles, mask):
    return jnp.where(mask, _abs(a_tiles), jnp.zeros_like(_abs(a_tiles)))


def _sumsq_scaled(absa):
    """(scale, sumsq) such that ||x||_F = scale*sqrt(sumsq)
    (ref: lassq-style scaled accumulation used by genorm Fro)."""
    scale = jnp.max(absa)
    scale_safe = jnp.where(scale == 0, jnp.ones_like(scale), scale)
    s = jnp.sum((absa / scale_safe) ** 2)
    return scale, s


def ge_norm(norm: Norm, a_tiles, m, n, mb, nb):
    """General-matrix norm over masked tiles (ref: internal_genorm.cc)."""
    mask = entry_mask(m, n, mb, nb)
    absa = _masked(a_tiles, mask)
    if norm is Norm.Max:
        return jnp.max(absa)
    if norm is Norm.One:                      # max column sum
        colsums = jnp.sum(absa, axis=(0, 2))  # [Nt, nb]
        return jnp.max(colsums)
    if norm is Norm.Inf:                      # max row sum
        rowsums = jnp.sum(absa, axis=(1, 3))  # [Mt, mb]
        return jnp.max(rowsums)
    if norm is Norm.Fro:
        scale, s = _sumsq_scaled(absa)
        return scale * jnp.sqrt(s)
    # slate-lint: disable=TRC006 -- static Norm enum fall-through: fails at trace time, never in-graph
    raise ValueError(norm)


def ge_col_norms(a_tiles, m, n, mb, nb):
    """Per-column max-abs (ref: colNorms, Norm::Max scope=Columns,
    internal_genorm.cc NormScope::Columns path). Returns [n]."""
    mask = entry_mask(m, n, mb, nb)
    absa = _masked(a_tiles, mask)
    Nt = a_tiles.shape[1]
    per_col = jnp.max(absa, axis=(0, 2))      # [Nt, nb]
    return per_col.reshape(Nt * nb)[:n]


def tr_norm(norm: Norm, a_tiles, m, n, mb, nb, uplo_lower, unit_diag=False):
    """Trapezoid/triangular norm (ref: internal_trnorm.cc:815)."""
    mask = entry_mask(m, n, mb, nb) & tri_mask(m, n, mb, nb, uplo_lower,
                                               strict=unit_diag)
    absa = _masked(a_tiles, mask)
    if unit_diag:
        # add implicit unit diagonal contributions
        k = min(m, n)
        diag = _diag_mask(a_tiles.shape, mb, nb, k)
        absa = jnp.where(diag, jnp.ones_like(absa), absa)
    if norm is Norm.Max:
        return jnp.max(absa)
    if norm is Norm.One:
        return jnp.max(jnp.sum(absa, axis=(0, 2)))
    if norm is Norm.Inf:
        return jnp.max(jnp.sum(absa, axis=(1, 3)))
    if norm is Norm.Fro:
        scale, s = _sumsq_scaled(absa)
        return scale * jnp.sqrt(s)
    # slate-lint: disable=TRC006 -- static Norm enum fall-through: fails at trace time, never in-graph
    raise ValueError(norm)


def _diag_mask(shape, mb, nb, k):
    import numpy as np
    Mt, Nt, mb_, nb_ = shape
    gi = np.arange(Mt)[:, None, None, None] * mb + \
        np.arange(mb_)[None, None, :, None]
    gj = np.arange(Nt)[None, :, None, None] * nb + \
        np.arange(nb_)[None, None, None, :]
    return jnp.asarray((gi == gj) & (gi < k))


def sy_norm(norm: Norm, a_tiles, n, nb, uplo_lower, hermitian=False):
    """Symmetric/Hermitian norm from one stored triangle
    (ref: internal_synorm.cc:842, internal_henorm.cc:780).

    One == Inf by symmetry; row/col sums combine the stored triangle with
    its mirrored counterpart exactly once (diagonal not double-counted)."""
    mask_full = entry_mask(n, n, nb, nb)
    tri = tri_mask(n, n, nb, nb, uplo_lower)
    stri = tri_mask(n, n, nb, nb, uplo_lower, strict=True)
    absa = _masked(a_tiles, mask_full & tri)
    abs_strict = _masked(a_tiles, mask_full & stri)
    if norm is Norm.Max:
        return jnp.max(absa)
    if norm in (Norm.One, Norm.Inf):
        col = jnp.sum(absa, axis=(0, 2))          # stored triangle col sums
        row_of_strict = jnp.sum(abs_strict, axis=(1, 3))  # mirrored part
        total = col.reshape(-1) + row_of_strict.reshape(-1)
        return jnp.max(total)
    if norm is Norm.Fro:
        scale, s = _sumsq_scaled(abs_strict)
        # off-diagonal counted twice + diagonal once
        diag = _masked(a_tiles, mask_full & tri & ~stri)
        dscale, ds = _sumsq_scaled(diag)
        tot = jnp.sqrt(2.0 * (scale ** 2) * s + (dscale ** 2) * ds)
        return tot
    # slate-lint: disable=TRC006 -- static Norm enum fall-through: fails at trace time, never in-graph
    raise ValueError(norm)


def band_mask(m, n, mb, nb, kl, ku):
    import numpy as np
    Mt, Nt = -(-m // mb), -(-n // nb)
    gi = (np.arange(Mt)[:, None, None, None] * mb +
          np.arange(mb)[None, None, :, None])
    gj = (np.arange(Nt)[None, :, None, None] * nb +
          np.arange(nb)[None, None, None, :])
    return jnp.asarray((gj - gi <= ku) & (gi - gj <= kl))


def gb_norm(norm: Norm, a_tiles, m, n, mb, nb, kl, ku):
    """General band norm (ref: internal_gbnorm.cc:627)."""
    mask = entry_mask(m, n, mb, nb) & band_mask(m, n, mb, nb, kl, ku)
    absa = _masked(a_tiles, mask)
    if norm is Norm.Max:
        return jnp.max(absa)
    if norm is Norm.One:
        return jnp.max(jnp.sum(absa, axis=(0, 2)))
    if norm is Norm.Inf:
        return jnp.max(jnp.sum(absa, axis=(1, 3)))
    if norm is Norm.Fro:
        scale, s = _sumsq_scaled(absa)
        return scale * jnp.sqrt(s)
    # slate-lint: disable=TRC006 -- static Norm enum fall-through: fails at trace time, never in-graph
    raise ValueError(norm)


def hb_norm(norm: Norm, a_tiles, n, nb, kd, uplo_lower):
    """Hermitian band norm (ref: internal_hbnorm.cc:761)."""
    kl, ku = (kd, 0) if uplo_lower else (0, kd)
    mask = (entry_mask(n, n, nb, nb) & band_mask(n, n, nb, nb, kl, ku) &
            tri_mask(n, n, nb, nb, uplo_lower))
    stri = tri_mask(n, n, nb, nb, uplo_lower, strict=True)
    absa = _masked(a_tiles, mask)
    abs_strict = _masked(a_tiles, mask & stri)
    if norm is Norm.Max:
        return jnp.max(absa)
    if norm in (Norm.One, Norm.Inf):
        col = jnp.sum(absa, axis=(0, 2)).reshape(-1)
        row = jnp.sum(abs_strict, axis=(1, 3)).reshape(-1)
        return jnp.max(col + row)
    if norm is Norm.Fro:
        oscale, os = _sumsq_scaled(abs_strict)
        diag = _masked(a_tiles, mask & ~stri)
        dscale, ds = _sumsq_scaled(diag)
        return jnp.sqrt(2.0 * (oscale ** 2) * os + (dscale ** 2) * ds)
    # slate-lint: disable=TRC006 -- static Norm enum fall-through: fails at trace time, never in-graph
    raise ValueError(norm)
