"""Batched elementwise tile kernels.

TPU-native analog of the reference's device kernel set (ref: src/cuda/
device_geadd.cu, device_gecopy.cu, device_gescale.cu,
device_gescale_row_col.cu, device_geset.cu, device_transpose.cu and the tz*
triangular variants device_tzadd.cu/tzcopy/tzscale/tzset; dispatched through
src/internal/internal_geadd.cc:494, internal_gecopy.cc, internal_gescale.cc,
internal_geset.cc etc.).

The reference launches one CUDA block per tile over pointer arrays; here each
kernel is ONE vectorised XLA op over the canonical tile array
``[Mt, Nt, mb, nb]`` — XLA fuses chains of them into single HBM passes, which
is the TPU replacement for hand-fused kernels.

Triangular (tz*) variants take an ``uplo`` and a per-tile role: tiles strictly
below/above the block diagonal are full; diagonal-block tiles get an
elementwise triangle mask — exactly the lower/upper split the reference makes
per-tile (device_tzset.cu).

All kernels preserve the pad-region-zero invariant (masks supplied by
:func:`valid_masks`).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def valid_masks(m, n, mb, nb):
    """Boolean masks of valid (non-pad) entries: ([Mt, mb], [Nt, nb])."""
    Mt, Nt = -(-m // mb), -(-n // nb)
    ri = np.arange(Mt)[:, None] * mb + np.arange(mb)[None, :]
    cj = np.arange(Nt)[:, None] * nb + np.arange(nb)[None, :]
    return jnp.asarray(ri < m), jnp.asarray(cj < n)


def entry_mask(m, n, mb, nb):
    """[Mt, Nt, mb, nb] mask of valid entries."""
    rm, cm = valid_masks(m, n, mb, nb)
    return rm[:, None, :, None] & cm[None, :, None, :]


def tri_mask(m, n, mb, nb, uplo_lower: bool, strict: bool = False):
    """[Mt, Nt, mb, nb] triangle mask over GLOBAL indices (tz* kernels)."""
    Mt, Nt = -(-m // mb), -(-n // nb)
    gi = (np.arange(Mt)[:, None] * mb + np.arange(mb)[None, :])
    gj = (np.arange(Nt)[:, None] * nb + np.arange(nb)[None, :])
    gi = gi[:, None, :, None]
    gj = gj[None, :, None, :]
    if uplo_lower:
        mask = (gi > gj) if strict else (gi >= gj)
    else:
        mask = (gi < gj) if strict else (gi <= gj)
    return jnp.asarray(mask)


# ---- general kernels (ge*) ----

def geadd(alpha, a_tiles, beta, b_tiles):
    """B = alpha*A + beta*B (ref: device_geadd.cu, internal_geadd.cc)."""
    return alpha * a_tiles + beta * b_tiles


def gecopy(a_tiles, dtype=None):
    """Precision-converting copy (ref: device_gecopy.cu; copy driver
    src/copy.cc supports inter-precision copies)."""
    return a_tiles.astype(dtype) if dtype is not None else a_tiles


def gescale(numer, denom, a_tiles):
    """A *= numer/denom (ref: device_gescale.cu safe-scaling signature)."""
    return a_tiles * (numer / denom)


def gescale_row_col(r, c, a_tiles, m, n, mb, nb):
    """A[i, j] *= r[i] * c[j] (ref: device_gescale_row_col.cu, used by
    equilibration).  r: [m], c: [n] vectors."""
    Mt, Nt = -(-m // mb), -(-n // nb)
    rp = jnp.pad(r, (0, Mt * mb - m)).reshape(Mt, mb)
    cp = jnp.pad(c, (0, Nt * nb - n)).reshape(Nt, nb)
    return a_tiles * rp[:, None, :, None] * cp[None, :, None, :]


def geset(offdiag, diag, like_tiles, m, n, mb, nb):
    """A = offdiag everywhere, diag on the diagonal (ref: device_geset.cu;
    geset(0, 1) builds identity).  Pad region set to zero."""
    Mt, Nt, _, _ = like_tiles.shape
    gi = np.arange(Mt)[:, None, None, None] * mb + \
        np.arange(mb)[None, None, :, None]
    gj = np.arange(Nt)[None, :, None, None] * nb + \
        np.arange(nb)[None, None, None, :]
    eye = jnp.asarray(gi == gj)
    out = jnp.where(eye, diag, offdiag) * jnp.ones_like(like_tiles)
    return out * entry_mask(m, n, mb, nb).astype(like_tiles.dtype)


def transpose_tiles(a_tiles, conj=False):
    """Out-of-place blocked transpose: [Mt,Nt,mb,nb] -> [Nt,Mt,nb,mb]
    (ref: device_transpose.cu in/out-of-place batched transpose)."""
    t = a_tiles.transpose(1, 0, 3, 2)
    return jnp.conj(t) if conj else t


# ---- triangular/trapezoid kernels (tz*) ----

def tzadd(alpha, a_tiles, beta, b_tiles, m, n, mb, nb, uplo_lower):
    """Triangle-masked add (ref: device_tzadd.cu)."""
    mask = tri_mask(m, n, mb, nb, uplo_lower)
    return jnp.where(mask, alpha * a_tiles + beta * b_tiles, b_tiles)


def tzcopy(a_tiles, b_tiles, m, n, mb, nb, uplo_lower, dtype=None):
    """Triangle-masked converting copy (ref: device_tzcopy.cu)."""
    src = a_tiles.astype(dtype or b_tiles.dtype)
    mask = tri_mask(m, n, mb, nb, uplo_lower)
    return jnp.where(mask, src, b_tiles)


def tzscale(numer, denom, a_tiles, m, n, mb, nb, uplo_lower):
    """Triangle-masked scale (ref: device_tzscale.cu)."""
    mask = tri_mask(m, n, mb, nb, uplo_lower)
    return jnp.where(mask, a_tiles * (numer / denom), a_tiles)


def tzset(offdiag, diag, like_tiles, m, n, mb, nb, uplo_lower):
    """Triangle set (ref: device_tzset.cu)."""
    full = geset(offdiag, offdiag, like_tiles, m, n, mb, nb)
    Mt, Nt, mb_, nb_ = like_tiles.shape
    gi = np.arange(Mt)[:, None, None, None] * mb + \
        np.arange(mb_)[None, None, :, None]
    gj = np.arange(Nt)[None, :, None, None] * nb + \
        np.arange(nb_)[None, None, None, :]
    eye = jnp.asarray(gi == gj)
    full = jnp.where(eye, diag, full)
    mask = tri_mask(m, n, mb, nb, uplo_lower) & entry_mask(m, n, mb, nb)
    return jnp.where(mask, full, jnp.zeros_like(full))
