"""Mesh collectives: the TPU-native communication backend.

Analog of the reference's MPI tile-communication layer (ref:
include/slate/BaseMatrix.hh:1923-2492 listBcast/listBcastMT/listReduce,
src/internal/internal_comm.cc:17-123 hypercube patterns + subcommunicators,
src/stubs/mpi_stubs.cc serial fallback).

Mapping:

- ``BcastList`` — "broadcast tile (i, k) to the ranks owning row i / col j"
  (BaseMatrix.hh:42-55) — becomes :func:`bcast_along` : a masked ``psum`` (or
  one-hot ``all_gather`` pick) along a mesh axis, executed inside shard_map.
  The root is *data-dependent* (owner column k % q), which MPI expresses with
  per-tile point-to-point trees and XLA expresses with a single collective
  whose contribution is masked to the owner.  On TPU ICI the collective IS a
  near-optimal ring/tree — the hand-built radix-4 hypercube of
  ``listBcastMT`` (BaseMatrix.hh:2073-2174) is what XLA emits natively.
- ``ReduceList`` (BaseMatrix.hh:2180-2217) becomes :func:`reduce_along` — a
  ``psum`` whose result only the root keeps (others discard), or a full psum
  when every rank wants the sum.
- Panel subcommunicators (internal_comm.cc:17-48, used by the LU panel's
  MAXLOC allreduce, Tile_getrf.hh:260-315) become reductions along ONE mesh
  axis: the set "ranks owning tiles of panel column k" is exactly mesh column
  k % q, so `commFromSet` degenerates to choosing the axis name.
- MPI_MAXLOC becomes :func:`pargmax`: an argmax carried through psum via
  (value, index) packing.
- The serial stubs (src/stubs/) correspond to ``Grid(1, 1)``: all functions
  here are only ever traced inside shard_map, and single-target drivers never
  call them.

Workspace life counters (receive-and-release, MatrixStorage.hh:1274-1283)
have no analog: a broadcast value is an SSA temporary whose buffer XLA frees
after its last use in the step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.grid import AXIS_P, AXIS_Q


def my_coords():
    """This shard's (p, q) coordinate — only valid inside shard_map."""
    return lax.axis_index(AXIS_P), lax.axis_index(AXIS_Q)


def bcast_along(x, root, axis: str):
    """Broadcast ``x`` from the shard at index ``root`` along mesh ``axis``.

    ``root`` may be a traced value (e.g. ``k % q`` inside a fori_loop) — the
    data-dependent-root case that forces the reference to build explicit
    rank lists (BaseMatrix.hh:2365-2427 tileIbcastToSet).  Implemented as a
    masked psum: zeros are contributed by non-roots, so the sum is exactly
    the root's value.
    """
    me = lax.axis_index(axis)
    contrib = jnp.where(me == root, x, jnp.zeros_like(x))
    return lax.psum(contrib, axis)


def bcast_from_col(x, root_col):
    """Broadcast along the q axis: tile column owners -> whole mesh row
    (ref: A.listBcast of A(i, k) to owners of C(i, :), gemmC.cc:83-115)."""
    return bcast_along(x, root_col, AXIS_Q)


def bcast_from_row(x, root_row):
    """Broadcast along the p axis: tile row owners -> whole mesh column."""
    return bcast_along(x, root_row, AXIS_P)


def reduce_along(x, axis: str):
    """Sum-reduce along a mesh axis, result replicated (ReduceList analog,
    BaseMatrix.hh:2180-2217; accumulate via tile::add ≙ psum)."""
    return lax.psum(x, axis)


def reduce_scatter_along(x, axis: str, tiled_axis: int = 0):
    """Scatter-reduce along a mesh axis (ICI-efficient ReduceList when each
    rank only needs its own slice of the sum)."""
    return lax.psum_scatter(x, axis, scatter_dimension=tiled_axis,
                            tiled=True)


def allgather_along(x, axis: str, concat_axis: int = 0):
    return lax.all_gather(x, axis, axis=concat_axis, tiled=True)


def pargmax(value, index, axis: str):
    """MPI_Allreduce(MAXLOC) analog (ref: Tile_getrf.hh:260-262).

    value: per-shard candidate magnitudes [...], index: their global indices.
    Returns (max value, index of max) replicated along ``axis``; ties resolve
    to the lowest index, matching MAXLOC.
    """
    vals = lax.all_gather(value, axis)          # [n_axis, ...]
    idxs = lax.all_gather(index, axis)
    flat_arg = jnp.argmax(vals, axis=0)
    best_val = jnp.take_along_axis(vals, flat_arg[None], axis=0)[0]
    # tie-break: among shards achieving best_val pick smallest index
    is_best = vals == best_val[None]
    big = jnp.iinfo(jnp.int32).max
    cand = jnp.where(is_best, idxs, big)
    best_idx = jnp.min(cand, axis=0)
    return best_val, best_idx


def ppermute_shift(x, axis: str, shift: int, size: int):
    """Cyclic shift along a mesh axis (ref: pipeline/ring patterns;
    lax.ppermute is the ICI point-to-point primitive)."""
    perm = [(i, (i + shift) % size) for i in range(size)]
    return lax.ppermute(x, axis, perm)


def ring_bcast_along(x, root, axis: str, size: int):
    """Ring broadcast of ``x`` from the (possibly traced) ``root`` shard.

    Same contract as :func:`bcast_along`, different dataflow: instead of a
    full-axis masked psum — whose reduction tree is a barrier every shard
    must enter before any shard leaves — the value hops neighbour-to
    -neighbour via ``size - 1`` unit-shift ppermutes.  Each hop is an ICI
    point-to-point send the XLA scheduler can overlap with unrelated
    compute, which is what lets a lookahead pipeline hide the panel
    broadcast underneath the trailing update (ref listBcast pipelining,
    BaseMatrix.hh:2073-2174; SLATE's lookahead tasks, potrf.cc:266-287).

    Pure data movement: the root's bytes are forwarded unchanged, so the
    result is bit-identical to the masked-psum path for every shard and
    any root.  The shard at ring distance ``s`` from the root adopts the
    payload on hop ``s``; everyone else forwards what it already holds.
    """
    me = lax.axis_index(axis)
    dist = (me - root) % size
    have = jnp.where(dist == 0, x, jnp.zeros_like(x))
    for s in range(1, size):
        recv = ppermute_shift(have, axis, 1, size)
        have = jnp.where(dist == s, recv, have)
    return have


def ring_bcast_from_col(x, root_col, q: int):
    """Ring variant of :func:`bcast_from_col` (broadcast along the q axis
    from the column owner, ``q`` mesh columns)."""
    return ring_bcast_along(x, root_col, AXIS_Q, q)


def ring_bcast_from_row(x, root_row, p: int):
    """Ring variant of :func:`bcast_from_row` (broadcast along the p axis
    from the row owner, ``p`` mesh rows)."""
    return ring_bcast_along(x, root_row, AXIS_P, p)
