"""Buffer-pointer driver entry points backing the embedded C API.

Analog of the reference's generated driver C API (ref:
src/c_api/wrappers.cc:1-1307, include/slate/c_api/wrappers.h): C callers
hand raw buffers to driver-level routines.  The reference's C tier wraps
its C++ runtime directly; here the runtime is the JAX program layer, so
the C tier (native/slate_tpu_capi.cc) EMBEDS the interpreter and calls
these functions — pointers arrive as integers, are wrapped zero-copy
with numpy, and results are written back into the caller's output
buffer.  Double precision, row-major with a row stride ("ld" = elements
between consecutive rows), full matrices.

Every function returns 0 on success, 1 on failure (exceptions are caught
and reported on stderr — a C caller cannot unwind Python exceptions).
"""

from __future__ import annotations

import ctypes
import os
import traceback

import numpy as np

import jax

if os.environ.get("SLATE_CAPI_PLATFORM"):
    # embedding hosts cannot call jax.config themselves; the env var
    # JAX_PLATFORMS is overridden by preinstalled accelerator plugins on
    # some deployments, so honor an explicit knob here
    jax.config.update("jax_platforms", os.environ["SLATE_CAPI_PLATFORM"])
# the C API traffics in doubles: without x64 JAX silently computes the
# whole solve in f32 (this module is only imported by embedding hosts,
# so the global flag is theirs to own)
jax.config.update("jax_enable_x64", True)


def _in(ptr, rows, cols, ld):
    """Wrap a caller buffer [rows, ld] and copy out the [rows, cols]
    payload (drivers may run on accelerators; zero-copy aliasing of user
    memory across the device boundary is not meaningful)."""
    base = np.ctypeslib.as_array(
        ctypes.cast(int(ptr), ctypes.POINTER(ctypes.c_double)),
        shape=(int(rows), int(ld)))
    return np.array(base[:, :int(cols)], dtype=np.float64)


def _out(ptr, rows, cols, ld, value):
    base = np.ctypeslib.as_array(
        ctypes.cast(int(ptr), ctypes.POINTER(ctypes.c_double)),
        shape=(int(rows), int(ld)))
    base[:, :int(cols)] = np.asarray(value, dtype=np.float64)


def _vec_out(ptr, n, value):
    base = np.ctypeslib.as_array(
        ctypes.cast(int(ptr), ctypes.POINTER(ctypes.c_double)),
        shape=(int(n),))
    base[:] = np.asarray(value, dtype=np.float64)


def _guard(fn):
    try:
        fn()
        return 0
    except Exception:  # noqa: BLE001 — C boundary: report, return rc
        traceback.print_exc()
        return 1


def dgesv(n, nrhs, a_ptr, lda, b_ptr, ldb, x_ptr, ldx, nb):
    """Solve A X = B by LU (ref: c_api slate_dgesv wrapper)."""
    def run():
        import slate_tpu as st
        A = st.Matrix.from_numpy(_in(a_ptr, n, n, lda), nb, nb)
        B = st.Matrix.from_numpy(_in(b_ptr, n, nrhs, ldb), nb, nb)
        _, X = st.gesv(A, B)
        _out(x_ptr, n, nrhs, ldx, X.to_numpy())
    return _guard(run)


def dposv(n, nrhs, a_ptr, lda, b_ptr, ldb, x_ptr, ldx, nb):
    """Hermitian positive-definite solve (ref: c_api slate_dposv)."""
    def run():
        import slate_tpu as st
        H = st.HermitianMatrix.from_numpy(_in(a_ptr, n, n, lda), nb,
                                          st.Uplo.Lower)
        B = st.Matrix.from_numpy(_in(b_ptr, n, nrhs, ldb), nb, nb)
        _, X = st.posv(H, B)
        _out(x_ptr, n, nrhs, ldx, X.to_numpy())
    return _guard(run)


def dgels(m, n, nrhs, a_ptr, lda, b_ptr, ldb, x_ptr, ldx, nb):
    """Least squares min ||A X - B|| (ref: c_api slate_dgels)."""
    def run():
        import slate_tpu as st
        A = st.Matrix.from_numpy(_in(a_ptr, m, n, lda), nb, nb)
        B = st.Matrix.from_numpy(_in(b_ptr, m, nrhs, ldb), nb, nb)
        X = st.gels(A, B)
        _out(x_ptr, n, nrhs, ldx, X.to_numpy())
    return _guard(run)


def dsyev(n, a_ptr, lda, w_ptr, nb):
    """Symmetric eigenvalues (ref: c_api slate_dsyev, values mode)."""
    def run():
        import slate_tpu as st
        H = st.HermitianMatrix.from_numpy(_in(a_ptr, n, n, lda), nb,
                                          st.Uplo.Lower)
        w = st.heev_vals(H)
        _vec_out(w_ptr, n, np.sort(np.asarray(w)))
    return _guard(run)


def dgesvd(m, n, a_ptr, lda, s_ptr, nb):
    """Singular values (ref: c_api slate_dgesvd, values mode)."""
    def run():
        import slate_tpu as st
        A = st.Matrix.from_numpy(_in(a_ptr, m, n, lda), nb, nb)
        s = st.svd_vals(A)
        _vec_out(s_ptr, min(m, n), np.asarray(s))
    return _guard(run)
