"""ScaLAPACK descriptor import/export.

Analog of the reference's ScaLAPACK API tier (ref:
scalapack_api/scalapack_slate.hh slate_scalapack_submatrix /
fromScaLAPACK wrapping, scalapack_api/scalapack_gemm.cc:14-38): a legacy
application owns per-process local arrays in ScaLAPACK's 2D block-cyclic
column-major layout, described by the classic 9-integer array descriptor

    DESC = [DTYPE, CTXT, M, N, MB, NB, RSRC, CSRC, LLD]

This module converts between that world and ``TileStorage``:

- ``from_scalapack(desc, locals_, grid)`` assembles the per-process local
  arrays into a tiled Matrix (the analog of ``fromScaLAPACK`` views —
  here a copy, since TPU HBM tiles are one sharded array, not pointers
  into user memory),
- ``to_scalapack(A)`` produces the descriptor + per-process local arrays
  (exactly numroc-sized, column-major), making it a portable checkpoint/
  interchange format: a real ScaLAPACK program could consume the output.

Only RSRC = CSRC = 0 is supported (the reference's wrappers assert the
same before wrapping, scalapack_api/scalapack_slate.hh).
"""

from __future__ import annotations

import numpy as np

from ..core.grid import Grid
from ..core.storage import TileStorage
from ..exceptions import slate_error

DTYPE_DENSE = 1  # ScaLAPACK descriptor DTYPE_ for dense matrices


def numroc(n: int, nb: int, iproc: int, isrc: int, nprocs: int) -> int:
    """NUMber of Rows Or Columns owned locally — the classic ScaLAPACK
    TOOLS routine (same contract as scalapack's numroc.f).  Pure Python:
    a per-call FFI hop costs ~13x more than this integer arithmetic; the
    native library exports the same routine for C-API callers
    (native/slate_tpu_native.h), cross-checked in tests/test_native.py."""
    mydist = (nprocs + iproc - isrc) % nprocs
    nblocks = n // nb
    num = (nblocks // nprocs) * nb
    extrablocks = nblocks % nprocs
    if mydist < extrablocks:
        num += nb
    elif mydist == extrablocks:
        num += n % nb
    return num


def descinit(m: int, n: int, mb: int, nb: int, grid: Grid,
             rsrc: int = 0, csrc: int = 0, ctxt: int = 0) -> tuple:
    """Build the 9-integer array descriptor (scalapack descinit.f).
    LLD is the max over the grid column's local row counts, as a
    single-descriptor program would allocate."""
    slate_error(rsrc == 0 and csrc == 0,
                "descinit: only RSRC=CSRC=0 supported")
    lld = max(1, max(numroc(m, mb, pr, rsrc, grid.p)
                     for pr in range(grid.p)))
    return (DTYPE_DENSE, ctxt, m, n, mb, nb, rsrc, csrc, lld)


def _check_desc(desc) -> tuple:
    slate_error(len(desc) == 9, "descriptor must have 9 entries")
    dtype_, _, m, n, mb, nb, rsrc, csrc, lld = (int(x) for x in desc)
    slate_error(dtype_ == DTYPE_DENSE, "only dense (DTYPE=1) descriptors")
    slate_error(rsrc == 0 and csrc == 0, "only RSRC=CSRC=0 supported")
    return m, n, mb, nb, lld


def from_scalapack(desc, locals_, grid: Grid | None = None):
    """Assemble per-process local arrays into a tiled Matrix.

    ``locals_``: mapping {(pr, pc): 2D array} or nested list
    ``locals_[pr][pc]`` of the exactly numroc-sized column-major local
    pieces (Fortran or C memory order both accepted — shape is what
    matters).  Returns a ``Matrix`` with tile sizes (MB, NB) on ``grid``.
    """
    from ..core.matrix import Matrix
    grid = grid or Grid(1, 1)
    m, n, mb, nb, _ = _check_desc(desc)
    p, q = grid.p, grid.q

    def loc(pr, pc):
        piece = (locals_[(pr, pc)] if isinstance(locals_, dict)
                 else locals_[pr][pc])
        return np.asarray(piece)

    dense = np.zeros((m, n), loc(0, 0).dtype)
    for pr in range(p):
        for pc in range(q):
            piece = loc(pr, pc)
            ml = numroc(m, mb, pr, 0, p)
            nl = numroc(n, nb, pc, 0, q)
            slate_error(piece.shape == (ml, nl),
                        f"local ({pr},{pc}) shape {piece.shape} != "
                        f"numroc ({ml},{nl})")
            # local block row lb covers global rows of block ib = lb*p + pr
            for lb in range(-(-ml // mb) if mb else 0):
                gi = (lb * p + pr) * mb
                h = min(mb, m - gi, ml - lb * mb)
                for lc in range(-(-nl // nb) if nb else 0):
                    gj = (lc * q + pc) * nb
                    w = min(nb, n - gj, nl - lc * nb)
                    dense[gi:gi + h, gj:gj + w] = \
                        piece[lb * mb:lb * mb + h, lc * nb:lc * nb + w]
    return Matrix(TileStorage.from_dense(dense, mb, nb, grid))


def to_scalapack(A):
    """Export a Matrix to (desc, {(pr, pc): local array}) in ScaLAPACK
    layout on A's grid.  Local arrays are Fortran-ordered (column-major),
    as a ScaLAPACK program would hold them."""
    grid = A.grid
    m, n, mb, nb = A.m, A.n, A.mb, A.nb
    desc = descinit(m, n, mb, nb, grid)
    dense = np.asarray(A.to_dense())
    p, q = grid.p, grid.q
    out = {}
    for pr in range(p):
        for pc in range(q):
            ml = numroc(m, mb, pr, 0, p)
            nl = numroc(n, nb, pc, 0, q)
            piece = np.zeros((ml, nl), dense.dtype, order="F")
            for lb in range(-(-ml // mb) if mb else 0):
                gi = (lb * p + pr) * mb
                h = min(mb, m - gi, ml - lb * mb)
                for lc in range(-(-nl // nb) if nb else 0):
                    gj = (lc * q + pc) * nb
                    w = min(nb, n - gj, nl - lc * nb)
                    piece[lb * mb:lb * mb + h, lc * nb:lc * nb + w] = \
                        dense[gi:gi + h, gj:gj + w]
            out[(pr, pc)] = piece
    return desc, out
