"""ScaLAPACK descriptor import/export.

Analog of the reference's ScaLAPACK API tier (ref:
scalapack_api/scalapack_slate.hh slate_scalapack_submatrix /
fromScaLAPACK wrapping, scalapack_api/scalapack_gemm.cc:14-38): a legacy
application owns per-process local arrays in ScaLAPACK's 2D block-cyclic
column-major layout, described by the classic 9-integer array descriptor

    DESC = [DTYPE, CTXT, M, N, MB, NB, RSRC, CSRC, LLD]

This module converts between that world and ``TileStorage``:

- ``from_scalapack(desc, locals_, grid)`` assembles the per-process local
  arrays into a tiled Matrix (the analog of ``fromScaLAPACK`` views —
  here a copy, since TPU HBM tiles are one sharded array, not pointers
  into user memory),
- ``to_scalapack(A)`` produces the descriptor + per-process local arrays
  (exactly numroc-sized, column-major), making it a portable checkpoint/
  interchange format: a real ScaLAPACK program could consume the output.

Local arrays on import may be either exactly numroc-sized or allocated
with LLD rows (the padded shape a single-descriptor ScaLAPACK program
holds); at ragged sizes the two differ for processes owning the short
block row, and both must round-trip.

This layout IS the checkpoint interchange format: ``robust/checkpoint.py``
serializes factorization state through the pure-numpy ``scatter_locals``/
``gather_locals`` pair below, so a checkpoint payload is consumable by a
real ScaLAPACK program (and vice versa) without any slate-specific
decoder.

Only RSRC = CSRC = 0 is supported (the reference's wrappers assert the
same before wrapping, scalapack_api/scalapack_slate.hh).
"""

from __future__ import annotations

import numpy as np

from ..core.grid import Grid
from ..core.storage import TileStorage
from ..exceptions import slate_error

DTYPE_DENSE = 1  # ScaLAPACK descriptor DTYPE_ for dense matrices


def numroc(n: int, nb: int, iproc: int, isrc: int, nprocs: int) -> int:
    """NUMber of Rows Or Columns owned locally — the classic ScaLAPACK
    TOOLS routine (same contract as scalapack's numroc.f).  Pure Python:
    a per-call FFI hop costs ~13x more than this integer arithmetic; the
    native library exports the same routine for C-API callers
    (native/slate_tpu_native.h), cross-checked in tests/test_native.py."""
    mydist = (nprocs + iproc - isrc) % nprocs
    nblocks = n // nb
    num = (nblocks // nprocs) * nb
    extrablocks = nblocks % nprocs
    if mydist < extrablocks:
        num += nb
    elif mydist == extrablocks:
        num += n % nb
    return num


def descinit_pq(m: int, n: int, mb: int, nb: int, p: int,
                rsrc: int = 0, csrc: int = 0, ctxt: int = 0) -> tuple:
    """Grid-free ``descinit``: builds the descriptor from the process-row
    count alone (LLD only depends on ``p``).  Pure integers, no devices —
    this is the entry the checkpoint layer uses."""
    slate_error(rsrc == 0 and csrc == 0,
                "descinit: only RSRC=CSRC=0 supported")
    lld = max(1, max(numroc(m, mb, pr, rsrc, p) for pr in range(p)))
    return (DTYPE_DENSE, ctxt, m, n, mb, nb, rsrc, csrc, lld)


def descinit(m: int, n: int, mb: int, nb: int, grid: Grid,
             rsrc: int = 0, csrc: int = 0, ctxt: int = 0) -> tuple:
    """Build the 9-integer array descriptor (scalapack descinit.f).
    LLD is the max over the grid column's local row counts, as a
    single-descriptor program would allocate."""
    return descinit_pq(m, n, mb, nb, grid.p, rsrc, csrc, ctxt)


def _check_desc(desc) -> tuple:
    slate_error(len(desc) == 9, "descriptor must have 9 entries")
    dtype_, _, m, n, mb, nb, rsrc, csrc, lld = (int(x) for x in desc)
    slate_error(dtype_ == DTYPE_DENSE, "only dense (DTYPE=1) descriptors")
    slate_error(rsrc == 0 and csrc == 0, "only RSRC=CSRC=0 supported")
    return m, n, mb, nb, lld


def gather_locals(desc, locals_, p: int, q: int) -> np.ndarray:
    """Assemble per-process ScaLAPACK locals into one dense numpy array.

    ``locals_``: mapping {(pr, pc): 2D array} or nested list
    ``locals_[pr][pc]``.  Each piece may be exactly numroc-sized
    ``(ml, nl)`` or LLD-row-padded ``(lld, nl)`` with ``lld >= ml`` — the
    shape a real single-descriptor program allocates; at ragged sizes the
    short-block-row processes have ``ml < lld`` and only the leading
    ``ml`` rows are meaningful.  Pure numpy (no devices): usable from the
    checkpoint layer on a host with no accelerator attached.
    """
    m, n, mb, nb, lld = _check_desc(desc)

    def loc(pr, pc):
        piece = (locals_[(pr, pc)] if isinstance(locals_, dict)
                 else locals_[pr][pc])
        return np.asarray(piece)

    dense = np.zeros((m, n), loc(0, 0).dtype)
    for pr in range(p):
        for pc in range(q):
            piece = loc(pr, pc)
            ml = numroc(m, mb, pr, 0, p)
            nl = numroc(n, nb, pc, 0, q)
            slate_error(
                piece.shape == (ml, nl)
                or (piece.shape[0] == lld >= ml and piece.shape[1] == nl),
                f"local ({pr},{pc}) shape {piece.shape} != "
                f"numroc ({ml},{nl}) nor LLD-padded ({lld},{nl})")
            piece = piece[:ml]
            # local block row lb covers global rows of block ib = lb*p + pr
            for lb in range(-(-ml // mb) if mb else 0):
                gi = (lb * p + pr) * mb
                h = min(mb, m - gi, ml - lb * mb)
                for lc in range(-(-nl // nb) if nb else 0):
                    gj = (lc * q + pc) * nb
                    w = min(nb, n - gj, nl - lc * nb)
                    dense[gi:gi + h, gj:gj + w] = \
                        piece[lb * mb:lb * mb + h, lc * nb:lc * nb + w]
    return dense


def scatter_locals(dense: np.ndarray, mb: int, nb: int,
                   p: int, q: int) -> tuple:
    """Split a dense numpy array into (desc, {(pr, pc): local array}) in
    ScaLAPACK 2D block-cyclic layout.  Local arrays are Fortran-ordered
    and exactly numroc-sized.  Pure numpy; the checkpoint layer's
    serialization path."""
    dense = np.asarray(dense)
    m, n = dense.shape
    desc = descinit_pq(m, n, mb, nb, p)
    out = {}
    for pr in range(p):
        for pc in range(q):
            ml = numroc(m, mb, pr, 0, p)
            nl = numroc(n, nb, pc, 0, q)
            piece = np.zeros((ml, nl), dense.dtype, order="F")
            for lb in range(-(-ml // mb) if mb else 0):
                gi = (lb * p + pr) * mb
                h = min(mb, m - gi, ml - lb * mb)
                for lc in range(-(-nl // nb) if nb else 0):
                    gj = (lc * q + pc) * nb
                    w = min(nb, n - gj, nl - lc * nb)
                    piece[lb * mb:lb * mb + h, lc * nb:lc * nb + w] = \
                        dense[gi:gi + h, gj:gj + w]
            out[(pr, pc)] = piece
    return desc, out


def from_scalapack(desc, locals_, grid: Grid | None = None):
    """Assemble per-process local arrays into a tiled Matrix.

    ``locals_``: mapping {(pr, pc): 2D array} or nested list
    ``locals_[pr][pc]`` of the column-major local pieces — exactly
    numroc-sized or LLD-row-padded, Fortran or C memory order both
    accepted (shape is what matters).  Returns a ``Matrix`` with tile
    sizes (MB, NB) on ``grid``.
    """
    from ..core.matrix import Matrix
    grid = grid or Grid(1, 1)
    m, n, mb, nb, _ = _check_desc(desc)
    dense = gather_locals(desc, locals_, grid.p, grid.q)
    return Matrix(TileStorage.from_dense(dense, mb, nb, grid))


def to_scalapack(A):
    """Export a Matrix to (desc, {(pr, pc): local array}) in ScaLAPACK
    layout on A's grid.  Local arrays are Fortran-ordered (column-major),
    as a ScaLAPACK program would hold them."""
    dense = np.asarray(A.to_dense())
    return scatter_locals(dense, A.mb, A.nb, A.grid.p, A.grid.q)
