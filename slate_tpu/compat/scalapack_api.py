"""ScaLAPACK-style routine entry points over descriptor + local arrays.

Analog of the reference's scalapack_api tier (ref:
scalapack_api/scalapack_gemm.cc:24-38 slate_pdgemm and the pdgesv /
pdpotrf / pdgeqrf / pdsyev wrapper files, each of which converts the
caller's (descriptor, local array) pairs into framework matrices, runs
the native driver, and writes results back in ScaLAPACK layout).

Each ``pd*`` function here takes the classic 9-integer descriptor plus a
``{(pr, pc): local array}`` mapping per matrix (the layout
compat/scalapack.py's ``to_scalapack`` emits and a real ScaLAPACK
program holds), runs the corresponding slate_tpu driver on ``grid``, and
returns results converted back with ``to_scalapack``.  Only full-matrix
operations (IA = JA = 1 in ScaLAPACK terms) and RSRC = CSRC = 0 are
supported, matching the subset the reference's wrappers assert before
delegating (scalapack_api/scalapack_slate.hh).
"""

from __future__ import annotations

import numpy as np

from ..core.grid import Grid
from ..core.matrix import HermitianMatrix, Matrix
from ..exceptions import slate_error
from ..types import Uplo
from .scalapack import from_scalapack, to_scalapack


def _mat(desc, locals_, grid: Grid) -> Matrix:
    return from_scalapack(desc, locals_, grid)


def _trans_mat(trans: str, A: Matrix):
    t = trans.lower()
    slate_error(t in ("n", "t", "c"), "trans must be 'n', 't' or 'c'")
    if t == "n":
        return A
    return A.transpose() if t == "t" else A.conj_transpose()


def pdgemm(transa, transb, m, n, k, alpha, desca, a_locals, descb,
           b_locals, beta, descc, c_locals, grid: Grid):
    """C = alpha op(A) op(B) + beta C (ref: scalapack_api/
    scalapack_gemm.cc slate_pdgemm).  Returns (descc, c_locals)."""
    from ..drivers.blas3 import gemm
    A = _trans_mat(transa, _mat(desca, a_locals, grid))
    B = _trans_mat(transb, _mat(descb, b_locals, grid))
    C = _mat(descc, c_locals, grid)
    slate_error((A.m, A.n, B.n) == (m, k, n), "pdgemm: dims vs descriptors")
    out = gemm(alpha, A, B, beta, C)
    return to_scalapack(out)


def pdgesv(n, nrhs, desca, a_locals, descb, b_locals, grid: Grid):
    """Solve A X = B by LU (ref: scalapack_api/scalapack_gesv.cc).
    Returns (descx, x_locals)."""
    from ..drivers.lu import gesv
    A = _mat(desca, a_locals, grid)
    B = _mat(descb, b_locals, grid)
    slate_error(A.m == n and B.n == nrhs, "pdgesv: dims vs descriptors")
    _, X = gesv(A, B)
    return to_scalapack(X)


def pdpotrf(uplo, n, desca, a_locals, grid: Grid):
    """Cholesky factor (ref: scalapack_api/scalapack_potrf.cc).  Returns
    (desc, locals) of the triangular factor (L for 'l', U for 'u')."""
    from ..drivers.cholesky import potrf
    up = Uplo.Lower if str(uplo).lower().startswith("l") else Uplo.Upper
    A = HermitianMatrix._from_view(_mat(desca, a_locals, grid), up)
    slate_error(A.m == n, "pdpotrf: dims vs descriptor")
    L = potrf(A)
    return to_scalapack(L.general())


def pdposv(uplo, n, nrhs, desca, a_locals, descb, b_locals, grid: Grid):
    """Hermitian positive-definite solve (ref: scalapack_api/
    scalapack_posv.cc).  Returns (descx, x_locals)."""
    from ..drivers.cholesky import posv
    up = Uplo.Lower if str(uplo).lower().startswith("l") else Uplo.Upper
    A = HermitianMatrix._from_view(_mat(desca, a_locals, grid), up)
    B = _mat(descb, b_locals, grid)
    slate_error(A.m == n and B.n == nrhs, "pdposv: dims vs descriptors")
    _, X = posv(A, B)
    return to_scalapack(X)


def pdgels(m, n, nrhs, desca, a_locals, descb, b_locals, grid: Grid):
    """Least squares min ||A X - B|| (ref: scalapack_api/
    scalapack_gels.cc).  Returns (descx, x_locals)."""
    from ..drivers.qr import gels
    A = _mat(desca, a_locals, grid)
    B = _mat(descb, b_locals, grid)
    slate_error((A.m, A.n, B.n) == (m, n, nrhs),
                "pdgels: dims vs descriptors")
    X = gels(A, B)
    return to_scalapack(X)


def pdsyev(jobz, uplo, n, desca, a_locals, grid: Grid):
    """Symmetric eigendecomposition (ref: scalapack_api/
    scalapack_heev.cc).  Returns (w, descz, z_locals) — z parts None for
    jobz='n'."""
    from ..drivers.heev import heev
    up = Uplo.Lower if str(uplo).lower().startswith("l") else Uplo.Upper
    A = HermitianMatrix._from_view(_mat(desca, a_locals, grid), up)
    slate_error(A.m == n, "pdsyev: dims vs descriptor")
    want_z = str(jobz).lower().startswith("v")
    w, Z = heev(A, jobz=want_z)
    if not want_z:
        return np.asarray(w), None, None
    descz, z_locals = to_scalapack(Z)
    return np.asarray(w), descz, z_locals


def pdgesvd(jobu, m, n, desca, a_locals, grid: Grid):
    """SVD (ref: scalapack_api/scalapack_gesvd.cc).  Returns
    (s, descu, u_locals, descvt, vt_locals) — U/V parts None for
    jobu='n'."""
    from ..drivers.svd import svd
    A = _mat(desca, a_locals, grid)
    slate_error((A.m, A.n) == (m, n), "pdgesvd: dims vs descriptor")
    want_uv = str(jobu).lower().startswith("v")
    s, U, V = svd(A, jobu=want_uv)
    if not want_uv:
        return np.asarray(s), None, None, None, None
    descu, u_locals = to_scalapack(U)
    descvt, vt_locals = to_scalapack(V.conj_transpose())
    return np.asarray(s), descu, u_locals, descvt, vt_locals
