"""LAPACK-style shims: column-major arrays in, arrays out, single grid.

Analog of the reference's lapack_api tier (ref: lapack_api/lapack_slate.hh
slate_dgesv / slate_dposv / ... — LAPACK calling conventions routed to
1-rank SLATE).  Here each shim takes plain numpy/jax arrays, runs the
framework drivers on a 1x1 grid with a heuristic tile size, and returns
plain arrays — the path a legacy LAPACK caller migrates through first.

Naming follows LAPACK with the precision prefix dropped (precision comes
from the input dtype, as in modern LAPACK wrappers)."""

from __future__ import annotations

import numpy as np

from ..core.matrix import HermitianMatrix, Matrix
from ..options import Option, get_option
from ..types import Uplo


def _nb(n: int, opts=None) -> int:
    """Tile size: Option.BlockSize when given (ref: enums.hh:72
    'block size, >= 1' — the nb the LAPACK/ScaLAPACK tiers pass through),
    else a size heuristic."""
    bs = get_option(opts, Option.BlockSize)
    if bs:
        return int(bs)
    return max(8, min(256, 1 << max(3, (n // 4).bit_length())))


def _mat(a, nb=None, opts=None) -> Matrix:
    a = np.asarray(a)
    nb = nb or _nb(max(a.shape), opts)
    return Matrix.from_numpy(a, min(nb, a.shape[0]), min(nb, a.shape[1]))


def gesv(a, b, opts=None):
    """Solve A X = B (LAPACK dgesv).  Returns (x, perm)."""
    from ..drivers.lu import gesv as _gesv
    F, X = _gesv(_mat(a, opts=opts), _mat(b, opts=opts), opts)
    return np.asarray(X.to_numpy()), np.asarray(F.perm)


def getrf(a, opts=None):
    """LU factor (LAPACK dgetrf).  Returns (lu, perm) with A[perm] = L U."""
    from ..drivers.lu import getrf as _getrf
    F = _getrf(_mat(a, opts=opts), opts)
    return np.asarray(F.LU.to_numpy()), np.asarray(F.perm)


def posv(a, b, uplo: str = "L", opts=None):
    """Solve A X = B, A Hermitian positive definite (LAPACK dposv).
    Returns x."""
    from ..drivers.cholesky import posv as _posv
    A = HermitianMatrix.from_numpy(np.asarray(a), _nb(len(a), opts),
                                   uplo=Uplo.Lower if uplo.upper() == "L"
                                   else Uplo.Upper)
    _, X = _posv(A, _mat(b, opts=opts), opts)
    return np.asarray(X.to_numpy())


def potrf(a, uplo: str = "L", opts=None):
    """Cholesky factor (LAPACK dpotrf).  Returns the triangular factor."""
    from ..drivers.cholesky import potrf as _potrf
    A = HermitianMatrix.from_numpy(np.asarray(a), _nb(len(a), opts),
                                   uplo=Uplo.Lower if uplo.upper() == "L"
                                   else Uplo.Upper)
    return np.asarray(_potrf(A, opts).to_numpy())


def gels(a, b, opts=None):
    """Least squares min ||A X - B|| (LAPACK dgels).  Returns x."""
    from ..drivers.qr import gels as _gels
    return np.asarray(_gels(_mat(a, opts=opts), _mat(b, opts=opts), opts).to_numpy())


def geqrf(a, opts=None):
    """QR factor (LAPACK dgeqrf).  Returns the packed QR Matrix factors."""
    from ..drivers.qr import geqrf as _geqrf
    return _geqrf(_mat(a, opts=opts), opts)


def heev(a, uplo: str = "L", opts=None):
    """Hermitian eigendecomposition (LAPACK dsyev/zheev).
    Returns (eigenvalues, eigenvectors)."""
    from ..drivers.heev import heev as _heev
    A = HermitianMatrix.from_numpy(np.asarray(a), _nb(len(a), opts),
                                   uplo=Uplo.Lower if uplo.upper() == "L"
                                   else Uplo.Upper)
    lam, Z = _heev(A, opts)
    return np.asarray(lam), np.asarray(Z.to_numpy())


def gesvd(a, opts=None):
    """SVD (LAPACK dgesvd).  Returns (u, s, vh)."""
    from ..drivers.svd import svd as _svd
    s, U, V = _svd(_mat(a, opts=opts), opts)
    return (np.asarray(U.to_numpy()), np.asarray(s),
            np.conj(np.asarray(V.to_numpy())).T)


def gesvd_vals(a, opts=None):
    """Singular values only."""
    from ..drivers.svd import svd_vals as _svd_vals
    return np.asarray(_svd_vals(_mat(a, opts=opts), opts))


def gecon(a, opts=None):
    """Reciprocal 1-norm condition estimate via the Higham/Hager
    estimator (LAPACK dgecon analog)."""
    from ..drivers.auxiliary import norm as _norm
    from ..drivers.condest import gecondest
    from ..drivers.lu import getrf as _getrf
    from ..types import Norm
    A = _mat(a, opts=opts)
    return float(gecondest(_getrf(A, opts), _norm(Norm.One, A)))
