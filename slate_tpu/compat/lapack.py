"""LAPACK-style shims: column-major arrays in, arrays out, single grid.

Analog of the reference's lapack_api tier (ref: lapack_api/lapack_slate.hh
slate_dgesv / slate_dposv / ... — LAPACK calling conventions routed to
1-rank SLATE).  Here each shim takes plain numpy/jax arrays, runs the
framework drivers on a 1x1 grid with a heuristic tile size, and returns
plain arrays — the path a legacy LAPACK caller migrates through first.

Naming follows LAPACK with the precision prefix dropped (precision comes
from the input dtype, as in modern LAPACK wrappers)."""

from __future__ import annotations

import numpy as np

from ..core.matrix import HermitianMatrix, Matrix
from ..options import Option, get_option
from ..types import Uplo


def _nb(n: int, opts=None) -> int:
    """Tile size: Option.BlockSize when given (ref: enums.hh:72
    'block size, >= 1' — the nb the LAPACK/ScaLAPACK tiers pass through),
    else a size heuristic."""
    bs = get_option(opts, Option.BlockSize)
    if bs:
        return int(bs)
    return max(8, min(256, 1 << max(3, (n // 4).bit_length())))


def _mat(a, nb=None, opts=None) -> Matrix:
    a = np.asarray(a)
    nb = nb or _nb(max(a.shape), opts)
    return Matrix.from_numpy(a, min(nb, a.shape[0]), min(nb, a.shape[1]))


def gesv(a, b, opts=None):
    """Solve A X = B (LAPACK dgesv).  Returns (x, perm)."""
    from ..drivers.lu import gesv as _gesv
    F, X = _gesv(_mat(a, opts=opts), _mat(b, opts=opts), opts)
    return np.asarray(X.to_numpy()), np.asarray(F.perm)


def getrf(a, opts=None):
    """LU factor (LAPACK dgetrf).  Returns (lu, perm) with A[perm] = L U."""
    from ..drivers.lu import getrf as _getrf
    F = _getrf(_mat(a, opts=opts), opts)
    return np.asarray(F.LU.to_numpy()), np.asarray(F.perm)


def posv(a, b, uplo: str = "L", opts=None):
    """Solve A X = B, A Hermitian positive definite (LAPACK dposv).
    Returns x."""
    from ..drivers.cholesky import posv as _posv
    A = HermitianMatrix.from_numpy(np.asarray(a), _nb(len(a), opts),
                                   uplo=Uplo.Lower if uplo.upper() == "L"
                                   else Uplo.Upper)
    _, X = _posv(A, _mat(b, opts=opts), opts)
    return np.asarray(X.to_numpy())


def potrf(a, uplo: str = "L", opts=None):
    """Cholesky factor (LAPACK dpotrf).  Returns the triangular factor."""
    from ..drivers.cholesky import potrf as _potrf
    A = HermitianMatrix.from_numpy(np.asarray(a), _nb(len(a), opts),
                                   uplo=Uplo.Lower if uplo.upper() == "L"
                                   else Uplo.Upper)
    return np.asarray(_potrf(A, opts).to_numpy())


def gels(a, b, opts=None):
    """Least squares min ||A X - B|| (LAPACK dgels).  Returns x."""
    from ..drivers.qr import gels as _gels
    return np.asarray(_gels(_mat(a, opts=opts), _mat(b, opts=opts), opts).to_numpy())


def geqrf(a, opts=None):
    """QR factor (LAPACK dgeqrf).  Returns the packed QR Matrix factors."""
    from ..drivers.qr import geqrf as _geqrf
    return _geqrf(_mat(a, opts=opts), opts)


def heev(a, uplo: str = "L", opts=None):
    """Hermitian eigendecomposition (LAPACK dsyev/zheev).
    Returns (eigenvalues, eigenvectors)."""
    from ..drivers.heev import heev as _heev
    A = HermitianMatrix.from_numpy(np.asarray(a), _nb(len(a), opts),
                                   uplo=Uplo.Lower if uplo.upper() == "L"
                                   else Uplo.Upper)
    lam, Z = _heev(A, opts)
    return np.asarray(lam), np.asarray(Z.to_numpy())


def gesvd(a, opts=None):
    """SVD (LAPACK dgesvd).  Returns (u, s, vh)."""
    from ..drivers.svd import svd as _svd
    s, U, V = _svd(_mat(a, opts=opts), opts)
    return (np.asarray(U.to_numpy()), np.asarray(s),
            np.conj(np.asarray(V.to_numpy())).T)


def gesvd_vals(a, opts=None):
    """Singular values only."""
    from ..drivers.svd import svd_vals as _svd_vals
    return np.asarray(_svd_vals(_mat(a, opts=opts), opts))


def gecon(a, opts=None):
    """Reciprocal 1-norm condition estimate via the Higham/Hager
    estimator (LAPACK dgecon analog)."""
    from ..drivers.auxiliary import norm as _norm
    from ..drivers.condest import gecondest
    from ..drivers.lu import getrf as _getrf
    from ..types import Norm
    A = _mat(a, opts=opts)
    return float(gecondest(_getrf(A, opts), _norm(Norm.One, A)))


# ---- BLAS-3 tier (ref: lapack_api/lapack_gemm.cc, _hemm, _herk, _her2k,
# _symm, _syrk, _syr2k, _trmm, _trsm) ----

def _op_mat(a, trans: str, opts=None) -> Matrix:
    return _apply_trans(_mat(a, opts=opts), trans)


def _uplo(uplo: str) -> Uplo:
    return Uplo.Lower if uplo.upper().startswith("L") else Uplo.Upper


def gemm(transa, transb, alpha, a, b, beta=0.0, c=None, opts=None):
    """C = alpha op(A) op(B) + beta C (LAPACK-style dgemm)."""
    from ..drivers.blas3 import gemm as _gemm
    C = None if c is None else _mat(c, opts=opts)
    out = _gemm(alpha, _op_mat(a, transa, opts), _op_mat(b, transb, opts),
                beta, C, opts)
    return np.asarray(out.to_numpy())


def hemm(side, uplo, alpha, a, b, beta=0.0, c=None, opts=None):
    """C = alpha A B + beta C with A Hermitian (dhemm/zhemm)."""
    from ..drivers.blas3 import hemm as _hemm
    A = HermitianMatrix.from_numpy(np.asarray(a), _nb(len(a), opts),
                                   uplo=_uplo(uplo))
    C = None if c is None else _mat(c, opts=opts)
    return np.asarray(_hemm(side, alpha, A, _mat(b, opts=opts), beta, C,
                            opts).to_numpy())


def symm(side, uplo, alpha, a, b, beta=0.0, c=None, opts=None):
    """C = alpha A B + beta C with A SYMMETRIC (dsymm/zsymm) — a complex
    symmetric A must expand as tri + tri^T, NOT conj-mirrored like the
    Hermitian wrapper would."""
    from ..core.matrix import SymmetricMatrix
    from ..drivers.blas3 import symm as _symm
    A = SymmetricMatrix.from_numpy(np.asarray(a), _nb(len(a), opts),
                                   uplo=_uplo(uplo))
    C = None if c is None else _mat(c, opts=opts)
    return np.asarray(_symm(side, alpha, A, _mat(b, opts=opts), beta, C,
                            opts).to_numpy())


def _rank_k(kind, uplo, alpha, a, beta, c, opts, b=None):
    from ..core.matrix import SymmetricMatrix
    from ..drivers import blas3
    herm = kind in ("herk", "her2k")
    cls = HermitianMatrix if herm else SymmetricMatrix
    n = np.asarray(a).shape[0]
    cm = (np.zeros((n, n), np.asarray(a).dtype) if c is None
          else np.asarray(c))
    C = cls.from_numpy(cm, _nb(len(cm), opts), uplo=_uplo(uplo))
    A = _mat(a, opts=opts)
    if kind == "herk":
        out = blas3.herk(alpha, A, beta, C, opts)
    elif kind == "syrk":
        out = blas3.syrk(alpha, A, beta, C, opts)
    elif kind == "her2k":
        out = blas3.her2k(alpha, A, _mat(b, opts=opts), beta, C, opts)
    else:
        out = blas3.syr2k(alpha, A, _mat(b, opts=opts), beta, C, opts)
    return np.asarray(out.general().to_numpy())


def herk(uplo, alpha, a, beta=0.0, c=None, opts=None):
    """C = alpha A A^H + beta C, C Hermitian (zherk).  Returns the full
    (Hermitian-completed) array."""
    return _rank_k("herk", uplo, alpha, a, beta, c, opts)


def syrk(uplo, alpha, a, beta=0.0, c=None, opts=None):
    """C = alpha A A^T + beta C, C symmetric (dsyrk)."""
    return _rank_k("syrk", uplo, alpha, a, beta, c, opts)


def her2k(uplo, alpha, a, b, beta=0.0, c=None, opts=None):
    """C = alpha A B^H + conj(alpha) B A^H + beta C (zher2k)."""
    return _rank_k("her2k", uplo, alpha, a, beta, c, opts, b=b)


def syr2k(uplo, alpha, a, b, beta=0.0, c=None, opts=None):
    """C = alpha A B^T + alpha B A^T + beta C (dsyr2k)."""
    return _rank_k("syr2k", uplo, alpha, a, beta, c, opts, b=b)


def _apply_trans(M, trans: str):
    """op() dispatch shared by every shim taking a trans character."""
    t = trans.lower()
    if t.startswith("t"):
        return M.transpose()
    if t.startswith("c"):
        return M.conj_transpose()
    return M


def _tri_mat(a, uplo, diag, opts):
    from ..core.matrix import TriangularMatrix
    from ..types import Diag
    A = _mat(a, opts=opts)
    return TriangularMatrix._from_view(
        A, _uplo(uplo),
        Diag.Unit if diag.upper().startswith("U") else Diag.NonUnit)


def trmm(side, uplo, transa, diag, alpha, a, b, opts=None):
    """B = alpha op(A) B or alpha B op(A), A triangular (dtrmm)."""
    from ..drivers.blas3 import trmm as _trmm
    T = _apply_trans(_tri_mat(a, uplo, diag, opts), transa)
    return np.asarray(_trmm(side, alpha, T, _mat(b, opts=opts),
                            opts).to_numpy())


def trsm(side, uplo, transa, diag, alpha, a, b, opts=None):
    """Solve op(A) X = alpha B or X op(A) = alpha B (dtrsm)."""
    from ..drivers.blas3 import trsm as _trsm
    T = _apply_trans(_tri_mat(a, uplo, diag, opts), transa)
    return np.asarray(_trsm(side, alpha, T, _mat(b, opts=opts),
                            opts).to_numpy())


# ---- norms (ref: lapack_api/lapack_lange.cc, _lanhe, _lansy, _lantr) ----

def _norm_kind(norm):
    """LAPACK norm character -> Norm enum, shared by the lan* shims."""
    from ..types import Norm
    return {"m": Norm.Max, "1": Norm.One, "o": Norm.One, "i": Norm.Inf,
            "f": Norm.Fro, "e": Norm.Fro}[str(norm).lower()]


def lange(norm, a, opts=None):
    """General matrix norm: 'm'|'1'|'i'|'f' (dlange)."""
    from ..drivers.auxiliary import norm as _norm
    from ..types import Norm
    m = _norm_kind(norm)
    return float(_norm(m, _mat(a, opts=opts)))


def lanhe(norm, uplo, a, opts=None):
    """Hermitian matrix norm (zlanhe)."""
    from ..drivers.auxiliary import norm as _norm
    from ..types import Norm
    m = _norm_kind(norm)
    A = HermitianMatrix.from_numpy(np.asarray(a), _nb(len(a), opts),
                                   uplo=_uplo(uplo))
    return float(_norm(m, A))


def lansy(norm, uplo, a, opts=None):
    """Symmetric matrix norm (dlansy)."""
    from ..core.matrix import SymmetricMatrix
    from ..drivers.auxiliary import norm as _norm
    from ..types import Norm
    m = _norm_kind(norm)
    A = SymmetricMatrix.from_numpy(np.asarray(a), _nb(len(a), opts),
                                   uplo=_uplo(uplo))
    return float(_norm(m, A))


def lantr(norm, uplo, diag, a, opts=None):
    """Triangular matrix norm (dlantr)."""
    from ..drivers.auxiliary import norm as _norm
    from ..types import Norm
    m = _norm_kind(norm)
    return float(_norm(m, _tri_mat(a, uplo, diag, opts)))


# ---- solves/inverses from factors (ref: lapack_api/lapack_getrs.cc,
# _getri, _potri, _gesv_mixed) ----

def getrs(lu, perm, b, trans: str = "n", opts=None):
    """Solve op(A) X = B from getrf's (lu, perm) (dgetrs)."""
    from ..drivers.lu import LUFactors, getrs as _getrs
    from ..drivers.blas3 import trsm as _t
    lu = np.asarray(lu)
    perm = np.asarray(perm)
    F = LUFactors(_mat(lu, opts=opts), perm)
    t = trans.lower()
    if t.startswith("n"):
        return np.asarray(_getrs(F, _mat(b, opts=opts), opts).to_numpy())
    # op(A) x = b with A[perm] = L U:  op(A) = op(U) op(L) P, so
    # w = op(U)^-1 b, v = op(L)^-1 w, x[perm] = v
    op = "c" if t.startswith("c") else "t"
    U = F.upper().conj_transpose() if op == "c" else F.upper().transpose()
    L = F.lower().conj_transpose() if op == "c" else F.lower().transpose()
    w = _t("l", 1.0, U, _mat(b, opts=opts), opts)
    v = np.asarray(_t("l", 1.0, L, w, opts).to_numpy())
    x = np.zeros_like(v)
    x[perm] = v
    return x


def getri(lu, perm, opts=None):
    """Matrix inverse from getrf factors (dgetri)."""
    from ..drivers.lu import LUFactors, getri as _getri
    F = LUFactors(_mat(np.asarray(lu), opts=opts), np.asarray(perm))
    return np.asarray(_getri(F, opts).to_numpy())


def potri(l, uplo: str = "L", opts=None):
    """Inverse from the Cholesky factor (dpotri).  Returns the full
    (Hermitian-completed) inverse."""
    from ..core.matrix import TriangularMatrix
    from ..drivers.cholesky import potri as _potri
    T = TriangularMatrix._from_view(_mat(np.asarray(l), opts=opts),
                                    _uplo(uplo))
    return np.asarray(_potri(T, opts).general().to_numpy())


def gesv_mixed(a, b, opts=None):
    """Mixed-precision iterative-refinement solve (dsgesv analog).
    Returns (x, iters)."""
    from ..drivers.mixed import gesv_mixed as _gm
    res = _gm(_mat(a, opts=opts), _mat(b, opts=opts), opts)
    return np.asarray(res.X.to_numpy()), int(np.asarray(res.iters))
