"""Compatibility APIs: ScaLAPACK descriptors and LAPACK-style shims.

Analog of the reference's compat tier (ref: scalapack_api/, lapack_api/):
legacy callers keep their data layouts and calling conventions; the shims
translate in/out of the framework's tiled storage.
"""

from . import lapack, scalapack  # noqa: F401
from .scalapack import descinit, from_scalapack, numroc, to_scalapack  # noqa: F401
