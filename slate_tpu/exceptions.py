"""Error handling for slate_tpu.

TPU-native analog of the reference's exception layer
(reference: include/slate/Exception.hh:1-122): `slate::Exception` plus the
`slate_error` / `slate_assert` macros.  Here errors are Python exceptions; the
`slate_assert` / `slate_error` helpers keep call sites terse and uniform.
"""

from __future__ import annotations


class SlateError(Exception):
    """Base error for slate_tpu (ref: Exception.hh `slate::Exception`)."""


class SlateValueError(SlateError, ValueError):
    """Invalid argument (shape/uplo/op mismatches)."""


class SlateUnsupportedDtypeError(SlateValueError):
    """A boundary was handed a dtype it cannot serve.

    Raised by ``robust.precision.normalize_dtype`` when a caller names a
    dtype outside the boundary's supported set (e.g. float64 at the
    serving front door).  The contract is refuse-loudly: an unsupported
    dtype must never silently take a slow or wrong-precision route.
    ``dtype`` carries the canonical spelling that was rejected."""

    def __init__(self, msg: str, dtype: str = ""):
        super().__init__(msg)
        self.dtype = dtype


class SlateNotConvergedError(SlateError):
    """Iterative routine failed to converge (ref: gesv_mixed itermax path)."""

    def __init__(self, msg: str, iters: int = -1):
        super().__init__(msg)
        self.iters = iters


class SlateNotPositiveDefiniteError(SlateError):
    """potrf encountered a non-positive-definite matrix."""

    def __init__(self, msg: str, info: int = 0):
        super().__init__(msg)
        self.info = info


class SlateSingularError(SlateError):
    """Factorization hit an exactly-zero (or non-finite) pivot.

    ``info`` follows the LAPACK getrf convention: the 1-based index of the
    first unusable pivot, 0 when the position is unknown."""

    def __init__(self, msg: str, info: int = 0):
        super().__init__(msg)
        self.info = info


class SlateServeError(SlateError):
    """Serving front-door failure (admission, flush, watchdog).

    The serving layer never loses an error: flush failures are stored
    per-request on the ticket (sticky) and re-raised at the caller's
    ``result()`` / ``drain()`` site, so a failed background flush is
    loud even when the queue is empty by the time anyone looks."""


class SlateServeTimeoutError(SlateServeError):
    """A request or flush ran out of time: the watchdog declared an
    in-flight flush wedged (stuck compile or device hang), a per-request
    deadline would expire before service, or ``Ticket.result(timeout)``
    elapsed.  ``reason`` carries which (``watchdog`` / ``deadline`` /
    ``result_timeout`` / ``wedged`` / ``shutdown``)."""

    def __init__(self, msg: str, reason: str = "timeout"):
        super().__init__(msg)
        self.reason = reason


class SlateServeOverloadError(SlateServeError):
    """Admission control rejected or shed a request under overload
    (bounded queue full, or SLO backpressure tightened capacity).
    ``policy`` names the overflow policy that fired."""

    def __init__(self, msg: str, policy: str = "reject"):
        super().__init__(msg)
        self.policy = policy


class SlateCheckpointError(SlateError):
    """A checkpoint could not be trusted for resume.

    Raised by ``robust/checkpoint.py`` when verification fails BEFORE any
    work continues — a torn/truncated payload, a digest or ABFT checksum
    mismatch, a manifest/payload skew (stale read), or a run whose
    resolved options/plan fingerprint differs from the one that wrote the
    snapshot.  The contract is refuse-loudly: a bad checkpoint must never
    silently restart or silently resume into a wrong answer.

    ``reason`` carries which rung refused (``missing`` / ``torn`` /
    ``corrupt`` / ``stale`` / ``abft`` / ``fingerprint``); ``step`` is the
    panel-step index the checkpoint claimed, -1 when unknown."""

    def __init__(self, msg: str, reason: str = "corrupt", step: int = -1):
        super().__init__(msg)
        self.reason = reason
        self.step = step


def slate_error(cond: bool, msg: str = "error") -> None:
    """Raise SlateValueError unless ``cond`` (ref: Exception.hh slate_error)."""
    if not cond:
        raise SlateValueError(msg)


def slate_assert(cond: bool, msg: str = "assertion failed") -> None:
    """Internal-consistency assert (ref: Exception.hh slate_assert)."""
    if not cond:
        raise AssertionError(msg)
