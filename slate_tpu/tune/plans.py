"""Persistent tile-plan store + the trace-time plan resolver.

The r05 bench showed nb=256/512/1024 each winning at different n — the
panel-kernel choice is a *search* problem per (op, n, dtype, chip), not
a constant (PAPERS.md "Design in Tiles" / "TileLoom").  This module
owns the answer: a small on-disk JSON cache of winning ``TilePlan``s,
written by ``slate_tpu.tune.autotune`` and read back by the internal
dispatch seams (potrf_tile, getrf panel, geqrf panel) through ONE
function, :func:`resolve_plan`.

Trace-safety contract (slate-lint TRC): ``resolve_plan`` takes only
host-static values (python ints from ``.shape``, dtype names) and
returns a plain NamedTuple consumed as static configuration — it runs
at trace time, never on tracer data, so cached-plan dispatch lowers to
a fixed kernel choice with no data-dependent control flow.

Seam contract (slate-lint SEAM011): drivers and internal modules must
NOT touch the raw cache (load_cache / save_cache / record_plan /
cache_path) — they call ``resolve_plan`` only.  The raw accessors exist
for the autotuner and for tests.

Cache schema (version 1)::

    {"version": 1,
     "chips": {"<chip-kind>": {"<op>": {"n=512,dtype=float32":
         {"kernel": "pallas", "nb": 512, "bw": 8, "gflops": 123.4}}}}}

``SLATE_PALLAS`` is REMOVED (deprecated in the previous release): the
variable is IGNORED and setting it warns once per process, pointing at
``plan_override`` and the ``python -m slate_tpu.tune`` CLI.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import threading
import warnings
from typing import NamedTuple

SCHEMA_VERSION = 1
OPS = ("potrf_tile", "potrf_panel", "getrf_panel", "lu_select",
       "geqrf_panel", "batch_potrf", "batch_getrf", "batch_geqrf")
# ``dist_lookahead`` is a pseudo-op: it does not pick a panel kernel but
# the comm/compute pipeline depth of the distributed kernels — kernel
# "xla" means the bulk-synchronous masked-psum path (depth 0, the parity
# oracle), kernel "ring" means the lookahead pipeline with ``bw`` as the
# measured depth (1 or 2).  Resolved only via lookahead_depth(), and —
# like ``serve_bucket`` — schema-accepted but excluded from OPS so the
# kernel autotuner's candidate sweeps never try to measure it (lookahead
# wins are measured end to end by bench_*_lookahead instead).
DIST_LOOKAHEAD_OP = "dist_lookahead"
# The serving layer's bucket ladder rides the same cache file but is NOT a
# kernel-tuning op (no candidate sweep): each recorded entry's ``n`` is one
# ladder rung for this chip (see serve_buckets / docs/SERVING.md).
SERVE_BUCKET_OP = "serve_bucket"
# Out-of-core panel width is another pseudo-op: not a kernel choice but
# the host<->device streaming granularity of the TileMap drivers
# (drivers/cholesky.py potrf_ooc, drivers/lu.py getrf_ooc) — wide enough
# to amortize the H2D/D2H copies, narrow enough that two panels plus one
# trailing window fit HBM.  Resolved only via ooc_panel_width(); like
# the other pseudo-ops it is schema-accepted but excluded from OPS so
# kernel candidate sweeps never measure it (OOC wins are end-to-end,
# bench_potrf_ooc).
OOC_PANEL_OP = "ooc_panel"
ALL_OPS = OPS + (DIST_LOOKAHEAD_OP, SERVE_BUCKET_OP, OOC_PANEL_OP)
KERNELS = ("xla", "pallas", "ring")


class TilePlan(NamedTuple):
    """One tuned dispatch decision: which kernel, at which tile width
    ``nb`` (advisory — drivers tile by Matrix.nb; the tuner records the
    width that won so callers picking a tiling can consult it), with
    which Pallas row-panel width ``bw``."""
    kernel: str = "xla"
    nb: int = 512
    bw: int = 8


XLA_PLAN = TilePlan()

_LOCK = threading.Lock()
_CACHE: dict | None = None          # lazily loaded, keyed by cache_path()
_CACHE_KEY: str | None = None
_OVERRIDES: dict[str, TilePlan] = {}
_WARNED = False


def cache_path() -> str:
    """Plan-cache location: $SLATE_TUNE_CACHE, else
    ~/.cache/slate_tpu/plans.json."""
    env = os.environ.get("SLATE_TUNE_CACHE")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "slate_tpu",
                        "plans.json")


def _empty() -> dict:
    return {"version": SCHEMA_VERSION, "chips": {}}


def plan_key(n: int, dtype: str) -> str:
    """Cache entry key; dtype spellings normalize through the one shared
    helper (robust/precision.normalize_dtype) so "bf16" and "bfloat16"
    land on the same entry — a typo'd dtype raises instead of silently
    keying a fresh miss."""
    from ..robust.precision import normalize_dtype
    return f"n={int(n)},dtype={normalize_dtype(dtype)}"


def _parse_key(key: str) -> tuple[int, str]:
    n_part, dt_part = key.split(",", 1)
    if not (n_part.startswith("n=") and dt_part.startswith("dtype=")):
        raise ValueError(f"plan cache: bad entry key {key!r}")
    return int(n_part[2:]), dt_part[6:]


def validate_cache(obj) -> None:
    """Raise ValueError unless ``obj`` matches the version-1 schema."""
    if not isinstance(obj, dict):
        raise ValueError("plan cache: top level must be an object")
    if obj.get("version") != SCHEMA_VERSION:
        raise ValueError(
            f"plan cache: version must be {SCHEMA_VERSION}, "
            f"got {obj.get('version')!r}")
    chips = obj.get("chips")
    if not isinstance(chips, dict):
        raise ValueError("plan cache: 'chips' must be an object")
    if set(obj) - {"version", "chips"}:
        raise ValueError("plan cache: unknown top-level keys "
                         f"{sorted(set(obj) - {'version', 'chips'})}")
    for chip, ops in chips.items():
        if not isinstance(ops, dict):
            raise ValueError(f"plan cache: chip {chip!r} must map ops")
        for op, entries in ops.items():
            if op not in ALL_OPS:
                raise ValueError(f"plan cache: unknown op {op!r} "
                                 f"(known: {ALL_OPS})")
            if not isinstance(entries, dict):
                raise ValueError(f"plan cache: {chip}/{op} must be an "
                                 "object")
            for key, ent in entries.items():
                _parse_key(key)
                if not isinstance(ent, dict):
                    raise ValueError(
                        f"plan cache: {chip}/{op}/{key} must be an object")
                if ent.get("kernel") not in KERNELS:
                    raise ValueError(
                        f"plan cache: {chip}/{op}/{key} kernel must be one "
                        f"of {KERNELS}, got {ent.get('kernel')!r}")
                for field in ("nb", "bw"):
                    v = ent.get(field)
                    if not isinstance(v, int) or v <= 0:
                        raise ValueError(
                            f"plan cache: {chip}/{op}/{key} '{field}' must "
                            f"be a positive int, got {v!r}")
                g = ent.get("gflops")
                if g is not None and not isinstance(g, (int, float)):
                    raise ValueError(
                        f"plan cache: {chip}/{op}/{key} 'gflops' must be "
                        f"a number, got {g!r}")


def chip_kind() -> str:
    """Cache key for the local accelerator: the device kind string
    (e.g. 'tpu-v5-lite'), normalized; 'cpu' off-accelerator."""
    try:
        import jax
        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", "") or dev.platform
    except Exception:                            # uninitialized backend
        return "cpu"
    return str(kind).strip().lower().replace(" ", "-")


def load_cache(path: str | None = None) -> dict:
    """Read + validate the plan cache; a missing file is an empty cache."""
    path = path or cache_path()
    if not os.path.exists(path):
        return _empty()
    with open(path, encoding="utf-8") as fh:
        obj = json.load(fh)
    validate_cache(obj)
    return obj


def save_cache(obj: dict, path: str | None = None) -> str:
    """Validate + atomically persist the plan cache; returns the path."""
    validate_cache(obj)
    path = path or cache_path()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(obj, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    reload()
    return path


def reload() -> None:
    """Drop the in-memory cache (next resolve_plan re-reads disk)."""
    global _CACHE, _CACHE_KEY
    with _LOCK:
        _CACHE = None
        _CACHE_KEY = None


def _cached() -> dict:
    global _CACHE, _CACHE_KEY
    path = cache_path()
    with _LOCK:
        if _CACHE is None or _CACHE_KEY != path:
            try:
                _CACHE = load_cache(path)
            except (ValueError, OSError) as e:
                warnings.warn(f"slate_tpu.tune: ignoring bad plan cache "
                              f"at {path}: {e}", stacklevel=3)
                _CACHE = _empty()
            _CACHE_KEY = path
        return _CACHE


def record_plan(op: str, n: int, dtype: str, plan: TilePlan,
                gflops: float | None = None, chip: str | None = None,
                path: str | None = None) -> str:
    """Persist one winning plan (autotuner/tests only — drivers resolve
    through resolve_plan)."""
    if op not in ALL_OPS:
        raise ValueError(f"unknown op {op!r} (known: {ALL_OPS})")
    obj = load_cache(path)
    ent = {"kernel": plan.kernel, "nb": int(plan.nb), "bw": int(plan.bw)}
    if gflops is not None:
        ent["gflops"] = float(gflops)
    chip = chip or chip_kind()
    obj.setdefault("chips", {}).setdefault(chip, {}).setdefault(
        op, {})[plan_key(n, dtype)] = ent
    return save_cache(obj, path)


@contextlib.contextmanager
def plan_override(op: str, plan: TilePlan):
    """Force ``resolve_plan(op, ...)`` to return ``plan`` (tests)."""
    if op not in ALL_OPS:
        raise ValueError(f"unknown op {op!r} (known: {ALL_OPS})")
    prev = _OVERRIDES.get(op)
    _OVERRIDES[op] = plan
    try:
        yield
    finally:
        if prev is None:
            _OVERRIDES.pop(op, None)
        else:
            _OVERRIDES[op] = prev


def _warn_removed_env() -> None:
    """SLATE_PALLAS is REMOVED: warn once per process that the variable
    is ignored, pointing at the supported seams."""
    global _WARNED
    if _WARNED or os.environ.get("SLATE_PALLAS") is None:
        return
    _WARNED = True
    warnings.warn(
        "SLATE_PALLAS has been removed and is IGNORED; kernel selection "
        "comes from the autotuner plan cache. Use plan_override() in "
        "tests or tune plans with `python -m slate_tpu.tune` "
        "(see docs/TUNING.md).", stacklevel=3)


def _lookup(op: str, n: int, dtype: str):
    """Nearest tuned plan by |log2(n/n')|, same dtype only.  Returns
    ``(TilePlan, dist)`` — dist 0.0 is an exact size hit — or None."""
    entries = _cached().get("chips", {}).get(chip_kind(), {}).get(op)
    if not entries:
        return None
    best_key, best_dist = None, None
    for key in entries:
        kn, kdt = _parse_key(key)
        if kdt != dtype:
            continue
        dist = abs(math.log2(max(n, 1) / max(kn, 1)))
        if best_dist is None or dist < best_dist:
            best_key, best_dist = key, dist
    if best_key is None:
        return None
    ent = entries[best_key]
    return TilePlan(ent["kernel"], int(ent["nb"]), int(ent["bw"])), best_dist


def resolve_plan(op: str, n: int, dtype: str = "float32") -> TilePlan:
    """The ONLY plan entry point for dispatch seams: the tuned
    ``TilePlan`` for ``op`` at problem size ``n`` (nearest tuned size
    for this chip kind wins; exact match preferred).  Arguments must be
    host-static (shape ints / dtype names) — the result is static
    configuration, safe inside jit-traced drivers.  Each resolution is
    noted into the open obs event frame (cache hit vs nearest-n
    distance), so production events audit plan usage."""
    from ..obs import events as _obs
    from ..robust.precision import normalize_dtype
    if op not in OPS and op not in (DIST_LOOKAHEAD_OP, OOC_PANEL_OP):
        raise ValueError(
            f"unknown op {op!r} "
            f"(known: {OPS + (DIST_LOOKAHEAD_OP, OOC_PANEL_OP)})")
    dtype = normalize_dtype(dtype)
    _warn_removed_env()
    ov = _OVERRIDES.get(op)
    if ov is not None:
        _obs.note_plan(op, int(n), dtype, ov.kernel, ov.nb,
                       "override", None)
        return ov
    found = _lookup(op, int(n), dtype)
    if found is None:
        plan, source, dist = XLA_PLAN, "default", None
    else:
        plan, dist = found
        source = "exact" if dist == 0.0 else "nearest"
    _obs.note_plan(op, int(n), dtype, plan.kernel, plan.nb, source, dist)
    return plan


def lookahead_depth(n: int, dtype: str = "float32") -> int:
    """Tuned comm/compute lookahead depth for the distributed kernels.

    The SINGLE accessor the dist wrappers consult (SEAM011 — same
    contract as resolve_plan, which it rides): host-static arguments,
    static int result.  Untuned chips resolve to the default XLA_PLAN
    (kernel "xla") and get depth 0, the bulk-synchronous bit-exact
    fallback; a tuned ``dist_lookahead`` entry with kernel "ring" turns
    on the pipeline at depth ``bw``, clamped to the supported 1..2."""
    plan = resolve_plan(DIST_LOOKAHEAD_OP, n, dtype)
    if plan.kernel != "ring":
        return 0
    return max(1, min(2, int(plan.bw)))


def ooc_panel_width(n: int, dtype: str = "float32",
                    default: int = 256) -> int:
    """Tuned out-of-core panel width for the TileMap streaming drivers.

    The SINGLE accessor potrf_ooc/getrf_ooc consult when the caller does
    not pin ``nb`` (SEAM011 — rides resolve_plan like lookahead_depth):
    host-static arguments, static int result.  Untuned chips resolve to
    the default XLA_PLAN and get ``default`` (clamped to n); a tuned
    ``ooc_panel`` entry contributes its measured ``nb``.  The width also
    feeds the resumed-run fingerprint (robust/checkpoint.py), so a tuned
    width change between save and resume refuses rather than silently
    changing the panel schedule."""
    plan = resolve_plan(OOC_PANEL_OP, n, dtype)
    width = plan.nb if plan is not XLA_PLAN else default
    return max(1, min(int(width), int(n)))


def serve_buckets(dtype: str = "float32") -> tuple[int, ...] | None:
    """Tuned serving bucket ladder for this chip, or None when untuned.

    The serving layer (slate_tpu.serve.bucket) calls THIS accessor — not
    the raw cache (SEAM011) — to override its default geometric ladder.
    Each ``serve_bucket`` entry recorded via :func:`record_plan` (op
    ``SERVE_BUCKET_OP``, ``n`` = the bucket edge, kernel/nb/bw ignored)
    contributes one rung; the returned tuple is sorted ascending."""
    from ..robust.precision import normalize_dtype
    dtype = normalize_dtype(dtype)
    entries = _cached().get("chips", {}).get(chip_kind(), {}).get(
        SERVE_BUCKET_OP)
    if not entries:
        return None
    rungs = sorted({n for n, dt in map(_parse_key, entries)
                    if dt == dtype})
    return tuple(rungs) or None
