"""CLI: ``python -m slate_tpu.tune [--op OP ...] [--n N ...]``.

Measures every candidate plan for the requested (op, n) grid, prints
one JSON line per candidate, and persists the winners to the plan
cache (unless --dry-run).  Run once per new chip kind."""

from __future__ import annotations

import argparse
import json
import sys

from . import autotune, plans


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m slate_tpu.tune")
    ap.add_argument("--op", action="append", choices=plans.OPS,
                    help="op(s) to tune (default: all)")
    ap.add_argument("--n", action="append", type=int,
                    help="problem size(s) (default: 256 512 1024)")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--dry-run", action="store_true",
                    help="measure + print, do not persist")
    args = ap.parse_args(argv)
    ops = args.op or list(plans.OPS)
    ns = args.n or [256, 512, 1024]
    chip = plans.chip_kind()
    for op in ops:
        for n in ns:
            best_plan, best_gf = None, -1.0
            for plan, gf in autotune.sweep(op, n, args.dtype,
                                           iters=args.iters):
                print(json.dumps({"op": op, "n": n, "chip": chip,
                                  "kernel": plan.kernel, "nb": plan.nb,
                                  "bw": plan.bw,
                                  "gflops": round(gf, 3)}))
                if gf > best_gf:
                    best_plan, best_gf = plan, gf
            if not args.dry_run:
                plans.record_plan(op, n, args.dtype, best_plan,
                                  gflops=best_gf)
            print(json.dumps({"op": op, "n": n, "chip": chip,
                              "winner": best_plan.kernel,
                              "nb": best_plan.nb, "bw": best_plan.bw,
                              "persisted": not args.dry_run}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
