"""CLI: ``python -m slate_tpu.tune [--op OP ...] [--n N ...]``.

Measures every candidate plan for the requested (op, n) grid, prints
one JSON line per candidate, and persists the winners to the plan
cache (unless --dry-run).  Run once per new chip kind.

``--serve-hist SIZES.jsonl`` switches to serve-bucket ladder fitting:
the file holds one recorded request size per line (a bare integer or
an object with an ``n``/``size`` field, e.g. a log of serve submits);
the tuner fits a padded-area-optimal ladder of at most ``--hist-rungs``
rungs and persists one ``serve_bucket`` cache entry per rung, which
``tune.serve_buckets`` / ``serve.bucket.default_ladder`` then serve."""

from __future__ import annotations

import argparse
import json
import sys

from . import autotune, plans


def _read_hist(path: str) -> list[int]:
    sizes = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if isinstance(rec, dict):
                rec = rec.get("n", rec.get("size"))
            if rec is None:
                raise ValueError(f"--serve-hist: line without n/size: "
                                 f"{line!r}")
            sizes.append(int(rec))
    return sizes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m slate_tpu.tune")
    ap.add_argument("--op", action="append", choices=plans.OPS,
                    help="op(s) to tune (default: all)")
    ap.add_argument("--n", action="append", type=int,
                    help="problem size(s) (default: 256 512 1024)")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--dry-run", action="store_true",
                    help="measure + print, do not persist")
    ap.add_argument("--serve-hist", metavar="SIZES.jsonl",
                    help="fit + persist the serve_bucket ladder from a "
                         "request-size histogram instead of tuning ops")
    ap.add_argument("--hist-rungs", type=int, default=8,
                    help="max ladder rungs for --serve-hist (default 8)")
    args = ap.parse_args(argv)
    chip = plans.chip_kind()

    if args.serve_hist:
        sizes = _read_hist(args.serve_hist)
        rungs, w_geo, w_tuned = autotune.tune_serve_buckets(
            sizes, dtype=args.dtype, max_rungs=args.hist_rungs,
            persist=not args.dry_run)
        for r in rungs:
            print(json.dumps({"op": plans.SERVE_BUCKET_OP, "chip": chip,
                              "dtype": args.dtype, "rung": int(r)}))
        print(json.dumps({"op": plans.SERVE_BUCKET_OP, "chip": chip,
                          "dtype": args.dtype, "sizes": len(sizes),
                          "rungs": [int(r) for r in rungs],
                          "padding_waste_geometric": round(w_geo, 4),
                          "padding_waste_tuned": round(w_tuned, 4),
                          "persisted": not args.dry_run}))
        return 0

    ops = args.op or list(plans.OPS)
    ns = args.n or [256, 512, 1024]
    for op in ops:
        for n in ns:
            best_plan, best_gf = None, -1.0
            for plan, gf in autotune.sweep(op, n, args.dtype,
                                           iters=args.iters):
                print(json.dumps({"op": op, "n": n, "chip": chip,
                                  "kernel": plan.kernel, "nb": plan.nb,
                                  "bw": plan.bw,
                                  "gflops": round(gf, 3)}))
                if gf > best_gf:
                    best_plan, best_gf = plan, gf
            if not args.dry_run:
                plans.record_plan(op, n, args.dtype, best_plan,
                                  gflops=best_gf)
            print(json.dumps({"op": op, "n": n, "chip": chip,
                              "winner": best_plan.kernel,
                              "nb": best_plan.nb, "bw": best_plan.bw,
                              "persisted": not args.dry_run}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
