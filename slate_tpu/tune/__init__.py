"""Tile-plan autotuner: measure candidate (kernel, nb, bw) plans per
(op, n, dtype, chip), persist winners to an on-disk JSON cache, and
resolve them statically at trace time (plans.resolve_plan — the only
entry point dispatch seams may use; see docs/TUNING.md).  The serving
layer's bucket ladder rides the same cache under ``SERVE_BUCKET_OP``,
read back through :func:`plans.serve_buckets` (docs/SERVING.md); the
out-of-core drivers' streaming panel width rides it under
``OOC_PANEL_OP``, read back through :func:`plans.ooc_panel_width`
(docs/ROBUSTNESS.md "Durable jobs")."""

from .plans import (ALL_OPS, DIST_LOOKAHEAD_OP, OOC_PANEL_OP, OPS,
                    SCHEMA_VERSION, SERVE_BUCKET_OP, TilePlan, XLA_PLAN,
                    cache_path, chip_kind, load_cache, lookahead_depth,
                    ooc_panel_width, plan_override, record_plan, reload,
                    resolve_plan, save_cache, serve_buckets,
                    validate_cache)

__all__ = ["ALL_OPS", "DIST_LOOKAHEAD_OP", "OOC_PANEL_OP", "OPS",
           "SCHEMA_VERSION", "SERVE_BUCKET_OP", "TilePlan", "XLA_PLAN",
           "cache_path", "chip_kind", "load_cache", "lookahead_depth",
           "ooc_panel_width", "plan_override", "record_plan", "reload",
           "resolve_plan", "save_cache", "serve_buckets",
           "validate_cache"]
