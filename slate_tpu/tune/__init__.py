"""Tile-plan autotuner: measure candidate (kernel, nb, bw) plans per
(op, n, dtype, chip), persist winners to an on-disk JSON cache, and
resolve them statically at trace time (plans.resolve_plan — the only
entry point dispatch seams may use; see docs/TUNING.md)."""

from .plans import (OPS, SCHEMA_VERSION, TilePlan, XLA_PLAN, cache_path,
                    chip_kind, load_cache, plan_override, record_plan,
                    reload, resolve_plan, save_cache, validate_cache)

__all__ = ["OPS", "SCHEMA_VERSION", "TilePlan", "XLA_PLAN", "cache_path",
           "chip_kind", "load_cache", "plan_override", "record_plan",
           "reload", "resolve_plan", "save_cache", "validate_cache"]
