"""Autotuner: measure candidate (kernel, nb, bw) plans, persist winners.

One measurement = build a representative problem for the op at size n,
jit the candidate's code path, warm it up (compile excluded), then take
the best of ``iters`` timed runs.  Winners go to the plan cache via
plans.record_plan; dispatch seams read them back with resolve_plan.

Off-TPU the Pallas candidates run in interpret mode — functionally
identical, uselessly slow — so tuning there just confirms the XLA
default.  Re-tune on a new chip with ``python -m slate_tpu.tune``
(docs/TUNING.md).
"""

from __future__ import annotations

import time

from .plans import OPS, TilePlan, record_plan

CANDIDATE_NB = (128, 256, 512)
CANDIDATE_BW = (8, 16)
_SEED = 0


def _interpret() -> bool:
    import jax
    return jax.default_backend() != "tpu"


def candidates(op: str, n: int, dtype: str = "float32") -> list[TilePlan]:
    """The search space for one (op, n, dtype): always the XLA fallback,
    plus every shape-legal Pallas (nb, bw) pair."""
    if op not in OPS:
        raise ValueError(f"unknown op {op!r} (known: {OPS})")
    plans = [TilePlan("xla", min(n, 512), 8)]
    if dtype != "float32":
        return plans                  # pallas kernels are f32-only
    if op in ("potrf_tile", "lu_select"):
        nbs = [n] if n % 128 == 0 and 128 <= n <= 1024 else []
    else:
        nbs = [nb for nb in CANDIDATE_NB if nb <= n and n % nb == 0]
    for nb in nbs:
        if op == "geqrf_panel":       # no bw knob in the QR kernel
            plans.append(TilePlan("pallas", nb, 8))
            continue
        plans.extend(TilePlan("pallas", nb, bw) for bw in CANDIDATE_BW
                     if nb % bw == 0)
    return plans


def _problem(op: str, plan: TilePlan, n: int):
    """Returns (thunk, flops): a zero-arg jitted candidate runner and the
    nominal flop count it performs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..internal import getrf, qr, trsm
    from ..internal.pallas_chol import chol_panel_fused, chol_tile_pallas
    from ..internal.pallas_lu import lu_panel_fused, lu_select_pallas
    from ..internal.pallas_qr import qr_panel_pallas

    rng = np.random.default_rng(_SEED)
    interp = _interpret()
    nb = min(plan.nb, n)
    pallas = plan.kernel == "pallas"

    if op == "potrf_tile":
        g = rng.standard_normal((n, n)).astype(np.float32)
        a = jnp.asarray(g @ g.T + n * np.eye(n, dtype=np.float32))
        if pallas:
            fn = jax.jit(lambda x: chol_tile_pallas(x, bw=plan.bw,
                                                    interpret=interp))
        else:
            fn = jax.jit(jnp.linalg.cholesky)
        return (lambda: fn(a)), n ** 3 / 3

    if op == "potrf_panel":
        g = rng.standard_normal((n, n)).astype(np.float32)
        a = g @ g.T + n * np.eye(n, dtype=np.float32)
        llead = np.linalg.cholesky(a[:nb, :nb]).astype(np.float32)
        col = jnp.asarray(a[:, :nb])
        left = jnp.asarray(np.tile(llead, (n // nb, 1)))
        lead = jnp.asarray(llead.T)
        if pallas:
            fn = jax.jit(lambda c, lf, ld: chol_panel_fused(
                c, lf, ld, bw=plan.bw, interpret=interp))
        else:
            def fn(c, lf, ld):
                upd = c - lf @ ld
                lkk = jnp.linalg.cholesky(upd[:nb])
                return upd, jnp.concatenate(
                    [lkk, upd[nb:] @ trsm.tri_inv_lower(lkk).T])
            fn = jax.jit(fn)
        flops = 2 * n * nb * nb + nb ** 3 / 3 + (n - nb) * nb ** 2
        return (lambda: fn(col, left, lead)), flops

    if op == "getrf_panel":
        p = rng.standard_normal((n, nb)).astype(np.float32)
        p[:nb] += nb * np.eye(nb, dtype=np.float32)
        panel = jnp.asarray(p)
        if pallas:
            fn = jax.jit(lambda x: lu_panel_fused(x, bw=plan.bw,
                                                  interpret=interp))
        else:
            fn = jax.jit(lambda x: getrf.panel_lu_nopiv(x)[0])
        return (lambda: fn(panel)), n * nb ** 2

    if op == "lu_select":
        chunk = jnp.asarray(rng.standard_normal((n, nb)).astype(np.float32))
        if pallas:
            fn = jax.jit(lambda x: lu_select_pallas(x, bw=plan.bw,
                                                    interpret=interp))
        else:
            fn = jax.jit(lambda x: jax.lax.linalg.lu(x)[2][:nb])
        return (lambda: fn(chunk)), n * nb ** 2

    if op == "geqrf_panel":
        panel = jnp.asarray(rng.standard_normal((n, nb)).astype(np.float32))
        if pallas:
            fn = jax.jit(lambda x: qr_panel_pallas(x, interpret=interp))
        else:
            fn = jax.jit(qr.householder_panel_blocked)
        return (lambda: fn(panel)), 2 * n * nb ** 2

    raise ValueError(f"unknown op {op!r}")


def measure(op: str, plan: TilePlan, n: int, iters: int = 3) -> float:
    """GFLOP/s of one candidate (best of ``iters``, compile excluded)."""
    import jax

    thunk, flops = _problem(op, plan, n)
    jax.block_until_ready(thunk())               # compile + warm caches
    best = float("inf")
    for _ in range(max(1, iters)):
        t0 = time.perf_counter()
        jax.block_until_ready(thunk())
        best = min(best, time.perf_counter() - t0)
    return flops / best / 1e9


def sweep(op: str, n: int, dtype: str = "float32", iters: int = 3):
    """Yield (plan, gflops) for every candidate of (op, n, dtype)."""
    for plan in candidates(op, n, dtype):
        yield plan, measure(op, plan, n, iters=iters)


def tune_op(op: str, n: int, dtype: str = "float32", iters: int = 3,
            persist: bool = True) -> tuple[TilePlan, float]:
    """Measure all candidates, persist the winner, return it."""
    best_plan, best_gf = None, -1.0
    for plan, gf in sweep(op, n, dtype, iters=iters):
        if gf > best_gf:
            best_plan, best_gf = plan, gf
    if persist:
        record_plan(op, n, dtype, best_plan, gflops=best_gf)
    return best_plan, best_gf


def tune_all(ns=(256, 512, 1024), ops=OPS, dtype: str = "float32",
             iters: int = 3, persist: bool = True):
    """Tune every (op, n) pair; returns {(op, n): (plan, gflops)}."""
    out = {}
    for op in ops:
        for n in ns:
            out[(op, n)] = tune_op(op, n, dtype, iters=iters,
                                   persist=persist)
    return out
