"""Autotuner: measure candidate (kernel, nb, bw) plans, persist winners.

One measurement = build a representative problem for the op at size n,
jit the candidate's code path, warm it up (compile excluded), then take
the best of ``iters`` timed runs.  Winners go to the plan cache via
plans.record_plan; dispatch seams read them back with resolve_plan.

Off-TPU the Pallas candidates run in interpret mode — functionally
identical, uselessly slow — so tuning there just confirms the XLA
default.  Re-tune on a new chip with ``python -m slate_tpu.tune``
(docs/TUNING.md).
"""

from __future__ import annotations

import time

from .plans import OPS, TilePlan, record_plan

CANDIDATE_NB = (128, 256, 512)
CANDIDATE_BW = (8, 16)
_SEED = 0


def _interpret() -> bool:
    import jax
    return jax.default_backend() != "tpu"


def candidates(op: str, n: int, dtype: str = "float32") -> list[TilePlan]:
    """The search space for one (op, n, dtype): always the XLA fallback,
    plus every shape-legal Pallas (nb, bw) pair.  The batched ragged
    panels additionally take bf16 storage (f32 accumulation inside the
    kernels — internal/pallas_*.py), so those three ops sweep Pallas
    candidates for bf16 too; every other kernel is f32-only."""
    if op not in OPS:
        raise ValueError(f"unknown op {op!r} (known: {OPS})")
    plans = [TilePlan("xla", min(n, 512), 8)]
    batch = op in ("batch_potrf", "batch_getrf", "batch_geqrf")
    if dtype != "float32" and not (batch and dtype == "bfloat16"):
        return plans
    if op in ("potrf_tile", "lu_select"):
        nbs = [n] if n % 128 == 0 and 128 <= n <= 1024 else []
    else:
        nbs = [nb for nb in CANDIDATE_NB if nb <= n and n % nb == 0]
    for nb in nbs:
        if op in ("geqrf_panel", "batch_geqrf"):  # no bw knob in QR kernels
            plans.append(TilePlan("pallas", nb, 8))
            continue
        plans.extend(TilePlan("pallas", nb, bw) for bw in CANDIDATE_BW
                     if nb % bw == 0)
    return plans


def _problem(op: str, plan: TilePlan, n: int, dtype: str = "float32"):
    """Returns (thunk, flops): a zero-arg jitted candidate runner and the
    nominal flop count it performs.  ``dtype`` reaches only the batched
    ops (the single-shot kernels are f32-only, see candidates()); their
    XLA fallbacks compute through f32 exactly as the serving route's
    promote/demote emulation does, so the measurement is honest."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..internal import getrf, qr, trsm
    from ..internal.pallas_chol import chol_panel_fused, chol_tile_pallas
    from ..internal.pallas_lu import lu_panel_fused, lu_select_pallas
    from ..internal.pallas_qr import qr_panel_pallas

    rng = np.random.default_rng(_SEED)
    interp = _interpret()
    nb = min(plan.nb, n)
    pallas = plan.kernel == "pallas"

    if op == "potrf_tile":
        g = rng.standard_normal((n, n)).astype(np.float32)
        a = jnp.asarray(g @ g.T + n * np.eye(n, dtype=np.float32))
        if pallas:
            fn = jax.jit(lambda x: chol_tile_pallas(x, bw=plan.bw,
                                                    interpret=interp))
        else:
            fn = jax.jit(jnp.linalg.cholesky)
        return (lambda: fn(a)), n ** 3 / 3

    if op == "potrf_panel":
        g = rng.standard_normal((n, n)).astype(np.float32)
        a = g @ g.T + n * np.eye(n, dtype=np.float32)
        llead = np.linalg.cholesky(a[:nb, :nb]).astype(np.float32)
        col = jnp.asarray(a[:, :nb])
        left = jnp.asarray(np.tile(llead, (n // nb, 1)))
        lead = jnp.asarray(llead.T)
        if pallas:
            fn = jax.jit(lambda c, lf, ld: chol_panel_fused(
                c, lf, ld, bw=plan.bw, interpret=interp))
        else:
            def fn(c, lf, ld):
                upd = c - lf @ ld
                lkk = jnp.linalg.cholesky(upd[:nb])
                return upd, jnp.concatenate(
                    [lkk, upd[nb:] @ trsm.tri_inv_lower(lkk).T])
            fn = jax.jit(fn)
        flops = 2 * n * nb * nb + nb ** 3 / 3 + (n - nb) * nb ** 2
        return (lambda: fn(col, left, lead)), flops

    if op == "getrf_panel":
        p = rng.standard_normal((n, nb)).astype(np.float32)
        p[:nb] += nb * np.eye(nb, dtype=np.float32)
        panel = jnp.asarray(p)
        if pallas:
            fn = jax.jit(lambda x: lu_panel_fused(x, bw=plan.bw,
                                                  interpret=interp))
        else:
            fn = jax.jit(lambda x: getrf.panel_lu_nopiv(x)[0])
        return (lambda: fn(panel)), n * nb ** 2

    if op == "lu_select":
        chunk = jnp.asarray(rng.standard_normal((n, nb)).astype(np.float32))
        if pallas:
            fn = jax.jit(lambda x: lu_select_pallas(x, bw=plan.bw,
                                                    interpret=interp))
        else:
            fn = jax.jit(lambda x: jax.lax.linalg.lu(x)[2][:nb])
        return (lambda: fn(chunk)), n * nb ** 2

    if op == "geqrf_panel":
        panel = jnp.asarray(rng.standard_normal((n, nb)).astype(np.float32))
        if pallas:
            fn = jax.jit(lambda x: qr_panel_pallas(x, interpret=interp))
        else:
            fn = jax.jit(qr.householder_panel_blocked)
        return (lambda: fn(panel)), 2 * n * nb ** 2

    if op in ("batch_potrf", "batch_getrf", "batch_geqrf"):
        # Representative ragged bucket: B identity-augmented slots whose
        # live sizes sweep the bucket (serve/server.py's packing), flops
        # counted over LIVE work only so both routes report waste-adjusted
        # throughput against the same denominator.
        from ..internal import batched

        B = 8
        sizes = np.asarray([max(1, ((i + 1) * n) // B) for i in range(B)],
                           np.int32)
        a = np.zeros((B, n, n), np.float32)
        for i, s in enumerate(sizes):
            s = int(s)
            g = rng.standard_normal((s, s)).astype(np.float32)
            if op == "batch_potrf":
                a[i, :s, :s] = g @ g.T + s * np.eye(s, dtype=np.float32)
            elif op == "batch_getrf":
                a[i, :s, :s] = g + s * np.eye(s, dtype=np.float32)
            else:
                a[i, :s, :s] = g
            idx = np.arange(s, n)
            a[i, idx, idx] = 1.0                 # identity augmentation
        live = sizes.astype(np.float64)
        if op == "batch_geqrf":
            # problem-granular raggedness: live slots factor the whole
            # bucket panel (padding columns own real reflectors), slot 0
            # is a zero filler the kernel passes through
            sizes = np.where(np.arange(B) == 0, 0, n).astype(np.int32)
            a[0] = 0.0
            flops = 2 * n ** 3 / 3 * int((sizes > 0).sum())
        elif op == "batch_potrf":
            flops = float((live ** 3).sum()) / 3
        else:
            flops = 2 * float((live ** 3).sum()) / 3
        aj = jnp.asarray(a).astype(dtype)
        sj = jnp.asarray(sizes)
        f32 = lambda x: x.astype(jnp.float32)             # noqa: E731
        if op == "batch_potrf":
            if pallas:
                fn = jax.jit(lambda x, s: batched.batch_potrf(
                    x, s, nb=nb, bw=plan.bw, interpret=interp)[0])
            else:
                fn = jax.jit(lambda x, s: jax.vmap(jnp.linalg.cholesky)(
                    f32(x)).astype(x.dtype))
        elif op == "batch_getrf":
            if pallas:
                fn = jax.jit(lambda x, s: batched.batch_getrf(
                    x, s, nb=nb, bw=plan.bw, interpret=interp))
            else:
                fn = jax.jit(lambda x, s: jax.vmap(
                    lambda xi: jax.lax.linalg.lu(xi)[0])(
                        f32(x)).astype(x.dtype))
        else:
            if pallas:
                fn = jax.jit(lambda x, s: batched.batch_geqrf(
                    x, s, nb=nb, interpret=interp)[0])
            else:
                fn = jax.jit(lambda x, s: jax.vmap(
                    lambda xi: jnp.linalg.qr(xi, mode="r"))(
                        f32(x)).astype(x.dtype))
        return (lambda: fn(aj, sj)), flops

    raise ValueError(f"unknown op {op!r}")


def measure(op: str, plan: TilePlan, n: int, iters: int = 3,
            dtype: str = "float32") -> float:
    """GFLOP/s of one candidate (best of ``iters``, compile excluded)."""
    import jax

    thunk, flops = _problem(op, plan, n, dtype)
    jax.block_until_ready(thunk())               # compile + warm caches
    best = float("inf")
    for _ in range(max(1, iters)):
        t0 = time.perf_counter()
        jax.block_until_ready(thunk())
        best = min(best, time.perf_counter() - t0)
    return flops / best / 1e9


def sweep(op: str, n: int, dtype: str = "float32", iters: int = 3):
    """Yield (plan, gflops) for every candidate of (op, n, dtype)."""
    for plan in candidates(op, n, dtype):
        yield plan, measure(op, plan, n, iters=iters, dtype=dtype)


def tune_op(op: str, n: int, dtype: str = "float32", iters: int = 3,
            persist: bool = True) -> tuple[TilePlan, float]:
    """Measure all candidates, persist the winner, return it."""
    best_plan, best_gf = None, -1.0
    for plan, gf in sweep(op, n, dtype, iters=iters):
        if gf > best_gf:
            best_plan, best_gf = plan, gf
    if persist:
        record_plan(op, n, dtype, best_plan, gflops=best_gf)
    return best_plan, best_gf


def tune_all(ns=(256, 512, 1024), ops=OPS, dtype: str = "float32",
             iters: int = 3, persist: bool = True):
    """Tune every (op, n) pair; returns {(op, n): (plan, gflops)}."""
    out = {}
    for op in ops:
        for n in ns:
            out[(op, n)] = tune_op(op, n, dtype, iters=iters,
                                   persist=persist)
    return out


# -------------------------------------------------- serve_bucket ladder
#
# Not a kernel sweep: the ``serve_bucket`` pseudo-op records the bucket
# LADDER for this chip from a recorded request-size histogram.  Rungs
# are chosen to minimize total padded area (sum over requests of
# rung^2) by dynamic programming over the distinct tile-rounded sizes,
# then persisted one cache entry per rung so serve.bucket.default_ladder
# picks them up through tune.serve_buckets.


def serve_ladder_from_sizes(sizes, max_rungs: int = 8,
                            base: int = 32) -> tuple:
    """Padded-area-optimal bucket ladder (<= ``max_rungs`` rungs) for a
    request-size sample.  Sizes round up to ``base`` multiples (the tile
    edge — finer rungs cannot change the packed shapes); each rung must
    be one of the distinct rounded sizes and the top rung covers the
    largest, so every recorded request buckets without doubling.

    Two callers: the offline ``--serve-hist`` CLI fit (persisted as the
    ``serve_bucket`` plan) and the live server's online retune
    (``serve.Server.retune_now`` / the background retune tick), which
    hot-swaps the fitted ladder per process without persisting —
    docs/TUNING.md "Hot-swap"."""
    import collections

    pad = [max(base, -(-int(s) // base) * base) for s in sizes if int(s) > 0]
    if not pad:
        raise ValueError("serve_ladder_from_sizes: no positive sizes")
    hist = collections.Counter(pad)
    edges = sorted(hist)
    ne = len(edges)
    if ne <= max_rungs:
        return tuple(edges)
    # cost[lo][hi]: every request in edges[lo..hi] served at edges[hi]
    cost = [[0.0] * ne for _ in range(ne)]
    for lo in range(ne):
        cnt = 0
        for hi in range(lo, ne):
            cnt += hist[edges[hi]]
            cost[lo][hi] = cnt * edges[hi] ** 2
    inf = float("inf")
    dp = [[inf] * ne for _ in range(max_rungs + 1)]
    cut = [[-1] * ne for _ in range(max_rungs + 1)]
    for hi in range(ne):
        dp[1][hi] = cost[0][hi]
    for r in range(2, max_rungs + 1):
        for hi in range(r - 1, ne):
            for mid in range(r - 2, hi):
                c = dp[r - 1][mid] + cost[mid + 1][hi]
                if c < dp[r][hi]:
                    dp[r][hi] = c
                    cut[r][hi] = mid
    best_r = min(range(1, max_rungs + 1), key=lambda r: dp[r][ne - 1])
    rungs, r, hi = [], best_r, ne - 1
    while r > 1:
        rungs.append(edges[hi])
        hi = cut[r][hi]
        r -= 1
    rungs.append(edges[hi])
    return tuple(sorted(rungs))


def ladder_waste(sizes, ladder) -> float:
    """Padding waste (1 - live/padded area) of serving ``sizes`` square
    problems on ``ladder`` (a serve.bucket.BucketLadder)."""
    live = padded = 0
    for s in sizes:
        s = int(s)
        if s <= 0:
            continue
        b = ladder.bucket_for(s)
        live += s * s
        padded += b * b
    return 1.0 - live / padded if padded else 0.0


def tune_serve_buckets(sizes, dtype: str = "float32", max_rungs: int = 8,
                       persist: bool = True):
    """Fit a bucket ladder to a request-size histogram and persist it as
    ``serve_bucket`` plan-cache entries (one per rung).  Returns
    ``(rungs, waste_geometric, waste_tuned)`` so the CLI can report the
    padding-waste improvement over the geometric default."""
    from ..serve import bucket as _bucket
    from .plans import SERVE_BUCKET_OP, XLA_PLAN

    rungs = serve_ladder_from_sizes(sizes, max_rungs=max_rungs)
    w_geo = ladder_waste(sizes, _bucket.geometric_ladder())
    w_tuned = ladder_waste(sizes, _bucket.BucketLadder(rungs, "tuned"))
    if persist:
        for r in rungs:
            record_plan(SERVE_BUCKET_OP, int(r), dtype, XLA_PLAN)
    return rungs, w_geo, w_tuned
