"""slate_tpu — TPU-native distributed dense linear algebra.

A from-scratch framework with the capabilities of the reference SLATE library
(distributed tiled BLAS-3, linear solvers, least squares, eigensolvers, SVD;
ref: /root/reference README.md:15-37), re-designed for TPU:

- tiles live as one blocked, 2D-block-cyclic-sharded array per matrix in HBM,
- drivers compile to single XLA programs (jit) with MXU-shaped contractions,
- the distributed backend is jax.shard_map + ICI collectives over a
  ``jax.sharding.Mesh`` process grid,
- mixed-precision (f32 factor + f64 refine) is the native high-precision path.
"""

from .util import compat_jax as _compat_jax  # noqa: F401  (installs shims)
from .version import __version__, id, version  # noqa: F401
from .types import Diag, Layout, Norm, Op, Side, TileKind, Uplo  # noqa: F401
from .options import (  # noqa: F401
    ErrorPolicy, GridOrder, MethodCholQR, MethodEig, MethodGels, MethodGemm,
    MethodHemm, MethodLU, MethodSvd, MethodTrsm, NormScope, Option,
    Precision, Speculate, Target,
)
from .exceptions import (  # noqa: F401
    SlateError, SlateNotConvergedError, SlateNotPositiveDefiniteError,
    SlateSingularError, SlateUnsupportedDtypeError, SlateValueError,
)
from . import robust  # noqa: F401
from .robust.health import HealthInfo  # noqa: F401
from .core.grid import Grid, make_grid  # noqa: F401
from .core.storage import TileStorage  # noqa: F401
from .core.matrix import (  # noqa: F401
    BandMatrix, BaseBandMatrix, BaseMatrix, BaseTrapezoidMatrix,
    HermitianBandMatrix, HermitianMatrix, Matrix, SymmetricMatrix,
    TrapezoidMatrix, TriangularBandMatrix, TriangularMatrix,
)
from .drivers.blas3 import (  # noqa: F401
    gemm, gemmA, gemmC, hemm, hemmA, her2k, herk, symm, syr2k, syrk, trmm,
    trsm,
)
from .drivers.auxiliary import (  # noqa: F401
    add, col_norms, copy, norm, redistribute, scale, scale_row_col, set,
)
from .drivers.cholesky import (  # noqa: F401
    posv, potrf, potrf_ooc, potri, potrs,
)
from .drivers.inverse import trtri, trtrm  # noqa: F401
from .drivers.lu import (  # noqa: F401
    LUFactors, OocLUFactors, RBTFactors, gesv, gesv_nopiv, getrf,
    getrf_nopiv, getrf_ooc, getrf_rbt, getrf_tntpiv, getri, getriOOP,
    getrs,
)
from .drivers.qr import (  # noqa: F401
    CAQRFactors, LQFactors, QRFactors, cholqr, gelqf, gels, gels_cholqr,
    gels_qr, geqrf, qr_multiply, unmlq, unmqr,
)
from .drivers.band import (  # noqa: F401
    GBFactors, PBFactors, gbmm, gbsv, gbtrf, gbtrs, hbmm, pbsv, pbtrf,
    pbtrs, tbsm,
)
from .drivers.heev import (  # noqa: F401
    heev, heev_vals, heevd, hegst, hegv, hb2st, steqr, sterf,
)
from .drivers.stedc import stedc  # noqa: F401
from .drivers.printing import format_matrix, print_matrix  # noqa: F401
from .drivers.condest import gecondest, norm1est, trcondest  # noqa: F401
from .drivers.hetrf import HEFactors, hesv, hetrf, hetrs  # noqa: F401
from .drivers.svd import bdsqr, svd, svd_vals, tb2bd  # noqa: F401
from .drivers.mixed import (  # noqa: F401
    MixedResult, gesv_mixed, gesv_mixed_gmres, posv_mixed, posv_mixed_gmres,
)
from .util.generator import generate_hermitian, generate_matrix  # noqa: F401
from . import api, compat, obs, serve  # noqa: F401
