"""Distributed two-stage SVD stage 1: general m x n -> upper band, on mesh.

Analog of the reference's ge2tb driver (ref: src/ge2tb.cc QR+LQ panel
alternation with internal::geqrf/gelqf + unmqr/unmlq trailing updates;
back-transforms src/unmbr_ge2tb.cc).

TPU-first shape (ONE shard_map program, superblocked like dist_he2hb):

per panel k                               | here
----------------------------------------- | -------------------------------
geqrf on block column k (rows >= k)       | column gathered (scatter+psum),
                                          |   rolled, factored REPLICATED
unmqr trailing: C -= V Tq^H V^H C         | one psum of G = V^H C over the
                                          |   row axis, then local MXU
                                          |   gemms per rank (cols > k)
gelqf on block row k (cols >= k+1)        | row gathered, conj-transposed,
                                          |   rolled, factored REPLICATED
unmlq trailing: C -= (C Vl) Tl Vl^H       | one psum of H = C Vl over the
                                          |   column axis, local gemms
                                          |   (rows > k)

All O(mn^2) trailing flops are mesh-distributed; the skinny panel QR/LQ
factorizations (O(n nb^2) each) are replicated (the dist_lu trade).  Four
psums of skinny buffers per panel.  The packed result matches the dense
_ge2tb_dense layout: QR reflectors below the diagonal, the LQ L block
merged with conjugated reflector rows above the band, band on/above the
diagonal (tile (g, g) triu + tile (g, g+1) tril).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.grid import AXIS_P, AXIS_Q, TILE_SPEC, Grid
from ..util.compat_jax import shard_map_unchecked
from ..internal.qr import householder_panel_blocked, unit_lower
from .dist_chol import superblock
from .dist_he2hb import larfb_left_local, v_from_gathered
from .dist_lu import _gather_panel


def _gather_row(a_loc, k, p, q, ntl, r, c):
    """Replicate tile-row k on every rank: [q*ntl, nb, nb] (global col
    tile j at slot j) — the row mirror of dist_lu._gather_panel."""
    nb = a_loc.shape[-1]
    kkr = k // p
    rk = k % p
    row = lax.dynamic_index_in_dim(a_loc, kkr, axis=0, keepdims=False)
    gj_all = c + q * jnp.arange(ntl)
    buf = jnp.zeros((q * ntl, nb, nb), a_loc.dtype)
    buf = buf.at[gj_all].set(row)
    buf = jnp.where(r == rk, buf, jnp.zeros_like(buf))
    return lax.psum(lax.psum(buf, AXIS_P), AXIS_Q)


def _ge2tb_local(a_loc, Mt: int, Ntn: int, m: int, n: int, p: int, q: int,
                 mtl: int, ntl: int, sb: int):
    r = lax.axis_index(AXIS_P)
    c = lax.axis_index(AXIS_Q)
    nb = a_loc.shape[-1]
    dt = a_loc.dtype
    K = Ntn                                       # QR panels 0..Ntn-1
    gi_all = r + p * jnp.arange(mtl)
    gj_all = c + q * jnp.arange(ntl)
    zi = jnp.zeros((), jnp.int32)
    Tqs = jnp.zeros((K, nb, nb), dt)
    Tls = jnp.zeros((K, nb, nb), dt)

    for k0 in range(0, K, sb):
        k1s = min(k0 + sb, K)
        W0 = Mt - k0                              # QR panel window (rows)
        W0n = Ntn - (k0 + 1)                      # LQ panel window (cols)
        S = mtl - (k0 // p)                       # row slots with gi >= k0
        S1 = mtl - ((k0 + 1) // p)                # gi >= k0+1
        T1 = ntl - ((k0 + 1) // q)                # gj >= k0+1

        def super_step(k, carry, W0=W0, W0n=W0n, S=S, S1=S1, T1=T1, k0=k0):
            a_loc, Tqs, Tls = carry
            ki = k.astype(jnp.int32)
            ck, rk = k % q, k % p
            kkc, kkr = k // q, k // p

            # ================= QR panel (block column k, rows >= k) ======
            gpan = _gather_panel(a_loc, k, p, q, mtl, r, c)
            panel = gpan[k0: Mt].reshape(W0 * nb, nb)
            shift = (k - k0) * nb
            panel = jnp.roll(panel, -shift, axis=0)
            prow = jnp.arange(W0 * nb)
            live = prow < (m - k * nb)
            panel = jnp.where(live[:, None], panel, jnp.zeros_like(panel))
            packed, Tq = householder_panel_blocked(panel)
            Tqs = lax.dynamic_update_slice(Tqs, Tq[None], (ki, zi, zi))

            vwin = jnp.roll(unit_lower(packed), shift, axis=0)
            keepm = ((jnp.arange(W0 * nb) >= shift)
                     & jnp.roll(live, shift))[:, None]
            vwin = jnp.where(keepm, vwin, jnp.zeros_like(vwin))
            vfull = jnp.zeros((p * mtl * nb, nb), dt)
            vfull = vfull.at[k0 * nb: Mt * nb].set(vwin)
            Vt = vfull.reshape(p * mtl, nb, nb)

            # write the packed panel back (owner column only)
            pwin = jnp.roll(packed, shift, axis=0)
            pwin = jnp.where((jnp.arange(W0 * nb) >= shift)[:, None], pwin,
                             jnp.zeros_like(pwin))
            ptiles = pwin.reshape(W0, nb, nb)
            ptiles_all = jnp.take(ptiles, jnp.clip(gi_all - k0, 0, W0 - 1),
                                  axis=0)
            oldcol = lax.dynamic_index_in_dim(a_loc, kkc, axis=1,
                                              keepdims=False)
            newcol = jnp.where((gi_all >= k)[:, None, None], ptiles_all,
                               oldcol)
            col_sel = jnp.where(c == ck, newcol, oldcol)
            a_loc = lax.dynamic_update_slice(
                a_loc, col_sel[:, None], (zi, kkc.astype(jnp.int32), zi, zi))

            # ---- left trailing update on cols > k: C -= V Tq^H (V^H C) --
            sr = jnp.clip(-(-(k0 - r) // p), 0, mtl - S).astype(jnp.int32)
            sc1 = jnp.clip(-(-(k0 + 1 - c) // q), 0, ntl - T1).astype(
                jnp.int32)
            gi = r + p * (sr + jnp.arange(S))
            gj1 = c + q * (sc1 + jnp.arange(T1))
            A_w = lax.dynamic_slice(a_loc, (sr, sc1, zi, zi),
                                    (S, T1, nb, nb))
            Vr = Vt[gi]
            G = jnp.einsum('iab,ijac->jbc', jnp.conj(Vr), A_w)
            G = lax.psum(G, AXIS_P)               # [T1, nb, nb]
            TG = jnp.einsum('ab,jbc->jac', jnp.conj(Tq).T, G)
            updl = jnp.einsum('iab,jbc->ijac', Vr, TG)
            colmask = (gj1 > k)[None, :, None, None]
            A_w = jnp.where(colmask, A_w - updl, A_w)
            a_loc = lax.dynamic_update_slice(a_loc, A_w, (sr, sc1, zi, zi))

            # ================= LQ panel (block row k, cols >= k+1) =======
            # zero-width when (k+1)*nb >= n: all masks below no-op
            if W0n <= 0:              # static: no columns right of panel
                return a_loc, Tqs, Tls
            grow = _gather_row(a_loc, k, p, q, ntl, r, c)
            rblk = grow[k0 + 1: Ntn]              # [W0n, nb(row), nb(col)]
            # conj-transpose to column-reflector form [W0n*nb, nb]
            rpan = jnp.conj(jnp.transpose(rblk, (0, 2, 1))).reshape(
                W0n * nb, nb)
            rpan = jnp.roll(rpan, -shift, axis=0)
            lrow = jnp.arange(W0n * nb)
            livel = lrow < (n - (k + 1) * nb)
            rpan = jnp.where(livel[:, None], rpan, jnp.zeros_like(rpan))
            packed_l, Tl = householder_panel_blocked(rpan)
            has_lq = (k + 1) * nb < n
            Tl = jnp.where(has_lq, Tl, jnp.zeros_like(Tl))
            Tls = lax.dynamic_update_slice(Tls, Tl[None], (ki, zi, zi))

            vlwin = jnp.roll(unit_lower(packed_l), shift, axis=0)
            keepl = ((jnp.arange(W0n * nb) >= shift)
                     & jnp.roll(livel, shift))[:, None]
            vlwin = jnp.where(keepl, vlwin, jnp.zeros_like(vlwin))
            vlfull = jnp.zeros((q * ntl * nb, nb), dt)
            vlfull = vlfull.at[(k0 + 1) * nb: Ntn * nb].set(vlwin)
            Vlt = vlfull.reshape(q * ntl, nb, nb)

            # merged write-back of block row k (L on/below its diagonal,
            # conjugated reflector rows above — the gelqf packing)
            iw = jnp.arange(nb)[:, None]          # row within the block
            jk = jnp.arange(W0n * nb)[None, :]    # ROLLED col (0 = col k1)
            ell = jnp.conj(jnp.triu(packed_l)).T  # [nb, W0n*nb]
            vrows = jnp.conj(packed_l).T
            newblk = jnp.where(jk <= iw, ell, vrows)
            newblk = jnp.roll(newblk, shift, axis=1)
            newblk = jnp.where((jnp.arange(W0n * nb) >= shift)[None, :],
                               newblk, jnp.zeros((1, 1), dt))
            ntiles = jnp.transpose(newblk.reshape(nb, W0n, nb), (1, 0, 2))
            ntiles_all = jnp.take(ntiles, jnp.clip(gj_all - (k0 + 1), 0,
                                                   max(W0n - 1, 0)), axis=0)
            oldrow = lax.dynamic_index_in_dim(a_loc, kkr, axis=0,
                                              keepdims=False)
            newrow = jnp.where((has_lq & (gj_all >= k + 1))[:, None, None],
                               ntiles_all, oldrow)
            row_sel = jnp.where(r == rk, newrow, oldrow)
            a_loc = lax.dynamic_update_slice(
                a_loc, row_sel[None], (kkr.astype(jnp.int32), zi, zi, zi))

            # ---- right trailing update on rows > k: C -= (C Vl) Tl Vl^H -
            sr1 = jnp.clip(-(-(k0 + 1 - r) // p), 0, mtl - S1).astype(
                jnp.int32)
            gi1 = r + p * (sr1 + jnp.arange(S1))
            B_w = lax.dynamic_slice(a_loc, (sr1, sc1, zi, zi),
                                    (S1, T1, nb, nb))
            Vlc = Vlt[gj1]
            H = jnp.einsum('ijab,jbc->iac', B_w, Vlc)
            H = lax.psum(H, AXIS_Q)               # [S1, nb, nb]
            HT = jnp.einsum('iab,bc->iac', H, Tl)
            updr = jnp.einsum('iac,jbc->ijab', HT, jnp.conj(Vlc))
            rowmask = (gi1 > k)[:, None, None, None]
            B_w = jnp.where(rowmask, B_w - updr, B_w)
            a_loc = lax.dynamic_update_slice(a_loc, B_w, (sr1, sc1, zi, zi))
            return a_loc, Tqs, Tls

        if W0 <= 0 or S <= 0:
            continue
        a_loc, Tqs, Tls = lax.fori_loop(k0, k1s, super_step,
                                        (a_loc, Tqs, Tls))

    return a_loc, Tqs, Tls


def dist_ge2tb(data, Mt: int, Ntn: int, m: int, n: int, grid: Grid,
               sb: int | None = None):
    """Reduce cyclic storage of a general m x n (m >= n) matrix to the
    two-stage upper band form in place.  Returns (data, Tqs, Tls)."""
    mtl = data.shape[0] // grid.p
    ntl = data.shape[1] // grid.q
    sb = sb if sb is not None else superblock(max(Ntn, 1))
    spec = TILE_SPEC
    fn = shard_map_unchecked(
        lambda a: _ge2tb_local(a, Mt, Ntn, m, n, grid.p, grid.q, mtl, ntl,
                               sb),
        mesh=grid.mesh, in_specs=(spec,), out_specs=(spec, P(), P()))
    return fn(data)


def _unmbr_u_local(a_loc, z_loc, Tqs, m: int, p: int, q: int, mtl: int):
    """Z <- U1 Z, QR panels descending (ref: unmbr_ge2tb U side)."""
    r = lax.axis_index(AXIS_P)
    c = lax.axis_index(AXIS_Q)
    nb = a_loc.shape[-1]
    K = Tqs.shape[0]
    gi_all = r + p * jnp.arange(mtl)

    def body(i, z_loc):
        k = K - 1 - i
        gpan = _gather_panel(a_loc, k, p, q, mtl, r, c)
        v = v_from_gathered(gpan.reshape(p * mtl * nb, nb), k * nb, m)
        Vt = v.reshape(p * mtl, nb, nb)
        Tk = lax.dynamic_index_in_dim(Tqs, k, axis=0, keepdims=False)
        return larfb_left_local(z_loc, Vt, Tk, gi_all)

    if K <= 0:
        return z_loc
    return lax.fori_loop(0, K, body, z_loc)


def _unmbr_v_local(a_loc, z_loc, Tls, n: int, p: int, q: int, ntl: int,
                   mtl_z: int):
    """Z <- V1 Z, LQ panels descending (ref: unmbr_ge2tb V side); Z's rows
    live in A's column space (the LQ reflectors are row-space)."""
    r = lax.axis_index(AXIS_P)
    c = lax.axis_index(AXIS_Q)
    nb = a_loc.shape[-1]
    dt = a_loc.dtype
    K = Tls.shape[0]
    gi_all = r + p * jnp.arange(mtl_z)
    nz_pad = p * mtl_z * nb

    def body(i, z_loc):
        k = K - 1 - i
        grow = _gather_row(a_loc, k, p, q, ntl, r, c)
        rpan = jnp.conj(jnp.transpose(grow, (0, 2, 1))).reshape(
            q * ntl * nb, nb)
        v = v_from_gathered(rpan, (k + 1) * nb, n)
        # re-pad from A's column space to Z's row space
        vz = jnp.zeros((nz_pad, nb), dt)
        ncopy = min(nz_pad, q * ntl * nb)
        vz = vz.at[:ncopy].set(v[:ncopy])
        Vt = vz.reshape(p * mtl_z, nb, nb)
        Tk = lax.dynamic_index_in_dim(Tls, k, axis=0, keepdims=False)
        return larfb_left_local(z_loc, Vt, Tk, gi_all)

    if K <= 0:
        return z_loc
    return lax.fori_loop(0, K, body, z_loc)


def dist_unmbr_ge2tb_u(a_data, Tqs, z_data, grid: Grid, m: int):
    """Apply the ge2tb U1 (QR chain) to mesh-distributed Z."""
    mtl = a_data.shape[0] // grid.p
    spec = TILE_SPEC
    fn = shard_map_unchecked(
        lambda a, z, t: _unmbr_u_local(a, z, t, m, grid.p, grid.q, mtl),
        mesh=grid.mesh, in_specs=(spec, spec, P()), out_specs=spec)
    return fn(a_data, z_data, Tqs)


def dist_unmbr_ge2tb_v(a_data, Tls, z_data, grid: Grid, n: int):
    """Apply the ge2tb V1 (LQ chain) to mesh-distributed Z (rows in A's
    column space)."""
    ntl = a_data.shape[1] // grid.q
    mtl_z = z_data.shape[0] // grid.p
    spec = TILE_SPEC
    fn = shard_map_unchecked(
        lambda a, z, t: _unmbr_v_local(a, z, t, n, grid.p, grid.q,
                                       ntl, mtl_z),
        mesh=grid.mesh, in_specs=(spec, spec, P()), out_specs=spec)
    return fn(a_data, z_data, Tls)
