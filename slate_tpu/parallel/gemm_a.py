"""Stationary-A distributed gemm: reduce-over-C instead of broadcast-A.

Analog of the reference's gemmA algorithm (ref: src/gemmA.cc:1-893,
src/internal/internal_gemmA.cc): when C is much smaller than A (the
single-block-column solves inside IR, skinny projections, colNorms-style
updates), broadcasting A's panels — SUMMA / gemmC's pattern, O(m*k/p)
per rank — dwarfs the useful work.  gemmA keeps A stationary:

1. B (small: k x n with n << k) is replicated — two ring all-gathers,
   O(k*n) per rank, the analog of the reference broadcasting B's block
   column to A's owners (gemmA.cc bcast phase).
2. Each rank contracts its LOCAL A tiles against the matching B rows in
   one einsum — A never moves, each global k tile is covered by exactly
   the mesh column that owns it.
3. One psum_scatter along q both completes the k sum AND hands each rank
   exactly its C tiles — the reference's listReduce over C owners
   (gemmA.cc reduce phase) fused into a single ICI collective.

Comm: k*n (B replicate) + m*n/p (C reduce) per rank vs SUMMA's m*k/p.
Wins whenever n << k; the method auto-selection keeps SUMMA otherwise.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.grid import AXIS_P, AXIS_Q, TILE_SPEC, Grid


def dist_gemmA_data(a_data, b_data, c_data, alpha, beta, Kt: int,
                    grid: Grid):
    """C = alpha A B + beta C with A stationary.

    a_data [p*mtl, q*ktl_a, mb, kb], b_data [p*ktl_b, q*ntl, kb, nb],
    c_data [p*mtl, q*ntl, mb, nb] cyclic storage.
    """
    p, q = grid.p, grid.q
    ktl_a = a_data.shape[1] // q
    ntl = c_data.shape[1] // q

    def local(a_loc, b_loc, c_loc):
        c = lax.axis_index(AXIS_Q)
        dt = c_loc.dtype

        # ---- step 1: replicate B (skinny) ----
        ball = lax.all_gather(b_loc, AXIS_P, axis=0, tiled=False)
        ball = lax.all_gather(ball, AXIS_Q, axis=0, tiled=False)
        # ball[c', r', kl, jl] = B tile (gk = r' + p*kl, gj = c' + q*jl)

        # ---- step 2: local contraction, A stationary ----
        # my A k tiles are gk = c + q*ka; B rows for them, ALL columns:
        gk = c + q * jnp.arange(ktl_a)           # [ktl_a]
        gj = jnp.arange(q * ntl)                 # [Nt_pad]
        bsel = ball[(gj % q)[None, :], (gk % p)[:, None],
                    (gk // p)[:, None], (gj // q)[None, :]]
        # bsel [ktl_a, Nt_pad, kb, nb]; pad k tiles (gk >= Kt) hold zeros
        # by the storage pad invariant, so they add nothing.
        partial = jnp.einsum("ikab,kjbc->ijac", a_loc, bsel,
                             preferred_element_type=dt)

        # ---- step 3: fused k-sum + scatter to C owners along q ----
        # global j = c' + q*jl -> chunk c' carries cols {j ≡ c'}
        chunks = jnp.stack([partial[:, c2::q] for c2 in range(q)])
        mine = lax.psum_scatter(chunks, AXIS_Q, scatter_dimension=0,
                                tiled=False)     # [mtl, ntl, mb, nb]
        return jnp.asarray(alpha, dt) * mine + jnp.asarray(beta, dt) * c_loc

    spec = TILE_SPEC
    fn = jax.shard_map(local, mesh=grid.mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
    return fn(a_data, b_data, c_data)
