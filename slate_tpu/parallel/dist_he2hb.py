"""Distributed two-stage eig stage 1: Hermitian full -> band, on the mesh.

Analog of the reference's he2hb driver + internal kernels
(ref: src/he2hb.cc:25-600 panel QR + two-sided update task graph;
src/internal/internal_he2hb_hemm.cc:1-850 Y = A V with Hermitian A read
from the stored lower triangle; internal_he2hb_her2k_offdiag_ranks.cc:588
rank-2k trailing update; internal_he2hb_trmm/gemm.cc back-multiplies).

TPU-first shape (ONE shard_map program, superblocked like dist_chol):

per panel k                               | here
----------------------------------------- | -------------------------------
geqrf on the panel block column           | panel tile-column gathered to
  (he2hb.cc:112 internal::geqrf)          |   all ranks (scatter + psum),
                                          |   rolled to the top, factored
                                          |   REPLICATED by the fori_loop
                                          |   Householder kernel (the
                                          |   dist_lu replicated-panel trade)
listBcast of V, T to trailing owners      | (absorbed: panel replicated)
he2hb_hemm: W1 = A V over lower tiles     | per-rank einsum over its static
  (internal_he2hb_hemm.cc rank lists)     |   trailing window: lower tiles
                                          |   contribute A_ij V_j -> Y_i AND
                                          |   A_ij^H V_i -> Y_j, diagonal
                                          |   tiles Hermitian-completed
                                          |   in-register; ONE psum -> Y
W = Y T - 1/2 V (T^H (V^H Y) T)           | replicated skinny ops (V, Y, W
                                          |   are n x nb, tile-stacked)
her2k trailing: A -= V W^H + W V^H        | LOCAL einsum on the rank's
  (her2k_offdiag_ranks)                   |   window — zero communication
                                          |   (V, W replicated by rows)

The O(n^3) hemm + her2k flops are thus spread across the mesh; only the
skinny panel QR (O(n nb^2) per panel) is replicated, and communication is
two psums of [n, nb] buffers per panel.  Ragged last tiles ride the
pad-rows-are-zero storage invariant (zero rows produce identity reflectors).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.grid import AXIS_P, AXIS_Q, TILE_SPEC, Grid
from ..util.compat_jax import shard_map_unchecked
from ..internal.qr import householder_panel_blocked, unit_lower
from .dist_chol import superblock
from ..util.trace import span
from .dist_lu import _gather_panel


def _tril_real_diag(t):
    """tril(tile) with a real diagonal (Hermitian diag tiles may carry junk
    imaginary parts in storage; ref: potrf's same completion)."""
    out = jnp.tril(t)
    if jnp.iscomplexobj(t):
        nb = t.shape[-1]
        eye = jnp.eye(nb, dtype=bool)
        out = jnp.where(eye, jnp.real(out).astype(t.dtype), out)
    return out


def _he2hb_local(a_loc, Nt: int, n: int, p: int, q: int, mtl: int, ntl: int,
                 sb: int):
    r = lax.axis_index(AXIS_P)
    c = lax.axis_index(AXIS_Q)
    nb = a_loc.shape[-1]
    dt = a_loc.dtype
    K = Nt - 1                                   # panels 0..Nt-2
    gi_all = r + p * jnp.arange(mtl)
    rows_g = jnp.arange(p * mtl)
    zi = jnp.zeros((), jnp.int32)
    Ts = jnp.zeros((max(K, 1), nb, nb), dt)
    if K <= 0:
        return a_loc, Ts

    for k0 in range(0, K, sb):
        k1s = min(k0 + sb, K)
        # static windows for this superblock: panel rows / trailing tiles
        # with global index >= k0+1
        W0 = Nt - (k0 + 1)                       # panel window tiles
        S = mtl - ((k0 + 1) // p)                # trailing row slots
        T_ = ntl - ((k0 + 1) // q)               # trailing col slots

        def super_step(k, carry, W0=W0, S=S, T_=T_, k0=k0):
            a_loc, Ts = carry
            ck = k % q
            kkc = k // q

            # ---- gather + factor the panel (replicated) ----
            with span("slate.he2hb/panel"):
                gpan = _gather_panel(a_loc, k, p, q, mtl, r, c)
            panel = gpan[k0 + 1: Nt].reshape(W0 * nb, nb)
            shift = (k - k0) * nb
            panel = jnp.roll(panel, -shift, axis=0)
            prow = jnp.arange(W0 * nb)
            live = prow < (n - (k + 1) * nb)     # rows of the active panel
            panel = jnp.where(live[:, None], panel, jnp.zeros_like(panel))
            packed, Tk = householder_panel_blocked(panel)
            Ts = lax.dynamic_update_slice(
                Ts, Tk[None], (k.astype(jnp.int32), zi, zi))

            # V at full height [p*mtl, nb, nb], tile g = global tile row g
            vwin = jnp.roll(unit_lower(packed), shift, axis=0)
            vwin = jnp.where(
                (jnp.arange(W0 * nb) >= shift)[:, None]
                & jnp.roll(live, shift)[:, None], vwin, jnp.zeros_like(vwin))
            vfull = jnp.zeros((p * mtl * nb, nb), dt)
            vfull = vfull.at[(k0 + 1) * nb: Nt * nb].set(vwin)
            Vt = vfull.reshape(p * mtl, nb, nb)

            # ---- write the packed panel back (owner column only) ----
            pwin = jnp.roll(packed, shift, axis=0)
            pwin = jnp.where((jnp.arange(W0 * nb) >= shift)[:, None], pwin,
                             jnp.zeros_like(pwin))
            ptiles = pwin.reshape(W0, nb, nb)
            ptiles_all = jnp.take(ptiles, jnp.clip(gi_all - (k0 + 1), 0,
                                                   W0 - 1), axis=0)
            oldcol = lax.dynamic_index_in_dim(a_loc, kkc, axis=1,
                                              keepdims=False)
            newcol = jnp.where((gi_all >= k + 1)[:, None, None], ptiles_all,
                               oldcol)
            col_sel = jnp.where(c == ck, newcol, oldcol)
            a_loc = lax.dynamic_update_slice(
                a_loc, col_sel[:, None], (zi, kkc.astype(jnp.int32), zi, zi))

            # ---- trailing window (static sizes) ----
            sr = jnp.clip(-(-(k0 + 1 - r) // p), 0, mtl - S).astype(jnp.int32)
            sc = jnp.clip(-(-(k0 + 1 - c) // q), 0, ntl - T_).astype(jnp.int32)
            gi = r + p * (sr + jnp.arange(S))
            gj = c + q * (sc + jnp.arange(T_))
            A_win = lax.dynamic_slice(a_loc, (sr, sc, zi, zi),
                                      (S, T_, nb, nb))
            low = (gi[:, None] > gj[None, :])[:, :, None, None]
            eq = (gi[:, None] == gj[None, :])[:, :, None, None]
            Vr = Vt[gi]                          # [S,  nb, nb]
            Vc = Vt[gj]                          # [T_, nb, nb]

            # ---- Y = A V from the stored lower triangle (he2hb_hemm) ----
            zer = jnp.zeros_like(A_win)
            Aeff1 = jnp.where(low, A_win,
                              jnp.where(eq, _tril_real_diag(A_win), zer))
            Aeff2 = jnp.where(low, A_win,
                              jnp.where(eq, jnp.tril(A_win, -1), zer))
            with span("slate.he2hb/hemm"):
                y1 = jnp.einsum('stab,tbc->sac', Aeff1, Vc)
            y2 = jnp.einsum('stab,sac->tbc', jnp.conj(Aeff2), Vr)
            ybuf = jnp.zeros((p * mtl, nb, nb), dt)
            ybuf = ybuf.at[gi].add(y1)
            ybuf = ybuf.at[gj].add(y2)
            Y = lax.psum(lax.psum(ybuf, AXIS_P), AXIS_Q)
            Y = jnp.where((rows_g > k)[:, None, None], Y, jnp.zeros_like(Y))

            # ---- W = Y T - 1/2 V (T^H (V^H Y) T), replicated skinny ----
            VY = jnp.einsum('gab,gac->bc', jnp.conj(Vt), Y)
            inner = jnp.conj(Tk).T @ VY @ Tk
            Wt = (jnp.einsum('gab,bc->gac', Y, Tk)
                  - 0.5 * jnp.einsum('gab,bc->gac', Vt, inner))

            # ---- her2k trailing update, fully local ----
            Wr, Wc = Wt[gi], Wt[gj]
            with span("slate.he2hb/her2k"):
                upd = (jnp.einsum('sac,tbc->stab', Vr, jnp.conj(Wc))
                       + jnp.einsum('sac,tbc->stab', Wr, jnp.conj(Vc)))
            geq = (gi[:, None] >= gj[None, :])[:, :, None, None]
            new = jnp.where(geq, A_win - upd, A_win)
            a_loc = lax.dynamic_update_slice(a_loc, new, (sr, sc, zi, zi))
            return a_loc, Ts

        if S <= 0 or T_ <= 0 or W0 <= 0:
            continue
        a_loc, Ts = lax.fori_loop(k0, k1s, super_step, (a_loc, Ts))

    return a_loc, Ts


def dist_he2hb(data, Nt: int, grid: Grid, n: int | None = None,
               sb: int | None = None):
    """Reduce the cyclic storage of a Hermitian (lower-stored) matrix to
    band form in place: diagonal tiles hold the band diagonal blocks, tile
    (k+1, k) holds R (upper triangle; band subdiagonal block) over the
    Householder panel V (strictly below), matching the dense he2hb packing.

    Returns (data, Ts[K, nb, nb]) with K = Nt - 1 block-reflector
    triangles, replicated."""
    mtl = data.shape[0] // grid.p
    ntl = data.shape[1] // grid.q
    nb = data.shape[-1]
    n = n if n is not None else Nt * nb
    K = Nt - 1
    sb = sb if sb is not None else superblock(max(K, 1))
    spec = TILE_SPEC
    fn = shard_map_unchecked(
        lambda a: _he2hb_local(a, Nt, n, grid.p, grid.q, mtl, ntl, sb),
        mesh=grid.mesh, in_specs=(spec,), out_specs=(spec, P()))
    return fn(data)


def v_from_gathered(full, b, lim):
    """Unit-lower reflector block V from a gathered flat panel ``full``
    [N, nb]: active rows [b, lim), unit diagonal starting at row b.

    Shared by every descending panel applier (unmtr_he2hb / unmbr_ge2tb):
    roll the active rows to the top, zero the dead tail, extract the unit
    lower trapezoid, roll back, and mask to [b, lim)."""
    N = full.shape[0]
    rows_el = jnp.arange(N)
    rolled = jnp.roll(full, -b, axis=0)
    live = rows_el < (lim - b)
    rolled = jnp.where(live[:, None], rolled, jnp.zeros_like(rolled))
    v = jnp.roll(unit_lower(rolled), b, axis=0)
    return jnp.where(((rows_el >= b) & (rows_el < lim))[:, None], v,
                     jnp.zeros_like(v))


def larfb_left_local(z_loc, Vt, Tk, gi_all):
    """One distributed larfb: Z -= V Tk (V^H Z) with V replicated in tile
    form [*, nb, nb] and Z's rows sharded over AXIS_P (one psum)."""
    Vr = Vt[gi_all]
    G = lax.psum(jnp.einsum('iab,ijac->jbc', jnp.conj(Vr), z_loc), AXIS_P)
    TG = jnp.einsum('ab,jbc->jac', Tk, G)
    return z_loc - jnp.einsum('iab,jbc->ijac', Vr, TG)


def _unmtr_local(a_loc, z_loc, Ts, Nt: int, n: int, p: int, q: int,
                 mtl: int):
    """Z <- Q1 Z with Q1 the he2hb panel product (ref: src/unmtr_he2hb.cc):
    panels applied descending; V gathered per panel, the larfb update is
    one psum over the row axis + local MXU gemms on each rank's Z tiles."""
    r = lax.axis_index(AXIS_P)
    c = lax.axis_index(AXIS_Q)
    nb = a_loc.shape[-1]
    K = Nt - 1
    gi_all = r + p * jnp.arange(mtl)

    def body(i, z_loc):
        k = K - 1 - i
        gpan = _gather_panel(a_loc, k, p, q, mtl, r, c)
        v = v_from_gathered(gpan.reshape(p * mtl * nb, nb), (k + 1) * nb, n)
        Vt = v.reshape(p * mtl, nb, nb)
        Tk = lax.dynamic_index_in_dim(Ts, k, axis=0, keepdims=False)
        return larfb_left_local(z_loc, Vt, Tk, gi_all)

    if K <= 0:
        return z_loc
    return lax.fori_loop(0, K, body, z_loc)


def dist_unmtr_he2hb(a_data, Ts, z_data, Nt: int, grid: Grid,
                     n: int | None = None):
    """Apply the he2hb Q1 to a mesh-distributed Z (cyclic tile storage)."""
    mtl = a_data.shape[0] // grid.p
    nb = a_data.shape[-1]
    n = n if n is not None else Nt * nb
    spec = TILE_SPEC
    fn = shard_map_unchecked(
        lambda a, z, t: _unmtr_local(a, z, t, Nt, n, grid.p, grid.q, mtl),
        mesh=grid.mesh, in_specs=(spec, spec, P()), out_specs=spec)
    return fn(a_data, z_data, Ts)
