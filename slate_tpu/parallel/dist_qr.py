"""Distributed communication-avoiding QR (CAQR) over the 2D block-cyclic mesh.

Analog of the reference's geqrf driver (ref: src/geqrf.cc:195-206 local panel
+ ttqrt reduction tree; src/internal/internal_ttqrt.cc:1-160 triangle-triangle
factor; internal_ttmqr.cc:389 tree apply; internal_unmqr.cc trailing larfb):

reference step k                         | here (ONE shard_map program)
---------------------------------------- | ----------------------------------
internal::geqrf threaded local panel     | each mesh row factors its OWN
  (internal_geqrf.cc:450)                |   block-cyclic rows of the panel
                                         |   with one fori_loop Householder
                                         |   kernel (internal/qr.py)
ttqrt pairwise tree over panel ranks     | nb x nb R factors psum-gathered
  (ttqrt: MPI p2p of triangles)          |   (p*nb*nb bytes) and the tree QR
                                         |   recomputed REPLICATED: the tree
                                         |   is flattened into one stacked QR
                                         |   — same flops, zero extra latency
unmqr + ttmqr trailing updates           | local larfb + ONE psum along p for
                                         |   the tree stage per panel
T triangles stored per rank              | Tloc [p, Kt, nb, nb] + replicated
                                         |   tree factors Vtree/Ttree

Rows are processed in each rank's LOCAL tile order (valid tiles rolled to the
front); the R-stack uses a static permutation that places real rows first so
reflections never touch pad rows (ragged tiles) or empty ranks.  All of this
is permutation-consistent between factorization and apply, which is the only
requirement for correctness (inner products are row-order invariant).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..comm.collectives import bcast_along, ring_bcast_from_col
from ..core.grid import AXIS_P, AXIS_Q, TILE_SPEC, Grid
from ..util.trace import span
from ..util.compat_jax import pvary, shard_map_unchecked
from ..internal.qr import (build_t, geqrf_panel, householder_panel,
                           unit_lower)


def _panel_tables(k: int, Mt: int, m: int, nb: int, p: int):
    """Static per-panel tables: skip (invalid leading tiles per mesh row),
    real rows in each rank's R block, and the stack row permutation that
    puts real rows first (rotated rank order, diag owner first)."""
    skip = np.array([max(0, -(-(k - r) // p)) for r in range(p)], np.int32)
    real = np.zeros(p, np.int32)
    for r in range(p):
        rows = sum(min(nb, m - gi * nb)
                   for gi in range(k, Mt) if gi % p == r)
        real[r] = min(nb, rows)
    order = [(k + t) % p for t in range(p)]
    pos = np.zeros((p, nb), np.int32)
    nxt = 0
    for r in order:
        pos[r, : real[r]] = np.arange(nxt, nxt + real[r])
        nxt += int(real[r])
    for r in order:
        pad = nb - real[r]
        pos[r, real[r]:] = np.arange(nxt, nxt + pad)
        nxt += pad
    return skip, real, pos


def _rows_view(a_loc, shift):
    """Local tiles -> element-row-major [mtl*nb, ntl*nb], valid tiles rolled
    to the front by ``shift`` (traced)."""
    mtl, ntl, nb, _ = a_loc.shape
    rolled = jnp.roll(a_loc, -shift, axis=0)
    return rolled.transpose(0, 2, 1, 3).reshape(mtl * nb, ntl * nb)


def _rows_unview(flat, shift, mtl, ntl, nb):
    a = flat.reshape(mtl, nb, ntl, nb).transpose(0, 2, 1, 3)
    return jnp.roll(a, shift, axis=0)


def _tree_apply(Y, Vs_mine, Ts, conj_trans: bool):
    """Apply the replicated tree reflector to the distributed R-slot rows:
    Y [nb, W] is this rank's slot; one psum along p forms V_s^H Y."""
    Z = lax.psum(jnp.conj(Vs_mine).T @ Y, AXIS_P)
    Tm = jnp.conj(Ts).T if conj_trans else Ts
    return Y - Vs_mine @ (Tm @ Z)


def _local_apply(C, Vr, Tr, conj_trans: bool):
    W1 = jnp.conj(Vr).T @ C
    Tm = jnp.conj(Tr).T if conj_trans else Tr
    return C - Vr @ (Tm @ W1)


def _panel_apply(C, Vr, Tr, Vs_mine, Ts, conj_trans: bool):
    """Apply this panel's implicit Q (or Q^H) to local rows C [mtl*nb, W].

    Q_panel = diag(Q_local) o Q_tree: Q^H C applies local then tree,
    Q C applies tree then local (ref: unmqr + ttmqr ordering,
    src/geqrf.cc:203-276 mirrored in src/unmqr.cc)."""
    nb = Vr.shape[1]
    if conj_trans:
        C = _local_apply(C, Vr, Tr, True)
        Y = _tree_apply(C[:nb], Vs_mine, Ts, True)
        return C.at[:nb].set(Y)
    Y = _tree_apply(C[:nb], Vs_mine, Ts, False)
    C = C.at[:nb].set(Y)
    return _local_apply(C, Vr, Tr, False)


def _all_panel_tables(Kt: int, Mt: int, m: int, nb: int, p: int):
    """Stack _panel_tables over every k: [Kt, p] skips, [Kt, p, nb] stack
    positions — indexed with the traced k inside the fori_loop bodies."""
    skips = np.zeros((Kt, p), np.int32)
    poss = np.zeros((Kt, p, nb), np.int32)
    for k in range(Kt):
        skips[k], _, poss[k] = _panel_tables(k, Mt, m, nb, p)
    return jnp.asarray(skips), jnp.asarray(poss)


def _geqrf_local(a_loc, Kt, Mt, m, n, p, q, mtl, ntl, la: int = 0):
    """ONE lax.fori_loop over the Kt panels (per-step shapes are
    k-independent, so no superblocking is needed — the compiled program is
    O(1) in Kt).

    ``la`` (0/1/2, static) is the lookahead pipeline depth: at la >= 1 the
    carry holds the NEXT panel's local QR + ring-broadcast result, issued
    after the early update of columns k+1..k+la and before the late
    trailing update (columns > k+la), so the q-axis panel share rides
    under the big larfb.  Column independence of the reflector apply (per
    output element, the reduction runs over rows only) makes the
    early/late split bit-identical to the single masked apply at la=0."""
    r = lax.axis_index(AXIS_P)
    c = lax.axis_index(AXIS_Q)
    nb = a_loc.shape[-1]
    dt = a_loc.dtype
    tile_idx = jnp.arange(mtl)
    skips, poss = _all_panel_tables(Kt, Mt, m, nb, p)
    gi_all = r + p * tile_idx
    gj_all = c + q * jnp.arange(ntl)

    # Initial carries must carry the same device-variance the loop body
    # produces: Tr varies over mesh rows (p) but is bcast along q; the tree
    # factors are psum-replicated everywhere (out_specs P() relies on it).
    Tloc0 = pvary(jnp.zeros((Kt, nb, nb), dt), (AXIS_P,))
    Vtree0 = jnp.zeros((Kt, p * nb, nb), dt)
    Ttree0 = jnp.zeros((Kt, nb, nb), dt)

    def _share_psum(x, ck):
        return bcast_along(x, ck, AXIS_Q)

    def _share_ring(x, ck):
        return ring_bcast_from_col(x, ck, q)

    def panel_qr(a_loc, k, share):
        """Local panel QR of tile-column k + owner-column share along q.
        ``share`` is _share_psum (depth 0) or _share_ring (lookahead
        issue) — both deliver the owner's exact bytes."""
        kkc = k // q
        ck = k % q
        skip = skips[k, r]
        pan = lax.dynamic_index_in_dim(a_loc, kkc, axis=1, keepdims=False)
        pan = jnp.where((gi_all >= k)[:, None, None], pan,
                        jnp.zeros_like(pan))
        pan = jnp.roll(pan, -skip, axis=0)
        slab = pan.reshape(mtl * nb, nb)
        packed, Tr = geqrf_panel(slab)   # tuned: Pallas panel or XLA
        # only the owner column's panel is real; share it across the row
        packed = jnp.where(c == ck, packed, jnp.zeros_like(packed))
        Tr = jnp.where(c == ck, Tr, jnp.zeros_like(Tr))
        packed = share(packed, ck)
        Tr = share(Tr, ck)
        return packed, Tr

    def consume(k, a_loc, Tloc, Vtree, Ttree, packed, Tr):
        """Tree factor + V writeback for step k from the shared panel;
        returns the pieces the trailing updates need."""
        rk = k % p
        ck = k % q
        kkc = k // q
        skip = skips[k, r]
        posr = poss[k, r]
        Vr = unit_lower(packed)
        Tloc = Tloc.at[k].set(Tr)

        # ---- R-stack tree: gather nb x nb R factors, factor replicated ----
        with span("slate.geqrf/tree"):
            Rr = jnp.triu(packed[:nb])
            buf = jnp.zeros((p * nb, nb), dt).at[posr].set(Rr)
            stack = lax.psum(buf, AXIS_P)
            packed_s, taus_s = householder_panel(stack)
            Ts = build_t(packed_s, taus_s)
            Vs = unit_lower(packed_s)
            Vs_mine = Vs[posr]                       # my slot rows [nb, nb]
            Rfin = jnp.triu(packed_s[:nb])
            Vtree = Vtree.at[k].set(Vs)
            Ttree = Ttree.at[k].set(Ts)

        # ---- write back V (head tile: strict lower; diag tile adds R) ----
        with span("slate.geqrf/writeback"):
            pan0 = lax.dynamic_index_in_dim(a_loc, kkc, axis=1,
                                            keepdims=False)
            head = jnp.tril(packed[:nb], -1)
            head = jnp.where(r == rk, head + Rfin, head)
            vstore = packed.at[:nb].set(head)
            vtiles = _rows_unview(vstore, skip, mtl, 1, nb)[:, 0]
            newcol = jnp.where((gi_all >= k)[:, None, None], vtiles, pan0)
            col_sel = jnp.where(c == ck, newcol, pan0)
            zi = jnp.zeros((), jnp.int32)
            a_loc = lax.dynamic_update_slice(
                a_loc, col_sel[:, None], (zi, kkc.astype(jnp.int32), zi, zi))
        return a_loc, Tloc, Vtree, Ttree, Vr, Vs_mine, Ts

    def apply_cols(k, a_loc, colsel, Vr, Tr, Vs_mine, Ts):
        """Q^H on the local rows of the columns selected by ``colsel``
        (boolean over gj_all).  Zeroed non-selected columns pass through
        the reflectors as exact zeros, so any column split applies each
        selected column's transform once, bit-identically."""
        skip = skips[k, r]
        with span("slate.geqrf/update"):
            Cl = _rows_view(a_loc, skip)             # [mtl*nb, ntl*nb]
            colmask = jnp.repeat(colsel, nb)[None, :]
            Cm = jnp.where(colmask, Cl, jnp.zeros_like(Cl))
            Cm = _panel_apply(Cm, Vr, Tr, Vs_mine, Ts, conj_trans=True)
            Cl = jnp.where(colmask, Cm, Cl)
            newt = _rows_unview(Cl, skip, mtl, ntl, nb)
            rowmask = (gi_all >= k)[:, None, None, None]
            cmask = colsel[None, :, None, None]
            return jnp.where(rowmask & cmask, newt, a_loc)

    if la == 0:
        def step(k, carry):
            a_loc, Tloc, Vtree, Ttree = carry
            # ---- local panel QR on my rolled rows of tile-column k ----
            with span("slate.geqrf/panel"):
                packed, Tr = panel_qr(a_loc, k, _share_psum)
            a_loc, Tloc, Vtree, Ttree, Vr, Vs_mine, Ts = consume(
                k, a_loc, Tloc, Vtree, Ttree, packed, Tr)
            # ---- trailing update: Q^H on columns gj > k ----
            a_loc = apply_cols(k, a_loc, gj_all > k, Vr, Tr, Vs_mine, Ts)
            return a_loc, Tloc, Vtree, Ttree

        return lax.fori_loop(0, Kt, step, (a_loc, Tloc0, Vtree0, Ttree0))

    def step(k, carry):
        a_loc, Tloc, Vtree, Ttree, packed, Tr = carry
        a_loc, Tloc, Vtree, Ttree, Vr, Vs_mine, Ts = consume(
            k, a_loc, Tloc, Vtree, Ttree, packed, Tr)
        # ---- lookahead: finish columns k+1..k+la, issue step k+1's
        #      panel (ring), THEN the late trailing update rides over
        #      the in-flight hops.  The final step re-issues the clamped
        #      last column; the garbage panel dies with the carry ----
        a_loc = apply_cols(k, a_loc, (gj_all > k) & (gj_all <= k + la),
                           Vr, Tr, Vs_mine, Ts)
        with span("slate.geqrf/bcast_ahead"):
            nxt = panel_qr(a_loc, jnp.minimum(k + 1, Kt - 1),
                           _share_ring)
        a_loc = apply_cols(k, a_loc, gj_all > k + la, Vr, Tr, Vs_mine, Ts)
        return (a_loc, Tloc, Vtree, Ttree) + nxt

    with span("slate.geqrf/bcast_ahead"):
        packed0, Tr0 = panel_qr(a_loc, 0, _share_ring)
    a_loc, Tloc, Vtree, Ttree, _, _ = lax.fori_loop(
        0, Kt, step, (a_loc, Tloc0, Vtree0, Ttree0, packed0, Tr0))
    return a_loc, Tloc, Vtree, Ttree


def dist_geqrf_data(data, Kt, Mt, m, n, grid: Grid, la: int | None = None):
    """``la`` is the lookahead pipeline depth; None resolves the tuned
    depth through the ``dist_lookahead`` plan (SEAM011)."""
    if la is None:
        from ..tune import lookahead_depth
        la = lookahead_depth(n, data.dtype.name)
    mtl = data.shape[0] // grid.p
    ntl = data.shape[1] // grid.q
    spec = TILE_SPEC
    fn = shard_map_unchecked(
        lambda a: _geqrf_local(a, Kt, Mt, m, n, grid.p, grid.q, mtl, ntl,
                               la=la),
        mesh=grid.mesh, in_specs=(spec,),
        out_specs=(spec, P(AXIS_P, None, None), P(), P()))
    data, Tloc, Vtree, Ttree = fn(data)
    Tloc = Tloc.reshape(grid.p, Kt, *Tloc.shape[1:])
    return data, Tloc, Vtree, Ttree


def _unmqr_local(a_loc, c_loc, Tloc, Vtree, Ttree, Kt, Mt, m, p, q,
                 mtl, ntl_c, conj_trans: bool):
    """Apply Q (or Q^H) from the left to local rows of C."""
    r = lax.axis_index(AXIS_P)
    c = lax.axis_index(AXIS_Q)
    nb = a_loc.shape[-1]
    tile_idx = jnp.arange(mtl)
    Tl = Tloc[0]                                  # [Kt, nb, nb] my mesh row
    skips, poss = _all_panel_tables(Kt, Mt, m, nb, p)
    gi_all = r + p * tile_idx

    def step(t, c_loc):
        k = t if conj_trans else Kt - 1 - t
        rk, ck = k % p, k % q
        kkc = k // q
        skip = skips[k, r]
        posr = poss[k, r]

        # rebuild my local V for panel k from stored tiles
        pan = lax.dynamic_index_in_dim(a_loc, kkc, axis=1, keepdims=False)
        pan = jnp.where((gi_all >= k)[:, None, None], pan,
                        jnp.zeros_like(pan))
        pan = jnp.roll(pan, -skip, axis=0)
        slab = pan.reshape(mtl * nb, nb)
        slab = bcast_along(jnp.where(c == ck, slab, jnp.zeros_like(slab)),
                           ck, AXIS_Q)
        # head tile: strict lower + implied unit diag; tail beyond valid
        # tiles is exact zero already (masked above)
        rows = jnp.arange(mtl * nb)[:, None]
        cols = jnp.arange(nb)[None, :]
        head_zone = rows < nb
        Vr = jnp.where(head_zone & (rows <= cols), jnp.zeros_like(slab),
                       slab)
        Vr = jnp.where(head_zone & (rows == cols), jnp.ones_like(slab), Vr)
        Tr = Tl[k]
        Vs_mine = Vtree[k][posr]
        Ts = Ttree[k]

        Cl = _rows_view(c_loc, skip)
        Cn = _panel_apply(Cl, Vr, Tr, Vs_mine, Ts, conj_trans)
        newt = _rows_unview(Cn, skip, mtl, ntl_c, nb)
        rowmask = (gi_all >= k)[:, None, None, None]
        return jnp.where(rowmask, newt, c_loc)

    return lax.fori_loop(0, Kt, step, c_loc)


def dist_unmqr_data(a_data, c_data, Tloc, Vtree, Ttree, Kt, Mt, m,
                    grid: Grid, conj_trans: bool):
    mtl = a_data.shape[0] // grid.p
    ntl_c = c_data.shape[1] // grid.q
    spec = TILE_SPEC
    fn = shard_map_unchecked(
        lambda a, cd, tl, vt, tt: _unmqr_local(
            a, cd, tl, vt, tt, Kt, Mt, m, grid.p, grid.q, mtl, ntl_c,
            conj_trans),
        mesh=grid.mesh,
        in_specs=(spec, spec, P(AXIS_P, None, None, None), P(), P()),
        out_specs=spec)
    return fn(a_data, c_data, Tloc, Vtree, Ttree)
