"""Distributed triangular solve over the 2D block-cyclic mesh.

Analog of the reference's trsm driver bodies (ref: src/trsmB.cc ->
src/work/work_trsm.cc:395 task loops with lookahead, panel bcasts via
listBcastMT, and internal::trsm single-block-row solves).

Left-side solve op(A) X = alpha B with A triangular, B distributed
[Mt_b x Nt_b] on the same grid.  The four (uplo, op) combinations reduce to
forward substitution on an effective-lower factor or backward substitution
on an effective-upper factor; the panel of effective column k is A's column
k (op == NoTrans) or A's row k op-applied (op == Trans/ConjTrans), mirroring
how work::trsm walks the transposed matrix (work_trsm.cc).

Right-side solves are mapped to left solves by the driver via
X op(A) = B  <=>  op(A)^T X^T = B^T (ref: trsm.cc does the same with views).

Structure per step k (inside ONE unrolled shard_map program):
  1. gather diag tile A(k,k), build effective triangle, replicate
  2. ranks owning B(k, :) solve their RHS tiles (vmapped triangular_solve)
  3. broadcast X(k, :) along the p axis; broadcast the effective panel
     column of A via scatter + psum (the listBcast analog)
  4. every rank updates its not-yet-solved local B rows:
     B(i, :) -= Aeff(i, k) @ X(k, :)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.grid import AXIS_P, AXIS_Q, Grid
from ..internal.trsm import apply_op_tile
from ..types import Op, Uplo


def _trsm_local(a_loc, b_loc, alpha, *, Nt, n, p, q, lower, op_a, unit_diag,
                mtl_a, ntl_a, mtl_b, ntl_b):
    r = lax.axis_index(AXIS_P)
    c = lax.axis_index(AXIS_Q)
    nb = a_loc.shape[-1]
    nbr = b_loc.shape[-1]
    dt = b_loc.dtype

    b_loc = alpha * b_loc

    eff_lower = lower if op_a is Op.NoTrans else not lower
    order = range(Nt) if eff_lower else range(Nt - 1, -1, -1)

    for k in order:
        rk, ck = k % p, k % q
        kkr, kkc = k // p, k // q

        # -- effective diagonal tile (pad diagonal identity-augmented so the
        # ragged last tile stays nonsingular; B's pad rows are zero so the
        # pad solution is exactly zero) --
        vk = nb if k < Nt - 1 else n - (Nt - 1) * nb
        idx = jnp.arange(nb)
        pad_eye = jnp.diag((idx >= vk).astype(a_loc.dtype))
        dtile = jnp.where((r == rk) & (c == ck), a_loc[kkr, kkc],
                          jnp.zeros((nb, nb), a_loc.dtype))
        dtile = lax.psum(lax.psum(dtile, AXIS_P), AXIS_Q)
        deff = apply_op_tile(dtile, op_a) + pad_eye

        # -- solve block row k of B on its owner row, bcast along p --
        brow = b_loc[kkr]                           # [ntl_b, nb, nbr]
        xk = jax.vmap(lambda bb: lax.linalg.triangular_solve(
            deff, bb, left_side=True, lower=eff_lower,
            unit_diagonal=unit_diag))(brow)
        xk = jnp.where(r == rk, xk, jnp.zeros_like(xk))
        xk = lax.psum(xk, AXIS_P)                   # replicated down columns
        b_loc = jnp.where(r == rk, b_loc.at[kkr].set(xk), b_loc)

        # remaining rows to update: i > k (fwd) or i < k (bwd)
        rem = (Nt - 1 - k) if eff_lower else k
        if rem == 0:
            continue

        # -- effective panel column k of A, as a global buffer --
        # op == NoTrans: tiles A(i, k) live in mesh column ck at local col kkc
        # op != NoTrans: tiles op(A(k, i)) live in mesh row rk at local row kkr
        if op_a is Op.NoTrans:
            pan = a_loc[:, kkc]                     # [mtl_a, nb, nb]
            gi_all = r + p * jnp.arange(mtl_a)
            buf = jnp.zeros((p * mtl_a, nb, nb), a_loc.dtype)
            buf = buf.at[gi_all].set(pan)
            buf = jnp.where(c == ck, buf, jnp.zeros_like(buf))
        else:
            pan = apply_op_tile(a_loc[kkr], op_a)   # [ntl_a, nb, nb]
            gj_all = c + q * jnp.arange(ntl_a)
            buf = jnp.zeros((q * ntl_a, nb, nb), a_loc.dtype)
            buf = buf.at[gj_all].set(pan)
            buf = jnp.where(r == rk, buf, jnp.zeros_like(buf))
        gpan = lax.psum(lax.psum(buf, AXIS_P), AXIS_Q)

        # -- update this rank's remaining local rows --
        S = mtl_b - max(0, (k + 1) // p) if eff_lower \
            else -(-k // p)                        # max local rows with i<k
        if S <= 0:
            continue
        if eff_lower:
            sr = jnp.clip((k + 1 - r + p - 1) // p, 0, mtl_b - S)
        else:
            sr = jnp.zeros((), r.dtype)
        gi = r + p * (sr + jnp.arange(S))
        arow = gpan[gi]                             # [S, nb, nb] Aeff(i, k)
        z = jnp.zeros((), r.dtype)
        cur = lax.dynamic_slice(b_loc, (sr.astype(r.dtype), z, z, z),
                                (S, ntl_b, nb, nbr))
        upd = jnp.einsum("iab,jbc->ijac", arow, xk,
                         preferred_element_type=dt)
        if eff_lower:
            mask = (gi > k)[:, None, None, None]
        else:
            mask = (gi < k)[:, None, None, None]
        new = jnp.where(mask, cur - upd, cur)
        b_loc = lax.dynamic_update_slice(b_loc, new,
                                         (sr.astype(r.dtype), z, z, z))

    return b_loc


def dist_trsm_left(a_data, b_data, alpha, *, Nt, grid: Grid, lower: bool,
                   op_a: Op, unit_diag: bool, n: int | None = None):
    """Solve op(A) X = alpha B; returns X in B's cyclic storage layout."""
    mtl_a = a_data.shape[0] // grid.p
    ntl_a = a_data.shape[1] // grid.q
    mtl_b = b_data.shape[0] // grid.p
    ntl_b = b_data.shape[1] // grid.q
    n = n if n is not None else Nt * a_data.shape[-1]
    spec = P(AXIS_P, AXIS_Q, None, None)
    fn = jax.shard_map(
        lambda a, b: _trsm_local(
            a, b, alpha, Nt=Nt, n=n, p=grid.p, q=grid.q, lower=lower,
            op_a=op_a,
            unit_diag=unit_diag, mtl_a=mtl_a, ntl_a=ntl_a, mtl_b=mtl_b,
            ntl_b=ntl_b),
        mesh=grid.mesh, in_specs=(spec, spec), out_specs=spec)
    return fn(a_data, b_data)
