"""Distributed triangular solve over the 2D block-cyclic mesh.

Analog of the reference's trsm driver bodies (ref: src/trsmB.cc ->
src/work/work_trsm.cc:395 task loops with lookahead, panel bcasts via
listBcastMT, and internal::trsm single-block-row solves).

Left-side solve op(A) X = alpha B with A triangular, B distributed
[Mt_b x Nt_b] on the same grid.  The four (uplo, op) combinations reduce to
forward substitution on an effective-lower factor or backward substitution
on an effective-upper factor; the panel of effective column k is A's column
k (op == NoTrans) or A's row k op-applied (op == Trans/ConjTrans), mirroring
how work::trsm walks the transposed matrix (work_trsm.cc).

Right-side solves are mapped to left solves by the driver via
X op(A) = B  <=>  op(A)^T X^T = B^T (ref: trsm.cc does the same with views).

Structure per step k (inside ONE shard_map program, superblocked like
dist_chol — ~SUPERBLOCKS unrolled bodies, lax.fori_loop inside each):
  1. gather diag tile A(k,k), build effective triangle, replicate
  2. ranks owning B(k, :) solve their RHS tiles (vmapped triangular_solve)
  3. broadcast X(k, :) along the p axis; broadcast the effective panel
     column of A via scatter + psum (the listBcast analog)
  4. every rank updates its not-yet-solved local B rows:
     B(i, :) -= Aeff(i, k) @ X(k, :)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.grid import AXIS_P, AXIS_Q, TILE_SPEC, Grid
from ..internal.trsm import apply_op_tile
from ..robust import faults
from ..types import Op, Uplo
from ..util.trace import span
from .dist_chol import superblock


def _trsm_local(a_loc, b_loc, alpha, *, Nt, n, p, q, lower, op_a, unit_diag,
                mtl_a, ntl_a, mtl_b, ntl_b, sb):
    r = lax.axis_index(AXIS_P)
    c = lax.axis_index(AXIS_Q)
    nb = a_loc.shape[-1]
    nbr = b_loc.shape[-1]
    dt = b_loc.dtype
    idx = jnp.arange(nb)
    zi = jnp.zeros((), jnp.int32)

    b_loc = alpha * b_loc

    eff_lower = lower if op_a is Op.NoTrans else not lower

    def step(k, b_loc):
        """Solve block row k and broadcast X(k,:) + A's effective panel."""
        with span("slate.trsm/bcast"):
            rk, ck = k % p, k % q
            kkr, kkc = k // p, k // q

            # -- effective diagonal tile (pad diagonal identity-augmented
            # so the ragged last tile stays nonsingular; B's pad rows are
            # zero so the pad solution is exactly zero) --
            vk = jnp.where(k < Nt - 1, nb, n - (Nt - 1) * nb)
            pad_eye = jnp.diag((idx >= vk).astype(a_loc.dtype))
            dtile = lax.dynamic_index_in_dim(
                lax.dynamic_index_in_dim(a_loc, kkr, axis=0, keepdims=False),
                kkc, axis=0, keepdims=False)
            dtile = jnp.where((r == rk) & (c == ck), dtile,
                              jnp.zeros((nb, nb), a_loc.dtype))
            dtile = lax.psum(lax.psum(dtile, AXIS_P), AXIS_Q)
            deff = apply_op_tile(dtile, op_a) + pad_eye

            # -- solve block row k of B on its owner row, bcast along p --
            brow = lax.dynamic_index_in_dim(b_loc, kkr, axis=0,
                                            keepdims=False)
            xk = jax.vmap(lambda bb: lax.linalg.triangular_solve(
                deff, bb, left_side=True, lower=eff_lower,
                unit_diagonal=unit_diag))(brow)
            xk = jnp.where(r == rk, xk, jnp.zeros_like(xk))
            xk = lax.psum(xk, AXIS_P)               # replicated down columns
            xk = faults.maybe_corrupt("post_collective", xk)
            row_sel = jnp.where(r == rk, xk, brow)
            b_loc = lax.dynamic_update_slice(
                b_loc, row_sel[None], (kkr.astype(jnp.int32), zi, zi, zi))

            # -- effective panel column k of A, as a global buffer --
            # op == NoTrans: tiles A(i, k) live in mesh col ck, local col kkc
            # op != NoTrans: tiles op(A(k, i)) in mesh row rk, local row kkr
            if op_a is Op.NoTrans:
                pan = lax.dynamic_index_in_dim(a_loc, kkc, axis=1,
                                               keepdims=False)
                gi_a = r + p * jnp.arange(mtl_a)
                buf = jnp.zeros((p * mtl_a, nb, nb), a_loc.dtype)
                buf = buf.at[gi_a].set(pan)
                buf = jnp.where(c == ck, buf, jnp.zeros_like(buf))
            else:
                arow = lax.dynamic_index_in_dim(a_loc, kkr, axis=0,
                                                keepdims=False)
                pan = apply_op_tile(arow, op_a)     # [ntl_a, nb, nb]
                gj_a = c + q * jnp.arange(ntl_a)
                buf = jnp.zeros((q * ntl_a, nb, nb), a_loc.dtype)
                buf = buf.at[gj_a].set(pan)
                buf = jnp.where(r == rk, buf, jnp.zeros_like(buf))
            gpan = lax.psum(lax.psum(buf, AXIS_P), AXIS_Q)
            return b_loc, xk, gpan

    def update(b_loc, k, xk, gpan, S, sr):
        """B(i,:) -= Aeff(i,k) @ X(k,:) on the not-yet-solved window."""
        with span("slate.trsm/update"):
            gi = r + p * (sr + jnp.arange(S))
            arow = gpan[gi]                         # [S, nb, nb]
            cur = lax.dynamic_slice(b_loc, (sr, zi, zi, zi),
                                    (S, ntl_b, nb, nbr))
            upd = jnp.einsum("iab,jbc->ijac", arow, xk,
                             preferred_element_type=dt)
            if eff_lower:
                mask = (gi > k)[:, None, None, None]
            else:
                mask = (gi < k)[:, None, None, None]
            new = jnp.where(mask, cur - upd, cur)
            return lax.dynamic_update_slice(b_loc, new, (sr, zi, zi, zi))

    if eff_lower:
        for k0 in range(0, Nt, sb):
            k1 = min(k0 + sb, Nt)
            S = mtl_b - ((k0 + 1) // p)             # rows that can be > k0
            S = max(S, 1)                           # degenerate, masked out

            def fwd(k, b_loc, S=S):
                b_loc, xk, gpan = step(k, b_loc)

                def upd_fn(b):
                    sr = jnp.clip(-(-(k0 + 1 - r) // p), 0,
                                  mtl_b - S).astype(jnp.int32)
                    return update(b, k, xk, gpan, S, sr)

                return lax.cond(k < Nt - 1, upd_fn, lambda b: b, b_loc)

            b_loc = lax.fori_loop(k0, k1, fwd, b_loc)
    else:
        for k0 in reversed(range(0, Nt, sb)):
            k1 = min(k0 + sb, Nt)
            S = max(-(-k1 // p), 1)                 # rows that can be < k1

            def bwd(t, b_loc, S=S, k1=k1):
                k = k1 - 1 - t

                b_loc, xk, gpan = step(k, b_loc)

                def upd_fn(b):
                    sr = jnp.zeros((), jnp.int32)
                    return update(b, k, xk, gpan, S, sr)

                return lax.cond(k > 0, upd_fn, lambda b: b, b_loc)

            b_loc = lax.fori_loop(0, k1 - k0, bwd, b_loc)

    return b_loc


def _trsm_right_local(a_loc, b_loc, alpha, *, Nt, n, p, q, lower, op_a,
                      unit_diag, mtl_a, ntl_a, mtl_b, ntl_b, sb):
    """Right-side solve X op(A) = alpha B by column-block substitution —
    the mirror of _trsm_local with the q axis in the starring role (so no
    dense transpose round-trip is ever needed, ref: trsm.cc handles Right
    with views the same way)."""
    r = lax.axis_index(AXIS_P)
    c = lax.axis_index(AXIS_Q)
    nb = a_loc.shape[-1]
    mbr = b_loc.shape[-2]
    dt = b_loc.dtype
    idx = jnp.arange(nb)
    zi = jnp.zeros((), jnp.int32)

    b_loc = alpha * b_loc

    eff_lower = lower if op_a is Op.NoTrans else not lower
    # X Aeff = B: lower Aeff couples column k to LATER columns -> walk
    # k downward; upper walks upward

    def step(k, b_loc):
        with span("slate.trsm/bcast"):
            rk, ck = k % p, k % q
            kkr, kkc = k // p, k // q

            vk = jnp.where(k < Nt - 1, nb, n - (Nt - 1) * nb)
            pad_eye = jnp.diag((idx >= vk).astype(a_loc.dtype))
            dtile = lax.dynamic_index_in_dim(
                lax.dynamic_index_in_dim(a_loc, kkr, axis=0, keepdims=False),
                kkc, axis=0, keepdims=False)
            dtile = jnp.where((r == rk) & (c == ck), dtile,
                              jnp.zeros((nb, nb), a_loc.dtype))
            dtile = lax.psum(lax.psum(dtile, AXIS_P), AXIS_Q)
            deff = apply_op_tile(dtile, op_a) + pad_eye

            # -- solve block column k of B on its owner column, bcast
            # along q --
            bcol = lax.dynamic_index_in_dim(b_loc, kkc, axis=1,
                                            keepdims=False)
            xk = jax.vmap(lambda bb: lax.linalg.triangular_solve(
                deff, bb, left_side=False, lower=eff_lower,
                unit_diagonal=unit_diag))(bcol)
            xk = jnp.where(c == ck, xk, jnp.zeros_like(xk))
            xk = lax.psum(xk, AXIS_Q)               # replicated across rows
            xk = faults.maybe_corrupt("post_collective", xk)
            col_sel = jnp.where(c == ck, xk, bcol)
            b_loc = lax.dynamic_update_slice(
                b_loc, col_sel[:, None], (zi, kkc.astype(jnp.int32), zi, zi))

            # -- effective row k of A as a global buffer over tile columns --
            # op == NoTrans: tiles A(k, j) live in mesh row rk, local row kkr
            # op != NoTrans: tiles op(A(j, k)) in mesh col ck, local col kkc
            if op_a is Op.NoTrans:
                pan = lax.dynamic_index_in_dim(a_loc, kkr, axis=0,
                                               keepdims=False)
                gj_a = c + q * jnp.arange(ntl_a)
                buf = jnp.zeros((q * ntl_a, nb, nb), a_loc.dtype)
                buf = buf.at[gj_a].set(pan)
                buf = jnp.where(r == rk, buf, jnp.zeros_like(buf))
            else:
                acol = lax.dynamic_index_in_dim(a_loc, kkc, axis=1,
                                                keepdims=False)
                pan = apply_op_tile(acol, op_a)     # [mtl_a, nb, nb]
                gi_a = r + p * jnp.arange(mtl_a)
                buf = jnp.zeros((p * mtl_a, nb, nb), a_loc.dtype)
                buf = buf.at[gi_a].set(pan)
                buf = jnp.where(c == ck, buf, jnp.zeros_like(buf))
            gpan = lax.psum(lax.psum(buf, AXIS_P), AXIS_Q)
            return b_loc, xk, gpan

    def update(b_loc, k, xk, gpan, T, sc):
        with span("slate.trsm/update"):
            gj = c + q * (sc + jnp.arange(T))
            acol = gpan[gj]                         # [T, nb, nb] Aeff(k, j)
            cur = lax.dynamic_slice(b_loc, (zi, sc, zi, zi),
                                    (mtl_b, T, mbr, nb))
            upd = jnp.einsum("iab,jbc->ijac", xk, acol,
                             preferred_element_type=dt)
            if eff_lower:
                mask = (gj < k)[None, :, None, None]
            else:
                mask = (gj > k)[None, :, None, None]
            new = jnp.where(mask, cur - upd, cur)
            return lax.dynamic_update_slice(b_loc, new, (zi, sc, zi, zi))

    if eff_lower:
        # columns solved from high k downward; updates hit columns < k
        for k0 in reversed(range(0, Nt, sb)):
            k1 = min(k0 + sb, Nt)
            T = max(-(-k1 // q), 1)

            def bwd(t, b_loc, T=T, k1=k1):
                k = k1 - 1 - t
                b_loc, xk, gpan = step(k, b_loc)

                def upd_fn(b):
                    return update(b, k, xk, gpan, T, jnp.zeros((), jnp.int32))

                return lax.cond(k > 0, upd_fn, lambda b: b, b_loc)

            b_loc = lax.fori_loop(0, k1 - k0, bwd, b_loc)
    else:
        for k0 in range(0, Nt, sb):
            k1 = min(k0 + sb, Nt)
            T = max(ntl_b - ((k0 + 1) // q), 1)

            def fwd(k, b_loc, T=T):
                b_loc, xk, gpan = step(k, b_loc)

                def upd_fn(b):
                    sc = jnp.clip(-(-(k0 + 1 - c) // q), 0,
                                  ntl_b - T).astype(jnp.int32)
                    return update(b, k, xk, gpan, T, sc)

                return lax.cond(k < Nt - 1, upd_fn, lambda b: b, b_loc)

            b_loc = lax.fori_loop(k0, k1, fwd, b_loc)

    return b_loc


def dist_trsm_right(a_data, b_data, alpha, *, Nt, grid: Grid, lower: bool,
                    op_a: Op, unit_diag: bool, n: int | None = None,
                    sb: int | None = None):
    """Solve X op(A) = alpha B; returns X in B's cyclic storage layout."""
    mtl_a = a_data.shape[0] // grid.p
    ntl_a = a_data.shape[1] // grid.q
    mtl_b = b_data.shape[0] // grid.p
    ntl_b = b_data.shape[1] // grid.q
    n = n if n is not None else Nt * a_data.shape[-1]
    sb = sb if sb is not None else superblock(Nt)
    spec = TILE_SPEC
    fn = jax.shard_map(
        lambda a, b: _trsm_right_local(
            a, b, alpha, Nt=Nt, n=n, p=grid.p, q=grid.q, lower=lower,
            op_a=op_a, unit_diag=unit_diag, mtl_a=mtl_a, ntl_a=ntl_a,
            mtl_b=mtl_b, ntl_b=ntl_b, sb=sb),
        mesh=grid.mesh, in_specs=(spec, spec), out_specs=spec)
    return fn(a_data, b_data)


def dist_trsm_left(a_data, b_data, alpha, *, Nt, grid: Grid, lower: bool,
                   op_a: Op, unit_diag: bool, n: int | None = None,
                   sb: int | None = None):
    """Solve op(A) X = alpha B; returns X in B's cyclic storage layout."""
    mtl_a = a_data.shape[0] // grid.p
    ntl_a = a_data.shape[1] // grid.q
    mtl_b = b_data.shape[0] // grid.p
    ntl_b = b_data.shape[1] // grid.q
    n = n if n is not None else Nt * a_data.shape[-1]
    sb = sb if sb is not None else superblock(Nt)
    spec = TILE_SPEC
    fn = jax.shard_map(
        lambda a, b: _trsm_local(
            a, b, alpha, Nt=Nt, n=n, p=grid.p, q=grid.q, lower=lower,
            op_a=op_a,
            unit_diag=unit_diag, mtl_a=mtl_a, ntl_a=ntl_a, mtl_b=mtl_b,
            ntl_b=ntl_b, sb=sb),
        mesh=grid.mesh, in_specs=(spec, spec), out_specs=spec)
    return fn(a_data, b_data)
