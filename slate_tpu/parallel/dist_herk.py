"""Triangle-aware distributed rank-k / rank-2k updates and trmm.

Analog of the reference's internal_herk.cc:1-843 / internal_her2k.cc /
internal_syrk.cc / internal_trmm.cc: the reference enumerates only the
STORED triangle's tiles (diagonal tiles get herk, off-diagonal gemm), so a
rank-k update costs half a gemm's flops and communication.

TPU-first shape: static shapes everywhere, so "skip the other triangle"
becomes a *packed pair list*.  For each rank the set of its local tiles
that fall in the stored triangle is computed as a (statically-sized,
dynamically-indexed) list of (row, col) tile pairs — the pair count varies
by ±1 across ranks, so every rank pads to the mesh-wide max S and masks.
The update is then ONE batched einsum over S tile pairs per k step —
half the flops of the full [mtl x ntl] outer product, still MXU-batched.

Communication per step k matches dist_chol's herk trailing pattern: the
panel tile-column is broadcast along q (row owners) and all-gathered along
p (column owners) — the reference's symmetric listBcast of the panel to
both row and column communicators (src/potrf.cc:232-242).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..comm.collectives import bcast_from_col
from ..core.grid import AXIS_P, AXIS_Q, TILE_SPEC, Grid
from ..util.compat_jax import pvary, shard_map_unchecked
from ..util.trace import span


def _pair_budget(Mt: int, Nt: int, p: int, q: int, mtl: int, ntl: int,
                 lower: bool) -> int:
    """Max over ranks of #local tiles in the stored triangle (static)."""
    best = 1
    for r in range(p):
        for c in range(q):
            gi = r + p * np.arange(mtl)
            gj = c + q * np.arange(ntl)
            m = (gi[:, None] >= gj[None, :]) if lower else \
                (gi[:, None] <= gj[None, :])
            m &= (gi[:, None] < Mt) & (gj[None, :] < Nt)
            best = max(best, int(m.sum()))
    return best


def _local_pairs(r, c, p, q, mtl, ntl, Mt, Nt, S, lower: bool):
    """Packed (il, jl) lists of this rank's triangle tiles + validity."""
    gi = r + p * jnp.arange(mtl)
    gj = c + q * jnp.arange(ntl)
    cmp = (gi[:, None] >= gj[None, :]) if lower else \
        (gi[:, None] <= gj[None, :])
    mask = cmp & (gi[:, None] < Mt) & (gj[None, :] < Nt)
    flat = mask.reshape(-1).astype(jnp.int32)
    _, idx = lax.top_k(flat, S)                  # distinct flat positions
    valid = jnp.take(flat, idx).astype(bool)
    return idx // ntl, idx % ntl, idx, valid, mask


def _gather_panel_rows(pan, gj, p):
    """All panel tiles along the p axis, then pick rows gj (the tiles the
    column owners need): pan [mtl, nb, kb] -> [ntl, nb, kb]."""
    allpan = lax.all_gather(pan, AXIS_P)         # [p, mtl, nb, kb]
    return allpan[gj % p, gj // p]


def dist_herk_data(a_data, c_data, alpha, beta, Kt: int, Mt: int, Nt: int,
                   grid: Grid, lower: bool, conj: bool,
                   b_data=None, alpha2=None):
    """C_tri = alpha A op(A) + beta C_tri on the stored triangle's tiles.

    a_data: A in cyclic storage [p*mtl, q*ktl, nb, kb]
    c_data: C cyclic [p*mtl, q*ntl, nb, nb] (square tiles)
    b_data: if given, rank-2k: C += alpha A op(B) + alpha2 B op(A)
    op is conj-transpose (conj=True, herk/her2k) or transpose (syrk/syr2k).
    Tiles outside the stored triangle are returned UNCHANGED (they are
    never read through the Hermitian/symmetric wrappers).
    """
    p, q = grid.p, grid.q
    mtl = a_data.shape[0] // p
    ntl = c_data.shape[1] // q
    S = _pair_budget(Mt, Nt, p, q, mtl, ntl, lower)
    two_k = b_data is not None
    a2 = alpha2 if alpha2 is not None else alpha

    def local(a_loc, c_loc, *maybe_b):
        r = lax.axis_index(AXIS_P)
        c = lax.axis_index(AXIS_Q)
        gj = c + q * jnp.arange(ntl)
        il, jl, idx, valid, mask = _local_pairs(
            r, c, p, q, mtl, ntl, Mt, Nt, S, lower)
        dt = c_loc.dtype
        nb = c_loc.shape[-1]

        def panel(k, data):
            with span("slate.herk/bcast"):
                pan = lax.dynamic_index_in_dim(data, k // q, axis=1,
                                               keepdims=False)
                pan = bcast_from_col(pan, k % q)   # [mtl, nb, kb] my rows
                cols = _gather_panel_rows(pan, gj, p)  # [ntl] my cols
                return pan, cols

        def pair_update(rows, cols):
            rg = jnp.take(rows, il, axis=0)      # [S, nb, kb]
            cg = jnp.take(cols, jl, axis=0)      # [S, nb, kb]
            cg = jnp.conj(cg) if conj else cg
            return jnp.einsum("sab,scb->sac", rg, cg,
                              preferred_element_type=dt)

        def body(k, acc):
            arow, acol = panel(k, a_loc)
            if two_k:
                brow, bcol = panel(k, maybe_b[0])
                with span("slate.herk/update"):
                    upd = (jnp.asarray(alpha, dt) * pair_update(arow, bcol)
                           + jnp.asarray(a2, dt) * pair_update(brow, acol))
            else:
                with span("slate.herk/update"):
                    upd = jnp.asarray(alpha, dt) * pair_update(arow, acol)
            return acc + upd

        acc0 = pvary(jnp.zeros((S, nb, nb), dt), (AXIS_P, AXIS_Q))
        acc = lax.fori_loop(0, Kt, body, acc0)
        cflat = c_loc.reshape(mtl * ntl, nb, nb)
        # beta applies to the stored triangle only; other tiles unchanged
        tri = mask.reshape(-1)
        cflat = jnp.where(tri[:, None, None], jnp.asarray(beta, dt) * cflat,
                          cflat)
        cflat = cflat.at[idx].add(
            jnp.where(valid[:, None, None], acc, jnp.zeros_like(acc)))
        return cflat.reshape(mtl, ntl, nb, nb)

    spec = TILE_SPEC
    args = (a_data, c_data) + ((b_data,) if two_k else ())
    fn = shard_map_unchecked(local, mesh=grid.mesh,
                       in_specs=(spec,) * len(args), out_specs=spec)
    return fn(*args)


def _tri_mask_tile(tile, on_diag, before_diag, lower: bool,
                   unit_diag: bool):
    """Mask one batch of A tiles to the stored triangle: full inside the
    triangle, tri-masked on the diagonal, zero outside.  ``before_diag``
    = this tile is on the triangle's full side."""
    dt = tile.dtype
    nb = tile.shape[-1]
    ii = jnp.arange(nb)
    tri = (ii[:, None] >= ii[None, :]) if lower else \
        (ii[:, None] <= ii[None, :])
    eye = jnp.eye(nb, dtype=dt)
    out = jnp.where(on_diag[:, None, None], tile * tri[None], tile)
    if unit_diag:
        out = jnp.where(on_diag[:, None, None], out * (1 - eye) + eye, out)
    keep = on_diag | before_diag
    return jnp.where(keep[:, None, None], out, jnp.zeros_like(out))


def dist_trmm_data(a_data, b_data, alpha, Kt: int, Mt: int, grid: Grid,
                   lower: bool, unit_diag: bool, n: int,
                   sb: int | None = None):
    """B = alpha A B with A triangular, stored triangle only (ref:
    src/trmm.cc -> work::trmm).  SUMMA k loop with STATIC shrinking row
    windows (the dist_chol superblock discipline): step k multiplies A's
    masked tile column k against B's broadcast tile row k and accumulates
    into only the rows the triangle can touch — half a gemm's flops, no
    dense expansion, diagonal tiles masked on the fly so junk in A's
    unstored half never leaks in.

    a_data: A cyclic [p*mtl, q*ktl, nb, nb]; b_data [p*mtl, q*ntl, nb, cb].
    """
    from .dist_chol import superblock
    p, q = grid.p, grid.q
    mtl = a_data.shape[0] // p
    ntl = b_data.shape[1] // q
    nb = a_data.shape[-1]
    sb = sb if sb is not None else superblock(Kt)

    def local(a_loc, b_loc):
        r = lax.axis_index(AXIS_P)
        c = lax.axis_index(AXIS_Q)
        dt = b_loc.dtype
        cb = b_loc.shape[-1]
        gi_all = r + p * jnp.arange(mtl)
        zi = jnp.zeros((), jnp.int32)
        acc = pvary(jnp.zeros((mtl, ntl, nb, cb), dt),
                    (AXIS_P, AXIS_Q))

        def panel_k(k, a_loc, b_loc):
            with span("slate.trmm/bcast"):
                # A tile column k -> all mesh columns (panel listBcast)
                pan = lax.dynamic_index_in_dim(a_loc, k // q, axis=1,
                                               keepdims=False)
                pan = bcast_from_col(pan, k % q)   # [mtl, nb, nb] my rows
                pan = _tri_mask_tile(
                    pan, gi_all == k,
                    (gi_all > k) if lower else (gi_all < k), lower,
                    unit_diag)
                # B tile row k -> all mesh rows
                row = lax.dynamic_index_in_dim(b_loc, k // p, axis=0,
                                               keepdims=False)
                me = lax.axis_index(AXIS_P)
                row = jnp.where(me == k % p, row, jnp.zeros_like(row))
                row = lax.psum(row, AXIS_P)      # [ntl, nb, cb]
                return pan, row

        for k0 in range(0, Kt, sb):
            k1 = min(k0 + sb, Kt)
            if lower:
                S = mtl - (k0 // p)              # rows gi >= k0
            else:
                S = min(mtl, -(-k1 // p))        # rows gi <= k1-1

            def super_step(k, acc, S=S, k0=k0):
                pan, row = panel_k(k, a_loc, b_loc)
                with span("slate.trmm/update"):
                    if lower:
                        sr = jnp.clip(-(-(k0 - r) // p), 0,
                                      mtl - S).astype(jnp.int32)
                    else:
                        sr = zi
                    pwin = lax.dynamic_slice(pan, (sr, zi, zi), (S, nb, nb))
                    upd = jnp.einsum("iab,jbc->ijac", pwin, row,
                                     preferred_element_type=dt)
                    cur = lax.dynamic_slice(acc, (sr, zi, zi, zi),
                                            (S, ntl, nb, cb))
                    return lax.dynamic_update_slice(acc, cur + upd,
                                                    (sr, zi, zi, zi))

            if S > 0:
                acc = lax.fori_loop(k0, k1, super_step, acc)
        return jnp.asarray(alpha, dt) * acc

    spec = TILE_SPEC
    fn = shard_map_unchecked(local, mesh=grid.mesh, in_specs=(spec, spec),
                       out_specs=spec)
    return fn(a_data, b_data)


def dist_trmm_right_data(a_data, b_data, alpha, Kt: int, Nt: int,
                         grid: Grid, lower: bool, unit_diag: bool, n: int,
                         sb: int | None = None):
    """B = alpha B A with A triangular: the mirror of the left kernel —
    k runs over A's tile ROWS, B's tile column k is broadcast along q,
    and the static window covers the columns the triangle can touch."""
    from .dist_chol import superblock
    p, q = grid.p, grid.q
    ntl = a_data.shape[1] // q
    mtl = b_data.shape[0] // p
    nb = a_data.shape[-1]
    sb = sb if sb is not None else superblock(Kt)

    def local(a_loc, b_loc):
        r = lax.axis_index(AXIS_P)
        c = lax.axis_index(AXIS_Q)
        dt = b_loc.dtype
        cb = b_loc.shape[-2]
        gj_all = c + q * jnp.arange(ntl)
        zi = jnp.zeros((), jnp.int32)
        acc = pvary(jnp.zeros((mtl, ntl, cb, nb), dt),
                    (AXIS_P, AXIS_Q))

        def panel_k(k, a_loc, b_loc):
            with span("slate.trmm/bcast"):
                # A tile row k -> all mesh rows
                arow = lax.dynamic_index_in_dim(a_loc, k // p, axis=0,
                                                keepdims=False)
                me = lax.axis_index(AXIS_P)
                arow = jnp.where(me == k % p, arow, jnp.zeros_like(arow))
                arow = lax.psum(arow, AXIS_P)    # [ntl, nb, nb] my cols
                # A[k, j] is full for j < k (lower) / j > k (upper)
                arow = _tri_mask_tile(
                    arow, gj_all == k,
                    (gj_all < k) if lower else (gj_all > k), lower,
                    unit_diag)
                # B tile column k -> all mesh columns
                bcol = lax.dynamic_index_in_dim(b_loc, k // q, axis=1,
                                                keepdims=False)
                bcol = bcast_from_col(bcol, k % q)   # [mtl, cb, nb]
                return arow, bcol

        for k0 in range(0, Kt, sb):
            k1 = min(k0 + sb, Kt)
            if lower:
                T = min(ntl, -(-k1 // q))        # cols gj <= k1-1
            else:
                T = ntl - (k0 // q)              # cols gj >= k0

            def super_step(k, acc, T=T, k0=k0):
                arow, bcol = panel_k(k, a_loc, b_loc)
                with span("slate.trmm/update"):
                    if lower:
                        sc = zi
                    else:
                        sc = jnp.clip(-(-(k0 - c) // q), 0,
                                      ntl - T).astype(jnp.int32)
                    awin = lax.dynamic_slice(arow, (sc, zi, zi),
                                             (T, nb, nb))
                    upd = jnp.einsum("iab,jbc->ijac", bcol, awin,
                                     preferred_element_type=dt)
                    cur = lax.dynamic_slice(acc, (zi, sc, zi, zi),
                                            (mtl, T, cb, nb))
                    return lax.dynamic_update_slice(acc, cur + upd,
                                                    (zi, sc, zi, zi))

            if T > 0:
                acc = lax.fori_loop(k0, k1, super_step, acc)
        return jnp.asarray(alpha, dt) * acc

    spec = TILE_SPEC
    fn = shard_map_unchecked(local, mesh=grid.mesh, in_specs=(spec, spec),
                       out_specs=spec)
    return fn(a_data, b_data)
