"""Distributed gemm: stationary-C SUMMA over the 2D block-cyclic mesh.

Analog of the reference's gemmC driver + internal::gemm<Devices>
(ref: src/gemmC.cc:29-192, src/internal/internal_gemm.cc:383-688):

reference                             | here
------------------------------------- | ----------------------------------
omp task DAG over k, lookahead la     | software pipeline in the fori_loop
  (gemmC.cc:99-115)                   |   carry: step k+la's ring broadcast
                                      |   is issued before step k's MXU
                                      |   accumulate, so ICI rides under
                                      |   compute (depth from tune/
                                      |   ``dist_lookahead``; 0 = the
                                      |   bulk-synchronous oracle)
A.listBcastMT(A(i,k) -> row owners)   | ring_bcast_from_col(a_col, k % q)
B.listBcastMT(B(k,j) -> col owners)   | ring_bcast_from_row(b_row, k % p)
blas::batch::gemm 4-region            | one einsum over local tile batch
tileTick workspace release            | SSA temporary, freed by XLA

The loop body is identical on every rank (SPMD); the data-dependent owner
(k % q) is handled by a masked-psum broadcast at depth 0 and by a
ppermute ring at depth >= 1 — both deliver the owner's exact bytes, so
every depth produces bit-identical results and depth 0 stays the parity
oracle.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..comm.collectives import (bcast_from_col, bcast_from_row,
                                ring_bcast_from_col, ring_bcast_from_row)
from ..core.grid import AXIS_P, AXIS_Q, TILE_SPEC, Grid
from ..internal.gemm import tile_outer_product
from ..robust import abft as _abft
from ..robust import faults
from ..util.trace import span


def summa_local(a_loc, b_loc, c_loc, alpha, beta, Kt: int, p: int, q: int,
                abft: bool = False, la: int = 0):
    """Per-shard SUMMA body (runs inside shard_map).

    a_loc [mtl, ktl_a, mb, kb], b_loc [ktl_b, ntl, kb, nb],
    c_loc [mtl, ntl, mb, nb] — this shard's block-cyclic tiles.

    ``abft`` carries Huang-Abraham checksums of the accumulator through
    the k loop: the broadcast panels already ride the existing
    collectives, so the expected row/column sums of ``sum_k A(:,k)
    B(k,:)`` are accumulated locally at O(nb^2) per step — zero extra
    communication.  After the loop the accumulator is verified tile by
    tile and a single corrupted element is repaired in place
    (robust/abft.py); returns ``(result, detected, corrected, site)``
    with the counters psum-combined over the whole mesh.

    ``la`` (0/1/2, static) is the lookahead depth: at depth d >= 1 the
    fori_loop carry holds the next d steps' panels already in flight —
    the prologue issues the first d ring broadcasts, and each body issues
    step k+d's before accumulating the carried step k, so the broadcast
    rides ICI underneath the MXU accumulate (ref gemmC.cc:99-115).  The
    final body iterations re-issue the clamped last panel; the result is
    dropped with the carry, and since gemm writes no panel state back
    there is nothing to mask.  Checksums are maintained from the consumed
    buffer, so ABFT counters match depth 0 exactly.
    """

    def fetch(k):
        a_col = lax.dynamic_index_in_dim(a_loc, k // q, axis=1,
                                         keepdims=False)
        b_row = lax.dynamic_index_in_dim(b_loc, k // p, axis=0,
                                         keepdims=False)
        return a_col, b_row

    def step(k):
        with span("slate.gemm/bcast"):
            a_col, b_row = fetch(k)
            a_col = bcast_from_col(a_col, k % q)
            b_row = bcast_from_row(b_row, k % p)
        return a_col, b_row

    def issue(k):
        with span("slate.gemm/bcast_ahead"):
            a_col, b_row = fetch(k)
            a_col = ring_bcast_from_col(a_col, k % q, q)
            b_row = ring_bcast_from_row(b_row, k % p, p)
        return a_col, b_row

    if not abft:
        if la == 0:
            def body(k, acc):
                a_col, b_row = step(k)
                with span("slate.gemm/accumulate"):
                    return acc + tile_outer_product(a_col, b_row)

            acc = lax.fori_loop(0, Kt, body, jnp.zeros_like(c_loc))
        else:
            def body(k, carry):
                acc, bufs = carry
                nxt = issue(jnp.minimum(k + la, Kt - 1))
                a_col, b_row = bufs[0]
                with span("slate.gemm/accumulate"):
                    acc = acc + tile_outer_product(a_col, b_row)
                return acc, bufs[1:] + (nxt,)

            bufs = tuple(issue(min(d, Kt - 1)) for d in range(la))
            acc, _ = lax.fori_loop(0, Kt, body,
                                   (jnp.zeros_like(c_loc), bufs))
        acc = faults.maybe_corrupt("post_collective", acc)
        return alpha * acc + beta * c_loc

    mtl, ntl, mb, nb = c_loc.shape
    kb = a_loc.shape[3]
    dt = c_loc.dtype

    def consume(k, acc, rexp, cexp, a_col, b_row):
        with span("slate.gemm/accumulate"):
            acc = acc + tile_outer_product(a_col, b_row)
            # checksum maintenance without forming the product:
            # A (B e) and (e^T A) B per tile pair, O(tiles * nb^2)
            rexp = rexp + _abft.tile_product_row_sums(a_col[:, None],
                                                      b_row[None])
            cexp = cexp + _abft.tile_product_col_sums(a_col[:, None],
                                                      b_row[None])
        return acc, rexp, cexp

    zero = (jnp.zeros_like(c_loc), jnp.zeros((mtl, ntl, mb), dt),
            jnp.zeros((mtl, ntl, nb), dt))
    if la == 0:
        def body(k, carry):
            a_col, b_row = step(k)
            return consume(k, *carry, a_col, b_row)

        acc, rexp, cexp = lax.fori_loop(0, Kt, body, zero)
    else:
        def body(k, carry):
            acc, rexp, cexp, bufs = carry
            nxt = issue(jnp.minimum(k + la, Kt - 1))
            a_col, b_row = bufs[0]
            acc, rexp, cexp = consume(k, acc, rexp, cexp, a_col, b_row)
            return acc, rexp, cexp, bufs[1:] + (nxt,)

        bufs = tuple(issue(min(d, Kt - 1)) for d in range(la))
        acc, rexp, cexp, _ = lax.fori_loop(0, Kt, body, zero + (bufs,))
    acc = faults.maybe_corrupt("post_collective", acc)
    acc, ev, ti_l, tj_l = _abft.tile_sum_check(acc, rexp, cexp,
                                               n_ctx=Kt * kb)
    r = lax.axis_index(AXIS_P)
    c = lax.axis_index(AXIS_Q)
    site_l = jnp.where(ev.detected > 0,
                       _abft.site_code(r + p * ti_l, c + q * tj_l),
                       jnp.asarray(-1, jnp.int32))
    det = lax.psum(lax.psum(ev.detected, AXIS_P), AXIS_Q)
    cor = lax.psum(lax.psum(ev.corrected, AXIS_P), AXIS_Q)
    site = lax.pmax(lax.pmax(site_l, AXIS_P), AXIS_Q)
    return alpha * acc + beta * c_loc, det, cor, site


def summa_gemm_data(a_data, b_data, c_data, alpha, beta, Kt, grid: Grid,
                    abft: bool = False, la: int | None = None):
    """shard_map wrapper over the cyclic storage arrays.  With ``abft``
    returns ``(data, detected, corrected, site)`` — the extra outputs
    are fully replicated scalars.  ``la`` is the lookahead depth; None
    resolves the tuned depth through the ``dist_lookahead`` plan
    (SEAM011 — untuned chips stay on the depth-0 oracle)."""
    if la is None:
        from ..tune import lookahead_depth
        la = lookahead_depth(Kt * a_data.shape[3], a_data.dtype.name)
    spec = TILE_SPEC
    out_specs = (spec, P(), P(), P()) if abft else spec
    fn = jax.shard_map(
        lambda a, b, c: summa_local(a, b, c, alpha, beta, Kt,
                                    grid.p, grid.q, abft=abft, la=la),
        mesh=grid.mesh, in_specs=(spec, spec, spec), out_specs=out_specs)
    return fn(a_data, b_data, c_data)
