"""Distributed gemm: stationary-C SUMMA over the 2D block-cyclic mesh.

Analog of the reference's gemmC driver + internal::gemm<Devices>
(ref: src/gemmC.cc:29-192, src/internal/internal_gemm.cc:383-688):

reference                             | here
------------------------------------- | ----------------------------------
omp task DAG over k, lookahead la     | lax.fori_loop over k; XLA/TPU
  (gemmC.cc:99-115)                   |   pipelines independent steps and
                                      |   overlaps DMA/ICI with MXU compute
A.listBcastMT(A(i,k) -> row owners)   | bcast_from_col(a_col, k % q)
B.listBcastMT(B(k,j) -> col owners)   | bcast_from_row(b_row, k % p)
blas::batch::gemm 4-region            | one einsum over local tile batch
tileTick workspace release            | SSA temporary, freed by XLA

The loop body is identical on every rank (SPMD); the data-dependent owner
(k % q) is handled by masked-psum broadcast, so the whole multiply is ONE
compiled XLA program with Kt collective-permute steps riding ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..comm.collectives import bcast_from_col, bcast_from_row
from ..core.grid import AXIS_P, AXIS_Q, Grid
from ..internal.gemm import tile_outer_product
from ..robust import faults


def summa_local(a_loc, b_loc, c_loc, alpha, beta, Kt: int, p: int, q: int):
    """Per-shard SUMMA body (runs inside shard_map).

    a_loc [mtl, ktl_a, mb, kb], b_loc [ktl_b, ntl, kb, nb],
    c_loc [mtl, ntl, mb, nb] — this shard's block-cyclic tiles.
    """

    def body(k, acc):
        a_col = lax.dynamic_index_in_dim(a_loc, k // q, axis=1, keepdims=False)
        a_col = bcast_from_col(a_col, k % q)
        b_row = lax.dynamic_index_in_dim(b_loc, k // p, axis=0, keepdims=False)
        b_row = bcast_from_row(b_row, k % p)
        return acc + tile_outer_product(a_col, b_row)

    acc = lax.fori_loop(0, Kt, body, jnp.zeros_like(c_loc))
    acc = faults.maybe_corrupt("post_collective", acc)
    return alpha * acc + beta * c_loc


def summa_gemm_data(a_data, b_data, c_data, alpha, beta, Kt, grid: Grid):
    """shard_map wrapper over the cyclic storage arrays."""
    spec = P(AXIS_P, AXIS_Q, None, None)
    fn = jax.shard_map(
        lambda a, b, c: summa_local(a, b, c, alpha, beta, Kt,
                                    grid.p, grid.q),
        mesh=grid.mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(a_data, b_data, c_data)
