"""Distributed LU with partial pivoting over the 2D block-cyclic mesh.

Analog of the reference's getrf driver task graph (ref: src/getrf.cc:23-240):

reference step k                          | here (ONE shard_map program)
----------------------------------------- | ---------------------------------
getrf_panel: threads + panel-rank MPI,    | panel tile-column gathered to all
  MPI_Allreduce(MAXLOC) per column        |   ranks (scatter + psum), factored
  (internal_getrf.cc:20-119,              |   REPLICATED with XLA's pivoted
   Tile_getrf.hh:199-315)                 |   LU — no per-column MAXLOC
                                          |   latency (see internal/getrf.py)
listBcast(A(i,k) -> row i) + pivot bcast  | (absorbed: panel replicated)
internal::permuteRows row exchange        | batched bundle exchange: the
  (internal_swap.cc:199-320 row batches   |   <=2nb displaced rows are
   per rank pair)                         |   top_k-extracted, gathered with
                                          |   one psum along p, re-scattered
trsm U12 row + listBcast (getrf.cc:174+)  | row-k owners solve, psum-bcast
batched trailing gemm                     | one einsum per rank on its
                                          |   static-size trailing slice
pivot-left task (getrf.cc:154-172)        | bundle exchange covers all
                                          |   columns, left included

Compile-time scaling mirrors dist_chol: ~SUPERBLOCKS unrolled superblocks,
each a lax.fori_loop over its k steps.  The replicated panel buffer is the
superblock-start size (Nt-k0 tiles); each inner step ROLLS the active rows
to the top and zeroes the factored tail (zero rows lose every pivot
contest, so XLA's pivoted LU of the padded panel equals the LU of the
active panel with identity tail permutation).

The permutation is tracked as a full row-permutation vector ``perm`` with
``A[perm] == L @ U`` (identical semantics to composing the reference's
Pivot lists).  Square matrices only (gesv path); ragged last tiles handled
by identity-augmenting the pad block of the final panel.

The replicated panel factor routes through internal/getrf.py's seams
(panel_lu_nopiv / panel_lu_tournament), whose kernel choice — fused
Pallas panel, Pallas pivot selection, or XLA — comes from the autotuner
plan cache (slate_tpu.tune, docs/TUNING.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..comm.collectives import ring_bcast_from_col
from ..core.grid import AXIS_P, AXIS_Q, TILE_SPEC, Grid
from ..internal.getrf import (panel_lu, panel_lu_nopiv, panel_lu_threshold,
                              panel_lu_tournament)
from ..robust import abft as _abft
from ..robust import faults
from ..util.compat_jax import shard_map_unchecked
from ..util.trace import span
from .dist_chol import superblock


def _gather_panel(a_loc, k, p, q, mtl, r, c):
    """Replicate panel tile-column k on every rank: [p*mtl, nb, nb]."""
    nb = a_loc.shape[-1]
    kkc = k // q
    ck = k % q
    pan = lax.dynamic_index_in_dim(a_loc, kkc, axis=1, keepdims=False)
    gi_all = r + p * jnp.arange(mtl)
    buf = jnp.zeros((p * mtl, nb, nb), a_loc.dtype)
    buf = buf.at[gi_all].set(pan)
    buf = jnp.where(c == ck, buf, jnp.zeros_like(buf))
    return lax.psum(lax.psum(buf, AXIS_P), AXIS_Q)


def _gather_panel_ring(a_loc, k, p, q, mtl, r, c):
    """Ring variant of :func:`_gather_panel` for the lookahead pipeline.

    The p-axis merge stays a psum (disjoint row slots scatter-merge, not
    a broadcast), but the q-axis owner-column replication becomes a
    ppermute ring so the next panel's hops can slide underneath the
    trailing einsum that runs between issue and consumption.  Pure data
    movement — bit-identical to the psum-masked gather."""
    nb = a_loc.shape[-1]
    kkc = k // q
    ck = k % q
    pan = lax.dynamic_index_in_dim(a_loc, kkc, axis=1, keepdims=False)
    gi_all = r + p * jnp.arange(mtl)
    buf = jnp.zeros((p * mtl, nb, nb), a_loc.dtype)
    buf = buf.at[gi_all].set(pan)
    buf = jnp.where(c == ck, buf, jnp.zeros_like(buf))
    buf = lax.psum(buf, AXIS_P)
    return ring_bcast_from_col(buf, ck, q)


def _row_bundle_exchange(a_loc, out_rows, in_rows, p, r, nbundle):
    """Move rows: new A[out_rows[b], :] = old A[in_rows[b], :] for all local
    columns, with one psum along the p axis (permuteRows analog).

    out_rows/in_rows: [nbundle] global element-row indices (padded entries
    are fixed points out==in, harmless rewrites)."""
    mtl, ntl, nb, _ = a_loc.shape

    def fetch(g):
        lt = (g // nb) // p
        tg = g % nb
        own = ((g // nb) % p) == r
        row = lax.dynamic_index_in_dim(a_loc, lt, axis=0, keepdims=False)
        row = lax.dynamic_index_in_dim(row, tg, axis=1, keepdims=False)
        return jnp.where(own, row, jnp.zeros_like(row))   # [ntl, nb]

    bundle = jax.vmap(fetch)(in_rows)            # [nbundle, ntl, nb]
    bundle = lax.psum(bundle, AXIS_P)

    def scatter(a_loc, b):
        g = out_rows[b]
        lt = (g // nb) // p
        tg = g % nb
        own = ((g // nb) % p) == r
        cur = lax.dynamic_index_in_dim(a_loc, lt, axis=0, keepdims=False)
        cur = lax.dynamic_index_in_dim(cur, tg, axis=1, keepdims=False)
        new = jnp.where(own, bundle[b], cur)
        return a_loc.at[lt, :, tg, :].set(new), None

    a_loc, _ = lax.scan(scatter, a_loc, jnp.arange(nbundle))
    return a_loc


def _dist_getrf_local(a_loc, Nt, n, p, q, mtl, ntl, method: str,
                      ib: int, sb: int, tau: float = 1.0, mpt: int = 4,
                      depth: int = 2, abft: bool = False, la: int = 0):
    r = lax.axis_index(AXIS_P)
    c = lax.axis_index(AXIS_Q)
    nb = a_loc.shape[-1]
    dt = a_loc.dtype
    m_pad = p * mtl * nb
    # sb*nb slack so the dynamic window slice below never clips
    perm_g = jnp.arange(m_pad + sb * nb)
    gi_all = r + p * jnp.arange(mtl)
    idx = jnp.arange(nb)
    zi = jnp.zeros((), jnp.int32)
    # health trace: smallest |U diagonal| seen and its global element row.
    # The panel is psum-replicated, so every rank tracks identical values
    # (valid for out_specs P(); the scan-carry replication checker cannot
    # prove it, hence shard_map_unchecked in dist_getrf).
    rdt = jnp.zeros((), dt).real.dtype
    minpiv = jnp.asarray(jnp.inf, rdt)
    minidx = jnp.zeros((), jnp.int32)
    # ABFT counters, two accumulation disciplines (docs/ROBUSTNESS.md):
    # ``rep`` counts checks of psum-REPLICATED data (the panel) — every
    # rank computes the identical value, so it is never summed across the
    # mesh.  ``loc`` counts checks each rank performs on its OWN tiles
    # (U12 columns masked to the owner row, trailing tiles) and is
    # psum'd over both axes once at the end.  (det, cor, site) int32.
    neg1 = jnp.asarray(-1, jnp.int32)
    rep = (zi, zi, neg1)
    loc = (zi, zi, neg1)

    # Lookahead prologue: panel 0's gather is already in flight (carried as
    # G) when the first step starts; each step then issues step k+1's ring
    # gather before its late trailing update so the broadcast rides under
    # the einsum (ref getrf.cc lookahead task priorities).  G is the full
    # [p*mtl, nb, nb] pre-factor column — superblock-independent shape, so
    # it crosses superblock boundaries; the window slice happens at
    # consumption with the consuming superblock's static bounds.
    if la > 0:
        with span("slate.getrf/bcast_ahead"):
            G = _gather_panel_ring(a_loc, 0, p, q, mtl, r, c)

    for k0 in range(0, Nt, sb):
        k1 = min(k0 + sb, Nt)
        W0 = Nt - k0                             # panel tiles this superblock
        W = W0 * nb
        nbundle = min(2 * nb, W)
        S = mtl - ((k0 + 1) // p)                # static trailing bounds
        T = ntl - ((k0 + 1) // q)

        def super_step(k, carry, W0=W0, W=W, nbundle=nbundle, S=S, T=T,
                       k0=k0):
            if la == 0:
                a_loc, perm_g, minpiv, minidx, rep, loc = carry
            else:
                a_loc, perm_g, minpiv, minidx, rep, loc, G = carry
            rk, ck = k % p, k % q
            kkr = k // p
            vk = jnp.where(k < Nt - 1, nb, n - (Nt - 1) * nb)

            # ---- gather + factor the panel (replicated).  At la >= 1 the
            #      gather already happened at the previous step (carried in
            #      G, issued before that step's late trailing update) ----
            with span("slate.getrf/panel"):
                gpan = (_gather_panel(a_loc, k, p, q, mtl, r, c)
                        if la == 0 else G)
                panel = gpan[k0:Nt].reshape(W, nb)   # static slice
                # roll active rows (>= k) to the top, zero the factored
                # tail
                shift = (k - k0) * nb
                panel = jnp.roll(panel, -shift, axis=0)
                rows = jnp.arange(W)
                panel = jnp.where((rows < (Nt - k) * nb)[:, None], panel,
                                  jnp.zeros_like(panel))
                # ragged final tile: identity-augment its pad block (only
                # the last panel has vk < nb, and it is then the top tile)
                panel = panel + jnp.concatenate(
                    [jnp.diag((idx >= vk).astype(dt)),
                     jnp.zeros((W - nb, nb), dt)], axis=0)
                if method == "nopiv":
                    lu, perm = panel_lu_nopiv(panel)
                elif method == "tntpiv":
                    br = max(ib, nb,
                             (-(-panel.shape[0] // (mpt * nb))) * nb)
                    lu, perm = panel_lu_tournament(panel, block_rows=br,
                                                   arity=depth)
                elif tau < 1.0:
                    lu, perm = panel_lu_threshold(panel, tau)
                else:
                    lu, perm = panel_lu(panel)
                lu = faults.maybe_corrupt("post_panel", lu)
            if abft:
                # verify L\U against the pre-factor panel's checksums
                # (replicated data -> replicated counters).  Rolled row
                # i0 is global element row k*nb + i0.
                lu, det, cor, pi_, _ = _abft.lu_panel_check(
                    panel, lu, perm, n_ctx=n)
                ev = _abft.count_event(det, cor, k + pi_ // nb, k)
                rep = (rep[0] + ev.detected, rep[1] + ev.corrected,
                       jnp.where(rep[2] >= 0, rep[2], ev.site))
            lut = lu.reshape(W0, nb, nb)

            # ---- health trace: this step's U diagonal is diag(lut[0]);
            #      NaN entries count as zero pivots, pad entries (ragged
            #      final tile, idx >= vk) are excluded ----
            d = jnp.abs(jnp.diagonal(lut[0]))
            d = jnp.where(jnp.isnan(d), jnp.zeros_like(d), d)
            d = jnp.where(idx < vk, d, jnp.full_like(d, jnp.inf))
            j = jnp.argmin(d).astype(jnp.int32)
            upd = d[j] < minpiv
            minpiv = jnp.where(upd, d[j], minpiv)
            minidx = jnp.where(upd, (k * nb + j).astype(jnp.int32), minidx)

            # ---- batched row exchange for ALL columns (left + right +
            #      panel; panel values rewritten below) ----
            if method != "nopiv":
                with span("slate.getrf/swap"):
                    iota = jnp.arange(W)
                    displaced = lax.top_k((perm != iota).astype(jnp.int32),
                                          nbundle)[1]
                    out_rows = displaced + k * nb
                    in_rows = perm[displaced] + k * nb
                    a_loc = _row_bundle_exchange(a_loc, out_rows, in_rows,
                                                 p, r, nbundle)
                    pw = lax.dynamic_slice(perm_g, (k * nb,), (W,))
                    perm_g = lax.dynamic_update_slice(perm_g, pw[perm],
                                                      (k * nb,))

            # ---- write the factored panel column back (owners col ck) ----
            ltiles_all = jnp.take(lut, jnp.clip(gi_all - k, 0, W0 - 1),
                                  axis=0)        # [mtl, nb, nb]
            oldcol = lax.dynamic_index_in_dim(a_loc, k // q, axis=1,
                                              keepdims=False)
            newcol = jnp.where((gi_all >= k)[:, None, None], ltiles_all,
                               oldcol)
            col_sel = jnp.where(c == ck, newcol, oldcol)
            a_loc = lax.dynamic_update_slice(
                a_loc, col_sel[:, None],
                (zi, (k // q).astype(jnp.int32), zi, zi))

            def solve_u12(a_loc, loc):
                # ---- U12: row-k owners solve vs unit-lower L11, bcast ----
                with span("slate.getrf/trsm"):
                    l11 = lut[0]
                    urow = lax.dynamic_index_in_dim(a_loc, kkr, axis=0,
                                                    keepdims=False)
                    u12 = jax.vmap(lambda t: lax.linalg.triangular_solve(
                        l11, t, left_side=True, lower=True,
                        unit_diagonal=True))(urow)
                    gj_all = c + q * jnp.arange(ntl)
                    if abft:
                        # R's checksums ride the SAME psum as the solved
                        # tiles: the payload grows to [ntl, nb+1, nb+1] but
                        # no collective round is added.  After the bcast
                        # every rank re-verifies L11 @ U12 = R per local
                        # column tile and repairs a single struck element.
                        aug = jnp.zeros((ntl, nb + 1, nb + 1), dt)
                        aug = aug.at[:, :nb, :nb].set(u12)
                        aug = aug.at[:, :nb, nb].set(jnp.sum(urow, axis=2))
                        aug = aug.at[:, nb, :nb].set(jnp.sum(urow, axis=1))
                        aug = jnp.where(r == rk, aug, jnp.zeros_like(aug))
                        aug = lax.psum(aug, AXIS_P)
                        u12 = faults.maybe_corrupt("post_collective",
                                                   aug[:, :nb, :nb])
                        r_row, r_col = aug[:, :nb, nb], aug[:, nb, :nb]
                        u12, det_t, cor_t, _, _ = jax.vmap(
                            lambda xx, rr, cc: _abft.left_product_check(
                                l11, xx, rr, cc, unit=True,
                                n_ctx=n))(u12, r_row, r_col)
                        # count each global tile once: owner row rk only
                        live = (gj_all > k) & (r == rk)
                        det_n = jnp.sum(live & det_t, dtype=jnp.int32)
                        cor_n = jnp.sum(live & cor_t, dtype=jnp.int32)
                        tj_loc = jnp.argmax(live & det_t)
                        s = jnp.where(
                            det_n > 0,
                            _abft.site_code(k, c + q * tj_loc),
                            jnp.asarray(-1, jnp.int32))
                        loc = (loc[0] + det_n, loc[1] + cor_n,
                               jnp.where(loc[2] >= 0, loc[2], s))
                    else:
                        u12 = jnp.where(r == rk, u12, jnp.zeros_like(u12))
                        u12 = lax.psum(u12, AXIS_P)  # all ranks, own cols
                        u12 = faults.maybe_corrupt("post_collective", u12)
                    newrow = jnp.where((gj_all > k)[:, None, None], u12, urow)
                    row_sel = jnp.where(r == rk, newrow, urow)
                    a_loc = lax.dynamic_update_slice(
                        a_loc, row_sel[None], (kkr.astype(jnp.int32), zi, zi, zi))
                return a_loc, loc, u12

            def early_cols(a_loc, loc, u12):
                # ---- lookahead priority columns k+1 .. k+la: update them
                #      FIRST so the next panel gather (issued before the
                #      late trailing update below) reads finished tiles.
                #      Each rank's u12 slot cd//q holds column cd's solved
                #      tile exactly on the owner column cd % q; elsewhere
                #      (and on dead steps near the edge) the operand is
                #      zeroed, so the ABFT expectation collapses to cur's
                #      own sums and the check is clean by construction ----
                for dcol in range(1, la + 1):
                    cd = jnp.minimum(k + dcol, Nt - 1)
                    act = (k + dcol < Nt) & (c == cd % q)
                    slot = (cd // q).astype(jnp.int32)
                    lrows_e = jnp.take(lut, jnp.clip(gi_all - k, 0, W0 - 1),
                                       axis=0)
                    lrows_e = jnp.where((gi_all > k)[:, None, None], lrows_e,
                                        jnp.zeros_like(lrows_e))
                    ucol = lax.dynamic_index_in_dim(u12, slot, axis=0,
                                                    keepdims=False)[None]
                    ucol = jnp.where(act, ucol, jnp.zeros_like(ucol))
                    upd = jnp.einsum("iab,jbc->ijac", lrows_e, ucol,
                                     preferred_element_type=dt)
                    cur = lax.dynamic_slice(a_loc, (zi, slot, zi, zi),
                                            (mtl, 1, nb, nb))
                    mask = (gi_all > k)[:, None, None, None] & act
                    new = cur - upd
                    if abft:
                        exp_r = (jnp.sum(cur, axis=3)
                                 - _abft.tile_product_row_sums(
                                     lrows_e[:, None], ucol[None]))
                        exp_c = (jnp.sum(cur, axis=2)
                                 - _abft.tile_product_col_sums(
                                     lrows_e[:, None], ucol[None]))
                        new, ev, ti_l, _ = _abft.tile_sum_check(
                            new, exp_r, exp_c, n_ctx=n)
                        s = jnp.where(ev.detected > 0,
                                      _abft.site_code(gi_all[ti_l], cd),
                                      jnp.asarray(-1, jnp.int32))
                        loc = (loc[0] + ev.detected, loc[1] + ev.corrected,
                               jnp.where(loc[2] >= 0, loc[2], s))
                    a_loc = lax.dynamic_update_slice(
                        a_loc, jnp.where(mask, new, cur), (zi, slot, zi, zi))
                return a_loc, loc

            def late_gemm(a_loc, loc, u12, gj_min):
                # ---- trailing update on the static-size slice (columns
                #      > gj_min; gj_min = k at depth 0, k+la pipelined).
                #      Storage pad columns (gj >= Nt) are always late:
                #      early_cols clamps to real columns, so the junk
                #      tiles must follow the depth-0 schedule here or
                #      bit-exact storage parity between depths breaks ----
                with span("slate.getrf/gemm"):
                    sr = jnp.clip(-(-(k0 + 1 - r) // p), 0,
                                  mtl - S).astype(jnp.int32)
                    sc = jnp.clip(-(-(k0 + 1 - c) // q), 0,
                                  ntl - T).astype(jnp.int32)
                    gi = r + p * (sr + jnp.arange(S))
                    gj = c + q * (sc + jnp.arange(T))
                    lrows = jnp.take(lut, jnp.clip(gi - k, 0, W0 - 1), axis=0)
                    lrows = jnp.where((gi > k)[:, None, None], lrows,
                                      jnp.zeros_like(lrows))
                    ucols = lax.dynamic_slice(u12, (sc, zi, zi), (T, nb, nb))
                    ucols = jnp.where(((gj > gj_min) | (gj >= Nt))[:, None, None],
                                      ucols, jnp.zeros_like(ucols))
                    upd = jnp.einsum("iab,jbc->ijac", lrows, ucols,
                                     preferred_element_type=dt)
                    cur = lax.dynamic_slice(a_loc, (sr, sc, zi, zi),
                                            (S, T, nb, nb))
                    mask = ((gi > k)[:, None, None, None] &
                            ((gj > gj_min) | (gj >= Nt))[None, :, None, None])
                    new = cur - upd
                    if abft:
                        # per-tile checksum maintenance of the rank-local
                        # GEMM (masked-out tiles have lrows/ucols zeroed, so
                        # their expectation collapses to cur's own sums and
                        # they verify clean by construction)
                        exp_r = (jnp.sum(cur, axis=3)
                                 - _abft.tile_product_row_sums(
                                     lrows[:, None], ucols[None]))
                        exp_c = (jnp.sum(cur, axis=2)
                                 - _abft.tile_product_col_sums(
                                     lrows[:, None], ucols[None]))
                        new, ev, ti_l, tj_l = _abft.tile_sum_check(
                            new, exp_r, exp_c, n_ctx=n)
                        s = jnp.where(ev.detected > 0,
                                      _abft.site_code(gi[ti_l], gj[tj_l]),
                                      jnp.asarray(-1, jnp.int32))
                        loc = (loc[0] + ev.detected, loc[1] + ev.corrected,
                               jnp.where(loc[2] >= 0, loc[2], s))
                    a_loc = lax.dynamic_update_slice(
                        a_loc, jnp.where(mask, new, cur), (sr, sc, zi, zi))
                return a_loc, loc

            if la == 0:
                def tail(cr):
                    a_loc, perm_g, loc = cr
                    a_loc, loc, u12 = solve_u12(a_loc, loc)
                    a_loc, loc = late_gemm(a_loc, loc, u12, k)
                    return a_loc, perm_g, loc

                if S > 0 and T > 0:
                    # slate-lint: disable=COL003,COL005 -- k is the replicated fori_loop index and Nt is static: every rank evaluates the same predicate, so the psum branch is taken mesh-uniformly
                    a_loc, perm_g, loc = lax.cond(k < Nt - 1, tail,
                                                  lambda cr: cr,
                                                  (a_loc, perm_g, loc))
                return a_loc, perm_g, minpiv, minidx, rep, loc

            # ---- la >= 1 pipeline: solve U12 + finish the priority
            #      columns, issue step k+1's panel gather, THEN run the
            #      late trailing update (columns > k+la) so the ring hops
            #      overlap the big einsum.  The final step's issue is
            #      clamped to column Nt-1 (already factored, pure read)
            #      and its result dies with the dropped carry ----
            def head(cr):
                a_loc, loc, u12 = cr
                a_loc, loc, u12 = solve_u12(a_loc, loc)
                a_loc, loc = early_cols(a_loc, loc, u12)
                return a_loc, loc, u12

            u12 = jnp.zeros((ntl, nb, nb), dt)
            if S > 0 and T > 0:
                # slate-lint: disable=COL003,COL005 -- k is the replicated fori_loop index and Nt is static: every rank evaluates the same predicate, so the psum branch is taken mesh-uniformly
                a_loc, loc, u12 = lax.cond(k < Nt - 1, head,
                                           lambda cr: cr,
                                           (a_loc, loc, u12))
            with span("slate.getrf/bcast_ahead"):
                G = _gather_panel_ring(a_loc, jnp.minimum(k + 1, Nt - 1),
                                       p, q, mtl, r, c)
            if S > 0 and T > 0:
                a_loc, loc = lax.cond(
                    k < Nt - 1,
                    lambda cr: late_gemm(cr[0], cr[1], u12, k + la),
                    lambda cr: cr, (a_loc, loc))
            return a_loc, perm_g, minpiv, minidx, rep, loc, G

        carry = (a_loc, perm_g, minpiv, minidx, rep, loc)
        if la > 0:
            carry = carry + (G,)
        carry = lax.fori_loop(k0, k1, super_step, carry)
        if la > 0:
            a_loc, perm_g, minpiv, minidx, rep, loc, G = carry
        else:
            a_loc, perm_g, minpiv, minidx, rep, loc = carry

    ldet = lax.psum(lax.psum(loc[0], AXIS_P), AXIS_Q)
    lcor = lax.psum(lax.psum(loc[1], AXIS_P), AXIS_Q)
    lsite = lax.pmax(lax.pmax(loc[2], AXIS_P), AXIS_Q)
    adet = rep[0] + ldet
    acor = rep[1] + lcor
    asite = jnp.where(rep[2] >= 0, rep[2], lsite)
    return a_loc, perm_g[:m_pad], minpiv, minidx, adet, acor, asite


def dist_permute_rows(b_data, perm, grid: Grid):
    """Sharded application of a global row permutation:
    new B[g, :] = old B[perm[g], :] (the getrs pivot-apply,
    ref: src/getrs.cc permuteRows + internal_swap.cc batches).

    Each rank all-gathers its tile-COLUMN strip along the p axis — memory
    m x n/q per rank, a 1/q slice of the matrix, never a replicated dense
    copy — then gathers its own rows from the strip.

    Works for any B row tiling, including mb != the LU's nb: ``perm``
    entries for real rows are always < m (dist_getrf zeroes factored/pad
    tail rows so they lose every pivot contest, and the ragged pad block is
    identity-augmented), real element-row indices are tiling-independent,
    and ``perm_pad`` extends identity over B's OWN padded row space here.
    tests/test_lu.py::test_mesh_getrs_mismatched_b_tiling covers this."""
    p, q = grid.p, grid.q
    mtl = b_data.shape[0] // p
    mb = b_data.shape[2]
    m_pad = p * mtl * mb
    perm_pad = jnp.concatenate(
        [jnp.asarray(perm),
         jnp.arange(perm.shape[0], m_pad)]).astype(jnp.int32)

    def local(b_loc, perm_pad):
        r = lax.axis_index(AXIS_P)
        ntl = b_loc.shape[1]
        nbr = b_loc.shape[3]
        allb = lax.all_gather(b_loc, AXIS_P)       # [p, mtl, ntl, mb, nbr]
        # element-rows-major view of the full column strip:
        # global row g at strip index (g//mb % p, g//mb // p, :, g % mb, :)
        strip = allb.transpose(0, 1, 3, 2, 4).reshape(p * mtl * mb,
                                                      ntl, nbr)
        gt = r + p * jnp.arange(mtl)               # my global tile rows
        gr = (gt[:, None] * mb + jnp.arange(mb)[None, :]).reshape(-1)
        src = perm_pad[gr]                         # source element rows
        st_, so = src // mb, src % mb
        strip_idx = (st_ % p) * (mtl * mb) + (st_ // p) * mb + so
        mine = strip[strip_idx]                    # [mtl*mb, ntl, nbr]
        return mine.reshape(mtl, mb, ntl, nbr).transpose(0, 2, 1, 3)

    spec = TILE_SPEC
    fn = jax.shard_map(local, mesh=grid.mesh, in_specs=(spec, P()),
                       out_specs=spec)
    return fn(b_data, perm_pad)


def dist_rbt_two_sided(data, u_levels, v_levels, grid: Grid, n: int):
    """Sharded two-sided butterfly transform U^T diag(A, I_pad) V on
    block-cyclic storage (drivers/lu.py getrf_rbt mesh path; butterflies
    from internal/rbt.py).

    Each rank all-gathers its tile-COLUMN strip along the p axis to apply
    the row butterflies in global element order, then its tile-ROW strip
    along the q axis for the column butterflies — memory is a 1/q (then
    1/p) slice of the matrix, never a replicated dense copy (the
    dist_permute_rows discipline).  The butterfly diagonals are host-seeded
    trace constants replicated on every rank, so each application is pure
    elementwise work on the gathered strip: O(d m^2/q) flops per rank, no
    matmuls, and only the two all_gathers as communication."""
    from ..internal import rbt
    p, q = grid.p, grid.q
    mtl = data.shape[0] // p
    ntl = data.shape[1] // q
    nb = data.shape[-1]
    m_pad = p * mtl * nb
    # identity-augment the pad diagonal: the transform must act on
    # diag(A, I), not diag(A, 0) (pads are zero by the canonical invariant)
    if m_pad > n:
        g = jnp.arange(n, m_pad)
        data = data.at[g // nb, g // nb, g % nb, g % nb].set(1)

    def local(a_loc, lu, lv):
        r = lax.axis_index(AXIS_P)
        c = lax.axis_index(AXIS_Q)
        gidx = jnp.arange(m_pad)
        # strip index of global element row/col g (see dist_permute_rows)
        si = ((gidx // nb % p) * (mtl * nb) + (gidx // nb // p) * nb
              + gidx % nb)
        sj = ((gidx // nb % q) * (ntl * nb) + (gidx // nb // q) * nb
              + gidx % nb)
        # row pass: U^T @ (.) on the full column strip in global row order
        allp = lax.all_gather(a_loc, AXIS_P)      # [p, mtl, ntl, nb, nb]
        strip = allp.transpose(0, 1, 3, 2, 4).reshape(m_pad, ntl, nb)
        ordered = rbt.apply_axis(lu, strip[si], "t", 0)
        gr = ((r + p * jnp.arange(mtl))[:, None] * nb
              + jnp.arange(nb)[None, :]).reshape(-1)
        a_loc = ordered[gr].reshape(mtl, nb, ntl, nb).transpose(0, 2, 1, 3)
        # column pass: (.) @ V on the full row strip in global column order
        allq = lax.all_gather(a_loc, AXIS_Q)      # [q, mtl, ntl, nb, nb]
        cstrip = allq.transpose(1, 3, 0, 2, 4).reshape(mtl, nb,
                                                       q * ntl * nb)
        cordered = rbt.apply_axis(lv, cstrip[:, :, sj], "t", 2)
        gc = ((c + q * jnp.arange(ntl))[:, None] * nb
              + jnp.arange(nb)[None, :]).reshape(-1)
        return cordered[:, :, gc].reshape(mtl, nb, ntl, nb).transpose(
            0, 2, 1, 3)

    spec = TILE_SPEC
    fn = jax.shard_map(local, mesh=grid.mesh, in_specs=(spec, P(), P()),
                       out_specs=spec)
    return fn(data, u_levels, v_levels)


def dist_getrf(data, Nt: int, grid: Grid, n: int, method: str = "partial",
               ib: int = 16, sb: int | None = None, tau: float = 1.0,
               mpt: int = 4, depth: int = 2, abft: bool = False,
               la: int | None = None):
    """Factor square cyclic storage in place; returns
    (data, perm, minpiv, minidx, abft_detected, abft_corrected,
    abft_site) with A[perm] = L @ U (perm over the padded row space,
    identity on pads).  ``minpiv``/``minidx`` are the smallest
    |U diagonal| encountered and its global element row — replicated
    scalars feeding drivers/lu.py's HealthInfo.

    ``abft`` (static) turns on Huang-Abraham checksum verification of
    every panel, U12 bcast and trailing update (robust/abft.py): single
    struck elements are repaired in place and counted in the three
    trailing replicated int32 scalars (all zero / -1 when off or clean).

    ``tau`` (Option.PivotThreshold) < 1 switches the partial-pivot panel to
    threshold pivoting; ``mpt`` (Option.MaxPanelThreads) sizes the CALU
    tournament row blocks; ``depth`` (Option.Depth) its tree fan-in.

    ``la`` (0/1/2, static) is the lookahead pipeline depth — NOT the CALU
    ``depth`` above: at la >= 1 each step rings the NEXT panel's gather
    ahead of its late trailing update (and finishes columns k+1..k+la
    first so the gather reads complete tiles).  Bit-identical to la=0.
    None resolves the tuned depth via the ``dist_lookahead`` plan
    (SEAM011)."""
    if la is None:
        from ..tune import lookahead_depth
        la = lookahead_depth(n, data.dtype.name)
    mtl = data.shape[0] // grid.p
    ntl = data.shape[1] // grid.q
    sb = sb if sb is not None else superblock(Nt)
    spec = TILE_SPEC
    fn = shard_map_unchecked(
        lambda a: _dist_getrf_local(a, Nt, n, grid.p, grid.q, mtl, ntl,
                                    method, ib, sb, tau, mpt, depth, abft,
                                    la=la),
        mesh=grid.mesh, in_specs=(spec,),
        out_specs=(spec, P(), P(), P(), P(), P(), P()))
    return fn(data)
