"""Distributed LU with partial pivoting over the 2D block-cyclic mesh.

Analog of the reference's getrf driver task graph (ref: src/getrf.cc:23-240):

reference step k                          | here (ONE shard_map program)
----------------------------------------- | ---------------------------------
getrf_panel: threads + panel-rank MPI,    | panel tile-column gathered to all
  MPI_Allreduce(MAXLOC) per column        |   ranks (scatter + psum), factored
  (internal_getrf.cc:20-119,              |   REPLICATED with XLA's pivoted
   Tile_getrf.hh:199-315)                 |   LU — no per-column MAXLOC
                                          |   latency (see internal/getrf.py)
listBcast(A(i,k) -> row i) + pivot bcast  | (absorbed: panel replicated)
internal::permuteRows row exchange        | batched bundle exchange: the
  (internal_swap.cc:199-320 row batches   |   <=2nb displaced rows are
   per rank pair)                         |   top_k-extracted, gathered with
                                          |   one psum along p, re-scattered
trsm U12 row + listBcast (getrf.cc:174+)  | row-k owners solve, psum-bcast
batched trailing gemm                     | one einsum per rank on its
                                          |   static-size trailing slice
pivot-left task (getrf.cc:154-172)        | bundle exchange covers all
                                          |   columns, left included

The permutation is tracked as a full row-permutation vector ``perm`` with
``A[perm] == L @ U`` (identical semantics to composing the reference's
Pivot lists).  Square matrices only (gesv path); ragged last tiles handled
by identity-augmenting the pad block of the final panel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.grid import AXIS_P, AXIS_Q, Grid
from ..internal.getrf import panel_lu, panel_lu_nopiv, panel_lu_tournament


def _gather_panel(a_loc, k, p, q, mtl, r, c):
    """Replicate panel tile-column k on every rank: [p*mtl, nb, nb]."""
    nb = a_loc.shape[-1]
    kkc = k // q
    ck = k % q
    pan = a_loc[:, kkc]                          # my rows of column k
    gi_all = r + p * jnp.arange(mtl)
    buf = jnp.zeros((p * mtl, nb, nb), a_loc.dtype)
    buf = buf.at[gi_all].set(pan)
    buf = jnp.where(c == ck, buf, jnp.zeros_like(buf))
    return lax.psum(lax.psum(buf, AXIS_P), AXIS_Q)


def _row_bundle_exchange(a_loc, out_rows, in_rows, k_nb, p, r, nbundle):
    """Move rows: new A[out_rows[b], :] = old A[in_rows[b], :] for all local
    columns, with one psum along the p axis (permuteRows analog).

    out_rows/in_rows: [nbundle] global element-row indices (padded entries
    are fixed points out==in, harmless rewrites)."""
    mtl, ntl, nb, _ = a_loc.shape

    def fetch(g):
        lt = (g // nb) // p
        tg = g % nb
        own = ((g // nb) % p) == r
        row = lax.dynamic_index_in_dim(a_loc, lt, axis=0, keepdims=False)
        row = lax.dynamic_index_in_dim(row, tg, axis=1, keepdims=False)
        return jnp.where(own, row, jnp.zeros_like(row))   # [ntl, nb]

    bundle = jax.vmap(fetch)(in_rows)            # [nbundle, ntl, nb]
    bundle = lax.psum(bundle, AXIS_P)

    def scatter(a_loc, b):
        g = out_rows[b]
        lt = (g // nb) // p
        tg = g % nb
        own = ((g // nb) % p) == r
        cur = lax.dynamic_index_in_dim(a_loc, lt, axis=0, keepdims=False)
        cur = lax.dynamic_index_in_dim(cur, tg, axis=1, keepdims=False)
        new = jnp.where(own, bundle[b], cur)
        return a_loc.at[lt, :, tg, :].set(new), None

    a_loc, _ = lax.scan(scatter, a_loc, jnp.arange(nbundle))
    return a_loc


def _dist_getrf_local(a_loc, Nt, n, p, q, mtl, ntl, method: str,
                      ib: int):
    r = lax.axis_index(AXIS_P)
    c = lax.axis_index(AXIS_Q)
    nb = a_loc.shape[-1]
    dt = a_loc.dtype
    m_pad = p * mtl * nb
    perm_g = jnp.arange(m_pad)

    for k in range(Nt):
        rk, ck = k % p, k % q
        kkr, kkc = k // p, k // q
        W = (Nt - k) * nb                        # panel window rows
        vk = nb if k < Nt - 1 else n - (Nt - 1) * nb

        # ---- gather + factor the panel (replicated) ----
        gpan = _gather_panel(a_loc, k, p, q, mtl, r, c)
        panel = gpan[k:Nt].reshape(W, nb)
        if vk < nb:                              # ragged final tile: augment
            t = jnp.arange(nb - vk)
            panel = panel.at[vk + t, vk + t].set(jnp.ones((), dt))
        if method == "nopiv":
            lu, perm = panel_lu_nopiv(panel)
        elif method == "tntpiv":
            lu, perm = panel_lu_tournament(panel, block_rows=max(ib, nb))
        else:
            lu, perm = panel_lu(panel)
        lut = lu.reshape(Nt - k, nb, nb)

        # ---- batched row exchange for ALL columns (left + right + panel;
        #      panel values rewritten below) ----
        if method != "nopiv":
            iota = jnp.arange(W)
            nbundle = min(2 * nb, W)
            displaced = lax.top_k((perm != iota).astype(jnp.int32),
                                  nbundle)[1]
            out_rows = displaced + k * nb
            in_rows = perm[displaced] + k * nb
            a_loc = _row_bundle_exchange(a_loc, out_rows, in_rows, k * nb,
                                         p, r, nbundle)
            pw = perm_g[k * nb:k * nb + W]
            perm_g = lax.dynamic_update_slice(perm_g, pw[perm], (k * nb,))

        # ---- write the factored panel column back (owners in col ck) ----
        gi_all = r + p * jnp.arange(mtl)         # global tile row per slot
        ltiles_all = jnp.take(lut, jnp.clip(gi_all - k, 0, Nt - k - 1),
                              axis=0)            # [mtl, nb, nb]
        newcol = jnp.where((gi_all >= k)[:, None, None], ltiles_all,
                           a_loc[:, kkc])
        a_loc = jnp.where(c == ck, a_loc.at[:, kkc].set(newcol), a_loc)

        if k == Nt - 1:
            break

        # ---- U12: row-k owners solve against unit-lower L11, bcast ----
        l11 = lut[0]
        urow = a_loc[kkr]                        # [ntl, nb, nb] my row k
        u12 = jax.vmap(lambda t: lax.linalg.triangular_solve(
            l11, t, left_side=True, lower=True, unit_diagonal=True))(urow)
        u12 = jnp.where(r == rk, u12, jnp.zeros_like(u12))
        u12 = lax.psum(u12, AXIS_P)              # all ranks, their own cols
        gj_all = c + q * jnp.arange(ntl)
        newrow = jnp.where((gj_all > k)[:, None, None], u12, a_loc[kkr])
        a_loc = jnp.where(r == rk, a_loc.at[kkr].set(newrow), a_loc)

        # ---- trailing update on static-size slice ----
        S = mtl - max(0, (k + 1) // p)
        T = ntl - max(0, (k + 1) // q)
        if S <= 0 or T <= 0:
            continue
        sr = jnp.clip((k + 1 - r + p - 1) // p, 0, mtl - S)
        sc = jnp.clip((k + 1 - c + q - 1) // q, 0, ntl - T)
        gi = r + p * (sr + jnp.arange(S))
        gj = c + q * (sc + jnp.arange(T))
        lrows = jnp.take(lut, jnp.clip(gi - k, 0, Nt - k - 1), axis=0)
        ucols = lax.dynamic_slice(u12, (sc, jnp.zeros((), sc.dtype),
                                        jnp.zeros((), sc.dtype)),
                                  (T, nb, nb))
        upd = jnp.einsum("iab,jbc->ijac", lrows, ucols,
                         preferred_element_type=dt)
        z = jnp.zeros((), sr.dtype)
        cur = lax.dynamic_slice(a_loc, (sr, sc, z, z), (S, T, nb, nb))
        mask = ((gi > k)[:, None, None, None] & (gj > k)[None, :, None, None])
        a_loc = lax.dynamic_update_slice(
            a_loc, jnp.where(mask, cur - upd, cur), (sr, sc, z, z))

    return a_loc, perm_g


def dist_getrf(data, Nt: int, grid: Grid, n: int, method: str = "partial",
               ib: int = 16):
    """Factor square cyclic storage in place; returns (data, perm) with
    A[perm] = L @ U (perm over the padded row space, identity on pads)."""
    mtl = data.shape[0] // grid.p
    ntl = data.shape[1] // grid.q
    spec = P(AXIS_P, AXIS_Q, None, None)
    fn = jax.shard_map(
        lambda a: _dist_getrf_local(a, Nt, n, grid.p, grid.q, mtl, ntl,
                                    method, ib),
        mesh=grid.mesh, in_specs=(spec,),
        out_specs=(spec, P()))
    return fn(data)
