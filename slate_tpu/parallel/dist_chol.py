"""Distributed right-looking Cholesky over the 2D block-cyclic mesh.

Analog of the reference's potrf<Devices> task graph (ref: src/potrf.cc:141-302
and the HostTask variant :23-133):

reference step k                       | here (inside ONE shard_map program)
-------------------------------------- | -----------------------------------
internal::potrf on diagonal tile :213  | diag tile psum-gathered, cholesky
                                       |   replicated on all ranks (cheaper
                                       |   than a second broadcast round)
tileBcast(k,k -> panel column) :219    | (absorbed into the above)
internal::trsm on panel column :225    | vmapped triangular_solve on the
                                       |   owner column's local panel tiles
listBcastMT(A(i,k) -> row i, col i)    | scatter into a global panel buffer
  :232-242                             |   + psum over both mesh axes
internal::herk trailing update :254    | einsum over the rank's trailing
                                       |   slice (static shrinking sizes)
lookahead tasks :266-287               | software pipeline in the fori_loop
                                       |   carry (``la`` >= 1): columns
                                       |   k+1..k+la get step k's herk at
                                       |   priority, panel k+1 is factored
                                       |   + ring-broadcast next, and only
                                       |   then the late trailing update
                                       |   (cols > k+la) runs — so the
                                       |   in-flight broadcast rides ICI
                                       |   under the trailing MXU work
release/tileUpdateAllOrigin :289-302   | SSA buffer lifetimes

Compile-time scaling: the k loop is TWO-LEVEL.  The outer level unrolls
~SUPERBLOCKS superblocks at trace time, each with STATIC trailing-slice
sizes (the ScaLAPACK shrinking discipline, so masked-FLOP waste is bounded
by ~1.5·sb/Nt); the inner level is a lax.fori_loop over the superblock's k
steps with traced indices — so the compiled program size is O(SUPERBLOCKS),
not O(Nt), and n=50k/nb=256 (Nt≈196) compiles like Nt=16 does.

Only Uplo.Lower is implemented here; the driver maps Upper problems onto it
(ref: potrf.cc handles Upper by conjugate-transposing views the same way).

The diagonal-tile factor routes through internal/potrf.py potrf_tile,
whose kernel choice (XLA Cholesky vs the VMEM-resident Pallas tile) now
comes from the autotuner plan cache (slate_tpu.tune, docs/TUNING.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..comm.collectives import ring_bcast_from_col, ring_bcast_from_row
from ..core.grid import AXIS_P, AXIS_Q, TILE_SPEC, Grid
from ..internal.herk import herk_panel_update
from ..robust import abft as _abft
from ..robust import faults
from ..util.compat_jax import shard_map_unchecked
from ..util.trace import span
from ..internal.potrf import potrf_tile
from ..internal.trsm import trsm_tile_batch
from ..types import Op

SUPERBLOCKS = 16


def superblock(Nt: int, target: int = SUPERBLOCKS) -> int:
    """Inner fori_loop span: ~``target`` compiled bodies regardless of Nt."""
    return max(1, -(-Nt // target))


def _potrf_local(a_loc, Nt: int, n: int, p: int, q: int, mtl: int, ntl: int,
                 sb: int, abft: bool = False, la: int = 0):
    """Per-shard body; a_loc [mtl, ntl, nb, nb] block-cyclic local tiles.

    ``la`` (0/1/2, static) is the lookahead depth.  Depth 0 is the
    bulk-synchronous oracle below.  At depth >= 1 each fori_loop body
    runs the SLATE lookahead schedule (ref potrf.cc:266-287): columns
    k+1..k+la receive step k's herk at priority, panel k+1 is factored
    and ring-broadcast immediately after (carried in flight), and only
    then does the late trailing update (columns > k+la) consume step k's
    panel — XLA sees the in-flight broadcast and the late herk as
    data-independent and can overlap ICI with MXU time.  Every trailing
    tile is updated exactly once by per-tile-independent einsums and the
    ring broadcast moves the owner's exact bytes, so any depth is
    bit-identical to depth 0, ABFT counters included."""
    r = lax.axis_index(AXIS_P)
    c = lax.axis_index(AXIS_Q)
    nb = a_loc.shape[-1]
    dt = a_loc.dtype
    idx = jnp.arange(nb)
    gi_all = r + p * jnp.arange(mtl)              # global tile row per slot
    zi = jnp.zeros((), jnp.int32)
    # health trace: smallest L diagonal seen and its global element row
    # (replicated — valid for out_specs P(); the scan-carry replication
    # checker cannot prove it, hence shard_map_unchecked in dist_potrf)
    rdt = jnp.zeros((), dt).real.dtype
    minpiv = jnp.asarray(jnp.inf, rdt)
    minidx = jnp.zeros((), jnp.int32)
    # ABFT counters (same discipline as dist_lu): ``rep`` for checks of
    # replicated data (diag tile, broadcast panel) — never mesh-summed;
    # ``loc`` for each rank's own trailing tiles — psum'd at the end.
    neg1 = jnp.asarray(-1, jnp.int32)
    rep = (zi, zi, neg1)
    loc = (zi, zi, neg1)

    def step(k, carry):
        a_loc, minpiv, minidx, rep, loc = carry
        rk, ck = k % p, k % q
        kkr, kkc = k // p, k // q
        # valid extent of diagonal tile k (ragged last tile); pad diagonal
        # identity-augmented so the tile factor stays finite, re-zeroed on
        # write-back to keep the pad==0 invariant
        vk = jnp.where(k < Nt - 1, nb, n - (Nt - 1) * nb)
        pad_eye = jnp.diag((idx >= vk).astype(dt))
        vmask = (idx[:, None] < vk) & (idx[None, :] < vk)

        # -- diagonal tile: gather from owner, factor everywhere --
        with span("slate.potrf/panel"):
            dtile = lax.dynamic_index_in_dim(
                lax.dynamic_index_in_dim(a_loc, kkr, axis=0, keepdims=False),
                kkc, axis=0, keepdims=False)
            dtile = jnp.where((r == rk) & (c == ck), dtile,
                              jnp.zeros((nb, nb), dt))
            dtile = lax.psum(lax.psum(dtile, AXIS_P), AXIS_Q)
            # Hermitian-complete from the stored lower triangle: only the
            # lower triangle of the input is ever read, so callers may pass
            # storage whose upper tiles hold junk (XLA's cholesky reads the
            # full tile on some backends)
            dlow = jnp.tril(dtile)
            ddiag = jnp.diagonal(dtile)
            if jnp.iscomplexobj(dtile):
                ddiag = jnp.real(ddiag).astype(dt)
            dtile = (dlow + jnp.conj(dlow).T).at[idx, idx].set(ddiag)
            lkk_aug = potrf_tile(dtile + pad_eye)
            lkk_aug = faults.maybe_corrupt("post_panel", lkk_aug)
            if abft:
                # verify/repair the replicated diag factor BEFORE the
                # health trace reads its diagonal (a corrected strike
                # must not leave a phantom zero pivot)
                lkk_aug, det, cor = _abft.chol_tile_check(
                    dtile + pad_eye, lkk_aug, n_ctx=n)
                ev = _abft.count_event(det, cor, k, k)
                rep = (rep[0] + ev.detected, rep[1] + ev.corrected,
                       jnp.where(rep[2] >= 0, rep[2], ev.site))
            lkk = jnp.where(vmask, lkk_aug, jnp.zeros_like(lkk_aug))

            # health trace: smallest L diagonal (replicated — every rank
            # factored the same psum-gathered tile).  A non-HPD leading
            # minor shows up as NaN on the diagonal, counted as a zero
            # pivot; pad entries (idx >= vk) are excluded.
            d = jnp.abs(jnp.diagonal(lkk_aug))
            d = jnp.where(jnp.isnan(d), jnp.zeros_like(d), d)
            d = jnp.where(idx < vk, d, jnp.full_like(d, jnp.inf))
            j = jnp.argmin(d).astype(jnp.int32)
            upd = d[j] < minpiv
            minpiv = jnp.where(upd, d[j], minpiv)
            minidx = jnp.where(upd, (k * nb + j).astype(jnp.int32), minidx)

            # -- panel trsm on the owner column's local tiles --
            pan = lax.dynamic_index_in_dim(a_loc, kkc, axis=1, keepdims=False)
            sol = trsm_tile_batch(lkk_aug, pan, left=False, lower=True,
                                  op_tri=Op.ConjTrans)

            keep = (gi_all[:, None, None] <= k)
            newcol = jnp.where(keep, pan, sol)
            newcol = jnp.where((gi_all == k)[:, None, None], lkk, newcol)
            col_sel = jnp.where(c == ck, newcol, pan)
            a_loc = lax.dynamic_update_slice(
                a_loc, col_sel[:, None], (zi, kkc.astype(jnp.int32), zi, zi))

        # -- broadcast the panel column to every rank (ref listBcastMT
        #    potrf.cc:232-242): scatter to global buffer, psum the mesh --
        with span("slate.potrf/bcast"):
            contrib = jnp.where((gi_all > k)[:, None, None], sol,
                                jnp.zeros_like(sol))
            if abft:
                # checksums of R (the pre-solve panel column) ride the
                # SAME psum as the solved tiles: payload [.., nb+1, nb+1],
                # no extra collective round.  The broadcast result is
                # replicated, so every rank runs the identical per-tile
                # verify of X L^H = R (as L X^H = R^H) -> rep counters.
                augl = jnp.zeros((mtl, nb + 1, nb + 1), dt)
                augl = augl.at[:, :nb, :nb].set(contrib)
                rmask = (gi_all > k)[:, None]
                augl = augl.at[:, :nb, nb].set(
                    jnp.where(rmask, jnp.sum(pan, axis=2), 0))
                augl = augl.at[:, nb, :nb].set(
                    jnp.where(rmask, jnp.sum(pan, axis=1), 0))
                buf = jnp.zeros((p * mtl, nb + 1, nb + 1), dt)
                buf = buf.at[gi_all].set(augl)
                buf = jnp.where(c == ck, buf, jnp.zeros_like(buf))
                aug = lax.psum(lax.psum(buf, AXIS_P), AXIS_Q)
                gpan = faults.maybe_corrupt("post_collective",
                                            aug[:, :nb, :nb])
                r_row = jnp.conj(aug[:, nb, :nb])  # (R^H) e = conj(e^T R)
                r_col = jnp.conj(aug[:, :nb, nb])  # e^T R^H = conj(R e)
                xh, det_t, cor_t, _, _ = jax.vmap(
                    lambda xx, rr, cc: _abft.left_product_check(
                        lkk_aug, jnp.conj(xx).T, rr, cc,
                        unit=False, n_ctx=n))(gpan, r_row, r_col)
                gpan = jnp.conj(xh).transpose(0, 2, 1)
                live = jnp.arange(p * mtl) > k
                det_n = jnp.sum(live & det_t, dtype=jnp.int32)
                cor_n = jnp.sum(live & cor_t, dtype=jnp.int32)
                ti_g = jnp.argmax(live & det_t).astype(jnp.int32)
                s = jnp.where(det_n > 0, _abft.site_code(ti_g, k), neg1)
                rep = (rep[0] + det_n, rep[1] + cor_n,
                       jnp.where(rep[2] >= 0, rep[2], s))
            else:
                buf = jnp.zeros((p * mtl, nb, nb), dt)
                buf = buf.at[gi_all].set(contrib)
                buf = jnp.where(c == ck, buf, jnp.zeros_like(buf))
                gpan = lax.psum(lax.psum(buf, AXIS_P), AXIS_Q)
                gpan = faults.maybe_corrupt("post_collective", gpan)
        return (a_loc, minpiv, minidx, rep, loc), gpan

    def issue(kn, a_loc, minpiv, minidx, rep, live):
        """Panel factor + ring broadcast for step ``kn`` — the in-flight
        half of the software pipeline (``la`` >= 1).  Same arithmetic as
        ``step`` with the masked-psum broadcasts replaced by ppermute
        rings (bit-identical: pure data movement).  All state writes are
        masked by ``live``: the final body iteration re-issues the
        clamped last panel, whose garbage factor must stay confined to
        the dropped loop carry."""
        kn = jnp.asarray(kn, jnp.int32)
        rk, ck = kn % p, kn % q
        kkr, kkc = kn // p, kn // q
        vk = jnp.where(kn < Nt - 1, nb, n - (Nt - 1) * nb)
        pad_eye = jnp.diag((idx >= vk).astype(dt))
        vmask = (idx[:, None] < vk) & (idx[None, :] < vk)

        with span("slate.potrf/panel"):
            dtile = lax.dynamic_index_in_dim(
                lax.dynamic_index_in_dim(a_loc, kkr, axis=0, keepdims=False),
                kkc, axis=0, keepdims=False)
            dtile = jnp.where((r == rk) & (c == ck), dtile,
                              jnp.zeros((nb, nb), dt))
            dtile = ring_bcast_from_row(dtile, rk, p)
            dtile = ring_bcast_from_col(dtile, ck, q)
            dlow = jnp.tril(dtile)
            ddiag = jnp.diagonal(dtile)
            if jnp.iscomplexobj(dtile):
                ddiag = jnp.real(ddiag).astype(dt)
            dtile = (dlow + jnp.conj(dlow).T).at[idx, idx].set(ddiag)
            lkk_aug = potrf_tile(dtile + pad_eye)
            lkk_aug = faults.maybe_corrupt("post_panel", lkk_aug)
            if abft:
                lkk_aug, det, cor = _abft.chol_tile_check(
                    dtile + pad_eye, lkk_aug, n_ctx=n)
                ev = _abft.count_event(det, cor, kn, kn)
                rep = (rep[0] + jnp.where(live, ev.detected, 0),
                       rep[1] + jnp.where(live, ev.corrected, 0),
                       jnp.where(rep[2] >= 0, rep[2],
                                 jnp.where(live, ev.site, neg1)))
            lkk = jnp.where(vmask, lkk_aug, jnp.zeros_like(lkk_aug))

            d = jnp.abs(jnp.diagonal(lkk_aug))
            d = jnp.where(jnp.isnan(d), jnp.zeros_like(d), d)
            d = jnp.where(idx < vk, d, jnp.full_like(d, jnp.inf))
            j = jnp.argmin(d).astype(jnp.int32)
            upd = (d[j] < minpiv) & live
            minpiv = jnp.where(upd, d[j], minpiv)
            minidx = jnp.where(upd, (kn * nb + j).astype(jnp.int32), minidx)

            pan = lax.dynamic_index_in_dim(a_loc, kkc, axis=1, keepdims=False)
            sol = trsm_tile_batch(lkk_aug, pan, left=False, lower=True,
                                  op_tri=Op.ConjTrans)
            keep = (gi_all[:, None, None] <= kn)
            newcol = jnp.where(keep, pan, sol)
            newcol = jnp.where((gi_all == kn)[:, None, None], lkk, newcol)
            col_sel = jnp.where(live & (c == ck), newcol, pan)
            a_loc = lax.dynamic_update_slice(
                a_loc, col_sel[:, None], (zi, kkc.astype(jnp.int32), zi, zi))

        with span("slate.potrf/bcast_ahead"):
            contrib = jnp.where((gi_all > kn)[:, None, None], sol,
                                jnp.zeros_like(sol))
            if abft:
                augl = jnp.zeros((mtl, nb + 1, nb + 1), dt)
                augl = augl.at[:, :nb, :nb].set(contrib)
                rmask = (gi_all > kn)[:, None]
                augl = augl.at[:, :nb, nb].set(
                    jnp.where(rmask, jnp.sum(pan, axis=2), 0))
                augl = augl.at[:, nb, :nb].set(
                    jnp.where(rmask, jnp.sum(pan, axis=1), 0))
                buf = jnp.zeros((p * mtl, nb + 1, nb + 1), dt)
                buf = buf.at[gi_all].set(augl)
                buf = jnp.where(c == ck, buf, jnp.zeros_like(buf))
                # the p-axis combine is a scatter-merge of disjoint row
                # slots (not single-root), so it stays a psum; the q-axis
                # broadcast from the owner column becomes the ring
                aug = lax.psum(buf, AXIS_P)
                aug = ring_bcast_from_col(aug, ck, q)
                gpan = faults.maybe_corrupt("post_collective",
                                            aug[:, :nb, :nb])
                r_row = jnp.conj(aug[:, nb, :nb])  # (R^H) e = conj(e^T R)
                r_col = jnp.conj(aug[:, :nb, nb])  # e^T R^H = conj(R e)
                xh, det_t, cor_t, _, _ = jax.vmap(
                    lambda xx, rr, cc: _abft.left_product_check(
                        lkk_aug, jnp.conj(xx).T, rr, cc,
                        unit=False, n_ctx=n))(gpan, r_row, r_col)
                gpan = jnp.conj(xh).transpose(0, 2, 1)
                trail = jnp.arange(p * mtl) > kn
                det_n = jnp.where(live, jnp.sum(trail & det_t,
                                                dtype=jnp.int32), 0)
                cor_n = jnp.where(live, jnp.sum(trail & cor_t,
                                                dtype=jnp.int32), 0)
                ti_g = jnp.argmax(trail & det_t).astype(jnp.int32)
                s = jnp.where(det_n > 0, _abft.site_code(ti_g, kn), neg1)
                rep = (rep[0] + det_n, rep[1] + cor_n,
                       jnp.where(rep[2] >= 0, rep[2], s))
            else:
                buf = jnp.zeros((p * mtl, nb, nb), dt)
                buf = buf.at[gi_all].set(contrib)
                buf = jnp.where(c == ck, buf, jnp.zeros_like(buf))
                gpan = lax.psum(buf, AXIS_P)
                gpan = ring_bcast_from_col(gpan, ck, q)
                gpan = faults.maybe_corrupt("post_collective", gpan)
        return a_loc, minpiv, minidx, rep, gpan

    def early_update(k, a_loc, loc, gpan):
        """Priority herk (SLATE's lookahead tasks, potrf.cc:266-287):
        apply step k's panel to columns k+1..k+la only, so ``issue`` can
        factor the next panel before the late trailing update runs."""
        prow = gpan[gi_all]                        # [mtl, nb, nb]
        for dcol in range(1, la + 1):
            cd = jnp.minimum(k + dcol, Nt - 1)
            livec = k + dcol < Nt
            slot = (cd // q).astype(jnp.int32)
            pcol = gpan[cd][None]                  # [1, nb, nb]
            with span("slate.potrf/herk"):
                upd = herk_panel_update(prow, pcol)   # [mtl, 1, nb, nb]
            cur = lax.dynamic_slice(a_loc, (zi, slot, zi, zi),
                                    (mtl, 1, nb, nb))
            mask = ((gi_all > k)[:, None, None, None] & livec &
                    (c == cd % q))
            new = cur - upd
            if abft:
                pch = jnp.conj(pcol).transpose(0, 2, 1)
                exp_r = (jnp.sum(cur, axis=3)
                         - _abft.tile_product_row_sums(
                             prow[:, None], pch[None]))
                exp_c = (jnp.sum(cur, axis=2)
                         - _abft.tile_product_col_sums(
                             prow[:, None], pch[None]))
                new, ev, ti_l, tj_l = _abft.tile_sum_check(
                    new, exp_r, exp_c, n_ctx=n)
                s = jnp.where((ev.detected > 0) & livec,
                              _abft.site_code(gi_all[ti_l], cd), neg1)
                loc = (loc[0] + jnp.where(livec, ev.detected, 0),
                       loc[1] + jnp.where(livec, ev.corrected, 0),
                       jnp.where(loc[2] >= 0, loc[2], s))
            new = jnp.where(mask, new, cur)
            a_loc = lax.dynamic_update_slice(a_loc, new,
                                             (zi, slot, zi, zi))
        return a_loc, loc

    def trailing_update(k, a_loc, loc, gpan, k0, S, T, gj_min):
        """Trailing herk of step k over this superblock's static [S, T]
        window, restricted to columns gj > gj_min (k at depth 0; k + la
        in the pipeline, whose priority phase already did the rest).
        Per-tile-independent einsums, so splitting the column range
        across phases is bit-exact.  Storage pad columns (gj >= Nt, on
        grids where ntl * q > Nt) are always late: the priority phase
        clamps its targets to real columns, so without this the junk
        tiles would see a different update count than depth 0 and the
        bit-exact storage parity between depths would break."""
        sr = jnp.clip(-(-(k0 - r) // p), 0, mtl - S).astype(jnp.int32)
        sc = jnp.clip(-(-(k0 - c) // q), 0, ntl - T).astype(jnp.int32)
        gi = r + p * (sr + jnp.arange(S))
        gj = c + q * (sc + jnp.arange(T))
        prow = gpan[gi]                   # [S, nb, nb]
        pcol = gpan[gj]                   # [T, nb, nb]
        with span("slate.potrf/herk"):
            upd = herk_panel_update(prow, pcol)
        cur = lax.dynamic_slice(a_loc, (sr, sc, zi, zi),
                                (S, T, nb, nb))
        mask = ((gi > k)[:, None, None, None] &
                ((gj > gj_min) | (gj >= Nt))[None, :, None, None])
        new = cur - upd
        if abft:
            # per-tile checksum maintenance of the rank-local
            # herk (dead tiles have zero gpan entries, so their
            # expectation collapses to cur's own sums)
            pch = jnp.conj(pcol).transpose(0, 2, 1)
            exp_r = (jnp.sum(cur, axis=3)
                     - _abft.tile_product_row_sums(
                         prow[:, None], pch[None]))
            exp_c = (jnp.sum(cur, axis=2)
                     - _abft.tile_product_col_sums(
                         prow[:, None], pch[None]))
            new, ev, ti_l, tj_l = _abft.tile_sum_check(
                new, exp_r, exp_c, n_ctx=n)
            s = jnp.where(ev.detected > 0,
                          _abft.site_code(gi[ti_l], gj[tj_l]),
                          neg1)
            loc = (loc[0] + ev.detected, loc[1] + ev.corrected,
                   jnp.where(loc[2] >= 0, loc[2], s))
        new = jnp.where(mask, new, cur)
        return lax.dynamic_update_slice(a_loc, new, (sr, sc, zi, zi)), loc

    if la == 0:
        for k0 in range(0, Nt, sb):
            k1 = min(k0 + sb, Nt)
            # static trailing window (max over ranks) for this superblock:
            # local slots whose global index can be >= k0
            S = mtl - (k0 // p)
            T = ntl - (k0 // q)

            def super_step(k, carry, k0=k0, S=S, T=T):
                (a_loc, minpiv, minidx, rep, loc), gpan = step(k, carry)
                a_loc, loc = lax.cond(
                    k < Nt - 1,
                    lambda args: trailing_update(k, args[0], args[1], gpan,
                                                 k0, S, T, k),
                    lambda args: args, (a_loc, loc))
                return a_loc, minpiv, minidx, rep, loc

            if S <= 0 or T <= 0:
                # no rank has trailing tiles only when k0 >= Nt
                continue
            a_loc, minpiv, minidx, rep, loc = lax.fori_loop(
                k0, k1, super_step, (a_loc, minpiv, minidx, rep, loc))
    else:
        a_loc, minpiv, minidx, rep, gpan = issue(
            0, a_loc, minpiv, minidx, rep, jnp.asarray(True))
        for k0 in range(0, Nt, sb):
            k1 = min(k0 + sb, Nt)
            S = mtl - (k0 // p)
            T = ntl - (k0 // q)

            def super_pipe(k, carry, k0=k0, S=S, T=T):
                a_loc, minpiv, minidx, rep, loc, gpan = carry
                # (1) priority phase: columns k+1..k+la get step k's herk
                a_loc, loc = early_update(k, a_loc, loc, gpan)
                # (2) issue panel k+1 — its ring broadcast is in flight
                #     while (3) runs, which is the whole point
                a_loc, minpiv, minidx, rep, gpan_next = issue(
                    jnp.minimum(k + 1, Nt - 1), a_loc, minpiv, minidx,
                    rep, k + 1 < Nt)
                # (3) late trailing update of step k (columns > k+la)
                a_loc, loc = lax.cond(
                    k < Nt - 1,
                    lambda args: trailing_update(k, args[0], args[1], gpan,
                                                 k0, S, T, k + la),
                    lambda args: args, (a_loc, loc))
                return a_loc, minpiv, minidx, rep, loc, gpan_next

            if S <= 0 or T <= 0:
                continue
            a_loc, minpiv, minidx, rep, loc, gpan = lax.fori_loop(
                k0, k1, super_pipe,
                (a_loc, minpiv, minidx, rep, loc, gpan))

    ldet = lax.psum(lax.psum(loc[0], AXIS_P), AXIS_Q)
    lcor = lax.psum(lax.psum(loc[1], AXIS_P), AXIS_Q)
    lsite = lax.pmax(lax.pmax(loc[2], AXIS_P), AXIS_Q)
    adet = rep[0] + ldet
    acor = rep[1] + lcor
    asite = jnp.where(rep[2] >= 0, rep[2], lsite)
    return a_loc, minpiv, minidx, adet, acor, asite


def dist_potrf(data, Nt: int, grid: Grid, n: int | None = None,
               sb: int | None = None, abft: bool = False,
               la: int | None = None):
    """Factor the cyclic storage array of a Hermitian (lower) matrix in
    place: lower tiles of the result hold L.  ``n`` is the element dimension
    (for ragged last tiles); defaults to Nt*nb (exact tiling).  ``sb`` is
    the inner fori_loop span (default: ~SUPERBLOCKS compiled bodies).

    Returns ``(data, minpiv, minidx, abft_detected, abft_corrected,
    abft_site)``: the factored storage plus the smallest L-diagonal
    magnitude seen and its global element row (replicated scalars feeding
    drivers/cholesky.py's HealthInfo; a NaN diagonal — non-HPD leading
    minor — is recorded as a zero pivot).  ``abft`` (static) turns on
    Huang-Abraham checksum verification of the diagonal factor, the
    broadcast panel and the trailing herk (robust/abft.py); the three
    trailing int32 scalars are zero / -1 when off or clean.

    ``la`` is the comm/compute lookahead depth (see _potrf_local); None
    resolves the tuned depth through the ``dist_lookahead`` plan
    (SEAM011 — untuned chips stay on the depth-0 oracle)."""
    mtl = data.shape[0] // grid.p
    ntl = data.shape[1] // grid.q
    nb = data.shape[-1]
    n = n if n is not None else Nt * nb
    sb = sb if sb is not None else superblock(Nt)
    if la is None:
        from ..tune import lookahead_depth
        la = lookahead_depth(n, data.dtype.name)
    spec = TILE_SPEC
    fn = shard_map_unchecked(
        lambda a: _potrf_local(a, Nt, n, grid.p, grid.q, mtl, ntl, sb,
                               abft, la),
        mesh=grid.mesh, in_specs=(spec,),
        out_specs=(spec, P(), P(), P(), P(), P()))
    return fn(data)
