"""Distributed right-looking Cholesky over the 2D block-cyclic mesh.

Analog of the reference's potrf<Devices> task graph (ref: src/potrf.cc:141-302
and the HostTask variant :23-133):

reference step k                       | here (inside ONE shard_map program)
-------------------------------------- | -----------------------------------
internal::potrf on diagonal tile :213  | diag tile psum-gathered, cholesky
                                       |   replicated on all ranks (cheaper
                                       |   than a second broadcast round)
tileBcast(k,k -> panel column) :219    | (absorbed into the above)
internal::trsm on panel column :225    | vmapped triangular_solve on the
                                       |   owner column's local panel tiles
listBcastMT(A(i,k) -> row i, col i)    | scatter into a global panel buffer
  :232-242                             |   + psum over both mesh axes
internal::herk trailing update :254    | einsum over the rank's trailing
                                       |   slice (static shrinking sizes)
lookahead tasks :266-287               | XLA pipelines across unrolled k
release/tileUpdateAllOrigin :289-302   | SSA buffer lifetimes

The k loop is UNROLLED at trace time: each step has statically-shaped
shrinking trailing slices (the ScaLAPACK discipline), so no masked-FLOP waste
grows with Nt; per-rank ragged boundaries are handled by masking at most one
extra tile row/col.  Block-cyclic distribution keeps every rank busy until
the final panels — the load-balance property the reference gets from the same
distribution (MatrixStorage.hh:555-568).

Only Uplo.Lower is implemented here; the driver maps Upper problems onto it
(ref: potrf.cc handles Upper by conjugate-transposing views the same way).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.grid import AXIS_P, AXIS_Q, Grid
from ..internal.herk import herk_panel_update
from ..internal.potrf import potrf_tile
from ..internal.trsm import trsm_tile_batch
from ..types import Op


def _potrf_local(a_loc, Nt: int, n: int, p: int, q: int, mtl: int, ntl: int):
    """Per-shard body; a_loc [mtl, ntl, nb, nb] block-cyclic local tiles."""
    r = lax.axis_index(AXIS_P)
    c = lax.axis_index(AXIS_Q)
    nb = a_loc.shape[-1]
    dt = a_loc.dtype

    for k in range(Nt):
        rk, ck = k % p, k % q
        kkr, kkc = k // p, k // q
        # valid extent of diagonal tile k (last tile may be ragged); the pad
        # diagonal is identity-augmented so the tile factor stays finite
        # (XLA's potrf NaN-fills the whole tile on a singular input), then
        # zeroed again before write-back to keep the pad==0 invariant.
        vk = nb if k < Nt - 1 else n - (Nt - 1) * nb
        idx = jnp.arange(nb)
        pad_eye = jnp.diag((idx >= vk).astype(dt))
        vmask = ((idx[:, None] < vk) & (idx[None, :] < vk))

        # -- diagonal tile: gather from owner, factor everywhere --
        dtile = jnp.where((r == rk) & (c == ck),
                          a_loc[kkr, kkc], jnp.zeros((nb, nb), dt))
        dtile = lax.psum(lax.psum(dtile, AXIS_P), AXIS_Q)
        lkk_aug = potrf_tile(dtile + pad_eye)
        lkk = jnp.where(vmask, lkk_aug, jnp.zeros_like(lkk_aug))

        # -- panel trsm on the owner column's local tiles --
        pan = a_loc[:, kkc]                       # [mtl, nb, nb]
        sol = trsm_tile_batch(lkk_aug, pan, left=False, lower=True,
                              op_tri=Op.ConjTrans)

        # write back: row k gets L_kk (at its owner), rows i>k the solve
        gi_all = r + p * jnp.arange(mtl)          # global row of each slot
        keep = (gi_all[:, None, None] <= k)
        newcol = jnp.where(keep, pan, sol)
        newcol = jnp.where((gi_all == k)[:, None, None], lkk, newcol)
        a_loc = jnp.where((c == ck),
                          a_loc.at[:, kkc].set(newcol), a_loc)

        if k == Nt - 1:
            break

        # -- broadcast the panel column to every rank (row i + col i owners,
        #    ref listBcastMT potrf.cc:232-242): scatter to global buffer and
        #    psum over the mesh --
        buf = jnp.zeros((p * mtl, nb, nb), dt)
        contrib = jnp.where((gi_all > k)[:, None, None], sol,
                            jnp.zeros_like(sol))
        buf = buf.at[gi_all].set(contrib)
        buf = jnp.where(c == ck, buf, jnp.zeros_like(buf))
        gpan = lax.psum(lax.psum(buf, AXIS_P), AXIS_Q)   # [p*mtl, nb, nb]

        # -- trailing update on this rank's static-size slice --
        S = mtl - max(0, (k + 1) // p)            # max local trailing rows
        T = ntl - max(0, (k + 1) // q)
        if S <= 0 or T <= 0:
            continue
        sr = jnp.clip((k + 1 - r + p - 1) // p, 0, mtl - S)
        sc = jnp.clip((k + 1 - c + q - 1) // q, 0, ntl - T)

        gi = r + p * (sr + jnp.arange(S))         # global rows of the slice
        gj = c + q * (sc + jnp.arange(T))
        prow = gpan[gi]                           # [S, nb, nb]
        pcol = gpan[gj]                           # [T, nb, nb]
        upd = herk_panel_update(prow, pcol)       # [S, T, nb, nb]

        z = jnp.zeros((), sr.dtype)
        cur = lax.dynamic_slice(a_loc, (sr, sc, z, z), (S, T, nb, nb))
        mask = ((gi > k)[:, None, None, None] & (gj > k)[None, :, None, None])
        new = jnp.where(mask, cur - upd, cur)
        a_loc = lax.dynamic_update_slice(a_loc, new, (sr, sc, z, z))

    return a_loc


def dist_potrf(data, Nt: int, grid: Grid, n: int | None = None):
    """Factor the cyclic storage array of a Hermitian (lower) matrix in
    place: lower tiles of the result hold L.  ``n`` is the element dimension
    (for ragged last tiles); defaults to Nt*nb (exact tiling)."""
    mtl = data.shape[0] // grid.p
    ntl = data.shape[1] // grid.q
    nb = data.shape[-1]
    n = n if n is not None else Nt * nb
    spec = P(AXIS_P, AXIS_Q, None, None)
    fn = jax.shard_map(
        lambda a: _potrf_local(a, Nt, n, grid.p, grid.q, mtl, ntl),
        mesh=grid.mesh, in_specs=(spec,), out_specs=spec)
    return fn(data)
