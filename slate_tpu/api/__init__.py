"""Simplified API: verb-named veneer over the LAPACK-named drivers.

Analog of the reference's simplified API (ref:
include/slate/simplified_api.hh:1-838), which maps readable names —
``multiply``, ``lu_solve``, ``chol_factor``, ``least_squares_solve``,
``eig_vals`` — onto the classic routines, dispatching on matrix structure
the way the C++ overload set does (general/Hermitian/symmetric/band pick
gemm/hemm/symm/gbmm, gesv/gbsv, posv/pbsv, ...).

All functions are functional (they return results instead of overwriting
operands) and accept the same ``opts`` dict as the underlying drivers.

    import slate_tpu as st
    from slate_tpu import api

    C = api.multiply(1.0, A, B)              # gemm/hemm/symm/gbmm/hbmm
    X = api.lu_solve(A, B)                   # gesv
    L = api.chol_factor(H)                   # potrf
    lam = api.eig_vals(H)                    # heev_vals
"""

from __future__ import annotations

from ..core.matrix import (BandMatrix, BaseTrapezoidMatrix,
                           HermitianBandMatrix, HermitianMatrix,
                           SymmetricMatrix, TriangularBandMatrix,
                           TriangularMatrix)
from ..drivers import auxiliary as _aux
from ..drivers import band as _band
from ..drivers import blas3 as _blas3
from ..drivers import cholesky as _chol
from ..drivers import heev as _heev
from ..drivers import hetrf as _hetrf
from ..drivers import lu as _lu
from ..drivers import qr as _qr
from ..drivers import svd as _svd
from ..exceptions import slate_error
from ..types import Side

__all__ = [
    "multiply", "triangular_multiply", "triangular_solve",
    "rank_k_update", "rank_2k_update",
    "lu_solve", "lu_solve_nopiv", "lu_factor", "lu_factor_nopiv",
    "lu_solve_using_factor", "lu_solve_using_factor_nopiv",
    "lu_inverse_using_factor", "lu_inverse_using_factor_out_of_place",
    "band_lu_solve",
    "chol_solve", "chol_factor", "chol_solve_using_factor",
    "chol_inverse_using_factor", "band_chol_solve",
    "indefinite_solve", "indefinite_factor", "indefinite_solve_using_factor",
    "least_squares_solve",
    "qr_factor", "qr_multiply_by_q", "lq_factor", "lq_multiply_by_q",
    "eig", "eig_vals", "svd", "svd_vals",
    "norm", "add", "copy", "scale",
    "batch_solve", "batch_chol_solve", "batch_least_squares_solve",
]


# ------------------------------------------------------------------ BLAS-3

def multiply(alpha, A, B, beta=0.0, C=None, opts=None):
    """C = alpha A B + beta C, dispatching on structure (ref:
    simplified_api.hh multiply overload set -> gemm/hemm/symm/gbmm/hbmm)."""
    if isinstance(A, HermitianBandMatrix):
        return _band.hbmm(Side.Left, alpha, A, B, beta, C, opts)
    if isinstance(B, HermitianBandMatrix):
        return _band.hbmm(Side.Right, alpha, B, A, beta, C, opts)
    if isinstance(A, BandMatrix):
        return _band.gbmm(alpha, A, B, beta, C, opts)
    if isinstance(A, HermitianMatrix):
        return _blas3.hemm(Side.Left, alpha, A, B, beta, C, opts)
    if isinstance(B, HermitianMatrix):
        return _blas3.hemm(Side.Right, alpha, B, A, beta, C, opts)
    if isinstance(A, SymmetricMatrix):
        return _blas3.symm(Side.Left, alpha, A, B, beta, C, opts)
    if isinstance(B, SymmetricMatrix):
        return _blas3.symm(Side.Right, alpha, B, A, beta, C, opts)
    return _blas3.gemm(alpha, A, B, beta, C, opts)


def triangular_multiply(alpha, A, B, opts=None):
    """B = alpha A B (A triangular) or alpha B A (B triangular)
    (ref: simplified_api.hh triangular_multiply -> trmm)."""
    if isinstance(A, TriangularMatrix):
        return _blas3.trmm(Side.Left, alpha, A, B, opts)
    slate_error(isinstance(B, TriangularMatrix),
                "triangular_multiply: one operand must be triangular")
    return _blas3.trmm(Side.Right, alpha, B, A, opts)


def triangular_solve(alpha, A, B, opts=None):
    """Solve A X = alpha B (A triangular first) or X A = alpha B
    (triangular second); band-triangular A rides tbsm
    (ref: simplified_api.hh triangular_solve -> trsm/tbsm)."""
    if isinstance(A, TriangularBandMatrix):
        return _band.tbsm(Side.Left, alpha, A, B, opts=opts)
    if isinstance(A, TriangularMatrix):
        return _blas3.trsm(Side.Left, alpha, A, B, opts)
    if isinstance(B, TriangularBandMatrix):
        return _band.tbsm(Side.Right, alpha, B, A, opts=opts)
    slate_error(isinstance(B, TriangularMatrix),
                "triangular_solve: one operand must be triangular")
    return _blas3.trsm(Side.Right, alpha, B, A, opts)


def rank_k_update(alpha, A, beta, C, opts=None):
    """C = alpha A A^{H|T} + beta C (ref: rank_k_update -> herk/syrk)."""
    slate_error(isinstance(C, BaseTrapezoidMatrix),
                "rank_k_update: C must be Hermitian or symmetric")
    if isinstance(C, SymmetricMatrix):
        return _blas3.syrk(alpha, A, beta, C, opts)
    return _blas3.herk(alpha, A, beta, C, opts)


def rank_2k_update(alpha, A, B, beta, C, opts=None):
    """C = alpha A B^{H|T} + (conj)(alpha) B A^{H|T} + beta C
    (ref: rank_2k_update -> her2k/syr2k)."""
    slate_error(isinstance(C, BaseTrapezoidMatrix),
                "rank_2k_update: C must be Hermitian or symmetric")
    if isinstance(C, SymmetricMatrix):
        return _blas3.syr2k(alpha, A, B, beta, C, opts)
    return _blas3.her2k(alpha, A, B, beta, C, opts)


# ------------------------------------------------------------------ LU

def lu_solve(A, B, opts=None):
    """Solve A X = B via partial-pivot LU; band A rides gbsv
    (ref: lu_solve -> gesv / gbsv).  Returns X."""
    if isinstance(A, BandMatrix):
        _, X = _band.gbsv(A, B, opts)
        return X
    _, X = _lu.gesv(A, B, opts)
    return X


band_lu_solve = lu_solve


def lu_solve_nopiv(A, B, opts=None):
    """ref: lu_solve_nopiv -> gesv_nopiv.  Returns X."""
    _, X = _lu.gesv_nopiv(A, B, opts)
    return X


def lu_factor(A, opts=None):
    """ref: lu_factor -> getrf / gbtrf (band)."""
    if isinstance(A, BandMatrix):
        return _band.gbtrf(A, opts)
    return _lu.getrf(A, opts)


def lu_factor_nopiv(A, opts=None):
    """ref: lu_factor_nopiv -> getrf_nopiv."""
    return _lu.getrf_nopiv(A, opts)


def lu_solve_using_factor(F, B, opts=None):
    """ref: lu_solve_using_factor -> getrs / gbtrs (band factors)."""
    if isinstance(F, _band.GBFactors):
        return _band.gbtrs(F, B, opts)
    return _lu.getrs(F, B, opts)


lu_solve_using_factor_nopiv = lu_solve_using_factor


def lu_inverse_using_factor(F, opts=None):
    """ref: lu_inverse_using_factor -> getri."""
    return _lu.getri(F, opts)


def lu_inverse_using_factor_out_of_place(A, opts=None):
    """ref: lu_inverse_using_factor_out_of_place -> getriOOP."""
    return _lu.getriOOP(A, opts)


# ------------------------------------------------------------------ Cholesky

def chol_solve(A, B, opts=None):
    """Solve A X = B, A positive definite; band A rides pbsv
    (ref: chol_solve -> posv / pbsv).  Returns X."""
    if isinstance(A, HermitianBandMatrix):
        _, X = _band.pbsv(A, B, opts)
        return X
    _, X = _chol.posv(A, B, opts)
    return X


band_chol_solve = chol_solve


def chol_factor(A, opts=None):
    """ref: chol_factor -> potrf / pbtrf (band)."""
    if isinstance(A, HermitianBandMatrix):
        return _band.pbtrf(A, opts)
    return _chol.potrf(A, opts)


def chol_solve_using_factor(F, B, opts=None):
    """ref: chol_solve_using_factor -> potrs / pbtrs (band factors)."""
    if isinstance(F, _band.PBFactors):
        return _band.pbtrs(F, B, opts)
    return _chol.potrs(F, B, opts)


def chol_inverse_using_factor(L, opts=None):
    """ref: chol_inverse_using_factor -> potri."""
    return _chol.potri(L, opts)


# ------------------------------------------------------------------ indefinite

def indefinite_solve(A, B, opts=None):
    """Solve A X = B, A Hermitian indefinite (ref: indefinite_solve ->
    hesv, Aasen's factorization).  Returns X."""
    _, X = _hetrf.hesv(A, B, opts)
    return X


def indefinite_factor(A, opts=None):
    """ref: indefinite_factor -> hetrf."""
    return _hetrf.hetrf(A, opts)


def indefinite_solve_using_factor(F, B, opts=None):
    """ref: indefinite_solve_using_factor -> hetrs."""
    return _hetrf.hetrs(F, B, opts)


# ------------------------------------------------------------------ QR / LS

def least_squares_solve(A, B, opts=None):
    """min ||A X - B||_2 (ref: least_squares_solve -> gels, QR vs CholQR
    by MethodGels).  Returns X."""
    return _qr.gels(A, B, opts)


def qr_factor(A, opts=None):
    """ref: qr_factor -> geqrf (CAQR on mesh)."""
    return _qr.geqrf(A, opts)


def qr_multiply_by_q(side, op, F, C, opts=None):
    """C = op(Q) C or C op(Q) (ref: qr_multiply_by_q -> unmqr)."""
    return _qr.unmqr(side, op, F, C, opts)


def lq_factor(A, opts=None):
    """ref: lq_factor -> gelqf."""
    return _qr.gelqf(A, opts)


def lq_multiply_by_q(side, op, F, C, opts=None):
    """ref: lq_multiply_by_q -> unmlq."""
    return _qr.unmlq(side, op, F, C, opts)


# ------------------------------------------------------------------ eig / SVD

def eig(A, opts=None):
    """Full Hermitian eigendecomposition (ref: simplified heev call).
    Returns (eigenvalues, eigenvector Matrix)."""
    return _heev.heev(A, opts)


def eig_vals(A, opts=None):
    """Eigenvalues only (ref: eig_vals -> heev with Job::NoVec)."""
    return _heev.heev_vals(A, opts)


def svd(A, opts=None):
    """Full SVD (ref: simplified svd call).  Returns per drivers.svd."""
    return _svd.svd(A, opts)


def svd_vals(A, opts=None):
    """Singular values only (ref: svd_vals)."""
    return _svd.svd_vals(A, opts)


# ------------------------------------------------------------------ batched
#
# Leading-axis entry points over the serve-layer vmap-clean cores: one
# stack of same-shaped dense problems in, per-problem solutions plus a
# leading-axis HealthInfo and escalation flags out.  Mixed SIZES go
# through serve.Server (docs/SERVING.md), which buckets and packs before
# landing on these same cores.


def _full_sizes(a, live: int):
    """Every problem in a same-shaped API stack is full-size: the sizes
    vector the serve cores take is constant (serve.Server passes true
    mixed sizes; here raggedness has nothing to skip)."""
    import jax.numpy as jnp
    return jnp.full((a.shape[0],), live, jnp.int32)


def batch_solve(a, b, opts=None):
    """Solve A_i X_i = B_i over the leading axis: ``a`` is (batch, n, n),
    ``b`` (batch, n, k).  Returns ``(x, HealthInfo, escalated)`` with
    per-problem health and in-graph per-problem escalation (NoPiv fast
    rung -> partial-pivot LU; serve/batched.py)."""
    from ..serve import batched as _batched
    return _batched.make_batched("solve", opts)(
        a, b, _full_sizes(a, int(a.shape[1])))


def batch_chol_solve(a, b, opts=None):
    """Solve the HPD systems A_i X_i = B_i over the leading axis; ``a``
    holds full (symmetric) dense matrices.  Cholesky fast rung with
    per-problem LU escalation for indefinite members."""
    from ..serve import batched as _batched
    return _batched.make_batched("chol_solve", opts)(
        a, b, _full_sizes(a, int(a.shape[1])))


def batch_least_squares_solve(a, b, opts=None):
    """min ||A_i X_i - B_i|| over the leading axis, m >= n: CholQR
    semi-normal equations with per-problem Householder-QR escalation.
    Returns x of shape (batch, n, k)."""
    from ..serve import batched as _batched
    return _batched.make_batched("least_squares_solve", opts)(
        a, b, _full_sizes(a, int(a.shape[1])))


# ------------------------------------------------------------------ aux

norm = _aux.norm
add = _aux.add
copy = _aux.copy
scale = _aux.scale
