"""Analytic flop/byte models per public op — the MFU denominator.

Every MFU number in the repo comes from here: ``bench.py`` and the
structured-event layer (events.py) both resolve the SAME registered
model for an op, so a bench line and a production event for the same
shapes can never disagree about what "the flops" are (asserted in
tests/test_obs_perf.py).  Formulas follow the reference tester's
nominal counts (gemm 2mnk ref src/gemm.cc:24, potrf n^3/3 ref
src/potrf.cc:334, getrf 2n^3/3, geqrf 2mn^2 - 2n^3/3 — testsweeper
gflop helpers); methods that do different work (gels via CholQR, svd
via one- vs two-stage) report the NOMINAL count for the op, exactly as
the reference tester does, so MFU stays comparable across methods.

Registration is static and lint-audited: slate-lint OBS002 parses this
module's ``@register("<op>", ...)`` string literals and demands every
``@annotate``-decorated public driver either appear here or carry an
explicit ``# slate-lint: disable=OBS002 -- reason`` — a new op can
never silently read ``mfu: n/a``.

A model receives the event's recorded ``shapes`` (one entry per
Matrix-like argument) and may return ``None`` when those shapes cannot
determine the cost (e.g. a factor object whose panel count is not an
argument) — that is an explicit "unknown", distinct from a missing
registration.  The ``batch_*`` models additionally accept the serving
layer's per-problem live-size vector and sum LIVE work only, so a
ragged batch's MFU measures useful flops, not padding.

Byte models are the analytic minimum traffic — each operand read once
plus a result of the first operand's footprint written once — used for
``achieved_gbps``; real traffic is higher (factor re-reads, checksum
shadows), so the number is a lower bound on attained bandwidth.
"""

from __future__ import annotations

import contextlib
import threading

_MODELS: dict = {}
_PEAK_LOCK = threading.Lock()
_PEAK: dict = {}                   # dtype name -> cached peak (or None)
_PEAK_OVERRIDE: list = [None]

#: public spec-sheet dense-matmul peaks per chip generation, keyed by
#: STORAGE dtype.  bf16 is the native MXU rate; f32 lists the same value
#: because XLA's default f32 matmul runs single-pass on the MXU (a
#: HIGHEST-precision f32 matmul is multi-pass and lands below it, which
#: MFU then honestly under-reports).  float64 has no MXU path on any
#: listed chip and is deliberately ABSENT: a f64 batch reads ``mfu: n/a``
#: rather than a number against a peak the hardware cannot reach.
PEAK_TABLE = (
    ("v6", {"bfloat16": 918e12, "float32": 918e12}),
    ("v5p", {"bfloat16": 459e12, "float32": 459e12}),
    ("v5 lite", {"bfloat16": 197e12, "float32": 197e12}),
    ("v5e", {"bfloat16": 197e12, "float32": 197e12}),
    ("v4", {"bfloat16": 275e12, "float32": 275e12}),
    ("v3", {"bfloat16": 123e12, "float32": 123e12}),
    ("v2", {"bfloat16": 46e12, "float32": 46e12}),
)

#: dtype assumed when a caller does not say (the historical single-peak
#: behavior: every chip's headline number is its bf16 rate)
DEFAULT_PEAK_DTYPE = "bfloat16"


def register(*names):
    """Register one analytic flop model under the given op names.

    Names must be STRING LITERALS at the call site — slate-lint OBS002
    discovers the registered set by AST, without importing jax."""
    def deco(fn):
        for name in names:
            if name in _MODELS:
                raise ValueError(f"duplicate flops model for {name!r}")
            _MODELS[name] = fn
        return fn
    return deco


def registered_ops() -> frozenset:
    return frozenset(_MODELS)


def op_flops(op: str, shapes, sizes=None) -> float | None:
    """Analytic flop count for one call of ``op`` on ``shapes`` (the
    event's recorded argument shapes), or None when unregistered or the
    shapes cannot determine the cost.  ``sizes`` is the serving layer's
    live-size vector, consumed by the ``batch_*`` models only."""
    model = _MODELS.get(op)
    if model is None:
        return None
    try:
        return model([tuple(int(d) for d in s) for s in shapes], sizes)
    except (TypeError, ValueError, IndexError):
        return None


def op_bytes(op: str, shapes, dtype) -> float | None:
    """Analytic minimum memory traffic: every operand read once plus a
    result the size of the first operand written once."""
    if op not in _MODELS or not shapes:
        return None
    item = _itemsize(dtype)
    try:
        elems = sum(_prod(s) for s in shapes) + _prod(shapes[0])
    except (TypeError, ValueError):
        return None
    return float(elems) * item


def _itemsize(dtype) -> int:
    name = str(dtype or "")
    for tag, size in (("128", 16), ("64", 8), ("32", 4), ("16", 2),
                      ("8", 1)):
        if name.endswith(tag):
            return size
    return 4


def _prod(shape) -> float:
    out = 1.0
    for d in shape:
        out *= int(d)
    return out


# ---------------------------------------------------------------- peak


def _peak_dtype(dtype) -> str:
    """Normalize a peak-table dtype key through the one shared spelling
    helper (robust/precision.py).  Observability must never throw, so an
    unrecognized spelling degrades to itself — it simply misses the
    table and reads ``mfu: n/a``."""
    if dtype is None:
        return DEFAULT_PEAK_DTYPE
    from ..robust.precision import normalize_dtype
    try:
        return normalize_dtype(dtype)
    except Exception:
        return str(dtype)


def chip_peak(dtype=None):
    """(dense-matmul peak FLOP/s or None, device kind) for the local
    accelerator — PEAK_TABLE keyed by the jax device kind and the
    storage ``dtype`` (default bf16, the headline rate).  A dtype with
    no table entry for the chip (e.g. float64) reads None."""
    dt = _peak_dtype(dtype)
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
    except Exception:                        # no backend at all
        return None, "cpu"
    for key, peaks in PEAK_TABLE:
        if key in kind:
            return peaks.get(dt), kind
    return None, kind


def peak(dtype=None) -> float | None:
    """The cached chip peak (FLOP/s) for ``dtype`` (default bf16),
    honoring :func:`peak_override` — an override pins EVERY dtype, so
    off-accelerator tests keep working unchanged."""
    if _PEAK_OVERRIDE[0] is not None:
        return _PEAK_OVERRIDE[0]
    dt = _peak_dtype(dtype)
    with _PEAK_LOCK:
        if dt not in _PEAK:
            _PEAK[dt] = chip_peak(dt)[0]
        return _PEAK[dt]


@contextlib.contextmanager
def peak_override(value: float | None):
    """Pin the chip peak for the scope (tests, off-accelerator MFU)."""
    prev = _PEAK_OVERRIDE[0]
    _PEAK_OVERRIDE[0] = value
    try:
        yield
    finally:
        _PEAK_OVERRIDE[0] = prev


def mfu(flops: float | None, seconds: float | None,
        dtype=None) -> float | None:
    """flops / seconds as a fraction of the chip peak for ``dtype``
    (default bf16 — the historical single-peak behavior), or None when
    any ingredient (flops model, timing, known peak) is missing."""
    p = peak(dtype)
    if not flops or not seconds or seconds <= 0 or not p:
        return None
    return round(flops / seconds / p, 4)


def achieved_gbps(nbytes: float | None, seconds: float | None
                  ) -> float | None:
    if not nbytes or not seconds or seconds <= 0:
        return None
    return round(nbytes / seconds / 1e9, 3)


# -------------------------------------------------------------- models
#
# Dimension conventions: _s(shapes, i) is the i-th recorded argument
# shape; k (rhs count) defaults to the second shape's trailing dim.


def _s(shapes, i):
    if i >= len(shapes) or len(shapes[i]) < 1:
        raise ValueError("missing shape")
    return shapes[i]


def _rhs(shapes, default=1):
    try:
        s = _s(shapes, 1)
        return s[-1] if len(s) >= 2 else default
    except ValueError:
        return default


@register("gemm")
def _f_gemm(shapes, sizes):
    (m, k), (_, n) = _s(shapes, 0)[:2], _s(shapes, 1)[:2]
    return 2.0 * m * k * n


@register("trsm", "trmm")
def _f_trsm(shapes, sizes):
    m = _s(shapes, 0)[0]
    return float(m) * m * _rhs(shapes)


@register("herk", "syrk")
def _f_herk(shapes, sizes):
    n, k = _s(shapes, 0)[:2]
    return float(n) * n * k


@register("her2k", "syr2k")
def _f_her2k(shapes, sizes):
    n, k = _s(shapes, 0)[:2]
    return 2.0 * n * n * k


@register("hemm")
def _f_hemm(shapes, sizes):
    m = _s(shapes, 0)[0]
    return 2.0 * m * m * _rhs(shapes)


@register("potrf", "potrf_ooc")
def _f_potrf(shapes, sizes):
    n = _s(shapes, 0)[0]
    return n ** 3 / 3.0


@register("potrs", "hetrs", "getrs")
def _f_potrs(shapes, sizes):
    n = _s(shapes, 0)[0]
    return 2.0 * n * n * _rhs(shapes)


@register("posv", "posv_mixed", "posv_mixed_gmres", "hesv")
def _f_posv(shapes, sizes):
    n, k = _s(shapes, 0)[0], _rhs(shapes)
    return n ** 3 / 3.0 + 2.0 * n * n * k


@register("potri", "trtri", "trtrm")
def _f_potri(shapes, sizes):
    n = _s(shapes, 0)[0]
    return n ** 3 / 3.0


@register("getrf", "getrf_nopiv", "getrf_tntpiv", "getrf_rbt", "hetrf",
          "getrf_ooc")
def _f_getrf(shapes, sizes):
    n = min(_s(shapes, 0)[:2]) if len(_s(shapes, 0)) >= 2 \
        else _s(shapes, 0)[0]
    return 2.0 * n ** 3 / 3.0


@register("gesv", "gesv_mixed", "gesv_mixed_gmres")
def _f_gesv(shapes, sizes):
    n, k = _s(shapes, 0)[0], _rhs(shapes)
    return 2.0 * n ** 3 / 3.0 + 2.0 * n * n * k


@register("getri", "getriOOP")
def _f_getri(shapes, sizes):
    n = _s(shapes, 0)[0]
    return 4.0 * n ** 3 / 3.0


@register("geqrf", "gelqf")
def _f_geqrf(shapes, sizes):
    m, n = _s(shapes, 0)[:2]
    hi, lo = max(m, n), min(m, n)           # gelqf is the transpose count
    return 2.0 * hi * lo * lo - 2.0 * lo ** 3 / 3.0


@register("unmqr", "unmlq")
def _f_unmqr(shapes, sizes):
    m, k = _s(shapes, 0)[:2]
    return 4.0 * m * k * _rhs(shapes, default=k)


@register("cholqr")
def _f_cholqr(shapes, sizes):
    m, n = _s(shapes, 0)[:2]
    return 2.0 * m * n * n + n ** 3 / 3.0


@register("gels", "gels_cholqr", "gels_qr")
def _f_gels(shapes, sizes):
    # nominal QR-path count regardless of method, as the reference tester
    m, n = _s(shapes, 0)[:2]
    return (2.0 * m * n * n - 2.0 * n ** 3 / 3.0
            + 4.0 * m * n * _rhs(shapes))


@register("heev", "heevd", "heev_vals", "stedc")
def _f_heev(shapes, sizes):
    n = _s(shapes, 0)[0]
    return 4.0 * n ** 3 / 3.0


@register("hegst")
def _f_hegst(shapes, sizes):
    n = _s(shapes, 0)[0]
    return float(n) ** 3


@register("hegv")
def _f_hegv(shapes, sizes):
    n = _s(shapes, 0)[0]
    return 8.0 * n ** 3 / 3.0               # hegst + potrf + heev


@register("steqr")
def _f_steqr(shapes, sizes):
    n = _s(shapes, 0)[0]
    return 6.0 * n ** 3 if any(len(s) >= 2 for s in shapes) else 9.0 * n * n


@register("sterf", "bdsqr", "tb2bd", "hb2st")
def _f_sterf(shapes, sizes):
    # values-only tridiagonal/band stages: O(n^2) nominal (the band
    # width is not an event shape; this is a documented lower bound)
    n = _s(shapes, 0)[0]
    return 9.0 * float(n) * n


@register("svd", "svd_vals")
def _f_svd(shapes, sizes):
    m, n = _s(shapes, 0)[:2]
    hi, lo = max(m, n), min(m, n)
    return 4.0 * hi * lo * lo - 4.0 * lo ** 3 / 3.0


@register("gecondest", "trcondest")
def _f_condest(shapes, sizes):
    n = _s(shapes, 0)[0]
    return 8.0 * float(n) * n               # a handful of n^2 solves


# serving batch kernels: live sizes sum when the vector is supplied,
# full-bucket nominal otherwise


def _batch_dims(shapes):
    s = _s(shapes, 0)
    if len(s) < 3:
        raise ValueError("batch op needs a [B, m, n] operand")
    return s[0], s[1], s[2]


@register("batch_potrf")
def _f_batch_potrf(shapes, sizes):
    b, _, n = _batch_dims(shapes)
    if sizes is not None:
        return sum(float(ni) ** 3 / 3.0 for ni in sizes)
    return b * n ** 3 / 3.0


@register("batch_getrf")
def _f_batch_getrf(shapes, sizes):
    b, _, n = _batch_dims(shapes)
    if sizes is not None:
        return sum(2.0 * float(ni) ** 3 / 3.0 for ni in sizes)
    return b * 2.0 * n ** 3 / 3.0


@register("batch_geqrf")
def _f_batch_geqrf(shapes, sizes):
    b, m, n = _batch_dims(shapes)
    if sizes is not None:
        return sum(2.0 * float(mi) * n * n - 2.0 * n ** 3 / 3.0
                   for mi in sizes)
    return b * (2.0 * m * n * n - 2.0 * n ** 3 / 3.0)


#: serving front-end op -> the driver model that prices one problem
SERVE_OP_MODEL = {"solve": "gesv", "chol_solve": "posv",
                  "least_squares_solve": "gels"}


def serve_flops(op: str, problems) -> float | None:
    """Summed LIVE flops for one served batch: ``problems`` is an
    iterable of (a_shape, b_shape) per real request — filler slots and
    padding contribute nothing, so MFU from this number is
    waste-adjusted by construction."""
    model_op = SERVE_OP_MODEL.get(op)
    if model_op is None:
        return None
    total = 0.0
    for a_shape, b_shape in problems:
        fl = op_flops(model_op, [a_shape, b_shape])
        if fl is None:
            return None
        total += fl
    return total
