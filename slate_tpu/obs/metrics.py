"""Metrics aggregation: event/bench JSONL -> per-op tables.

Consumes the two line formats the repo emits —

- ``slate-obs-v1`` driver events (obs/events.py), spans, and
  ``serve_batch`` records (serve/server.py),
- ``slate-bench-v1`` bench lines (bench.py; pre-schema BENCH_r*.json
  lines are accepted too: anything with a ``metric`` key),

and aggregates them into per-op latency percentiles (p50/p99 of
``dur_ms``), escalation / ABFT / certificate-failure rates, plan-usage
tables, a serving table (bucket occupancy p50/p99, padding waste,
escalations per 1k problems, retrace/compile counts) and a bench-round
summary.  Pure stdlib; the CLI front-end is obs/__main__.py.
"""

from __future__ import annotations

import json
import math

EVENT_SCHEMA = "slate-obs-v1"
BENCH_SCHEMA = "slate-bench-v1"


def load_lines(paths) -> list[dict]:
    """Parse JSONL files (or whole-file JSON arrays); non-JSON lines and
    non-dict records are skipped, not fatal — logs interleave."""
    return load_records(paths)[0]


def load_records(paths) -> tuple[list[dict], int]:
    """Like :func:`load_lines` but also counts MALFORMED lines — lines
    that look like truncated/garbled JSON records (start with ``{`` but
    fail to parse, exactly what a watchdog-killed run leaves behind).
    Ordinary interleaved log lines stay silently skipped.

    Also accepts the historical ``BENCH_r*.json`` wrapper format: a
    single pretty-printed JSON object whose ``tail`` string holds the
    run's log+JSONL mixed output — the metric lines inside ``tail`` are
    extracted as records."""
    out: list[dict] = []
    malformed = 0
    for path in paths:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
        stripped = text.lstrip()
        whole = None
        if stripped.startswith(("[", "{")):
            try:
                whole = json.loads(stripped)
            except ValueError:
                whole = None
        if isinstance(whole, list):
            for x in whole:
                if isinstance(x, dict):
                    out.append(x)
                else:
                    malformed += 1
            continue
        if isinstance(whole, dict):
            if isinstance(whole.get("tail"), str):
                # pre-schema bench-round wrapper: harvest the tail
                n, m = _parse_lines(whole["tail"], out)
                malformed += m
                if n == 0 and m == 0:
                    out.append(whole)      # no records inside: keep wrapper
            else:
                out.append(whole)          # single-record file
            continue
        malformed += _parse_lines(text, out)[1]
    return out, malformed


def _parse_lines(text: str, out: list) -> tuple[int, int]:
    """Append each parseable JSON-dict line of ``text`` to ``out``;
    returns (records appended, malformed lines).  A line counts as
    malformed only when it *starts* like a JSON record (``{``) and fails
    — plain log lines are not data and are skipped silently."""
    added = malformed = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            if line.startswith("{"):
                malformed += 1
            continue
        if isinstance(obj, dict):
            out.append(obj)
            added += 1
        elif line.startswith(("{", "[")):
            malformed += 1
    return added, malformed


def split_records(records):
    """(events, spans, serve, bench, ckpt, unknown) from a mixed record
    list.  ``ckpt`` holds the durability layer's ``checkpoint_save`` /
    ``checkpoint_restore`` records (robust/checkpoint.py via
    obs.events.emit_checkpoint); it is appended AFTER bench so existing
    positional consumers (compare.py takes [3], slo.py takes [2]) stay
    valid."""
    events, spans, serve, bench, ckpt, unknown = [], [], [], [], [], []
    for r in records:
        schema, kind = r.get("schema"), r.get("kind")
        if schema == EVENT_SCHEMA and kind == "event":
            events.append(r)
        elif schema == EVENT_SCHEMA and kind == "span":
            spans.append(r)
        elif schema == EVENT_SCHEMA and kind in (
                "serve_batch", "serve_shed", "serve_quarantine",
                "serve_device", "serve_retune"):
            serve.append(r)
        elif schema == EVENT_SCHEMA and kind in (
                "checkpoint_save", "checkpoint_restore"):
            ckpt.append(r)
        elif schema == BENCH_SCHEMA or "metric" in r:
            bench.append(r)
        else:
            unknown.append(r)
    return events, spans, serve, bench, ckpt, unknown


def percentile(values, q: float) -> float | None:
    """Linear-interpolated percentile of a list (q in [0, 100])."""
    if not values:
        return None
    vs = sorted(values)
    if len(vs) == 1:
        return float(vs[0])
    pos = (len(vs) - 1) * q / 100.0
    lo = math.floor(pos)
    hi = math.ceil(pos)
    frac = pos - lo
    return float(vs[lo] * (1.0 - frac) + vs[hi] * frac)


def summarize_events(events) -> dict:
    """Per-op aggregate: counts, latency percentiles, failure rates."""
    ops: dict[str, dict] = {}
    for e in events:
        op = e.get("op") or "?"
        s = ops.setdefault(op, {
            "count": 0, "traced": 0, "errors": 0, "escalated": 0,
            "speculated": 0, "abft_detected": 0, "abft_corrected": 0,
            "cert_fail": 0, "unhealthy": 0, "_durs": [], "_dev": [],
            "_mfu": []})
        s["count"] += 1
        if e.get("traced"):
            s["traced"] += 1
        else:
            d = e.get("dur_ms")
            if isinstance(d, (int, float)):
                s["_durs"].append(float(d))
        if isinstance(e.get("device_ms"), (int, float)):
            s["_dev"].append(float(e["device_ms"]))
        if isinstance(e.get("mfu"), (int, float)):
            s["_mfu"].append(float(e["mfu"]))
        status = e.get("status") or "ok"
        if status != "ok":
            s["errors"] += 1
        path = e.get("path") or ""
        if path.startswith("escalated"):
            s["escalated"] += 1
        elif path.startswith("speculated"):
            s["speculated"] += 1
        h = e.get("health")
        if isinstance(h, dict):
            s["abft_detected"] += int(h.get("abft_detected") or 0)
            s["abft_corrected"] += int(h.get("abft_corrected") or 0)
            if h.get("converged") is False:
                s["cert_fail"] += 1
            if h.get("ok") is False:
                s["unhealthy"] += 1
    for s in ops.values():
        durs = s.pop("_durs")
        dev, mfus = s.pop("_dev"), s.pop("_mfu")
        n = max(s["count"], 1)
        s["p50_ms"] = percentile(durs, 50)
        s["p99_ms"] = percentile(durs, 99)
        s["device_p50_ms"] = percentile(dev, 50)
        s["mfu"] = round(sum(mfus) / len(mfus), 4) if mfus else None
        s["escalation_rate"] = round(s["escalated"] / n, 4)
        s["cert_fail_rate"] = round(s["cert_fail"] / n, 4)
        s["error_rate"] = round(s["errors"] / n, 4)
    return ops


def summarize_plans(events) -> dict:
    """Plan-usage table: how often each (op, kernel, nb, source) tuned
    decision was consulted by an emitting driver call."""
    table: dict[str, int] = {}
    for e in events:
        for p in e.get("plans") or []:
            key = (f"{p.get('op')} kernel={p.get('kernel')} "
                   f"nb={p.get('nb')} source={p.get('source')}")
            table[key] = table.get(key, 0) + 1
    return dict(sorted(table.items(), key=lambda kv: -kv[1]))


def summarize_bench(bench) -> dict:
    """Bench lines -> {metric: {value, unit, chip, ...}} plus skip/error
    tallies (watchdog skip lines carry phase + elapsed_s)."""
    metrics: dict[str, dict] = {}
    skipped, errors = [], []
    for b in bench:
        name = b.get("metric") or "?"
        if b.get("skipped"):
            skipped.append({"metric": name, "reason": b.get("reason"),
                            "phase": b.get("phase"),
                            "elapsed_s": b.get("elapsed_s")})
            continue
        if b.get("error"):
            errors.append({"metric": name, "error": b.get("error")})
            continue
        metrics[name] = {k: b[k] for k in
                         ("value", "unit", "chip", "mfu", "vs_baseline",
                          "nb", "bw", "kernel", "op", "n")
                         if k in b and b[k] is not None}
    return {"metrics": metrics, "skipped": skipped, "errors": errors}


def summarize_serve(serve) -> dict:
    """Serving table: per (op, dtype) batch counts, bucket occupancy
    percentiles, padding waste, escalations per 1k problems, the
    retrace/compile accounting that proves a warmed server stays warm,
    and ``wa_pps`` — padding-waste-adjusted problems/s, raw throughput
    over the batch durations divided by (1 - waste): throughput per
    unit of LIVE work, the number the ragged serving cores improve.

    Survival records ride the same stream: ``serve_shed`` records count
    into ``shed`` / ``shed_per_1k`` (per 1k offered = served + shed)
    and ``serve_quarantine`` into ``quarantined`` / ``quar_per_1k``
    (per 1k served problems).

    Device-pool records ride it too: ``dev`` counts the distinct pool
    members that served a row's batches, ``failovers`` sums the
    redispatches its batches survived (``serve_batch.failovers``, so
    nothing double-counts the pool's own ``serve_device`` records), and
    ``serve_retune`` hot-swaps land on their own ``ladder/<dtype>``
    row's ``retunes`` column."""
    table: dict[str, dict] = {}

    def row(key):
        return table.setdefault(key, {
            "batches": 0, "problems": 0, "escalated": 0, "compiles": 0,
            "retraces": 0, "shed": 0, "quarantined": 0, "failovers": 0,
            "retunes": 0, "_occ": [], "_waste": [], "_dur_ms": 0.0,
            "_lat": [], "_age": [], "_mfu": [], "_devs": set()})

    for e in serve:
        kind = e.get("kind")
        if kind == "serve_device":
            continue        # pool lifecycle, not serving work
        key = f"{e.get('op') or '?'}/{e.get('dtype') or '?'}"
        s = row(key)
        if kind == "serve_shed":
            s["shed"] += 1
            continue
        if kind == "serve_quarantine":
            s["quarantined"] += 1
            continue
        if kind == "serve_retune":
            s["retunes"] += 1
            continue
        s["batches"] += 1
        s["failovers"] += int(e.get("failovers") or 0)
        if e.get("device_id") is not None:
            s["_devs"].add(int(e["device_id"]))
        s["problems"] += int(e.get("problems") or 0)
        s["escalated"] += int(e.get("escalated") or 0)
        s["compiles"] += 1 if e.get("compiled") else 0
        s["retraces"] += int(e.get("retraces") or 0)
        if isinstance(e.get("occupancy"), (int, float)):
            s["_occ"].append(float(e["occupancy"]))
        if isinstance(e.get("padding_waste"), (int, float)):
            s["_waste"].append(float(e["padding_waste"]))
        if isinstance(e.get("dur_ms"), (int, float)):
            s["_dur_ms"] += float(e["dur_ms"])
        # flight-recorder fields: per-problem lists per batch
        for field, acc in (("latency_ms", "_lat"),
                           ("age_at_flush_ms", "_age")):
            vals = e.get(field)
            if isinstance(vals, list):
                s[acc].extend(float(v) for v in vals
                              if isinstance(v, (int, float)))
        if isinstance(e.get("mfu"), (int, float)):
            s["_mfu"].append(float(e["mfu"]))
    for s in table.values():
        occ, waste = s.pop("_occ"), s.pop("_waste")
        lat, age, mfus = s.pop("_lat"), s.pop("_age"), s.pop("_mfu")
        dur_s = s.pop("_dur_ms") / 1e3
        s["dev"] = len(s.pop("_devs"))
        s["occupancy_p50"] = percentile(occ, 50)
        s["occupancy_p99"] = percentile(occ, 99)
        s["padding_waste_p50"] = percentile(waste, 50)
        s["latency_p50_ms"] = percentile(lat, 50)
        s["latency_p99_ms"] = percentile(lat, 99)
        s["age_p99_ms"] = percentile(age, 99)
        s["mfu"] = round(sum(mfus) / len(mfus), 4) if mfus else None
        probs = max(s["problems"], 1)
        s["esc_per_1k"] = round(1000.0 * s["escalated"] / probs, 2)
        offered = max(s["problems"] + s["shed"], 1)
        s["shed_per_1k"] = round(1000.0 * s["shed"] / offered, 2)
        s["quar_per_1k"] = round(1000.0 * s["quarantined"] / probs, 2)
        w = s["padding_waste_p50"] or 0.0
        s["wa_pps"] = (round(s["problems"] / dur_s / max(1.0 - w, 1e-9), 2)
                       if dur_s > 0 else None)
    return dict(sorted(table.items()))


def summarize_checkpoint(ckpt) -> dict:
    """Durability table: per (op, kind) checkpoint traffic — event count,
    bytes moved, save/restore wall-clock percentiles and the verify
    outcome tally (ok vs each typed refusal reason), so a glance shows
    whether resumes are verifying cleanly and what snapshots cost."""
    table: dict[str, dict] = {}
    for e in ckpt:
        key = f"{e.get('op') or '?'}/{e.get('kind') or '?'}"
        s = table.setdefault(key, {
            "count": 0, "bytes": 0, "ok": 0, "refused": 0,
            "_wall": [], "_reasons": {}})
        s["count"] += 1
        if isinstance(e.get("bytes"), (int, float)):
            s["bytes"] += int(e["bytes"])
        if isinstance(e.get("wall_ms"), (int, float)):
            s["_wall"].append(float(e["wall_ms"]))
        verify = e.get("verify") or "?"
        if verify == "ok":
            s["ok"] += 1
        else:
            s["refused"] += 1
            s["_reasons"][verify] = s["_reasons"].get(verify, 0) + 1
    for s in table.values():
        wall = s.pop("_wall")
        reasons = s.pop("_reasons")
        s["wall_p50_ms"] = percentile(wall, 50)
        s["wall_p99_ms"] = percentile(wall, 99)
        s["refusals"] = ",".join(f"{k}={v}" for k, v in
                                 sorted(reasons.items())) or None
    return dict(sorted(table.items()))


def summarize(paths) -> dict:
    """Everything the CLI prints, as one JSON-able dict."""
    records, malformed = load_records(paths)
    events, spans, serve, bench, ckpt, unknown = split_records(records)
    return {
        "files": [str(p) for p in paths],
        "counts": {"events": len(events), "spans": len(spans),
                   "serve": len(serve), "bench": len(bench),
                   "checkpoint": len(ckpt),
                   "unknown": len(unknown), "malformed": malformed},
        "ops": summarize_events(events),
        "plans": summarize_plans(events),
        "serve": summarize_serve(serve),
        "checkpoint": summarize_checkpoint(ckpt),
        "bench": summarize_bench(bench),
    }


# ------------------------------------------------------------- rendering


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.3f}".rstrip("0").rstrip(".") or "0"
    return str(v)


def _table(headers, rows) -> str:
    cols = [headers] + [[_fmt(c) for c in r] for r in rows]
    widths = [max(len(row[i]) for row in cols) for i in range(len(headers))]
    lines = ["  ".join(h.ljust(w) for h, w in zip(headers, widths)),
             "  ".join("-" * w for w in widths)]
    for r in cols[1:]:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return "\n".join(lines)


def render(summary: dict) -> str:
    """Human tables for one summarize() result."""
    parts = []
    c = summary["counts"]
    parts.append(f"records: {c['events']} events, {c['spans']} spans, "
                 f"{c.get('serve', 0)} serve batches, "
                 f"{c['bench']} bench lines"
                 + (f", {c['checkpoint']} checkpoint"
                    if c.get("checkpoint") else "")
                 + (f", {c['unknown']} unknown" if c["unknown"] else ""))
    if summary["ops"]:
        rows = [[op, s["count"], s["traced"], s["p50_ms"], s["p99_ms"],
                 s.get("device_p50_ms"), s.get("mfu"),
                 s["escalation_rate"], s["cert_fail_rate"],
                 f"{s['abft_corrected']}/{s['abft_detected']}",
                 s["error_rate"]]
                for op, s in sorted(summary["ops"].items())]
        parts.append("\nper-op events\n" + _table(
            ["op", "calls", "traced", "p50_ms", "p99_ms", "dev_p50_ms",
             "mfu", "esc_rate", "certfail_rate", "abft c/d", "err_rate"],
            rows))
    if summary["plans"]:
        rows = [[k, v] for k, v in summary["plans"].items()]
        parts.append("\nplan usage\n" + _table(["plan", "calls"], rows))
    if summary.get("serve"):
        rows = [[key, s["batches"], s["problems"], s["occupancy_p50"],
                 s["occupancy_p99"], s["padding_waste_p50"],
                 s.get("latency_p50_ms"), s.get("latency_p99_ms"),
                 s.get("mfu"), s.get("wa_pps"), s["esc_per_1k"],
                 s.get("shed_per_1k"), s.get("quar_per_1k"),
                 s.get("dev"), s.get("failovers"), s.get("retunes"),
                 s["retraces"], s["compiles"]]
                for key, s in summary["serve"].items()]
        parts.append("\nserving\n" + _table(
            ["op/dtype", "batches", "problems", "occ_p50", "occ_p99",
             "waste_p50", "lat_p50_ms", "lat_p99_ms", "mfu", "wa_pps",
             "esc/1k", "shed/1k", "quar/1k", "dev", "failovers",
             "retunes", "retraces", "compiles"],
            rows))
    if summary.get("checkpoint"):
        rows = [[key, s["count"], s["bytes"], s["wall_p50_ms"],
                 s["wall_p99_ms"], s["ok"], s["refused"],
                 s.get("refusals")]
                for key, s in summary["checkpoint"].items()]
        parts.append("\ndurability\n" + _table(
            ["op/kind", "count", "bytes", "wall_p50_ms", "wall_p99_ms",
             "ok", "refused", "refusals"], rows))
    bench = summary["bench"]
    if bench["metrics"]:
        rows = [[m, d.get("value"), d.get("unit"), d.get("mfu"),
                 d.get("chip")] for m, d in sorted(bench["metrics"].items())]
        parts.append("\nbench metrics\n" + _table(
            ["metric", "value", "unit", "mfu", "chip"], rows))
    if bench["skipped"]:
        rows = [[s["metric"], s.get("phase"), s.get("elapsed_s"),
                 s.get("reason")] for s in bench["skipped"]]
        parts.append("\nbench skipped\n" + _table(
            ["metric", "phase", "elapsed_s", "reason"], rows))
    if bench["errors"]:
        rows = [[e["metric"], e.get("error")] for e in bench["errors"]]
        parts.append("\nbench errors\n" + _table(["metric", "error"], rows))
    if c.get("malformed"):
        parts.append(f"\nmalformed={c['malformed']} truncated/garbled "
                     f"line(s) skipped")
    return "\n".join(parts) + "\n"
