"""Perf-regression sentinel: diff two bench rounds metric-by-metric.

``python -m slate_tpu.obs --compare OLD.json NEW.json [--gate pct]``
loads any two bench outputs — ``slate-bench-v1`` JSONL *or* the
pre-schema ``BENCH_r*.json`` wrapper files (metrics.load_records
harvests the JSON lines out of their ``tail`` transcript) — and
classifies every shared metric:

- **improved** / **regressed**: the relative change exceeds the
  metric's NOISE band in the better/worse direction,
- **flat**: within noise.

Direction is a property of the metric (GFLOP/s, speedups, problems/s,
occupancy and mfu are higher-better; waste, overhead percentages and
millisecond latencies are lower-better — :func:`direction`), and the
noise band is wider for metrics we know run noisy (serving throughput,
sweep lines) than for dense single-op GFLOP/s (:func:`noise_pct`).

The GATE is what CI enforces: exit 1 iff any metric regresses beyond
``max(gate, noise)`` percent, so a future TPU round can mechanically
answer "better or worse than r05?" instead of hand-reading JSON.
Metrics present on only one side are reported (``only_old`` /
``only_new``) but never gate — rounds legitimately grow and lose
metrics as budgets shift.
"""

from __future__ import annotations

from . import metrics as _metrics

#: default relative noise band (percent) and CI gate (percent)
DEFAULT_NOISE_PCT = 5.0
DEFAULT_GATE_PCT = 10.0

#: substrings marking a metric whose smaller values are better
_LOWER_BETTER = ("waste", "overhead", "latency", "_ms", "compile",
                 "retrace", "shed", "quar", "slowdown")
#: metric-name substrings with wider run-to-run noise (percent); first
#: match wins, so survival (timing-sensitive shed/quarantine rates under
#: a live flush loop) and precision (the bf16-rung bench times two full
#: Server routes back to back, doubling the timing jitter surface) and
#: pool (live failover/retune drills riding the same flush loop)
#: outrank the generic serve band
_NOISY = (("survival", 20.0), ("durability", 20.0), ("precision", 20.0),
          ("pool", 20.0), ("serve", 15.0), ("sweep", 10.0),
          ("batch", 10.0), ("lookahead", 10.0))


def direction(metric: str, unit: str | None = None) -> str:
    """'higher' or 'lower' (which way is better) for one metric."""
    name = metric.lower()
    if any(tag in name for tag in _LOWER_BETTER):
        return "lower"
    if unit and unit.lower() in ("ms", "s", "pct_overhead"):
        return "lower"
    return "higher"


def noise_pct(metric: str) -> float:
    name = metric.lower()
    for tag, pct in _NOISY:
        if tag in name:
            return pct
    return DEFAULT_NOISE_PCT


def load_round(path) -> dict:
    """{metric: {value, unit}} for one bench round file; skipped and
    errored lines are excluded (they have no value to compare)."""
    records, _ = _metrics.load_records([path])
    bench = _metrics.split_records(records)[3]
    summary = _metrics.summarize_bench(bench)
    out = {}
    for name, d in summary["metrics"].items():
        v = d.get("value")
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[name] = {"value": float(v), "unit": d.get("unit")}
    return out


def compare(old_path, new_path, noise: float | None = None,
            gate: float = DEFAULT_GATE_PCT) -> dict:
    """Classify every shared metric of two rounds.

    Returns ``rows`` (one per shared metric: old/new values, delta_pct,
    class, gated flag), ``only_old`` / ``only_new`` name lists, and
    ``regressions`` — the gated failures that make the CLI exit 1."""
    old, new = load_round(old_path), load_round(new_path)
    rows, regressions = [], []
    for name in sorted(set(old) & set(new)):
        vo, vn = old[name]["value"], new[name]["value"]
        unit = new[name]["unit"] or old[name]["unit"]
        band = noise if noise is not None else noise_pct(name)
        delta_pct = ((vn - vo) / abs(vo) * 100.0) if vo else (
            0.0 if vn == vo else float("inf"))
        better = direction(name, unit)
        gain = delta_pct if better == "higher" else -delta_pct
        if gain > band:
            cls = "improved"
        elif gain < -band:
            cls = "regressed"
        else:
            cls = "flat"
        gated = cls == "regressed" and -gain > max(gate, band)
        row = {"metric": name, "unit": unit, "old": vo, "new": vn,
               "delta_pct": round(delta_pct, 2), "better": better,
               "noise_pct": band, "class": cls, "gated": gated}
        rows.append(row)
        if gated:
            regressions.append(row)
    return {
        "old": str(old_path), "new": str(new_path),
        "gate_pct": gate, "rows": rows, "regressions": regressions,
        "only_old": sorted(set(old) - set(new)),
        "only_new": sorted(set(new) - set(old)),
    }


def render_compare(result: dict) -> str:
    rows = [[r["metric"], r["old"], r["new"], f"{r['delta_pct']:+.1f}%",
             r["unit"] or "-", r["class"] + (" [GATED]" if r["gated"]
                                             else "")]
            for r in result["rows"]]
    parts = [f"compare: {result['old']} -> {result['new']} "
             f"(gate {result['gate_pct']:g}%)"]
    if rows:
        parts.append(_metrics._table(
            ["metric", "old", "new", "delta", "unit", "class"], rows))
    else:
        parts.append("no shared metrics")
    if result["only_old"]:
        parts.append("only in old: " + ", ".join(result["only_old"]))
    if result["only_new"]:
        parts.append("only in new: " + ", ".join(result["only_new"]))
    tally = {"improved": 0, "regressed": 0, "flat": 0}
    for r in result["rows"]:
        tally[r["class"]] += 1
    parts.append(f"compare: {tally['improved']} improved, "
                 f"{tally['flat']} flat, {tally['regressed']} regressed "
                 f"({len(result['regressions'])} gated)")
    return "\n".join(parts) + "\n"
