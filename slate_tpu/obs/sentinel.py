"""Retrace/compile sentinel: count driver traces, warn on churn.

Every boundary execution that happens while jax is TRACING (i.e. the
driver is being staged into a jaxpr — each such staging is followed by
an XLA compile for an unseen signature) is counted here per
``(op, signature)``.  Two pathologies produce warnings, each once per
op, rate-limited:

- the SAME signature traced more than ``SLATE_OBS_RETRACE_LIMIT``
  times (default 3): the caller is rebuilding jitted callables (new
  lambdas/partials per call) and paying a full trace+compile every
  time;
- more than ``SLATE_OBS_SIGNATURE_LIMIT`` distinct signatures
  (default 32) for one op: unbucketed dynamic shapes — every new shape
  compiles a fresh program, the classic serving-layer latency cliff.

The sentinel is always on: when nothing traces it does nothing, and its
per-trace cost (a dict update) is noise next to the trace itself.
Counters are process-global; :func:`reset` clears them (tests).
"""

from __future__ import annotations

import contextlib
import os
import threading
import warnings


class SlateRetraceWarning(UserWarning):
    """A driver is retracing/recompiling more than expected."""


_LOCK = threading.Lock()
_TRACES: dict[str, dict[str, int]] = {}
_WARNED: set[tuple[str, str]] = set()
_TLS = threading.local()


@contextlib.contextmanager
def suppressed():
    """Ignore driver-boundary trace records for the scope.

    One deliberate staging of a program that calls N same-shaped
    drivers enters N depth-0 boundaries in a single trace, which the
    per-boundary counters cannot tell apart from N user retraces.  The
    serve executable cache (the one sanctioned bulk-compile site) wraps
    its AOT compile in this and records ONE serve-level trace for it,
    so the sentinel keeps observing serving compiles at the granularity
    that matters — per executable — without false retrace warnings."""
    prev = getattr(_TLS, "suppress", False)
    _TLS.suppress = True
    try:
        yield
    finally:
        _TLS.suppress = prev


def _limit(env: str, default: int) -> int:
    try:
        return int(os.environ.get(env, "") or default)
    except ValueError:
        return default


def record_trace(op: str, signature: str) -> None:
    """Count one traced boundary execution (called by obs.events)."""
    if getattr(_TLS, "suppress", False):
        return
    retrace_limit = _limit("SLATE_OBS_RETRACE_LIMIT", 3)
    sig_limit = _limit("SLATE_OBS_SIGNATURE_LIMIT", 32)
    with _LOCK:
        sigs = _TRACES.setdefault(op, {})
        sigs[signature] = count = sigs.get(signature, 0) + 1
        nsigs = len(sigs)
        warn_retrace = (count > retrace_limit
                        and (op, "retrace") not in _WARNED)
        if warn_retrace:
            _WARNED.add((op, "retrace"))
        warn_sigs = (nsigs > sig_limit and (op, "signatures") not in _WARNED)
        if warn_sigs:
            _WARNED.add((op, "signatures"))
    if warn_retrace:
        warnings.warn(
            f"{op}: traced {count}x for the same signature "
            f"[{signature}] (limit {retrace_limit}) — the caller is likely "
            "re-jitting per call (fresh lambda/partial each time); hoist "
            "the jitted callable. Raise SLATE_OBS_RETRACE_LIMIT to "
            "silence.", SlateRetraceWarning, stacklevel=3)
    if warn_sigs:
        warnings.warn(
            f"{op}: {nsigs} distinct trace signatures (limit {sig_limit}) "
            "— unbucketed dynamic shapes recompile per shape; pad/bucket "
            "inputs. Raise SLATE_OBS_SIGNATURE_LIMIT to silence.",
            SlateRetraceWarning, stacklevel=3)


def stats() -> dict:
    """Per-op trace counters: total traces, distinct signatures, and the
    hottest signature's count."""
    with _LOCK:
        return {
            op: {
                "traces": sum(sigs.values()),
                "signatures": len(sigs),
                "max_per_signature": max(sigs.values(), default=0),
            }
            for op, sigs in _TRACES.items()
        }


def reset() -> None:
    """Clear counters and re-arm the once-per-op warnings (tests)."""
    with _LOCK:
        _TRACES.clear()
        _WARNED.clear()
