"""SLO verdicts over serve_batch event streams.

The serving flight recorder (serve/server.py) stamps every request at
submit and every batch at flush, so a stream of ``serve_batch`` records
carries everything a serving SLO needs: per-problem submit->drain
latency, padding waste, escalations, waste-adjusted throughput, and
(under ``obs.timing()``) device-time MFU.  This module turns such a
stream into pass/fail verdicts against DECLARED budgets — the Ragged
Paged Attention evaluation style of reporting (PAPERS.md): tail latency
and waste-adjusted throughput as first-class serving metrics, not
bench-day footnotes.

Budgets are a JSON object mapping a target — an ``op/dtype`` key as the
serving table prints it, a bare op (any dtype), or ``"*"`` for the
whole stream — to bounds per metric::

    {
      "*":             {"latency_p99_ms": 250, "esc_per_1k": 5},
      "solve/float32": {"wa_pps": 120, "padding_waste_p50": 0.35}
    }

The bound's DIRECTION is a property of the metric, not the file:
latency / waste / age / escalations are maxima, throughput / occupancy
/ mfu are minima (:data:`METRIC_DIRECTION`).  A budget naming a metric
the stream has no data for FAILS — an SLO that silently passes because
nothing was measured is how regressions ship.

CLI: ``python -m slate_tpu.obs --slo budgets.json events.jsonl``
(exit 0 all pass, 1 any fail); ``--prom`` emits the aggregate as
Prometheus-style text instead of tables.
"""

from __future__ import annotations

import json
import threading
from collections import deque

from . import metrics as _metrics

#: metric -> "max" (bound is a ceiling) or "min" (bound is a floor)
METRIC_DIRECTION = {
    "latency_p50_ms": "max", "latency_p99_ms": "max", "age_p99_ms": "max",
    "padding_waste_p50": "max", "esc_per_1k": "max", "retraces": "max",
    "compiles": "max", "shed_per_1k": "max", "quar_per_1k": "max",
    "occupancy_p50": "min", "occupancy_p99": "min", "wa_pps": "min",
    "mfu": "min", "problems": "min", "batches": "min",
}


def latency_budget_ms(budgets: dict, target: str = "*") -> float | None:
    """The ``latency_p99_ms`` bound a budgets dict declares for
    ``target`` (the live-control signal admission control consumes),
    or None when the budgets declare no latency ceiling there."""
    bound = (budgets.get(target) or {}).get("latency_p99_ms")
    return float(bound) if isinstance(bound, (int, float)) else None


class LatencyGovernor:
    """Rolling-window latency controller: the SLO budget as a LIVE
    control signal, not a post-hoc verdict.

    The serving flush loop feeds every delivered request's
    submit->result latency into :meth:`observe`; admission control asks
    :meth:`overloaded` (rolling p99 over the declared ``budget_ms``
    ceiling — backpressure tightens effective queue capacity) and
    :meth:`estimate_wait_ms` (rolling p50 — the service-time estimate
    that sheds deadline-doomed requests at admission instead of wasting
    a batch slot).  With no budget declared the governor never reports
    overload; with no observations yet it estimates zero wait —
    admission stays permissive until there is data to act on.

    Per-device tails (the PR 19 device pool): ``observe(lat, device=i)``
    additionally files the sample under pool member ``i``, so
    :meth:`p99_ms` / :meth:`overloaded` answer for one device and
    :meth:`overload_fraction` reports WHICH SHARE of the pool is over
    budget.  Backpressure then scales with the sick fraction — one slow
    device out of four tightens admission by an eighth, not by half —
    so the pool's survivors keep serving at capacity while the governor
    and the pool's own quarantine machinery isolate the sick member.
    Union-only streams (no device ids observed) keep the pre-pool
    semantics: overload means the whole world is slow, fraction 1."""

    def __init__(self, budget_ms: float | None = None, window: int = 64):
        self.budget_ms = budget_ms
        self._window = max(int(window), 1)
        self._lock = threading.Lock()
        self._lat: deque = deque(maxlen=self._window)
        self._dev_lat: dict = {}       # device id -> deque of latencies

    def observe(self, latency_ms: float, device: int | None = None) -> None:
        """Record one delivered request's submit->result latency,
        optionally filed under the pool member that served it."""
        with self._lock:
            self._lat.append(float(latency_ms))
            if device is not None:
                dq = self._dev_lat.get(device)
                if dq is None:
                    dq = self._dev_lat[device] = deque(
                        maxlen=self._window)
                dq.append(float(latency_ms))

    def _samples(self, device: int | None) -> list:
        with self._lock:
            if device is None:
                return list(self._lat)
            return list(self._dev_lat.get(device, ()))

    def p99_ms(self, device: int | None = None) -> float | None:
        return _metrics.percentile(self._samples(device), 99)

    def device_p99s(self) -> dict:
        """Rolling p99 per observed pool member (the flight recorder's
        per-device tail view)."""
        with self._lock:
            devs = {d: list(dq) for d, dq in self._dev_lat.items()}
        return {d: _metrics.percentile(vals, 99)
                for d, vals in sorted(devs.items())}

    def estimate_wait_ms(self) -> float:
        """Expected admission->result wait (rolling p50; 0 cold)."""
        return _metrics.percentile(self._samples(None), 50) or 0.0

    def overloaded(self, device: int | None = None) -> bool:
        """Is the rolling p99 (of one device, or the union) over the
        declared budget?  Admission capacity tightens while this holds."""
        if self.budget_ms is None:
            return False
        p99 = self.p99_ms(device)
        return p99 is not None and p99 > self.budget_ms

    def overload_fraction(self) -> float:
        """The share of the pool that is over budget, in [0, 1].

        With per-device observations: overloaded devices / observed
        devices.  Without (union-only stream): 1.0 when the union p99
        is over budget, else 0.0 — the pre-pool halving behavior.
        Admission control scales its capacity by ``1 - fraction/2``."""
        if self.budget_ms is None:
            return 0.0
        with self._lock:
            devs = list(self._dev_lat)
        if not devs:
            return 1.0 if self.overloaded() else 0.0
        over = sum(1 for d in devs if self.overloaded(d))
        return over / len(devs)


def aggregate(records) -> dict:
    """Per-``op/dtype`` serving stats plus an ``"*"`` union row, from
    any mixed record list (non-serve records are ignored).

    Batches stamped with a ``device_id`` (the device pool) additionally
    aggregate into ``device:<id>`` rows, so a budgets file can declare
    per-device latency targets — ``{"device:0": {"latency_p99_ms":
    250}}`` — and a single slow pool member fails its own row instead
    of hiding inside the union tail."""
    serve = _metrics.split_records(records)[2]
    table = _metrics.summarize_serve(serve)
    if serve:
        union = _metrics.summarize_serve(
            [{**e, "op": "*", "dtype": "all"} for e in serve])
        table["*"] = next(iter(union.values()))
    by_dev: dict = {}
    for e in serve:
        dev = e.get("device_id")
        # serve_device (pool lifecycle) records also carry device_id but
        # summarize to nothing — a member that only got quarantined must
        # not produce an empty row
        if isinstance(dev, int) and e.get("kind") == "serve_batch":
            by_dev.setdefault(dev, []).append(
                {**e, "op": "device", "dtype": str(dev)})
    for dev, evs in sorted(by_dev.items()):
        row = _metrics.summarize_serve(evs)
        if row:
            table[f"device:{dev}"] = next(iter(row.values()))
    return table


def _rows_for(stats: dict, target: str) -> list[tuple[str, dict]]:
    if target in stats:
        return [(target, stats[target])]
    # bare-op target: every dtype row of that op
    return [(k, s) for k, s in stats.items()
            if k.split("/")[0] == target]


def evaluate(stats: dict, budgets: dict) -> list[dict]:
    """Budget verdicts, one per (target row, metric bound).

    Each verdict: ``target`` (budget key), ``row`` (matched stats row),
    ``metric``, ``value`` (measured, None = no data), ``bound``,
    ``direction``, ``ok``.  Unknown metrics and targets with no
    matching data fail loudly (``value=None, ok=False``)."""
    verdicts = []
    for target in sorted(budgets):
        bounds = budgets[target]
        rows = _rows_for(stats, target)
        if not rows:
            for metric in sorted(bounds):
                verdicts.append({
                    "target": target, "row": None, "metric": metric,
                    "value": None, "bound": bounds[metric],
                    "direction": METRIC_DIRECTION.get(metric, "max"),
                    "ok": False})
            continue
        for row_key, row in rows:
            for metric in sorted(bounds):
                bound = bounds[metric]
                direction = METRIC_DIRECTION.get(metric, "max")
                value = row.get(metric)
                if not isinstance(value, (int, float)):
                    ok, value = False, None
                elif direction == "max":
                    ok = value <= bound
                else:
                    ok = value >= bound
                verdicts.append({
                    "target": target, "row": row_key, "metric": metric,
                    "value": value, "bound": bound,
                    "direction": direction, "ok": ok})
    return verdicts


def load_budgets(path) -> dict:
    with open(path, encoding="utf-8") as fh:
        budgets = json.load(fh)
    if not isinstance(budgets, dict) or not all(
            isinstance(v, dict) for v in budgets.values()):
        raise ValueError(
            f"{path}: budgets must be {{target: {{metric: bound}}}}")
    return budgets


def render_verdicts(verdicts) -> str:
    rows = [[v["target"], v["row"] or "-", v["metric"],
             v["value"] if v["value"] is not None else "no-data",
             ("<=" if v["direction"] == "max" else ">=") + _metrics._fmt(
                 v["bound"]),
             "PASS" if v["ok"] else "FAIL"]
            for v in verdicts]
    failed = sum(1 for v in verdicts if not v["ok"])
    table = _metrics._table(
        ["budget", "row", "metric", "value", "bound", "verdict"], rows)
    return (f"slo\n{table}\n\n"
            f"slo: {len(verdicts) - failed}/{len(verdicts)} budget "
            f"check(s) passed\n")


def export_prometheus(stats: dict) -> str:
    """The aggregated serving stats as Prometheus-style text — one
    ``slate_serve_<metric>{op=...,dtype=...}`` gauge per numeric stat
    (the ``"*"`` union row exports with ``op="*"``)."""
    seen_help = set()
    lines = []
    for key in sorted(stats):
        op, _, dtype = key.partition("/")
        labels = f'op="{op}",dtype="{dtype}"'
        for metric in sorted(stats[key]):
            value = stats[key][metric]
            if not isinstance(value, (int, float)) or isinstance(value,
                                                                 bool):
                continue
            name = "slate_serve_" + metric.replace("/", "_")
            if name not in seen_help:
                seen_help.add(name)
                lines.append(f"# HELP {name} serving aggregate "
                             f"{metric} (slate_tpu.obs.slo)")
                lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name}{{{labels}}} {value}")
    return "\n".join(lines) + ("\n" if lines else "")
