"""Recording span tracer: util.trace.span() with wall times kept.

The reference renders its trace::Block marks into an SVG timeline
(ref src/internal/Trace.cc:359-448).  Our spans already label
jax.profiler timelines (TraceAnnotation + named_scope); this recorder
additionally keeps the host-side enter/exit times of every span inside
a :func:`record_spans` scope and exports them as

- Chrome/Perfetto trace JSON (``chrome://tracing`` / ui.perfetto.dev —
  the TPU-native successor to the SVG timeline), or
- one-span-per-line JSONL for ad-hoc analysis.

Spans recorded while jax is tracing measure TRACE time (the span body
runs once, at staging) — useful in its own right for finding where
trace time goes, and flagged ``"traced": true`` so timelines can
distinguish staging from execution.

Zero overhead when no recorder is active: util.trace.span does one
thread-local attribute read.
"""

from __future__ import annotations

import json
import threading
import time

import jax

_TLS = threading.local()


def active():
    """The innermost active SpanRecorder on this thread, or None."""
    stack = getattr(_TLS, "recorders", None)
    return stack[-1] if stack else None


class SpanRecorder:
    """Collects completed spans as dicts (name, ts_ms, dur_ms, depth)."""

    def __init__(self):
        self.spans: list[dict] = []
        self._t0 = time.perf_counter()
        self._depth = 0

    # -- called by util.trace.span -------------------------------------
    def enter(self, name: str):
        self._depth += 1
        return (name, time.perf_counter(), self._depth,
                not jax.core.trace_state_clean())

    def exit(self, token) -> None:
        name, t0, depth, traced = token
        now = time.perf_counter()
        self._depth = depth - 1
        self.spans.append({
            "name": name,
            "ts_ms": round((t0 - self._t0) * 1e3, 3),
            "dur_ms": round((now - t0) * 1e3, 3),
            "depth": depth,
            "traced": traced,
            "tid": threading.get_ident(),
        })

    # -- exports --------------------------------------------------------
    def export_chrome_trace(self, path: str) -> None:
        """Write Chrome trace-event JSON (complete 'X' events, µs)."""
        events = [{
            "name": s["name"],
            "ph": "X",
            "ts": round(s["ts_ms"] * 1e3, 1),
            "dur": round(s["dur_ms"] * 1e3, 1),
            "pid": 0,
            "tid": s["tid"],
            "args": {"depth": s["depth"], "traced": s["traced"]},
        } for s in self.spans]
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, fh)
            fh.write("\n")

    def export_jsonl(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as fh:
            for s in self.spans:
                fh.write(json.dumps({"schema": "slate-obs-v1",
                                     "kind": "span", **s}) + "\n")


class record_spans:
    """Context manager activating a SpanRecorder on this thread::

        with obs.record_spans() as rec:
            st.posv(A, B)
        rec.export_chrome_trace("/tmp/slate-trace.json")

    Nests: the innermost recorder captures; outer recorders resume when
    it exits (matching how one would scope a sub-timeline)."""

    def __enter__(self) -> SpanRecorder:
        stack = getattr(_TLS, "recorders", None)
        if stack is None:
            stack = _TLS.recorders = []
        self._rec = SpanRecorder()
        stack.append(self._rec)
        return self._rec

    def __exit__(self, *exc) -> None:
        stack = _TLS.recorders
        if self._rec in stack:
            stack.remove(self._rec)
