"""CLI: aggregate slate event/bench JSONL; SLO verdicts; round compare.

    python -m slate_tpu.obs events.jsonl BENCH_r07.json
    python -m slate_tpu.obs --json events.jsonl > summary.json
    python -m slate_tpu.obs --slo budgets.json events.jsonl
    python -m slate_tpu.obs --prom events.jsonl
    python -m slate_tpu.obs --compare BENCH_r04.json BENCH_r05.json \
        --gate 10

Accepts any mix of obs event JSONL (slate-obs-v1), span JSONL,
serve_batch records (serve/server.py), and bench output
(slate-bench-v1 — and pre-schema BENCH_r*.json wrapper files), and
prints per-op latency/device-time/MFU tables, plan-usage, serving
(occupancy, waste, submit->drain latency p50/p99, waste-adjusted
throughput) and bench tables (docs/OBSERVABILITY.md).

Exit codes: 0 clean; 1 a gated ``--compare`` regression or a failed
``--slo`` budget; 2 usage / unreadable input.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import compare as _compare
from . import metrics, slo


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m slate_tpu.obs",
        description="Summarize slate_tpu event/bench JSONL files, check "
                    "serving SLO budgets, or diff two bench rounds.")
    parser.add_argument("files", nargs="*",
                        help="event JSONL and/or bench JSON-lines files")
    parser.add_argument("--json", action="store_true",
                        help="print results as JSON instead of tables")
    parser.add_argument("--slo", metavar="BUDGETS.json",
                        help="evaluate serving SLO budgets over the "
                             "given event files (exit 1 on any failed "
                             "budget)")
    parser.add_argument("--prom", action="store_true",
                        help="emit the serving aggregate as "
                             "Prometheus-style text")
    parser.add_argument("--compare", nargs=2,
                        metavar=("OLD.json", "NEW.json"),
                        help="diff two bench rounds metric-by-metric "
                             "(exit 1 on a gated regression)")
    parser.add_argument("--gate", type=float,
                        default=_compare.DEFAULT_GATE_PCT,
                        help="regression gate threshold in percent for "
                             "--compare (default %(default)s)")
    parser.add_argument("--noise", type=float, default=None,
                        help="override the per-metric noise band "
                             "(percent) for --compare")
    args = parser.parse_args(argv)

    try:
        if args.compare:
            return _run_compare(args)
        if not args.files:
            parser.error("at least one input file is required "
                         "(or use --compare OLD NEW)")
        if args.slo or args.prom:
            return _run_slo(args)
        summary = metrics.summarize(args.files)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        json.dump(summary, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(metrics.render(summary))
    return 0


def _run_compare(args) -> int:
    old_path, new_path = args.compare
    result = _compare.compare(old_path, new_path, noise=args.noise,
                              gate=args.gate)
    if args.json:
        json.dump(result, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(_compare.render_compare(result))
    return 1 if result["regressions"] else 0


def _run_slo(args) -> int:
    records, _ = metrics.load_records(args.files)
    stats = slo.aggregate(records)
    if args.prom:
        sys.stdout.write(slo.export_prometheus(stats))
    if not args.slo:
        return 0
    try:
        budgets = slo.load_budgets(args.slo)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    verdicts = slo.evaluate(stats, budgets)
    if args.json:
        json.dump({"stats": stats, "verdicts": verdicts}, sys.stdout,
                  indent=1, sort_keys=True)
        sys.stdout.write("\n")
    elif not args.prom:
        sys.stdout.write(slo.render_verdicts(verdicts))
    return 1 if any(not v["ok"] for v in verdicts) else 0


if __name__ == "__main__":
    sys.exit(main())
