"""CLI: aggregate slate event/bench JSONL into summary tables.

    python -m slate_tpu.obs events.jsonl BENCH_r07.json
    python -m slate_tpu.obs --json events.jsonl > summary.json

Accepts any mix of obs event JSONL (slate-obs-v1), span JSONL,
serve_batch records (serve/server.py), and bench output
(slate-bench-v1 — and pre-schema BENCH_r*.json lines), and prints
per-op latency percentiles, escalation/ABFT/certificate rates,
plan-usage, serving (bucket occupancy, padding waste, escalations per
1k problems, retrace/compile counts) and bench tables (see
docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import argparse
import json
import sys

from . import metrics


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m slate_tpu.obs",
        description="Summarize slate_tpu event/bench JSONL files.")
    parser.add_argument("files", nargs="+",
                        help="event JSONL and/or bench JSON-lines files")
    parser.add_argument("--json", action="store_true",
                        help="print the summary as JSON instead of tables")
    args = parser.parse_args(argv)
    try:
        summary = metrics.summarize(args.files)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        json.dump(summary, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(metrics.render(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
