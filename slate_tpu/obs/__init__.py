"""slate_tpu.obs — the observability spine (docs/OBSERVABILITY.md).

Four pieces, all host-side and zero-overhead when disabled:

- structured driver events (:mod:`events`): one JSON record per public
  driver call — op, shapes, resolved policy/speculate/abft, path taken,
  HealthInfo counters, tuned plans, wall duration;
- a recording span tracer (:mod:`tracer`): ``util.trace.span`` wall
  times exported as Chrome/Perfetto trace JSON or JSONL;
- a retrace sentinel (:mod:`sentinel`): per-signature trace counters
  with rate-limited warnings on retrace/recompile churn;
- metrics aggregation (:mod:`metrics`) behind the
  ``python -m slate_tpu.obs`` CLI;
- device-time truth (:mod:`flops`): one analytic flop/byte model per
  public op feeding ``device_ms``/``mfu``/``achieved_gbps`` on events
  under the opt-in :func:`timing` mode — bench.py prices its lines
  from the SAME registry;
- serving SLOs (:mod:`slo`) over the flight-recorder fields, and a
  bench-round regression sentinel (:mod:`compare`) behind
  ``--slo`` / ``--compare``.

The jaxpr-identity guarantee: enabling any of this changes NOTHING in
traced computations (no io_callback, no extra ops) — recording reads
returned HealthInfo and host clocks only.
"""

from . import compare, flops, slo
from .events import (SCHEMA, boundary_enter, boundary_exit, clear,
                     configure, disable, enable, enabled, emit_serve_batch,
                     emit_serve_quarantine, emit_serve_shed, note_health,
                     note_path, note_plan, note_resolved, recent, recording,
                     set_timing, timing, timing_enabled)
from .metrics import render, summarize
from .sentinel import SlateRetraceWarning
from .sentinel import reset as reset_sentinel
from .sentinel import stats as sentinel_stats
from .tracer import SpanRecorder, record_spans

__all__ = [
    "SCHEMA", "SlateRetraceWarning", "SpanRecorder", "boundary_enter",
    "boundary_exit", "clear", "compare", "configure", "disable", "enable",
    "enabled", "emit_serve_batch", "emit_serve_quarantine",
    "emit_serve_shed", "flops", "note_health", "note_path",
    "note_plan", "note_resolved", "recent", "record_spans", "recording",
    "render", "reset_sentinel", "sentinel_stats", "set_timing", "slo",
    "summarize", "timing", "timing_enabled",
]
