"""Structured driver events: one host-side record per public driver call.

The reference renders an execution timeline from trace::Block RAII marks
(ref include/slate/internal/Trace.hh); what it never keeps are the
*decisions* — which method ran, whether speculation was accepted, what
the autotuner picked.  This layer captures exactly that at the existing
driver boundaries: the ``@annotate`` wrapper (util/trace.py) opens a
boundary frame, the ``health.finalize`` / ``recovery`` / ``tune`` seams
note what they resolved into it, and the OUTERMOST frame emits one JSON
event when the driver returns.

Contract (the jaxpr-identity guarantee, tested in tests/test_obs.py):

- Recording happens on the HOST only — timestamps, returned HealthInfo
  scalars, trace-time plan decisions.  No ``io_callback`` rides in the
  computation; enabling or disabling observability produces
  byte-identical jaxprs.
- Exactly ONE event per public driver call: nested driver calls (gesv's
  internal getrf/getrs/gemm) open inner frames that are discarded; all
  notes land on the outermost frame, last-write-wins, so the boundary's
  own finalize is what the event reports.
- A driver call executed while TRACING (the user jitted the driver)
  still emits an event, flagged ``"traced": true`` with health counters
  omitted (they are tracers), and always feeds the retrace sentinel.

Event schema ``slate-obs-v1`` is documented in docs/OBSERVABILITY.md.

This module imports only the stdlib and jax — it sits below every other
slate_tpu package so drivers, robust/, tune/ and util/ can all hook in
without cycles.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque

import jax

from . import flops as _flops
from . import sentinel as _sentinel

SCHEMA = "slate-obs-v1"
_MAX_PLANS_PER_EVENT = 8          # bound event size for tile-heavy drivers

_TLS = threading.local()
_LOCK = threading.Lock()
_CFG = {"enabled": False, "path": None, "timing": False}
_RING: deque = deque(maxlen=int(os.environ.get("SLATE_OBS_RING", "256")))
_COLLECTORS: list[list] = []


class _Frame:
    """One open driver boundary (host-side bookkeeping only)."""

    __slots__ = ("op", "t0", "traced", "shapes", "dtype", "notes",
                 "plans_seen", "device_ms")

    def __init__(self, op, traced, shapes, dtype):
        self.op = op
        self.t0 = time.perf_counter()
        self.traced = traced
        self.shapes = shapes
        self.dtype = dtype
        self.notes: dict = {}
        self.plans_seen: set = set()
        self.device_ms: float | None = None


def _frames() -> list:
    fs = getattr(_TLS, "frames", None)
    if fs is None:
        fs = _TLS.frames = []
    return fs


def _active() -> bool:
    # slate-lint: disable=CON001 -- designed lock-free peek on the per-call fast path: a stale read only delays one event past a concurrent enable/disable, never tears (dict read is atomic under the GIL)
    return _CFG["enabled"] or bool(_COLLECTORS)


def enabled() -> bool:
    """Is event recording currently on (global switch or a collector)?"""
    return _active()


def configure(enabled: bool | None = None, path: str | None = None) -> None:
    """Flip the global recording switch and/or set the JSONL sink path.

    ``path=None`` keeps events in the in-process ring buffer only (see
    :func:`recent`).  ``SLATE_OBS_EVENTS=<path>`` in the environment
    enables recording to that path at import time."""
    with _LOCK:
        if enabled is not None:
            _CFG["enabled"] = bool(enabled)
        if path is not None:
            _CFG["path"] = path or None


def enable(path: str | None = None) -> None:
    configure(enabled=True, path=path)


def disable() -> None:
    configure(enabled=False)


@contextlib.contextmanager
def recording(path: str | None = None):
    """Collect events for the scope; yields the (live) list of events.

        with obs.recording() as events:
            st.gesv(A, B)
        assert events[0]["op"] == "gesv"

    With ``path`` the events are also appended to a JSONL file."""
    events: list = []
    with _LOCK:
        _COLLECTORS.append(events)
        prev_path = _CFG["path"]
    if path is not None:
        configure(path=path)
    try:
        yield events
    finally:
        with _LOCK:
            _COLLECTORS.remove(events)
            _CFG["path"] = prev_path


def recent(n: int | None = None) -> list:
    """The last ``n`` events from the in-process ring buffer."""
    with _LOCK:
        out = list(_RING)
    return out if n is None else out[-n:]


def clear() -> None:
    with _LOCK:
        _RING.clear()


# ------------------------------------------------------------------ timing
#
# Device timing is OPT-IN: when on, the outermost eager driver boundary
# blocks until its result is device-ready and the event's ``device_ms``
# measures dispatch->ready instead of just host wall time.  The sync
# happens strictly OUTSIDE traced code (the annotate wrapper consults
# :func:`should_time`, which refuses traced frames), so the
# jaxpr-identity guarantee is untouched: timing on or off, the traced
# computation is byte-identical — only the host waits differently.


def timing_enabled() -> bool:
    """Is device-time measurement on (``obs.timing()`` or
    ``SLATE_OBS_TIMING=1``)?"""
    # slate-lint: disable=CON001 -- designed lock-free peek on the per-call fast path: one boundary may miss a concurrent toggle, which is benign (atomic dict read under the GIL)
    return _CFG["timing"]


def set_timing(on: bool) -> None:
    with _LOCK:
        _CFG["timing"] = bool(on)


@contextlib.contextmanager
def timing(on: bool = True):
    """Scope device-time measurement: events gain ``device_ms`` /
    ``mfu`` / ``achieved_gbps`` (None outside the scope)."""
    with _LOCK:
        prev = _CFG["timing"]
    set_timing(on)
    try:
        yield
    finally:
        set_timing(prev)


def should_time(token) -> bool:
    """Should the annotate wrapper block_until_ready for this boundary?
    Only the OUTERMOST eager frame with timing on — nested boundaries
    would double-sync, and traced frames hold tracers, not buffers."""
    # slate-lint: disable=CON001 -- designed lock-free peek on the per-call fast path: one boundary may miss a concurrent toggle, which is benign (atomic dict read under the GIL)
    if token is None or not _CFG["timing"] or token.traced:
        return False
    frames = _frames()
    return bool(frames) and frames[0] is token


def note_device_ready(token) -> None:
    """Stamp the boundary's dispatch->device-ready time (called by the
    annotate wrapper right after ``jax.block_until_ready(out)``)."""
    if token is not None:
        token.device_ms = round((time.perf_counter() - token.t0) * 1e3, 3)


# ---------------------------------------------------------------- describe


def _describe(x):
    """Best-effort (shape, dtype) of one driver argument — Matrix-likes
    expose .m/.n, raw arrays .shape; anything else is skipped."""
    shape = getattr(x, "shape", None)
    if shape is None and hasattr(x, "m") and hasattr(x, "n"):
        shape = (getattr(x, "m"), getattr(x, "n"))
    if shape is None:
        return None
    try:
        shape = tuple(int(s) for s in shape)
    except (TypeError, ValueError):
        return None
    dt = getattr(x, "dtype", None)
    return shape, (str(getattr(dt, "name", dt)) if dt is not None else None)


def _describe_args(args):
    shapes, dtype = [], None
    for a in args:
        d = _describe(a)
        if d is None:
            continue
        shapes.append(list(d[0]))
        if dtype is None:
            dtype = d[1]
    return shapes, dtype


def _signature(shapes, dtype) -> str:
    return f"{dtype}:" + ";".join(
        "x".join(str(s) for s in shape) for shape in shapes)


# ---------------------------------------------------------------- boundary


def boundary_enter(op: str, args=()):
    """Open a driver boundary frame (called by util.trace.annotate).

    Returns an opaque token for :func:`boundary_exit`, or None when
    recording is off — the disabled path does no per-call work beyond a
    depth bump and the traced-ness check that feeds the retrace
    sentinel.  Only the OUTERMOST boundary feeds the sentinel: a single
    user trace of posv stages its internal trsm/gemm boundaries too, and
    counting those would flag the caller for retraces it never made."""
    depth = getattr(_TLS, "depth", 0)
    _TLS.depth = depth + 1
    traced = not jax.core.trace_state_clean()
    if traced and depth == 0:
        shapes, dtype = _describe_args(args)
        _sentinel.record_trace(op, _signature(shapes, dtype))
        if not _active():
            return None
    elif not _active():
        return None
    else:
        shapes, dtype = _describe_args(args)
    frame = _Frame(op, traced, shapes, dtype)
    _frames().append(frame)
    return frame


def boundary_exit(token, error: BaseException | None = None) -> None:
    """Close a boundary frame; the outermost frame emits its event."""
    depth = getattr(_TLS, "depth", 0)
    if depth > 0:
        _TLS.depth = depth - 1
    if token is None:
        return
    frames = _frames()
    try:
        i = frames.index(token)
    except ValueError:
        return                      # configure() flipped mid-call: drop
    del frames[i:]
    if i == 0:
        _emit(_build(token, error))


def _outer() -> _Frame | None:
    frames = _frames()
    return frames[0] if frames else None


def _build(frame: _Frame, error) -> dict:
    notes = frame.notes
    op = frame.op[6:] if frame.op.startswith("slate.") else frame.op
    mfu = gbps = None
    if frame.device_ms:
        secs = frame.device_ms * 1e-3
        mfu = _flops.mfu(_flops.op_flops(op, frame.shapes), secs,
                         frame.dtype)
        gbps = _flops.achieved_gbps(
            _flops.op_bytes(op, frame.shapes, frame.dtype), secs)
    return {
        "schema": SCHEMA,
        "kind": "event",
        "ts": time.time(),
        "op": op,
        "shapes": frame.shapes,
        "dtype": frame.dtype,
        "traced": frame.traced,
        "dur_ms": round((time.perf_counter() - frame.t0) * 1e3, 3),
        "device_ms": frame.device_ms,
        "mfu": mfu,
        "achieved_gbps": gbps,
        "policy": notes.get("policy"),
        "speculate": notes.get("speculate"),
        "abft": notes.get("abft"),
        "path": notes.get("path", "direct"),
        "escalations": notes.get("escalations", 0),
        "health": notes.get("health"),
        "plans": notes.get("plans", []),
        "status": ("ok" if error is None
                   else f"error:{type(error).__name__}"),
    }


def emit_serve_batch(payload: dict) -> None:
    """One ``slate-obs-v1`` record per executed serving batch (kind
    ``serve_batch``; slate_tpu.serve.server is the only caller).  The
    payload carries bucket occupancy, padding-waste, escalation and
    executable-cache stats — docs/SERVING.md documents the fields.  Like
    driver boundaries this is host-side only and a no-op while recording
    is off."""
    if not _active():
        return
    _emit({"schema": SCHEMA, "kind": "serve_batch", "ts": time.time(),
           **payload})


def emit_serve_shed(payload: dict) -> None:
    """One record per request shed by serving admission control (kind
    ``serve_shed``; serve/server.py is the only caller).  The payload
    carries op/dtype, the shed ``reason`` (deadline / overflow_* /
    watchdog / shutdown), the victim's age and the queue depth — the
    inputs behind the serving table's ``shed/1k`` column.  ``device_id``
    is always None: shedding happens at admission, before the device
    pool picks a member."""
    if not _active():
        return
    _emit({"schema": SCHEMA, "kind": "serve_shed", "ts": time.time(),
           **payload})


def emit_serve_quarantine(payload: dict) -> None:
    """One record per request quarantined to the singleton slow path
    after exhausting the fresh-batch retry (kind ``serve_quarantine``;
    serve/server.py is the only caller) — the ``quar/1k`` column.
    ``device_id`` is the pool member that served the singleton."""
    if not _active():
        return
    _emit({"schema": SCHEMA, "kind": "serve_quarantine", "ts": time.time(),
           **payload})


def emit_serve_device(payload: dict) -> None:
    """One record per device-pool health transition (kind
    ``serve_device``; serve/pool.py is the only caller).  The payload
    carries ``event`` (failover / quarantine / probe_fail / readmit),
    the pool member's ``device_id``, the triggering ``reason``
    (exception / nonfinite / deadline / canary / flake) and the strike
    count — the inputs behind the serving table's ``failovers``
    column and the kill-a-device drill's assertions."""
    if not _active():
        return
    _emit({"schema": SCHEMA, "kind": "serve_device", "ts": time.time(),
           **payload})


def emit_serve_retune(payload: dict) -> None:
    """One record per online ladder hot-swap (kind ``serve_retune``;
    serve/server.py is the only caller).  The payload carries the
    op/dtype whose ladder was refit, the old and new rungs, the live
    vs fitted padded-waste ratios that justified the swap, and how
    many observed sizes fed the DP fitter — the ``retunes`` column."""
    if not _active():
        return
    _emit({"schema": SCHEMA, "kind": "serve_retune", "ts": time.time(),
           **payload})


def emit_checkpoint(kind: str, payload: dict) -> None:
    """One record per checkpoint save or verified restore (kinds
    ``checkpoint_save`` / ``checkpoint_restore``; robust/checkpoint.py
    is the only caller).  The payload carries the op, the panel-step
    index ``step``, payload ``bytes``, the ``verify`` result ("ok" or
    the typed refusal reason — torn / stale / corrupt / abft /
    fingerprint) and ``wall_ms`` — the inputs behind the metrics CLI's
    durability table (docs/ROBUSTNESS.md "Durable jobs")."""
    if not _active():
        return
    _emit({"schema": SCHEMA, "kind": kind, "ts": time.time(), **payload})


def _emit(event: dict) -> None:
    with _LOCK:
        _RING.append(event)
        for c in _COLLECTORS:
            c.append(event)
        path = _CFG["path"] if _CFG["enabled"] else None
    if path:
        line = json.dumps(event)
        with _LOCK:
            with open(path, "a", encoding="utf-8") as fh:
                fh.write(line + "\n")


# ------------------------------------------------------------------- notes
#
# All note_* calls attach to the OUTERMOST open frame (the one that will
# emit) and are no-ops when no frame is open — so the seams below can call
# unconditionally with zero cost while recording is off.


def note_health(name: str, h, policy: str) -> None:
    """Called by health.finalize with the boundary's resolved policy and
    HealthInfo.  Traced health (a jitted-driver trace) is recorded as
    None — tracers have no values to read.  Last write wins, which makes
    the boundary's own (merged) finalize the one the event reports."""
    frame = _outer()
    if frame is None:
        return
    frame.notes["policy"] = policy
    if h is None or h.is_traced():
        frame.notes["health"] = None
        return
    site = int(h.abft_site)
    frame.notes["health"] = {
        "ok": bool(h.ok),
        "info": int(h.info),
        "nonfinite": bool(h.nonfinite),
        "min_pivot": float(h.min_pivot),
        "min_pivot_index": int(h.min_pivot_index),
        "growth": float(h.growth),
        "iters": int(h.iters),
        "converged": bool(h.converged),
        "abft_detected": int(h.abft_detected),
        "abft_corrected": int(h.abft_corrected),
        "abft_site": ([site >> 16, site & 0xffff] if site >= 0 else None),
    }


def note_resolved(knob: str, value) -> None:
    """Called by options.resolve_speculate / resolve_abft: record the
    once-per-boundary resolution ('speculate' / 'abft')."""
    frame = _outer()
    if frame is not None:
        frame.notes.setdefault(knob, bool(value))


def note_path(first: str, rungs, used: int, speculated: bool) -> None:
    """Called by the recovery boundaries: which attempt produced the
    result.  ``first`` names the primary attempt, ``rungs`` the fallback
    ladder in order, ``used`` how many rungs bounded_retry consumed."""
    frame = _outer()
    if frame is None:
        return
    rungs = list(rungs)
    if used <= 0 or used > len(rungs):
        kind = "speculated" if speculated else "direct"
        frame.notes["path"] = f"{kind}:{first}"
    else:
        frame.notes["path"] = f"escalated:{rungs[used - 1]}"
    frame.notes["escalations"] = min(max(used, 0), len(rungs))


def note_plan(op: str, n: int, dtype: str, kernel: str, nb: int,
              source: str, dist: float | None) -> None:
    """Called by tune.resolve_plan: one tuned-dispatch decision.  A
    driver resolves plans per panel, so identical decisions dedupe and
    the list is capped at _MAX_PLANS_PER_EVENT."""
    frame = _outer()
    if frame is None:
        return
    key = (op, n, dtype, kernel, nb, source)
    if key in frame.plans_seen:
        return
    frame.plans_seen.add(key)
    plans = frame.notes.setdefault("plans", [])
    if len(plans) >= _MAX_PLANS_PER_EVENT:
        return
    plans.append({"op": op, "n": int(n), "dtype": dtype, "kernel": kernel,
                  "nb": int(nb), "source": source,
                  "dist": (None if dist is None else round(float(dist), 3))})


def _init_from_env() -> None:
    path = os.environ.get("SLATE_OBS_EVENTS")
    if path:
        configure(enabled=True, path=path)
    if os.environ.get("SLATE_OBS_TIMING", "").lower() in ("1", "true",
                                                          "on", "yes"):
        set_timing(True)


_init_from_env()
