"""Packed-band kernels: storage conversion + blocked band factor/solve.

Analog of the reference's band internals (ref: src/gbtrf.cc:1-318 block-column
LU restricted to in-band tiles, src/pbtrf.cc:1-241 band Cholesky,
src/tbsm.cc band triangular solve with pivots, src/gbmm.cc / src/hbmm.cc
band multiply).  The reference keeps band matrices as block-cyclic tiles and
simply never inserts out-of-band tiles; a TPU-first design instead keeps the
band in LAPACK-style *packed* storage — a dense ``[bandwidth+1, n]`` array —
and runs every algorithm as a ``lax.scan`` over block columns with
STATICALLY-shaped dense windows gathered from / scattered to the packed
array.  All the O(n·kd²) flops land in MXU-shaped dense blocks; compile time
is O(1) in n (one scan body per routine).

Packed layouts (LAPACK conventions):
- Hermitian/lower-triangular band, bandwidth kd:  ``Lp[i, j] = A[j+i, j]``
  for ``0 <= i <= kd`` (shape ``[kd+1, n]``).
- General band kl/ku: ``P[ku+i-j, j] = A[i, j]`` (shape ``[kl+ku+1, n]``).
- gbtrf working array: ``[2kl+ku+1, n]`` — kl extra TOP rows hold the U
  fill-in from partial pivoting (U bandwidth grows to kl+ku), exactly
  LAPACK's dgbtrf ldab layout.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


# ------------------------------------------------------------- conversions

def dense_to_banded(a, kl: int, ku: int):
    """Dense [m, n] -> general packed band [kl+ku+1, n]."""
    m, n = a.shape
    r = jnp.arange(kl + ku + 1)[:, None]
    j = jnp.arange(n)[None, :]
    i = j + (r - ku)
    valid = (i >= 0) & (i < m)
    return jnp.where(valid, a[jnp.clip(i, 0, m - 1), j], 0)


def banded_to_dense(p, kl: int, ku: int, m: int, n: int):
    """General packed band [kl+ku+1, n] -> dense [m, n]."""
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    r = ku + i - j
    valid = (r >= 0) & (r <= kl + ku)
    return jnp.where(valid, p[jnp.clip(r, 0, kl + ku), j],
                     jnp.zeros((), p.dtype))


def band_transpose(p, kl: int, ku: int, n: int, conj: bool = False):
    """Packed band of op(A) from packed band of A (m == n):
    T[rt, c] = P[kl+ku-rt, c+rt-kl]; result has (kl', ku') = (ku, kl)."""
    nr = kl + ku + 1
    rt = jnp.arange(nr)[:, None]
    c = jnp.arange(n)[None, :]
    src_r = kl + ku - rt
    src_c = c + rt - kl
    valid = (src_c >= 0) & (src_c < n)
    out = jnp.where(valid, p[src_r, jnp.clip(src_c, 0, n - 1)],
                    jnp.zeros((), p.dtype))
    return jnp.conj(out) if conj else out


def hermitian_band_expand(lp, kd: int, n: int):
    """Lower Hermitian packed [kd+1, n] -> general packed [2kd+1, n]
    (ku = kl = kd), mirroring the strictly-lower part conjugated."""
    up = band_transpose(lp, kd, 0, n, conj=True)   # [kd+1, n], (kl,ku)=(0,kd)
    g = jnp.zeros((2 * kd + 1, n), lp.dtype)
    g = g.at[kd:].set(lp)                          # rows kd..2kd: lower diags
    g = g.at[:kd + 1].add(up)                      # rows 0..kd: upper diags
    g = g.at[kd].add(-lp[0])                       # diagonal counted twice
    return g


# --------------------------------------------------------- window gather/scatter

def _gather_window(strip, kl: int, ku: int, Wr: int, Wc: int):
    """Dense window W[r, c] = strip[ku + r - c, c] from a packed strip
    [kl+ku+1, Wc] (band entries; out-of-band = 0)."""
    r = jnp.arange(Wr)[:, None]
    c = jnp.arange(Wc)[None, :]
    rr = ku + r - c
    valid = (rr >= 0) & (rr <= kl + ku)
    return jnp.where(valid, strip[jnp.clip(rr, 0, kl + ku), c],
                     jnp.zeros((), strip.dtype))


def _scatter_window(strip, w_new, kl: int, ku: int):
    """Inverse of _gather_window: write dense window values back into the
    packed strip (only in-band positions)."""
    nr, Wc = strip.shape
    Wr = w_new.shape[0]
    rr = jnp.arange(nr)[:, None]
    c = jnp.arange(Wc)[None, :]
    r = c + (rr - ku)
    valid = (r >= 0) & (r < Wr)
    return jnp.where(valid, w_new[jnp.clip(r, 0, Wr - 1), c], strip)


# ------------------------------------------------------------- pbtrf / pbtrs

def pbtrf_banded(lp, kd: int, n: int, w: int):
    """Blocked band Cholesky of a Hermitian positive-definite band matrix in
    lower packed storage [kd+1, n] -> packed L (ref: src/pbtrf.cc potrf +
    trsm + herk block-column sweep).  ``w`` is the block width.

    One lax.scan over ceil(n/w) block columns; each step factors a
    (w+kd)x(w+kd) dense window: potrf(W11), L21 = A21 L11^-H, W22 -= L21
    L21^H — all MXU-shaped."""
    nblk = -(-n // w)
    n_pad = nblk * w + kd
    dt = lp.dtype
    lpp = jnp.zeros((kd + 1, n_pad), dt).at[:, :n].set(lp[:, :n])
    # pad columns: unit diagonal so the pad block factors to identity
    lpp = lpp.at[0, n:].set(jnp.ones((), dt))
    sz = w + kd

    def step(carry, k):
        lpp = carry
        k0 = k * w
        strip = lax.dynamic_slice(lpp, (0, k0), (kd + 1, sz))
        W = _gather_window(strip, kd, 0, sz, sz)
        # Hermitian-complete the lower-only window (XLA's cholesky reads
        # the full matrix on some backends)
        w11 = W[:w, :w]
        w11 = w11 + jnp.conj(jnp.tril(w11, -1)).T
        l11 = lax.linalg.cholesky(w11)
        l21 = lax.linalg.triangular_solve(
            l11, W[w:, :w], left_side=False, lower=True,
            transpose_a=True, conjugate_a=True)
        w22 = W[w:, w:] - l21 @ jnp.conj(l21).T
        Wn = jnp.zeros_like(W)
        Wn = Wn.at[:w, :w].set(jnp.tril(l11))
        Wn = Wn.at[w:, :w].set(l21)
        Wn = Wn.at[w:, w:].set(jnp.tril(w22))
        strip = _scatter_window(strip, Wn, kd, 0)
        lpp = lax.dynamic_update_slice(lpp, strip, (0, k0))
        return lpp, None

    lpp, _ = lax.scan(step, lpp, jnp.arange(nblk))
    return lpp[:, :n]


def banded_trsm_lower(lp, kd: int, n: int, w: int, b, *,
                      conj_trans: bool = False, unit_diag: bool = False):
    """Solve L X = b (or L^H X = b when conj_trans) with L lower band in
    packed storage; b [n, nrhs].  Blocked forward (or backward)
    substitution as one lax.scan with (w+kd)-row windows."""
    nblk = -(-n // w)
    n_pad = nblk * w + kd
    dt = b.dtype
    nrhs = b.shape[1]
    lpp = jnp.zeros((kd + 1, n_pad), lp.dtype).at[:, :n].set(lp[:, :n])
    lpp = lpp.at[0, n:].set(jnp.ones((), lp.dtype))
    bp = jnp.zeros((n_pad, nrhs), dt).at[:n].set(b)
    sz = w + kd

    def get_l(k0):
        strip = lax.dynamic_slice(lpp, (0, k0), (kd + 1, sz))
        W = _gather_window(strip, kd, 0, sz, sz)
        return W[:w, :w], W[w:, :w]                # L11, L21

    if not conj_trans:
        def fstep(bp, k):
            k0 = k * w
            l11, l21 = get_l(k0)
            bw = lax.dynamic_slice(bp, (k0, 0), (sz, nrhs))
            y = lax.linalg.triangular_solve(
                l11, bw[:w], left_side=True, lower=True,
                unit_diagonal=unit_diag)
            rest = bw[w:] - l21 @ y
            bw = bw.at[:w].set(y).at[w:].set(rest)
            return lax.dynamic_update_slice(bp, bw, (k0, 0)), None
        bp, _ = lax.scan(fstep, bp, jnp.arange(nblk))
    else:
        def bstep(bp, k):
            k0 = k * w
            l11, l21 = get_l(k0)
            bw = lax.dynamic_slice(bp, (k0, 0), (sz, nrhs))
            rhs = bw[:w] - jnp.conj(l21).T @ bw[w:]
            y = lax.linalg.triangular_solve(
                l11, rhs, left_side=True, lower=True, transpose_a=True,
                conjugate_a=True, unit_diagonal=unit_diag)
            bw = bw.at[:w].set(y)
            return lax.dynamic_update_slice(bp, bw, (k0, 0)), None
        bp, _ = lax.scan(bstep, bp, jnp.arange(nblk - 1, -1, -1))
    return bp[:n]


def pbtrs_banded(lp, kd: int, n: int, w: int, b):
    """Solve A X = b from pbtrf's packed L: L (L^H X) = b."""
    y = banded_trsm_lower(lp, kd, n, w, b)
    return banded_trsm_lower(lp, kd, n, w, y, conj_trans=True)


# ------------------------------------------------------------- gbtrf / gbtrs

def gbtrf_banded(gp, kl: int, ku: int, n: int, w: int):
    """Blocked band LU with partial pivoting (ref: src/gbtrf.cc).

    ``gp`` is the [2kl+ku+1, n] input array (initial band in rows
    kl..2kl+ku, top kl rows zero fill space).  Returns (gp_factored, perms):
    the factored array has kl+w-1 multiplier rows below the diagonal —
    in-panel pivoting can displace rows downward within the (w+kl)-row
    window, leaving L multipliers up to w-1 diagonals below the kl band
    (LAPACK's dgbtrf spills the same triangle into its WORK31 array and
    undoes interchanges to squeeze back into 2kl+ku+1 rows; carrying w-1
    extra rows is O(w·n) storage and needs no undo dance).  U needs no
    spill: a pivot row's entries are bounded by column c0 + kl + ku.
    ``perms`` [nblk, w+kl] holds each block's window-local row permutation
    (panel[perm] = L U), replayed by gbtrs — the analog of the reference's
    per-panel pivot lists."""
    from .getrf import panel_lu
    kuw = kl + ku                                  # working upper bandwidth
    klx = kl + w - 1                               # extended L bandwidth
    nblk = -(-n // w)
    Wr = w + kl
    Wc = w + kuw
    n_pad = nblk * w + kuw
    dt = gp.dtype
    gpp = jnp.zeros((klx + kuw + 1, n_pad), dt)
    gpp = gpp.at[:kl + kuw + 1, :n].set(gp[:, :n])
    gpp = gpp.at[kuw, n:].set(jnp.ones((), dt))    # pad diag = 1

    def step(gpp, k):
        k0 = k * w
        strip = lax.dynamic_slice(gpp, (0, k0), (klx + kuw + 1, Wc))
        W = _gather_window(strip, klx, kuw, Wr, Wc)
        lu, perm = panel_lu(W[:, :w])
        Wp = W[perm]
        u12 = lax.linalg.triangular_solve(
            lu[:w, :w], Wp[:w, w:], left_side=True, lower=True,
            unit_diagonal=True)
        w22 = Wp[w:, w:] - lu[w:, :w] @ u12
        Wn = jnp.concatenate(
            [lu, jnp.concatenate([u12, w22], axis=0)], axis=1)
        strip = _scatter_window(strip, Wn, klx, kuw)
        gpp = lax.dynamic_update_slice(gpp, strip, (0, k0))
        return gpp, perm

    gpp, perms = lax.scan(step, gpp, jnp.arange(nblk))
    return gpp[:, :n], perms


def gbtrs_banded(gp, perms, kl: int, ku: int, n: int, w: int, b):
    """Solve A X = b from gbtrf's factors (``gp`` [kl+w-1 + kl+ku + 1, n]):
    replay per-block perms + banded unit-L forward solve, then banded U
    (bandwidth kl+ku) backward solve."""
    kuw = kl + ku
    klx = kl + w - 1
    nblk = -(-n // w)
    dt = b.dtype
    nrhs = b.shape[1]
    n_pad = nblk * w + kuw
    gpp = jnp.zeros((klx + kuw + 1, n_pad), gp.dtype).at[:, :n].set(
        gp[:, :n])
    gpp = gpp.at[kuw, n:].set(jnp.ones((), gp.dtype))
    bp = jnp.zeros((n_pad, nrhs), dt).at[:n].set(b)
    Wr = w + kl
    Wc = w + kuw

    def fstep(bp, ka):
        k, perm = ka
        k0 = k * w
        strip = lax.dynamic_slice(gpp, (0, k0), (klx + kuw + 1, Wc))
        W = _gather_window(strip, klx, kuw, Wr, Wc)
        bw = lax.dynamic_slice(bp, (k0, 0), (Wr, nrhs))
        bw = bw[perm]
        y = lax.linalg.triangular_solve(
            W[:w, :w], bw[:w], left_side=True, lower=True,
            unit_diagonal=True)
        rest = bw[w:] - W[w:, :w] @ y
        bw = bw.at[:w].set(y).at[w:].set(rest)
        return lax.dynamic_update_slice(bp, bw, (k0, 0)), None

    bp, _ = lax.scan(fstep, bp, (jnp.arange(nblk), perms))

    def bstep(bp, k):
        k0 = k * w
        strip = lax.dynamic_slice(gpp, (0, k0), (klx + kuw + 1, Wc))
        # U window: rows [k0, k0+w), cols [k0, k0+w+kuw)
        U = _gather_window(strip, klx, kuw, Wr, Wc)[:w]
        xw = lax.dynamic_slice(bp, (k0, 0), (Wc, nrhs))
        rhs = xw[:w] - U[:, w:] @ xw[w:]
        x = lax.linalg.triangular_solve(
            U[:, :w], rhs, left_side=True, lower=False)
        return lax.dynamic_update_slice(bp, x, (k0, 0)), None

    bp, _ = lax.scan(bstep, bp, jnp.arange(nblk - 1, -1, -1))
    return bp[:n]


def banded_trsm_upper(up, ku: int, n: int, w: int, b, *,
                      unit_diag: bool = False):
    """Solve U X = b with U upper band (packed [ku+1, n], kl = 0)."""
    nblk = -(-n // w)
    n_pad = nblk * w + ku
    dt = b.dtype
    nrhs = b.shape[1]
    upp = jnp.zeros((ku + 1, n_pad), up.dtype).at[:, :n].set(up[:, :n])
    upp = upp.at[ku, n:].set(jnp.ones((), up.dtype))
    bp = jnp.zeros((n_pad, nrhs), dt).at[:n].set(b)
    Wc = w + ku

    def bstep(bp, k):
        k0 = k * w
        strip = lax.dynamic_slice(upp, (0, k0), (ku + 1, Wc))
        U = _gather_window(strip, 0, ku, Wc, Wc)[:w]
        xw = lax.dynamic_slice(bp, (k0, 0), (Wc, nrhs))
        rhs = xw[:w] - U[:, w:] @ xw[w:]
        x = lax.linalg.triangular_solve(
            U[:, :w], rhs, left_side=True, lower=False,
            unit_diagonal=unit_diag)
        return lax.dynamic_update_slice(bp, x, (k0, 0)), None

    bp, _ = lax.scan(bstep, bp, jnp.arange(nblk - 1, -1, -1))
    return bp[:n]


# ------------------------------------------------------------- gbmm

def gbmm_banded(gp, kl: int, ku: int, m: int, n: int, b, alpha, beta, c):
    """C = alpha A B + beta C with A an m x n band in general packed
    storage, B [n, nrhs], C [m, nrhs] (ref: src/gbmm.cc).  One fori_loop
    over the kl+ku+1 stored diagonals; each step is a fused
    multiply-accumulate over the full RHS block — bandwidth-bound by
    nature, no MXU contraction to be had."""
    nrhs = b.shape[1]
    dt = jnp.result_type(gp.dtype, b.dtype)
    # accumulator must hold every diagonal's n-row contribution window
    # ([o, o+n) for o up to kl+ku) AND the m output rows at [ku, ku+m)
    cp = jnp.zeros((max(m, n) + kl + ku, nrhs), dt)
    j = jnp.arange(n)

    def body(o, cp):
        # diagonal o holds A[i, j] with i = j + o - ku
        i = j + o - ku
        d = jnp.where((i >= 0) & (i < m), gp[o], jnp.zeros_like(gp[o]))
        contrib = d[:, None] * b                   # [n, nrhs]
        seg = lax.dynamic_slice(cp, (o, 0), (n, nrhs))
        return lax.dynamic_update_slice(cp, seg + contrib, (o, 0))

    cp = lax.fori_loop(0, kl + ku + 1, body, cp)
    out = cp[ku:ku + m]
    return alpha * out + (beta * c if c is not None else 0)
