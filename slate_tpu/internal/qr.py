"""internal QR kernels: Householder panel, block reflector T, larfb apply.

Analog of the reference's QR internals (ref: src/internal/internal_geqrf.cc
+ Tile_geqrf.hh threaded panel; internal_unmqr.cc:581 larfb-style trailing
update; lapackpp larft/larfb used per tile).  TPU-first shape:

- the panel factorization is ONE fori_loop of masked rank-1 updates on the
  whole [mm, w] panel — static shapes, no per-tile objects, compiles once;
- the block-reflector triangle T is built from a single MXU gram product
  V^H V plus a w-step triangular recursion (larft Forward/Columnwise);
- trailing updates are three MXU gemms (larfb): C -= V T^(H) V^H C.

Conventions (LAPACK-compatible): A = Q R with Q = H_0 H_1 ... H_{r-1},
H_j = I - tau_j v_j v_j^H, v_j[j] = 1, v_j[:j] = 0.  The factorization
applies H_j^H (= H_j for real) to the trailing columns.  Q = I - V T V^H.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _larfg(alpha, x):
    """larfg scalar core shared by the panel loop and householder_vec: given
    the pivot ``alpha`` and the tail ``x`` (entries outside the tail MUST
    already be zeroed), return (tau, beta, scale, live).

    beta = -copysign(mu, Re(alpha)); ``live`` False (identity reflector,
    tau = 0) when mu == 0."""
    real_dt = jnp.real(x).dtype
    sigma2 = jnp.sum(jnp.real(x * jnp.conj(x)))
    mu = jnp.sqrt(jnp.real(alpha * jnp.conj(alpha)) + sigma2)
    beta = jnp.where(jnp.real(alpha) >= 0, -mu, mu).astype(real_dt)
    live = mu > 0
    safe_beta = jnp.where(live, beta, jnp.ones_like(beta))
    tau = jnp.where(live, (safe_beta - alpha) / safe_beta,
                    jnp.zeros_like(alpha))
    scale = jnp.where(live, 1 / jnp.where(live, alpha - safe_beta,
                                          jnp.ones_like(alpha)),
                      jnp.zeros_like(alpha))
    return tau, beta, scale, live


def phase_of(z):
    """z / |z| elementwise, with phase 1 where z == 0 (safe division)."""
    az = jnp.abs(z)
    return jnp.where(az > 0, z / jnp.where(az > 0, az, jnp.ones_like(az)),
                     jnp.ones_like(z))


def householder_panel(a):
    """Householder QR of a panel ``a`` [mm, w] (mm >= 1, any w).

    Returns (packed, taus): ``packed`` holds R in/above the diagonal and the
    Householder vectors below it (unit diagonal implied); ``taus`` [w].
    """
    mm, w = a.shape
    r = min(mm, w)
    rows = jnp.arange(mm)
    cols = jnp.arange(w)

    def body(j, carry):
        a, taus = carry
        colj = lax.dynamic_index_in_dim(a, j, axis=1, keepdims=False)
        alpha = lax.dynamic_index_in_dim(colj, j, axis=0, keepdims=False)
        x = jnp.where(rows > j, colj, jnp.zeros_like(colj))
        tau, beta, scale, live = _larfg(alpha, x)
        v = jnp.where(rows > j, x * scale, jnp.zeros_like(x))
        v = jnp.where(rows == j, jnp.ones_like(v), v)
        # trailing update: a[:, j+1:] -= conj(tau) v (v^H a[:, j+1:])
        wrow = jnp.conj(v) @ a                       # [w]
        wrow = jnp.where(cols > j, wrow, jnp.zeros_like(wrow))
        a = a - jnp.conj(tau) * v[:, None] * wrow[None, :]
        # write column j: R above+diag(beta), v strictly below
        newc = jnp.where(rows < j, colj, x * scale)
        newc = jnp.where(rows == j, beta.astype(a.dtype), newc)
        newc = jnp.where(live, newc, colj)           # mu==0: leave column
        a = jnp.where((cols == j)[None, :], newc[:, None], a)
        taus = taus.at[j].set(tau)
        return a, taus

    taus0 = jnp.zeros_like(a[0])         # inherits device-variance from a
    packed, taus = lax.fori_loop(0, r, body, (a, taus0))
    return packed, taus


def panel_qr_cholqr(a):
    """CholQR2 + Householder reconstruction of a tall panel [mm, w]:
    every op an MXU gemm / small batched inverse — no per-column loop.

    CholQR2: G = P^H P, R1 = chol(G)^H, Q = P R1^-1, repeated once (the
    second pass restores orthogonality to eps * kappa(Q1)^2 ~ eps).
    Reconstruction (the LAPACK dorhr_col idea): with S = diag(s),
    s_j = -phase(Q_jj), the matrix  M = E - Q S  (E = [I_w; 0]) has an
    unpivoted LU  M = V W  with V exactly the unit-lower Householder
    aggregate and W = T V1^H — so V comes from one small LU and
    T = W V1^-H.  Then A = (I - V T V^H) E (S R): the packed panel holds
    S R above the diagonal and V below, byte-compatible with
    householder_panel's output.

    Returns (packed, T, ok): ok is False when the gram Cholesky broke
    down (kappa(P)^2 beyond the dtype — callers fall back to the scan
    panel), detected as any non-finite output."""
    from .getrf import _lu_nopiv_square
    from .trsm import tri_inv_lower, tri_inv_upper
    mm, w = a.shape
    eye = jnp.eye(w, dtype=a.dtype)
    iw = jnp.arange(w)
    # only the GRAMS need 3-pass ("high", ~f32-mantissa) matmuls: the
    # cancellation Q^H Q - I is what the kappa^2 term amplifies; the tall
    # Q updates ride the default single-pass rate (their elementwise
    # error is the framework's f32-on-TPU story, and pass 2's
    # high-precision gram sees — and corrects — pass 1's products)
    hi = dict(precision=lax.Precision.HIGH)
    G = jnp.matmul(jnp.conj(a).T, a, **hi)
    L1 = jnp.linalg.cholesky(G)
    Q = a @ jnp.conj(tri_inv_lower(L1)).T
    G2 = jnp.matmul(jnp.conj(Q).T, Q, **hi)
    L2 = jnp.linalg.cholesky(G2)
    Q = Q @ jnp.conj(tri_inv_lower(L2)).T
    R = jnp.matmul(jnp.conj(L2).T, jnp.conj(L1).T, **hi)
    s = -phase_of(jnp.diagonal(Q[:w]))
    M = (-Q * s[None, :]).at[iw, iw].add(1)          # E - Q S
    lu_top = _lu_nopiv_square(M[:w])
    V1 = jnp.tril(lu_top, -1) + eye
    W = jnp.triu(lu_top)
    V2 = M[w:] @ tri_inv_upper(W)
    T = jnp.matmul(W, jnp.conj(tri_inv_lower(V1, unit_diag=True)).T, **hi)
    # A = (I - V T V^H) E (S^-1 R); S is unitary diagonal so S^-1 = conj(S)
    Rs = jnp.triu(R * jnp.conj(s)[:, None])
    packed = jnp.concatenate([Rs + jnp.tril(V1, -1), V2], axis=0)
    ok = jnp.all(jnp.isfinite(packed)) & jnp.all(jnp.isfinite(T))
    return packed, T, ok


def householder_panel_blocked(a, base_w: int = 32):
    """Blocked Householder QR of a panel [mm, w].

    Tall panels (mm >= 2 w) first try the one-shot CholQR2 +
    reconstruction route (:func:`panel_qr_cholqr`) — ~8 bandwidth passes
    over the panel, all MXU — and fall back under lax.cond to the
    recursive scan path only when the gram Cholesky breaks down
    (kappa(P) beyond ~1/sqrt(eps), or structurally rank-deficient
    panels such as the zero-padded tails of the scan-form reductions).

    The fallback splits the columns, factors left, larfbs the right
    half, factors right, and merges the T triangles —
    T = [[T1, -T1 (V1^H V2) T2], [0, T2]] (the compact WY merge,
    ref: lapack dlarft recursion / internal_geqrf's ib blocking) — with
    the sequential rank-1 loop confined to ``base_w``-wide base panels.

    Returns (packed, T) — the T triangle directly, unlike
    householder_panel's taus."""
    mm, w = a.shape
    if mm >= 2 * w and w >= 8:
        pc, Tc, ok = panel_qr_cholqr(a)
        return lax.cond(ok, lambda: (pc, Tc),
                        lambda: _householder_blocked_rec(a, base_w))
    return _householder_blocked_rec(a, base_w)


def _qr_panel_ok(dtype, mm: int, w: int) -> bool:
    """True when the tuned plan routes this panel through the Pallas
    Householder kernel (internal/pallas_qr.py): real f32, MXU-aligned
    width, and the whole [mm, w] panel + its T triangle resident in
    VMEM (~4 MB per panel copy caps mm * w at 2^20)."""
    if not (dtype == jnp.float32 and mm >= w and mm % 8 == 0
            and w % 128 == 0 and 128 <= w <= 512 and mm * w <= 2 ** 20):
        return False
    from ..tune import resolve_plan
    return resolve_plan("geqrf_panel", mm, "float32").kernel == "pallas"


def geqrf_panel(a, base_w: int = 32):
    """Tuned panel seam for geqrf/gels and the mesh QR panel step.

    Routes through the plan for ("geqrf_panel", mm): the VMEM-resident
    Pallas Householder panel (qr_panel_pallas — panel + compact-WY T in
    one kernel) when the plan selects it, else
    :func:`householder_panel_blocked`.  Returns (packed, T)."""
    mm, w = a.shape
    # slate-lint: disable=TRC001 -- capability probe: reads only static shape/dtype/plan, never tracer data
    if _qr_panel_ok(a.dtype, mm, w):
        from .pallas_qr import qr_panel_pallas
        from .potrf import _interpret
        return qr_panel_pallas(a, interpret=_interpret())
    return householder_panel_blocked(a, base_w)


def _householder_blocked_rec(a, base_w: int = 32):
    """The scan-based recursive panel (see householder_panel_blocked)."""
    mm, w = a.shape
    if w <= base_w or mm < w:
        packed, taus = householder_panel(a)
        return packed, build_t(packed, taus)
    h = w // 2
    p1, T1 = _householder_blocked_rec(a[:, :h], base_w)
    right = apply_q_left(p1, T1, a[:, h:], conj_trans=True)
    p2, T2 = _householder_blocked_rec(right[h:], base_w)
    packed = jnp.concatenate(
        [p1, jnp.concatenate([right[:h], p2], axis=0)], axis=1)
    # V2's top h rows are structurally zero: restrict the gram product to
    # V1's live rows instead of multiplying 131072-tall zero padding
    T12 = -T1 @ (jnp.conj(unit_lower(p1)[h:]).T @ unit_lower(p2)) @ T2
    T = jnp.zeros((w, w), a.dtype)
    T = T.at[:h, :h].set(T1).at[h:, h:].set(T2).at[:h, h:].set(T12)
    return packed, T


def unit_lower(packed, r: int | None = None):
    """Extract V (unit lower trapezoid) from a packed panel [mm, w]."""
    mm, w = packed.shape
    r = min(mm, w) if r is None else r
    rows = jnp.arange(mm)[:, None]
    cols = jnp.arange(w)[None, :]
    v = jnp.where(rows > cols, packed, jnp.zeros_like(packed))
    return jnp.where((rows == cols) & (cols < r),
                     jnp.ones_like(packed), v)


def build_t(packed, taus):
    """Block-reflector triangle T [w, w] (larft Forward/Columnwise):
    Q = I - V T V^H, T[j, j] = tau_j, T[:j, j] = -tau_j T V^H v_j."""
    mm, w = packed.shape
    V = unit_lower(packed)
    G = jnp.conj(V).T @ V                            # [w, w] one MXU gram
    idx = jnp.arange(w)

    def body(j, T):
        tj = lax.dynamic_index_in_dim(taus, j, axis=0, keepdims=False)
        gj = lax.dynamic_index_in_dim(G, j, axis=1, keepdims=False)
        gj = jnp.where(idx < j, gj, jnp.zeros_like(gj))
        tcol = -tj * (T @ gj)
        tcol = jnp.where(idx == j, tj, tcol)
        return jnp.where((idx == j)[None, :], tcol[:, None], T)

    T0 = jnp.zeros_like(G)               # inherits device-variance from V
    return lax.fori_loop(0, min(mm, w), body, T0)


def householder_vec(x):
    """One Householder reflector mapping x -> beta e_0 (ref: the larfg
    kernel used throughout src/internal/internal_gebr.cc / hebr.cc).

    Returns (v, tau, beta): H = I - tau v v^H, v[0] = 1, beta real.
    Zero (or already-reduced) x yields tau = 0 (identity).
    """
    alpha = x[0]
    rows = jnp.arange(x.shape[0])
    tail = jnp.where(rows > 0, x, jnp.zeros_like(x))
    tau, beta, scale, live = _larfg(alpha, tail)
    v = jnp.where(rows > 0, tail * scale, jnp.zeros_like(x))
    v = jnp.where(rows == 0, jnp.ones_like(v), v)
    return v, tau, jnp.where(live, beta, jnp.real(alpha))


# ---- larfb: apply the block reflector (ref: internal_unmqr.cc larfb path).
# Q = I - V T V^H;  Q^H = I - V T^H V^H.

def apply_q_left(packed, T, C, conj_trans: bool):
    """C := Q C (conj_trans=False) or Q^H C (True); rows of C match packed."""
    V = unit_lower(packed)
    W = jnp.conj(V).T @ C                            # [w, nc]
    Tm = jnp.conj(T).T if conj_trans else T
    return C - V @ (Tm @ W)


def apply_q_right(packed, T, C, conj_trans: bool):
    """C := C Q (conj_trans=False) or C Q^H (True); cols of C match packed."""
    V = unit_lower(packed)
    W = C @ V                                        # [nr, w]
    Tm = jnp.conj(T).T if conj_trans else T
    return C - (W @ Tm) @ jnp.conj(V).T


def rolled_apply(Vstack, Tstack, offsets, Z):
    """Z <- (prod_k Q_k) Z over a reverse lax.scan of stacked panels.

    Shared back-transform engine for the scan-form two-stage reductions
    (heev he2hb / svd ge2tb; ref: src/unmtr_he2hb.cc, unmbr_ge2tb).
    Panel k is stored from local row 0; its true position is
    ``offsets[k]`` rows down in Z, so each step zero-pads to Z's height
    and rolls — the panels' zero tails wrap to the top, landing exactly
    on the rows Q_k must not touch."""
    K = Tstack.shape[0]
    if K == 0:
        return Z
    pad_rows = Z.shape[0]

    def step(Z, xs):
        packed, T, off = xs
        V = unit_lower(packed)
        Vfull = jnp.zeros((pad_rows, V.shape[1]), V.dtype)
        Vfull = Vfull.at[: V.shape[0]].set(V)
        Vr = jnp.roll(Vfull, off, axis=0)
        Z = Z - Vr @ (T @ (jnp.conj(Vr).T @ Z))
        return Z, None

    Z, _ = lax.scan(step, Z, (Vstack[::-1], Tstack[::-1], offsets[::-1]))
    return Z
