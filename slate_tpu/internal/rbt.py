"""internal::rbt — recursive random butterfly transforms (RBT / PRBT).

Partial pivoting is a sequential, latency-bound row hunt per panel column;
on TPU it is the one part of LU that cannot feed the MXU (docs/PERF.md:
the CALU tournament's ~400 ms pivoting wall at n=16384 vs posv's 66 ms
pivot-free Cholesky floor).  The classical alternative (Parker '95;
Baboulin et al., "Accelerating linear system solutions using randomization
techniques") is to precondition with random butterflies so that NO pivoting
is needed with probability ~1: factor

    A~ = U^T diag(A, I_pad) V,      x = V y,   A~ y = U^T [b; 0]

with U, V independent depth-``d`` recursive butterflies.  A butterfly of
size s is

    B = (1/sqrt(2)) [[R0,  R1],
                     [R0, -R1]]

with R0, R1 random diagonal — so applying B (or B^T, or B^-1) to a vector
is one add/sub of its halves plus a diagonal scale: O(s) elementwise work,
no matmul.  A depth-d recursive butterfly is W = L_0 L_1 ... L_{d-1} where
L_0 is one full-size butterfly and L_l is block-diagonal with 2^l
butterflies of size n/2^l; the two-sided transform costs O(d n^2) total
and every entry of A~ mixes 4^d entries of A, which destroys the
adversarial structure (zero leading pivots, growth drivers) that makes
NoPiv LU unsafe.

Exactness is what makes the transform certifiable: with entries
r = exp(u/10), u ~ U(-1/2, 1/2), each level is exactly invertible
elementwise (B^-1 is B^T with R -> R^-1 and the same 1/sqrt(2) scale), so
apply -> unapply round-trips to the identity at working precision — see
tests/test_rbt.py.

This module is pure mechanism: host-seeded constants + jnp elementwise
combines, no Options, no policy.  The driver seam lives in
drivers/lu.py:getrf_rbt and robust/recovery.py (speculate-then-certify).

Butterfly representation: a tuple of ``depth`` levels, level ``l`` being a
pair ``(r0, r1)`` of flat [n/2] real arrays — the concatenated top-half /
bottom-half diagonals of that level's 2^l butterflies.  The levels are
generated with HOST numpy from a static seed, so under jit they are trace
constants (the same discipline as robust/faults.py fault positions).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

#: element padding granularity for a depth-2 transform
DEFAULT_DEPTH = 2


def padded_size(n: int, depth: int = DEFAULT_DEPTH) -> int:
    """Smallest multiple of 2**depth that is >= n (and >= 2**depth)."""
    m = 1 << depth
    return max(-(-int(n) // m) * m, m)


def generate(n: int, depth: int = DEFAULT_DEPTH, seed: int = 0,
             dtype=jnp.float64):
    """A random depth-``depth`` butterfly of size ``n`` (n % 2**depth == 0)
    as a tuple of per-level ``(r0, r1)`` diagonal pairs.

    Entries are exp(u/10), u ~ U(-1/2, 1/2) (Baboulin et al.'s scaling):
    positive, O(1), and exactly invertible elementwise.  ``dtype`` may be
    complex; the diagonals are always its real counterpart."""
    if n <= 0 or n % (1 << depth):
        raise ValueError(
            f"rbt.generate: n={n} must be a positive multiple of "
            f"2**depth={1 << depth}")
    rdt = np.finfo(np.dtype(dtype)).dtype
    rng = np.random.default_rng(seed)
    levels = []
    for _ in range(depth):
        r = np.exp(rng.uniform(-0.5, 0.5, size=n) / 10.0).astype(rdt)
        levels.append((jnp.asarray(r[: n // 2]), jnp.asarray(r[n // 2:])))
    return tuple(levels)


def _combine(r0, r1, top, bot, mode, s):
    """One butterfly block: B = s[[R0, R1], [R0, -R1]], s = 1/sqrt(2)."""
    if mode == "n":                         # B x
        return s * (r0 * top + r1 * bot), s * (r0 * top - r1 * bot)
    if mode == "t":                         # B^T x
        return s * r0 * (top + bot), s * r1 * (top - bot)
    if mode == "inv":                       # B^-1 x  (B^T with R -> R^-1)
        return s * (top + bot) / r0, s * (top - bot) / r1
    # "invt": B^-T x  (B with R -> R^-1)
    return s * (top / r0 + bot / r1), s * (top / r0 - bot / r1)


def apply_axis(levels, x, mode: str, axis: int = 0):
    """Apply W = L_0 L_1 ... L_{d-1} (or its transpose/inverse) along one
    axis of ``x``.  ``mode``: "n" W, "t" W^T, "inv" W^-1, "invt" W^-T.
    Pure jnp — traces through jit/shard_map unchanged."""
    x = jnp.moveaxis(jnp.asarray(x), axis, 0)
    n = x.shape[0]
    d = len(levels)
    s = float(np.sqrt(0.5))
    # W x applies the innermost (smallest-block) level first; W^T / W^-1
    # reverse the product, so they apply the full-size level first.
    order = range(d) if mode in ("t", "inv") else range(d - 1, -1, -1)
    for lev in order:
        r0, r1 = levels[lev]
        nblk = 1 << lev
        half = n // nblk // 2
        shp = (nblk, half) + (1,) * (x.ndim - 1)
        r0b = jnp.asarray(r0).reshape(shp)
        r1b = jnp.asarray(r1).reshape(shp)
        xb = x.reshape(nblk, 2, half, *x.shape[1:])
        top, bot = _combine(r0b, r1b, xb[:, 0], xb[:, 1], mode, s)
        x = jnp.stack([top, bot], axis=1).reshape(n, *x.shape[1:])
    return jnp.moveaxis(x, 0, axis)


def apply_left(levels, x):
    """W @ x — the solution back-transform x = V y."""
    return apply_axis(levels, x, "n", 0)


def apply_left_t(levels, x):
    """W^T @ x — the RHS forward transform U^T b."""
    return apply_axis(levels, x, "t", 0)


def apply_left_inv(levels, x):
    """W^-1 @ x (exact elementwise inverse; round-trip tests)."""
    return apply_axis(levels, x, "inv", 0)


def apply_right(levels, a):
    """a @ W — the column side of the two-sided transform."""
    # a @ W == (W^T a^T)^T: the "t" combine along axis 1, same level order.
    return apply_axis(levels, a, "t", 1)


def transform(a, u_levels, v_levels):
    """A~ = U^T A V (two independent butterflies, O(d n^2) elementwise)."""
    return apply_right(v_levels, apply_left_t(u_levels, a))


def untransform(at, u_levels, v_levels):
    """A = U^-T A~ V^-1 — exact inverse of :func:`transform`."""
    left = apply_axis(u_levels, at, "invt", 0)      # U^-T A~
    return apply_axis(v_levels, left, "invt", 1)    # ... V^-1
